"""Benchmark: K-FAC-preconditioned Transformer LM training throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Measures tokens/sec of a jitted K-FAC train step (the platform-default
compute path: INVERSE + Newton-Schulz on TPU, EIGEN elsewhere — see
kfac_tpu.default_compute_method; factor update every 10 steps, inverse
update every 100 — the reference's ImageNet cadence,
examples/torch_imagenet_resnet.py:158-167) against the same model
trained with plain SGD on identical hardware in the same process.
``vs_baseline`` is the throughput ratio kfac/sgd: the *cost* of adding
second-order preconditioning (1.0 = free). KAISA's value proposition is
fewer steps to target quality at small per-step overhead.

Extra fields in the JSON line:
- ``platform`` / ``device_kind``: where the numbers were measured. The TPU
  backend in this container is a single-client tunnel that can be wedged by
  other processes, so availability is probed in a sacrificial subprocess
  (bounded retry); on failure the bench falls back to CPU rather than
  crashing, and says so here.
- ``mfu``: model FLOPs utilization of the K-FAC step — model FLOPs only
  (6*N per token plus the 12*L*d*S attention term, the standard accounting),
  excluding the K-FAC factor/eigh work itself, over the chip's peak bf16
  FLOP/s. ``null`` when the peak for the platform is unknown (CPU).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

_T0 = time.time()


def _log(msg: str) -> None:
    """Phase progress to stderr: a killed-by-outer-timeout run still leaves
    a diagnosable trail (round-1 lesson: rc=124 with an empty log)."""
    print(f'[bench +{time.time() - _T0:7.1f}s] {msg}', file=sys.stderr, flush=True)


def _persist(result: dict, partial: bool = True) -> None:
    """Snapshot the result-so-far to BENCH_PARTIAL_PATH (atomic rename).

    Called after every completed phase so even a SIGKILLed run (driver
    timeout, wedged tunnel) leaves its measured numbers on disk — the
    round-3 lesson: a healthy measurement phase is worthless if the
    process dies before the final JSON line prints. ``main`` re-stamps the
    snapshot ``partial=False`` once the final line printed.
    """
    path = os.environ.get('BENCH_PARTIAL_PATH', 'bench_partial.json')
    if not path:
        return
    tmp = f'{path}.tmp.{os.getpid()}'
    try:
        with open(tmp, 'w') as f:
            json.dump({**result, 'partial': partial}, f)
        os.replace(tmp, path)
    except Exception:  # persistence is best-effort; never kill the bench
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _clear_partial() -> None:
    """Remove any snapshot from a PREVIOUS run before measuring: a stale
    file must not be misattributed to this run if it dies pre-first-phase."""
    path = os.environ.get('BENCH_PARTIAL_PATH', 'bench_partial.json')
    if not path:
        return
    try:
        os.unlink(path)
    except OSError:
        pass

# bf16 peak FLOP/s per chip, keyed by device_kind substring (lowercase).
_PEAK_FLOPS = {
    'v6e': 918e12,
    'v6 lite': 918e12,
    'v5p': 459e12,
    'v5e': 197e12,
    'v5 lite': 197e12,
    'v5': 459e12,
    'v4': 275e12,
    'v3': 123e12,
    'v2': 46e12,
}


def _peak_flops(device_kind: str) -> float | None:
    kind = device_kind.lower()
    # Longest key first so 'v5e'/'v5 lite' can never be shadowed by 'v5'.
    for key in sorted(_PEAK_FLOPS, key=len, reverse=True):
        if key in kind:
            return _PEAK_FLOPS[key]
    return None


def _probe_backend():
    """Check whether the default JAX backend initializes, in a subprocess.

    The axon TPU tunnel hangs `jax.devices()` indefinitely when wedged and
    raises UNAVAILABLE when another client holds the single-client claim
    (observed round 1: rc=1 UNAVAILABLE; round 2: 125 s of timeouts under
    the driver while the same chip probed healthy in 3.9 s moments later).
    Both symptoms are transient, so the first touch happens in a sacrificial
    child and failures are retried with backoff over a multi-minute budget
    (BENCH_PROBE_BUDGET_S, default 420), plus one final grace attempt after
    the budget is spent — the round-2 capture shows the chip coming back
    right after the old 125 s probe gave up. Returns
    (platform, device_kind) or None if no healthy non-CPU backend appeared.
    """
    if os.environ.get('JAX_PLATFORMS') == 'cpu':
        # Platform explicitly pinned to host (CI / CPU smoke) — skip the
        # sacrificial child. An absent axon tunnel does NOT skip: a normal
        # accelerator backend (e.g. libtpu) should still be detected.
        return None
    budget_s = float(os.environ.get('BENCH_PROBE_BUDGET_S', '420'))
    code = (
        'import jax; d = jax.devices()[0]; '
        "print('PROBE', d.platform, getattr(d, 'device_kind', ''))"
    )
    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        remaining = budget_s - (time.monotonic() - start)
        final = remaining <= 0
        timeout_s = 45.0 if final else min(90.0, max(remaining, 30.0))
        # On timeout, SIGTERM with a grace period — SIGKILLing a JAX process
        # mid-TPU-claim is itself a documented tunnel-wedge trigger.
        proc = subprocess.Popen(
            [sys.executable, '-c', code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        rc, stdout = None, ''
        try:
            stdout, _ = proc.communicate(timeout=timeout_s)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=15.0)
            except subprocess.TimeoutExpired:
                proc.kill()  # last resort
                proc.wait()
        if rc == 0:
            for line in stdout.splitlines():
                if line.startswith('PROBE '):
                    parts = line.split(' ', 2)
                    platform = parts[1]
                    kind = parts[2] if len(parts) > 2 else ''
                    if platform != 'cpu':
                        _log(f'probe attempt {attempt}: healthy {platform}')
                        return platform, kind
                    # Default backend is already CPU: no accelerator plugin
                    # registered at all — retrying cannot change that.
                    return None
        _log(
            f'probe attempt {attempt}: '
            f'{"timeout" if rc is None else f"rc={rc}"} '
            f'({time.monotonic() - start:.0f}s / {budget_s:.0f}s budget)'
        )
        if final:
            return None
        time.sleep(min(5.0 + 5.0 * attempt, 30.0))


def _timeit(step_for_iter, args, warmup: int = 5, iters: int = 100) -> float:
    """Average seconds/step of a cadence-dispatched step sequence.

    ``step_for_iter(i)`` returns the jitted step function for global step i,
    so the measured loop amortizes capture/inverse cadence exactly like a
    real training run. The default window of 100 steps (measured steps
    5..104) contains 10 factor captures and exactly one inverse/eigh update
    at step 100 — the full inv_update_steps cadence, so the eigh cost is
    represented at its true 1/100 proportion rather than excluded.
    """
    import jax

    out = None
    for i in range(warmup):
        out = step_for_iter(i)(*args)
        args = (out[0], out[1], out[2], args[3])
    jax.block_until_ready(out)
    start = time.perf_counter()
    for i in range(warmup, warmup + iters):
        out = step_for_iter(i)(*args)
        args = (out[0], out[1], out[2], args[3])
    jax.block_until_ready(out)
    return (time.perf_counter() - start) / iters


def _run(result: dict) -> None:
    _clear_partial()
    _log('probing backend health')
    probe = _probe_backend()
    _log(f'probe -> {probe}')
    result['probe_seconds'] = round(time.time() - _T0, 1)
    _persist(result)

    import jax

    if probe is None:
        # No healthy accelerator: pin the host platform before first backend
        # init so the wedged axon plugin is never touched in this process.
        # This is a measured-configuration CHANGE (tiny smoke model, float32,
        # EIGEN): the labels below keep it from reading as a TPU number.
        jax.config.update('jax_platforms', 'cpu')
        if os.environ.get('JAX_PLATFORMS') != 'cpu':
            result['fallback'] = 'tpu_probe_failed'

    import jax.numpy as jnp
    import optax

    import kfac_tpu
    from kfac_tpu.models import TransformerLM, lm_loss

    # The probe child held the single-client tunnel claim moments ago; if it
    # isn't released by the time the parent inits, jax.devices() here would
    # hang unkillably (C-level). A watchdog guarantees the JSON line still
    # prints and the process exits with a diagnosable error instead of
    # rc=124 from the driver's outer timeout.
    def _watchdog_fire():
        try:
            where = (
                'TPU backend init hung after healthy probe'
                if probe is not None
                else 'CPU-pinned backend init stalled'
            )
            result['error'] = f'{where} past the 180s watchdog'
            _persist(result)  # stdout may be a broken pipe; disk first
            print(json.dumps(result), flush=True)
        finally:
            os._exit(1)  # must fire even if the dump raced/raised

    watchdog = threading.Timer(180.0, _watchdog_fire)
    watchdog.daemon = True
    watchdog.start()
    try:
        dev = jax.devices()[0]
    finally:
        watchdog.cancel()
    on_tpu = dev.platform != 'cpu'
    result['platform'] = dev.platform
    result['device_kind'] = getattr(dev, 'device_kind', '')
    _log(f'backend up: {dev.platform} {result["device_kind"]}')
    _persist(result)

    # Overall deadline: if any single compile/execute phase stalls past the
    # budget (wedgy tunnel, pathological compile), emit whatever phases
    # completed instead of dying JSON-less under the driver's timeout.
    def _deadline_fire():
        try:
            # snapshot: the main thread may be mutating `result` right now
            out = dict(result)
            out.setdefault('error', 'internal deadline hit; partial results')
            _persist(out)  # stdout may be a broken pipe; disk first
            print(json.dumps(out), flush=True)
        finally:
            os._exit(1)  # must fire even if the dump itself raced

    # The budget is measured from process start (not backend-up) so a long
    # probe phase shrinks the compute budget instead of overrunning the
    # driver's outer timeout.
    deadline = threading.Timer(
        max(
            300.0,
            float(os.environ.get('BENCH_DEADLINE_S', '1350'))
            - (time.time() - _T0),
        ),
        _deadline_fire,
    )
    deadline.daemon = True
    deadline.start()

    if on_tpu:
        batch, seq, d_model, layers, vocab = 16, 512, 512, 6, 8192
        dtype = jnp.bfloat16
        # Clock sanity: time an input-varying bf16 matmul chain with known
        # FLOPs. The axon pool backend has been observed returning
        # impossibly fast timings (cached/elided repeat computations);
        # recording the measured ceiling lets the MFU numbers be read
        # honestly.
        n = 2048
        x0 = jax.random.normal(jax.random.PRNGKey(2), (n, n), jnp.bfloat16)

        @jax.jit
        def chain(x):
            for _ in range(16):
                x = x @ x0 + x
            return x

        x = chain(x0)
        jax.block_until_ready(x)
        t0 = time.perf_counter()
        for _ in range(10):
            x = chain(x)  # input evolves: no result reuse possible
        jax.block_until_ready(x)
        dt = (time.perf_counter() - t0) / 10
        measured = 16 * 2 * n**3 / dt
        result['clock_check_tflops'] = round(measured / 1e12, 1)
        _persist(result)
        _log(f'clock check: {measured / 1e12:.1f} Tflop/s apparent')
    else:  # keep the CPU smoke fast
        batch, seq, d_model, layers, vocab = 4, 128, 128, 2, 512
        dtype = jnp.float32
    result['model_config'] = (
        f'{"tpu_lm" if on_tpu else "cpu_smoke"}'
        f'_L{layers}_d{d_model}_s{seq}_b{batch}_v{vocab}'
    )

    # 4 heads -> head_dim 128: lane-aligned for the Pallas flash-attention
    # kernel (ops/pallas_attention dispatches on d % 128 == 0)
    model = TransformerLM(
        vocab_size=vocab, d_model=d_model, num_heads=4, num_layers=layers,
        max_len=seq, dtype=dtype,
    )
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (batch, seq), 0, vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    params = model.init(jax.random.PRNGKey(1), tokens)['params']
    loss = lm_loss(model)

    # The output head is excluded from K-FAC, as in the reference's LM
    # example (its decoder layer is skipped by default,
    # examples/torch_language_model.py:163-168): the head's G factor is
    # vocab x vocab — an 8192^2 eigendecomposition that costs more than the
    # entire rest of the step and is why second-order methods skip LM heads.
    # Its gradient still flows (SGD-updated), so model FLOPs are unchanged.
    reg = kfac_tpu.register_model(model, tokens, skip_layers=['lm_head'])
    # compute_method is left unset: the library's platform-aware default
    # (kfac_tpu.default_compute_method) picks INVERSE+Newton-Schulz on TPU
    # (eigh lowers to a sequential panel algorithm there; the EIGEN step was
    # measured never to finish compiling inside a 20-minute budget on v5e)
    # and EIGEN — the reference's default — on the CPU smoke config.
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, damping=0.003, lr=0.1,
        factor_update_steps=10, inv_update_steps=100,
    )
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(loss)
    opt = optax.sgd(0.1, momentum=0.9)

    @jax.jit
    def kfac_step_capture(params, kstate, opt_state, batch):
        (l, _), grads, stats = run(params, batch)
        kstate, pgrads = kfac.step(kstate, grads, stats)
        updates, opt_state = opt.update(pgrads, opt_state, params)
        return optax.apply_updates(params, updates), kstate, opt_state, l

    @jax.jit
    def kfac_step_plain(params, kstate, opt_state, batch):
        l, grads = jax.value_and_grad(loss)(params, batch)
        kstate, pgrads = kfac.step(kstate, grads, None)
        updates, opt_state = opt.update(pgrads, opt_state, params)
        return optax.apply_updates(params, updates), kstate, opt_state, l

    @jax.jit
    def sgd_step(params, _unused, opt_state, batch):
        l, grads = jax.value_and_grad(loss)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), _unused, opt_state, l

    data = (tokens, targets)
    _log('timing SGD step (compile + 100 iters)')
    t_sgd = _timeit(lambda i: sgd_step, (params, 0, opt.init(params), data))
    result['sgd_tokens_per_sec'] = round(batch * seq / t_sgd, 1)
    _persist(result)
    _log(f'sgd: {t_sgd * 1e3:.1f} ms/step; timing K-FAC eager steps')
    t_kfac = _timeit(
        lambda i: kfac_step_capture if i % 10 == 0 else kfac_step_plain,
        (params, kfac.init(), opt.init(params), data),
    )
    result['eager_tokens_per_sec'] = round(batch * seq / t_kfac, 1)
    _persist(result)
    _log(f'kfac eager: {t_kfac * 1e3:.1f} ms/step; timing scan loop')

    # Fully-compiled loop: 100 steps as one lax.scan with device-side
    # cadence (Trainer.scan_steps) — no per-step host dispatch. The scan
    # window spans the full inverse cadence, like _timeit's.
    from kfac_tpu import training as training_lib

    trainer = training_lib.Trainer(
        loss_fn=lambda p, ms, b: (loss(p, b), ms), optimizer=opt, kfac=kfac
    )
    scan_steps_n = 100
    scan_batches = (
        jnp.broadcast_to(tokens, (scan_steps_n,) + tokens.shape),
        jnp.broadcast_to(targets, (scan_steps_n,) + targets.shape),
    )
    sstate = trainer.init(params)
    sstate, _ = trainer.scan_steps(sstate, scan_batches)  # compile + warm
    jax.block_until_ready(sstate.params)
    t0 = time.perf_counter()
    sstate, scan_losses = trainer.scan_steps(sstate, scan_batches)
    jax.block_until_ready(scan_losses)
    t_scan = (time.perf_counter() - t0) / scan_steps_n
    _log(f'scan: {t_scan * 1e3:.1f} ms/step; finalizing')

    # Model FLOPs (fwd+bwd = 3x fwd): 6*N per token for the parameter
    # matmuls plus 12*L*d*S per token for self-attention scores/values.
    # Embedding/positional tables are gathers/adds, not matmuls — they carry
    # no 2*p FLOPs per token, so they are excluded from the matmul count
    # (the lm_head output projection is a real matmul and stays in).
    n_params = 0
    n_matmul_params = 0
    for path, p in jax.tree_util.tree_flatten_with_path(params)[0]:
        size = int(p.size)
        n_params += size
        if not any('embed' in str(k).lower() for k in path):
            n_matmul_params += size
    flops_per_step = batch * seq * (
        6 * n_matmul_params + 12 * layers * d_model * seq
    )
    peak = _peak_flops(result['device_kind']) if on_tpu else None

    # headline: the faster K-FAC stepping mode (eager dispatch vs compiled
    # scan loop); both are recorded
    t_best = min(t_kfac, t_scan)
    tokens_per_sec = batch * seq / t_best
    result.update(
        value=round(tokens_per_sec, 1),
        vs_baseline=round(t_sgd / t_best, 4),
        eager_tokens_per_sec=round(batch * seq / t_kfac, 1),
        scan_tokens_per_sec=round(batch * seq / t_scan, 1),
        sgd_tokens_per_sec=round(batch * seq / t_sgd, 1),
        n_params=n_params,
        mfu=(round(flops_per_step / t_best / peak, 4) if peak else None),
        sgd_mfu=(round(flops_per_step / t_sgd / peak, 4) if peak else None),
    )
    if peak and result.get('clock_check_tflops', 0) > peak / 1e12 * 1.1:
        # apparent throughput above the chip's physical peak: the backend's
        # completion signaling is unreliable, so MFU here is an upper bound
        # on trust, not a measurement
        result['timing_suspect'] = True
    deadline.cancel()
    _persist(result)


def main() -> None:
    result = {
        'metric': 'kfac_lm_tokens_per_sec',
        'value': 0.0,
        'unit': 'tokens/s',
        'vs_baseline': 0.0,
        'platform': 'unknown',
    }
    failed = False
    try:
        _run(result)
    except BaseException as exc:  # noqa: BLE001 - JSON line must still print
        result['error'] = f'{type(exc).__name__}: {exc}'
        failed = True
    print(json.dumps(result))
    _persist(result, partial=failed)
    if failed:
        sys.exit(1)


if __name__ == '__main__':
    main()
