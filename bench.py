"""Benchmark: K-FAC-preconditioned Transformer LM training throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures tokens/sec of a jitted K-FAC train step (eigen method, factor
update every 10 steps, inverse update every 100 — the reference's ImageNet
cadence, examples/torch_imagenet_resnet.py:158-167) against the same model
trained with plain SGD on identical hardware in the same process.
``vs_baseline`` is the throughput ratio kfac/sgd: the *cost* of adding
second-order preconditioning (1.0 = free). KAISA's value proposition is
fewer steps to target quality at small per-step overhead.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import optax

import kfac_tpu
from kfac_tpu.models import TransformerLM, lm_loss


def _timeit(step_for_iter, args, warmup: int = 5, iters: int = 30) -> float:
    """Average seconds/step of a cadence-dispatched step sequence.

    ``step_for_iter(i)`` returns the jitted step function for global step i,
    so the measured loop amortizes capture/inverse cadence exactly like a
    real training run.
    """
    out = None
    for i in range(warmup):
        out = step_for_iter(i)(*args)
        args = (out[0], out[1], out[2], args[3])
    jax.block_until_ready(out)
    start = time.perf_counter()
    for i in range(warmup, warmup + iters):
        out = step_for_iter(i)(*args)
        args = (out[0], out[1], out[2], args[3])
    jax.block_until_ready(out)
    return (time.perf_counter() - start) / iters


def main() -> None:
    on_tpu = jax.devices()[0].platform != 'cpu'
    if on_tpu:
        batch, seq, d_model, layers, vocab = 16, 512, 512, 6, 8192
        dtype = jnp.bfloat16
    else:  # keep the CPU smoke fast
        batch, seq, d_model, layers, vocab = 4, 128, 128, 2, 512
        dtype = jnp.float32

    model = TransformerLM(
        vocab_size=vocab, d_model=d_model, num_heads=8, num_layers=layers,
        max_len=seq, dtype=dtype,
    )
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (batch, seq), 0, vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    params = model.init(jax.random.PRNGKey(1), tokens)['params']
    loss = lm_loss(model)

    reg = kfac_tpu.register_model(model, tokens)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, damping=0.003, lr=0.1,
        factor_update_steps=10, inv_update_steps=100,
    )
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(loss)
    opt = optax.sgd(0.1, momentum=0.9)

    @jax.jit
    def kfac_step_capture(params, kstate, opt_state, batch):
        (l, _), grads, stats = run(params, batch)
        kstate, pgrads = kfac.step(kstate, grads, stats)
        updates, opt_state = opt.update(pgrads, opt_state, params)
        return optax.apply_updates(params, updates), kstate, opt_state, l

    @jax.jit
    def kfac_step_plain(params, kstate, opt_state, batch):
        l, grads = jax.value_and_grad(loss)(params, batch)
        kstate, pgrads = kfac.step(kstate, grads, None)
        updates, opt_state = opt.update(pgrads, opt_state, params)
        return optax.apply_updates(params, updates), kstate, opt_state, l

    @jax.jit
    def sgd_step(params, _unused, opt_state, batch):
        l, grads = jax.value_and_grad(loss)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), _unused, opt_state, l

    data = (tokens, targets)
    t_sgd = _timeit(lambda i: sgd_step, (params, 0, opt.init(params), data))
    t_kfac = _timeit(
        lambda i: kfac_step_capture if i % 10 == 0 else kfac_step_plain,
        (params, kfac.init(), opt.init(params), data),
    )

    tokens_per_sec = batch * seq / t_kfac
    print(
        json.dumps(
            {
                'metric': 'kfac_lm_tokens_per_sec',
                'value': round(tokens_per_sec, 1),
                'unit': 'tokens/s',
                'vs_baseline': round(t_sgd / t_kfac, 4),
            }
        )
    )


if __name__ == '__main__':
    main()
