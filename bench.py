"""Benchmark: K-FAC-preconditioned Transformer LM training throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Round-5 architecture — a staged orchestrator (the round-4 lesson: the one
run that reached the chip died silently at the first K-FAC compile and a
later CPU-fallback run overwrote its partial data):

- Every run writes a per-run timestamped record ``bench_runs/run_<ts>.json``
  that nothing ever overwrites; ``bench_partial.json`` is a latest-pointer
  that a CPU-fallback result may NOT clobber when it currently holds a
  TPU-platform result.
- On probe success the stages run smallest-first, each in its OWN
  subprocess with a SIGTERM-grace watchdog, so a wedged XLA compile or a
  dropped tunnel costs one stage, not the run:
    1. ``micro_safe``      tools/tpu_microbench.py --no-pallas (per-op
                           signal on validated XLA ops; cheapest first)
    2. ``lm_tiny``         a 2-layer d128 K-FAC LM step (proves K-FAC
                           compiles+runs on the chip at minimum cost)
    3. ``lm_flagship``     the headline config (Pallas gated OFF —
                           default path, ops validated by stages 1-2)
    4. ``micro_pallas``    tools/tpu_microbench.py --pallas-only (on-chip
                           validation of the gated kernels)
    5. ``lm_flagship_pallas``  the flagship again with KFAC_TPU_PALLAS=1,
                           only if stage 4 passed (measures the kernel win)
  Each stage persists phase-by-phase partials to its own file; the
  orchestrator merges after every stage, so the answer to "what stalled"
  is always on disk (stage name + last announced op).
- With no healthy accelerator the CPU-smoke ``lm_tiny`` stage runs alone,
  as in rounds 1-4.

Measured quantity per LM stage: tokens/sec of a jitted K-FAC train step
(the platform-default compute path: INVERSE + Newton-Schulz on TPU, EIGEN
elsewhere — see kfac_tpu.default_compute_method; factor update every 10
steps, inverse update every 100 — the reference's ImageNet cadence,
examples/torch_imagenet_resnet.py:158-167) against the same model trained
with plain SGD on identical hardware in the same process. ``vs_baseline``
is the throughput ratio kfac/sgd: the *cost* of adding second-order
preconditioning (1.0 = free). KAISA's value proposition is fewer steps to
target quality at small per-step overhead.

Extra fields in the JSON line:
- ``platform`` / ``device_kind``: where the numbers were measured. The TPU
  backend in this container is a single-client tunnel that can be wedged by
  other processes, so availability is probed in a sacrificial subprocess
  (bounded retry); on failure the bench falls back to CPU rather than
  crashing, and says so here.
- ``mfu``: model FLOPs utilization of the K-FAC step — model FLOPs only
  (6*N per token plus the 12*L*d*S attention term, the standard accounting),
  excluding the K-FAC factor/eigh work itself, over the chip's peak bf16
  FLOP/s. ``null`` when the peak for the platform is unknown (CPU).
- ``stages``: per-stage status + key numbers from this run.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

_T0 = time.time()
# stage subprocesses inherit the orchestrator's run id via the env
_RUN_ID = os.environ.get('BENCH_RUN_ID') or time.strftime('%Y%m%d_%H%M%S')


def _log(msg: str) -> None:
    """Phase progress to stderr: a killed-by-outer-timeout run still leaves
    a diagnosable trail (round-1 lesson: rc=124 with an empty log)."""
    print(f'[bench +{time.time() - _T0:7.1f}s] {msg}', file=sys.stderr, flush=True)


def _atomic_write(path: str, payload: dict) -> None:
    tmp = f'{path}.tmp.{os.getpid()}'
    try:
        with open(tmp, 'w') as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except Exception:  # persistence is best-effort; never kill the bench
        try:
            os.unlink(tmp)
        except OSError:
            pass


_CPUISH = (None, '', 'cpu', 'unknown')


def _persist(result: dict, partial: bool = True) -> None:
    """Snapshot the result-so-far after every completed phase.

    Two sinks (``BENCH_PARTIAL_PATH=''`` disables both):
    - ``bench_runs/run_<RUN_ID>.json``: this run's own record; append-only
      across runs, so no later run can destroy this one's data (the
      round-4 data-loss: a TPU SGD measurement survived only in a stderr
      log because a CPU-fallback run overwrote ``bench_partial.json``).
    - ``BENCH_PARTIAL_PATH`` (default ``bench_partial.json``): the latest
      pointer — refreshed EXCEPT when it holds a TPU-platform record and
      this run is CPU-bound, which would destroy strictly better data.
      Because of that guard (and crashes before the first phase), the
      pointer can lag: consumers attribute it by comparing its ``run_id``
      against ``bench_runs/LATEST.json`` (written at every run start by
      :func:`_mark_run_started`).
    """
    path = os.environ.get('BENCH_PARTIAL_PATH', 'bench_partial.json')
    if not path:
        return
    payload = {**result, 'partial': partial, 'run_id': _RUN_ID}
    runs_dir = os.environ.get('BENCH_RUNS_DIR', 'bench_runs')
    try:
        os.makedirs(runs_dir, exist_ok=True)
        _atomic_write(os.path.join(runs_dir, f'run_{_RUN_ID}.json'), payload)
    except Exception:
        pass
    try:
        with open(path) as f:
            existing_platform = json.load(f).get('platform')
    except Exception:
        existing_platform = None
    if (
        existing_platform not in _CPUISH
        and result.get('platform') in _CPUISH
    ):
        return  # never clobber a TPU record with a CPU fallback
    _atomic_write(path, payload)


def _mark_run_started() -> None:
    """Stamp ``bench_runs/LATEST.json`` with this run's id at process
    start. The latest-pointer file may legitimately belong to an OLDER run
    (clobber guard; a run killed pre-first-phase), so attribution goes
    through this marker: ``bench_partial.json`` describes the current run
    iff its ``run_id`` matches ``LATEST.json``'s."""
    if not os.environ.get('BENCH_PARTIAL_PATH', 'bench_partial.json'):
        return
    runs_dir = os.environ.get('BENCH_RUNS_DIR', 'bench_runs')
    try:
        os.makedirs(runs_dir, exist_ok=True)
        _atomic_write(
            os.path.join(runs_dir, 'LATEST.json'),
            {'run_id': _RUN_ID, 'started_unix': round(_T0, 1)},
        )
    except Exception:
        pass


# bf16 peak FLOP/s per chip, keyed by device_kind substring (lowercase).
_PEAK_FLOPS = {
    'v6e': 918e12,
    'v6 lite': 918e12,
    'v5p': 459e12,
    'v5e': 197e12,
    'v5 lite': 197e12,
    'v5': 459e12,
    'v4': 275e12,
    'v3': 123e12,
    'v2': 46e12,
}


def _peak_flops(device_kind: str) -> float | None:
    kind = device_kind.lower()
    # Longest key first so 'v5e'/'v5 lite' can never be shadowed by 'v5'.
    for key in sorted(_PEAK_FLOPS, key=len, reverse=True):
        if key in kind:
            return _PEAK_FLOPS[key]
    return None


def _probe_backend():
    """Check whether the default JAX backend initializes, in a subprocess.

    The axon TPU tunnel hangs `jax.devices()` indefinitely when wedged and
    raises UNAVAILABLE when another client holds the single-client claim
    (observed round 1: rc=1 UNAVAILABLE; round 2: 125 s of timeouts under
    the driver while the same chip probed healthy in 3.9 s moments later).
    Both symptoms are transient, so the first touch happens in a sacrificial
    child and failures are retried with backoff over a multi-minute budget
    (BENCH_PROBE_BUDGET_S, default 420), plus one final grace attempt after
    the budget is spent — the round-2 capture shows the chip coming back
    right after the old 125 s probe gave up. Returns
    (platform, device_kind) or None if no healthy non-CPU backend appeared.
    """
    if os.environ.get('JAX_PLATFORMS') == 'cpu':
        # Platform explicitly pinned to host (CI / CPU smoke) — skip the
        # sacrificial child. An absent axon tunnel does NOT skip: a normal
        # accelerator backend (e.g. libtpu) should still be detected.
        return None
    budget_s = float(os.environ.get('BENCH_PROBE_BUDGET_S', '420'))
    code = (
        'import jax; d = jax.devices()[0]; '
        "print('PROBE', d.platform, getattr(d, 'device_kind', ''))"
    )
    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        remaining = budget_s - (time.monotonic() - start)
        final = remaining <= 0
        timeout_s = 45.0 if final else min(90.0, max(remaining, 30.0))
        # On timeout, SIGTERM with a grace period — SIGKILLing a JAX process
        # mid-TPU-claim is itself a documented tunnel-wedge trigger.
        proc = subprocess.Popen(
            [sys.executable, '-c', code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        rc, stdout = None, ''
        try:
            stdout, _ = proc.communicate(timeout=timeout_s)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=15.0)
            except subprocess.TimeoutExpired:
                proc.kill()  # last resort
                proc.wait()
        if rc == 0:
            for line in stdout.splitlines():
                if line.startswith('PROBE '):
                    parts = line.split(' ', 2)
                    platform = parts[1]
                    kind = parts[2] if len(parts) > 2 else ''
                    if platform != 'cpu':
                        _log(f'probe attempt {attempt}: healthy {platform}')
                        return platform, kind
                    # Default backend is already CPU: no accelerator plugin
                    # registered at all — retrying cannot change that.
                    return None
        _log(
            f'probe attempt {attempt}: '
            f'{"timeout" if rc is None else f"rc={rc}"} '
            f'({time.monotonic() - start:.0f}s / {budget_s:.0f}s budget)'
        )
        if final:
            return None
        time.sleep(min(5.0 + 5.0 * attempt, 30.0))


def _timeit(step_for_iter, args, warmup: int = 5, iters: int = 100) -> float:
    """Average seconds/step of a cadence-dispatched step sequence.

    ``step_for_iter(i)`` returns the jitted step function for global step i,
    so the measured loop amortizes capture/inverse cadence exactly like a
    real training run. The default window of 100 steps (measured steps
    5..104) contains 10 factor captures and exactly one inverse/eigh update
    at step 100 — the full inv_update_steps cadence, so the eigh cost is
    represented at its true 1/100 proportion rather than excluded.
    """
    import jax

    out = None
    for i in range(warmup):
        # per-iteration announcements: warmup i=0 is the capture-variant
        # compile, i=1 the plain variant — a stalled run's last stderr
        # line names which program wedged (the r5s3 lm_large lesson)
        t0 = time.perf_counter()
        out = step_for_iter(i)(*args)
        jax.block_until_ready(out)
        _log(f'  warmup {i}: {time.perf_counter() - t0:.1f}s')
        args = (out[0], out[1], out[2], args[3])
    jax.block_until_ready(out)
    start = time.perf_counter()
    for i in range(warmup, warmup + iters):
        out = step_for_iter(i)(*args)
        args = (out[0], out[1], out[2], args[3])
    jax.block_until_ready(out)
    return (time.perf_counter() - start) / iters


def _async_spike_probe(d: int = 512, window: int = 8, windows: int = 3) -> dict:
    """Per-step latency series of a d>=512 MLP: synchronous boundary
    refresh vs the sliced async backend (``kfac_tpu.async_inverse``).

    Builds its own model rather than reusing the stage's — the refresh
    spike only shows where the boundary eigh (~30 d^3) dominates a step,
    and the CPU-smoke LM never reaches that regime. Reports p50/p95/max
    per-step milliseconds for both paths plus ``refresh_spike_ratio``
    (max step / median step over ``windows`` full cadence windows): the
    sync path spikes multi-x at every boundary, the sliced path must
    stay flat (acceptance bar: <= 1.5).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import kfac_tpu
    from kfac_tpu.models import MLP

    model = MLP(features=(d, d, d), num_classes=32)
    x = jax.random.normal(jax.random.PRNGKey(3), (256, d))
    y = jax.random.normal(jax.random.PRNGKey(4), (256, 32))

    def loss(p, batch):
        xx, yy = batch
        return jnp.mean((model.apply({'params': p}, xx) - yy) ** 2)

    params = model.init(jax.random.PRNGKey(5), x)['params']
    reg = kfac_tpu.register_model(model, x)
    opt = optax.sgd(0.05)

    def series(async_inverse):
        kfac = kfac_tpu.KFACPreconditioner(
            registry=reg, damping=1e-3, lr=0.1,
            factor_update_steps=window, inv_update_steps=window,
            async_inverse=async_inverse,
        )
        run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss)

        @jax.jit
        def step(p, kstate, opt_state, batch):
            (l, _), grads, stats = run(p, batch)
            kstate, pgrads = kfac.step(kstate, grads, stats)
            updates, opt_state = opt.update(pgrads, opt_state, p)
            return optax.apply_updates(p, updates), kstate, opt_state, l

        args = (params, kfac.init(), opt.init(params), (x, y))
        out = None
        for _ in range(window + 1):  # compile + one full warm window
            out = step(*args)
            args = (out[0], out[1], out[2], args[3])
        jax.block_until_ready(out[3])
        times = []
        for _ in range(window * windows):
            t0 = time.perf_counter()
            out = step(*args)
            jax.block_until_ready(out[3])
            times.append((time.perf_counter() - t0) * 1e3)
            args = (out[0], out[1], out[2], args[3])
        return np.asarray(times)

    t_sync = series(None)
    t_sliced = series('sliced')

    def stats(prefix, ts):
        return {
            f'step_p50_ms{prefix}': round(float(np.percentile(ts, 50)), 3),
            f'step_p95_ms{prefix}': round(float(np.percentile(ts, 95)), 3),
            f'step_max_ms{prefix}': round(float(np.max(ts)), 3),
            f'refresh_spike_ratio{prefix}': round(
                float(np.max(ts) / np.median(ts)), 3
            ),
        }

    out = {'async_probe_config': f'mlp_d{d}_b256_w{window}'}
    out.update(stats('', t_sliced))
    out.update(stats('_sync', t_sync))
    return out


def _compression_probe(d: int = 256, steps: int = 24) -> dict:
    """Compressed-transport + cold-factor-offload probe
    (docs/ARCHITECTURE.md "Compression & offload").

    A/B's the distributed bucketed engine on the same MLP at the f32 vs
    int8 wire: reports the static wire-bytes ratio from
    ``comms_report()`` (the >= 3x acceptance figure) next to eager
    per-step medians for both wires. Then runs a short eager offload
    Trainer loop (factor cadence 8, ``min_cold_steps=2``,
    ``prefetch_lead=1``) and reports the live ``OffloadManager``
    counters — ``prefetch_hit_rate`` 1.0 means every restore found its
    host->device transfer already in flight.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import kfac_tpu
    from kfac_tpu import training
    from kfac_tpu.models import MLP
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    model = MLP(features=(d, d), num_classes=16)
    x = jax.random.normal(jax.random.PRNGKey(6), (128, d))
    y = jax.random.normal(jax.random.PRNGKey(7), (128, 16))
    params = model.init(jax.random.PRNGKey(8), x)['params']
    reg = kfac_tpu.register_model(model, x)

    def loss(p, batch):
        xx, yy = batch
        return jnp.mean((model.apply({'params': p}, xx) - yy) ** 2)

    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss)
    mesh = kaisa_mesh(grad_worker_fraction=1.0)

    def series(stat_compression):
        cfg = kfac_tpu.KFACPreconditioner(
            registry=reg, damping=1e-3, lr=0.1,
            allreduce_method='allreduce_bucketed',
            stat_compression=stat_compression,
        )
        eng = DistributedKFAC(config=cfg, mesh=mesh)

        @jax.jit
        def step(state, p, batch):
            (l, _), grads, stats = run(p, batch)
            return eng.step(state, grads, stats, loss=l)

        state = eng.init()
        state, pg = step(state, params, (x, y))  # compile — excluded
        jax.block_until_ready(pg)
        times = []
        for _ in range(10):
            t0 = time.perf_counter()
            state, pg = step(state, params, (x, y))
            jax.block_until_ready(pg)
            times.append((time.perf_counter() - t0) * 1e3)
        return float(np.median(times)), eng.comms_report()['stat_transport']

    t_f32, st_f32 = series(None)
    t_int8, st_int8 = series('int8')
    out = {
        'compression_probe_config': f'mlp_d{d}_b128_bucketed',
        'wire_ratio_int8': round(
            st_int8['raw_bytes'] / st_int8['wire_bytes'], 3),
        'stat_wire_bytes_f32': st_f32['wire_bytes'],
        'stat_wire_bytes_int8': st_int8['wire_bytes'],
        'step_p50_ms_f32_wire': round(t_f32, 3),
        'step_p50_ms_int8_wire': round(t_int8, 3),
    }

    # cold-factor offload: the eager Trainer loop is what drives the
    # host-side pump, so the counters only move on this path
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, damping=1e-3, lr=0.1,
        factor_update_steps=8, inv_update_steps=8,
        offload=kfac_tpu.OffloadConfig(min_cold_steps=2, prefetch_lead=1),
    )

    def loss3(p, model_state, batch):
        return loss(p, batch), model_state

    trainer = training.Trainer(
        loss_fn=loss3, optimizer=optax.sgd(0.05), kfac=kfac
    )
    tstate = trainer.init(params)
    last = None
    for _ in range(steps):
        tstate, last = trainer.step(tstate, (x, y))
    jax.block_until_ready(last)
    counters = dict(trainer.kfac._offload_manager.stats)
    attempts = counters['prefetch_hits'] + counters['prefetch_misses']
    counters['prefetch_hit_rate'] = (
        round(counters['prefetch_hits'] / attempts, 3) if attempts else None
    )
    out['offload'] = counters
    return out


def _fleet_probe(steps: int = 6) -> dict:
    """Self-driving fleet probe (docs/ROBUSTNESS.md "Self-driving fleet").

    Drives a tiny fleet-managed Trainer with a skew-injecting drain
    (``testing/faults.skewed_drain``) so the drift detector arms a
    model-only retune and executes a live layout migration at the first
    checkpoint boundary. Reports the retune wall-clock (the cost-model
    fast path the controller runs in-job), the end-to-end migration
    wall-clock (blocking save -> rebuild -> elastic restore -> swap) and
    the migration downtime in steps (boundary step minus arming step —
    the window the job kept training on the stale layout). The HBM
    budget handed to the cost model is sized between the MEM-OPT and
    COMM-OPT footprints so the retune MUST move off the starting
    COMM-OPT layout.
    """
    import tempfile
    import warnings as pywarnings

    import jax
    import jax.numpy as jnp
    import optax

    import kfac_tpu
    from kfac_tpu.autotune import model as autotune_model
    from kfac_tpu.autotune import search as autotune_search
    from kfac_tpu.models import MLP
    from testing import faults

    # d=16 keeps the cost-model ranking honest for the story below:
    # unconstrained, COMM-OPT genuinely wins (comm-free grad workers),
    # so the starting plan is a real frac-1.0 layout; under the tight
    # budget the frac-1.0 footprint is infeasible and MEM-OPT takes it
    d = 16
    world = jax.device_count()
    model = MLP(features=(d, d), num_classes=8)
    x = jax.random.normal(jax.random.PRNGKey(9), (64, d))
    y = jax.random.normal(jax.random.PRNGKey(10), (64, 8))
    params = model.init(jax.random.PRNGKey(11), x)['params']
    reg = kfac_tpu.register_model(model, x)

    def loss_fn(p, model_state, batch):
        xx, yy = batch
        pred = model.apply({'params': p}, xx)
        return jnp.mean((pred - yy) ** 2), model_state

    def bare():
        return kfac_tpu.KFACPreconditioner(
            registry=reg, damping=1e-3, lr=0.1, flight=8
        )

    # the stale starting point: a plan genuinely tuned to COMM-OPT
    plan = autotune_search.autotune(
        bare(), measure=False, world=world,
        fractions=(1.0,), granularities=(1,),
    )
    rows = [
        autotune_model.predict(c, bare(), world)
        for c in autotune_search.baseline_candidates(world, bare())
    ]
    mems = sorted(r['memory_per_device_bytes']['total'] for r in rows)
    tight = autotune_model.HardwareSpec(hbm_bytes=(mems[0] + mems[-1]) / 2)

    with tempfile.TemporaryDirectory() as td:
        mgr = kfac_tpu.CheckpointManager(
            td, save_interval_steps=4, keep=2,
            install_signals=(), async_save=False,
        )
        ctrl = kfac_tpu.FleetController(
            mgr,
            kfac_tpu.FleetConfig(
                check_every=2, drift_keys=('grad_norm',),
                drift_threshold=0.5, drift_window=2, drift_patience=1,
                cooldown_steps=8,
            ),
            plan=plan, hardware=tight,
            drain=faults.skewed_drain('grad_norm', 2.0),
        )
        trainer = kfac_tpu.Trainer(
            loss_fn=loss_fn, optimizer=optax.sgd(0.05),
            kfac=bare(), fleet=ctrl,
        )
        workers_before = ctrl.engine.grad_workers
        state = trainer.init(params)
        with pywarnings.catch_warnings():
            pywarnings.simplefilter('ignore')
            for _ in range(steps):
                state, last = trainer.step(state, (x, y))
        jax.block_until_ready(last)
        return {
            'fleet_probe_config': f'mlp_d{d}_world{world}',
            'migrations': ctrl.stats['migrations'],
            'aborts': ctrl.stats['aborts'],
            'retune_wall_s': round(ctrl.stats['retune_s'] or 0.0, 6),
            'migration_wall_s': round(ctrl.stats['migration_s'] or 0.0, 3),
            'migration_downtime_steps': ctrl.stats['downtime_steps'],
            'grad_workers_before': workers_before,
            'grad_workers_after': ctrl.engine.grad_workers,
            'events': [e['event'] for e in ctrl.events],
        }


def _pipeline_probe() -> dict:
    """3D-planner pipeline-schedule probe (docs/AUTOTUNE.md "3D topology
    planner").

    Folds the committed measured-vs-predicted bubble table
    (``kfac_tpu/planner/bubble_table.json``) into the round JSON: per
    ``(schedule, p, v)`` the simulator's predicted bubble fraction, the
    measured fraction, the p50 step wall-clock, and the floor-verdict
    flag, under the one-dispatch harness provenance the measured tier
    recorded (harness_version / dispatch_mode / dispatches). Read-only —
    it loads the artifact rather than re-measuring, so a bench round
    stays bounded while still publishing how far each schedule's
    wall-clock sits from its simulated prediction.
    """
    from kfac_tpu.planner import execute

    table = execute.load_bubble_table(execute.ARTIFACT_PATH)
    if not table:
        return {'status': 'missing'}
    rows = [
        {
            'schedule': r['schedule'], 'p': r['p'], 'v': r['v'],
            'predicted_fraction': round(r['predicted_fraction'], 4),
            'measured_fraction': round(r['measured']['fraction'], 4),
            'wall_clock_p50_s': r['measured']['wall_clock_p50_s'],
            'contaminated': r['contaminated'],
        }
        for r in table['rows']
    ]
    return {
        'status': 'ok',
        'schema': table['schema'],
        'tolerance': table['tolerance'],
        'clean_rows': sum(not r['contaminated'] for r in rows),
        'rows': rows,
        'provenance': table.get('provenance', {}),
    }


def _chaos_probe() -> dict:
    """Chaos-harness recovery SLOs (docs/ROBUSTNESS.md "Chaos harness").

    Folds the committed storm artifact
    (``kfac_tpu/resilience/chaos_slo.json``, written by
    ``tools/kfac_chaos.py --out``) into the round JSON: per fault class
    the measured downtime steps, recovery wall-clock, restore fallback
    depth, and worst divergence vs the uninterrupted control run, plus
    the storm's shape and whether every SLO budget held. Read-only — a
    storm spawns a real multi-process pod (minutes), so bench rounds
    publish the last measured storm rather than re-running one.
    """
    from kfac_tpu.resilience import chaos

    artifact = chaos.load_slo_artifact()
    if artifact is None:
        return {'status': 'missing'}
    cfg = artifact.get('config', {})
    return {
        'status': 'ok' if artifact.get('ok') else 'blown',
        'rows': artifact['rows'],
        'procs': cfg.get('procs'),
        'max_steps': cfg.get('max_steps'),
        'schedule': [e.get('fault') for e in artifact.get('schedule', ())],
        'blown': artifact.get('blown', []),
    }


def _ledger_probe(result: dict) -> dict:
    """Perf-regression sentinel verdict (docs/OBSERVABILITY.md "Run
    ledger"): this round's headline keys vs the committed baseline
    ``bench_runs/LEDGER.json``, per-key ok/regressed/missing plus a
    top-level status. Provenance-aware — a CPU-fallback round is never
    compared against TPU medians (status ``refused``), and a missing
    baseline is ``no_baseline``, not a failure. Read-only and advisory
    inside the round: CI gates on ``tools/kfac_ledger.py --check``,
    whose exit code carries the same verdict.
    """
    try:
        import importlib.util

        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            'kfac_tpu', 'observability', 'ledger.py')
        spec = importlib.util.spec_from_file_location('_kfac_ledger', path)
        assert spec is not None and spec.loader is not None
        ledger = importlib.util.module_from_spec(spec)
        sys.modules['_kfac_ledger'] = ledger
        spec.loader.exec_module(ledger)
        baseline_path = os.path.join(
            os.environ.get('BENCH_RUNS_DIR', 'bench_runs'), 'LEDGER.json')
        baseline = (ledger.load_baseline(baseline_path)
                    if os.path.exists(baseline_path) else None)
        verdict = ledger.sentinel_check(result, baseline)
        return {
            'status': verdict['status'],
            'regressed_keys': verdict['regressed_keys'],
            'baseline_platform': verdict['baseline_platform'],
            'keys': {k: v['verdict'] for k, v in verdict['keys'].items()},
        }
    except Exception as exc:  # never kill the round over the sentinel
        return {'status': 'error', 'error': f'{type(exc).__name__}: {exc}'}


def _fused_kernel_probe(d: int = 256, rows: int = 512) -> dict:
    """Within-run A/B of the fused step-path kernels vs their unfused
    XLA expressions (docs/ARCHITECTURE.md "Fused step-path kernels").

    Per family (cov_ema / ns / klclip): p50 wall-clock of each variant,
    timed back-to-back in THIS process so the comparison shares one
    host-load regime, plus per-variant device milliseconds attributed
    from a short profiler trace when the backend has device lanes
    (empty off-TPU — the host p50s stand alone). Off-TPU the fused
    variants run in interpret mode, so their numbers measure the
    emulation, not Mosaic; the ``interpret`` flag says which regime the
    record is from.
    """
    import jax
    import jax.numpy as jnp

    from kfac_tpu.ops import pallas_cov_ema, pallas_ns

    interp = pallas_ns.interpret_mode()
    a = jax.random.normal(jax.random.PRNGKey(7), (rows, d), jnp.float32)
    eye = jnp.eye(d, dtype=jnp.float32)
    cov = a.T @ a / rows + 0.003 * eye
    x0 = eye / jnp.trace(cov)
    mx0 = cov @ x0
    gmat = 0.5 * cov + 0.1 * eye
    beta, coeff = 0.95, 0.05 / rows

    def ema_unfused(f, x):
        acc = jax.lax.dot_general(
            x, x, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return beta * f + coeff * acc

    def ns_unfused(mm, x, mx):
        y = x @ (2.0 * eye - mx)
        my = mm @ y
        return y, my, jnp.linalg.norm(eye - my) / jnp.sqrt(float(d))

    def kl_unfused(p, g):
        return p * jnp.sum(p * g)

    def kl_fused(p, g):
        s = pallas_ns.fused_klclip_dot(p, g, interpret=interp)
        return pallas_ns.fused_klclip_scale(p, s, interpret=interp)

    pairs = {
        'cov_ema': (ema_unfused,
                    lambda f, x: pallas_cov_ema._fused(
                        f, x, beta, coeff, interpret=interp),
                    (eye, a)),
        'ns': (ns_unfused,
               lambda mm, x, mx: pallas_ns.fused_ns_step(
                   mm, x, mx, interpret=interp),
               (cov, x0, mx0)),
        'klclip': (kl_unfused, kl_fused, (cov, gmat)),
    }

    def p50_ms(fn, args, n=9):
        jax.block_until_ready(fn(*args))  # compile outside the clock
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return round(ts[len(ts) // 2] * 1e3, 3)

    out: dict = {'config': f'd{d}_rows{rows}', 'interpret': interp}
    jitted: dict = {}
    for fam, (unfused, fused, args) in pairs.items():
        scopes = {}
        for variant, fn in (('unfused', unfused), ('fused', fused)):
            name = f'fused_probe.{fam}_{variant}'
            scopes[variant] = name
            jitted[name] = (
                jax.jit(lambda *xs, _f=fn, _n=name: (
                    jax.named_scope(_n)(_f)(*xs)
                )),
                args,
            )
        row = {'unfused_p50_ms': p50_ms(*jitted[scopes['unfused']])}
        try:
            row['fused_p50_ms'] = p50_ms(*jitted[scopes['fused']])
            row['speedup'] = round(
                row['unfused_p50_ms'] / max(row['fused_p50_ms'], 1e-9), 3
            )
        except Exception as exc:  # one variant's failure costs one row
            row['fused_error'] = f'{type(exc).__name__}: {exc}'
        out[fam] = row

    # device-truth attribution: trace one pass over every variant and
    # attribute device lanes per probe scope (empty off-TPU)
    try:
        from kfac_tpu.observability import profiler, trace_attrib

        tdir = tempfile.mkdtemp(prefix='fused_probe_trace_')
        order = list(jitted)

        def _traced(i):
            fn, args = jitted[order[i % len(order)]]
            return fn(*args)

        profiler.capture_steps(tdir, _traced, steps=len(order))
        device = trace_attrib.device_breakdown_ms(tdir, scopes=order)
        if device:
            out['device_ms'] = device
    except Exception as exc:
        out['trace_error'] = f'{type(exc).__name__}: {exc}'
    return out


def _compile_probe(reg, run, params, data) -> dict:
    """Compile & memory truth probe (docs/OBSERVABILITY.md "Compile &
    memory truth").

    Routes a watched ``step`` on BOTH engines through the compile watch
    and reports: per-entry lowering/compile wall-clock and XLA-reported
    memory (``memory_analysis``), the recompile count after warm
    re-steps — the "jit cache stays at 1" pin as a bench headline, must
    be 0 on both engines — and the process persistent compile-cache
    hit/miss counters (``jax.monitoring``) as deltas over the probe, so
    a round can tell a warm-cache start from a cold one.
    """
    import jax

    import kfac_tpu
    from kfac_tpu.observability import compile_watch as compile_watch_lib
    from kfac_tpu.parallel import DistributedKFAC

    counters = compile_watch_lib.persistent_cache_counters()
    before = counters.snapshot()
    out: dict = {'entries': {}, 'recompiles_after_warmup': {}}

    (_, _), grads, stats = jax.jit(run)(params, data)

    def dense():
        return kfac_tpu.KFACPreconditioner(
            registry=reg, compile_watch=True)

    def distributed():
        return DistributedKFAC(config=kfac_tpu.KFACPreconditioner(
            registry=reg, compile_watch=True))

    for label, build in (('dense', dense), ('distributed', distributed)):
        engine = build()
        step = engine.watched('step')
        state = engine.init()
        for _ in range(3):  # first call compiles; the rest must not
            state, _ = step(state, grads, stats)
        jax.block_until_ready(state)
        watch = engine.compile_watcher()
        out['recompiles_after_warmup'][label] = watch.recompile_count()
        report = engine.compiled_memory_report()
        for name, snap in report.items():
            event = watch.events_for(name)[-1]
            out['entries'][name] = {
                'lowering_s': round(event['lowering_s'], 3),
                'compile_s': round(event['compile_s'], 3),
                'compiles': watch.compile_count(name),
                'hbm_bytes': snap['hbm_bytes'],
            }

    after = counters.snapshot()
    out['persistent_cache'] = {
        'hits': (after['persistent_cache_hits']
                 - before['persistent_cache_hits']),
        'misses': (after['persistent_cache_misses']
                   - before['persistent_cache_misses']),
        'dir': after['persistent_cache_dir'],
        'counters_installed': counters.installed,
    }
    return out


_SERVING_SHAPES = (8, 32, 64)


def _serving_probe() -> dict:
    """Posterior serving probe (docs/SERVING.md): request latency
    p50/p95 and requests/s at three batch shapes through BOTH compiled
    paths (MC predictive and closed-form last-layer variance), the
    cold-vs-warm AOT warmup A/B over a fresh persistent compile cache
    (warm must be faster — the disk cache is what makes replica
    bring-up cheap), and the steady-state recompile count, which must
    be 0: every batch shape lands in a pre-compiled padding bucket.

    Latencies come from the warm engine so the numbers describe a
    replica in steady state, not one paying first-compile costs.
    """
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    import kfac_tpu
    from kfac_tpu import health as health_lib
    from kfac_tpu.models import MLP
    from kfac_tpu.serving import ServingConfig, ServingEngine

    # toy classifier: one factor update is all the export needs
    m = MLP(features=(8,), num_classes=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 6))
    y = jax.random.randint(jax.random.PRNGKey(2), (64,), 0, 4)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, health=health_lib.HealthConfig(warn=False))

    def loss_fn(p, b):
        xx, yy = b
        logits = m.apply({'params': p}, xx)
        onehot = jax.nn.one_hot(yy, 4)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))

    cap = kfac_tpu.CurvatureCapture(reg)
    _, grads, stats = cap.value_stats_and_grad(loss_fn)(params, (x, y))
    state = kfac.update_factors(kfac.init(), stats)

    post_dir = tempfile.mkdtemp(prefix='serving_probe_post_')
    kfac_tpu.export_posterior(
        kfac, state, params, post_dir,
        config=kfac_tpu.laplace.LaplaceConfig(mode='last_layer'),
        overwrite=True,
    )
    post = kfac_tpu.load_posterior(post_dir)

    def apply_fn(p, xx):
        return m.apply({'params': p}, xx)

    def phi_fn(p, xx):
        h = xx.reshape(xx.shape[0], -1)
        return jax.nn.relu(h @ p['dense0']['kernel'] + p['dense0']['bias'])

    cfg = ServingConfig(
        bucket_granularity=8, max_batch=64, n_samples=8,
        warmup_batches=_SERVING_SHAPES,
    )

    def build():
        return ServingEngine(post, apply_fn, phi_fn=phi_fn, config=cfg)

    # cold-vs-warm A/B over a FRESH persistent cache dir: engine A pays
    # real XLA compiles and populates the disk cache; engine B re-traces
    # the same programs and must warm-start from it, measurably faster
    cache_dir = tempfile.mkdtemp(prefix='serving_probe_cache_')
    saved = {
        k: getattr(jax.config, k)
        for k in ('jax_compilation_cache_dir',
                  'jax_persistent_cache_min_entry_size_bytes',
                  'jax_persistent_cache_min_compile_time_secs')
    }
    # the cache enable/disable decision latches at the process's first
    # compile — reset so the fresh dir takes effect mid-process (and
    # again afterwards so the rest of the stage keeps its own cache)
    from jax._src import compilation_cache as cc_lib

    try:
        jax.config.update('jax_compilation_cache_dir', cache_dir)
        jax.config.update('jax_persistent_cache_min_entry_size_bytes', 0)
        jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.0)
        cc_lib.reset_cache()
        key = jax.random.PRNGKey(0)
        cold = build().warmup(x_spec=x[:1], key=key)
        eng = build()
        warm = eng.warmup(x_spec=x[:1], key=key)
    finally:
        for k, v in saved.items():
            jax.config.update(k, v)
        cc_lib.reset_cache()

    out: dict = {
        'warmup_cold': cold,
        'warmup_warm': warm,
        'warm_faster': warm['seconds'] < cold['seconds'],
        'shapes': {},
    }

    paths = ['mc']
    if eng.closed_form_available:
        paths.append('closed_form')
    for b in _SERVING_SHAPES:
        xb = x[:b]
        for path in paths:
            lats = []
            for i in range(20):
                res = eng.serve(
                    xb, key=jax.random.PRNGKey(100 + i), path=path)
                lats.append(res.latency_s)
            p50 = float(np.percentile(lats, 50)) * 1e3
            p95 = float(np.percentile(lats, 95)) * 1e3
            out['shapes'][f'{path}.b{b}'] = {
                'batch': b,
                'p50_ms': round(p50, 3),
                'p95_ms': round(p95, 3),
                'requests_per_sec': round(b / (p50 / 1e3), 1),
            }
    out['recompiles_after_warmup'] = eng.recompiles_after_warmup()

    # flat headline keys at the biggest shape — the DEFAULT_SENTINEL_KEYS
    # surface the perf sentinel gates (latency lower-is-better)
    big = _SERVING_SHAPES[-1]
    for path, tag in (('mc', 'mc'), ('closed_form', 'cf')):
        row = out['shapes'].get(f'{path}.b{big}')
        if row is None:
            continue
        out[f'serving_{tag}_p50_ms'] = row['p50_ms']
        out[f'serving_{tag}_p95_ms'] = row['p95_ms']
        out[f'serving_{tag}_requests_per_sec'] = row['requests_per_sec']
    eng.close()
    return out


def _obs_probe(result, out_path, reg, run, loss, opt, params, data):
    """Observability probe: per-step metrics JSONL, metrics-on overhead vs
    a metrics-off loop timed back-to-back, and a phase-level step-time
    breakdown.

    Exercises the telemetry spine (docs/OBSERVABILITY.md) on the same
    model the stage just timed. The overhead A/B re-times the metrics-off
    loop here rather than reusing the stage's earlier K-FAC figure —
    minutes-apart measurements on a shared host drift by more than the
    overhead being measured. The caller guards it: a probe failure is
    recorded (``obs_probe_error``) but never kills the stage's headline.
    """
    import jax
    import optax

    import kfac_tpu
    from kfac_tpu.observability import sinks

    def build(metrics):
        kfac = kfac_tpu.KFACPreconditioner(
            registry=reg, damping=0.003, lr=0.1,
            factor_update_steps=10, inv_update_steps=100,
            metrics=metrics,
        )

        @jax.jit
        def cap_step(params, kstate, opt_state, batch):
            (l, _), grads, stats = run(params, batch)
            kstate, pgrads = kfac.step(kstate, grads, stats)
            updates, opt_state = opt.update(pgrads, opt_state, params)
            return optax.apply_updates(params, updates), kstate, opt_state, l

        @jax.jit
        def plain_step(params, kstate, opt_state, batch):
            l, grads = jax.value_and_grad(loss)(params, batch)
            kstate, pgrads = kfac.step(kstate, grads, None)
            updates, opt_state = opt.update(pgrads, opt_state, params)
            return optax.apply_updates(params, updates), kstate, opt_state, l

        return kfac, cap_step, plain_step

    kfac_m, cap_step, plain_step = build(True)

    # 12-step eager loop draining the in-jit metrics to JSONL per step —
    # the documented training-loop integration, verbatim
    collector = kfac_tpu.MetricsCollector()
    mpath = out_path + '.metrics.jsonl'
    args = (params, kfac_m.init(), opt.init(params), data)
    out = None
    with sinks.JSONLWriter(mpath, append=False) as w:
        for i in range(12):
            fn = cap_step if i % 10 == 0 else plain_step
            out = fn(*args)
            args = (out[0], out[1], out[2], args[3])
            w.write(collector.drain(out[1]))
    jax.block_until_ready(out)
    result['metrics_jsonl'] = mpath
    # one compiled program per dispatch variant; anything above 2 means
    # the metrics state retriggered compilation across steps
    result['metrics_compilations'] = (
        cap_step._cache_size() + plain_step._cache_size())

    # metrics on/off A/B, alternating rounds back-to-back so shared-host
    # load drift hits both sides equally (acceptance bar: < 5%)
    kfac_o, cap_o, plain_o = build(None)
    t_on = t_off = float('inf')
    for _ in range(2):
        t_off = min(t_off, _timeit(
            lambda i: cap_o if i % 10 == 0 else plain_o,
            (params, kfac_o.init(), opt.init(params), data),
            warmup=2, iters=40,
        ))
        t_on = min(t_on, _timeit(
            lambda i: cap_step if i % 10 == 0 else plain_step,
            (params, kfac_m.init(), opt.init(params), data),
            warmup=2, iters=40,
        ))
    result['metrics_overhead_pct'] = round((t_on / t_off - 1.0) * 100.0, 2)

    # phase-level breakdown: each engine phase jitted alone and timed to
    # completion — where a step's milliseconds actually go
    phases: dict = {}

    def _phase(name, fn, *a, n=10):
        o = fn(*a)
        jax.block_until_ready(o)
        t0 = time.perf_counter()
        for _ in range(n):
            o = fn(*a)
        jax.block_until_ready(o)
        phases[name] = round((time.perf_counter() - t0) / n * 1e3, 3)
        return o

    kstate = kfac_m.init()
    jrun = jax.jit(run)
    (_, _), grads, stats = jrun(params, data)
    _phase('capture_ms', jrun, params, data)
    kstate = _phase('factors_ms', jax.jit(kfac_m.update_factors),
                    kstate, stats)
    kstate = _phase('inverses_ms', jax.jit(kfac_m.update_inverses), kstate)
    _phase('precondition_ms', jax.jit(kfac_m.precondition), kstate, grads)
    result['step_breakdown_ms'] = phases

    # device-truth counterpart of the host-clock phases above: capture a
    # short profiler trace of annotated steps and attribute its DEVICE
    # lanes per __kfac_scope__ (the host clocks include dispatch latency;
    # the trace numbers are chip-side — docs/OBSERVABILITY.md
    # "Measurement truth"). Empty off-TPU (no device lanes) — host
    # numbers stand alone and no key is emitted.
    try:
        from kfac_tpu.observability import profiler, trace_attrib

        tdir = out_path + '.trace'
        carry = list(args)

        def _traced_step(i):
            out = plain_step(*carry)
            carry[:3] = out[0], out[1], out[2]
            return out

        profiler.capture_steps(tdir, _traced_step, steps=3)
        device = trace_attrib.device_breakdown_ms(tdir)
        if device:
            phases['device'] = device
        result['trace_dir'] = tdir
    except Exception as exc:  # the probe never kills the headline
        result['trace_attrib_error'] = f'{type(exc).__name__}: {exc}'

    # async refresh spike probe, after the headline breakdown is safe on
    # disk — a failure here surfaces as obs_probe_error without losing it
    _atomic_write(out_path, result)
    _log('  async refresh spike probe (sync vs sliced, d=512)')
    phases.update(_async_spike_probe())
    result['step_breakdown_ms'] = phases

    # compressed-wire + offload probe, same guarded-by-caller contract
    _atomic_write(out_path, result)
    _log('  compression/offload probe (int8 vs f32 wire, cold factors)')
    result['compression_probe'] = _compression_probe()

    # self-driving fleet probe: drift retune + live migration downtime
    _atomic_write(out_path, result)
    _log('  fleet probe (model-only retune + migration downtime)')
    result['fleet_probe'] = _fleet_probe()

    # 3D-planner schedule table: measured-vs-predicted bubble fractions
    _atomic_write(out_path, result)
    _log('  pipeline probe (bubble table: measured vs simulated)')
    result['pipeline_probe'] = _pipeline_probe()

    # fused step-path kernel A/B: fused vs unfused, same process
    _atomic_write(out_path, result)
    _log('  fused kernel probe (cov+EMA / NS / kl-clip, fused vs unfused)')
    result['fused_kernel_probe'] = _fused_kernel_probe()

    # chaos-harness SLOs: committed storm artifact, read-only
    _atomic_write(out_path, result)
    _log('  chaos probe (preemption-storm recovery SLOs, committed artifact)')
    result['chaos_probe'] = _chaos_probe()

    # compile & memory truth: recompile attribution + XLA memory + cache
    _atomic_write(out_path, result)
    _log('  compile probe (recompile attribution + XLA memory + cache hit/miss)')
    result['compile_probe'] = _compile_probe(reg, run, params, data)

    # posterior serving tier: bucketed latency + cold/warm warmup A/B
    _atomic_write(out_path, result)
    _log('  serving probe (p50/p95 both paths, cold-vs-warm AOT warmup)')
    probe = _serving_probe()
    result['serving_probe'] = probe
    # lift the sentinel-gated flat keys (DEFAULT_SENTINEL_KEYS) so the
    # ledger probe can diff them against the committed baseline
    for k in ('serving_mc_p50_ms', 'serving_mc_p95_ms',
              'serving_cf_p50_ms', 'serving_cf_p95_ms',
              'serving_mc_requests_per_sec', 'serving_cf_requests_per_sec'):
        if k in probe:
            result[k] = probe[k]


# ---------------------------------------------------------------------------
# LM measurement stage (runs in its own subprocess: `bench.py --stage lm`)
# ---------------------------------------------------------------------------

_LM_CONFIGS = {
    # smallest-first: prove a K-FAC step compiles+executes on the chip at
    # minimum compile cost before paying for the flagship
    'tiny': dict(batch=4, seq=128, d_model=128, layers=2, vocab=512),
    'flagship': dict(batch=16, seq=512, d_model=512, layers=6, vocab=8192),
    # manual-only configs (not in the orchestrator plan; run via
    # `bench.py --stage lm --config <name>` in a chip session):
    # 'large' amortizes tunnel dispatch over bigger matmuls for an honest
    # MFU reading; 'longctx' puts s_k=2048 attention in range of the flash
    # kernel's measured win regime for an end-to-end A/B.
    'large': dict(batch=8, seq=1024, d_model=1024, layers=8, vocab=8192),
    'longctx': dict(batch=4, seq=2048, d_model=512, layers=6, vocab=8192),
}


def _claim_backend(result: dict, out_path: str, tag: str):
    """First backend touch under a watchdog; records platform fields.

    Backend init can hang unkillably (C-level) if the tunnel's
    single-client claim wasn't released; guarantee this process exits
    with a diagnosable record instead of eating the whole stage budget.
    """
    import jax

    def _watchdog_fire():
        try:
            result['error'] = 'backend init hung past the 180s watchdog'
            _atomic_write(out_path, result)
        finally:
            os._exit(3)

    watchdog = threading.Timer(180.0, _watchdog_fire)
    watchdog.daemon = True
    watchdog.start()
    try:
        dev = jax.devices()[0]
    finally:
        watchdog.cancel()
    result['platform'] = dev.platform
    result['device_kind'] = getattr(dev, 'device_kind', '')
    _log(f'{tag}: backend up: {dev.platform} {result["device_kind"]}')
    _atomic_write(out_path, result)
    return dev


def run_lm_stage(config_name: str, out_path: str) -> None:
    """Measure SGD vs K-FAC LM throughput at one config; write phase-by-
    phase partials to ``out_path`` so a watchdog kill preserves everything
    measured so far."""
    cfg = _LM_CONFIGS[config_name]
    result: dict = {'stage': f'lm_{config_name}', 'run_id': _RUN_ID}
    tp = _active_plan()
    if tp is not None:
        result['tuned_plan'] = tp
    dev = _claim_backend(result, out_path, f'lm_{config_name}')
    on_tpu = dev.platform != 'cpu'

    import jax
    import jax.numpy as jnp
    import optax

    import kfac_tpu
    from kfac_tpu.models import TransformerLM, lm_loss

    batch, seq = cfg['batch'], cfg['seq']
    d_model, layers, vocab = cfg['d_model'], cfg['layers'], cfg['vocab']
    dtype = jnp.bfloat16 if on_tpu else jnp.float32

    if on_tpu:
        # Clock sanity: time an input-varying bf16 matmul chain with known
        # FLOPs. The axon pool backend has been observed returning
        # impossibly fast timings (cached/elided repeat computations);
        # recording the measured ceiling lets the MFU numbers be read
        # honestly.
        n = 2048
        x0 = jax.random.normal(jax.random.PRNGKey(2), (n, n), jnp.bfloat16)

        @jax.jit
        def chain(x):
            for _ in range(16):
                x = x @ x0 + x
            return x

        _log(f'lm_{config_name}: clock check')
        x = chain(x0)
        jax.block_until_ready(x)
        t0 = time.perf_counter()
        for _ in range(10):
            x = chain(x)  # input evolves: no result reuse possible
        jax.block_until_ready(x)
        dt = (time.perf_counter() - t0) / 10
        measured = 16 * 2 * n**3 / dt
        result['clock_check_tflops'] = round(measured / 1e12, 1)
        _atomic_write(out_path, result)
        _log(f'lm_{config_name}: clock {measured / 1e12:.1f} Tflop/s '
             'apparent')

    result['model_config'] = (
        f'{"tpu_lm" if on_tpu else "cpu_smoke"}'
        f'_L{layers}_d{d_model}_s{seq}_b{batch}_v{vocab}'
    )

    # 4 heads -> head_dim = d_model/4: lane-aligned at the flagship's d512
    # for the (gated) Pallas flash-attention kernel
    model = TransformerLM(
        vocab_size=vocab, d_model=d_model, num_heads=4, num_layers=layers,
        max_len=seq, dtype=dtype,
    )
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (batch, seq), 0, vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    params = model.init(jax.random.PRNGKey(1), tokens)['params']
    loss = lm_loss(model)

    # The output head is excluded from K-FAC, as in the reference's LM
    # example (its decoder layer is skipped by default,
    # examples/torch_language_model.py:163-168): the head's G factor is
    # vocab x vocab — an 8192^2 eigendecomposition that costs more than the
    # entire rest of the step and is why second-order methods skip LM heads.
    # Its gradient still flows (SGD-updated), so model FLOPs are unchanged.
    reg = kfac_tpu.register_model(model, tokens, skip_layers=['lm_head'])
    # compute_method is left unset: the library's platform-aware default
    # (kfac_tpu.default_compute_method) picks INVERSE+Newton-Schulz on TPU
    # (eigh lowers to a sequential panel algorithm there; the EIGEN step was
    # measured never to finish compiling inside a 20-minute budget on v5e)
    # and EIGEN — the reference's default — on the CPU smoke config.
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, damping=0.003, lr=0.1,
        factor_update_steps=10, inv_update_steps=100,
    )
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(loss)
    opt = optax.sgd(0.1, momentum=0.9)

    @jax.jit
    def kfac_step_capture(params, kstate, opt_state, batch):
        (l, _), grads, stats = run(params, batch)
        kstate, pgrads = kfac.step(kstate, grads, stats)
        updates, opt_state = opt.update(pgrads, opt_state, params)
        return optax.apply_updates(params, updates), kstate, opt_state, l

    @jax.jit
    def kfac_step_plain(params, kstate, opt_state, batch):
        l, grads = jax.value_and_grad(loss)(params, batch)
        kstate, pgrads = kfac.step(kstate, grads, None)
        updates, opt_state = opt.update(pgrads, opt_state, params)
        return optax.apply_updates(params, updates), kstate, opt_state, l

    @jax.jit
    def sgd_step(params, _unused, opt_state, batch):
        l, grads = jax.value_and_grad(loss)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), _unused, opt_state, l

    data = (tokens, targets)
    _log(f'lm_{config_name}: timing SGD step (compile + 100 iters)')
    t_sgd = _timeit(lambda i: sgd_step, (params, 0, opt.init(params), data))
    result['sgd_tokens_per_sec'] = round(batch * seq / t_sgd, 1)
    _atomic_write(out_path, result)
    _log(f'lm_{config_name}: sgd {t_sgd * 1e3:.1f} ms/step; '
         'timing K-FAC eager steps')
    t_kfac = _timeit(
        lambda i: kfac_step_capture if i % 10 == 0 else kfac_step_plain,
        (params, kfac.init(), opt.init(params), data),
    )
    result['eager_tokens_per_sec'] = round(batch * seq / t_kfac, 1)
    _atomic_write(out_path, result)
    _log(f'lm_{config_name}: kfac eager {t_kfac * 1e3:.1f} ms/step; '
         'timing scan loop')

    # Fully-compiled loop: 100 steps as one lax.scan with device-side
    # cadence (Trainer.scan_steps) — no per-step host dispatch. The scan
    # window spans the full inverse cadence, like _timeit's.
    from kfac_tpu import training as training_lib

    trainer = training_lib.Trainer(
        loss_fn=lambda p, ms, b: (loss(p, b), ms), optimizer=opt, kfac=kfac
    )
    scan_steps_n = 100
    scan_batches = (
        jnp.broadcast_to(tokens, (scan_steps_n,) + tokens.shape),
        jnp.broadcast_to(targets, (scan_steps_n,) + targets.shape),
    )
    sstate = trainer.init(params)
    sstate, _ = trainer.scan_steps(sstate, scan_batches)  # compile + warm
    jax.block_until_ready(sstate.params)
    t0 = time.perf_counter()
    sstate, scan_losses = trainer.scan_steps(sstate, scan_batches)
    jax.block_until_ready(scan_losses)
    t_scan = (time.perf_counter() - t0) / scan_steps_n
    _log(f'lm_{config_name}: scan {t_scan * 1e3:.1f} ms/step; finalizing')

    # Model FLOPs (fwd+bwd = 3x fwd): 6*N per token for the parameter
    # matmuls plus 12*L*d*S per token for self-attention scores/values.
    # Embedding/positional tables are gathers/adds, not matmuls — they carry
    # no 2*p FLOPs per token, so they are excluded from the matmul count
    # (the lm_head output projection is a real matmul and stays in).
    n_params = 0
    n_matmul_params = 0
    for path, p in jax.tree_util.tree_flatten_with_path(params)[0]:
        size = int(p.size)
        n_params += size
        if not any('embed' in str(k).lower() for k in path):
            n_matmul_params += size
    flops_per_step = batch * seq * (
        6 * n_matmul_params + 12 * layers * d_model * seq
    )
    peak = _peak_flops(result['device_kind']) if on_tpu else None

    # headline: the faster K-FAC stepping mode (eager dispatch vs compiled
    # scan loop); both are recorded
    t_best = min(t_kfac, t_scan)
    tokens_per_sec = batch * seq / t_best
    result.update(
        value=round(tokens_per_sec, 1),
        vs_baseline=round(t_sgd / t_best, 4),
        scan_tokens_per_sec=round(batch * seq / t_scan, 1),
        n_params=n_params,
        mfu=(round(flops_per_step / t_best / peak, 4) if peak else None),
        sgd_mfu=(round(flops_per_step / t_sgd / peak, 4) if peak else None),
        ok=True,
    )
    if peak and result.get('clock_check_tflops', 0) > peak / 1e12 * 1.1:
        # apparent throughput above the chip's physical peak: the backend's
        # completion signaling is unreliable, so MFU here is an upper bound
        # on trust, not a measurement
        result['timing_suspect'] = True
    _atomic_write(out_path, result)

    _log(f'lm_{config_name}: observability probe')
    try:
        _obs_probe(result, out_path, reg, run, loss, opt, params, data)
    except Exception as e:  # never let telemetry kill the headline
        result['obs_probe_error'] = f'{type(e).__name__}: {e}'
    _atomic_write(out_path, result)


# ---------------------------------------------------------------------------
# ResNet measurement stage (manual-only: `bench.py --stage resnet --config X`)
# ---------------------------------------------------------------------------

_RESNET_CONFIGS = {
    # BASELINE.json's vision configs (the reference's CIFAR/ImageNet
    # entrypoints, examples/torch_cifar10_resnet.py and
    # torch_imagenet_resnet.py), shape-faithful synthetic batches
    'resnet32_cifar': dict(arch='resnet32', batch=256, hw=32, classes=10),
    'resnet50_imagenet': dict(arch='resnet50', batch=32, hw=224, classes=1000),
}


def run_resnet_stage(config_name: str, out_path: str) -> None:
    """SGD vs K-FAC ResNet step throughput at the reference's ImageNet
    cadence (factors every 10 steps, inverses every 100). Phase-by-phase
    partials go to ``out_path``; MFU uses XLA's own cost model for the
    conv FLOPs (the 6N rule only covers matmul parameters)."""
    cfg = _RESNET_CONFIGS[config_name]
    result: dict = {
        'stage': config_name, 'run_id': _RUN_ID,
        'model_config': f"{cfg['arch']}_b{cfg['batch']}_{cfg['hw']}px",
    }
    tp = _active_plan()
    if tp is not None:
        result['tuned_plan'] = tp
    dev = _claim_backend(result, out_path, config_name)
    on_tpu = dev.platform != 'cpu'

    import jax
    import jax.numpy as jnp
    import optax

    import kfac_tpu
    from kfac_tpu import training as training_lib
    from kfac_tpu.models import resnet as resnet_lib

    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    batch, hw, classes = cfg['batch'], cfg['hw'], cfg['classes']
    model = getattr(resnet_lib, cfg['arch'])(num_classes=classes, dtype=dtype)
    x = jax.random.normal(jax.random.PRNGKey(0), (batch, hw, hw, 3), dtype)
    y = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0, classes)
    variables = model.init(jax.random.PRNGKey(2), x, train=True)
    registry = kfac_tpu.register_model(model, x, train=False)
    result['n_kfac_layers'] = len(registry)

    def loss_fn(params, model_state, b):
        xb, yb = b
        logits, updates = model.apply(
            {'params': params, 'batch_stats': model_state}, xb, train=True,
            mutable=['batch_stats'],
        )
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        nll = -jnp.take_along_axis(logp, yb[:, None], axis=1).mean()
        return nll, updates['batch_stats']

    opt = optax.sgd(0.1, momentum=0.9)
    data = (x, y)

    def time_trainer(trainer, warmup: int = 5, iters: int = 100) -> float:
        # Warmup compiles both cadence variants (step 0 captures+inverts);
        # the measured window (steps 5..104) then spans 10 factor captures
        # and the step-100 inverse — the full cadence at true proportion,
        # matching _timeit's accounting for the LM stages.
        state = trainer.init(variables['params'], variables['batch_stats'])
        loss = None
        for _ in range(warmup):
            state, loss = trainer.step(state, data)
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            state, loss = trainer.step(state, data)
        jax.block_until_ready(loss)
        return (time.perf_counter() - t0) / iters

    sgd_tr = training_lib.Trainer(loss_fn=loss_fn, optimizer=opt)
    _log(f'{config_name}: timing SGD (compile + 100 iters)')
    t_sgd = time_trainer(sgd_tr)
    result['sgd_images_per_sec'] = round(batch / t_sgd, 1)
    try:
        state0 = sgd_tr.init(variables['params'], variables['batch_stats'])
        ca = sgd_tr._jit_no_stats.lower(state0, data).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        result['step_gflops_xla'] = round(float(ca['flops']) / 1e9, 2)
    except Exception as exc:  # cost-model availability varies by backend
        _log(f'{config_name}: cost_analysis unavailable ({exc})')
    _atomic_write(out_path, result)
    _log(f'{config_name}: sgd {t_sgd * 1e3:.1f} ms/step; timing K-FAC '
         '(factors/10, inverses/100)')

    kfac = kfac_tpu.KFACPreconditioner(
        registry=registry, damping=0.003, lr=0.1,
        factor_update_steps=10, inv_update_steps=100,
    )
    kfac_tr = training_lib.Trainer(loss_fn=loss_fn, optimizer=opt, kfac=kfac)
    t_kfac = time_trainer(kfac_tr)
    peak = _peak_flops(result['device_kind']) if on_tpu else None
    gflops = result.get('step_gflops_xla')
    result.update(
        kfac_images_per_sec=round(batch / t_kfac, 1),
        value=round(batch / t_kfac, 1),
        vs_baseline=round(t_sgd / t_kfac, 4),
        mfu=(round(gflops * 1e9 / t_kfac / peak, 4)
             if peak and gflops else None),
        sgd_mfu=(round(gflops * 1e9 / t_sgd / peak, 4)
                 if peak and gflops else None),
        ok=True,
    )
    _atomic_write(out_path, result)
    _log(f'{config_name}: kfac {t_kfac * 1e3:.1f} ms/step '
         f'({result["vs_baseline"]:.3f}x SGD)')


# ---------------------------------------------------------------------------
# Orchestrator
# ---------------------------------------------------------------------------


def _run_stage(
    name: str,
    argv: list[str],
    env_extra: dict[str, str],
    budget_s: float,
    stdout_path: str | None = None,
) -> str:
    """Run one stage as a subprocess under a SIGTERM-grace watchdog.

    stderr is inherited (the progress trail interleaves into this
    process's log); stdout optionally captured to ``stdout_path`` (the
    microbench stages emit JSON lines there). Returns
    'ok' | 'timeout' | 'rc=N'.
    """
    _log(f'stage {name}: starting (budget {budget_s:.0f}s)')
    stdout_f = open(stdout_path, 'w') if stdout_path else subprocess.DEVNULL
    try:
        proc = subprocess.Popen(
            argv, stdout=stdout_f, env={**os.environ, **env_extra}
        )
        status = 'ok'
        try:
            rc = proc.wait(timeout=budget_s)
            if rc != 0:
                status = f'rc={rc}'
        except subprocess.TimeoutExpired:
            status = 'timeout'
            # SIGTERM + generous grace: SIGKILLing a process mid-TPU-claim
            # wedges the tunnel for minutes (documented env behavior)
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
                time.sleep(10.0)  # let the tunnel settle after a hard kill
    finally:
        if stdout_path:
            stdout_f.close()
    _log(f'stage {name}: {status}')
    return status


def _active_plan() -> dict | None:
    """Identity of the tuned layout plan driving this run, if any.

    ``KFAC_TUNE_PLAN=/path/to/plan.json`` (see docs/AUTOTUNE.md) makes
    bench runs self-describing: the record carries the plan's knobs and
    fingerprint so A/B throughput numbers can be attributed to a layout.
    """
    path = os.environ.get('KFAC_TUNE_PLAN')
    if not path:
        return None
    try:
        with open(path) as f:
            plan = json.load(f)
        return {
            'path': path,
            'schema': plan.get('schema'),
            'knobs': plan.get('knobs'),
            'fingerprint': plan.get('fingerprint'),
        }
    except Exception as exc:  # noqa: BLE001 - a bad plan must not kill a run
        return {'path': path, 'error': f'{type(exc).__name__}: {exc}'}


def _read_json(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except Exception:
        return {}


def _read_jsonl(path: str) -> list[dict]:
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        pass
    except OSError:
        pass
    return out


def _run_id_epoch(run_id: str) -> float | None:
    try:
        return time.mktime(time.strptime(run_id, '%Y%m%d_%H%M%S'))
    except (TypeError, ValueError):
        return None


def _tpu_replay() -> dict | None:
    """Newest committed ``platform=tpu`` record, for probe-failure rounds.

    A failed TPU probe used to leave the round JSON with nothing but
    ``fallback: tpu_probe_failed`` — a consumer comparing rounds then sees
    the CPU-smoke number where the previous round had a chip measurement
    and reads it as a 20x regression. Replaying the newest committed TPU
    evidence (value, MFU, run id, age) into the round keeps the best
    known chip numbers attached to every round, clearly labelled as a
    replay rather than a fresh measurement.
    """
    here = os.path.dirname(os.path.abspath(__file__))
    candidates: list[tuple[str, dict]] = []
    runs_dir = os.environ.get('BENCH_RUNS_DIR', 'bench_runs')
    if not os.path.isabs(runs_dir):
        runs_dir = os.path.join(here, runs_dir)
    for path in sorted(glob.glob(os.path.join(runs_dir, 'run_*.json'))):
        rec = _read_json(path)
        if isinstance(rec, dict):
            stem = os.path.basename(path)[len('run_'):-len('.json')]
            rec.setdefault('run_id', stem)
            candidates.append((path, rec))
    # round-1..4 evidence predates the per-run record convention: the
    # round files keep the parsed JSON line under 'parsed'
    for path in sorted(glob.glob(os.path.join(here, 'BENCH_r*.json'))):
        rec = _read_json(path).get('parsed')
        if isinstance(rec, dict):
            candidates.append((path, rec))
    best = None
    for path, rec in candidates:
        if rec.get('platform') in _CPUISH:
            continue
        stamp = _run_id_epoch(rec.get('run_id'))
        if stamp is None:
            try:
                stamp = os.path.getmtime(path)
            except OSError:
                continue
        if best is None or stamp > best[0]:
            best = (stamp, path, rec)
    if best is None:
        return None
    stamp, path, rec = best
    return {
        'run_id': rec.get('run_id'),
        'source': os.path.relpath(path, here),
        'platform': rec.get('platform'),
        'device_kind': rec.get('device_kind'),
        'value': rec.get('value'),
        'metric': rec.get('metric'),
        'mfu': rec.get('mfu'),
        'age_hours': round((time.time() - stamp) / 3600.0, 1),
    }


# measurement provenance stamped into every round record (and echoed by
# the microbench stages' own header lines). Hardcoded rather than
# imported from tools/tpu_microbench — importing it pulls in jax at
# module scope, which the orchestrator must not do before stages pin
# their own JAX_PLATFORMS; tests/test_measurement.py pins this block to
# tpu_microbench.HARNESS_VERSION / the default dispatch mode.
_MEASUREMENT = {'harness_version': 2, 'dispatch_mode': 'fori_loop'}


_HEADLINE_KEYS = (
    'platform', 'device_kind', 'model_config', 'clock_check_tflops',
    'sgd_tokens_per_sec', 'eager_tokens_per_sec', 'scan_tokens_per_sec',
    'value', 'vs_baseline', 'n_params', 'mfu', 'sgd_mfu', 'timing_suspect',
    # resnet-stage fields (never lifted to the top level: the headline
    # pick stays lm_flagship/lm_tiny)
    'sgd_images_per_sec', 'kfac_images_per_sec', 'n_kfac_layers',
    'step_gflops_xla',
    # observability-probe fields (docs/OBSERVABILITY.md)
    'metrics_jsonl', 'metrics_compilations', 'metrics_overhead_pct',
    'step_breakdown_ms', 'obs_probe_error',
    # compressed-wire + cold-factor-offload probe (docs/ARCHITECTURE.md
    # "Compression & offload")
    'compression_probe',
    # 3D-planner bubble table: measured vs simulated schedule fractions
    # under the one-dispatch harness provenance (docs/AUTOTUNE.md)
    'pipeline_probe',
    # fused step-path kernel A/B: per-family fused-vs-unfused p50 + the
    # traced device attribution (docs/ARCHITECTURE.md "Fused step-path
    # kernels")
    'fused_kernel_probe',
    # chaos-harness recovery SLOs: per-fault-class downtime / recovery
    # wall-clock / fallback depth / divergence from the committed storm
    # artifact (docs/ROBUSTNESS.md "Chaos harness")
    'chaos_probe',
    # compile & memory truth: per-entry compile wall-clock + XLA-reported
    # HBM bytes, recompiles-after-warmup (must be 0 on both engines), and
    # persistent compile-cache hit/miss deltas (docs/OBSERVABILITY.md
    # "Compile & memory truth")
    'compile_probe',
    # posterior serving tier: per-bucket p50/p95 + req/s on both paths,
    # cold-vs-warm AOT warmup A/B, recompiles-after-warmup (must be 0),
    # plus the flat sentinel-gated latency/throughput keys
    # (docs/SERVING.md)
    'serving_probe',
    'serving_mc_p50_ms', 'serving_mc_p95_ms',
    'serving_cf_p50_ms', 'serving_cf_p95_ms',
    'serving_mc_requests_per_sec', 'serving_cf_requests_per_sec',
    # perf-regression sentinel verdict: this round's headline keys vs the
    # committed provenance-aware baseline bench_runs/LEDGER.json
    # (docs/OBSERVABILITY.md "Run ledger")
    'ledger_probe',
    # active tuned layout plan, when KFAC_TUNE_PLAN is set (docs/AUTOTUNE.md)
    'tuned_plan',
    # newest committed TPU evidence, replayed when the TPU probe fails
    'tpu_replay',
)


def _orchestrate(result: dict) -> None:
    _mark_run_started()
    _log('probing backend health')
    probe = _probe_backend()
    _log(f'probe -> {probe}')
    result['probe_seconds'] = round(time.time() - _T0, 1)
    on_tpu = probe is not None
    if on_tpu:
        result['platform'], result['device_kind'] = probe
    else:
        result['platform'] = 'cpu'
        if os.environ.get('JAX_PLATFORMS') != 'cpu':
            result['fallback'] = 'tpu_probe_failed'
            replay = _tpu_replay()
            if replay is not None:
                result['tpu_replay'] = replay
    tp = _active_plan()
    if tp is not None:
        result['tuned_plan'] = tp
    result['measurement'] = dict(_MEASUREMENT)
    _persist(result)

    deadline_ts = _T0 + float(os.environ.get('BENCH_DEADLINE_S', '1350'))

    def remaining() -> float:
        return deadline_ts - time.time()

    here = os.path.dirname(os.path.abspath(__file__))
    run_dir = os.path.join(
        os.environ.get('BENCH_RUNS_DIR', 'bench_runs'), f'stages_{_RUN_ID}'
    )
    os.makedirs(run_dir, exist_ok=True)
    # a persistent compile cache amortizes recompiles across stages and runs
    cache_env = {
        'JAX_COMPILATION_CACHE_DIR': os.environ.get(
            'BENCH_JAX_CACHE', '/tmp/kfac_bench_jax_cache'
        ),
        'BENCH_RUN_ID': _RUN_ID,
        # pin the gate OFF for every stage that isn't explicitly measuring
        # the kernels — an operator's exported KFAC_TPU_PALLAS=1 must not
        # silently put unvalidated kernels on the 'default path' headline
        'KFAC_TPU_PALLAS': '0',
    }
    stages: dict[str, dict] = {}
    result['stages'] = stages

    def stage_argv(stage: str, config: str, out: str) -> list[str]:
        return [
            sys.executable, os.path.join(here, 'bench.py'),
            '--stage', stage, '--config', config, '--out', out,
        ]

    def micro_argv(*flags: str) -> list[str]:
        return [
            sys.executable, os.path.join(here, 'tools', 'tpu_microbench.py'),
            '--sizes', '512', '1024', '--iters', '8', '--rows', '8192',
            *flags,
        ]

    def acc_stage(env: dict[str, str]) -> None:
        """Steps-to-target vs SGD on digits (the metric BASELINE.json
        names, in the driver-recorded line itself; full curves live in
        BENCH_ACC.md). Skipped when the remaining budget is tight."""
        budget = min(300.0, remaining() - 30.0)
        if budget < 60.0:
            stages['acc'] = {'status': 'skipped_no_budget'}
            return
        out = os.path.join(run_dir, 'acc.jsonl')
        status = _run_stage(
            'acc',
            [
                sys.executable,
                os.path.join(here, 'tools', 'bench_accuracy.py'),
                '--tasks', 'digits_mlp',
                '--out', os.path.join(run_dir, 'acc.md'),
            ],
            env, budget, stdout_path=out,
        )
        rows = [r for r in _read_jsonl(out) if 'step_ratio' in r]
        entry: dict = {'status': status}
        if rows:
            entry.update(rows[-1])
            result['acc_task'] = rows[-1].get('task')
            result['acc_step_ratio'] = rows[-1].get('step_ratio')
            result['acc_time_ratio'] = rows[-1].get('time_ratio')
        stages['acc'] = entry

    if not on_tpu:
        # CPU smoke: one tiny stage, pinned to host (PALLAS_AXON_POOL_IPS
        # scrub included — env var alone does not stop the sitecustomize
        # axon registration)
        out = os.path.join(run_dir, 'lm_tiny.json')
        env = {'JAX_PLATFORMS': 'cpu', 'PALLAS_AXON_POOL_IPS': '', **cache_env}
        status = _run_stage(
            'lm_tiny', stage_argv('lm', 'tiny', out), env,
            max(120.0, min(700.0, remaining() - 120.0)),
        )
        stage = _read_json(out)
        stages['lm_tiny'] = {'status': status, **{
            k: stage[k] for k in _HEADLINE_KEYS if k in stage
        }}
        for k in _HEADLINE_KEYS:
            if k in stage:
                result[k] = stage[k]
        _persist(result)
        acc_stage(env)
        result['ledger_probe'] = _ledger_probe(result)
        _persist(result, partial=not stage.get('ok', False))
        return

    # --- TPU plan, smallest-first ----------------------------------------
    plan = [
        # (name, argv_builder, env, cap_s, reserve_for_later_s)
        ('micro_safe', micro_argv('--no-pallas'), {**cache_env}, 360.0, 420.0),
        ('lm_tiny', None, {**cache_env}, 300.0, 300.0),
        ('lm_flagship', None, {**cache_env}, 600.0, 90.0),
        ('micro_pallas', micro_argv('--pallas-only'),
         {**cache_env, 'KFAC_TPU_PALLAS': '1'}, 240.0, 60.0),
        ('lm_flagship_pallas', None,
         {**cache_env, 'KFAC_TPU_PALLAS': '1'}, 600.0, 30.0),
        # opportunistic: only run on leftover budget (reserve keeps the
        # acc stage's slice). lm_large amortizes tunnel dispatch for an
        # honest MFU reading (its d1024 K-FAC compile is cold-cache slow —
        # fine to lose to the skip guard); resnet32 is the reference's
        # CIFAR vision config.
        ('lm_large', None, {**cache_env}, 420.0, 330.0),
        # reserve covers acc's 60s floor PLUS the kill-path overshoot
        # (up to 30s SIGTERM grace + 10s settle beyond the budget)
        ('resnet32_cifar', None, {**cache_env}, 420.0, 150.0),
    ]
    for name, argv, env, cap, reserve in plan:
        budget = min(cap, remaining() - reserve)
        if budget < 60.0:
            stages[name] = {'status': 'skipped_no_budget'}
            _log(f'stage {name}: skipped (remaining {remaining():.0f}s)')
            continue
        if name == 'lm_flagship_pallas':
            micro = stages.get('micro_pallas', {})
            if micro.get('status') != 'ok' or micro.get('pallas_errors'):
                stages[name] = {'status': 'skipped_kernels_unvalidated'}
                _log(f'stage {name}: skipped (micro_pallas not clean)')
                continue
        if name.startswith('lm_') or name in _RESNET_CONFIGS:
            out = os.path.join(run_dir, f'{name}.json')
            if name in _RESNET_CONFIGS:
                sargv = stage_argv('resnet', name, out)
            else:
                config = {'lm_tiny': 'tiny', 'lm_large': 'large'}.get(
                    name, 'flagship'
                )
                sargv = stage_argv('lm', config, out)
            status = _run_stage(name, sargv, env, budget)
            stage = _read_json(out)
            stages[name] = {'status': status, **{
                k: stage[k] for k in _HEADLINE_KEYS if k in stage
            }}
            if 'error' in stage:
                stages[name]['error'] = stage['error']
        else:
            out = os.path.join(run_dir, f'{name}.jsonl')
            status = _run_stage(name, argv, env, budget, stdout_path=out)
            ops = _read_jsonl(out)
            entry: dict = {'status': status, 'ops': ops}
            # a kernel miscompiling on real hardware shows up as wrong
            # NUMBERS, not an exception — gate on the reported oracle
            # error too (both comparisons accumulate in fp32, so the
            # honest bound is small even for bf16 inputs)
            errs = [
                o['op'] for o in ops
                if o.get('error')
                or (isinstance(o.get('max_err'), (int, float))
                    and o['max_err'] > 0.05)
            ]
            if errs:
                entry['pallas_errors'] = errs
            # measurement provenance: which harness produced these
            # numbers, and the per-family latency-floor verdicts the
            # harness appended (docs/OBSERVABILITY.md "Measurement
            # truth") — a contaminated family means the sweep's absolute
            # numbers are dispatch floor, not op time
            header = next(
                (o for o in ops if 'platform' in o and 'op' not in o), {})
            entry['measurement'] = {
                'harness_version': header.get('harness_version', 1),
                'dispatch_mode': header.get('dispatch_mode', 'legacy'),
                'dispatches': sorted({
                    o['dispatches'] for o in ops
                    if isinstance(o.get('dispatches'), int)
                }),
            }
            floors = {
                str(o['op']).split('/', 1)[1]: {
                    k: o[k]
                    for k in ('contaminated', 'spread', 'expected_ratio',
                              'floor_ms', 'n')
                    if k in o
                }
                for o in ops if str(o.get('op', '')).startswith('floor/')
            }
            if floors:
                entry['floor_verdicts'] = floors
                bad = sorted(
                    f for f, v in floors.items() if v.get('contaminated'))
                if bad:
                    entry['floor_contaminated'] = bad
            stages[name] = entry
        _persist(result)

    # headline: the default-path flagship if it produced numbers, else tiny
    for pick in ('lm_flagship', 'lm_tiny'):
        stage = stages.get(pick, {})
        if 'value' in stage or 'sgd_tokens_per_sec' in stage:
            for k in _HEADLINE_KEYS:
                if k in stage:
                    result[k] = stage[k]
            result['headline_stage'] = pick
            break
    # the kernel-enabled flagship rides along as a comparison, never the
    # headline (the headline must be the default path)
    pallas = stages.get('lm_flagship_pallas', {})
    if 'value' in pallas:
        result['pallas_tokens_per_sec'] = pallas['value']
        result['pallas_mfu'] = pallas.get('mfu')
    # opportunistic stages ride along as summary fields, never the headline
    large = stages.get('lm_large', {})
    if large.get('mfu') is not None:
        result['large_mfu'] = large['mfu']
        result['large_sgd_mfu'] = large.get('sgd_mfu')
        result['large_tokens_per_sec'] = large.get('value')
    r32 = stages.get('resnet32_cifar', {})
    if 'vs_baseline' in r32:
        result['resnet32_vs_baseline'] = r32['vs_baseline']
        result['resnet32_kfac_images_per_sec'] = r32.get(
            'kfac_images_per_sec'
        )
    acc_stage({**cache_env})
    result['ledger_probe'] = _ledger_probe(result)
    done = stages.get(result.get('headline_stage', ''), {}).get('status')
    _persist(result, partial=done != 'ok')


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--stage', choices=['lm', 'resnet'])
    parser.add_argument(
        '--config', choices=sorted(_LM_CONFIGS) + sorted(_RESNET_CONFIGS)
    )
    parser.add_argument('--out')
    args = parser.parse_args()

    if args.config and not args.stage:
        parser.error('--config requires --stage (lm or resnet)')
    if args.stage:
        if not args.config:
            parser.error(f'--stage {args.stage} requires --config')
        table = _LM_CONFIGS if args.stage == 'lm' else _RESNET_CONFIGS
        if args.config not in table:
            parser.error(
                f'--config {args.config} is not a {args.stage} config '
                f'(choose from {", ".join(sorted(table))})'
            )
        if not args.out:
            parser.error('--stage requires --out (the stage partial path)')
        stage_fn = run_lm_stage if args.stage == 'lm' else run_resnet_stage
        stage_fn(args.config, args.out)
        return

    result = {
        'metric': 'kfac_lm_tokens_per_sec',
        'value': 0.0,
        'unit': 'tokens/s',
        'vs_baseline': 0.0,
        'platform': 'unknown',
    }
    failed = False
    try:
        _orchestrate(result)
    except BaseException as exc:  # noqa: BLE001 - JSON line must still print
        result['error'] = f'{type(exc).__name__}: {exc}'
        failed = True
    print(json.dumps(result))
    _persist(result, partial=failed)
    if failed:
        sys.exit(1)


if __name__ == '__main__':
    main()
