"""Unified run ledger tests (docs/OBSERVABILITY.md "Run ledger").

Pins PR 18's acceptance criteria:

- every stream adapter parses its committed format from the
  ``tests/data/mini_ledger/`` fixture (counts, kinds, run-header
  ``run_id``; header-less files stay valid with ``run_id=None``);
- the correlated timeline over the fixture is byte-identical to
  ``TIMELINE.golden`` through both CLIs (``kfac_ledger --timeline``
  and ``kfac_inspect --timeline``) and joins >= 3 streams;
- each correlation rule has a true positive AND a clean negative
  (missing chain link, out-of-join-window, non-reaction fleet event);
- the perf-regression sentinel passes a clean same-provenance round,
  fails a doctored 1.5x regression with the named key and exit code 1,
  and REFUSES a cross-provenance comparison with exit code 2;
- the committed baseline artifact is deterministic (byte-identical
  rebuilds) and schema-checked on load;
- the shared run-header rides ``JSONLWriter`` (stamped once per file,
  re-stamped after rotation, never duplicated on append),
  ``PostmortemWriter`` MANIFESTs, and the Trainer -> compile-watch
  thread;
- KFL113 pins the doc tables to the live registries;
- ``bench._ledger_probe`` folds the same verdict into round JSON
  without ever killing the round.

Compile budget: everything here is host-side parsing — the one Trainer
test only constructs (never steps) the engine, so the module adds zero
XLA compiles.
"""

import copy
import json
import os
import subprocess
import sys

import pytest

from kfac_tpu.analysis import drift
from kfac_tpu.observability import ledger
from kfac_tpu.observability.flight_recorder import PostmortemWriter
from kfac_tpu.observability.sinks import JSONLWriter

REPO = os.path.join(os.path.dirname(__file__), os.pardir)
FIXTURE = os.path.join(os.path.dirname(__file__), 'data', 'mini_ledger')
LEDGER_CLI = os.path.join(REPO, 'tools', 'kfac_ledger.py')
INSPECT_CLI = os.path.join(REPO, 'tools', 'kfac_inspect.py')


def _fixture(name):
    return os.path.join(FIXTURE, name)


def _golden():
    with open(_fixture('TIMELINE.golden'), encoding='utf-8') as f:
        return f.read()


def _fixture_ledger():
    rl = ledger.RunLedger()
    rl.ingest_dir(FIXTURE)
    return rl


def _cli(*args):
    return subprocess.run(
        [sys.executable, *args], capture_output=True, text=True,
        cwd=REPO, timeout=120)


# ---------------------------------------------------------------- adapters


@pytest.mark.parametrize('stream, fname, count, kinds', [
    ('metrics', 'metrics.jsonl', 12, {'record'}),
    ('flight', 'flight.jsonl', 3, {'record'}),
    ('compile', 'compile.jsonl', 6, {'compile_phase'}),
    ('calibration', 'calib.jsonl', 3, {'record'}),
    ('fleet', 'fleet.jsonl', 4, {'fleet_event'}),
    ('chaos', 'chaos.jsonl', 7, {'chaos_event'}),
    ('trace', 'trace.json', 3, {'trace_step', 'trace_summary'}),
    ('serving', 'serving.jsonl', 4, {'serve'}),
    ('bench', 'bench_round.json', 1, {'bench_round'}),
])
def test_adapter_parses_committed_format(stream, fname, count, kinds):
    events = ledger.ADAPTERS[stream](_fixture(fname))
    assert len(events) == count
    assert {e['stream'] for e in events} == {stream}
    assert {e['kind'] for e in events} == kinds
    # the shared run-header names the run on every event
    assert {e['run_id'] for e in events} == {'mini0001'}
    # normalized schema: every adapter emits exactly these keys
    for e in events:
        assert set(e) == {
            'run_id', 'stream', 'step', 't', 'kind', 'detail', 'data'}


def test_headerless_sources_stay_valid(tmp_path):
    # iterable of raw records: no header, run_id stays None
    events = ledger.parse_metrics([{'step': 0, 'loss': 1.0}])
    assert [e['run_id'] for e in events] == [None]
    # same for an on-disk header-less JSONL (the pre-PR-18 format)
    p = tmp_path / 'metrics.jsonl'
    p.write_text(json.dumps({'step': 3, 'loss': 0.5}) + '\n')
    events = ledger.parse_metrics(p)
    assert len(events) == 1
    assert events[0]['run_id'] is None
    assert events[0]['step'] == 3


def test_run_header_shape_and_consumption():
    hdr = ledger.run_header('abc123', 'metrics')
    assert hdr == {'kind': 'run_header', 'run_id': 'abc123',
                   'schema': ledger.LEDGER_SCHEMA, 'stream': 'metrics'}
    # the header is consumed, not emitted as an event
    events = ledger.parse_metrics([hdr, {'step': 0, 'loss': 1.0}])
    assert len(events) == 1
    assert events[0]['run_id'] == 'abc123'


def test_new_run_id_format():
    rid = ledger.new_run_id()
    assert len(rid) == 12 and rid == rid.lower()
    int(rid, 16)  # hex
    assert ledger.new_run_id() != rid


def test_ingest_dir_discovers_every_stream():
    rl = _fixture_ledger()
    assert rl.runs() == ['mini0001']
    assert rl.streams() == sorted(ledger.ADAPTERS)
    assert len(rl.events) == 43


def test_step_clock_places_wall_clock_only_events():
    """The compile journal carries only wall clock; the chaos worker's
    (step, t) anchors teach the ledger the run's step clock, which
    lands the n=2 recompile at step 5 — flagged as estimated."""
    rl = _fixture_ledger()
    done = [e for e in rl.events
            if e['stream'] == 'compile' and e['data'].get('n') == 2
            and e['data'].get('phase') == 'done']
    assert len(done) == 1
    assert done[0]['step'] == 5
    assert done[0]['data']['step_est'] is True


# ------------------------------------------------------------ correlations


def test_fixture_timeline_fires_expected_rules_only():
    rl = _fixture_ledger()
    fired = {c['rule'] for c in rl.correlations()}
    assert fired == {'recompile_cascade', 'recompile_step_spike',
                     'calib_fleet_reaction', 'preempt_recovery'}
    # clean negative: no divergence evidence in the fixture
    assert 'factor_divergence' not in fired


def test_recompile_cascade_joins_at_least_three_streams():
    rl = _fixture_ledger()
    cascade = [c for c in rl.correlations()
               if c['rule'] == 'recompile_cascade']
    assert len(cascade) == 1
    assert len(cascade[0]['streams']) >= 3
    assert {'compile', 'calibration', 'fleet'} <= set(cascade[0]['streams'])


def test_fleet_cooldown_is_not_a_reaction():
    """The fixture's step-10 ``cooldown`` event is a built-in negative:
    only the reaction events (drift/retune/armed/migrated) anomalize."""
    rl = _fixture_ledger()
    assert not any('cooldown' in a['detail'] for a in rl.anomalies())
    reactions = [a for a in rl.anomalies() if a['kind'] == 'fleet_reaction']
    assert len(reactions) == 3


def test_factor_divergence_positive_and_join_window_negative():
    cfg = ledger.LedgerConfig()
    hot = [{'step': 1, 'loss': 1.0, 'kfac/factor_norm': 1e9},
           {'step': 2, 'loss': float('nan')}]
    anomalies = ledger.derive_anomalies(ledger.parse_metrics(hot), cfg)
    assert sorted(a['kind'] for a in anomalies) == [
        'huge_factor', 'nonfinite_loss']
    assert {c['rule'] for c in ledger.correlate(anomalies, cfg)} == {
        'factor_divergence'}
    # same evidence outside join_steps: full-chain-or-nothing
    far = [{'step': 1, 'loss': 1.0, 'kfac/factor_norm': 1e9},
           {'step': 20, 'loss': float('nan')}]
    anomalies = ledger.derive_anomalies(ledger.parse_metrics(far), cfg)
    assert ledger.correlate(anomalies, cfg) == []


def test_step_spike_without_recompile_is_clean_negative():
    cfg = ledger.LedgerConfig()
    recs = [{'step': s, 'step_time_s': 0.1} for s in range(6)]
    recs.append({'step': 6, 'step_time_s': 0.25})
    anomalies = ledger.derive_anomalies(ledger.parse_metrics(recs), cfg)
    assert [a['kind'] for a in anomalies] == ['step_time_spike']
    assert ledger.correlate(anomalies, cfg) == []


# ----------------------------------------------------------- timeline CLIs


def test_timeline_byte_stable_against_golden():
    """Acceptance: the committed fixture renders a deterministic
    timeline, pinned byte-for-byte."""
    assert ledger.render_timeline(_fixture_ledger()) == _golden()
    # twice in-process: no hidden ordering nondeterminism
    assert ledger.render_timeline(_fixture_ledger()) == _golden()


def test_kfac_ledger_cli_timeline_matches_golden():
    out = _cli(LEDGER_CLI, '--timeline', FIXTURE)
    assert out.returncode == 0, out.stderr
    assert out.stdout == _golden()


def test_kfac_inspect_cli_timeline_matches_golden():
    """Satellite: the SAME report through the triage CLI — divergence
    and compile verdicts ride the timeline, not a separate tool."""
    out = _cli(INSPECT_CLI, '--timeline', FIXTURE)
    assert out.returncode == 0, out.stderr
    assert out.stdout == _golden()
    assert 'verdicts:' in out.stdout and 'compile:' in out.stdout


def test_timeline_report_json_shape():
    report = ledger.timeline_report(_fixture_ledger())
    assert report['schema'] == ledger.LEDGER_SCHEMA
    assert report['runs'] == ['mini0001']
    assert report['n_events'] == 43
    assert report['verdicts']['compile'].startswith('ok')
    assert report['verdicts']['divergence'].startswith('none')


# ---------------------------------------------------------------- sentinel


def _fixture_round():
    with open(_fixture('bench_round.json'), encoding='utf-8') as f:
        return json.load(f)


def _fixture_baseline():
    return ledger.load_baseline(_fixture('LEDGER.json'))


def test_sentinel_clean_round_passes():
    verdict = ledger.sentinel_check(_fixture_round(), _fixture_baseline())
    assert verdict['status'] == 'ok'
    assert verdict['regressed_keys'] == []
    assert all(v['verdict'] == 'ok' for v in verdict['keys'].values())


def test_sentinel_doctored_regression_names_the_key():
    """Acceptance: a doctored 1.5x throughput regression fails with the
    named key."""
    rnd = _fixture_round()
    rnd['parsed']['value'] /= 1.5
    verdict = ledger.sentinel_check(rnd, _fixture_baseline())
    assert verdict['status'] == 'regressed'
    assert verdict['regressed_keys'] == ['value']
    assert verdict['keys']['value']['verdict'] == 'regressed'
    # the other keys stay individually ok — one regression, one name
    assert verdict['keys']['sgd_tokens_per_sec']['verdict'] == 'ok'


def test_sentinel_refuses_cross_provenance():
    """Acceptance: a CPU-fallback round is never compared against TPU
    medians (the PR-11 replay-defense lesson)."""
    rnd = _fixture_round()
    rnd['parsed']['platform'] = 'cpu'
    verdict = ledger.sentinel_check(rnd, _fixture_baseline())
    assert verdict['status'] == 'refused'
    assert verdict['keys'] == {} and verdict['regressed_keys'] == []
    assert 'not compared' in verdict['reason']


def test_sentinel_missing_baseline_is_not_a_failure():
    verdict = ledger.sentinel_check(_fixture_round(), None)
    assert verdict['status'] == 'no_baseline'
    assert verdict['regressed_keys'] == []


def test_sentinel_lower_is_better_direction():
    rnd = _fixture_round()
    rnd['parsed']['acc_time_ratio'] *= 2.0  # overhead doubled
    verdict = ledger.sentinel_check(rnd, _fixture_baseline())
    assert verdict['status'] == 'regressed'
    assert verdict['regressed_keys'] == ['acc_time_ratio']


def test_cli_check_exit_codes(tmp_path):
    """Acceptance: exit 0 clean, 1 regressed (named key on stdout),
    2 refused."""
    base = _fixture('LEDGER.json')
    ok = _cli(LEDGER_CLI, '--check', _fixture('bench_round.json'),
              '--baseline', base)
    assert ok.returncode == 0, ok.stderr

    doctored = _fixture_round()
    doctored['parsed']['value'] /= 1.5
    bad = tmp_path / 'bad_round.json'
    bad.write_text(json.dumps(doctored))
    out = _cli(LEDGER_CLI, '--check', str(bad), '--baseline', base)
    assert out.returncode == 1
    assert 'value' in out.stdout

    cpu = _fixture_round()
    cpu['parsed']['platform'] = 'cpu'
    crossed = tmp_path / 'cpu_round.json'
    crossed.write_text(json.dumps(cpu))
    out = _cli(LEDGER_CLI, '--check', str(crossed), '--baseline', base)
    assert out.returncode == 2


def test_kfac_ledger_selftest():
    out = _cli(LEDGER_CLI, '--selftest')
    assert out.returncode == 0, out.stdout + out.stderr


# ---------------------------------------------------------------- baseline


def test_build_baseline_deterministic_bytes(tmp_path):
    """TunedPlan artifact convention: same inputs, byte-identical
    file."""
    rounds = [{'parsed': {'platform': 'tpu', 'value': 100.0 + i}}
              for i in range(4)]
    a, b = tmp_path / 'a.json', tmp_path / 'b.json'
    ledger.save_baseline(a, ledger.build_baseline(rounds, sources=['x']))
    ledger.save_baseline(b, ledger.build_baseline(rounds, sources=['x']))
    assert a.read_bytes() == b.read_bytes()
    loaded = ledger.load_baseline(a)
    assert loaded['platform'] == 'tpu'
    assert loaded['keys']['value']['median'] == 101.5


def test_build_baseline_drops_off_provenance_rounds():
    rounds = [
        {'parsed': None},  # BENCH_r01-style provenance-less round
        {'parsed': {'platform': 'tpu', 'value': 10.0}},
        {'parsed': {'platform': 'cpu', 'value': 99.0}},
        {'parsed': {'platform': 'tpu', 'value': 12.0}},
    ]
    base = ledger.build_baseline(rounds)
    assert base['platform'] == 'tpu'
    assert base['n_rounds'] == 2
    assert base['n_dropped_provenance'] == 2
    assert base['keys']['value']['median'] == 11.0
    with pytest.raises(ValueError, match='provenance'):
        ledger.build_baseline([{'parsed': None}])


def test_load_baseline_rejects_foreign_artifacts(tmp_path):
    good = ledger.load_baseline(_fixture('LEDGER.json'))
    wrong_kind = dict(good, kind='tuned_plan')
    p = tmp_path / 'x.json'
    p.write_text(json.dumps(wrong_kind))
    with pytest.raises(ValueError, match='bench_baseline'):
        ledger.load_baseline(p)
    wrong_schema = dict(good, schema=ledger.LEDGER_SCHEMA + 1)
    p.write_text(json.dumps(wrong_schema))
    with pytest.raises(ValueError, match='schema'):
        ledger.load_baseline(p)


def test_committed_bench_baseline_is_loadable():
    base = ledger.load_baseline(os.path.join(REPO, 'bench_runs',
                                             'LEDGER.json'))
    assert base['platform'] == 'cpu'  # rounds 2-5 are CPU-fallback
    assert base['n_dropped_provenance'] == 1  # r1 has parsed: null
    assert set(base['keys']) <= set(ledger.DEFAULT_SENTINEL_KEYS)


# -------------------------------------------------------- run-id threading


def test_jsonl_writer_stamps_header_once(tmp_path):
    p = tmp_path / 'metrics.jsonl'
    hdr = ledger.run_header('run42ab', 'metrics')
    with JSONLWriter(p, run_header=hdr) as sink:
        sink.write({'step': 0, 'loss': 1.0})
    with JSONLWriter(p, run_header=hdr) as sink:  # append: no duplicate
        sink.write({'step': 1, 'loss': 0.9})
    lines = [json.loads(line) for line in p.read_text().splitlines()]
    assert len(lines) == 3
    assert lines[0]['kind'] == 'run_header'
    assert [ln.get('step') for ln in lines[1:]] == [0, 1]
    # and the adapter reads it back
    events = ledger.parse_metrics(p)
    assert {e['run_id'] for e in events} == {'run42ab'}


def test_jsonl_writer_restamps_header_after_rotation(tmp_path):
    p = tmp_path / 'metrics.jsonl'
    hdr = ledger.run_header('run42ab', 'metrics')
    with JSONLWriter(p, run_header=hdr, max_bytes=200) as sink:
        for step in range(12):
            sink.write({'step': step, 'loss': 1.0})
    assert os.path.exists(f'{p}.1')  # rotation happened
    first = json.loads(p.read_text().splitlines()[0])
    assert first.get('kind') == 'run_header'
    assert first['run_id'] == 'run42ab'


def test_jsonl_writer_without_header_unchanged(tmp_path):
    p = tmp_path / 'metrics.jsonl'
    with JSONLWriter(p) as sink:
        sink.write({'step': 0})
    lines = p.read_text().splitlines()
    assert len(lines) == 1 and 'run_header' not in lines[0]


def test_postmortem_manifest_carries_run_id(tmp_path):
    pm = PostmortemWriter(tmp_path / 'pms', engine=None, run_id='run42ab')
    bundle = pm.write_bundle(
        object(), reason='shutdown', record={'step': 3}, history=[], step=3)
    man = json.load(open(os.path.join(bundle, 'MANIFEST.json')))
    assert man['run_id'] == 'run42ab'
    # header-less writers predating the ledger stay valid
    pm = PostmortemWriter(tmp_path / 'pms2', engine=None)
    bundle = pm.write_bundle(
        object(), reason='shutdown', record={'step': 3}, history=[], step=3)
    man = json.load(open(os.path.join(bundle, 'MANIFEST.json')))
    assert man['run_id'] is None


def test_trainer_threads_run_id_into_compile_watch():
    """Construct-only (zero compiles): the Trainer generates/propagates
    the run_id into the engine's compile watch so journal records and
    drained events self-identify."""
    import jax
    import jax.numpy as jnp
    import optax

    import kfac_tpu
    from kfac_tpu import training
    from testing import models

    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=16)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, compile_watch=True)

    def loss_fn(p, model_state, batch):
        xx, yy = batch
        pred = m.apply({'params': p}, xx)
        return jnp.mean((pred - yy) ** 2), model_state

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac,
        run_id='run42ab')
    assert trainer.run_id == 'run42ab'
    assert kfac.compile_watcher().run_id == 'run42ab'
    assert trainer.run_header('metrics') == ledger.run_header(
        'run42ab', 'metrics')

    # unset: the Trainer mints one and still threads it
    kfac2 = kfac_tpu.KFACPreconditioner(registry=reg, compile_watch=True)
    trainer2 = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac2)
    assert trainer2.run_id and len(trainer2.run_id) == 12
    assert kfac2.compile_watcher().run_id == trainer2.run_id


# ------------------------------------------------------------------ drift


def test_kfl113_clean_on_committed_doc():
    assert drift.check_ledger_tables() == []


def test_kfl113_catches_doc_drift(tmp_path):
    doc = os.path.join(REPO, 'docs', 'OBSERVABILITY.md')
    with open(doc, encoding='utf-8') as f:
        text = f.read()
    doctored = tmp_path / 'OBSERVABILITY.md'
    doctored.write_text(
        text.replace('| `spike_factor` |', '| `spiek_factor` |'))
    problems = drift.check_ledger_tables(str(doctored))
    assert problems
    assert any('spike_factor' in p for p in problems)


def test_kfl113_registered():
    rules = {r.code for r in drift.core.all_rules()}
    assert 'KFL113' in rules


# ------------------------------------------------------------- bench probe


def test_bench_ledger_probe_statuses(tmp_path, monkeypatch):
    import bench

    monkeypatch.chdir(REPO)
    monkeypatch.setenv('BENCH_RUNS_DIR', FIXTURE)
    probe = bench._ledger_probe(_fixture_round())
    assert probe['status'] == 'ok'
    assert probe['keys']['value'] == 'ok'

    doctored = _fixture_round()
    doctored['parsed']['value'] /= 1.5
    probe = bench._ledger_probe(doctored)
    assert probe['status'] == 'regressed'
    assert probe['regressed_keys'] == ['value']

    cpu = copy.deepcopy(_fixture_round())
    cpu['parsed']['platform'] = 'cpu'
    probe = bench._ledger_probe(cpu)
    assert probe['status'] == 'refused'

    monkeypatch.setenv('BENCH_RUNS_DIR', str(tmp_path))  # no LEDGER.json
    probe = bench._ledger_probe(_fixture_round())
    assert probe['status'] == 'no_baseline'
    # the probe never kills the round
    assert bench._ledger_probe({'parsed': 'garbage'})['status'] in (
        'no_baseline', 'error')
