"""Compile & memory truth tests (docs/OBSERVABILITY.md "Compile &
memory truth").

Pins PR 17's acceptance criteria:

- a shape change on a watched entry emits exactly ONE compile event
  whose fingerprint diff names the changed dimension, and an unchanged
  re-step emits ZERO events — on both engines and both KAISA stat
  transports (the batch-shaped surface is the Trainer step, whose args
  actually carry the batch; the engine ``step`` args are batch-size
  invariant, which the engine test pins directly);
- heartbeat journaling follows ``lowering -> compiling -> done`` with
  the fsync-before-blocking contract, and a subprocess SIGKILLed
  mid-compile (via the ``fault_compile_sleep_s`` injection knob) leaves
  a journal ``tools/kfac_inspect.py`` resolves to a "died compiling X"
  verdict naming the entry and the phase;
- ``memory_usage()`` vs XLA ``memory_analysis()`` parity on CPU is
  recorded as a calibration residual (``observe_memory``), never a hard
  failure;
- all four Trainer step paths count into the engine's watch;
- ``PostmortemWriter`` bundles carry ``compile_events.jsonl`` and
  ``compile_memory.json``;
- watched dispatch leaves the plain jit cache untouched.

Compile budget: the Trainer-paths and bundle tests share module-scope
fixtures (PR-15 convention); the attribution tests build the small
per-case engines they mutate.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import optax
import pytest

import kfac_tpu
from kfac_tpu import health as health_lib
from kfac_tpu import training
from kfac_tpu.observability import calibration
from kfac_tpu.observability import compile_watch as cw
from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh
from testing import faults, models

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), os.pardir, 'tools')
)
import kfac_inspect  # noqa: E402


def _setup(n=32, **cfg_kw):
    cfg_kw.setdefault('compile_watch', True)
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=n)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, **cfg_kw)
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(
        models.mse_loss(m))
    return m, params, (x, y), reg, kfac, run


def _dist_setup(transport, **cfg_kw):
    cfg_kw.setdefault('compile_watch', True)
    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(
        registry=reg, allreduce_method=transport, **cfg_kw)
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(
        models.mse_loss(m))
    return m, params, (x, y), reg, dk, run


# ------------------------------------------------------------------ config


def test_config_normalization():
    reg = _setup()[3]
    k = kfac_tpu.KFACPreconditioner(registry=reg, compile_watch=True)
    assert isinstance(k.compile_watch, cw.CompileWatchConfig)
    k = kfac_tpu.KFACPreconditioner(registry=reg, compile_watch=False)
    assert k.compile_watch is None
    assert k.compile_watcher() is None
    k = kfac_tpu.KFACPreconditioner(
        registry=reg, compile_watch='/tmp/j.jsonl')
    assert k.compile_watch.journal_path == '/tmp/j.jsonl'
    with pytest.raises(TypeError, match='compile_watch'):
        kfac_tpu.KFACPreconditioner(registry=reg, compile_watch=3.5)
    with pytest.raises(ValueError, match='max_events'):
        cw.CompileWatchConfig(max_events=0)
    with pytest.raises(ValueError, match='fault_compile_sleep_s'):
        cw.CompileWatchConfig(fault_compile_sleep_s=-1.0)


def test_journal_path_env_fallback(monkeypatch, tmp_path):
    """scripts/tpu_session2b.sh arms journaling fleet-wide via the
    KFAC_COMPILE_JOURNAL env var; an explicit path still wins."""
    p = str(tmp_path / 'env.jsonl')
    monkeypatch.setenv('KFAC_COMPILE_JOURNAL', p)
    assert cw.CompileWatchConfig().journal_path == p
    assert cw.CompileWatchConfig(journal_path='/x.jsonl').journal_path == \
        '/x.jsonl'
    monkeypatch.delenv('KFAC_COMPILE_JOURNAL')
    assert cw.CompileWatchConfig().journal_path is None


def test_watched_validation():
    kfac = _setup()[4]
    with pytest.raises(ValueError, match='unknown entry'):
        kfac.watched('nope')
    reg = kfac.registry
    off = kfac_tpu.KFACPreconditioner(registry=reg)
    with pytest.raises(ValueError, match='compile_watch'):
        off.watched('step')


# ------------------------------------------------------------ fingerprints


def test_fingerprint_conventions():
    """Array leaves -> shape+dtype; python int/float -> type only (weak-
    typed under jit, the value does not select the program); bool/str ->
    value; statics -> value."""
    a = jnp.ones((4, 3), jnp.float32)
    fp1 = cw.fingerprint_args((a, 2), {'flag': True})
    fp2 = cw.fingerprint_args((a, 99), {'flag': True})
    assert fp1 == fp2  # int value is not a program selector
    fp3 = cw.fingerprint_args((a, 2), {'flag': False})
    assert fp1 != fp3  # bool value IS
    spec = [v for k, v in fp1.items() if 'flag' not in k and v.get('shape')]
    assert spec[0]['shape'] == [4, 3] and spec[0]['dtype'] == 'float32'
    fps = cw.fingerprint_args((a,), {}, statics={'mode': 'fast'})
    assert fps['static:mode'] == {'static': 'str', 'value': "'fast'"}
    assert cw.fingerprint_key(fp1) != cw.fingerprint_key(fp3)
    assert len(cw.fingerprint_key(fp1)) == 16


def test_fingerprint_diff_names_the_change():
    a = jnp.ones((4, 3), jnp.float32)
    b = jnp.ones((5, 3), jnp.float32)
    old = cw.fingerprint_args((a,), {})
    assert cw.fingerprint_diff(None, old) is None  # first compile
    assert cw.fingerprint_diff(old, dict(old)) == []  # identical print
    diff = cw.fingerprint_diff(old, cw.fingerprint_args((b,), {}))
    assert diff == ['[0][0]: dim 0 4 -> 5']
    diff = cw.fingerprint_diff(
        old, cw.fingerprint_args((a.astype(jnp.bfloat16),), {}))
    assert diff == ["[0][0]: dtype 'float32' -> 'bfloat16'"]
    (line,) = cw.fingerprint_diff(old, cw.fingerprint_args((a, a), {}))
    assert line.startswith('[0][1]: new argument')
    (line,) = cw.fingerprint_diff(cw.fingerprint_args((a, a), {}), old)
    assert line.startswith('[0][1]: argument dropped')


def test_sharding_never_keys_the_dispatch_cache():
    """_program_view strips sharding: repr churn on an unchanged program
    must not look like a different executable key (the distributed
    engine's init-state vs step-output shardings differ in repr while
    the compiled program accepts both)."""
    a = jnp.ones((4, 3), jnp.float32)
    fp = cw.fingerprint_args((a,), {})
    doctored = {
        k: dict(v, sharding='NamedSharding(elsewhere)')
        for k, v in fp.items()
    }
    assert cw.fingerprint_key(cw._program_view(fp)) == \
        cw.fingerprint_key(cw._program_view(doctored))
    assert cw.fingerprint_key(fp) != cw.fingerprint_key(doctored)


# --------------------------------------- attribution: engines + transports


def test_engine_step_compiles_once_dense():
    """Engine step args are batch-size invariant: the whole loop is one
    compile, zero events after it — and the plain jit cache stays EMPTY
    (watched dispatch is AOT; nothing changes for unwatched callers)."""
    _, params, batch, _, kfac, run = _setup()
    step = kfac.watched('step')
    state = kfac.init()
    for _ in range(3):
        (_, _), grads, stats = run(params, batch)
        state, _ = step(state, grads, stats)
    watch = kfac.compile_watcher()
    assert watch.counters() == {'kfac.step': 1}
    assert watch.recompile_count() == 0
    assert len(watch.events) == 1
    assert watch.events[0]['diff'] is None
    assert step._fn._cache_size() == 0  # jit cache unchanged
    assert step.cache_size() == 1


@pytest.mark.parametrize('transport', ['allreduce', 'allreduce_bucketed'])
def test_engine_step_compiles_once_distributed(transport):
    """Same pin on the sharded engine, both stat transports — including
    across the init-state -> step-output resharding, which plain jit
    recompiles for but an AOT executable accepts."""
    _, params, batch, _, dk, run = _dist_setup(transport)
    step = dk.watched('step')
    state = dk.init()
    for _ in range(3):
        (_, _), grads, stats = run(params, batch)
        state, _ = step(state, grads, stats)
    watch = dk.compile_watcher()
    assert watch.counters() == {'dist_kfac.step': 1}
    assert watch.recompile_count() == 0


@pytest.mark.parametrize('flavor', ['dense', 'allreduce',
                                    'allreduce_bucketed'])
def test_batch_shape_change_emits_exactly_one_named_event(flavor):
    """The acceptance headline, on the surface whose args actually carry
    the batch (the Trainer step), for both engines and both transports:
    unchanged re-steps emit zero events; one batch-dim change emits
    exactly one event whose diff names dimension 0 and its sizes."""
    if flavor == 'dense':
        m, params, (x, y), _, eng, _ = _setup()
    else:
        m, params, (x, y), _, eng, _ = _dist_setup(flavor)

    def loss_fn(p, model_state, batch):
        xx, yy = batch
        pred = m.apply({'params': p}, xx)
        return jnp.mean((pred - yy) ** 2), model_state

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=eng)
    watch = eng.compile_watcher()
    state = trainer.init(params)
    state, _ = trainer.step(state, (x, y))          # compile 1
    before = len(watch.events)
    state, _ = trainer.step(state, (x, y))          # unchanged re-step
    assert len(watch.events) == before              # zero new events
    n = x.shape[0]
    state, _ = trainer.step(state, (x[:n - 8], y[:n - 8]))
    new = watch.events[before:]
    assert len(new) == 1                            # exactly one event
    assert new[0]['entry'] == 'trainer.step/with_stats'
    assert any(f'dim 0 {n} -> {n - 8}' in d for d in new[0]['diff'])
    assert watch.recompile_count('trainer.step/with_stats') == 1


# ----------------------------------------------------------- journal + kill


def test_journal_phase_sequence(tmp_path):
    path = tmp_path / 'journal.jsonl'
    # str shorthand: the config carries the journal path
    _, params, batch, _, kfac, run = _setup(compile_watch=str(path))
    assert kfac.compile_watch.journal_path == str(path)
    step = kfac.watched('step')
    state = kfac.init()
    (_, _), grads, stats = run(params, batch)
    state, _ = step(state, grads, stats)
    state, _ = step(state, grads, stats)  # cached: no new records
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    assert [r['phase'] for r in recs] == ['lowering', 'compiling', 'done']
    assert all(r['kind'] == 'compile' for r in recs)
    assert all(r['entry'] == 'kfac.step' for r in recs)
    assert all(r['n'] == 1 for r in recs)
    assert all(r['pid'] == os.getpid() for r in recs)
    assert 'fingerprint' in recs[0] and recs[0]['diff'] is None
    assert recs[1]['aot'] is True and recs[1]['lowering_s'] >= 0
    assert recs[2]['compile_s'] >= 0
    ts = [r['t'] for r in recs]
    assert ts == sorted(ts)


_KILL_CHILD = r"""
import os
import jax
import jax.numpy as jnp
from kfac_tpu.observability import compile_watch as cw

watch = cw.CompileWatch(cw.CompileWatchConfig(
    journal_path=os.environ['KFAC_TEST_JOURNAL'],
    fault_compile_sleep_s=120.0,
))
f = watch.wrap('victim.step', jax.jit(lambda a: (a @ a.T).sum()))
f(jnp.ones((8, 8), jnp.float32))   # parent SIGKILLs us inside the sleep
raise SystemExit('unreachable: the fault sleep outlives the test timeout')
"""


def test_sigkill_mid_compile_leaves_resolvable_verdict(tmp_path):
    """The acceptance crash drill: fault-inject a slow compile in a
    subprocess, SIGKILL it between the 'compiling' heartbeat and 'done',
    and resolve the leftover journal — kfac_inspect must name the entry
    and the phase it died in."""
    journal = tmp_path / 'journal.jsonl'
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               KFAC_TEST_JOURNAL=str(journal))
    env.pop('KFAC_COMPILE_JOURNAL', None)
    repo = os.path.join(os.path.dirname(__file__), os.pardir)
    proc = subprocess.Popen(
        [sys.executable, '-c', _KILL_CHILD], env=env, cwd=repo)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            if journal.exists() and any(
                '"compiling"' in line
                for line in journal.read_text().splitlines()
            ):
                break
            if proc.poll() is not None:
                raise AssertionError(
                    f'child exited early with {proc.returncode}')
            time.sleep(0.05)
        else:
            raise AssertionError('never saw the compiling heartbeat')
        # the fsync contract: the heartbeat is durable BEFORE the
        # blocking phase — the child is now inside the fault sleep
        proc.kill()
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    records = kfac_inspect.load_jsonl(str(journal))
    compile_recs, metric_recs = kfac_inspect.split_compile_records(records)
    assert metric_recs == []
    comp = kfac_inspect.analyze_compile_journal(compile_recs)
    assert comp['verdict'] is not None
    assert "'victim.step'" in comp['verdict']
    assert "'compiling'" in comp['verdict']
    assert 'died compiling' in comp['verdict']
    (flight,) = comp['in_flight']
    assert flight['entry'] == 'victim.step'
    assert flight['phase'] == 'compiling'


# -------------------------------------------------------- memory accounting


@pytest.mark.parametrize('flavor', ['dense', 'distributed'])
def test_memory_report_parity_recorded_as_residual(flavor):
    """CPU backend reports real memory_analysis numbers; the gap against
    the model-side memory_usage() estimate is fed to the calibration
    monitor as a residual — by design NEVER a hard equality (the two
    count different things: persistent factor state vs whole-program
    arg/output/temp bytes)."""
    if flavor == 'dense':
        _, params, batch, _, eng, run = _setup()
    else:
        _, params, batch, _, eng, run = _dist_setup('allreduce')
    step = eng.watched('step')
    state = eng.init()
    (_, _), grads, stats = run(params, batch)
    state, _ = step(state, grads, stats)
    report = eng.compiled_memory_report()
    entry = ('kfac.step' if flavor == 'dense' else 'dist_kfac.step')
    snap = report[entry]
    assert snap['memory'] is not None  # CPU reports stats
    assert snap['hbm_bytes'] and snap['hbm_bytes'] > 0
    assert snap['hbm_bytes'] == cw.measured_hbm_bytes(snap['memory'])
    predicted = float(eng.memory_usage(state)['total'])
    assert predicted > 0
    mon = calibration.CalibrationMonitor(
        0.01, predicted_mem_bytes=predicted)
    mon.observe_memory_report(report)
    ratio = mon.mem_ratio()
    assert ratio is not None and ratio > 0  # residual, not a failure
    rec = mon.record()
    assert rec['calib/predicted_mem_bytes'] == predicted
    assert rec['calib/mem_ratio'] == pytest.approx(ratio)
    assert rec['calib/measured_mem_bytes'] == pytest.approx(
        ratio * predicted)


def test_memory_graceful_none():
    """Where the backend reports nothing, events carry memory=None and
    the report entry degrades — never an exception."""
    assert cw.measured_hbm_bytes(None) is None
    assert cw.measured_hbm_bytes({}) is None
    assert cw.measured_hbm_bytes(
        {'temp_size_in_bytes': 0, 'output_size_in_bytes': 0}) is None
    assert cw._memory_analysis(object()) is None


def test_persistent_cache_counters_singleton():
    c1 = cw.persistent_cache_counters()
    c2 = cw.persistent_cache_counters()
    assert c1 is c2
    snap = c1.snapshot()
    assert set(snap) == {
        'persistent_cache_hits', 'persistent_cache_misses',
        'persistent_cache_dir',
    }
    assert snap['persistent_cache_hits'] >= 0


# ----------------------------------------------------------- trainer paths


@pytest.fixture(scope='module')
def trainer_mod():
    """Module-scope shared-compile Trainer (PR-15 budget convention):
    every Trainer path driven once against one watched dense engine."""
    m, params, (x, y), reg, kfac, _ = _setup(n=32)

    def loss_fn(p, model_state, batch):
        xx, yy = batch
        pred = m.apply({'params': p}, xx)
        return jnp.mean((pred - yy) ** 2), model_state

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac)
    return trainer, params, (x, y), kfac


def test_all_trainer_paths_count_into_engine_watch(trainer_mod):
    trainer, params, (x, y), kfac = trainer_mod
    watch = kfac.compile_watcher()
    state = trainer.init(params)
    for _ in range(2):
        state, _ = trainer.step(state, (x, y))
    batches = (
        jnp.broadcast_to(x, (2,) + x.shape),
        jnp.broadcast_to(y, (2,) + y.shape),
    )
    state, _ = trainer.scan_steps(state, batches)
    state, _ = trainer.step_accumulate(state, [(x, y), (x, y)])
    state, _ = trainer.step_accumulate_scan(state, batches)
    counts = watch.counters()
    assert counts['trainer.step/with_stats'] == 1
    assert counts['trainer.scan_steps'] == 1
    assert counts['trainer.step_accumulate_scan'] == 1
    assert any(k.startswith('trainer.accumulate/') for k in counts)
    assert watch.recompile_count() == 0
    # memory report spans the trainer entries
    report = kfac.compiled_memory_report()
    assert 'trainer.step/with_stats' in report


def test_repeat_paths_zero_new_events(trainer_mod):
    """Re-driving every path after the module fixture warmed them adds
    zero compile events (ordering: runs after the counting test via the
    shared fixture, which is the point — the second pass is free)."""
    trainer, params, (x, y), kfac = trainer_mod
    watch = kfac.compile_watcher()
    state = trainer.init(params)
    state, _ = trainer.step(state, (x, y))
    before = len(watch.events)
    for _ in range(3):
        state, _ = trainer.step(state, (x, y))
    assert len(watch.events) == before
    assert watch.recompile_count() == 0


# -------------------------------------------------------- postmortem bundle


@pytest.mark.faults
def test_postmortem_bundle_carries_compile_events(tmp_path):
    m, params, (x, y), reg, kfac, _ = _setup(
        flight=8, health=health_lib.HealthConfig(warn=False))

    def loss_fn(p, model_state, batch):
        xx, yy = batch
        pred = m.apply({'params': p}, xx)
        return jnp.mean((pred - yy) ** 2), model_state

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac)
    state = trainer.init(params)
    for _ in range(2):
        state, _ = trainer.step(state, (x, y))
    pm = kfac_tpu.PostmortemWriter(tmp_path / 'pms', engine=kfac)
    coll = kfac_tpu.MetricsCollector()
    state, _ = trainer.step(state, faults.poison_batch((x, y), kind='nan'))
    bundle = pm.observe(state, coll.drain(state))
    assert bundle is not None
    events_path = os.path.join(bundle, 'compile_events.jsonl')
    assert os.path.exists(events_path)
    events = [json.loads(line)
              for line in open(events_path).read().splitlines()]
    assert any(e['entry'] == 'trainer.step/with_stats' for e in events)
    mem = json.load(open(os.path.join(bundle, 'compile_memory.json')))
    assert 'trainer.step/with_stats' in mem
    loaded = kfac_inspect.load_bundle(bundle)
    assert loaded['compile_events'] == events
    assert loaded['compile_memory'] == mem
