"""Tests for auxiliary subsystems: schedules, tracing, checkpointing."""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import kfac_tpu
from kfac_tpu import checkpoint, hyperparams, tracing
from testing import models


# ----------------------------------------------------------------- schedules


def test_exp_decay_factor_averaging_values():
    sched = hyperparams.exp_decay_factor_averaging()
    # reference values (kfac/hyperparams.py): step 0 -> treated as 1 -> 0;
    # step 2 -> 0.5; step 100 -> capped at 0.95
    assert float(sched(jnp.asarray(0))) == 0.0
    assert float(sched(jnp.asarray(2))) == 0.5
    np.testing.assert_allclose(float(sched(jnp.asarray(10))), 0.9)
    assert float(sched(jnp.asarray(1000))) == pytest.approx(0.95)


def test_exp_decay_rejects_bad_min():
    with pytest.raises(ValueError):
        hyperparams.exp_decay_factor_averaging(0.0)


def test_lambda_schedule_composes():
    sched = hyperparams.lambda_schedule(0.1, lambda s: 0.5 ** (s // 10))
    assert float(sched(jnp.asarray(0))) == pytest.approx(0.1)
    assert float(sched(jnp.asarray(20))) == pytest.approx(0.025)


def test_piecewise_constant():
    sched = hyperparams.piecewise_constant([10, 20], [1.0, 0.1, 0.01])
    assert float(sched(jnp.asarray(5))) == pytest.approx(1.0)
    assert float(sched(jnp.asarray(10))) == pytest.approx(0.1)
    assert float(sched(jnp.asarray(25))) == pytest.approx(0.01)


def test_schedules_work_inside_jit_as_hyperparams():
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg,
        factor_decay=hyperparams.exp_decay_factor_averaging(),
        damping=hyperparams.exponential_decay(0.01, 0.5, 100),
        kl_clip=None,
    )
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(models.mse_loss(m))
    state = kfac.init()
    (_, _), grads, stats = run(params, (x, y))
    state, pg = jax.jit(kfac.step)(state, grads, stats)
    assert bool(jnp.isfinite(pg['fc1']['kernel']).all())


# ------------------------------------------------------------------- tracing


def test_trace_records_and_averages():
    tracing.clear_trace()

    @tracing.trace(sync=True)
    def work(x):
        return jnp.sum(x * x)

    for _ in range(3):
        work(jnp.arange(100.0))
    t = tracing.get_trace()
    assert 'work' in t and t['work'] > 0
    total = tracing.get_trace(average=False)
    assert total['work'] >= t['work']
    bounded = tracing.get_trace(max_history=1)
    assert bounded['work'] > 0
    tracing.clear_trace()
    assert tracing.get_trace() == {}


def test_log_trace(caplog):
    tracing.clear_trace()

    @tracing.trace(name='custom')
    def f():
        return 1

    f()
    with caplog.at_level(logging.INFO, logger='kfac_tpu.tracing'):
        tracing.log_trace()
    assert any('custom' in r.message for r in caplog.records)


# ---------------------------------------------------------------- checkpoint


def _train_a_bit(kfac, reg, m, params, batch, steps=3):
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(models.mse_loss(m))
    state = kfac.init()
    for _ in range(steps):
        (_, _), grads, stats = run(params, batch)
        state, pg = kfac.step(state, grads, stats)
        params = jax.tree_util.tree_map(lambda p, g: p - 0.05 * g, params, pg)
    return state, params, grads, stats


def test_checkpoint_roundtrip_dense(tmp_path):
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None)
    state, params, grads, stats = _train_a_bit(kfac, reg, m, params, (x, y))

    path = str(tmp_path / 'ckpt')
    checkpoint.save(path, state, extra={'params': params})
    restored, extra = checkpoint.restore(path, kfac, extra_template={'params': params})
    assert int(restored.step) == int(state.step)
    np.testing.assert_allclose(
        np.asarray(restored.a['fc1']), np.asarray(state.a['fc1']), rtol=1e-6
    )
    # decompositions were rematerialized, preconditioning matches
    p1 = kfac.precondition(state, grads)
    p2 = kfac.precondition(restored, grads)
    np.testing.assert_allclose(
        np.asarray(p1['fc1']['kernel']), np.asarray(p2['fc1']['kernel']),
        rtol=1e-4, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(extra['params']['fc1']['kernel']),
        np.asarray(params['fc1']['kernel']),
    )


def test_checkpoint_roundtrip_distributed(tmp_path):
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None)
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(models.mse_loss(m))
    state = dk.init()
    (_, _), grads, stats = run(params, (x, y))
    state, _ = jax.jit(dk.step)(state, grads, stats)

    path = str(tmp_path / 'dckpt')
    checkpoint.save(path, state)
    restored, _ = checkpoint.restore(path, dk)
    assert int(restored.step) == 1
    key = dk.buckets[0].key
    np.testing.assert_allclose(
        np.asarray(restored.a[key]), np.asarray(state.a[key]), rtol=1e-6
    )
    p1 = dk.precondition(state, grads)
    p2 = dk.precondition(restored, grads)
    np.testing.assert_allclose(
        np.asarray(p1['fc1']['kernel']), np.asarray(p2['fc1']['kernel']),
        rtol=1e-4, atol=1e-6,
    )


def test_checkpoint_migrates_across_bucket_granularity(tmp_path):
    """A stacked checkpoint saved under one bucket_granularity restores
    into an engine with another: the manifest detects the layout change
    and migrates through per-layer factors (previously a silent orbax
    shape error — the documented footgun, now guarded)."""
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    mesh = kaisa_mesh(grad_worker_fraction=1.0)
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg1 = kfac_tpu.KFACPreconditioner(
        registry=reg, kl_clip=None, bucket_granularity=1
    )
    dk1 = DistributedKFAC(config=cfg1, mesh=mesh)
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(
        models.mse_loss(m)
    )
    state = dk1.init()
    (_, _), grads, stats = run(params, (x, y))
    state, _ = jax.jit(dk1.step)(state, grads, stats)

    # extras include an optax state (a namedtuple pytree: the structure a
    # target-less orbax restore flattens to dicts — migration must restore
    # extras against their real templates)
    import optax

    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(params)

    path = str(tmp_path / 'gran_ckpt')
    checkpoint.save(
        path, state, extra={'params': params, 'opt_state': opt_state},
        engine=dk1,
    )
    assert (tmp_path / 'gran_ckpt.manifest.json').exists()

    cfg2 = kfac_tpu.KFACPreconditioner(
        registry=reg, kl_clip=None, bucket_granularity=128
    )
    dk2 = DistributedKFAC(config=cfg2, mesh=mesh)
    with pytest.warns(UserWarning, match='migrating'):
        restored, extra = checkpoint.restore(
            path, dk2,
            extra_template={'params': params, 'opt_state': opt_state},
        )
    assert int(restored.step) == 1
    # extras keep their pytree types (optax namedtuples) and values
    assert jax.tree_util.tree_structure(
        extra['opt_state']
    ) == jax.tree_util.tree_structure(opt_state)
    np.testing.assert_array_equal(
        np.asarray(extra['params']['fc1']['kernel']),
        np.asarray(params['fc1']['kernel']),
    )
    p1 = dk1.precondition(state, grads)
    p2 = dk2.precondition(restored, grads)
    np.testing.assert_allclose(
        np.asarray(p1['fc1']['kernel']), np.asarray(p2['fc1']['kernel']),
        rtol=1e-4, atol=1e-6,
    )


def test_checkpoint_migrates_dense_to_distributed(tmp_path):
    """A dense-engine checkpoint with a manifest restores into the stacked
    distributed engine (engine-class layout change -> factor migration)."""
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None)
    state, params, grads, stats = _train_a_bit(kfac, reg, m, params, (x, y))

    path = str(tmp_path / 'dense_ckpt')
    checkpoint.save(path, state, engine=kfac)

    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None)
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    with pytest.warns(UserWarning, match='migrating'):
        restored, _ = checkpoint.restore(path, dk)
    assert int(restored.step) == int(state.step)
    p1 = kfac.precondition(state, grads)
    p2 = dk.precondition(restored, grads)
    np.testing.assert_allclose(
        np.asarray(p1['fc1']['kernel']), np.asarray(p2['fc1']['kernel']),
        rtol=1e-4, atol=1e-6,
    )


def test_elastic_restart_across_mesh_sizes(tmp_path):
    """Elastic restart: a checkpoint saved from an 8-device KAISA engine
    restores into engines built on 4- and 2-device meshes (scale-down
    after losing hosts) and onto a grown mesh again, preconditioning
    identically and continuing to train. The reference has no elastic
    story at all (torchrun --max_restarts 0); here the layout manifest +
    per-layer factor migration make restart topology-free, so 'elastic'
    reduces to re-launching on whatever devices remain."""
    from kfac_tpu.parallel import DistributedKFAC, batch_sharding, kaisa_mesh

    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(
        models.mse_loss(m)
    )
    dk8 = DistributedKFAC(
        config=kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None),
        mesh=kaisa_mesh(grad_worker_fraction=0.5),
    )
    state = dk8.init()
    (_, _), grads, stats = run(params, (x, y))
    state, _ = jax.jit(dk8.step)(state, grads, stats)
    p_ref = np.asarray(dk8.precondition(state, grads)['fc1']['kernel'])

    path = str(tmp_path / 'elastic_ckpt')
    checkpoint.save(path, state, engine=dk8)

    import warnings as warnings_mod

    def restart_on(ndev, from_path, expect_step, p_expect):
        """Restore ``from_path`` onto an ndev-device mesh, check the
        preconditioner output against the pre-restart engine's, take one
        more training step, and save a new checkpoint — returning it with
        the post-step preconditioner output as the next leg's reference."""
        dkn = DistributedKFAC(
            config=kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None),
            mesh=kaisa_mesh(grad_worker_fraction=0.5,
                            devices=jax.devices()[:ndev]),
        )
        with warnings_mod.catch_warnings():
            # migration warns when the slot layout differs; same-layout
            # meshes restore directly — both are fine here
            warnings_mod.simplefilter('ignore', UserWarning)
            restored, _ = checkpoint.restore(from_path, dkn)
        assert int(restored.step) == expect_step
        np.testing.assert_allclose(
            np.asarray(dkn.precondition(restored, grads)['fc1']['kernel']),
            p_expect, rtol=1e-4, atol=1e-6,
            err_msg=f'precondition mismatch after restart on {ndev} devices',
        )
        # training continues on the new topology
        bs = batch_sharding(dkn.mesh)
        (_, _), g2, s2 = run(
            params, (jax.device_put(x, bs), jax.device_put(y, bs))
        )
        restored, pg = jax.jit(dkn.step)(restored, g2, s2)
        assert int(restored.step) == expect_step + 1
        assert np.isfinite(np.asarray(pg['fc1']['kernel'], np.float32)).all()
        new_path = str(tmp_path / f'elastic_ckpt_{ndev}')
        checkpoint.save(new_path, restored, engine=dkn)
        return new_path, np.asarray(
            dkn.precondition(restored, grads)['fc1']['kernel']
        )

    # shrink 8 -> 4 -> 2: each restart resumes the PREVIOUS restart's
    # checkpoint, so every leg is a genuine cross-topology restore...
    path4, p_ref = restart_on(4, path, expect_step=1, p_expect=p_ref)
    path2, p_ref = restart_on(2, path4, expect_step=2, p_expect=p_ref)
    # ...then GROW 2 -> 8: the scale-up direction restores a checkpoint
    # WRITTEN on the 2-device mesh onto the full one
    restart_on(8, path2, expect_step=3, p_expect=p_ref)


def test_checkpoint_migration_rejects_layer_set_mismatch(tmp_path):
    """Factor migration requires identical registered layer sets — a clear
    error, not a silent partial restore."""
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None)
    state, params, grads, stats = _train_a_bit(kfac, reg, m, params, (x, y))
    path = str(tmp_path / 'mismatch_ckpt')
    checkpoint.save(path, state, engine=kfac)

    reg_partial = kfac_tpu.register_model(m, x, skip_layers=['fc2'])
    cfg = kfac_tpu.KFACPreconditioner(registry=reg_partial, kl_clip=None)
    dk = DistributedKFAC(config=cfg, mesh=kaisa_mesh(1.0))
    with pytest.raises(ValueError, match='identical layer sets'):
        checkpoint.restore(path, dk)


def test_checkpoint_migration_rejects_layer_width_change(tmp_path):
    """Same layer names, different widths (the model's hidden size changed
    between save and resume): migration must error, not identity-pad stale
    factors into the wider slots."""
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    x, y = models.regression_data(jax.random.PRNGKey(1), n=64)

    def setup(hidden, granularity):
        m = models.TinyModel(hidden=hidden)
        params = m.init(jax.random.PRNGKey(0), x)['params']
        reg = kfac_tpu.register_model(m, x)
        cfg = kfac_tpu.KFACPreconditioner(
            registry=reg, kl_clip=None, bucket_granularity=granularity
        )
        dk = DistributedKFAC(config=cfg, mesh=kaisa_mesh(1.0))
        run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(
            models.mse_loss(m)
        )
        return m, params, dk, run

    m, params, dk8, run = setup(hidden=8, granularity=1)
    state = dk8.init()
    (_, _), grads, stats = run(params, (x, y))
    state, _ = jax.jit(dk8.step)(state, grads, stats)
    path = str(tmp_path / 'width_ckpt')
    checkpoint.save(path, state, engine=dk8)

    # wider model, different granularity so the migration path triggers
    _, _, dk16, _ = setup(hidden=16, granularity=128)
    with pytest.raises(ValueError, match='layer widths'):
        checkpoint.restore(path, dk16)


def test_save_without_engine_clears_stale_manifest(tmp_path):
    """Re-saving at a path without engine= must delete a leftover sidecar
    so restore cannot slice the new payload with the old layout."""
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None)
    state, params, grads, stats = _train_a_bit(kfac, reg, m, params, (x, y))

    import shutil

    path = str(tmp_path / 'stale_ckpt')
    checkpoint.save(path, state, engine=kfac)
    assert (tmp_path / 'stale_ckpt.manifest.json').exists()
    shutil.rmtree(path)  # orbax refuses overwrite; users clear the dir
    checkpoint.save(path, state)
    assert not (tmp_path / 'stale_ckpt.manifest.json').exists()


def test_factors_from_saved_refuses_pipeline_layouts():
    """Stage-stacked pipeline payloads are not migratable (stage
    re-partition unsupported, as in the reference)."""
    assert (
        checkpoint._factors_from_saved({}, {'n_stages': 2, 'engine': 'X'})
        is None
    )


def test_scheduled_cadence():
    """factor/inv update cadence can itself be a schedule of the step
    (reference LambdaParamScheduler scales the update intervals)."""
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    # update factors every step for the first 2 steps, then every 4
    cadence = lambda step: jnp.where(step < 2, 1, 4)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, factor_update_steps=cadence, inv_update_steps=cadence,
        kl_clip=None,
    )
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(models.mse_loss(m))
    state = kfac.init()
    import jax as _jax
    step_fn = _jax.jit(kfac.step)
    a_hist = []
    for i in range(6):
        (_, _), grads, stats = run(params, (x, y))
        state, _ = step_fn(state, grads, stats)
        a_hist.append(np.asarray(state.a['fc1']).copy())
    # steps 0,1 update; steps 2,3 hold (2%4!=0, 3%4!=0); step 4 updates
    assert np.abs(a_hist[1] - a_hist[0]).max() > 0
    np.testing.assert_array_equal(a_hist[2], a_hist[1])
    np.testing.assert_array_equal(a_hist[3], a_hist[2])
    assert np.abs(a_hist[4] - a_hist[3]).max() > 0


def test_multihost_helpers_single_process():
    from kfac_tpu.parallel import multihost

    assert multihost.process_count() == 1
    assert multihost.process_index() == 0
    multihost.initialize(num_processes=1)  # no-op path
    mesh = multihost.hybrid_kaisa_mesh(grad_worker_fraction=0.5)
    assert mesh.shape['kfac_gw'] == 4 and mesh.shape['kfac_col'] == 2


def test_experimental_warning_importable():
    from kfac_tpu.warnings import ExperimentalFeatureWarning

    assert issubclass(ExperimentalFeatureWarning, Warning)


def test_mixed_cadence_validation():
    """An invalid int interval is rejected even when the other is a schedule."""
    m = models.TinyModel()
    x, _ = models.regression_data(jax.random.PRNGKey(1))
    reg = kfac_tpu.register_model(m, x)
    with pytest.raises(ValueError):
        kfac_tpu.KFACPreconditioner(
            registry=reg,
            factor_update_steps=lambda s: 1,
            inv_update_steps=0,
        )


def test_hybrid_mesh_columns_are_contiguous_blocks():
    from kfac_tpu.parallel import multihost

    mesh = multihost.hybrid_kaisa_mesh(grad_worker_fraction=0.5)
    # columns (grad-worker groups) must be consecutive device runs
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    for c in range(ids.shape[1]):
        col = ids[:, c]
        assert list(col) == list(range(col[0], col[0] + len(col)))


def test_describe_dumps_registration_and_assignment():
    """Pull-based parity with the reference's construction-time logging
    (kfac/preconditioner.py:264-268,300): the dense dump lists every layer
    with factor dims; the distributed dump adds strategy, buckets, and
    per-layer inverse workers."""
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    m = models.TinyModel()
    x, _ = models.regression_data(jax.random.PRNGKey(1))
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg)
    text = cfg.describe()
    for name, h in reg.layers.items():
        assert name in text
        assert f'A={h.a_factor_shape[0]}x{h.a_factor_shape[0]}' in text

    dk = DistributedKFAC(config=cfg, mesh=kaisa_mesh(0.5))
    dtext = dk.describe()
    assert 'strategy=HYBRID_OPT' in dtext
    assert 'bucket' in dtext
    assert 'inverse workers' in dtext
    for name in reg.layers:
        assert name in dtext


def test_metrics_writer_appends_csv(tmp_path):
    from examples import common

    path = str(tmp_path / 'metrics.csv')
    w = common.MetricsWriter(path)
    w.write(0, 'loss', 1.5)
    w.write_many(1, {'loss': 1.25, 'acc': 0.5})
    w.close()
    # append across writer instances (resume) without duplicating the header
    w2 = common.MetricsWriter(path)
    w2.write(2, 'loss', 1.0)
    w2.close()
    lines = open(path).read().splitlines()
    assert lines[0] == 'step,name,value'
    assert lines[1:] == [
        '0,loss,1.5', '1,loss,1.25', '1,acc,0.5', '2,loss,1',
    ]
    # disabled writer (no path) is a no-op
    w3 = common.MetricsWriter(None)
    w3.write(0, 'loss', 1.0)
    w3.close()


def test_factor_checkpoint_moves_between_engine_configs(tmp_path):
    """save_factors/load_factors are topology-independent (the reference's
    per-layer factor-dir checkpoints, gpt_neox/preconditioner.py:394-447):
    factors saved from an exact-dims distributed engine restore into a
    size-class engine AND into the dense engine, and all three produce the
    same preconditioned gradients."""
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=32, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    loss_fn = models.mse_loss(m)
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, (x, y))

    def dist_engine(granularity):
        cfg = kfac_tpu.KFACPreconditioner(
            registry=reg, damping=0.01, kl_clip=None,
            bucket_granularity=granularity,
        )
        return DistributedKFAC(config=cfg, mesh=kaisa_mesh(1.0))

    src = dist_engine(1)
    state = src.init()
    state, _ = jax.jit(src.step)(state, grads, stats)
    path = str(tmp_path / 'factors')
    checkpoint.save_factors(path, src, state)

    # source-truth: precondition with the source engine
    _, pg_src = jax.jit(src.step)(state, grads, None)

    # restore into a size-class engine (different bucket keys/shapes)
    dst = dist_engine(128)
    state_dst = checkpoint.load_factors(path, dst)
    assert int(
        state_dst.step if not isinstance(state_dst, dict)
        else state_dst['step']
    ) == 1
    _, pg_dst = jax.jit(dst.step)(state_dst, grads, None)

    # and into the DENSE engine
    dense = kfac_tpu.KFACPreconditioner(
        registry=reg, damping=0.01, kl_clip=None
    )
    state_dense = checkpoint.load_factors(path, dense)
    _, pg_dense = dense.step(state_dense, grads, None)

    for a, b, c in zip(
        jax.tree_util.tree_leaves(pg_src),
        jax.tree_util.tree_leaves(pg_dst),
        jax.tree_util.tree_leaves(pg_dense),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(c), rtol=2e-3, atol=1e-5
        )


def test_manifest_path_skips_remote_uris():
    """Remote checkpoint URIs have no plain-file sidecar: _manifest_path
    must return None (os.path.abspath would mangle the scheme and open()
    cannot write there) so save warns-and-skips instead of crashing and
    restore proceeds manifest-less."""
    from kfac_tpu import checkpoint

    assert checkpoint._manifest_path('gs://bucket/ckpt/step_5') is None
    assert checkpoint._manifest_path('s3://bucket/x') is None
    local = checkpoint._manifest_path('/tmp/ckpt/step_5')
    assert local == '/tmp/ckpt/step_5.manifest.json'


def test_lm_corpus_rejects_undersized_vocab_json(tmp_path):
    """A stale/hand-edited vocab.json smaller than max(token)+1 must error
    loudly: out-of-range targets would otherwise one_hot to all-zero rows
    and silently turn the fused NLL into bare logsumexp."""
    import json as json_lib

    import pytest

    from examples import data

    np.save(tmp_path / 'corpus.npy', np.array([0, 1, 2, 9], np.int32))
    (tmp_path / 'vocab.json').write_text(json_lib.dumps({'size': 5}))
    with pytest.raises(ValueError, match='vocab.json size=5'):
        data.lm_corpus(data_dir=str(tmp_path))
    # a consistent vocab loads fine
    (tmp_path / 'vocab.json').write_text(json_lib.dumps({'size': 10}))
    toks, vocab = data.lm_corpus(data_dir=str(tmp_path))
    assert vocab == 10 and int(toks.max()) == 9


def test_checkpoint_async_save_roundtrip(tmp_path):
    """save(..., wait=False) returns a handle immediately; the manifest
    sidecar appears only once wait_until_finished commits the write, and
    the checkpoint then restores identically to a blocking save."""
    import os

    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None)
    state, params, grads, stats = _train_a_bit(kfac, reg, m, params, (x, y))

    path = str(tmp_path / 'async_ck')
    handle = checkpoint.save(path, state, engine=kfac, wait=False)
    assert hasattr(handle, 'wait_until_finished')
    # durable-manifest invariant: no sidecar until the wait commits it
    assert not os.path.exists(checkpoint._manifest_path(path))
    handle.wait_until_finished()
    assert os.path.exists(checkpoint._manifest_path(path))
    restored, _ = checkpoint.restore(path, kfac)
    assert int(restored.step) == int(state.step)
    for name in state.a:
        np.testing.assert_allclose(
            np.asarray(restored.a[name]), np.asarray(state.a[name]),
            rtol=1e-6,
        )
