"""Tests for the preemption-safe checkpoint autopilot (kfac_tpu.resilience).

Covers the rotation invariants (fresh step dirs, atomic LATEST pointer,
keep-N pruning), the signal machinery (flag-only handlers, exit-outranks-
continue priority, on_step emergency flush), torn-write fallback via
testing/faults.corrupt_checkpoint, transient-I/O retry/backoff, elastic
dense <-> stacked restore through the manager, Trainer-integrated periodic
saves + resume continuity, and — slow-marked — a real ``kill -TERM``
against a subprocess training run that must leave a durable, resumable
checkpoint behind.
"""

import gc
import importlib.util
import json
import os
import signal as signal_mod
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import kfac_tpu
from kfac_tpu import checkpoint
from kfac_tpu.resilience import CheckpointManager, Preempted, signals
from kfac_tpu.warnings import CheckpointResilienceWarning
from testing import models
from testing.faults import corrupt_checkpoint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, 'testing', 'resilience_worker.py')


@pytest.fixture(autouse=True)
def _clean_signal_state():
    signals.reset()
    yield
    signals.reset()


def _dense_setup(n=64):
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=n)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None)
    return m, (x, y), params, reg, kfac


def _run_steps(kfac, reg, m, params, batch, state=None, steps=1):
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(
        models.mse_loss(m)
    )
    state = kfac.init() if state is None else state
    grads = None
    for _ in range(steps):
        (_, _), grads, stats = run(params, batch)
        state, pg = kfac.step(state, grads, stats)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 0.05 * g, params, pg
        )
    return state, params, grads


# ------------------------------------------------------------------ rotation


def test_rotation_keep_and_atomic_latest_pointer(tmp_path):
    m, batch, params, reg, kfac = _dense_setup()
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(
        models.mse_loss(m)
    )
    mgr = CheckpointManager(
        tmp_path, engine=kfac, save_interval_steps=2, keep=2,
        install_signals=(),
    )
    state = kfac.init()
    for _ in range(6):
        (_, _), grads, stats = run(params, batch)
        state, pg = kfac.step(state, grads, stats)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 0.05 * g, params, pg
        )
        mgr.on_step(state)
    mgr.finalize()
    # saved on cadence at steps 2, 4, 6; keep=2 pruned step 2
    assert mgr.rotation_steps() == [6, 4]
    assert mgr.latest_step() == 6
    with open(tmp_path / 'LATEST') as f:
        assert f.read().strip() == 'step_00000006'
    assert not os.path.exists(mgr.step_dir(2))
    for s in (4, 6):
        assert mgr._is_committed(s)
        # manifest sidecar rode along (elastic restore stays available)
        assert os.path.exists(mgr.checkpoint_path(s) + '.manifest.json')


def test_restore_latest_roundtrip(tmp_path):
    m, batch, params, reg, kfac = _dense_setup()
    state, params, grads = _run_steps(kfac, reg, m, params, batch, steps=2)
    mgr = CheckpointManager(
        tmp_path, engine=kfac, install_signals=(), async_save=False
    )
    path = mgr.save(state)
    result = mgr.restore_latest()
    assert result.step == 2
    assert result.path == path
    assert result.extra == {}
    np.testing.assert_allclose(
        np.asarray(result.state.a['fc1']), np.asarray(state.a['fc1']),
        rtol=1e-6,
    )
    p1 = kfac.precondition(state, grads)
    p2 = kfac.precondition(result.state, grads)
    np.testing.assert_allclose(
        np.asarray(p1['fc1']['kernel']), np.asarray(p2['fc1']['kernel']),
        rtol=1e-5, atol=1e-7,
    )


def test_restore_latest_empty_rotation(tmp_path):
    _, _, _, _, kfac = _dense_setup()
    mgr = CheckpointManager(tmp_path, engine=kfac, install_signals=())
    assert mgr.restore_latest() is None
    mgr2 = CheckpointManager(tmp_path / 'other', install_signals=())
    with pytest.raises(ValueError, match='engine'):
        mgr2.restore_latest()


@pytest.mark.faults
@pytest.mark.parametrize('mode', ['truncate', 'delete', 'metadata'])
def test_restore_falls_back_past_torn_checkpoint(tmp_path, mode):
    """A corrupt newest checkpoint (torn write, lost object, or missing
    commit markers) is skipped with a warning; the previous rotation
    entry restores — the run resumes instead of crashing."""
    m, batch, params, reg, kfac = _dense_setup()
    mgr = CheckpointManager(
        tmp_path, engine=kfac, install_signals=(), async_save=False, keep=3
    )
    state, params, _ = _run_steps(kfac, reg, m, params, batch)
    mgr.save(state)
    state, params, _ = _run_steps(
        kfac, reg, m, params, batch, state=state
    )
    newest = mgr.save(state)
    assert mgr.latest_step() == 2
    corrupt_checkpoint(newest, mode=mode)
    with pytest.warns(CheckpointResilienceWarning, match='falling back'):
        result = mgr.restore_latest()
    assert result.step == 1
    assert result.path == mgr.checkpoint_path(1)
    # the fallback warning is rate-limited per path: a second walk stays
    # quiet about the same corpse
    import warnings as warnings_mod

    with warnings_mod.catch_warnings():
        warnings_mod.simplefilter('error', CheckpointResilienceWarning)
        assert mgr.restore_latest().step == 1


@pytest.mark.faults
def test_restore_survives_torn_latest_pointer(tmp_path):
    """A LATEST pointer torn mid-write (truncated, then trailing garbage
    bytes — ``corrupt_checkpoint(..., 'torn_latest')``) must degrade to
    "no pointer", not crash: ``latest_step`` returns None and
    ``restore_latest`` still finds the newest COMMITTED step via the
    rotation scan."""
    m, batch, params, reg, kfac = _dense_setup()
    mgr = CheckpointManager(
        tmp_path, engine=kfac, install_signals=(), async_save=False, keep=3
    )
    state, params, _ = _run_steps(kfac, reg, m, params, batch)
    mgr.save(state)
    state, params, _ = _run_steps(kfac, reg, m, params, batch, state=state)
    mgr.save(state)
    assert mgr.latest_step() == 2
    victim = corrupt_checkpoint(str(tmp_path), mode='torn_latest')
    assert victim == os.path.join(str(tmp_path), 'LATEST')
    # the torn pointer reads as garbage -> None, no UnicodeDecodeError
    assert mgr.latest_step() is None
    result = mgr.restore_latest()
    assert result.step == 2
    assert int(result.state.step) == 2


@pytest.mark.faults
def test_restore_walks_back_on_torn_latest_plus_torn_payload(tmp_path):
    """The chaos harness's ``torn_checkpoint`` fault class end-to-end:
    LATEST torn AND the newest payload truncated — the restore must walk
    back to the newest intact rotation entry instead of crashing on
    either corruption."""
    m, batch, params, reg, kfac = _dense_setup()
    mgr = CheckpointManager(
        tmp_path, engine=kfac, install_signals=(), async_save=False, keep=3
    )
    state, params, _ = _run_steps(kfac, reg, m, params, batch)
    mgr.save(state)
    state, params, _ = _run_steps(kfac, reg, m, params, batch, state=state)
    newest = mgr.save(state)
    corrupt_checkpoint(str(tmp_path), mode='torn_latest')
    corrupt_checkpoint(newest, mode='truncate')
    with pytest.warns(CheckpointResilienceWarning, match='falling back'):
        result = mgr.restore_latest()
    assert result.step == 1
    assert result.path == mgr.checkpoint_path(1)


def test_corrupt_checkpoint_rejects_unknown_mode(tmp_path):
    with pytest.raises(ValueError, match='unknown corruption mode'):
        corrupt_checkpoint(str(tmp_path), mode='bitflip')
    with pytest.raises(FileNotFoundError):
        corrupt_checkpoint(str(tmp_path / 'nope'), mode='truncate')
    with pytest.raises(FileNotFoundError):
        corrupt_checkpoint(str(tmp_path), mode='torn_latest')  # no LATEST


# ----------------------------------------------------- checkpoint.py policy


def test_save_overwrite_policy(tmp_path):
    m, batch, params, reg, kfac = _dense_setup()
    state, params, _ = _run_steps(kfac, reg, m, params, batch)
    path = str(tmp_path / 'ckpt')
    checkpoint.save(path, state, engine=kfac)
    # the default refuses and the error names the path + the escape hatch
    with pytest.raises(ValueError, match='overwrite=True'):
        checkpoint.save(path, state)
    with pytest.raises(ValueError, match='ckpt'):
        checkpoint.save(path, state)
    state2, _, _ = _run_steps(kfac, reg, m, params, batch, state=state)
    checkpoint.save(path, state2, engine=kfac, overwrite=True)
    restored, _ = checkpoint.restore(path, kfac)
    assert int(restored.step) == 2


def test_async_handle_context_manager(tmp_path):
    m, batch, params, reg, kfac = _dense_setup()
    state, _, _ = _run_steps(kfac, reg, m, params, batch)
    path = str(tmp_path / 'actx')
    with checkpoint.save(path, state, engine=kfac, wait=False) as handle:
        pass
    # __exit__ waited: checkpoint durable and manifest finalized
    assert os.path.exists(path + '.manifest.json')
    restored, _ = checkpoint.restore(path, kfac)
    assert int(restored.step) == 1
    handle.wait_until_finished()  # idempotent


def test_async_handle_dropped_without_wait_warns(tmp_path):
    m, batch, params, reg, kfac = _dense_setup()
    state, _, _ = _run_steps(kfac, reg, m, params, batch)
    handle = checkpoint.save(str(tmp_path / 'adrop'), state, wait=False)
    ckptr = handle._ckptr  # keep orbax alive to drain its threads after
    with pytest.warns(ResourceWarning, match='wait_until_finished'):
        del handle
        gc.collect()
    ckptr.wait_until_finished()


def test_restore_without_manifest_warns(tmp_path):
    m, batch, params, reg, kfac = _dense_setup()
    state, _, _ = _run_steps(kfac, reg, m, params, batch)
    path = str(tmp_path / 'bare')
    checkpoint.save(path, state)  # no engine= -> no manifest sidecar
    with pytest.warns(CheckpointResilienceWarning, match='manifest'):
        restored, _ = checkpoint.restore(path, kfac)
    assert int(restored.step) == 1


# ------------------------------------------------------------------- signals


def test_signal_flag_priority_and_uninstall():
    before_term = signal_mod.getsignal(signal_mod.SIGTERM)
    before_usr1 = signal_mod.getsignal(signal_mod.SIGUSR1)
    with signals.install():
        assert signals.preemption_requested() is None
        os.kill(os.getpid(), signal_mod.SIGUSR1)
        assert signals.preemption_requested() == 'SIGUSR1'
        os.kill(os.getpid(), signal_mod.SIGTERM)
        assert signals.preemption_requested() == 'SIGTERM'
        # a continue-signal cannot demote a pending exit-signal
        os.kill(os.getpid(), signal_mod.SIGUSR1)
        assert signals.preemption_requested() == 'SIGTERM'
        assert signals.consume() == 'SIGTERM'
        assert signals.preemption_requested() is None
    assert signal_mod.getsignal(signal_mod.SIGTERM) is before_term
    assert signal_mod.getsignal(signal_mod.SIGUSR1) is before_usr1
    with pytest.raises(ValueError, match='SIGHUP'):
        signals.install(['SIGHUP'])


def test_signal_storm_redelivery_during_save_is_dropped():
    """Schedulers re-deliver SIGTERM every few seconds until the process
    dies. A re-delivery landing while the emergency save for that same
    signal is in flight must NOT re-arm the flag (it would re-enter
    save_emergency at the next boundary or leave a stale flag behind the
    Preempted unwind); an ESCALATION — SIGTERM during a SIGUSR1 save —
    must still latch."""
    with signals.install():
        # storm: N stacked SIGTERMs while the SIGTERM save runs
        with signals.save_in_flight('SIGTERM'):
            for _ in range(3):
                os.kill(os.getpid(), signal_mod.SIGTERM)
            assert signals.preemption_requested() is None
        assert signals.preemption_requested() is None  # nothing latched
        # escalation: SIGTERM during a SIGUSR1 snapshot save latches...
        with signals.save_in_flight('SIGUSR1'):
            os.kill(os.getpid(), signal_mod.SIGUSR1)  # re-delivery: dropped
            assert signals.preemption_requested() is None
            os.kill(os.getpid(), signal_mod.SIGTERM)  # escalation: latched
            assert signals.preemption_requested() == 'SIGTERM'
            # ...and a SIGUSR1 cannot demote the latched EXIT priority
            os.kill(os.getpid(), signal_mod.SIGUSR1)
            assert signals.preemption_requested() == 'SIGTERM'
        assert signals.consume() == 'SIGTERM'
    with pytest.raises(ValueError, match='SIGHUP'):
        with signals.save_in_flight('SIGHUP'):
            pass
    # reset() clears the in-flight marker too (crash-safety for tests)
    with signals.save_in_flight('SIGTERM'):
        assert signals.save_in_flight_signal() == 'SIGTERM'
        signals.reset()
        assert signals.save_in_flight_signal() is None


def test_save_emergency_idempotent_under_stacked_sigterm(tmp_path):
    """End-to-end storm idempotence: a second SIGTERM delivered WHILE
    save_emergency('SIGTERM') is writing must not re-enter the save or
    leave a pending flag; a SIGTERM delivered during a non-signal save
    (fleet migration) must still latch — the preemption notice outlives
    that save."""
    m, batch, params, reg, kfac = _dense_setup()
    state, _, _ = _run_steps(kfac, reg, m, params, batch)
    with CheckpointManager(
        tmp_path, engine=kfac, save_interval_steps=None, async_save=False
    ) as mgr:
        calls = []
        real_save = mgr.save

        def storming_save(state, step=None, block=True):
            calls.append(step)
            # the scheduler re-delivers mid-write, twice
            os.kill(os.getpid(), signal_mod.SIGTERM)
            os.kill(os.getpid(), signal_mod.SIGTERM)
            return real_save(state, step=step, block=block)

        mgr.save = storming_save
        path = mgr.save_emergency(state, reason='SIGTERM')
        assert calls == [1]
        assert path == mgr.checkpoint_path(1)
        # the storm was absorbed: no pending flag, nothing to re-enter
        assert signals.preemption_requested() is None
        # non-signal reason: a SIGTERM arriving DURING a fleet-migration
        # save still latches — the preemption notice outlives that save
        mgr.save = storming_save
        mgr.save_emergency(state, reason='fleet-migration', step=2)
        assert calls == [1, 2]
        assert signals.preemption_requested() == 'SIGTERM'
        signals.reset()


def test_on_step_sigusr1_saves_and_continues(tmp_path):
    m, batch, params, reg, kfac = _dense_setup()
    state, _, _ = _run_steps(kfac, reg, m, params, batch)
    with CheckpointManager(
        tmp_path, engine=kfac, save_interval_steps=None
    ) as mgr:
        assert mgr.on_step(state) is None  # no signal, periodic disabled
        os.kill(os.getpid(), signal_mod.SIGUSR1)
        path = mgr.on_step(state)
        assert path == mgr.checkpoint_path(1)
        assert mgr.latest_step() == 1
        assert signals.preemption_requested() is None  # consumed
        assert mgr.on_step(state) is None  # training continues normally


def test_on_step_sigterm_preempts_after_durable_save(tmp_path):
    m, batch, params, reg, kfac = _dense_setup()
    state, _, _ = _run_steps(kfac, reg, m, params, batch)
    with CheckpointManager(
        tmp_path, engine=kfac, save_interval_steps=None
    ) as mgr:
        os.kill(os.getpid(), signal_mod.SIGTERM)
        with pytest.raises(Preempted, match='SIGTERM') as excinfo:
            mgr.on_step(state)
        assert excinfo.value.step == 1
        # by the time Preempted unwinds, the checkpoint is durable
        assert mgr.latest_step() == 1
        assert mgr.restore_latest().step == 1


def test_multihost_coordination_defers_and_uses_agreed_step(
    tmp_path, monkeypatch
):
    """Multi-host (simulated): barrier participation depends only on the
    step cadence — an off-cadence local signal is deferred, not gathered;
    on the cadence step the pod-agreed (max) step names the rotation
    entry and the Preempted step, and a pod-wide EXIT is reported as
    SIGTERM even when this host only caught a SIGUSR1."""
    from kfac_tpu.parallel import multihost

    m, batch, params, reg, kfac = _dense_setup()
    state, _, _ = _run_steps(kfac, reg, m, params, batch)
    gathers, barriers = [], []
    monkeypatch.setattr(multihost, 'process_count', lambda: 2)
    monkeypatch.setattr(multihost, 'barrier', barriers.append)

    def fake_agree(code, step):
        # another host is 3 steps ahead and saw the SIGTERM
        gathers.append((code, step))
        return max(code, 2), step + 3

    monkeypatch.setattr(multihost, 'agree_emergency', fake_agree)
    with CheckpointManager(
        tmp_path, engine=kfac, save_interval_steps=None,
        coordinate_every=4,
    ) as mgr:
        os.kill(os.getpid(), signal_mod.SIGUSR1)
        # step 3 is off-cadence: no gather, the flag stays pending
        assert mgr.on_step(state, step=3) is None
        assert gathers == []
        assert signals.preemption_requested() == 'SIGUSR1'
        # step 4 coordinates: pod says EXIT at agreed step 7
        with pytest.raises(Preempted, match='SIGTERM') as excinfo:
            mgr.on_step(state, step=4)
        assert gathers == [(1, 4)]
        assert excinfo.value.step == 7
        assert excinfo.value.path == mgr.checkpoint_path(7)
        assert mgr.latest_step() == 7
        assert barriers  # rank 0's stale-dir clear is ordered before writes


def test_prune_removes_stale_uncommitted_dirs(tmp_path):
    """A torn corpse (step dir without orbax commit markers) older than
    the newest committed checkpoint is pruned at the next commit instead
    of accumulating forever; an uncommitted NEWER dir survives (it may be
    an async save still in flight)."""
    m, batch, params, reg, kfac = _dense_setup()
    state, _, _ = _run_steps(kfac, reg, m, params, batch)
    mgr = CheckpointManager(
        tmp_path, engine=kfac, install_signals=(), async_save=False
    )
    os.makedirs(os.path.join(mgr.step_dir(0), 'ckpt'))  # crashed attempt
    os.makedirs(os.path.join(mgr.step_dir(9), 'ckpt'))  # maybe in flight
    mgr.save(state)  # commits step 1 -> prune runs
    assert not os.path.exists(mgr.step_dir(0))
    assert os.path.exists(mgr.step_dir(9))
    assert mgr.latest_step() == 1


def test_save_emergency_reuses_committed_step(tmp_path):
    m, batch, params, reg, kfac = _dense_setup()
    state, _, _ = _run_steps(kfac, reg, m, params, batch)
    mgr = CheckpointManager(
        tmp_path, engine=kfac, install_signals=(), async_save=False
    )
    path = mgr.save(state)
    sentinel = os.path.join(mgr.step_dir(1), 'sentinel')
    open(sentinel, 'w').close()
    # already durable: the grace window is not spent re-writing the bytes
    assert mgr.save_emergency(state, reason='test') == path
    assert os.path.exists(sentinel)


# ------------------------------------------------------------ retry/backoff


def test_retry_backoff_on_transient_io(tmp_path, monkeypatch):
    m, batch, params, reg, kfac = _dense_setup()
    state, _, _ = _run_steps(kfac, reg, m, params, batch)
    sleeps = []
    mgr = CheckpointManager(
        tmp_path, engine=kfac, install_signals=(), async_save=False,
        backoff_base=0.5, backoff_max=8.0, sleep=sleeps.append,
    )
    real_save = checkpoint.save
    calls = {'n': 0}

    def flaky(*args, **kwargs):
        calls['n'] += 1
        if calls['n'] <= 2:
            raise OSError('simulated transient I/O failure')
        return real_save(*args, **kwargs)

    monkeypatch.setattr(checkpoint, 'save', flaky)
    with pytest.warns(CheckpointResilienceWarning, match='retry'):
        mgr.save(state)
    assert calls['n'] == 3
    assert sleeps == [0.5, 1.0]  # capped exponential backoff
    monkeypatch.undo()
    assert mgr.restore_latest().step == 1


def test_retry_exhaustion_raises(tmp_path, monkeypatch):
    m, batch, params, reg, kfac = _dense_setup()
    state, _, _ = _run_steps(kfac, reg, m, params, batch)
    sleeps = []
    mgr = CheckpointManager(
        tmp_path, engine=kfac, install_signals=(), async_save=False,
        max_retries=1, backoff_base=0.5, sleep=sleeps.append,
    )

    def always_fail(*args, **kwargs):
        raise OSError('disk on fire')

    monkeypatch.setattr(checkpoint, 'save', always_fail)
    with pytest.warns(CheckpointResilienceWarning, match='retry'):
        with pytest.raises(OSError, match='disk on fire'):
            mgr.save(state)
    assert sleeps == [0.5]


# ------------------------------------------------------------------- elastic


def test_elastic_restore_dense_and_stacked_via_manager(tmp_path):
    """Acceptance: a dense checkpoint restores through the manager into a
    stacked engine with a different bucket_granularity (and back),
    factors allclose, on the 8-device CPU mesh."""
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    m, batch, params, reg, kfac = _dense_setup()
    state, params, grads = _run_steps(kfac, reg, m, params, batch, steps=2)
    mgr = CheckpointManager(
        tmp_path / 'fwd', engine=kfac, install_signals=(), async_save=False
    )
    mgr.save(state)

    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    dk = DistributedKFAC(
        config=kfac_tpu.KFACPreconditioner(
            registry=reg, kl_clip=None, bucket_granularity=128
        ),
        mesh=mesh,
    )
    with pytest.warns(UserWarning, match='migrating'):
        result = mgr.restore_latest(engine=dk)
    assert result.step == 2
    src = kfac.extract_factors(state)
    dst = dk.extract_factors(result.state)
    for name, fg in src.items():
        for side in ('a', 'g'):
            np.testing.assert_allclose(
                np.asarray(dst[name][side]), np.asarray(fg[side]),
                rtol=1e-6, err_msg=f'{name}/{side}',
            )
    p1 = kfac.precondition(state, grads)
    p2 = dk.precondition(result.state, grads)
    np.testing.assert_allclose(
        np.asarray(p1['fc1']['kernel']), np.asarray(p2['fc1']['kernel']),
        rtol=1e-4, atol=1e-6,
    )

    # and back: stacked -> fresh dense engine
    mgr2 = CheckpointManager(
        tmp_path / 'back', engine=dk, install_signals=(), async_save=False
    )
    mgr2.save(result.state)
    kfac2 = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None)
    with pytest.warns(UserWarning, match='migrating'):
        back = mgr2.restore_latest(engine=kfac2)
    assert back.step == 2
    for name, fg in src.items():
        for side in ('a', 'g'):
            np.testing.assert_allclose(
                np.asarray(kfac2.extract_factors(back.state)[name][side]),
                np.asarray(fg[side]), rtol=1e-6,
                err_msg=f'{name}/{side}',
            )


@pytest.mark.faults
def test_restore_latest_every_candidate_corrupt_returns_none(tmp_path):
    """When EVERY rotation entry is unusable, restore_latest hands back
    None (the fresh-start contract) after warning exactly once per
    candidate — and a second walk over the same corpses stays quiet
    (the per-path rate limit)."""
    import warnings as warnings_mod

    m, batch, params, reg, kfac = _dense_setup()
    mgr = CheckpointManager(
        tmp_path, engine=kfac, install_signals=(), async_save=False, keep=3
    )
    state = None
    paths = []
    for _ in range(3):
        state, params, _ = _run_steps(
            kfac, reg, m, params, batch, state=state
        )
        paths.append(mgr.save(state))
    assert mgr.rotation_steps() == [3, 2, 1]
    for path in paths:
        corrupt_checkpoint(path, mode='truncate')
    with warnings_mod.catch_warnings(record=True) as caught:
        warnings_mod.simplefilter('always')
        assert mgr.restore_latest() is None
    unusable = [
        w for w in caught
        if isinstance(w.message, CheckpointResilienceWarning)
        and 'unusable' in str(w.message)
    ]
    assert len(unusable) == 3
    # rate-limited: the second walk re-visits no corpse loudly
    with warnings_mod.catch_warnings():
        warnings_mod.simplefilter('error', CheckpointResilienceWarning)
        assert mgr.restore_latest() is None


def test_elastic_restore_engine_overrides_manager_granularity(tmp_path):
    """restore_latest(engine=...) with a DIFFERENT bucket granularity
    than the manager's own engine migrates into the caller's layout —
    the manager binding is a default, not a constraint (the fleet
    controller's speculative-migration restore relies on this)."""
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    m, batch, params, reg, _ = _dense_setup()

    def stacked(granularity):
        return DistributedKFAC(
            config=kfac_tpu.KFACPreconditioner(
                registry=reg, kl_clip=None, bucket_granularity=granularity
            ),
            mesh=kaisa_mesh(grad_worker_fraction=0.5),
        )

    dk64 = stacked(64)
    state, params, _ = _run_steps(dk64, reg, m, params, batch, steps=2)
    mgr = CheckpointManager(
        tmp_path, engine=dk64, install_signals=(), async_save=False
    )
    mgr.save(state)

    dk128 = stacked(128)
    with pytest.warns(UserWarning, match='migrating'):
        result = mgr.restore_latest(engine=dk128)
    assert result.step == 2
    assert mgr.engine is dk64  # the binding itself is untouched
    src = dk64.extract_factors(state)
    dst = dk128.extract_factors(result.state)
    for name, fg in src.items():
        for side in ('a', 'g'):
            np.testing.assert_allclose(
                np.asarray(dst[name][side]), np.asarray(fg[side]),
                rtol=1e-6, err_msg=f'{name}/{side}',
            )


# -------------------------------------------------------- Trainer lifecycle


def test_trainer_periodic_saves_and_resume_continuity(tmp_path):
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(p, model_state, batch):
        bx, by = batch
        pred = m.apply({'params': p}, bx)
        return jnp.mean((pred - by) ** 2), model_state

    def make(directory):
        kfac = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None)
        mgr = CheckpointManager(
            directory, engine=kfac, save_interval_steps=2, keep=2,
            install_signals=(),
        )
        trainer = kfac_tpu.Trainer(
            loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac,
            checkpoints=mgr,
        )
        return trainer, mgr

    trainer, mgr = make(tmp_path)
    state = trainer.init(params)
    losses, state_at_4 = [], None
    for i in range(5):
        state, loss = trainer.step(state, (x, y))
        losses.append(float(loss))
        if i == 3:
            state_at_4 = state
    mgr.finalize()
    assert mgr.latest_step() == 4
    assert mgr.rotation_steps() == [4, 2]

    trainer2, mgr2 = make(tmp_path)
    resumed = trainer2.restore_latest(params)
    assert resumed is not None
    assert int(jax.device_get(resumed.kfac_state.step)) == 4
    np.testing.assert_array_equal(
        np.asarray(resumed.params['fc1']['kernel']),
        np.asarray(state_at_4.params['fc1']['kernel']),
    )
    # continuity: the resumed run's next step reproduces the original
    # run's 5th step
    resumed, loss5 = trainer2.step(resumed, (x, y))
    np.testing.assert_allclose(float(loss5), losses[4], rtol=1e-6)
    assert trainer2._step_count == 5
    assert int(jax.device_get(resumed.kfac_state.step)) == 5

    # an empty rotation hands the caller back to a fresh start
    trainer3, _ = make(tmp_path / 'empty')
    assert trainer3.restore_latest(params) is None


@pytest.mark.faults
def test_postmortem_degrade_flushes_emergency_checkpoint(tmp_path):
    """The health sentinel's degrade event, observed by the flight
    recorder's PostmortemWriter, flushes one emergency checkpoint into
    the manager's rotation and records its path in the bundle MANIFEST —
    the diverged state is preserved next to the telemetry."""
    from kfac_tpu import health as health_lib
    from testing import faults

    m, batch, params, reg, _ = _dense_setup()
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, kl_clip=None, flight=8,
        health=health_lib.HealthConfig(warn=False, degrade_after=1),
    )
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(
        models.mse_loss(m)
    )
    step = jax.jit(kfac.step)
    mgr = CheckpointManager(
        tmp_path / 'rot', engine=kfac, install_signals=(),
        async_save=False, save_interval_steps=None,
    )
    pm = kfac_tpu.PostmortemWriter(
        tmp_path / 'pms', engine=kfac, checkpoint_manager=mgr
    )
    state = kfac.init()
    (_, _), grads, stats = run(params, batch)
    state, _ = step(state, grads, stats, loss=jnp.float32(1.0))
    assert pm.observe(state) is None
    assert mgr.latest_step() is None  # healthy steps save nothing
    state, _ = step(
        state, grads, faults.poison_stats(stats, 'fc2', side='a'),
        loss=jnp.float32(1.0),
    )
    bundle = pm.observe(state)
    assert bundle is not None and 'degrade' in os.path.basename(bundle)
    man = json.load(open(os.path.join(bundle, 'MANIFEST.json')))
    assert man['emergency_checkpoint'] == mgr.checkpoint_path(2)
    assert mgr.latest_step() == 2
    # the quarantine rolled the poisoned factor back, so the emergency
    # checkpoint holds healthy factors and restores cleanly
    assert mgr.restore_latest().step == 2


# --------------------------------------------------------------- subprocess


def _read_events(text):
    events = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith('{'):
            try:
                events.append(json.loads(line))
            except ValueError:
                continue
    return events


@pytest.mark.slow
def test_subprocess_sigterm_leaves_resumable_checkpoint(tmp_path):
    """Real preemption: kill -TERM a live training process mid-run. The
    worker must exit 0 with a durable emergency checkpoint, and a second
    invocation must resume from exactly that step and train on."""
    ckpt_dir = str(tmp_path / 'rot')
    env = dict(os.environ)
    env['PALLAS_AXON_POOL_IPS'] = ''  # never touch the TPU tunnel
    env['JAX_PLATFORMS'] = 'cpu'
    env.pop('XLA_FLAGS', None)  # single-device worker: fastest compile
    env.setdefault(
        'JAX_COMPILATION_CACHE_DIR', os.path.join(REPO, '.jax_cache')
    )
    err_path = tmp_path / 'worker.err'
    with open(err_path, 'w') as errf:
        proc = subprocess.Popen(
            [sys.executable, WORKER, ckpt_dir, '1000', '2', '0.1'],
            stdout=subprocess.PIPE, stderr=errf, text=True, env=env,
            cwd=REPO,
        )
        events = []
        try:
            # the worker self-terminates only via Preempted, so the parent
            # must send the signal once training is demonstrably underway
            for line in proc.stdout:
                events.extend(_read_events(line))
                if events and events[-1].get('event') == 'step' and (
                    events[-1]['step'] >= 3
                ):
                    proc.send_signal(signal_mod.SIGTERM)
                    break
            out, _ = proc.communicate(timeout=300)
        finally:
            proc.kill()
    events.extend(_read_events(out))
    assert proc.returncode == 0, err_path.read_text()[-4000:]
    pre = [e for e in events if e.get('event') == 'preempted']
    assert pre, events
    assert pre[0]['signal'] == 'SIGTERM'
    saved = pre[0]['saved_step']
    assert saved >= 3
    assert pre[0]['latest'] == saved
    assert os.path.exists(os.path.join(ckpt_dir, 'LATEST'))

    # phase 2: a fresh process resumes from the emergency checkpoint and
    # runs two more steps to completion
    done_run = subprocess.run(
        [sys.executable, WORKER, ckpt_dir, str(saved + 2), '2'],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )
    assert done_run.returncode == 0, done_run.stderr[-4000:]
    ev2 = _read_events(done_run.stdout)
    start = next(e for e in ev2 if e['event'] == 'start')
    done = next(e for e in ev2 if e['event'] == 'done')
    assert start['resumed_step'] == saved
    assert done['final_step'] == saved + 2
    # one of the two extra steps hit the interval-2 cadence and its
    # finalized periodic save moved the pointer past the emergency one
    assert done['latest'] > saved


@pytest.mark.slow
def test_subprocess_sigterm_agreed_step_single_rotation_entry(tmp_path):
    """Real preemption under simulated pod skew: the worker shims
    ``agree_emergency`` so a peer is 3 steps ahead at coordination time.
    The emergency save must land under the POD-AGREED step — one
    rotation entry, pointed at by LATEST — never this host's local step
    (the PR-4 review fix: per-host saves at divergent steps tore the
    rotation). The saved state itself still carries the local counter,
    so a resume restarts from the local step inside the agreed entry."""
    skew = 3
    ckpt_dir = str(tmp_path / 'rot')
    env = dict(os.environ)
    env['PALLAS_AXON_POOL_IPS'] = ''  # never touch the TPU tunnel
    env['JAX_PLATFORMS'] = 'cpu'
    env.pop('XLA_FLAGS', None)  # single-device worker: fastest compile
    env.setdefault(
        'JAX_COMPILATION_CACHE_DIR', os.path.join(REPO, '.jax_cache')
    )
    err_path = tmp_path / 'worker.err'
    with open(err_path, 'w') as errf:
        proc = subprocess.Popen(
            [
                sys.executable, WORKER, ckpt_dir, '1000', '2', '0.1',
                str(skew),
            ],
            stdout=subprocess.PIPE, stderr=errf, text=True, env=env,
            cwd=REPO,
        )
        events = []
        try:
            for line in proc.stdout:
                events.extend(_read_events(line))
                if events and events[-1].get('event') == 'step' and (
                    events[-1]['step'] >= 3
                ):
                    proc.send_signal(signal_mod.SIGTERM)
                    break
            out, _ = proc.communicate(timeout=300)
        finally:
            proc.kill()
    events.extend(_read_events(out))
    assert proc.returncode == 0, err_path.read_text()[-4000:]
    pre = [e for e in events if e.get('event') == 'preempted']
    assert pre, events
    local = pre[0]['local_step']
    saved = pre[0]['saved_step']
    assert local is not None and local >= 3
    # the agreed (skewed-peer) step names the checkpoint, not the local
    assert saved == local + skew
    assert pre[0]['latest'] == saved
    # exactly one rotation entry for the agreed step, on disk and in the
    # worker's own view of the rotation
    assert pre[0]['rotation'].count(saved) == 1
    assert os.path.isdir(os.path.join(ckpt_dir, f'step_{saved:08d}'))

    # the agreed entry is restorable; the state inside carries the local
    # counter (the peer was ahead, this host's weights are at `local`)
    resume = subprocess.run(
        [sys.executable, WORKER, ckpt_dir, str(local + 1), '2'],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )
    assert resume.returncode == 0, resume.stderr[-4000:]
    ev2 = _read_events(resume.stdout)
    start = next(e for e in ev2 if e['event'] == 'start')
    assert start['resumed_step'] == local


# ---------------------------------------------------------------- docs lint


def test_signal_doc_lint_in_sync():
    spec = importlib.util.spec_from_file_location(
        'lint_signals', os.path.join(REPO, 'tools', 'lint_signals.py')
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.check(os.path.join(REPO, 'docs', 'ROBUSTNESS.md')) == []
