"""Integration gate: K-FAC must beat the first-order baseline.

The analogue of the reference's MNIST integration test
(tests/integration/mnist_integration_test.py:104-176: Adadelta+KFAC top-1
strictly greater than plain Adadelta after 5 epochs each), run on sklearn's
offline digits dataset (no network egress in CI).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import kfac_tpu
from kfac_tpu import training
from kfac_tpu.models import MLP

sklearn = pytest.importorskip('sklearn')


def _train(use_kfac: bool, epochs: int = 5) -> float:
    from examples import data

    (xtr, ytr), (xte, yte) = data.digits()
    m = MLP(features=(64,), num_classes=10)
    params = m.init(jax.random.PRNGKey(0), jnp.asarray(xtr[:8]))['params']
    reg = kfac_tpu.register_model(m, jnp.asarray(xtr[:8]))

    def loss_fn(p, ms, b):
        xx, yy = b
        logits = m.apply({'params': p}, xx)
        onehot = jax.nn.one_hot(yy, 10)
        return (
            -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1)),
            ms,
        )

    kfac = (
        kfac_tpu.KFACPreconditioner(
            registry=reg, damping=0.003, lr=0.1,
            factor_update_steps=5, inv_update_steps=25,
        )
        if use_kfac
        else None
    )
    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.adadelta(1.0), kfac=kfac
    )
    state = trainer.init(params)
    bsz = 100
    for _ in range(epochs):
        for i in range(0, len(xtr) - bsz + 1, bsz):
            state, _ = trainer.step(
                state, (jnp.asarray(xtr[i : i + bsz]), jnp.asarray(ytr[i : i + bsz]))
            )
    logits = m.apply({'params': state.params}, jnp.asarray(xte))
    return float((jnp.argmax(logits, -1) == jnp.asarray(yte)).mean())


def test_kfac_beats_first_order():
    acc_kfac = _train(True)
    acc_base = _train(False)
    assert np.isfinite(acc_kfac) and np.isfinite(acc_base)
    assert acc_kfac > acc_base, (
        f'KFAC accuracy {acc_kfac:.4f} must exceed baseline {acc_base:.4f}'
    )
