"""Tests for the pod-scale chaos harness (kfac_tpu/resilience/chaos.py).

Three tiers:

* Pure unit tests — config validation, storm schedule grammar
  (scripted + seeded), SLO reconciliation on synthetic pod records,
  report JSON, the committed-artifact loader. No processes.
* The tier-1 pod test — a REAL deterministic 4-process scripted storm:
  the conductor spawns gloo ``chaos_worker.py`` pods, delivers a
  SIGTERM wave, tears the rotation, shrinks the pod, snapshots via
  SIGUSR1, and the reconciled report must clear every SLO budget.
* A slow-marked seeded 16-process storm with a wall-clock budget.
"""

import dataclasses
import json
import os

import pytest

from kfac_tpu.resilience import chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------- config


def test_config_validation():
    with pytest.raises(ValueError, match='procs'):
        chaos.ChaosConfig(procs=1)
    with pytest.raises(ValueError, match='keep'):
        chaos.ChaosConfig(keep=1)
    with pytest.raises(ValueError, match='max_steps'):
        chaos.ChaosConfig(max_steps=0)
    with pytest.raises(ValueError, match='save_interval'):
        chaos.ChaosConfig(save_interval=0)
    with pytest.raises(ValueError, match='not both'):
        chaos.ChaosConfig(
            schedule=({'fault': 'sigterm_wave', 'at_step': 3},), seed=1
        )
    with pytest.raises(ValueError, match='unknown fault class'):
        chaos.ChaosConfig(schedule=({'fault': 'meteor', 'at_step': 3},))
    with pytest.raises(ValueError, match='fault_mix'):
        chaos.ChaosConfig(seed=1, fault_mix=('sigterm_wave', 'meteor'))


def test_scripted_storm_covers_committed_fault_classes():
    sched = chaos.resolve_schedule(chaos.ChaosConfig())
    faults = [e['fault'] for e in sched]
    # the three committed SLO fault classes plus the continue-signal path
    assert {'sigterm_wave', 'torn_checkpoint', 'shrink',
            'sigusr1'} <= set(faults)
    assert all(f in chaos.FAULT_CLASSES for f in faults)
    # kill points are ordered and leave room for the final run
    downs = [e['at_step'] for e in sched if e['fault'] != 'sigusr1']
    assert downs == sorted(downs)
    assert downs[-1] < chaos.ChaosConfig().max_steps


def test_explicit_schedule_wins_over_canonical():
    sched = ({'fault': 'sigterm_wave', 'ranks': (0,), 'at_step': 3},)
    assert chaos.resolve_schedule(
        chaos.ChaosConfig(schedule=sched)
    ) == sched


def test_seeded_storm_deterministic_and_valid():
    a = chaos.seeded_storm(chaos.ChaosConfig(seed=11, storm_events=4))
    b = chaos.seeded_storm(chaos.ChaosConfig(seed=11, storm_events=4))
    c = chaos.seeded_storm(chaos.ChaosConfig(seed=12, storm_events=4))
    assert a == b
    assert a != c
    downs = [e for e in a if e['fault'] != 'sigusr1']
    assert len(downs) == 4
    for ev in a:
        assert ev['fault'] in chaos.FAULT_CLASSES
        assert all(0 <= r < 4 for r in ev['ranks'])
        if ev['fault'] in ('shrink', 'grow'):
            assert ev['procs'] >= 2


# ---------------------------------------------------------------- reconcile


def _rec(procs, down, events, t_exit=10.0):
    r = chaos.RunRecord(procs=procs, skew=0.0, down_event=down)
    r.events = events
    r.t_exit = t_exit
    return r


def _step(rank, t, step, loss):
    return (rank, t, {'event': 'step', 'step': step, 'loss': loss})


def _start(rank, t, resumed, depth):
    return (rank, t, {
        'event': 'start', 'rank': rank, 'world': 2,
        'resumed_step': resumed, 'fallback_depth': depth,
    })


def _preempted(rank, t, saved):
    return (rank, t, {
        'event': 'preempted', 'signal': 'SIGTERM', 'saved_step': saved,
    })


_LOSSES = {1: 1.0, 2: 0.5, 3: 0.25, 4: 0.125}


def _clean_storm():
    down = {'fault': 'sigterm_wave', 'ranks': (0,), 'at_step': 2}
    runs = [{'down': down, 'snaps': ()}, {'down': None, 'snaps': ()}]
    records = [
        _rec(2, down, [_start(r, 1.0, 0, 0) for r in (0, 1)]
             + [_step(r, 2.0, s, _LOSSES[s])
                for r in (0, 1) for s in (1, 2)]
             + [_preempted(r, 3.0, 2) for r in (0, 1)]),
        _rec(2, None, [_start(r, 11.0, 2, 0) for r in (0, 1)]
             + [_step(r, 12.0, s, _LOSSES[s])
                for r in (0, 1) for s in (3, 4)]),
    ]
    control = _rec(2, None, [
        _step(r, 1.0, s, _LOSSES[s]) for r in (0, 1) for s in _LOSSES
    ])
    return runs, records, control


def test_reconcile_clean_storm_meets_budgets():
    runs, records, control = _clean_storm()
    cfg = chaos.ChaosConfig(procs=2, max_steps=4)
    report = chaos.reconcile(cfg, runs, records, control)
    assert report.ok
    assert report.blown == []
    row = report.rows['sigterm_wave']
    assert row['events'] == 1
    assert row['downtime_steps'] == 0  # resumed at the emergency step
    assert row['fallback_depth'] == 0
    assert row['max_divergence'] == 0.0
    js = report.to_json()
    assert js['ok'] is True
    json.dumps(js)  # artifact-serializable


def test_reconcile_counts_emergency_save_as_progress():
    """The boundary step's 'step' event is never emitted (Preempted
    unwinds inside trainer.step), so progress must come from the
    preempted event's saved_step — resuming AT it is zero downtime,
    resuming one rotation entry earlier is positive downtime."""
    runs, records, control = _clean_storm()
    assert records[0].progress() == 2  # saved_step, not max observed
    cfg = chaos.ChaosConfig(procs=2, max_steps=4)
    behind = [
        records[0],
        _rec(2, None, [_start(r, 11.0, 1, 1) for r in (0, 1)]
             + [_step(r, 12.0, s, _LOSSES[s])
                for r in (0, 1) for s in (2, 3, 4)]),
    ]
    report = chaos.reconcile(cfg, runs, behind, control)
    assert report.rows['sigterm_wave']['downtime_steps'] == 1


def test_reconcile_detects_divergence_and_rank_disagreement():
    runs, records, control = _clean_storm()
    cfg = chaos.ChaosConfig(procs=2, max_steps=4)
    diverged = [
        records[0],
        _rec(2, None, [_start(r, 11.0, 2, 0) for r in (0, 1)]
             + [_step(r, 12.0, s, _LOSSES[s] + 1e-3)
                for r in (0, 1) for s in (3, 4)]),
    ]
    report = chaos.reconcile(cfg, runs, diverged, control)
    assert not report.ok
    assert any('diverged' in b for b in report.blown)

    split_brain = [
        records[0],
        _rec(2, None, [_start(r, 11.0, 2, 0) for r in (0, 1)]
             + [_step(0, 12.0, 3, 0.25), _step(1, 12.0, 3, 0.26)]
             + [_step(r, 13.0, 4, _LOSSES[4]) for r in (0, 1)]),
    ]
    report2 = chaos.reconcile(cfg, runs, split_brain, control)
    assert any('disagrees' in b for b in report2.blown)


def test_reconcile_blows_budget_on_deep_fallback_and_incomplete_run():
    runs, records, control = _clean_storm()
    cfg = chaos.ChaosConfig(procs=2, max_steps=4)
    deep = [
        records[0],
        _rec(2, None, [_start(r, 11.0, 0, 3) for r in (0, 1)]
             + [_step(r, 12.0, s, _LOSSES[s])
                for r in (0, 1) for s in (1, 2, 3)]),  # never reaches 4
    ]
    report = chaos.reconcile(cfg, runs, deep, control)
    assert not report.ok
    assert any('fell back' in b for b in report.blown)
    assert any('never completed' in b for b in report.blown)


def test_reconcile_requires_torn_checkpoint_to_exercise_fallback():
    """A torn_checkpoint event whose restore did NOT fall back means the
    injected corruption was never exercised — the report must fail
    rather than certify an untested SLO."""
    runs, records, control = _clean_storm()
    runs[0]['down'] = dict(
        runs[0]['down'], fault='torn_checkpoint'
    )
    records[0].down_event = runs[0]['down']
    cfg = chaos.ChaosConfig(procs=2, max_steps=4)
    report = chaos.reconcile(cfg, runs, records, control)
    assert any('never exercised' in b for b in report.blown)


# ----------------------------------------------------------------- artifact


def test_committed_artifact_is_fresh_and_green():
    """The committed SLO artifact (kfac_tpu/resilience/chaos_slo.json)
    covers the three required fault classes, met every budget, and its
    knob snapshot matches the current ChaosConfig defaults (regenerate
    with ``python tools/kfac_chaos.py --out ...`` after changing
    either)."""
    artifact = chaos.load_slo_artifact()
    assert artifact is not None, (
        f'missing committed artifact {chaos.ARTIFACT_PATH}; generate with '
        'python tools/kfac_chaos.py --out kfac_tpu/resilience/chaos_slo.json'
    )
    assert artifact['ok'] is True
    assert artifact['blown'] == []
    rows = artifact['rows']
    for fault in ('sigterm_wave', 'torn_checkpoint', 'shrink'):
        assert fault in rows, f'artifact lacks SLO row for {fault!r}'
        assert rows[fault]['events'] >= 1
    # torn restore actually walked the rotation; clean wave did not
    assert rows['torn_checkpoint']['fallback_depth'] >= 1
    assert rows['sigterm_wave']['fallback_depth'] == 0
    assert rows['sigterm_wave']['max_divergence'] == 0.0
    cfg = artifact['config']
    defaults = dataclasses.asdict(chaos.ChaosConfig())
    stale = {
        k for k in defaults
        if k in cfg and json.loads(json.dumps(defaults[k])) != cfg[k]
    }
    assert not stale, (
        f'artifact config drifted from ChaosConfig defaults on {sorted(stale)}'
    )


def test_load_slo_artifact_tolerates_absence(tmp_path):
    assert chaos.load_slo_artifact(str(tmp_path / 'nope.json')) is None
    bad = tmp_path / 'bad.json'
    bad.write_text('{"not": "an artifact"}')
    assert chaos.load_slo_artifact(str(bad)) is None
    bad.write_text('not json at all')
    assert chaos.load_slo_artifact(str(bad)) is None


def test_bench_chaos_probe_folds_artifact():
    import bench

    probe = bench._chaos_probe()
    assert probe['status'] == 'ok'
    assert {'sigterm_wave', 'torn_checkpoint', 'shrink'} <= set(
        probe['rows']
    )
    assert probe['blown'] == []


def test_chaos_cli_selftest():
    import subprocess
    import sys

    res = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'kfac_chaos.py'),
         '--selftest'],
        capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert 'chaos selftest ok' in res.stdout


# ------------------------------------------------------------- faults (unit)


def test_newest_step_dir_and_disk_faults(tmp_path):
    cond = chaos.ChaosConductor(
        chaos.ChaosConfig(), root=str(tmp_path / 'root')
    )
    ckpt = tmp_path / 'rot'
    assert cond._newest_step_dir(str(ckpt)) is None
    for step in (2, 10):
        d = ckpt / f'step_{step:08d}'
        d.mkdir(parents=True)
        (d / 'payload.bin').write_bytes(b'x' * 64)
    (ckpt / 'garbage').mkdir()
    (ckpt / 'LATEST').write_text('step_00000010')
    assert cond._newest_step_dir(str(ckpt)) == str(ckpt / 'step_00000010')

    victims = cond._apply_disk_fault(str(ckpt), 'torn_checkpoint')
    assert str(ckpt / 'LATEST') in victims
    assert any('step_00000010' in v for v in victims)
    # torn pointer: garbage bytes, and the newest payload got truncated
    assert (ckpt / 'LATEST').read_bytes() != b'step_00000010'
    assert (ckpt / 'step_00000010' / 'payload.bin').stat().st_size < 64

    victims2 = cond._apply_disk_fault(str(ckpt), 'corrupt_payload')
    assert victims2
    with pytest.raises(chaos.ChaosError, match='no step dir'):
        cond._apply_disk_fault(str(tmp_path / 'empty'), 'corrupt_payload')


# ------------------------------------------------------------ real pod storms


def test_scripted_storm_4proc_meets_slos(tmp_path):
    """THE tier-1 chaos test: a real 4-process gloo pod rides the
    canonical scripted storm — SIGTERM wave, torn checkpoint (LATEST +
    payload), topology shrink to 2, in-flight SIGUSR1 snapshot — and
    every recovery SLO budget must hold, with the storm trajectory
    bit-identical to control on same-world runs."""
    config = chaos.ChaosConfig(procs=4, max_steps=8)
    conductor = chaos.ChaosConductor(config, root=str(tmp_path))
    report = conductor.run()  # raises ChaosError with the report on blow
    assert report.ok
    faults = {f['fault'] for f in report.faults_applied}
    assert {'sigterm_wave', 'torn_checkpoint', 'shrink'} <= faults
    assert report.rows['torn_checkpoint']['fallback_depth'] >= 1
    assert report.rows['sigterm_wave']['max_divergence'] == 0.0
    assert report.rows['sigusr1']['events'] >= 1
    # the shrink run really ran elastic: world changed mid-trajectory
    assert any(r['world_changed'] for r in report.runs)
    json.dumps(report.to_json())


@pytest.mark.slow
def test_seeded_storm_16proc(tmp_path):
    """Pod-scale seeded storm: 16 gloo processes, randomized fault
    draw (deterministic per seed), wall-clock budgeted — each pod run
    is bounded by ``phase_timeout_s`` (the conductor kills a wedged pod
    and fails), and the whole storm must clear an end-to-end budget.
    The report must reconcile green: whatever the seed drew, the stack
    healed."""
    import time

    budget_s = 1800.0
    config = chaos.ChaosConfig(
        procs=16, max_steps=8, seed=1337, storm_events=2,
        phase_timeout_s=600.0,
    )
    conductor = chaos.ChaosConductor(config, root=str(tmp_path))
    t0 = time.monotonic()
    report = conductor.run()
    wall = time.monotonic() - t0
    assert report.ok
    assert wall < budget_s, (
        f'16-proc seeded storm took {wall:.0f}s > {budget_s:.0f}s budget'
    )
    assert sum(
        row['events'] for f, row in report.rows.items() if f != 'sigusr1'
    ) == 2


# ----------------------------------------------------------- lint rule


def test_kfl111_chaos_knobs_doc_in_sync():
    from kfac_tpu.analysis import drift

    assert drift.check_chaos_knobs() == []


def test_kfl111_detects_doc_drift(tmp_path):
    from kfac_tpu.analysis import drift

    doc = tmp_path / 'ROBUSTNESS.md'
    rows = ''.join(
        f'| `{f.name}` | x | x |\n'
        for f in dataclasses.fields(chaos.ChaosConfig)
        if f.name != 'procs'
    )
    doc.write_text(
        '### Chaos knobs\n\n| knob | default | meaning |\n|---|---|---|\n'
        + rows + '| `phantom_knob` | x | x |\n'
    )
    problems = drift.check_chaos_knobs(str(doc))
    assert any('procs' in p and 'undocumented' in p for p in problems)
    assert any('phantom_knob' in p and 'not a ChaosConfig' in p
               for p in problems)
