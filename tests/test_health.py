"""Numerical-health sentinel fault suite (deterministic injection).

End-to-end proof of the three mechanisms in kfac_tpu/health.py — skip-step,
per-layer factor quarantine, graceful degradation to first-order updates —
driven by the injectors in testing/faults.py, under both the dense engine
and the stacked distributed engine (both stat transports). Run with
``make faults`` / ``pytest -m faults``.
"""

import warnings as py_warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import kfac_tpu
from kfac_tpu import checkpoint, enums, tracing, training
from kfac_tpu import health as health_lib
from kfac_tpu import warnings as kfac_warnings
from testing import faults, models

pytestmark = pytest.mark.faults


def _setup(**kw):
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=32, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    loss_fn = models.mse_loss(m)
    kw.setdefault('health', health_lib.HealthConfig(warn=False))
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, **kw)
    return m, params, (x, y), reg, loss_fn, kfac


def _capture(reg, loss_fn, params, batch):
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    return grads, stats


def _trainer(m, loss_fn2, kfac, lr=0.05):
    return training.Trainer(
        loss_fn=loss_fn2, optimizer=optax.sgd(lr), kfac=kfac
    )


def _trainer_loss(m):
    def loss_fn(params, model_state, batch):
        x, y = batch
        pred = m.apply({'params': params}, x)
        return jnp.mean((pred - y) ** 2), model_state

    return loss_fn


# ------------------------------------------------------------------ config


def test_health_config_validation():
    with pytest.raises(ValueError):
        health_lib.HealthConfig(damping_escalation=0.5)
    with pytest.raises(ValueError):
        health_lib.HealthConfig(damping_decay=1.5)
    with pytest.raises(ValueError):
        health_lib.HealthConfig(degrade_after=0)
    with pytest.raises(ValueError):
        health_lib.HealthConfig(quarantine_threshold=0.5)
    with pytest.raises(TypeError):
        kfac_tpu.KFACPreconditioner(
            registry=_setup()[3], health='yes'
        )


def test_health_disabled_is_reference_semantics():
    """health=None: zero health state, and a poisoned batch poisons the
    params (the reference's behavior the sentinel exists to prevent)."""
    m, params, batch, reg, loss_fn, kfac = _setup(health=None)
    state = kfac.init()
    assert state.health is None
    assert tracing.health_counters(state) == {}

    trainer = _trainer(m, _trainer_loss(m), kfac)
    tstate = trainer.init(params)
    tstate, loss = trainer.step(tstate, faults.poison_batch(batch))
    assert not bool(jnp.isfinite(loss))
    kernel = tstate.params['fc1']['kernel']
    assert not bool(jnp.isfinite(kernel).all())


# --------------------------------------------------------------- skip-step


def test_skip_step_eager_then_recovers():
    m, params, batch, reg, loss_fn, kfac = _setup()
    trainer = _trainer(m, _trainer_loss(m), kfac)
    tstate = trainer.init(params)

    bad = faults.poison_batch(batch, kind='nan')
    t1, loss = trainer.step(tstate, bad)
    assert not bool(jnp.isfinite(loss))
    # the whole update was dropped: params bitwise unchanged
    np.testing.assert_array_equal(
        np.asarray(t1.params['fc1']['kernel']),
        np.asarray(tstate.params['fc1']['kernel']),
    )
    assert int(t1.kfac_state.health.skipped_steps) == 1
    # the clock still advanced (schedules/cadence stay aligned)
    assert int(t1.kfac_state.step) == 1

    # next healthy batch applies normally
    t2, loss2 = trainer.step(t1, batch)
    assert bool(jnp.isfinite(loss2))
    assert int(t2.kfac_state.health.skipped_steps) == 1
    assert (
        float(
            jnp.abs(
                t2.params['fc1']['kernel'] - t1.params['fc1']['kernel']
            ).max()
        )
        > 0
    )


@pytest.mark.parametrize('kind', ['inf', '-inf'])
def test_skip_step_catches_infs_too(kind):
    m, params, batch, reg, loss_fn, kfac = _setup()
    trainer = _trainer(m, _trainer_loss(m), kfac)
    tstate = trainer.init(params)
    t1, _ = trainer.step(tstate, faults.poison_batch(batch, kind=kind))
    assert int(t1.kfac_state.health.skipped_steps) == 1
    np.testing.assert_array_equal(
        np.asarray(t1.params['fc2']['kernel']),
        np.asarray(tstate.params['fc2']['kernel']),
    )


def test_skip_step_accumulate_eager():
    """One poisoned micro-batch drops the whole accumulated step."""
    m, params, (x, y), reg, loss_fn, kfac = _setup()
    trainer = _trainer(m, _trainer_loss(m), kfac)
    tstate = trainer.init(params)
    mbs = [(x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8]) for i in range(4)]
    mbs[2] = faults.poison_batch(mbs[2])
    t1, loss = trainer.step_accumulate(tstate, mbs)
    assert int(t1.kfac_state.health.skipped_steps) == 1
    np.testing.assert_array_equal(
        np.asarray(t1.params['fc1']['kernel']),
        np.asarray(tstate.params['fc1']['kernel']),
    )
    # healthy accumulation afterwards applies
    good = [(x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8]) for i in range(4)]
    t2, loss2 = trainer.step_accumulate(t1, good)
    assert bool(jnp.isfinite(loss2))
    assert int(t2.kfac_state.health.skipped_steps) == 1
    assert (
        float(
            jnp.abs(
                t2.params['fc1']['kernel'] - t1.params['fc1']['kernel']
            ).max()
        )
        > 0
    )


def test_skip_step_accumulate_scan():
    m, params, (x, y), reg, loss_fn, kfac = _setup()
    trainer = _trainer(m, _trainer_loss(m), kfac)
    tstate = trainer.init(params)
    mbs = (x.reshape(4, 8, -1), y.reshape(4, 8, -1))
    bad = faults.poison_microbatch(mbs, which=1)
    t1, loss = trainer.step_accumulate_scan(tstate, bad)
    assert int(t1.kfac_state.health.skipped_steps) == 1
    np.testing.assert_array_equal(
        np.asarray(t1.params['fc1']['kernel']),
        np.asarray(tstate.params['fc1']['kernel']),
    )
    t2, loss2 = trainer.step_accumulate_scan(t1, mbs)
    assert bool(jnp.isfinite(loss2))
    assert int(t2.kfac_state.health.skipped_steps) == 1


def test_skip_step_inside_scan_steps():
    """A poisoned batch in the middle of a compiled lax.scan loop is
    skipped on-device; the surrounding steps train normally."""
    m, params, (x, y), reg, loss_fn, kfac = _setup()
    trainer = _trainer(m, _trainer_loss(m), kfac)
    tstate = trainer.init(params)
    batches = (
        jnp.stack([x, x, x]),
        jnp.stack([y, y, y]),
    )
    batches = faults.poison_microbatch(batches, which=1)
    t1, losses = trainer.scan_steps(tstate, batches)
    assert int(t1.kfac_state.health.skipped_steps) == 1
    assert int(t1.kfac_state.step) == 3
    assert bool(jnp.isfinite(losses[0])) and bool(jnp.isfinite(losses[2]))
    assert not bool(jnp.isfinite(losses[1]))
    # params stayed finite through the poisoned step
    assert bool(jnp.isfinite(t1.params['fc1']['kernel']).all())


# -------------------------------------------------------- factor quarantine


def test_quarantine_rollback_escalation_decay_dense():
    m, params, batch, reg, loss_fn, kfac = _setup()
    grads, stats = _capture(reg, loss_fn, params, batch)
    state = kfac.init()
    state = kfac.update_factors(state, stats)  # healthy baseline
    a_before = np.asarray(state.a['fc1'])

    bad = faults.poison_stats(stats, 'fc1', side='a', kind='nan')
    s1 = kfac.update_factors(state, bad)
    # fc1 rolled back to the previous factor, fc2 advanced on good stats
    np.testing.assert_array_equal(np.asarray(s1.a['fc1']), a_before)
    assert (
        float(jnp.abs(s1.a['fc2'] - state.a['fc2']).max()) > 0
    )
    assert int(s1.health.quarantined['fc1']) == 1
    assert int(s1.health.quarantine_events['fc1']) == 1
    assert float(s1.health.damping_mult['fc1']) == pytest.approx(10.0)
    assert int(s1.health.quarantined['fc2']) == 0
    assert float(s1.health.damping_mult['fc2']) == pytest.approx(1.0)

    # healthy update: consecutive counter resets, multiplier decays,
    # cumulative event counter is monotone
    s2 = kfac.update_factors(s1, stats)
    assert int(s2.health.quarantined['fc1']) == 0
    assert float(s2.health.damping_mult['fc1']) == pytest.approx(5.0)
    assert int(s2.health.quarantine_events['fc1']) == 1
    assert bool(jnp.isfinite(s2.a['fc1']).all())


def test_quarantine_on_gershgorin_bound_blowup():
    """A FINITE factor blow-up past the conditioning bound quarantines —
    the fp32 inverse of a kappa~1e30 factor is garbage even when finite."""
    m, params, batch, reg, loss_fn, kfac = _setup(damping=0.01)
    _, stats = _capture(reg, loss_fn, params, batch)
    state = kfac.init()
    huge = faults.huge_stats(stats, 'fc1', scale=1e30, side='a')
    s1 = kfac.update_factors(state, huge)
    assert int(s1.health.quarantined['fc1']) == 1
    np.testing.assert_array_equal(
        np.asarray(s1.a['fc1']), np.eye(s1.a['fc1'].shape[0])
    )
    # with the conditioning check disabled, the same finite blow-up passes
    kfac2 = kfac_tpu.KFACPreconditioner(
        registry=reg,
        damping=0.01,
        health=health_lib.HealthConfig(quarantine_threshold=None, warn=False),
    )
    s2 = kfac2.update_factors(kfac2.init(), huge)
    assert int(s2.health.quarantined['fc1']) == 0


# ------------------------------------------------------ graceful degradation


@pytest.mark.parametrize(
    'method', [enums.ComputeMethod.EIGEN, enums.ComputeMethod.INVERSE]
)
def test_degradation_bypass_and_recovery_dense(method):
    m, params, batch, reg, loss_fn, kfac = _setup(
        compute_method=method,
        kl_clip=None,
        damping=0.01,
        health=health_lib.HealthConfig(degrade_after=1, warn=False),
    )
    grads, stats = _capture(reg, loss_fn, params, batch)
    state = kfac.init()
    state = kfac.update_factors(state, stats)

    # poisoned stats -> quarantined factor -> quarantined inversion
    bad = faults.poison_stats(stats, 'fc1', side='g', kind='nan')
    s1 = kfac.update_factors(state, bad)
    s1 = kfac.update_inverses(s1)
    assert int(s1.health.bad_inv['fc1']) == 1
    pg = kfac.precondition(s1, grads)
    # degraded layer: raw gradient passes through exactly
    np.testing.assert_allclose(
        np.asarray(pg['fc1']['kernel']),
        np.asarray(grads['fc1']['kernel']),
        rtol=1e-6,
        atol=0,
    )
    # healthy layer is still genuinely preconditioned
    assert (
        float(jnp.abs(pg['fc2']['kernel'] - grads['fc2']['kernel']).max()) > 0
    )

    # recovery: healthy factor update + healthy inversion clears the counter
    s2 = kfac.update_factors(s1, stats)
    s2 = kfac.update_inverses(s2)
    assert int(s2.health.bad_inv['fc1']) == 0
    pg2 = kfac.precondition(s2, grads)
    assert (
        float(jnp.abs(pg2['fc1']['kernel'] - grads['fc1']['kernel']).max())
        > 0
    )


def test_degraded_training_still_decreases_loss():
    """With fc1 permanently degraded (poisoned stats every step), training
    continues partially-first-order and the loss still goes down."""
    m, params, batch, reg, loss_fn, kfac = _setup(
        kl_clip=None,
        damping=0.01,
        lr=0.05,
        health=health_lib.HealthConfig(degrade_after=1, warn=False),
    )
    state = kfac.init()
    losses = []
    step = jax.jit(kfac.step)
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(loss_fn)
    for _ in range(10):
        (loss, _), grads, stats = run(params, batch)
        losses.append(float(loss))
        bad = faults.poison_stats(stats, 'fc1', side='a', kind='nan')
        state, pg = step(state, grads, bad)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 0.05 * g, params, pg
        )
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    snap = health_lib.summary(kfac.health, state.health)
    assert snap['layers']['fc1']['status'] == 'degraded'
    assert snap['layers']['fc2']['status'] == 'ok'


def test_factors_poisoned_at_rest_degrade_at_next_refresh():
    """Corruption of resident factors (bad restore, bit flip) is caught by
    the inversion-time verdict even with no stats traffic at all."""
    m, params, batch, reg, loss_fn, kfac = _setup(
        kl_clip=None,
        health=health_lib.HealthConfig(degrade_after=1, warn=False),
    )
    grads, stats = _capture(reg, loss_fn, params, batch)
    state = kfac.update_factors(kfac.init(), stats)
    state = faults.poison_factors(kfac, state, 'fc2', side='a', kind='nan')
    s1 = kfac.update_inverses(state)
    assert int(s1.health.bad_inv['fc2']) == 1
    pg = kfac.precondition(s1, grads)
    np.testing.assert_allclose(
        np.asarray(pg['fc2']['kernel']),
        np.asarray(grads['fc2']['kernel']),
        rtol=1e-6,
        atol=0,
    )
    assert bool(jnp.isfinite(pg['fc1']['kernel']).all())


# ------------------------------------------------------- distributed engine

WORLD = 8


def _dist_setup(transport, frac=1.0, **cfg_kw):
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    mesh = kaisa_mesh(grad_worker_fraction=frac)
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=WORLD * 8, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg_kw.setdefault('health', health_lib.HealthConfig(warn=False))
    cfg = kfac_tpu.KFACPreconditioner(
        registry=reg, allreduce_method=transport, **cfg_kw
    )
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    loss_fn = models.mse_loss(m)
    return m, params, (x, y), reg, cfg, dk, loss_fn


@pytest.mark.slow
@pytest.mark.parametrize(
    'transport',
    [enums.AllreduceMethod.ALLREDUCE, enums.AllreduceMethod.ALLREDUCE_BUCKETED],
)
def test_stacked_quarantine_rollback(transport):
    m, params, batch, reg, cfg, dk, loss_fn = _dist_setup(transport)
    grads, stats = _capture(reg, loss_fn, params, batch)
    state = dk.init()
    state = jax.jit(dk.update_factors)(state, stats)
    a_before = np.asarray(dk.extract_factors(state)['fc1']['a'])

    bad = faults.poison_stats(stats, 'fc1', side='a', kind='nan')
    s1 = jax.jit(dk.update_factors)(state, bad)
    np.testing.assert_array_equal(
        np.asarray(dk.extract_factors(s1)['fc1']['a']), a_before
    )
    assert int(s1.health.quarantined['fc1']) == 1
    assert float(s1.health.damping_mult['fc1']) == pytest.approx(10.0)
    assert int(s1.health.quarantined['fc2']) == 0
    # fc2's EMA legitimately advanced on its good stats
    assert bool(jnp.isfinite(dk.extract_factors(s1)['fc2']['a']).all())

    s2 = jax.jit(dk.update_factors)(s1, stats)
    assert int(s2.health.quarantined['fc1']) == 0
    assert float(s2.health.damping_mult['fc1']) == pytest.approx(5.0)
    assert int(s2.health.quarantine_events['fc1']) == 1


@pytest.mark.slow
@pytest.mark.parametrize('frac', [1.0, 0.5])
def test_stacked_degradation_bypass(frac):
    m, params, batch, reg, cfg, dk, loss_fn = _dist_setup(
        enums.AllreduceMethod.ALLREDUCE,
        frac=frac,
        kl_clip=None,
        damping=0.01,
        health=health_lib.HealthConfig(degrade_after=1, warn=False),
    )
    grads, stats = _capture(reg, loss_fn, params, batch)
    state = dk.init()
    state = jax.jit(dk.update_factors)(state, stats)
    bad = faults.poison_stats(stats, 'fc1', side='g', kind='nan')
    s1 = jax.jit(dk.update_factors)(state, bad)
    s1 = jax.jit(dk.update_inverses)(s1)
    assert int(s1.health.bad_inv['fc1']) == 1
    pg = jax.jit(dk.precondition)(s1, grads)
    np.testing.assert_allclose(
        np.asarray(pg['fc1']['kernel']),
        np.asarray(grads['fc1']['kernel']),
        rtol=1e-5,
        atol=1e-7,
    )
    assert (
        float(jnp.abs(pg['fc2']['kernel'] - grads['fc2']['kernel']).max()) > 0
    )

    s2 = jax.jit(dk.update_factors)(s1, stats)
    s2 = jax.jit(dk.update_inverses)(s2)
    assert int(s2.health.bad_inv['fc1']) == 0
    pg2 = jax.jit(dk.precondition)(s2, grads)
    assert (
        float(jnp.abs(pg2['fc1']['kernel'] - grads['fc1']['kernel']).max())
        > 0
    )


# ---------------------------------------------------- tracing / checkpoint


def test_health_counters_snapshot():
    m, params, batch, reg, loss_fn, kfac = _setup()
    _, stats = _capture(reg, loss_fn, params, batch)
    state = kfac.update_factors(
        kfac.init(), faults.poison_stats(stats, 'fc1', side='a')
    )
    counters = tracing.health_counters(state)
    assert counters['health/skipped_steps'] == 0
    assert counters['health/fc1/quarantined'] == 1
    assert counters['health/fc1/damping_mult'] == pytest.approx(10.0)
    assert counters['health/fc2/quarantined'] == 0


def test_checkpoint_health_roundtrip(tmp_path):
    m, params, batch, reg, loss_fn, kfac = _setup()
    grads, stats = _capture(reg, loss_fn, params, batch)
    state = kfac.update_factors(kfac.init(), stats)
    state = kfac.update_factors(
        state, faults.poison_stats(stats, 'fc1', side='a')
    )
    state = health_lib.mark_skipped(state)
    state = health_lib.mark_skipped(state)

    path = str(tmp_path / 'ckpt')
    checkpoint.save(path, state, engine=kfac)
    restored, _ = checkpoint.restore(path, kfac)
    assert int(restored.health.skipped_steps) == 2
    assert int(restored.health.quarantined['fc1']) == 1
    assert int(restored.health.quarantine_events['fc1']) == 1
    assert float(restored.health.damping_mult['fc1']) == pytest.approx(10.0)
    assert int(restored.health.quarantined['fc2']) == 0


def test_restore_rejects_nonfinite_factors(tmp_path):
    m, params, batch, reg, loss_fn, kfac = _setup()
    _, stats = _capture(reg, loss_fn, params, batch)
    state = kfac.update_factors(kfac.init(), stats)
    state = faults.poison_factors(kfac, state, 'fc1', side='a', kind='nan')
    path = str(tmp_path / 'ckpt')
    checkpoint.save(path, state, engine=kfac)
    with pytest.raises(ValueError, match='fc1'):
        checkpoint.restore(path, kfac)


# ------------------------------------------------------------------ warnings


def test_health_warnings_fire_once():
    kfac_warnings.reset_health_warnings()
    m, params, batch, reg, loss_fn, _ = _setup()
    _, stats = _capture(reg, loss_fn, params, batch)
    cfg = health_lib.HealthConfig()  # warn=True defaults
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, health=cfg)
    state = kfac.update_factors(
        kfac.init(), faults.poison_stats(stats, 'fc1', side='a')
    )
    with pytest.warns(kfac_warnings.NumericalHealthWarning, match='fc1'):
        snap = health_lib.check_and_warn(cfg, state.health, step=1)
    assert snap['layers']['fc1']['status'] == 'quarantined'
    # second scan of the same condition is rate-limited: silent
    with py_warnings.catch_warnings(record=True) as caught:
        py_warnings.simplefilter('always')
        health_lib.check_and_warn(cfg, state.health, step=2)
    assert not [
        w
        for w in caught
        if issubclass(w.category, kfac_warnings.NumericalHealthWarning)
    ]
    kfac_warnings.reset_health_warnings()
