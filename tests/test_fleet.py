"""Self-driving fleet controller tests on the 8-virtual-device CPU mesh.

Covers the ISSUE acceptance surface end to end, entirely on CPU:

- retune-on-restore: train, doctor the persisted plan's fingerprint
  with ``testing.faults.change_topology`` (the "restored onto a resized
  pod" fault), and assert the fresh controller re-runs the cost-model
  fast path, lands on the NEWLY tuned layout (not the canonical
  defaults, not the stale plan), restores elastically, and continues
  with loss continuity against the uninterrupted run;
- drift-triggered live migration: a skew-injecting drain
  (``testing.faults.skewed_drain``) arms a retune whose migration
  executes at the next checkpoint boundary with bit-identical params
  versus a calm control run, plus the abort-and-rollback path when the
  pod-wide agreement vote fails;
- the unit surface: FleetConfig validation, retune retry/backoff,
  canonical fallbacks (permanent retune failure, tuned-restore
  failure), the Trainer constructor guards, and the deterministic
  fault injectors themselves.

The tuned-vs-default distinction is driven through the cost model's
public HBM budget: ``HardwareSpec(hbm_bytes=...)`` sized between the
MEM-OPT and COMM-OPT footprints makes every fraction-1.0 candidate
infeasible, so the model-only retune MUST move off the canonical
COMM-OPT layout — no monkeypatching of the search involved.
"""

import json
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import kfac_tpu
from kfac_tpu.autotune import model as model_lib
from kfac_tpu.autotune import search as search_lib
from kfac_tpu.enums import DistributedStrategy
from kfac_tpu.parallel import multihost
from kfac_tpu.resilience import CheckpointManager, fleet as fleet_lib
from kfac_tpu.warnings import (
    FleetWarning,
    reset_fleet_warnings,
    reset_layout_warnings,
)
from testing import faults, models

WORLD = 8

#: sized between the MEM-OPT (~4.7 kB) and COMM-OPT (~11.4 kB) per-device
#: footprints of the TinyModel factor state, so fraction-1.0 candidates
#: are infeasible and the model-only retune must leave the canonical
#: COMM-OPT layout
TIGHT_HBM = model_lib.HardwareSpec(hbm_bytes=8000.0)


@pytest.fixture(autouse=True)
def _clean_warning_state():
    reset_fleet_warnings()
    reset_layout_warnings()
    yield
    reset_fleet_warnings()
    reset_layout_warnings()


def _setup():
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(p, model_state, batch):
        bx, by = batch
        pred = m.apply({'params': p}, bx)
        return jnp.mean((pred - by) ** 2), model_state

    def bare():
        return kfac_tpu.KFACPreconditioner(
            registry=reg, kl_clip=None, damping=1e-3, flight=8
        )

    return m, (x, y), params, bare, loss_fn


def _fast_config(**kw):
    base = dict(
        check_every=2, drift_keys=('grad_norm',), drift_threshold=0.5,
        drift_window=2, drift_patience=1, cooldown_steps=1,
    )
    base.update(kw)
    return kfac_tpu.FleetConfig(**base)


def _make_fleet(directory, bare, loss_fn, *, ratio=0.0, hardware=None,
                plan=None, config=None, save_interval_steps=4):
    mgr = CheckpointManager(
        directory, save_interval_steps=save_interval_steps, keep=3,
        install_signals=(), async_save=False,
    )
    ctrl = kfac_tpu.FleetController(
        mgr,
        config if config is not None else _fast_config(),
        plan=plan,
        hardware=hardware if hardware is not None else TIGHT_HBM,
        drain=faults.skewed_drain('grad_norm', ratio),
    )
    trainer = kfac_tpu.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=bare(), fleet=ctrl,
    )
    return trainer, mgr, ctrl


def _comm_opt_plan(bare):
    """A genuinely tuned plan pinned to the canonical COMM-OPT layout —
    the 'stale' starting point the fleet must move away from."""
    return search_lib.autotune(
        bare(), measure=False, world=WORLD,
        fractions=(1.0,), granularities=(1,),
    )


# ------------------------------------------------------------ config surface


def test_fleet_config_validation():
    assert kfac_tpu.FleetConfig().check_every == 16
    # list drift_keys normalize to a tuple (hashable, lint-friendly)
    assert kfac_tpu.FleetConfig(drift_keys=['loss']).drift_keys == ('loss',)
    for bad in (
        dict(check_every=0), dict(drift_keys=()), dict(drift_threshold=0.0),
        dict(drift_window=0), dict(drift_patience=0),
        dict(cooldown_steps=-1), dict(retune_max_retries=-1),
        dict(retune_backoff_base=0.0), dict(retune_backoff_max=0.0),
    ):
        with pytest.raises(ValueError):
            kfac_tpu.FleetConfig(**bad)


def test_controller_rejects_unknown_search_overrides(tmp_path):
    mgr = CheckpointManager(tmp_path, install_signals=(), async_save=False)
    with pytest.raises(ValueError, match='unknown search_overrides'):
        kfac_tpu.FleetController(mgr, search_overrides={'granularity': (1,)})


def test_attach_rejects_built_engine(tmp_path):
    _, _, _, bare, _ = _setup()
    from kfac_tpu.parallel import DistributedKFAC

    mgr = CheckpointManager(tmp_path, install_signals=(), async_save=False)
    ctrl = kfac_tpu.FleetController(mgr)
    with pytest.raises(ValueError, match='bare KFACPreconditioner'):
        ctrl.attach(DistributedKFAC(config=bare()))


def test_trainer_fleet_constructor_guards(tmp_path):
    _, _, _, bare, loss_fn = _setup()
    from kfac_tpu.parallel import DistributedKFAC

    mgr = CheckpointManager(tmp_path, install_signals=(), async_save=False)
    ctrl = kfac_tpu.FleetController(mgr)
    with pytest.raises(ValueError, match='excludes auto_layout'):
        kfac_tpu.Trainer(
            loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=bare(),
            fleet=ctrl, auto_layout={'schema': 1},
        )
    with pytest.raises(ValueError, match='bare'):
        kfac_tpu.Trainer(
            loss_fn=loss_fn, optimizer=optax.sgd(0.05),
            kfac=DistributedKFAC(config=bare()), fleet=ctrl,
        )
    other = CheckpointManager(
        tmp_path / 'other', install_signals=(), async_save=False
    )
    with pytest.raises(ValueError, match='fleet controller'):
        kfac_tpu.Trainer(
            loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=bare(),
            fleet=ctrl, checkpoints=other,
        )


# ------------------------------------------------------------ retune-on-path


def test_retune_retry_backoff_then_success(tmp_path, monkeypatch):
    _, _, _, bare, _ = _setup()
    mgr = CheckpointManager(tmp_path, install_signals=(), async_save=False)
    delays = []
    ctrl = kfac_tpu.FleetController(
        mgr, kfac_tpu.FleetConfig(retune_max_retries=3),
        hardware=TIGHT_HBM, sleep=delays.append,
    )
    real = search_lib.autotune
    calls = {'n': 0}

    def flaky(*a, **kw):
        calls['n'] += 1
        if calls['n'] <= 2:
            raise OSError('transient search scratch failure')
        return real(*a, **kw)

    monkeypatch.setattr(fleet_lib.search_lib, 'autotune', flaky)
    engine = ctrl.attach(bare())
    # two failures -> two exponential backoffs, then the tuned engine
    assert delays == [0.5, 1.0]
    assert calls['n'] == 3
    assert ctrl.plan is not None
    assert ctrl.plan.meta['retune_reason'] == 'startup'
    assert ctrl.plan.meta['fleet'] is True
    assert ctrl.stats['retunes'] == 1
    assert ctrl.stats['retune_s'] is not None
    assert engine is ctrl.engine is mgr.engine


def test_retune_permanent_failure_falls_back_to_canonical(
    tmp_path, monkeypatch
):
    _, _, _, bare, _ = _setup()
    mgr = CheckpointManager(tmp_path, install_signals=(), async_save=False)
    ctrl = kfac_tpu.FleetController(
        mgr, kfac_tpu.FleetConfig(retune_max_retries=1),
        sleep=lambda s: None,
    )

    def broken(*a, **kw):
        raise OSError('no scratch space')

    monkeypatch.setattr(fleet_lib.search_lib, 'autotune', broken)
    with pytest.warns(FleetWarning, match='retune-failed'):
        engine = ctrl.attach(bare())
    # the job still comes up, on the canonical COMM-OPT layout
    assert ctrl.plan is None
    assert engine.grad_workers == WORLD
    assert [e['event'] for e in ctrl.events] == ['retune-failed']
    assert ctrl.stats['retunes'] == 0


def test_unreadable_plan_warns_and_retunes(tmp_path):
    _, _, _, bare, _ = _setup()
    mgr = CheckpointManager(tmp_path, install_signals=(), async_save=False)
    plan_path = os.path.join(mgr.directory, fleet_lib.PLAN_FILENAME)
    with open(plan_path, 'w') as f:
        f.write('{"schema": 999, "corrupt')
    ctrl = kfac_tpu.FleetController(mgr, hardware=TIGHT_HBM)
    with pytest.warns(FleetWarning, match='plan-unreadable'):
        ctrl.attach(bare())
    assert ctrl.plan is not None
    assert ctrl.plan.meta['retune_reason'] == 'startup'
    # the fresh plan overwrote the corrupt artifact
    assert json.load(open(plan_path))['schema'] == ctrl.plan.schema


def test_fleet_warnings_rate_limited_per_cause():
    assert kfac_tpu.warnings.warn_fleet_event('x-cause', 'one') is True
    assert kfac_tpu.warnings.warn_fleet_event('x-cause', 'two') is False
    reset_fleet_warnings()
    assert kfac_tpu.warnings.warn_fleet_event('x-cause', 'three') is True


def test_agree_decision_single_process():
    assert multihost.agree_decision(True) is True
    assert multihost.agree_decision(False) is False


# ------------------------------------------------------- fault injectors


def test_change_topology_doctors_fingerprint_only(tmp_path):
    _, _, _, bare, _ = _setup()
    plan = _comm_opt_plan(bare)
    doctored = faults.change_topology(plan)
    # default fault: the pod doubled
    assert doctored.fingerprint['device_count'] == 2 * WORLD
    # knobs/cost table untouched, input unmutated
    assert doctored.knobs == plan.knobs
    assert plan.fingerprint['device_count'] == WORLD
    # path form round-trips through disk
    path = str(tmp_path / 'p.json')
    plan.save(path)
    back = faults.change_topology(path, process_count=4, backend='tpu')
    again = type(plan).load(path)
    assert again.fingerprint == back.fingerprint
    assert back.fingerprint['process_count'] == 4
    assert back.fingerprint['backend'] == 'tpu'


def test_induce_skew_exact_ratio_and_unmutated_input():
    from kfac_tpu.observability import flight_recorder as flight_lib

    records = [
        {'step': 1, 'grad_norm': 2.0},
        {'step': 2, 'grad_norm': -4.0, 'skew_mean/grad_norm': -4.0},
        {'step': 3, 'loss': 1.0},  # no grad_norm: untouched
    ]
    out = faults.induce_skew(records, key='grad_norm', ratio=2.0)
    assert 'skew_min/grad_norm' not in records[0]
    for rec in out[:2]:
        assert flight_lib.skew_ratio(rec, 'grad_norm') == pytest.approx(2.0)
    assert out[2] == records[2]
    # skew_ratio needs all three columns
    assert flight_lib.skew_ratio(records[0], 'grad_norm') == 0.0


# ------------------------------------------- acceptance: retune-on-restore


def test_topology_change_retunes_on_restore_with_loss_continuity(tmp_path):
    m, batch, params, bare, loss_fn = _setup()
    # phase 1: train under the tuned COMM-OPT plan, periodic saves
    trainer, mgr, ctrl = _make_fleet(
        tmp_path, bare, loss_fn,
        hardware=model_lib.HardwareSpec(), plan=_comm_opt_plan(bare),
    )
    assert ctrl.engine.grad_workers == WORLD
    state = trainer.init(params)
    losses = []
    for _ in range(6):
        state, loss = trainer.step(state, batch)
        losses.append(float(loss))
    mgr.finalize()
    assert mgr.latest_step() == 4
    assert os.path.exists(ctrl.plan_path)

    # the fault: the job comes back on a "resized pod" — the persisted
    # plan's fingerprint no longer matches this topology
    faults.change_topology(ctrl.plan_path)

    # phase 2: a fresh controller on the same rotation, under an HBM
    # budget that rules the stale COMM-OPT layout out
    with pytest.warns(FleetWarning, match='topology-changed'):
        trainer2, mgr2, ctrl2 = _make_fleet(tmp_path, bare, loss_fn)
    # landed on the NEWLY tuned layout: not the canonical default
    # (COMM-OPT, 8 gradient workers), not the stale plan (same)
    assert ctrl2.plan is not None
    assert ctrl2.plan.meta['retune_reason'] == 'topology-changed'
    assert ctrl2.engine.grad_workers == 1
    assert ctrl2.engine.strategy == DistributedStrategy.MEM_OPT
    # the retuned plan replaced the stale artifact on disk
    assert json.load(open(ctrl.plan_path))['fingerprint']['device_count'] \
        == WORLD

    # elastic restore into the tuned layout, then exact continuity
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        state2 = trainer2.restore_latest(params)
    assert state2 is not None
    assert int(jax.device_get(state2.kfac_state.step)) == 4
    for i in range(4, 6):
        state2, loss = trainer2.step(state2, batch)
        np.testing.assert_allclose(float(loss), losses[i], rtol=1e-4)


def test_tuned_restore_falls_back_to_canonical(tmp_path, monkeypatch):
    m, batch, params, bare, loss_fn = _setup()
    trainer, mgr, ctrl = _make_fleet(
        tmp_path, bare, loss_fn,
        hardware=model_lib.HardwareSpec(), plan=_comm_opt_plan(bare),
    )
    state = trainer.init(params)
    for _ in range(4):
        state, _ = trainer.step(state, batch)
    mgr.finalize()
    assert mgr.latest_step() == 4

    trainer2, mgr2, ctrl2 = _make_fleet(
        tmp_path, bare, loss_fn,
        hardware=model_lib.HardwareSpec(), plan=_comm_opt_plan(bare),
    )
    tuned_engine = ctrl2.engine
    real = mgr2.restore_latest

    def poisoned(engine=None, **kw):
        if engine is tuned_engine:
            raise OSError('reshard scratch exhausted')
        return real(engine=engine, **kw)

    monkeypatch.setattr(mgr2, 'restore_latest', poisoned)
    with pytest.warns(FleetWarning, match='tuned-restore-failed'):
        with warnings.catch_warnings():
            warnings.simplefilter('always')
            state2 = trainer2.restore_latest(params)
    # the canonical fallback engine took over end to end
    assert state2 is not None
    assert int(jax.device_get(state2.kfac_state.step)) == 4
    assert ctrl2.plan is None
    assert ctrl2.engine is not tuned_engine
    assert trainer2.kfac is ctrl2.engine is mgr2.engine
    assert [e['event'] for e in ctrl2.events][-1] == 'restore-fallback'
    # and the fallback engine actually steps
    state2, _ = trainer2.step(state2, batch)
    assert trainer2._step_count == 5


def test_restore_elastic_empty_rotation_returns_none(tmp_path):
    _, _, params, bare, loss_fn = _setup()
    trainer, mgr, ctrl = _make_fleet(
        tmp_path, bare, loss_fn, hardware=model_lib.HardwareSpec(),
    )
    assert trainer.restore_latest(params) is None
    # params template was never mutated by the attempt
    assert set(params) == {'fc1', 'fc2'}


# ------------------------------------- acceptance: drift-triggered migration


def _run_paired(trainer_a, trainer_b, params, batch, n, caught=None):
    """Step two trainers in lockstep; warnings are silenced, or recorded
    into ``caught`` when a list is passed."""
    sa = trainer_a.init(params)
    sb = trainer_b.init(params)
    la, lb, params4 = [], [], None
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter('always' if caught is not None else 'ignore')
        for i in range(n):
            sa, a = trainer_a.step(sa, batch)
            sb, b = trainer_b.step(sb, batch)
            la.append(float(a))
            lb.append(float(b))
            if i == 3:
                params4 = jax.device_get(sb.params)
    if caught is not None:
        caught.extend(rec)
    return sa, sb, la, lb, params4


def test_drift_migration_at_boundary_bit_identical(tmp_path):
    m, batch, params, bare, loss_fn = _setup()
    # drifting run: every drained record reports 2x relative skew;
    # calm control: same controller, zero skew. Both start from the
    # tuned COMM-OPT plan the drift retune (tight HBM budget) must leave.
    plan = _comm_opt_plan(bare)
    trainer, mgr, ctrl = _make_fleet(
        tmp_path / 'a', bare, loss_fn, ratio=2.0, plan=plan,
    )
    control, _, ctrl_c = _make_fleet(
        tmp_path / 'b', bare, loss_fn, ratio=0.0, plan=plan,
    )
    assert ctrl.engine.grad_workers == WORLD  # COMM-OPT until drift
    _, _, la, lb, params4 = _run_paired(trainer, control, params, batch, 6)

    # drift detected at the first full-window check (step 2), migration
    # executed at the step-4 checkpoint boundary
    names = [e['event'] for e in ctrl.events]
    assert names[:4] == ['drift', 'retune', 'armed', 'migrated']
    assert ctrl_c.events == []  # the calm pod never re-layouts
    assert ctrl.stats['migrations'] == 1
    assert ctrl.stats['aborts'] == 0
    assert ctrl.stats['downtime_steps'] == 2  # armed at 2, executed at 4
    assert ctrl.stats['migration_s'] > 0
    # the live engine moved off the canonical layout pod-wide
    assert ctrl.engine.grad_workers == 1
    assert ctrl.engine.strategy == DistributedStrategy.MEM_OPT
    assert trainer.kfac is ctrl.engine is mgr.engine
    # loss continuity through the migration
    np.testing.assert_allclose(la, lb, rtol=1e-6)

    # bit-identical params across the migration: the rotation's step-4
    # checkpoint restored into the new layout must round-trip exactly
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        restored = trainer.restore_latest(params)
    assert int(jax.device_get(restored.kfac_state.step)) == 4
    for layer in ('fc1', 'fc2'):
        for leaf in ('kernel', 'bias'):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(restored.params[layer][leaf])),
                np.asarray(params4[layer][leaf]),
                err_msg=f'{layer}/{leaf}',
            )


def test_drift_migration_rollback_on_agreement_failure(
    tmp_path, monkeypatch
):
    m, batch, params, bare, loss_fn = _setup()
    plan = _comm_opt_plan(bare)
    # the long cooldown keeps the pod from re-arming after the abort
    cfg = _fast_config(cooldown_steps=16)
    trainer, mgr, ctrl = _make_fleet(
        tmp_path / 'a', bare, loss_fn, ratio=2.0, plan=plan, config=cfg,
    )
    control, _, _ = _make_fleet(
        tmp_path / 'b', bare, loss_fn, ratio=0.0, plan=plan, config=cfg,
    )
    old_engine = ctrl.engine
    # a peer host votes the migration down (e.g. its reshard failed)
    monkeypatch.setattr(
        fleet_lib.multihost, 'agree_decision', lambda ok: False
    )
    caught: list = []
    sa, sb, la, lb, _ = _run_paired(
        trainer, control, params, batch, 6, caught=caught
    )
    assert any(
        isinstance(w.message, FleetWarning)
        and 'migration-aborted' in str(w.message)
        for w in caught
    )

    names = [e['event'] for e in ctrl.events]
    assert 'migration-aborted' in names
    assert 'migrated' not in names
    assert ctrl.stats['aborts'] == 1
    assert ctrl.stats['migrations'] == 0
    # rollback == nothing mutated: same engine, bit-identical trajectory
    assert ctrl.engine is old_engine
    assert trainer.kfac is old_engine
    assert ctrl._pending_plan is None  # dropped, cooldown armed
    np.testing.assert_allclose(la, lb, rtol=0)
    for layer in ('fc1', 'fc2'):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(sa.params[layer]['kernel'])),
            np.asarray(jax.device_get(sb.params[layer]['kernel'])),
            err_msg=layer,
        )


def test_abort_storm_respects_cooldown_and_never_half_swaps(
    tmp_path, monkeypatch
):
    """Chaos-harness abort storm: a pod that votes ABORT on every
    migration round must degrade to hysteresis, not thrash — each abort
    arms the ``cooldown_steps`` suppression window before the drift
    detector may re-arm, the engine is never half-swapped (identity
    stable across every abort), and the trajectory stays bit-identical
    to an undrifted control."""
    m, batch, params, bare, loss_fn = _setup()
    plan = _comm_opt_plan(bare)
    cfg = _fast_config(cooldown_steps=4)
    trainer, mgr, ctrl = _make_fleet(
        tmp_path / 'a', bare, loss_fn, ratio=2.0, plan=plan, config=cfg,
    )
    control, _, _ = _make_fleet(
        tmp_path / 'b', bare, loss_fn, ratio=0.0, plan=plan, config=cfg,
    )
    old_engine = ctrl.engine
    # every round, a peer votes the migration down
    monkeypatch.setattr(
        fleet_lib.multihost, 'agree_decision', lambda ok: False
    )
    caught: list = []
    sa, sb, la, lb, _ = _run_paired(
        trainer, control, params, batch, 16, caught=caught
    )
    aborts = [e for e in ctrl.events if e['event'] == 'migration-aborted']
    # a storm, not a single event — and every abort left stats coherent
    assert len(aborts) >= 2
    assert ctrl.stats['aborts'] == len(aborts)
    assert ctrl.stats['migrations'] == 0
    assert [e['event'] for e in ctrl.events].count('migrated') == 0
    # hysteresis: consecutive aborts are separated by >= cooldown_steps
    abort_steps = [e['step'] for e in aborts]
    assert all(
        b - a >= cfg.cooldown_steps
        for a, b in zip(abort_steps, abort_steps[1:])
    ), abort_steps
    # the warning is rate-limited per cause; at least the first abort
    # of the storm surfaced to the operator
    assert any(
        isinstance(w.message, FleetWarning)
        and 'migration-aborted' in str(w.message)
        for w in caught
    )
    # never half-swapped: the SAME engine object served every step
    assert ctrl.engine is old_engine
    assert trainer.kfac is old_engine
    assert mgr.engine is old_engine
    # aborts mutate nothing: bit-identical losses and params vs control
    np.testing.assert_allclose(la, lb, rtol=0)
    for layer in ('fc1', 'fc2'):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(sa.params[layer]['kernel'])),
            np.asarray(jax.device_get(sb.params[layer]['kernel'])),
            err_msg=layer,
        )


def test_drift_without_periodic_saves_warns_and_stands_down(tmp_path):
    m, batch, params, bare, loss_fn = _setup()
    trainer, mgr, ctrl = _make_fleet(
        tmp_path, bare, loss_fn, ratio=2.0, save_interval_steps=None,
        plan=_comm_opt_plan(bare),
    )
    state = trainer.init(params)
    with pytest.warns(FleetWarning, match='migration-disabled'):
        for _ in range(2):
            state, _ = trainer.step(state, batch)
    assert [e['event'] for e in ctrl.events] == ['drift']
    assert ctrl._pending_plan is None


def test_drift_retune_noop_when_knobs_unchanged(tmp_path):
    m, batch, params, bare, loss_fn = _setup()
    # the current plan IS what the retune would pick: arm nothing
    plan = search_lib.autotune(
        bare(), measure=False, world=WORLD, hardware=TIGHT_HBM,
    )
    trainer, mgr, ctrl = _make_fleet(
        tmp_path, bare, loss_fn, ratio=2.0, plan=plan,
    )
    state = trainer.init(params)
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        for _ in range(2):
            state, _ = trainer.step(state, batch)
    names = [e['event'] for e in ctrl.events]
    assert names == ['drift', 'retune', 'retune-noop']
    assert ctrl._pending_plan is None
    assert ctrl.stats['migrations'] == 0


def test_calm_pod_skips_drift_checks_off_cadence(tmp_path):
    _, _, _, bare, loss_fn = _setup()
    seen = []

    def counting_drain(state):
        seen.append(1)
        return []

    mgr = CheckpointManager(
        tmp_path, save_interval_steps=4, install_signals=(),
        async_save=False,
    )
    ctrl = kfac_tpu.FleetController(
        mgr, _fast_config(check_every=4), drain=counting_drain,
    )
    trainer = kfac_tpu.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=bare(), fleet=ctrl,
    )
    m, batch, params, _, _ = _setup()
    state = trainer.init(params)
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        for _ in range(8):
            state, _ = trainer.step(state, batch)
    # drained only on the check_every cadence (steps 4 and 8)
    assert len(seen) == 2
