"""Measurement-truth layer, host side: trace attribution + calibration.

Three surfaces, all CPU-runnable:

- :mod:`kfac_tpu.observability.trace_attrib` against the committed
  mini-trace fixture (``tests/data/mini_trace``): device-lane filtering,
  identifier-boundary scope matching, group-id and window-fallback step
  mapping, args-string scope fallback, totals for out-of-window events;
- :class:`kfac_tpu.observability.calibration.CalibrationMonitor`:
  residual-ratio math (warmup, rolling window, direction-free fold
  error), the ``calib/*`` record/annotate emission contract, the
  rotating :class:`~kfac_tpu.observability.sinks.JSONLWriter`, and the
  rate-limited logger's ``calib/model_error`` headline;
- the ISSUE acceptance headline: a doctored 2x cost-model error drives
  the EXISTING :class:`kfac_tpu.FleetController` through its native
  drift -> retune -> armed -> migrated path, with no new controller
  machinery — the monitor only stamps synthetic skew columns into the
  drain. A perfectly calibrated control run never re-layouts, and the
  jit cache stays at one entry on both engines (host-side only).

The fleet harness mirrors tests/test_fleet.py (TIGHT_HBM sized between
the MEM-OPT and COMM-OPT footprints forces the drift retune off the
canonical COMM-OPT layout).
"""

import json
import os
import warnings

import jax
import optax
import pytest

import kfac_tpu
from kfac_tpu.autotune import model as model_lib
from kfac_tpu.autotune import search as search_lib
from kfac_tpu.enums import DistributedStrategy
from kfac_tpu.observability import calibration, trace_attrib
from kfac_tpu.observability import flight_recorder as flight_lib
from kfac_tpu.observability.sinks import JSONLWriter, RateLimitedLogger
from kfac_tpu.resilience import CheckpointManager
from kfac_tpu.warnings import reset_fleet_warnings, reset_layout_warnings
from testing import compile_pins, models

FIXTURE = os.path.join(os.path.dirname(__file__), 'data', 'mini_trace')

WORLD = 8

#: see tests/test_fleet.py — between MEM-OPT and COMM-OPT footprints, so
#: the model-only retune must leave the canonical COMM-OPT layout
TIGHT_HBM = model_lib.HardwareSpec(hbm_bytes=8000.0)


@pytest.fixture(autouse=True)
def _clean_warning_state():
    reset_fleet_warnings()
    reset_layout_warnings()
    yield
    reset_fleet_warnings()
    reset_layout_warnings()


# ----------------------------------------------------- trace attribution


def test_fixture_step_attribution_exact():
    """The committed mini-trace parses to pinned numbers: device lanes
    only, boundary-checked scopes, group-id + window step mapping."""
    out = trace_attrib.step_attribution(FIXTURE)
    assert out['n_steps'] == 2
    assert out['n_device_events'] == 7
    assert len(out['trace_files']) == 1
    # step 7: group_id events, including the dist_kfac.precondition one
    # that must NOT be miscounted as kfac.precondition (boundary check),
    # and the host-lane kfac.update_factors impostor that must be ignored
    assert out['steps'][7] == {
        'dist_kfac.precondition': 0.1,
        'kfac.precondition': 0.2,
        'kfac.update_factors': 0.3,
    }
    # step 8: window-fallback (no group_id), args long_name fallback for
    # the fusion event, and the unattributable infeed op
    assert out['steps'][8] == {
        'kfac.precondition': 0.05,
        'kfac.update_inverses': 0.4,
        'unattributed': 0.03,
    }
    # the out-of-window async refresh counts toward totals only
    assert out['total_ms'] == {
        'dist_kfac.precondition': 0.1,
        'kfac.async_refresh': 0.8,
        'kfac.precondition': 0.25,
        'kfac.update_factors': 0.3,
        'kfac.update_inverses': 0.4,
        'unattributed': 0.03,
    }
    # mean over the two annotated steps, async refresh excluded
    assert out['per_step_ms'] == {
        'kfac.update_factors': 0.15,
        'kfac.precondition': 0.125,
        'dist_kfac.precondition': 0.05,
        'kfac.update_inverses': 0.2,
        'unattributed': 0.015,
    }


def test_device_breakdown_is_per_step_view():
    assert (trace_attrib.device_breakdown_ms(FIXTURE)
            == trace_attrib.step_attribution(FIXTURE)['per_step_ms'])


def test_match_scope_boundary_and_depth():
    # identifier boundary: the kfac.* substring inside dist_kfac.* does
    # not count as a kfac.* scope entry
    assert (trace_attrib.match_scope('jit(f)/dist_kfac.update_factors/x')
            == 'dist_kfac.update_factors')
    assert trace_attrib.match_scope('a_kfac.step') is None
    # nested scopes attribute to the innermost (deepest occurrence)
    assert (trace_attrib.match_scope('kfac.step/kfac.precondition/fusion')
            == 'kfac.precondition')
    assert trace_attrib.match_scope('fusion.123') is None


def test_find_trace_files_resolution(tmp_path):
    files = trace_attrib.find_trace_files(FIXTURE)
    assert len(files) == 1 and files[0].endswith('trace.json.gz')
    # a direct file path passes through
    assert trace_attrib.find_trace_files(files[0]) == [files[0]]
    # a dir with no traces is empty, not an error
    assert trace_attrib.find_trace_files(tmp_path) == []


def test_host_only_trace_yields_empty_breakdown(tmp_path):
    """A CPU-backend capture (no device lanes) is a graceful no-op."""
    import gzip

    doc = {'traceEvents': [
        {'ph': 'M', 'pid': 1, 'name': 'process_name',
         'args': {'name': '/host:CPU'}},
        {'ph': 'X', 'pid': 1, 'name': 'kfac.update_factors',
         'ts': 0, 'dur': 100},
    ]}
    path = tmp_path / 'host.trace.json.gz'
    with gzip.open(path, 'wt') as f:
        json.dump(doc, f)
    out = trace_attrib.step_attribution(tmp_path)
    assert out['n_device_events'] == 0
    assert trace_attrib.device_breakdown_ms(tmp_path) == {}


# ------------------------------------------------------ JSONL rotation


def _lines(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def test_jsonl_rotation_off_by_default(tmp_path):
    path = tmp_path / 'metrics.jsonl'
    with JSONLWriter(path) as w:
        for i in range(50):
            w.write({'step': i, 'pad': 'x' * 64})
    assert len(_lines(path)) == 50
    assert not os.path.exists(f'{path}.1')


def test_jsonl_rotation_shifts_and_caps(tmp_path):
    path = str(tmp_path / 'metrics.jsonl')
    rec = {'step': 0, 'pad': 'x' * 40}
    size = len(json.dumps(rec, sort_keys=True)) + 1
    # room for exactly two records per file
    with JSONLWriter(path, max_bytes=2 * size + 1, max_files=2) as w:
        for i in range(9):
            w.write({'step': i, 'pad': 'x' * 40})
    # newest records in the active file, shifted history behind it,
    # oldest files deleted at the max_files cap
    assert [r['step'] for r in _lines(path)] == [8]
    assert [r['step'] for r in _lines(f'{path}.1')] == [6, 7]
    assert [r['step'] for r in _lines(f'{path}.2')] == [4, 5]
    assert not os.path.exists(f'{path}.3')


def test_jsonl_rotation_never_splits_a_record(tmp_path):
    path = str(tmp_path / 'metrics.jsonl')
    with JSONLWriter(path, max_bytes=64, max_files=4) as w:
        for i in range(12):
            w.write({'step': i, 'pad': 'y' * (i * 7)})
    # every surviving line — in every generation — parses whole, and the
    # step sequence across generations is a contiguous suffix
    steps = []
    for suffix in ('.4', '.3', '.2', '.1', ''):
        f = path + suffix
        if os.path.exists(f):
            steps.extend(r['step'] for r in _lines(f))
    assert steps == list(range(12 - len(steps), 12))


def test_jsonl_oversized_record_written_whole(tmp_path):
    path = str(tmp_path / 'metrics.jsonl')
    with JSONLWriter(path, max_bytes=16, max_files=2) as w:
        w.write({'huge': 'z' * 200})
    assert _lines(path) == [{'huge': 'z' * 200}]


def test_jsonl_rotation_validation(tmp_path):
    with pytest.raises(ValueError, match='max_bytes'):
        JSONLWriter(tmp_path / 'a.jsonl', max_bytes=-1)
    with pytest.raises(ValueError, match='max_files'):
        JSONLWriter(tmp_path / 'a.jsonl', max_files=0)


# -------------------------------------------------- calibration monitor


def test_calibration_config_validation():
    cfg = calibration.CalibrationConfig()
    assert (cfg.window, cfg.warmup_steps, cfg.prefix) == (32, 3, 'calib')
    with pytest.raises(ValueError, match='window'):
        calibration.CalibrationConfig(window=0)
    with pytest.raises(ValueError, match='warmup_steps'):
        calibration.CalibrationConfig(warmup_steps=-1)


def test_monitor_rejects_bad_predictions():
    with pytest.raises(ValueError, match='predicted_step_s'):
        calibration.CalibrationMonitor(0.0)
    # a non-positive spike prediction just disables the spike channel
    mon = calibration.CalibrationMonitor(0.01, refresh_spike_s=0.0)
    assert mon.refresh_spike_s is None
    assert mon.observe_spike(1.0) is None


def test_monitor_warmup_and_empty_record():
    cfg = calibration.CalibrationConfig(warmup_steps=2)
    mon = calibration.CalibrationMonitor(0.01, config=cfg)
    assert mon.record() == {}
    assert mon.observe_step(0.02) is None
    assert mon.observe_step(0.02) is None
    assert mon.record() == {}  # still no evidence
    assert mon.model_error() == 1.0  # idle monitor never looks drifted
    assert mon.observe_step(0.02) == pytest.approx(2.0)
    assert mon.record() != {}


def test_monitor_residual_math_and_fold_symmetry():
    cfg = calibration.CalibrationConfig(warmup_steps=0, window=8)
    mon = calibration.CalibrationMonitor(0.01, config=cfg)
    for _ in range(3):
        mon.observe_step(0.02)
    assert mon.step_ratio() == pytest.approx(2.0)
    assert mon.model_error() == pytest.approx(2.0)
    # a 2x-pessimistic model reads the same fold error
    pess = calibration.CalibrationMonitor(0.01, config=cfg)
    pess.observe_step(0.005)
    assert pess.step_ratio() == pytest.approx(0.5)
    assert pess.model_error() == pytest.approx(2.0)


def test_monitor_rolling_window_forgets():
    cfg = calibration.CalibrationConfig(warmup_steps=0, window=2)
    mon = calibration.CalibrationMonitor(1.0, config=cfg)
    mon.observe_step(1.0)
    mon.observe_step(1.0)
    mon.observe_step(3.0)
    mon.observe_step(3.0)
    assert mon.step_ratio() == pytest.approx(3.0)


def test_monitor_rejects_nonfinite_and_nonpositive():
    cfg = calibration.CalibrationConfig(warmup_steps=0)
    mon = calibration.CalibrationMonitor(0.01, config=cfg)
    for bad in (float('nan'), float('inf'), 0.0, -1.0):
        assert mon.observe_step(bad) is None
    assert mon.step_ratio() is None


def test_monitor_record_and_annotate_contract():
    cfg = calibration.CalibrationConfig(warmup_steps=0, window=4)
    mon = calibration.CalibrationMonitor(0.01, refresh_spike_s=0.5,
                                         config=cfg)
    mon.observe_step(0.02)
    mon.observe_step(0.02)
    assert mon.observe_spike(1.0) == pytest.approx(2.0)
    rec = mon.record()
    assert set(rec) == {
        'calib/predicted_step_s', 'calib/measured_step_s',
        'calib/step_ratio', 'calib/model_error', 'calib/n',
        'calib/predicted_spike_s', 'calib/spike_ratio',
    }
    assert rec['calib/predicted_step_s'] == pytest.approx(0.01)
    assert rec['calib/measured_step_s'] == pytest.approx(0.02)
    assert rec['calib/step_ratio'] == pytest.approx(2.0)
    assert rec['calib/model_error'] == pytest.approx(2.0)
    assert rec['calib/n'] == 2.0
    assert rec['calib/spike_ratio'] == pytest.approx(2.0)
    # annotate folds the same keys into a drained record, in place
    drained = {'step': 5, 'loss': 0.1}
    out = mon.annotate(drained)
    assert out is drained
    assert drained['calib/model_error'] == pytest.approx(2.0)
    assert drained['step'] == 5
    # custom prefix renames the metric namespace...
    alt = calibration.CalibrationMonitor(
        0.01, config=calibration.CalibrationConfig(
            warmup_steps=0, prefix='cm'))
    alt.observe_step(0.02)
    assert 'cm/model_error' in alt.record()
    # ...but the fleet bridge's drift key stays fixed
    assert calibration.DRIFT_KEY in alt.drift_skew_columns()


def test_monitor_from_real_tuned_plan():
    _, _, _, bare, _ = _setup()
    plan = _comm_opt_plan(bare)
    mon = calibration.CalibrationMonitor.from_plan(plan)
    assert mon.predicted_step_s == pytest.approx(
        plan.winner['predicted_step_s'])
    assert mon.predicted_step_s > 0
    row = calibration._winner_row(plan)
    assert row and row.get('knobs') == plan.knobs
    spike = row.get('refresh_spike_s')
    if spike is not None and spike > 0:
        assert mon.refresh_spike_s == pytest.approx(spike)
    else:
        assert mon.refresh_spike_s is None
    # plan dicts coerce through as_plan too
    mon2 = calibration.CalibrationMonitor.from_plan(plan.to_json())
    assert mon2.predicted_step_s == pytest.approx(mon.predicted_step_s)


def test_fleet_drift_keys_dedup():
    assert calibration.fleet_drift_keys() == (
        'calib/model_error', 'grad_norm')
    assert calibration.fleet_drift_keys(
        ('calib/model_error', 'loss')) == ('calib/model_error', 'loss')


def test_drift_skew_columns_speak_controller_dialect():
    cfg = calibration.CalibrationConfig(warmup_steps=0)
    mon = calibration.CalibrationMonitor(0.01, config=cfg)
    for _ in range(2):
        mon.observe_step(0.02)
    cols = mon.drift_skew_columns()
    # the controller's own skew_ratio reads fold_error - 1 off them
    assert flight_lib.skew_ratio(cols, calibration.DRIFT_KEY) == (
        pytest.approx(mon.model_error() - 1.0))
    # and an uncalibrated monitor reads as zero skew (no false drift)
    idle = calibration.CalibrationMonitor(0.01, config=cfg)
    assert flight_lib.skew_ratio(
        idle.drift_skew_columns(), calibration.DRIFT_KEY) == 0.0


def test_wrap_drain_stamps_every_record():
    cfg = calibration.CalibrationConfig(warmup_steps=0)
    mon = calibration.CalibrationMonitor(0.01, config=cfg)
    mon.observe_step(0.02)
    drain = mon.wrap_drain(lambda state: [{'step': 1}, {'step': 2}])
    records = drain(None)
    assert len(records) == 2
    for rec in records:
        assert rec[calibration.DRIFT_KEY] == pytest.approx(2.0)
        assert rec[f'skew_max/{calibration.DRIFT_KEY}'] == (
            pytest.approx(2.0))
        assert rec[f'skew_mean/{calibration.DRIFT_KEY}'] == 1.0


def test_rate_limited_logger_headlines_model_error(caplog):
    assert 'calib/model_error' in RateLimitedLogger._HEADLINE
    rl = RateLimitedLogger(min_interval_s=0.0)
    with caplog.at_level('INFO'):
        assert rl.emit({'step': 3, 'calib/model_error': 2.0,
                        'calib/step_ratio': 2.0})
    assert 'calib/model_error=2' in caplog.text


def test_monitor_records_flow_through_jsonl(tmp_path):
    cfg = calibration.CalibrationConfig(warmup_steps=0)
    mon = calibration.CalibrationMonitor(0.01, config=cfg)
    path = tmp_path / 'metrics.jsonl'
    with JSONLWriter(path) as w:
        w.write(mon.record())  # empty pre-evidence record is a no-op
        mon.observe_step(0.02)
        w.write(mon.record())
    lines = _lines(path)
    assert len(lines) == 1
    assert lines[0]['calib/model_error'] == pytest.approx(2.0)


# ------------------------------------------------- fleet drift headline


def _setup():
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(p, model_state, batch):
        bx, by = batch
        pred = m.apply({'params': p}, bx)
        return jax.numpy.mean((pred - by) ** 2), model_state

    def bare():
        return kfac_tpu.KFACPreconditioner(
            registry=reg, kl_clip=None, damping=1e-3, flight=8
        )

    return m, (x, y), params, bare, loss_fn


def _comm_opt_plan(bare):
    return search_lib.autotune(
        bare(), measure=False, world=WORLD,
        fractions=(1.0,), granularities=(1,),
    )


def _calibrated_fleet(directory, bare, loss_fn, plan, monitor):
    cfg = kfac_tpu.FleetConfig(
        check_every=2, drift_keys=calibration.fleet_drift_keys(),
        drift_threshold=0.5, drift_window=2, drift_patience=1,
        cooldown_steps=1,
    )
    mgr = CheckpointManager(
        directory, save_interval_steps=4, keep=3,
        install_signals=(), async_save=False,
    )
    ctrl = kfac_tpu.FleetController(
        mgr, cfg, plan=plan, hardware=TIGHT_HBM,
        drain=monitor.wrap_drain(),
    )
    trainer = kfac_tpu.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=bare(), fleet=ctrl,
    )
    return trainer, ctrl


def test_cost_model_drift_drives_existing_retune_path(tmp_path):
    """The ISSUE acceptance headline: a doctored 2x cost-model error —
    nothing else — walks the UNMODIFIED FleetController through drift ->
    retune -> armed -> migrated, while a perfectly calibrated control
    run on the same plan never re-layouts."""
    m, batch, params, bare, loss_fn = _setup()
    plan = _comm_opt_plan(bare)
    ccfg = calibration.CalibrationConfig(warmup_steps=0, window=4)

    drifted = calibration.CalibrationMonitor.from_plan(plan, ccfg)
    calm = calibration.CalibrationMonitor.from_plan(plan, ccfg)
    for _ in range(4):
        # steps measure 2x the model's prediction vs spot-on
        drifted.observe_step(2.0 * drifted.predicted_step_s)
        calm.observe_step(calm.predicted_step_s)
    assert drifted.model_error() == pytest.approx(2.0)
    assert calm.model_error() == pytest.approx(1.0)

    trainer, ctrl = _calibrated_fleet(
        tmp_path / 'a', bare, loss_fn, plan, drifted)
    control, ctrl_c = _calibrated_fleet(
        tmp_path / 'b', bare, loss_fn, plan, calm)
    assert ctrl.engine.grad_workers == WORLD  # COMM-OPT until drift

    state, cstate = trainer.init(params), control.init(params)
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        for _ in range(6):
            state, _ = trainer.step(state, batch)
            cstate, _ = control.step(cstate, batch)

    names = [e['event'] for e in ctrl.events]
    assert names[:4] == ['drift', 'retune', 'armed', 'migrated']
    assert ctrl.stats['migrations'] == 1
    # the tight HBM budget forced the retune off the canonical layout
    assert ctrl.engine.grad_workers == 1
    assert ctrl.engine.strategy == DistributedStrategy.MEM_OPT
    # the calibrated pod never moves
    assert ctrl_c.events == []
    assert ctrl_c.engine.grad_workers == WORLD


def test_memory_residual_drives_existing_retune_path(tmp_path):
    """PR-17 acceptance mirror of the time-residual headline: a doctored
    2x XLA-memory residual — step timings spot-on — walks the UNMODIFIED
    FleetController through drift -> retune -> armed -> migrated with
    zero new controller machinery, while a fully calibrated control run
    never re-layouts."""
    m, batch, params, bare, loss_fn = _setup()
    plan = _comm_opt_plan(bare)
    ccfg = calibration.CalibrationConfig(warmup_steps=0, window=4)

    drifted = calibration.CalibrationMonitor.from_plan(plan, ccfg)
    calm = calibration.CalibrationMonitor.from_plan(plan, ccfg)
    assert drifted.predicted_mem_bytes is not None  # plan carries memory
    for _ in range(4):
        # both pods time exactly as modelled; only the drifted pod's
        # measured HBM comes back 2x the cost model's prediction
        drifted.observe_step(drifted.predicted_step_s)
        drifted.observe_memory(2.0 * drifted.predicted_mem_bytes)
        calm.observe_step(calm.predicted_step_s)
        calm.observe_memory(calm.predicted_mem_bytes)
    assert drifted.step_ratio() == pytest.approx(1.0)
    assert drifted.model_error() == pytest.approx(2.0)  # memory channel
    assert calm.model_error() == pytest.approx(1.0)

    trainer, ctrl = _calibrated_fleet(
        tmp_path / 'a', bare, loss_fn, plan, drifted)
    control, ctrl_c = _calibrated_fleet(
        tmp_path / 'b', bare, loss_fn, plan, calm)
    assert ctrl.engine.grad_workers == WORLD  # COMM-OPT until drift

    state, cstate = trainer.init(params), control.init(params)
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        for _ in range(6):
            state, _ = trainer.step(state, batch)
            cstate, _ = control.step(cstate, batch)

    names = [e['event'] for e in ctrl.events]
    assert names[:4] == ['drift', 'retune', 'armed', 'migrated']
    assert ctrl.stats['migrations'] == 1
    assert ctrl.engine.grad_workers == 1
    assert ctrl.engine.strategy == DistributedStrategy.MEM_OPT
    # the calibrated pod never moves
    assert ctrl_c.events == []
    assert ctrl_c.engine.grad_workers == WORLD


# ------------------------------------------------- no-recompile pinning


def _observe_loop(kfac_like, run, params, batch, monitor, n=5):
    state = kfac_like.init()
    step = compile_pins.watched_jit(kfac_like.step)
    for _ in range(n):
        (_, _), grads, stats = run(params, batch)
        state, _ = step(state, grads, stats)
        monitor.observe_step(0.02)
        monitor.annotate({'step': 1})
    return step


def test_calibration_is_jit_invisible_dense():
    """Observing/annotating every step is purely host-side: one cache
    entry, exactly like an uninstrumented run."""
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, metrics=True)
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(
        models.mse_loss(m))
    mon = calibration.CalibrationMonitor(
        0.01, config=calibration.CalibrationConfig(warmup_steps=0))
    step = _observe_loop(kfac, run, params, (x, y), mon)
    compile_pins.assert_compiled_once(step)
    assert mon.model_error() == pytest.approx(2.0)


def test_calibration_is_jit_invisible_distributed():
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg, metrics=True)
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(
        models.mse_loss(m))
    mon = calibration.CalibrationMonitor(
        0.01, config=calibration.CalibrationConfig(warmup_steps=0))
    step = _observe_loop(dk, run, params, (x, y), mon, n=3)
    compile_pins.assert_compiled_once(step)
