"""Async inverse refresh: double-buffered decompositions off the step path.

Covers the ISSUE-6 acceptance surface: slice planning, the stale-inverse
equivalence contract (async at cadence N bit-identical to the synchronous
path one window earlier, for the dense engine and both KAISA transports),
the host-offloaded backend (LAPACK basis ambiguity makes raw eigenvector
comparison meaningless — preconditioned gradients are compared instead),
``inv_staleness/*`` metrics truthfulness under async refresh, the
quarantine interaction (an in-flight shadow refresh of a quarantined
layer is discarded, not swapped), checkpoint restore mid-window
(shadow ephemeral, rebuilt deterministically), and all four Trainer
paths.

The bit-equivalence contract requires ``factor_update_steps ==
inv_update_steps``: slices fold in the CURRENT factors, which only match
the synchronous boundary snapshot when factors change at boundaries
alone. With unaligned cadences the async path sees strictly FRESHER
mid-window factors — valid, but not bit-identical.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import kfac_tpu
from kfac_tpu import checkpoint, enums
from kfac_tpu import health as health_lib
from kfac_tpu.async_inverse import (
    AsyncInverseConfig,
    as_async_config,
    plan_slices,
)
from kfac_tpu.async_inverse import host as async_host
from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh
from testing import compile_pins, models

WORLD = 8
N = 4  # cadence window used throughout (factor == inverse, see docstring)

_FIELDS = ('qa', 'qg', 'da', 'dg', 'dgda', 'a_inv', 'g_inv')


def _decomps(state):
    return jax.tree.map(np.asarray, {f: getattr(state, f) for f in _FIELDS})


def _bit_equal(ref, got, msg):
    eq = jax.tree.map(lambda a, b: np.array_equal(a, b), ref, got)
    assert all(jax.tree.leaves(eq)), msg


# ------------------------------------------------------------- configuration


def test_async_config_normalization():
    assert as_async_config(None) is None
    assert as_async_config(False) is None
    assert as_async_config(True) == AsyncInverseConfig()
    assert as_async_config('host') == AsyncInverseConfig(mode='host')
    cfg = AsyncInverseConfig(mode='sliced', max_slices=3)
    assert as_async_config(cfg) is cfg
    with pytest.raises(ValueError, match='mode'):
        AsyncInverseConfig(mode='warp')
    with pytest.raises(TypeError):
        as_async_config(3.5)


def test_async_rejects_cadence_schedule():
    m = models.TinyModel()
    x, _ = models.regression_data(jax.random.PRNGKey(1), n=8, dim=6)
    reg = kfac_tpu.register_model(m, x)
    with pytest.raises(ValueError, match='static int'):
        kfac_tpu.KFACPreconditioner(
            registry=reg,
            inv_update_steps=lambda s: 4,
            async_inverse='sliced',
        )


def test_plan_slices_balances_and_is_deterministic():
    units = [
        ('a', 8.0), ('b', 1.0), ('c', 1.0),
        ('d', 6.0), ('e', 1.0), ('f', 1.0),
    ]
    s1 = plan_slices(units, 3)
    assert s1 == plan_slices(list(units), 3)
    assert sorted(k for sl in s1 for k in sl) == sorted(k for k, _ in units)
    costs = dict(units)
    loads = sorted(sum(costs[k] for k in sl) for sl in s1)
    # LPT: the dominant unit sits alone, the small ones backfill
    assert loads[-1] == 8.0
    # slice count caps at the unit count; empty slices are dropped
    assert plan_slices(units, 10) == plan_slices(units, len(units))
    with pytest.raises(ValueError):
        plan_slices(units, 0)


# --------------------------------------------------- dense engine equivalence


def _dense_pair(mode, method, health=None, prediv=False):
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=32, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    loss_fn = models.mse_loss(m)
    kw = dict(
        registry=reg, compute_method=method, kl_clip=None,
        inv_update_steps=N, factor_update_steps=N, health=health,
        prediv_eigenvalues=prediv,
    )
    sync = kfac_tpu.KFACPreconditioner(**kw)
    asy = kfac_tpu.KFACPreconditioner(**kw, async_inverse=mode)
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss_fn)
    return sync, asy, run, params, (x, y)


def _run_pair(sync, asy, run, params, batch, n=12, mode='sliced'):
    """Step both engines in lockstep on drifting params; returns per-step
    (sync_state, async_state) pairs and the last gradients."""
    ss, sa = sync.init(), asy.init()
    js, ja = jax.jit(sync.step), jax.jit(asy.step)
    hist, grads = [], None
    for i in range(n):
        (_, _), grads, stats = run(params, batch)
        if mode == 'host':
            sa = async_host.pump(asy, sa, step=i)
        ss, _ = js(ss, grads, stats)
        sa, _ = ja(sa, grads, stats)
        hist.append((ss, sa))
        params = jax.tree.map(lambda p: p * 0.999, params)
    return hist, grads


@pytest.mark.parametrize(
    'method,prediv,health',
    [
        (enums.ComputeMethod.EIGEN, False, None),
        (enums.ComputeMethod.EIGEN, True, None),
        (enums.ComputeMethod.EIGEN, False, health_lib.HealthConfig(warn=False)),
    ],
    ids=['eigen', 'prediv', 'health'],
)
def test_dense_sliced_bit_identical_one_window_lag(method, prediv, health):
    """Sliced async decompositions at step s equal the synchronous path's
    at the previous boundary — bit-for-bit, at every swap boundary and
    throughout the window (window 0 is the shared cold start)."""
    sync, asy, run, params, batch = _dense_pair(
        'sliced', method, health=health, prediv=prediv
    )
    hist, _ = _run_pair(sync, asy, run, params, batch)
    for s in range(N):
        _bit_equal(
            _decomps(hist[s][0]), _decomps(hist[s][1]),
            f'window-0 step {s} diverged from the shared cold start',
        )
    for s in range(N, len(hist)):
        lag = (s // N) * N - N
        _bit_equal(
            _decomps(hist[lag][0]), _decomps(hist[s][1]),
            f'async step {s} != sync step {lag}',
        )


def test_dense_sliced_inverse_matches_one_window_lag():
    """INVERSE mode: same one-window lag, allclose rather than bit-exact
    (the sliced warm start seeds Newton-Schulz from the ACTIVE inverse,
    the sync path from its own previous window's)."""
    sync, asy, run, params, batch = _dense_pair(
        'sliced', enums.ComputeMethod.INVERSE
    )
    hist, _ = _run_pair(sync, asy, run, params, batch)
    for s in range(N, len(hist)):
        lag = (s // N) * N - N
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-3),
            _decomps(hist[lag][0]), _decomps(hist[s][1]),
        )


@pytest.mark.parametrize(
    'method', [enums.ComputeMethod.EIGEN, enums.ComputeMethod.INVERSE]
)
def test_dense_host_preconditions_like_lagged_sync(method):
    """Host backend: LAPACK and XLA eigenvectors differ by sign/basis, so
    the contract is on the preconditioner's ACTION — async preconditioned
    gradients match the synchronous engine's one window earlier."""
    sync, asy, run, params, batch = _dense_pair('host', method)
    hist, grads = _run_pair(sync, asy, run, params, batch, mode='host')
    for s in range(N, len(hist)):
        lag = (s // N) * N - N
        ref = jax.tree.map(np.asarray, sync.precondition(hist[lag][0], grads))
        got = jax.tree.map(np.asarray, asy.precondition(hist[s][1], grads))
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-3, atol=1e-4),
            ref, got,
        )


# --------------------------------------------------------- KAISA equivalence


def _kaisa_pair(mode, method, frac=1.0, health=None, prediv=False,
                allreduce=enums.AllreduceMethod.ALLREDUCE):
    mesh = kaisa_mesh(grad_worker_fraction=frac)
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=WORLD * 8, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    loss_fn = models.mse_loss(m)
    kw = dict(
        registry=reg, compute_method=method, kl_clip=None,
        inv_update_steps=N, factor_update_steps=N, health=health,
        prediv_eigenvalues=prediv, allreduce_method=allreduce,
    )
    sync = DistributedKFAC(
        config=kfac_tpu.KFACPreconditioner(**kw), mesh=mesh
    )
    asy = DistributedKFAC(
        config=kfac_tpu.KFACPreconditioner(**kw, async_inverse=mode),
        mesh=mesh,
    )
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss_fn)
    return sync, asy, run, params, (x, y)


@pytest.mark.parametrize(
    'method,frac,prediv,health,allreduce',
    [
        ('eigen', 1.0, False, None, enums.AllreduceMethod.ALLREDUCE),
        ('eigen', 0.5, False, None, enums.AllreduceMethod.ALLREDUCE),
        ('eigen', 1.0, True, None, enums.AllreduceMethod.ALLREDUCE),
        ('inverse', 1.0, False, None, enums.AllreduceMethod.ALLREDUCE),
        (
            'eigen', 1.0, False, health_lib.HealthConfig(warn=False),
            enums.AllreduceMethod.ALLREDUCE,
        ),
        (
            'eigen', 1.0, False, None,
            enums.AllreduceMethod.ALLREDUCE_BUCKETED,
        ),
    ],
    ids=[
        'eigen', 'hybrid', 'prediv', 'inverse', 'health', 'bucketed',
    ],
)
def test_kaisa_sliced_bit_identical_one_window_lag(
    method, frac, prediv, health, allreduce
):
    """The distributed engine's sliced backend holds the same bit-level
    contract, across work placements and both stat transports."""
    sync, asy, run, params, batch = _kaisa_pair(
        'sliced', method, frac=frac, health=health, prediv=prediv,
        allreduce=allreduce,
    )
    hist, _ = _run_pair(sync, asy, run, params, batch)
    for s in range(N):
        _bit_equal(
            _decomps(hist[s][0]), _decomps(hist[s][1]),
            f'window-0 step {s} diverged from the shared cold start',
        )
    for s in range(N, len(hist)):
        lag = (s // N) * N - N
        _bit_equal(
            _decomps(hist[lag][0]), _decomps(hist[s][1]),
            f'async step {s} != sync step {lag}',
        )


@pytest.mark.parametrize(
    'method,frac,allreduce',
    [
        ('eigen', 1.0, enums.AllreduceMethod.ALLREDUCE),
        ('eigen', 0.5, enums.AllreduceMethod.ALLREDUCE_BUCKETED),
        ('inverse', 1.0, enums.AllreduceMethod.ALLREDUCE),
    ],
    ids=['eigen', 'hybrid_bucketed', 'inverse'],
)
def test_kaisa_host_preconditions_like_lagged_sync(method, frac, allreduce):
    sync, asy, run, params, batch = _kaisa_pair(
        'host', method, frac=frac, allreduce=allreduce
    )
    hist, grads = _run_pair(sync, asy, run, params, batch, mode='host')
    for s in range(N, len(hist)):
        lag = (s // N) * N - N
        ref = jax.tree.map(np.asarray, sync.precondition(hist[lag][0], grads))
        got = jax.tree.map(np.asarray, asy.precondition(hist[s][1], grads))
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-3, atol=1e-4),
            ref, got,
        )


# -------------------------------------------------------- staleness metrics


def test_inv_staleness_tracks_swap_not_schedule():
    """``last_inv_step`` advances at SWAP time: the staleness column
    cycles through the full cadence window, never exceeding N-1."""
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=32, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    loss_fn = models.mse_loss(m)
    asy = kfac_tpu.KFACPreconditioner(
        registry=reg, kl_clip=None, inv_update_steps=N,
        factor_update_steps=N, async_inverse='sliced', metrics=True,
    )
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss_fn)
    collector = kfac_tpu.MetricsCollector()
    state = asy.init()
    step = compile_pins.watched_jit(asy.step)
    staleness = []
    for i in range(3 * N):
        (_, _), grads, stats = run(params, (x, y))
        state, _ = step(state, grads, stats)
        staleness.append(int(collector.drain(state)['inv_staleness/fc1']))
    # cold start at 0, then a swap at every boundary
    assert staleness == [s % N for s in range(3 * N)]
    assert max(staleness) == N - 1
    # the sliced refresh schedule is in-jit (lax.cond on the step
    # counter): the full cadence window rides one compiled program
    compile_pins.assert_compiled_once(step)


# ---------------------------------------------------- quarantine interaction


def test_quarantined_layer_shadow_discarded_at_swap():
    """A layer quarantined at the boundary keeps its ACTIVE
    decompositions — the in-flight shadow refresh is discarded, counted
    as a bad inversion; healthy layers swap normally."""
    from testing import faults

    sync, asy, run, params, batch = _dense_pair(
        'sliced', enums.ComputeMethod.EIGEN,
        health=health_lib.HealthConfig(warn=False),
    )
    del sync
    state = asy.init()
    step = jax.jit(asy.step)
    for i in range(2 * N):  # through the first swap, up to the next boundary
        (_, _), grads, stats = run(params, batch)
        state, _ = step(state, grads, stats)
        params = jax.tree.map(lambda p: p * 0.999, params)
    before = _decomps(state)
    # the boundary step's factor update quarantines fc1 (poisoned stats);
    # the swap in the same step must then discard fc1's finished shadow
    (_, _), grads, stats = run(params, batch)
    bad = faults.poison_stats(stats, 'fc1', side='a', kind='nan')
    state, _ = step(state, grads, bad)  # boundary: swap fires
    assert int(state.health.quarantined['fc1']) == 1
    after = _decomps(state)
    _bit_equal(
        {f: before[f].get('fc1') for f in ('qa', 'qg', 'da', 'dg')},
        {f: after[f].get('fc1') for f in ('qa', 'qg', 'da', 'dg')},
        'quarantined layer swapped its shadow',
    )
    assert float(np.abs(after['qa']['fc2'] - before['qa']['fc2']).max()) > 0
    assert int(state.health.bad_inv['fc1']) == 1
    assert int(state.health.bad_inv['fc2']) == 0


# ------------------------------------------------------ checkpoint round-trip


def test_checkpoint_midwindow_restore_deterministic(tmp_path):
    """Killing a run mid-window and restoring rebuilds the active
    decompositions synchronously and resets the shadow: deterministic,
    no torn slot, and the resumed run stays healthy."""
    _, asy, run, params, batch = _dense_pair(
        'sliced', enums.ComputeMethod.EIGEN
    )
    state = asy.init()
    step = jax.jit(asy.step)
    for i in range(N + 2):  # mid second window: shadow partially written
        (_, _), grads, stats = run(params, batch)
        state, _ = step(state, grads, stats)
    assert int(state.shadow.progress) > 0
    path = str(tmp_path / 'ck')
    checkpoint.save(path, state, engine=asy)

    r1, _ = checkpoint.restore(path, asy)
    r2, _ = checkpoint.restore(path, asy)
    _bit_equal(
        jax.tree.map(np.asarray, r1),
        jax.tree.map(np.asarray, r2),
        'mid-window restore is not deterministic',
    )
    # shadow is ephemeral: rebuilt empty, progress reset
    assert int(r1.shadow.progress) == 0
    for f in ('qa', 'qg', 'da', 'dg'):
        for v in getattr(r1.shadow, f).values():
            assert float(jnp.abs(v).max()) == 0.0
    # active slots rematerialized whole from the restored factors
    fresh = asy.update_inverses(r1)
    _bit_equal(
        _decomps(fresh), _decomps(r1),
        'restored active decompositions are torn',
    )
    # and the resumed run steps cleanly through the next boundary
    for i in range(N + 1):
        (_, _), grads, stats = run(params, batch)
        r1, pg = step(r1, grads, stats)
    assert all(
        bool(jnp.all(jnp.isfinite(v))) for v in jax.tree.leaves(pg)
    )


# -------------------------------------------------------------- Trainer paths


def _trainer(mode, **kw):
    import optax

    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=32, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(p, model_state, batch):
        xx, yy = batch
        pred = m.apply({'params': p}, xx)
        return jnp.mean((pred - yy) ** 2), model_state

    cfg = kfac_tpu.KFACPreconditioner(
        registry=reg, kl_clip=None, inv_update_steps=N,
        factor_update_steps=N, async_inverse=mode, **kw,
    )
    from kfac_tpu import training

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=cfg
    )
    return trainer, trainer.init(params), (x, y)


@pytest.mark.parametrize('mode', ['sliced', 'host'])
def test_trainer_step_path(mode):
    trainer, state, batch = _trainer(mode)
    losses = []
    for _ in range(2 * N + 1):  # across two swap boundaries
        state, loss = trainer.step(state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


@pytest.mark.parametrize('mode', ['sliced', 'host'])
def test_trainer_scan_path(mode):
    trainer, state, (x, y) = _trainer(mode)
    n = 2 * N + 1
    batches = (
        jnp.broadcast_to(x, (n,) + x.shape),
        jnp.broadcast_to(y, (n,) + y.shape),
    )
    state, losses = trainer.scan_steps(state, batches)
    assert bool(jnp.all(jnp.isfinite(losses)))
    assert int(state.kfac_state.step) == n


@pytest.mark.parametrize('mode', ['sliced', 'host'])
def test_trainer_accumulate_paths(mode):
    trainer, state, (x, y) = _trainer(mode)
    mbs = (x.reshape(2, 16, -1), y.reshape(2, 16, -1))
    for _ in range(N + 1):  # eager microbatch accumulation across a swap
        trainer.accumulate_microbatch(state, (mbs[0][0], mbs[1][0]))
        trainer.accumulate_microbatch(state, (mbs[0][1], mbs[1][1]))
        state, loss = trainer.apply_accumulated(state)
        assert bool(jnp.isfinite(loss))
    for _ in range(N + 1):  # compiled accumulation loop
        state, loss = trainer.step_accumulate_scan(state, mbs)
        assert bool(jnp.isfinite(loss))
    assert int(state.kfac_state.step) == 2 * (N + 1)
