"""Layout autotuner tests on the 8-virtual-device CPU mesh.

Covers the ISSUE acceptance surface: cost-model byte parity with
``observability.comms.comms_summary`` for all three KAISA strategies,
TunedPlan round-trip into an identical engine configuration, fingerprint
gating with the rate-limited fallback warning, model-only determinism,
HBM feasibility pruning, and the measured search (winner never worse
than the hand-configured strategy baselines).
"""

import json

import jax
import jax.numpy as jnp
import optax
import pytest

import kfac_tpu
from kfac_tpu import assignment, autotune, training
from kfac_tpu.autotune import model as model_lib
from kfac_tpu.autotune import plan as plan_lib
from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh
from kfac_tpu.warnings import LayoutPlanWarning, reset_layout_warnings
from testing import models

WORLD = 8


def _base(**kw):
    m = models.TinyModel(hidden=16, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=WORLD * 4, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg, damping=1e-3, **kw)
    loss_fn = models.mse_loss(m)
    return cfg, m, params, (x, y), loss_fn


# ------------------------------------------------------------ candidate grid


def test_candidate_fractions_follow_divisor_structure():
    assert assignment.candidate_fractions(8) == (1.0, 0.5, 0.25, 0.125)
    assert assignment.candidate_fractions(6) == (1.0, 0.5, 1 / 3, 1 / 6)
    assert assignment.candidate_fractions(1) == (1.0,)
    with pytest.raises(ValueError):
        assignment.candidate_fractions(0)
    # every fraction yields an integer worker count
    for f in assignment.candidate_fractions(12):
        assignment.grad_worker_count(12, f)


def test_enumerate_candidates_grid_and_baselines():
    cfg, *_ = _base()
    cands = autotune.enumerate_candidates(WORLD, cfg)
    # fractions x granularities x transports x one inverse cadence
    assert len(cands) == 4 * 4 * 2
    assert len(set(cands)) == len(cands)
    # MEM-OPT candidates always colocate (single owner holds both sides)
    for c in cands:
        if assignment.grad_worker_count(WORLD, c.grad_worker_fraction) == 1:
            assert c.colocate_factors
    bases = autotune.baseline_candidates(WORLD, cfg)
    assert [c.grad_worker_fraction for c in bases] == [1.0, 0.25, 0.125]
    # baselines reuse the base transport, so they dedup against the grid
    assert all(b in cands for b in bases)


# ----------------------------------------------- cost model vs comms_summary


@pytest.mark.parametrize('frac', [1.0, 0.5, 0.125])
def test_static_layout_byte_parity_with_engine(frac):
    """The model's layout must report the exact comms_summary() bytes the
    real engine does — the model prices the same layout it predicts."""
    cfg, *_ = _base()
    layout = model_lib.StaticLayout(cfg, WORLD, frac)
    eng = DistributedKFAC(
        config=cfg, mesh=kaisa_mesh(grad_worker_fraction=frac)
    )
    assert layout.comms_report() == eng.comms_report()


def test_predict_terms_present_and_consistent():
    cfg, *_ = _base()
    cand = model_lib.Candidate(grad_worker_fraction=0.5, bucket_granularity=64)
    row = model_lib.predict(cand, cfg, WORLD, model_lib.HardwareSpec())
    assert row['feasible'] and row['infeasible_reason'] is None
    assert row['predicted_step_s'] > 0
    mem = row['memory_per_device_bytes']
    assert mem['total'] == (
        mem['factors'] + mem['decomps'] + mem['grad_stacks']
    )
    for k in ('stat_transport', 'grad_broadcast', 'decomp_reshard'):
        assert row['bytes_per_occurrence'][k] >= 0
    # COMM-OPT's grads are already replicated: the broadcast payload is
    # reported (comms_summary parity) but never billed per step
    comm = model_lib.predict(
        model_lib.Candidate(grad_worker_fraction=1.0, bucket_granularity=64),
        cfg, WORLD, model_lib.HardwareSpec(),
    )
    occ = comm['bytes_per_occurrence']
    assert comm['bytes_per_step'] == (
        occ['stat_transport'] + occ['decomp_reshard']
    )
    occ = row['bytes_per_occurrence']
    assert row['bytes_per_step'] == (
        occ['stat_transport'] + occ['decomp_reshard'] + occ['grad_broadcast']
    )


def test_hbm_budget_prunes_and_exhaustion_raises():
    cfg, *_ = _base()
    tight = model_lib.HardwareSpec(hbm_bytes=1)  # nothing fits in 1 byte
    cand = model_lib.Candidate(grad_worker_fraction=1.0, bucket_granularity=1)
    row = model_lib.predict(cand, cfg, WORLD, tight)
    assert not row['feasible'] and 'memory' in row['infeasible_reason']
    with pytest.raises(ValueError, match='HBM budget'):
        autotune.autotune(cfg, measure=False, hardware=tight)


def test_hbm_pruning_consistent_with_xla_reported_memory():
    """Cross-check the cost model's HBM pruning against XLA's own memory
    accounting: drive the distributed step under compile-watch, read the
    compiled program's reported temp+output bytes, and assert a budget
    set to exactly that figure does NOT prune the layout the program
    implements — the model's persistent-state prediction must fit inside
    what XLA says the step actually touches."""
    cfg, _, params, batch, loss_fn = _base(compile_watch=True)
    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    eng = DistributedKFAC(config=cfg, mesh=mesh)
    run = kfac_tpu.CurvatureCapture(cfg.registry).value_stats_and_grad(loss_fn)
    (_, _), grads, stats = jax.jit(run)(params, batch)
    state = eng.init()
    state, _ = eng.watched('step')(state, grads, stats)
    jax.block_until_ready(state)

    snap = eng.compiled_memory_report()['dist_kfac.step']
    mem = snap['memory']
    assert mem is not None, 'CPU backend reports memory_analysis()'
    temp_out = mem['temp_size_in_bytes'] + mem['output_size_in_bytes']
    assert temp_out > 0

    cand = model_lib.Candidate(grad_worker_fraction=0.5, bucket_granularity=1)
    row = model_lib.predict(
        cand, cfg, WORLD, model_lib.HardwareSpec(hbm_bytes=float(temp_out)))
    # the layout the compiled program implements stays feasible under a
    # budget of exactly the XLA-reported transient+output footprint ...
    assert row['feasible'], row.get('infeasible_reason')
    assert row['memory_per_device_bytes']['total'] <= temp_out
    # ... while the same budget scaled far below the prediction prunes
    tight = model_lib.HardwareSpec(
        hbm_bytes=0.01 * row['memory_per_device_bytes']['total'])
    assert not model_lib.predict(cand, cfg, WORLD, tight)['feasible']


# ------------------------------------------------------------- plan artifact


def test_model_only_plan_is_deterministic():
    cfg, *_ = _base()
    p1 = autotune.autotune(cfg, measure=False)
    p2 = autotune.autotune(cfg, measure=False)
    assert p1.to_json() == p2.to_json()
    assert p1.winner['picked_by'] == 'model'
    # cost table is ranked: feasible rows ascending by predicted cost
    preds = [r['predicted_step_s'] for r in p1.cost_table if r['feasible']]
    assert preds == sorted(preds)
    # serialized form is stable too (sorted keys, no timestamps)
    assert json.dumps(p1.to_json(), sort_keys=True) == json.dumps(
        p2.to_json(), sort_keys=True
    )


def test_plan_roundtrip_reproduces_engine_config(tmp_path):
    cfg, *_ = _base()
    plan = autotune.autotune(cfg, measure=False)
    path = tmp_path / 'plan.json'
    plan.save(path)
    loaded = kfac_tpu.TunedPlan.load(path)
    assert loaded.to_json() == plan.to_json()

    eng = DistributedKFAC(config=cfg, auto_layout=str(path))
    assert eng.auto_layout_applied
    frac = plan.knobs['grad_worker_fraction']
    ref = DistributedKFAC(
        config=autotune.apply_knobs(cfg, plan.knobs),
        mesh=kaisa_mesh(grad_worker_fraction=frac),
    )
    assert eng.describe() == ref.describe()
    assert eng.comms_report() == ref.comms_report()
    assert eng.granularity == plan.knobs['bucket_granularity']
    # the plan object and the raw dict apply identically
    eng2 = DistributedKFAC(config=cfg, auto_layout=plan.to_json())
    assert eng2.auto_layout_applied
    assert eng2.describe() == eng.describe()


def test_from_json_validates_schema():
    cfg, *_ = _base()
    good = autotune.autotune(cfg, measure=False).to_json()
    with pytest.raises(ValueError, match='schema'):
        kfac_tpu.TunedPlan.from_json(dict(good, schema=999))
    missing = dict(good)
    del missing['winner']
    with pytest.raises(ValueError, match='winner'):
        kfac_tpu.TunedPlan.from_json(missing)
    with pytest.raises(ValueError, match='unknown'):
        kfac_tpu.TunedPlan.from_json(dict(good, extra=1))
    bad_knobs = dict(good, knobs={'strategy': 'COMM_OPT'})
    with pytest.raises(ValueError):
        kfac_tpu.TunedPlan.from_json(bad_knobs)


def test_fingerprint_mismatch_falls_back_with_one_warning():
    cfg, *_ = _base()
    plan = autotune.autotune(cfg, measure=False).to_json()
    plan['fingerprint'] = dict(plan['fingerprint'], device_count=4096)
    reset_layout_warnings()
    with pytest.warns(LayoutPlanWarning):
        eng = DistributedKFAC(config=cfg, auto_layout=plan)
    assert not eng.auto_layout_applied
    # fell back to the explicit/default layout: full COMM-OPT mesh
    assert eng.grad_workers == WORLD
    # the warning is rate-limited: same cause never re-warns...
    import warnings as pywarnings

    with pywarnings.catch_warnings(record=True) as rec:
        pywarnings.simplefilter('always')
        eng2 = DistributedKFAC(config=cfg, auto_layout=plan)
    assert not eng2.auto_layout_applied
    assert not [r for r in rec if isinstance(r.message, LayoutPlanWarning)]
    # ...until reset (test isolation hook)
    reset_layout_warnings()
    with pytest.warns(LayoutPlanWarning):
        DistributedKFAC(config=cfg, auto_layout=plan)


def test_fingerprint_diff_reports_both_directions():
    cfg, *_ = _base()
    current = plan_lib.plan_fingerprint(cfg.registry)
    # a plan from an OLDER writer: one field doctored, one field the
    # current fingerprint carries missing entirely, and one extra field
    # only the plan has — the diff must surface all three
    stale = json.loads(json.dumps(current))
    stale['device_count'] = 4096
    missing = sorted(set(stale) - {'layers'})[0]
    del stale[missing]
    stale['legacy_only_field'] = 1
    diff = plan_lib.fingerprint_diff(stale, current)
    assert 'device_count' in diff
    assert missing in diff  # current-only key (old one-sided scan got this)
    assert 'legacy_only_field' in diff  # plan-only key (it missed this)
    assert diff == sorted(diff)
    # identical fingerprints (JSON-normalized tuples included) diff empty
    assert plan_lib.fingerprint_diff(current, json.loads(
        json.dumps(current))) == []
    # and the resolve-time warning names the plan-only key too
    doctored = autotune.autotune(cfg, measure=False).to_json()
    doctored['fingerprint']['legacy_only_field'] = 1
    reset_layout_warnings()
    with pytest.warns(LayoutPlanWarning, match='legacy_only_field'):
        eng = DistributedKFAC(config=cfg, auto_layout=doctored)
    assert not eng.auto_layout_applied
    reset_layout_warnings()


def test_model_fingerprint_mismatch_rejected():
    cfg, *_ = _base()
    plan = autotune.autotune(cfg, measure=False)
    other_cfg, *_ = _base()
    doctored = plan.to_json()
    doctored['fingerprint']['layers'] = {'not_my_model': [3, 3]}
    reset_layout_warnings()
    with pytest.warns(LayoutPlanWarning, match='fingerprint'):
        eng = DistributedKFAC(config=other_cfg, auto_layout=doctored)
    assert not eng.auto_layout_applied


# ------------------------------------------------------------ measured search


def test_measured_winner_not_worse_than_strategy_baselines():
    cfg, m, params, batch, loss_fn = _base(
        factor_update_steps=1, inv_update_steps=1
    )
    plan = autotune.autotune(
        cfg, loss_fn, params, batch,
        top_k=1, warmup=0, iters=1, granularities=(1,),
    )
    assert plan.winner['picked_by'] == 'measured'
    measured = {
        r['knobs']['strategy']: r['measured_step_s']
        for r in plan.cost_table if r['measured']
    }
    # all three hand-configured strategies were actually timed
    assert {'COMM_OPT', 'HYBRID_OPT', 'MEM_OPT'} <= set(measured)
    assert plan.winner['measured_step_s'] == min(measured.values())
    # the plan drives a real engine end to end
    eng = DistributedKFAC(config=cfg, auto_layout=plan)
    assert eng.auto_layout_applied
    state = eng.init()
    run = kfac_tpu.CurvatureCapture(cfg.registry).value_stats_and_grad(
        loss_fn
    )
    (loss, _), grads, stats = run(params, batch)
    state, pgrads = eng.step(state, grads, stats, loss=loss)
    assert all(
        bool(jnp.all(jnp.isfinite(v)))
        for v in jax.tree_util.tree_leaves(pgrads)
    )


def test_trainer_auto_layout_wiring(tmp_path):
    cfg, m, params, (x, y), _ = _base(lr=0.05)
    plan = autotune.autotune(cfg, measure=False)
    path = tmp_path / 'plan.json'
    plan.save(path)

    def loss_fn(p, model_state, batch):
        xx, yy = batch
        pred = m.apply({'params': p}, xx)
        return jnp.mean((pred - yy) ** 2), model_state

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=cfg,
        auto_layout=str(path),
    )
    assert trainer.kfac.auto_layout_applied
    state = trainer.init(params)
    losses = []
    for _ in range(3):
        state, loss = trainer.step(state, (x, y))
        losses.append(float(loss))
    assert all(jnp.isfinite(jnp.asarray(losses)))

    # guard rails: a plan needs a config to configure...
    with pytest.raises(ValueError, match='requires kfac'):
        training.Trainer(
            loss_fn=loss_fn, optimizer=optax.sgd(0.05),
            auto_layout=str(path),
        )
    # ...and a bare config, not an already-built engine
    eng = DistributedKFAC(config=cfg)
    with pytest.raises(ValueError, match='bare'):
        training.Trainer(
            loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=eng,
            auto_layout=str(path),
        )


# --------------------------------------------------------- async refresh knob


def test_predict_prices_async_refresh_spike():
    """The model prices the refresh spike the async backends flatten:
    sliced divides the boundary spike by the slice count, host drops the
    device decomposition FLOPs and pays only the payload transfer."""
    cfg, *_ = _base()
    hw = model_lib.HardwareSpec()

    def row(mode):
        cand = model_lib.Candidate(
            grad_worker_fraction=0.5, bucket_granularity=64,
            inv_update_steps=4, async_inverse=mode,
        )
        return model_lib.predict(cand, cfg, WORLD, hw)

    sync, sliced, host = row(None), row('sliced'), row('host')
    assert sync['refresh_spike_s'] > 0
    # sliced: same total device work, spread over the window's slices
    assert (
        sliced['flops_per_device_per_step']
        == sync['flops_per_device_per_step']
    )
    assert sliced['refresh_spike_s'] < sync['refresh_spike_s']
    # host: decomposition FLOPs leave the device entirely; the spike is
    # the boundary device_put of the refreshed payload
    assert (
        host['flops_per_device_per_step'] < sync['flops_per_device_per_step']
    )
    assert host['refresh_spike_s'] == (
        sync['bytes_per_occurrence']['decomp_reshard'] / hw.host_bandwidth
    )
    for r in (sync, sliced, host):
        assert r['predicted_step_s'] > 0


def test_async_base_widens_inverse_cadence_grid():
    cfg, *_ = _base(
        factor_update_steps=2, inv_update_steps=2, async_inverse='sliced'
    )
    cands = autotune.enumerate_candidates(WORLD, cfg)
    # fractions x granularities x transports x {c, 2c, 4c}
    assert len(cands) == 4 * 4 * 2 * 3
    assert {c.inv_update_steps for c in cands} == {2, 4, 8}
    assert all(c.async_inverse == 'sliced' for c in cands)
    bases = autotune.baseline_candidates(WORLD, cfg)
    assert all(b.async_inverse == 'sliced' for b in bases)
    assert all(b in cands for b in bases)
    # a sync base keeps the original one-cadence grid
    sync_cfg, *_ = _base()
    assert len(autotune.enumerate_candidates(WORLD, sync_cfg)) == 4 * 4 * 2


def test_async_knob_rides_the_plan_roundtrip(tmp_path):
    cfg, *_ = _base(inv_update_steps=2, async_inverse='host')
    plan = autotune.autotune(cfg, measure=False)
    assert plan.knobs['async_inverse'] == 'host'
    path = tmp_path / 'plan.json'
    plan.save(path)
    loaded = kfac_tpu.TunedPlan.load(path)
    new = autotune.apply_knobs(cfg, loaded.knobs)
    assert new.async_inverse == kfac_tpu.AsyncInverseConfig(mode='host')


def test_pre_async_plan_document_still_loads():
    """Plans written before the async knob existed lack
    ``knobs.async_inverse``; loading fills the sync default."""
    cfg, *_ = _base()
    doc = autotune.autotune(cfg, measure=False).to_json()
    legacy = json.loads(json.dumps(doc))
    del legacy['knobs']['async_inverse']
    loaded = kfac_tpu.TunedPlan.from_json(legacy)
    assert loaded.knobs['async_inverse'] is None
    applied = autotune.apply_knobs(cfg, loaded.knobs)
    assert applied.async_inverse is None


def test_apply_knobs_only_touches_layout_fields():
    cfg, *_ = _base()
    plan = autotune.autotune(cfg, measure=False)
    new = autotune.apply_knobs(cfg, plan.knobs)
    assert new.bucket_granularity == plan.knobs['bucket_granularity']
    assert new.allreduce_method.name == plan.knobs['allreduce_method']
    assert new.colocate_factors == plan.knobs['colocate_factors']
    # non-layout fields ride through untouched
    assert new.damping == cfg.damping
    assert new.registry is cfg.registry
