"""Trainability-mask tests: a frozen layer vanishes from every surface.

The contract (docs/ARCHITECTURE.md "Trainability masks"): masking IS
registry removal — a mask-frozen layer gets no capture taps, no factor
state, no engine slots, no metrics keys, and its gradients pass through
the preconditioner bit-identically. ``mask=None`` is pinned as the exact
identity so existing configs cannot drift.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import kfac_tpu
from kfac_tpu import health as health_lib
from kfac_tpu.layers import registry as registry_lib
from kfac_tpu.models import LoRADense
from kfac_tpu.observability import metrics as metrics_lib
from testing import models


def _setup():
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=32, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    return m, params, (x, y), reg, models.mse_loss(m)


def _pgrads(reg, params, batch, loss_fn, **kw):
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None, **kw)
    cap = kfac_tpu.CurvatureCapture(kfac.registry)
    _, grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    state = kfac.init()
    state, pgrads = kfac.step(state, grads, stats)
    return kfac, state, grads, pgrads


def test_mask_none_is_identity():
    _, params, batch, reg, loss_fn = _setup()
    assert registry_lib.masked_registry(reg, None) is reg
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, mask=None)
    assert kfac.registry is reg
    # and the preconditioned gradients are pinned bit-identical
    _, _, _, base = _pgrads(reg, params, batch, loss_fn)
    _, _, _, masked = _pgrads(reg, params, batch, loss_fn, mask=None)
    jax.tree_util.tree_map(np.testing.assert_array_equal, base, masked)


def test_frozen_layer_dropped_everywhere():
    _, params, batch, reg, loss_fn = _setup()
    mask = {'fc2': False}
    kfac, state, grads, pgrads = _pgrads(
        reg, params, batch, loss_fn, mask=mask,
        health=health_lib.HealthConfig(warn=False),
        metrics=kfac_tpu.MetricsConfig(),
    )
    # registry: dropped, taps and all
    assert sorted(kfac.registry.layers) == ['fc1']
    # factor state: no slot at all, not an untouched identity
    assert 'fc2' not in state.a and 'fc2' not in state.g
    # health + metrics schemas: keyed off the masked registry
    assert all('fc2' not in k for k in state.health.quarantined)
    names = list(kfac.registry.layers)
    for key in metrics_lib.metric_keys(kfac.metrics, names):
        assert 'fc2' not in key
    for key in health_lib.health_metric_keys(names):
        assert 'fc2' not in key
    # gradients: frozen layer's pass through bit-identically
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, grads['fc2'], pgrads['fc2']
    )
    # ...while the trainable layer is actually preconditioned
    assert float(jnp.abs(pgrads['fc1']['kernel'] - grads['fc1']['kernel']).max()) > 0


def test_mask_matches_skip_layers_exactly():
    """mask-frozen and never-registered produce the same preconditioning."""
    m, params, batch, reg, loss_fn = _setup()
    _, _, _, via_mask = _pgrads(reg, params, batch, loss_fn, mask={'fc2': False})
    reg_skip = kfac_tpu.register_model(m, batch[0], skip_layers=['fc2'])
    _, _, _, via_skip = _pgrads(reg_skip, params, batch, loss_fn)
    jax.tree_util.tree_map(np.testing.assert_array_equal, via_mask, via_skip)


def test_register_model_mask_kwarg_equals_masked_registry():
    m, _, batch, reg, _ = _setup()
    mask = {'fc1': False}
    direct = kfac_tpu.register_model(m, batch[0], mask=mask)
    wrapped = registry_lib.masked_registry(reg, mask)
    assert sorted(direct.layers) == sorted(wrapped.layers) == ['fc2']
    assert direct.param_paths == wrapped.param_paths


def test_mask_prefix_semantics():
    _, _, batch, reg, _ = _setup()
    # a bool at a prefix covers the subtree; unmentioned paths stay
    assert sorted(registry_lib.masked_registry(reg, {'fc1': False}).layers) == ['fc2']
    # a uniform-leaf subtree works like the covering bool
    masked = registry_lib.masked_registry(
        reg, {'fc1': {'kernel': False, 'bias': False}}
    )
    assert sorted(masked.layers) == ['fc2']
    # freezing everything is legal at the registry level (the engine
    # refuses an empty registry elsewhere)
    assert registry_lib.masked_registry(reg, False).layers == {}


def test_mask_splitting_a_layer_raises():
    _, _, _, reg, _ = _setup()
    with pytest.raises(ValueError, match='splits layer'):
        registry_lib.masked_registry(
            reg, {'fc1': {'kernel': False, 'bias': True}}
        )


def test_mask_bad_node_type_raises():
    _, _, _, reg, _ = _setup()
    with pytest.raises(TypeError, match='expected a bool or a mapping'):
        registry_lib.masked_registry(reg, 0.5)


def test_lora_unit_adapters_must_agree():
    class M(models.nn.Module):
        @models.nn.compact
        def __call__(self, x):
            return LoRADense(features=4, rank=2, name='lora')(x)

    m = M()
    x = jnp.ones((4, 6))
    reg = kfac_tpu.register_model(m, x)
    with pytest.raises(ValueError, match='one adapter'):
        registry_lib.masked_registry(reg, {'lora': {'down': False}})
    # freezing the (never-registered) base does NOT freeze the unit
    kept = registry_lib.masked_registry(reg, {'lora': {'base': False}})
    assert sorted(kept.layers) == ['lora']
    assert sorted(kept.taps) == ['lora/down', 'lora/up']
    # freezing both adapters drops the unit and its taps together
    dropped = registry_lib.masked_registry(
        reg, {'lora': {'down': False, 'up': False}}
    )
    assert dropped.layers == {} and dropped.taps == {}
