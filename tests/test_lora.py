"""LoRA-unit K-FAC tests (kfac_tpu/models/lora.py + layers.LoRAHelper).

The unit contract: a ``LoRADense`` registers as ONE fused unit with
block-diagonal Kronecker factors over its adapter pair, captured through
per-role taps (``Registry.taps``). Block-diagonal factors invert
block-wise and the packed gradient is block-diagonal too, so the unit's
preconditioned result must be EXACTLY two-layer K-FAC over the adapters —
that equivalence is tested in closed form below.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import kfac_tpu
from kfac_tpu import training
from kfac_tpu.layers import helpers as helpers_lib
from kfac_tpu.models import LoRADense
from kfac_tpu.ops import cov

D_IN, RANK, D_OUT = 6, 2, 4


class OneUnit(nn.Module):
    @nn.compact
    def __call__(self, x):
        return LoRADense(features=D_OUT, rank=RANK, name='lora')(x)


@pytest.fixture(scope='module')
def unit():
    """One registered LoRA unit shared module-wide: registration tracing
    and the capture compile are the costly part, and no test mutates it."""
    m = OneUnit()
    x = jax.random.normal(jax.random.PRNGKey(1), (16, D_IN))
    y = jax.random.normal(jax.random.PRNGKey(2), (16, D_OUT))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(p, b):
        xx, yy = b
        return jnp.mean((m.apply({'params': p}, xx) - yy) ** 2)

    return m, params, (x, y), reg, loss_fn


def test_unit_registration(unit):
    _, params, _, reg, _ = unit
    assert sorted(reg.layers) == ['lora']
    h = reg.layers['lora']
    assert isinstance(h, helpers_lib.LoRAHelper)
    assert h.a_factor_shape == (D_IN + RANK, D_IN + RANK)
    assert h.g_factor_shape == (RANK + D_OUT, RANK + D_OUT)
    assert reg.taps == {'lora/down': ('lora', 'down'), 'lora/up': ('lora', 'up')}
    # base/down/up children are the unit's, never registered separately
    assert sorted(params['lora']) == ['base', 'down', 'up']
    # at zero-init of up, the module computes exactly base(x)
    m = OneUnit()
    x = jax.random.normal(jax.random.PRNGKey(3), (8, D_IN))
    full = m.apply({'params': params}, x)
    base_only = (
        x @ params['lora']['base']['kernel'] + params['lora']['base']['bias']
    )
    np.testing.assert_allclose(full, base_only, rtol=1e-6)


def test_captured_factors_are_block_diagonal(unit):
    _, params, batch, reg, loss_fn = unit
    cap = kfac_tpu.CurvatureCapture(reg)
    _, _, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    a = np.asarray(stats.a['lora'])
    g = np.asarray(stats.g['lora'])
    # cross-adapter covariance blocks are the documented, zeroed approx
    np.testing.assert_array_equal(a[:D_IN, D_IN:], 0)
    np.testing.assert_array_equal(a[D_IN:, :D_IN], 0)
    np.testing.assert_array_equal(g[:RANK, RANK:], 0)
    np.testing.assert_array_equal(g[RANK:, :RANK], 0)
    # the down block of A is the plain dense A factor of the unit's input
    x = batch[0]
    expected = np.asarray(cov.linear_a_factor(x, has_bias=False))
    np.testing.assert_allclose(a[:D_IN, :D_IN], expected, rtol=1e-5, atol=1e-6)
    # up's input is down(x)
    h = x @ params['lora']['down']['kernel']
    expected_up = np.asarray(cov.linear_a_factor(h, has_bias=False))
    np.testing.assert_allclose(a[D_IN:, D_IN:], expected_up, rtol=1e-5, atol=1e-6)
    # zero-init up kernel: every down cotangent is identically zero, and
    # the routed normalization keeps that dead G block exactly zero
    np.testing.assert_array_equal(g[:RANK, :RANK], 0)
    assert float(np.abs(g[RANK:, RANK:]).max()) > 0


def test_unit_preconditioning_equals_two_layer_kfac():
    """Closed form: block-diag factor solve == per-adapter dense solves."""
    rng = np.random.default_rng(0)

    def spd(n):
        m = rng.standard_normal((n, n))
        return m @ m.T + n * np.eye(n)

    a_down, a_up = spd(D_IN), spd(RANK)
    g_down, g_up = spd(RANK), spd(D_OUT)
    w_down = rng.standard_normal((RANK, D_IN))   # packed (out, in) form
    w_up = rng.standard_normal((D_OUT, RANK))
    damping = 0.1

    h = helpers_lib.LoRAHelper(
        name='lora', has_bias=False,
        in_features=D_IN, rank=RANK, out_features=D_OUT,
    )
    grads = {
        'down': {'kernel': jnp.asarray(w_down.T)},
        'up': {'kernel': jnp.asarray(w_up.T)},
    }
    mat = np.asarray(h.grads_to_matrix(grads))
    a = np.zeros((D_IN + RANK,) * 2)
    a[:D_IN, :D_IN], a[D_IN:, D_IN:] = a_down, a_up
    g = np.zeros((RANK + D_OUT,) * 2)
    g[:RANK, :RANK], g[RANK:, RANK:] = g_down, g_up

    def solve(gf, wf, af):
        lam = np.sqrt(damping)
        gi = np.linalg.inv(gf + lam * np.eye(len(gf)))
        ai = np.linalg.inv(af + lam * np.eye(len(af)))
        return gi @ wf @ ai

    unit = solve(g, mat, a)
    out = h.matrix_to_grads(jnp.asarray(unit))
    # the helper packs through jnp float32; the reference solves run in
    # float64 — compare at float32 precision
    np.testing.assert_allclose(
        np.asarray(out['down']['kernel']).T, solve(g_down, w_down, a_down),
        rtol=1e-4, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(out['up']['kernel']).T, solve(g_up, w_up, a_up),
        rtol=1e-4, atol=1e-6,
    )


def test_lora_training_decreases_loss(unit):
    """Frozen-base LoRA fine-tune through the Trainer: the full routed
    capture -> block factors -> precondition -> mask pipeline."""
    m, params, (x, y), _, _ = unit
    mask = {'lora': {'base': False}}
    reg = kfac_tpu.register_model(m, x, mask=mask)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, lr=0.1, damping=1e-3,
        factor_update_steps=1, inv_update_steps=5,
    )
    labels = jax.tree_util.tree_map_with_path(
        lambda path, _: 'frozen'
        if 'base' in [getattr(k, 'key', '') for k in path]
        else 'train',
        params,
    )
    optimizer = optax.multi_transform(
        {'train': optax.sgd(0.1), 'frozen': optax.set_to_zero()}, labels
    )

    def loss_fn(p, ms, b):
        xx, yy = b
        return jnp.mean((m.apply({'params': p}, xx) - yy) ** 2), ms

    tr = training.Trainer(loss_fn=loss_fn, optimizer=optimizer, kfac=kfac)
    st = tr.init(params, None)
    st, first = tr.step(st, (x, y))
    for _ in range(19):
        st, last = tr.step(st, (x, y))
    assert float(last) < float(first)
    # the frozen base never moved; the adapters did
    np.testing.assert_array_equal(
        st.params['lora']['base']['kernel'], params['lora']['base']['kernel']
    )
    assert float(jnp.abs(st.params['lora']['up']['kernel']).max()) > 0
