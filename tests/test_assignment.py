"""Property tests for the KAISA assignment engine.

Behavioral targets from the reference's table-driven suite
(tests/assignment_test.py:61-541): grid partition properties, greedy balance,
worker/receiver group consistency — expressed as properties over sweeps of
world sizes and fractions rather than literal tables.
"""

import pytest

from kfac_tpu import assignment, enums


def _work(n_layers, base=10.0):
    return {
        f'layer{i}': {'A': base * (i + 1), 'G': base * (i + 1) / 2}
        for i in range(n_layers)
    }


@pytest.mark.parametrize('world,workers', [(8, 2), (8, 8), (8, 1), (4, 2), (12, 3)])
def test_grid_partitions_cover_world(world, workers):
    cols = assignment.partition_grad_workers(world, workers)
    rows = assignment.partition_grad_receivers(world, workers)
    assert sorted(d for c in cols for d in c) == list(range(world))
    assert sorted(d for r in rows for d in r) == list(range(world))
    assert all(len(c) == workers for c in cols)
    assert all(len(r) == world // workers for r in rows)
    # every (row, col) pair intersects in exactly one device
    for r in rows:
        for c in cols:
            assert len(set(r) & set(c)) == 1


def test_grid_example_from_kaisa_paper():
    # world 8, 2 grad workers: columns [0,4],[1,5],[2,6],[3,7]; rows
    # [0..3],[4..7] (reference docstring example kfac/assignment.py:330-342)
    cols = assignment.partition_grad_workers(8, 2)
    assert cols == [(0, 4), (1, 5), (2, 6), (3, 7)]
    rows = assignment.partition_grad_receivers(8, 2)
    assert rows == [(0, 1, 2, 3), (4, 5, 6, 7)]


def test_grid_rejects_nondivisible():
    with pytest.raises(ValueError):
        assignment.partition_grad_workers(8, 3)


@pytest.mark.parametrize(
    'world,frac,expected',
    [
        (8, 1.0, enums.DistributedStrategy.COMM_OPT),
        (8, 0.0, enums.DistributedStrategy.MEM_OPT),
        (8, 1 / 8, enums.DistributedStrategy.MEM_OPT),
        (8, 0.5, enums.DistributedStrategy.HYBRID_OPT),
        (8, 0.25, enums.DistributedStrategy.HYBRID_OPT),
        (1, 1.0, enums.DistributedStrategy.COMM_OPT),
    ],
)
def test_fraction_to_strategy(world, frac, expected):
    assert assignment.strategy_for_fraction(world, frac) == expected


def test_fraction_validation():
    with pytest.raises(ValueError):
        assignment.grad_worker_count(8, 0.3)  # 2.4 workers
    with pytest.raises(ValueError):
        assignment.grad_worker_count(8, -0.1)
    with pytest.raises(ValueError):
        assignment.grad_worker_count(8, 1.1)
    # 8 * 0.75 = 6 is an integer but does not divide 8
    with pytest.raises(ValueError):
        assignment.grad_worker_count(8, 0.75)


def test_greedy_assignment_balances_uniform_work():
    work = {f'l{i}': {'A': 1.0, 'G': 1.0} for i in range(16)}
    groups = [tuple(range(4))]
    placement = assignment.greedy_assign(work, groups, 4, colocate_factors=True)
    loads = [0.0] * 4
    for layer, factors in placement.items():
        for f, d in factors.items():
            loads[d] += work[layer][f]
    assert max(loads) == min(loads)  # 16 equal layers over 4 devices


def test_greedy_colocation():
    work = _work(6)
    placement = assignment.greedy_assign(
        work, [tuple(range(4))], 4, colocate_factors=True
    )
    for layer, factors in placement.items():
        assert factors['A'] == factors['G']


def test_greedy_no_colocation_spreads_within_group():
    work = {'big': {'A': 100.0, 'G': 100.0}}
    placement = assignment.greedy_assign(
        work, [(0, 1)], 2, colocate_factors=False
    )
    # two equal factors, two idle devices in the group: one each
    assert {placement['big']['A'], placement['big']['G']} == {0, 1}


def test_greedy_respects_group_constraint():
    work = _work(8)
    groups = [(0, 2), (1, 3)]  # columns of a 2x2 grid
    placement = assignment.greedy_assign(work, groups, 4, colocate_factors=False)
    for layer, factors in placement.items():
        devs = set(factors.values())
        assert devs <= {0, 2} or devs <= {1, 3}


def test_greedy_deterministic():
    work = _work(10)
    a = assignment.greedy_assign(work, [(0, 1), (2, 3)], 4, True)
    b = assignment.greedy_assign(work, [(0, 1), (2, 3)], 4, True)
    assert a == b


@pytest.mark.parametrize('world,frac', [(8, 1.0), (8, 0.5), (8, 0.25), (8, 1 / 8), (4, 0.5), (1, 1.0)])
def test_kaisa_assignment_consistency(world, frac):
    kaisa = assignment.KAISAAssignment(
        _work(7), world_size=world, grad_worker_fraction=frac
    )
    m, n = kaisa.mesh_shape()
    assert m * n == world
    for layer in kaisa.get_layers():
        col = kaisa.grad_worker_group(layer)
        assert len(col) == kaisa.grad_workers
        for factor in kaisa.get_factors(layer):
            assert kaisa.inv_worker(layer, factor) in col
        for dev in range(world):
            row = kaisa.grad_receiver_group(dev, layer)
            assert dev in row
            src = kaisa.src_grad_worker(dev, layer)
            # the source sits in both this device's row and the layer column
            assert src in row and src in col
            if kaisa.is_grad_worker(dev, layer):
                assert src == dev
        # every device is either a grad worker or receives from one
        workers = [d for d in range(world) if kaisa.is_grad_worker(d, layer)]
        assert len(workers) == kaisa.grad_workers


def test_comm_opt_no_grad_broadcast_mem_opt_no_inv_broadcast():
    comm = assignment.KAISAAssignment(_work(3), world_size=4, grad_worker_fraction=1.0)
    assert not comm.broadcast_gradients() and comm.broadcast_inverses()
    mem = assignment.KAISAAssignment(_work(3), world_size=4, grad_worker_fraction=0.0)
    assert mem.broadcast_gradients() and not mem.broadcast_inverses()
    hybrid = assignment.KAISAAssignment(_work(3), world_size=4, grad_worker_fraction=0.5)
    assert hybrid.broadcast_gradients() and hybrid.broadcast_inverses()


def test_mem_opt_requires_colocation():
    with pytest.raises(ValueError):
        assignment.KAISAAssignment(
            _work(3), world_size=4, grad_worker_fraction=0.0, colocate_factors=False
        )


def test_world_size_one_trivial():
    kaisa = assignment.KAISAAssignment(_work(3), world_size=1, grad_worker_fraction=1.0)
    for layer in kaisa.get_layers():
        assert kaisa.grad_worker_group(layer) == (0,)
        assert kaisa.inv_worker(layer, 'A') == 0
        assert kaisa.src_grad_worker(0, layer) == 0
    assert not kaisa.broadcast_gradients()
    assert not kaisa.broadcast_inverses()


def test_compute_work_costs_cubic_vs_quadratic():
    class H:
        a_factor_shape = (10, 10)
        g_factor_shape = (4, 4)

    costs = assignment.compute_work_costs({'l': H()})
    assert costs == {'l': {'A': 1000.0, 'G': 64.0}}
    costs_mem = assignment.compute_work_costs(
        {'l': H()}, enums.AssignmentStrategy.MEMORY
    )
    assert costs_mem == {'l': {'A': 100.0, 'G': 16.0}}


def test_greedy_balance_quality():
    """Greedy keeps the max/mean load ratio modest on heterogeneous work."""
    import random

    rng = random.Random(0)
    work = {
        f'l{i}': {'A': float(rng.randint(1, 100)) ** 3, 'G': float(rng.randint(1, 100)) ** 3}
        for i in range(40)
    }
    kaisa = assignment.KAISAAssignment(work, world_size=8, grad_worker_fraction=0.5)
    loads = [0.0] * 8
    for layer in kaisa.get_layers():
        for f in kaisa.get_factors(layer):
            loads[kaisa.inv_worker(layer, f)] += work[layer][f]
    mean = sum(loads) / len(loads)
    assert max(loads) < 2.0 * mean


def test_small_nonzero_fraction_rejected():
    """Fractions that are neither 0 nor produce an integer count must raise
    (a typo like 0.05 for 0.5 should not silently become MEM-OPT)."""
    with pytest.raises(ValueError):
        assignment.grad_worker_count(8, 0.05)
    assert assignment.grad_worker_count(8, 0.0) == 1
