"""Measurement truth layer: the one-dispatch microbench harness, the
latency-floor detector, and the dispatch-threshold artifact.

All CPU-runnable: the harness's fori_loop and legacy dispatch modes are
the SAME chained math (pinned by equivalence here), so everything but
the absolute numbers is testable off-chip. See docs/OBSERVABILITY.md
"Measurement truth".
"""

import json
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from kfac_tpu.ops import dispatch_tables

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), '..', 'tools'))
)
import tpu_microbench as mb  # noqa: E402

import bench  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_tables(monkeypatch):
    """Each test sees the real committed artifact unless it overrides
    the env var itself; the cache never leaks across tests."""
    monkeypatch.delenv(dispatch_tables.ENV_VAR, raising=False)
    dispatch_tables.invalidate_cache()
    yield
    dispatch_tables.invalidate_cache()


# ------------------------------------------------------ harness equivalence


def test_chain_result_fori_equals_legacy():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(16, 16)),
                    jnp.float32)

    def fn(a):
        return a @ a.T * 0.5 + 1.0

    fori = mb.chain_result(fn, x, iters=4, warmup=2, mode='fori_loop')
    legacy = mb.chain_result(fn, x, iters=4, warmup=2, mode='legacy')
    np.testing.assert_allclose(np.asarray(fori), np.asarray(legacy),
                               rtol=1e-5, atol=1e-5)


def test_chain_result_equivalence_pytree_multi_arg():
    rng = np.random.default_rng(1)
    tree = {
        'a': jnp.asarray(rng.normal(size=(8, 8)), jnp.float32),
        'ids': jnp.arange(8),  # int leaf must pass through unscaled
    }
    damping = jnp.float32(0.1)

    def fn(t, d):
        return {'y': t['a'] * (1.0 + d), 'z': jnp.sum(t['a'], axis=0)}

    fori = mb.chain_result(fn, tree, damping, iters=3, mode='fori_loop')
    legacy = mb.chain_result(fn, tree, damping, iters=3, mode='legacy')
    for k in ('y', 'z'):
        np.testing.assert_allclose(np.asarray(fori[k]),
                                   np.asarray(legacy[k]),
                                   rtol=1e-5, atol=1e-5)


def test_chain_is_a_real_dependency():
    """Successive iterations must produce different values (the perturbed
    scale) — a memoizable constant chain would defeat the measurement."""
    x = jnp.ones((4, 4), jnp.float32)
    one = mb.chain_result(lambda a: a * 2.0, x, iters=1, mode='legacy')
    two = mb.chain_result(lambda a: a * 2.0, x, iters=2, mode='legacy')
    assert not np.allclose(np.asarray(one), np.asarray(two))


# ----------------------------------------------------------- timeit contract


def test_timeit_fori_is_one_dispatch():
    x = jnp.ones((8, 8), jnp.float32)
    t = mb.timeit(lambda a: a @ a, x, iters=5, mode='fori_loop')
    assert isinstance(t, mb.Timing)
    assert float(t) > 0.0
    assert t.provenance == {
        'harness_version': mb.HARNESS_VERSION,
        'dispatch_mode': 'fori_loop',
        'dispatches': 1,
        'iters': 5,
    }


def test_timeit_legacy_mode_counts_dispatches():
    x = jnp.ones((8, 8), jnp.float32)
    t = mb.timeit(lambda a: a @ a, x, iters=4, mode='legacy')
    assert t.provenance['dispatch_mode'] == 'legacy'
    assert t.provenance['dispatches'] == 4


def test_timeit_falls_back_when_fn_cannot_trace():
    """AOT executables / host-round-trip callables can't run under jit:
    the harness must degrade to the legacy host loop, and say so."""
    x = jnp.ones((4, 4), jnp.float32)

    def untraceable(a):
        return jnp.asarray(np.asarray(a) * 2.0)  # concretizes: no tracers

    t = mb.timeit(untraceable, x, iters=3, mode='fori_loop')
    assert t.provenance['dispatch_mode'] == 'legacy'
    assert t.provenance['dispatches'] == 3


def test_report_lifts_provenance(capsys):
    mb.report('some_op', mb.Timing(0.002, {'dispatch_mode': 'fori_loop',
                                           'dispatches': 1}))
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec == {'op': 'some_op', 'ms': 2.0,
                   'dispatch_mode': 'fori_loop', 'dispatches': 1}


def test_bench_measurement_block_matches_harness():
    """bench.py hardcodes the provenance block (it must not import jax
    via tpu_microbench at orchestrator scope) — pin the copies."""
    assert bench._MEASUREMENT['harness_version'] == mb.HARNESS_VERSION
    assert bench._MEASUREMENT['dispatch_mode'] == mb._dispatch_mode()


# -------------------------------------------------------- floor detector


def test_floor_detector_flags_flat_sweep():
    verdict = dispatch_tables.latency_floor_verdict(
        [256, 512, 1024, 2048], [0.0716, 0.0756, 0.0828, 0.0753],
    )
    assert verdict is not None and verdict['contaminated']
    assert verdict['expected_ratio'] == 64.0
    assert verdict['n'] == 4
    assert verdict['floor_ms'] == pytest.approx(71.6)


def test_floor_detector_passes_scaling_sweep():
    sizes = [256, 512, 1024, 2048]
    verdict = dispatch_tables.latency_floor_verdict(
        sizes, [0.001 * (s / 256) ** 2 for s in sizes],
    )
    assert verdict is not None and not verdict['contaminated']


def test_floor_detector_abstains_without_evidence():
    # one point: nothing to compare
    assert dispatch_tables.latency_floor_verdict([512], [0.01]) is None
    # the sweep never leaves the latency-bound regime (work ratio < 4x)
    assert dispatch_tables.latency_floor_verdict(
        [128, 160], [0.01, 0.0101]) is None
    # None entries (errored ops) are dropped before judging
    assert dispatch_tables.latency_floor_verdict(
        [128, 256, 512], [None, 0.01, None]) is None


def test_report_floor_verdicts_emits_lines(capsys):
    verdicts = mb.report_floor_verdicts({
        'cov_dense_f32': (2.0, [(256, 0.075), (512, 0.076), (1024, 0.08),
                                (2048, 0.075)]),
        'eigh': (3.0, [(128, None)]),  # too thin: no line
    })
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    assert [ln['op'] for ln in lines] == ['floor/cov_dense_f32']
    assert lines[0]['contaminated'] is True
    assert set(verdicts) == {'cov_dense_f32'}


# --------------------------------------------------------- artifact loading


def test_committed_artifact_loads_from_a_clean_sweep():
    doc = dispatch_tables.load_tables(dispatch_tables.ARTIFACT_PATH)
    assert doc['schema'] == dispatch_tables.SCHEMA_VERSION
    assert doc['cov']['min_dim'] == 256
    assert doc['cov']['dtypes'] == ['float32']
    assert doc['attn']['min_sk_dense'] == 2048
    # re-derived from the clean one-dispatch sweep: no contaminated
    # baselines remain (the tunnel-contaminated v1 floor numbers are
    # retired), and everything still at its prior says why
    assert doc['provenance']['contaminated'] == {}
    assert 'cov/float32' in doc['provenance']['held']
    assert doc['provenance']['source']['records'] > 0


def test_accessors_fall_back_on_missing_artifact(monkeypatch, tmp_path):
    monkeypatch.setenv(dispatch_tables.ENV_VAR,
                       str(tmp_path / 'does_not_exist.json'))
    dispatch_tables.invalidate_cache()
    assert dispatch_tables.load_tables() == {}
    assert dispatch_tables.cov_min_dim(default=321) == 321
    assert dispatch_tables.cov_dtypes() == ('float32',)
    assert dispatch_tables.flash_min_sk_dense(default=4096) == 4096


def test_accessors_fall_back_on_schema_mismatch(monkeypatch, tmp_path):
    p = tmp_path / 'future.json'
    p.write_text(json.dumps({'schema': 99, 'cov': {'min_dim': 1}}))
    monkeypatch.setenv(dispatch_tables.ENV_VAR, str(p))
    dispatch_tables.invalidate_cache()
    assert dispatch_tables.load_tables() == {}
    assert dispatch_tables.cov_min_dim(default=256) == 256


def test_env_override_redirects_the_gates(monkeypatch, tmp_path):
    p = tmp_path / 'tuned.json'
    p.write_text(json.dumps({
        'schema': 1,
        'cov': {'min_dim': 512, 'dtypes': ['float32', 'bfloat16']},
        'attn': {'min_sk_dense': 1024},
    }))
    monkeypatch.setenv(dispatch_tables.ENV_VAR, str(p))
    dispatch_tables.invalidate_cache()
    assert dispatch_tables.cov_min_dim(default=256) == 512
    assert dispatch_tables.cov_dtypes() == ('float32', 'bfloat16')
    assert dispatch_tables.flash_min_sk_dense(default=2048) == 1024


def test_gate_functions_consume_the_tables(monkeypatch, tmp_path):
    """use_pallas_for / use_flash_for read the artifact through the
    accessors (off-TPU both still return False — backend check — so this
    pins the plumbing via the accessors the gates call)."""
    from kfac_tpu.ops import pallas_attention, pallas_cov

    assert pallas_cov.use_pallas_for(1024, jnp.float32) is False  # cpu
    assert pallas_attention.use_flash_for(128, 2048, 128, dense=True) is False
    # and the threshold values they would compare against come from the
    # committed artifact
    assert dispatch_tables.cov_min_dim(default=0) == 256
    assert dispatch_tables.flash_min_sk_dense(default=0) == 2048


# -------------------------------------------------------------- derivation


def _cov_sweep(dense_ms, pallas_ms, tag='f32', sizes=(256, 512, 1024, 2048)):
    return (
        [{'op': f'cov_dense_{d}_{tag}', 'ms': dense_ms(d)} for d in sizes]
        + [{'op': f'cov_pallas_{d}_{tag}', 'ms': pallas_ms(d)}
           for d in sizes]
    )


def test_derive_holds_prior_on_contaminated_baseline():
    t = dispatch_tables.derive_tables(
        _cov_sweep(lambda d: 75.0 + d % 7, lambda d: 15.0))
    assert t['cov'] == dispatch_tables.DEFAULTS['cov']
    assert 'cov_dense_f32' in t['provenance']['contaminated']


def test_derive_moves_threshold_on_clean_win_suffix():
    t = dispatch_tables.derive_tables(_cov_sweep(
        lambda d: 0.01 * d * d / 256,
        lambda d: 15.0 if d < 1024 else 0.001 * d * d / 256,
    ))
    assert t['cov']['min_dim'] == 1024
    assert 'float32' in t['cov']['dtypes']
    assert t['provenance']['derived']['cov/float32']['win_from_dim'] == 1024


def test_derive_rejects_single_point_win():
    """One anomalous winning size (the committed bf16 2048 outlier
    pattern) must not re-open a measured-loss regime."""
    ops = _cov_sweep(
        lambda d: 80.0 if d < 2048 else 2722.0, lambda d: 150.0, tag='bf16')
    t = dispatch_tables.derive_tables(ops)
    assert 'bfloat16' not in t['cov']['dtypes']
    assert 'cov/bfloat16' in t['provenance']['held']


def test_derive_attn_needs_min_win_points():
    ops = [{'op': f'attn_einsum_s{s}', 'ms': m}
           for s, m in [(512, 1.0), (1024, 4.0), (2048, 290.0)]]
    ops += [{'op': f'attn_flash_s{s}', 'ms': m}
            for s, m in [(512, 5.0), (1024, 6.0), (2048, 0.9)]]
    t = dispatch_tables.derive_tables(ops)
    assert t['attn']['min_sk_dense'] == (
        dispatch_tables.DEFAULTS['attn']['min_sk_dense'])
    assert 'attn/min_sk_dense' in t['provenance']['held']
    # two winning lengths flips it
    ops[-2]['ms'] = 2.0
    t = dispatch_tables.derive_tables(ops)
    assert t['attn']['min_sk_dense'] == 1024


def test_derive_tool_selftest_runs():
    import derive_dispatch_tables

    derive_dispatch_tables.selftest()
