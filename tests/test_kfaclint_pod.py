"""kfaclint pod tier suite: KFL301–KFL305 fixtures, the happens-before
proof that retired KFL002's inline suppressions, protocol-table model
checking, suppression/baseline round-trips, and the head-clean gate.

Convention matches tests/test_kfaclint.py: every rule is demonstrated
by a true-positive fixture asserted to flag *under that rule* and to be
clean under every other pod rule, so unregistering a rule fails its
fixture test.
"""

import os
import textwrap

import pytest

from kfac_tpu import analysis
from kfac_tpu.analysis import core
from kfac_tpu.analysis.pod import interleave, protocol

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_snippet(tmp_path, source, codes=None, filename='mod.py'):
    path = tmp_path / filename
    path.write_text(textwrap.dedent(source))
    project, errors = analysis.load_project(str(tmp_path))
    rules = analysis.get_rules(codes or analysis.POD_RULE_CODES)
    return analysis.analyze(project, rules, parse_errors=errors)


def codes_of(findings):
    return sorted({f.code for f in findings})


OTHER = {
    code: [c for c in analysis.POD_RULE_CODES if c != code]
    for code in analysis.POD_RULE_CODES
}


# ------------------------------------------------------------------ KFL301


KFL301_TP = '''
    from kfac_tpu.parallel import multihost

    def sync(x):
        if multihost.process_index() == 0:
            multihost.barrier('a')
            vals = multihost.allgather_scalars(x)
        else:
            vals = multihost.allgather_scalars(x)
            multihost.barrier('a')
        return vals
'''


def test_kfl301_flags_reordered_collectives(tmp_path):
    findings = run_snippet(tmp_path, KFL301_TP, ['KFL301'])
    assert len(findings) == 1
    assert 'different order' in findings[0].message


def test_kfl301_silent_when_disabled(tmp_path):
    assert run_snippet(tmp_path, KFL301_TP, OTHER['KFL301']) == []


def test_kfl301_clean_when_arms_agree(tmp_path):
    # identical blocking sequences on both arms pair rank-for-rank
    assert run_snippet(tmp_path, '''
        from kfac_tpu.parallel import multihost

        def sync(x):
            if multihost.process_index() == 0:
                multihost.barrier('a')
                vals = multihost.allgather_scalars(x)
            else:
                multihost.barrier('a')
                vals = multihost.allgather_scalars(x)
            return vals
    ''') == []


# ------------------------------------------------------------------ KFL302


KFL302_TP = '''
    from kfac_tpu.parallel import multihost

    def migrate(ok):
        if multihost.process_index() == 0:
            ok = multihost.agree_decision(ok)
        return ok
'''


def test_kfl302_flags_rank0_only_vote(tmp_path):
    findings = run_snippet(tmp_path, KFL302_TP, ['KFL302'])
    assert len(findings) == 1
    assert 'agree_decision' in findings[0].message


def test_kfl302_silent_when_disabled(tmp_path):
    assert run_snippet(tmp_path, KFL302_TP, OTHER['KFL302']) == []


def test_kfl302_flags_collective_after_rank_return(tmp_path):
    findings = run_snippet(tmp_path, '''
        from kfac_tpu.parallel import multihost

        def commit(path):
            if multihost.process_index() != 0:
                return
            multihost.barrier('commit')
    ''', ['KFL302'])
    assert len(findings) == 1
    assert 'early rank-guard return' in findings[0].message


def test_kfl302_flags_rank_dependent_loop(tmp_path):
    findings = run_snippet(tmp_path, '''
        from kfac_tpu.parallel import multihost

        def drain(items):
            pidx = multihost.process_index()
            for _ in range(pidx):
                multihost.barrier('drain')
    ''', ['KFL302'])
    assert len(findings) == 1
    assert 'trip count' in findings[0].message


def test_kfl302_flags_opaque_rank_branch(tmp_path):
    # the rank test flows through a local: still divergent
    findings = run_snippet(tmp_path, '''
        from kfac_tpu.parallel import multihost

        def maybe(x):
            is_writer = multihost.process_index() == 0
            extra = compute(x)
            if is_writer and extra:
                multihost.barrier('w')
    ''', ['KFL302'])
    assert len(findings) == 1


def test_kfl302_clean_on_uniform_guards(tmp_path):
    # count guards and plain config guards are uniform across ranks —
    # the multihost module's own single-host fast paths must not flag
    assert run_snippet(tmp_path, '''
        import jax
        from kfac_tpu.parallel import multihost

        def barrier_like(name, every, step):
            if jax.process_count() == 1:
                return
            if step % every != 0:
                return
            multihost.barrier(name)
    ''') == []


def test_kfl302_clean_on_inexact_single_writer(tmp_path):
    # `rank test AND unknown` bounds who may enter but proves nothing;
    # blocking ops are not inside the branch, so no finding (the flight
    # recorder's rank-0 bundle shape)
    assert run_snippet(tmp_path, '''
        import os
        from kfac_tpu.parallel import multihost

        def observe(out):
            if multihost.process_index() != 0:
                return None
            return write_bundle(out)

        def write_bundle(out):
            os.makedirs(out, exist_ok=True)
            return out
    ''', ['KFL301', 'KFL302', 'KFL303']) == []


# ------------------------------------------------------------------ KFL303


KFL303_TP = '''
    import jax

    @jax.jit
    def step(x):
        return x * 2

    def drive(x):
        pidx = jax.process_index()
        return step(x[: pidx + 1])
'''


def test_kfl303_flags_rank_tainted_operand(tmp_path):
    findings = run_snippet(tmp_path, KFL303_TP, ['KFL303'])
    assert len(findings) == 1
    assert 'process_index()-derived operand' in findings[0].message


def test_kfl303_silent_when_disabled(tmp_path):
    assert run_snippet(tmp_path, KFL303_TP, OTHER['KFL303']) == []


def test_kfl303_flags_divergent_launch(tmp_path):
    findings = run_snippet(tmp_path, '''
        import jax

        @jax.jit
        def step(x):
            return x * 2

        def drive(x):
            if jax.process_index() == 0:
                return step(x)
            return x
    ''', ['KFL303'])
    assert len(findings) == 1
    assert 'rank-divergent branch' in findings[0].message


def test_kfl303_clean_on_uniform_launch(tmp_path):
    assert run_snippet(tmp_path, '''
        import jax

        @jax.jit
        def step(x):
            return x * 2

        def drive(x):
            return step(x)
    ''') == []


# ------------------------------------------------------------------ KFL304


# the CheckpointManager.save shape with its barrier doctored out: the
# rank-0 stale-dir clear hides inside a retry lambda — this is the
# committed true-positive that stands in for the retired inline KFL002
# suppressions (acceptance bar: deleting the barrier must flag)
KFL304_TP = '''
    import os
    import shutil
    from kfac_tpu.parallel import multihost

    def _with_retries(what, fn):
        return fn()

    def save(state, sdir):
        if multihost.process_index() == 0 and os.path.exists(sdir):
            _with_retries('clearing stale dir',
                          lambda: shutil.rmtree(sdir))
        write(state, sdir)
'''


def test_kfl304_flags_unordered_lambda_mutation(tmp_path):
    findings = run_snippet(tmp_path, KFL304_TP, ['KFL304'])
    assert len(findings) == 1
    assert 'shutil.rmtree()' in findings[0].message
    assert 'rank 0 only' in findings[0].message


def test_kfl304_silent_when_disabled(tmp_path):
    assert run_snippet(tmp_path, KFL304_TP, OTHER['KFL304']) == []


def test_kfl304_cleared_by_barrier_in_same_function(tmp_path):
    src = KFL304_TP.replace(
        'write(state, sdir)',
        "multihost.barrier('save')\n        write(state, sdir)",
    )
    assert run_snippet(tmp_path, src, ['KFL304']) == []


def test_kfl304_cleared_by_ordering_in_calling_context(tmp_path):
    # the happens-before proof is cross-function: a wait op in the only
    # calling context orders the callee's rank-0 mutation
    assert run_snippet(tmp_path, '''
        import os

        def _commit(path):
            import jax
            if jax.process_index() != 0:
                return
            os.replace(path + '.tmp', path)

        def finish(ckptr, path):
            ckptr.wait_until_finished()
            _commit(path)
    ''', ['KFL304']) == []


def test_kfl304_one_unordered_root_defeats_the_proof(tmp_path):
    # same callee, two roots: one ordered, one not -> still a race
    findings = run_snippet(tmp_path, '''
        import os

        def _commit(path):
            import jax
            if jax.process_index() != 0:
                return
            os.replace(path + '.tmp', path)

        def finish(ckptr, path):
            ckptr.wait_until_finished()
            _commit(path)

        def hotpath(path):
            _commit(path)
    ''', ['KFL304'])
    assert len(findings) == 1
    assert 'hotpath' in findings[0].message


def test_kfl002_drops_findings_the_pod_proof_clears(tmp_path):
    # KFL002 alone cannot see the caller's ordering op; with the pod
    # proof consulted it stays silent — the mechanism that retired the
    # four inline suppressions in checkpoint.py / resilience/manager.py
    src = '''
        import os

        def _commit(path):
            import jax
            if jax.process_index() != 0:
                return
            os.replace(path + '.tmp', path)

        def finish(ckptr, path):
            ckptr.wait_until_finished()
            _commit(path)
    '''
    assert run_snippet(tmp_path, src, ['KFL002']) == []
    # ...and removing the ordering edge brings KFL002 back
    doctored = src.replace('ckptr.wait_until_finished()', 'pass')
    findings = run_snippet(tmp_path, doctored, ['KFL002'])
    assert codes_of(findings) == ['KFL002']


def test_retired_suppressions_are_gone():
    # the four inline KFL002 suppressions are retired for good; the
    # doctored fixture above is the surviving true-positive record
    for rel in ('kfac_tpu/checkpoint.py', 'kfac_tpu/resilience/manager.py'):
        with open(os.path.join(REPO_ROOT, rel), encoding='utf-8') as f:
            assert 'disable=KFL002' not in f.read(), rel


# ------------------------------------------------------------------ KFL305


KFL305_TP = '''
    SAVE_PROTOCOL = {
        'machine': 'sequence',
        'name': 'save',
        'function': 'save',
        'steps': (
            {'op': 'clear', 'rank': 0, 'kind': 'mutate',
             'effect': 'mutate_dir'},
            {'op': 'write', 'rank': 'all', 'kind': 'mutate',
             'effect': 'write_step_dir'},
            {'op': 'commit', 'rank': 0, 'kind': 'mutate',
             'effect': 'point_latest'},
        ),
    }

    def save():
        pass
'''


def test_kfl305_flags_doctored_save_sequence(tmp_path):
    findings = run_snippet(tmp_path, KFL305_TP, ['KFL305'])
    msgs = [f.message for f in findings]
    assert any('no barrier between' in m for m in msgs), msgs
    assert any('before the async write is awaited' in m for m in msgs)


def test_kfl305_silent_when_disabled(tmp_path):
    assert run_snippet(tmp_path, KFL305_TP, OTHER['KFL305']) == []


def test_kfl305_flags_code_drift_from_table(tmp_path):
    # a well-formed table whose function no longer takes the declared
    # barrier/wait ops: the cross-check rots with the code
    findings = run_snippet(tmp_path, '''
        SAVE_PROTOCOL = {
            'machine': 'sequence',
            'name': 'save',
            'function': 'save',
            'steps': (
                {'op': 'barrier', 'rank': 'all', 'kind': 'barrier'},
                {'op': 'write', 'rank': 'all', 'kind': 'mutate',
                 'effect': 'write_step_dir'},
                {'op': 'wait', 'rank': 'all', 'kind': 'wait'},
                {'op': 'commit', 'rank': 0, 'kind': 'mutate',
                 'effect': 'point_latest'},
            ),
        }

        def save(state):
            return state
    ''', ['KFL305'])
    msgs = [f.message for f in findings]
    assert any('barrier' in m and 'no longer reaches' in m for m in msgs)
    assert any('wait' in m and 'no longer reaches' in m for m in msgs)


def test_kfl305_flags_missing_vote_outcome(tmp_path):
    findings = run_snippet(tmp_path, '''
        from kfac_tpu.parallel import multihost

        MIGRATE_PROTOCOL = {
            'machine': 'state',
            'name': 'migrate',
            'function': 'migrate',
            'vote_op': 'agree_decision',
            'states': ('idle', 'boundary', 'committed'),
            'initial': 'idle',
            'transitions': (
                {'from': 'idle', 'event': 'checkpoint-boundary',
                 'to': 'boundary', 'mutates': ()},
                {'from': 'boundary', 'event': 'vote-commit',
                 'to': 'committed', 'mutates': ('plan',)},
                {'from': 'committed', 'event': 'cooldown',
                 'to': 'idle', 'mutates': ()},
            ),
        }

        def migrate(ok):
            return multihost.agree_decision(ok)
    ''', ['KFL305'])
    assert any('vote-abort' in f.message for f in findings), findings


def test_kfl305_flags_mutating_abort(tmp_path):
    findings = run_snippet(tmp_path, '''
        from kfac_tpu.parallel import multihost

        MIGRATE_PROTOCOL = {
            'machine': 'state',
            'name': 'migrate',
            'function': 'migrate',
            'vote_op': 'agree_decision',
            'states': ('boundary', 'committed', 'aborted'),
            'initial': 'boundary',
            'transitions': (
                {'from': 'boundary', 'event': 'vote-commit',
                 'to': 'committed', 'mutates': ('plan',)},
                {'from': 'boundary', 'event': 'vote-abort',
                 'to': 'aborted', 'mutates': ('plan',)},
            ),
        }

        def migrate(ok):
            return multihost.agree_decision(ok)
    ''', ['KFL305'])
    assert any(
        'without a committed vote' in f.message for f in findings
    ), findings


def test_kfl305_flags_lost_vote_op(tmp_path):
    findings = run_snippet(tmp_path, '''
        MIGRATE_PROTOCOL = {
            'machine': 'state',
            'name': 'migrate',
            'function': 'migrate',
            'vote_op': 'agree_decision',
            'states': ('boundary', 'committed', 'aborted'),
            'initial': 'boundary',
            'transitions': (
                {'from': 'boundary', 'event': 'vote-commit',
                 'to': 'committed', 'mutates': ('plan',)},
                {'from': 'boundary', 'event': 'vote-abort',
                 'to': 'aborted', 'mutates': ()},
            ),
        }

        def migrate(ok):
            return ok
    ''', ['KFL305'])
    assert any(
        'no longer reaches it' in f.message for f in findings
    ), findings


def test_kfl305_clean_on_sound_tables(tmp_path):
    assert run_snippet(tmp_path, '''
        from kfac_tpu.parallel import multihost

        SAVE_PROTOCOL = {
            'machine': 'sequence',
            'name': 'save',
            'function': 'save',
            'steps': (
                {'op': 'clear', 'rank': 0, 'kind': 'mutate',
                 'effect': 'mutate_dir'},
                {'op': 'barrier', 'rank': 'all', 'kind': 'barrier'},
                {'op': 'write', 'rank': 'all', 'kind': 'mutate',
                 'effect': 'write_step_dir'},
                {'op': 'wait', 'rank': 'all', 'kind': 'wait'},
                {'op': 'commit', 'rank': 0, 'kind': 'mutate',
                 'effect': 'point_latest'},
            ),
        }

        def save(ckptr):
            multihost.barrier('save')
            ckptr.wait_until_finished()
    ''') == []


# ----------------------------------------------------- interleave unit checks


def test_interleave_rejects_unknown_machine():
    assert interleave.check_table({'machine': 'petri-net'})


def test_interleave_rejects_non_all_barrier():
    problems = interleave.check_table({
        'machine': 'sequence', 'name': 'x', 'function': 'f',
        'steps': ({'op': 'b', 'rank': 0, 'kind': 'barrier'},),
    })
    assert any('deadlocks' in p for p in problems)


def test_interleave_flags_unreachable_state():
    problems = interleave.check_table({
        'machine': 'state', 'name': 'x', 'function': 'f',
        'vote_op': 'agree_decision',
        'states': ('a', 'b', 'orphan'), 'initial': 'a',
        'transitions': (
            {'from': 'a', 'event': 'go', 'to': 'b', 'mutates': ()},
        ),
    })
    assert any('unreachable' in p for p in problems)


def test_interleave_flags_double_commit_per_boundary():
    # two mutating commits reachable without a checkpoint boundary
    # between them — found by the bounded exploration, not structurally
    problems = interleave.check_table({
        'machine': 'state', 'name': 'x', 'function': 'f',
        'vote_op': 'agree_decision',
        'states': ('boundary', 'committed'), 'initial': 'boundary',
        'transitions': (
            {'from': 'boundary', 'event': 'vote-commit',
             'to': 'committed', 'mutates': ('plan',)},
            {'from': 'boundary', 'event': 'vote-abort',
             'to': 'boundary', 'mutates': ()},
            {'from': 'committed', 'event': 'vote-commit',
             'to': 'committed', 'mutates': ('plan',)},
            {'from': 'committed', 'event': 'vote-abort',
             'to': 'boundary', 'mutates': ()},
        ),
    })
    assert any('more than one mutating commit' in p for p in problems)


# ----------------------------------------------------- suppression / baseline


def test_pod_findings_honor_suppressions(tmp_path):
    src = KFL302_TP.replace(
        'ok = multihost.agree_decision(ok)',
        'ok = multihost.agree_decision(ok)  '
        '# kfaclint: disable=KFL302 (fixture: single-host test shim)',
    )
    assert run_snippet(tmp_path, src, ['KFL302']) == []
    # reason-less suppression does not suppress and is itself KFL000
    bare = KFL302_TP.replace(
        'ok = multihost.agree_decision(ok)',
        'ok = multihost.agree_decision(ok)  # kfaclint: disable=KFL302',
    )
    findings = run_snippet(tmp_path, bare, ['KFL302'])
    assert 'KFL000' in codes_of(findings)


def test_pod_findings_baseline_round_trip(tmp_path):
    findings = run_snippet(tmp_path, KFL304_TP, ['KFL304'])
    assert findings
    bpath = tmp_path / 'baseline.json'
    analysis.save_baseline(str(bpath), findings)
    new, matched = analysis.split_baseline(
        findings, analysis.load_baseline(str(bpath))
    )
    assert not new and matched == len(findings)


# ------------------------------------------------------------- head cleanness


def test_pod_rules_clean_on_head():
    """KFL301–KFL305 and KFL002 hold on the repo itself with an empty
    baseline — including the four KFL002 sites whose suppressions the
    pod proof retired."""
    project, errors = analysis.load_project(REPO_ROOT, ['kfac_tpu'])
    rules = analysis.get_rules(
        tuple(analysis.POD_RULE_CODES) + ('KFL002',)
    )
    findings = analysis.analyze(project, rules, parse_errors=errors)
    assert findings == [], [f.render() for f in findings]


def test_head_declares_both_protocol_tables():
    project, _ = analysis.load_project(REPO_ROOT, ['kfac_tpu'])
    tables, problems = protocol.load_protocol_tables(project)
    assert problems == []
    names = {t.name for t in tables}
    assert {'SAVE_PROTOCOL', 'MIGRATION_PROTOCOL'} <= names
    machines = {t.table['machine'] for t in tables}
    assert machines == {'sequence', 'state'}


def test_registry_parses_from_multihost_ast():
    project, _ = analysis.load_project(REPO_ROOT, ['kfac_tpu'])
    registry = protocol.load_op_registry(project)
    assert registry == protocol.DEFAULT_PROTOCOL_OPS, (
        'PROTOCOL_OPS in kfac_tpu/parallel/multihost.py must stay in '
        'sync with the pod tier fallback copy'
    )
