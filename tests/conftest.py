"""Test configuration: force an 8-device virtual CPU mesh.

The reference simulates clusters by forking gloo process groups
(testing/distributed.py:24-141). The JAX equivalent is a host-platform
device-count override: the same SPMD program that runs on a TPU pod runs on
8 virtual CPU devices, so every sharding/collective path is exercised
in-process. This must happen before the first JAX backend initialization.

Note: the container's sitecustomize registers an `axon` TPU plugin that
forces jax_platforms; overriding the config here keeps tests off the (single,
exclusive) TPU tunnel.
"""

import os

flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8'
    )

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_enable_x64', False)
