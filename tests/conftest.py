"""Test configuration: force an 8-device virtual CPU mesh.

The reference simulates clusters by forking gloo process groups
(testing/distributed.py:24-141). The JAX equivalent is a host-platform
device-count override: the same SPMD program that runs on a TPU pod runs on
8 virtual CPU devices, so every sharding/collective path is exercised
in-process. This must happen before the first JAX backend initialization.

Note: the container's sitecustomize registers an `axon` TPU plugin that
forces jax_platforms; overriding the config here keeps tests off the (single,
exclusive) TPU tunnel.
"""

import os

flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8'
    )

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_enable_x64', False)

# Persistent compilation cache: the container has ONE cpu core, so the
# suite's wall-clock is almost entirely XLA compiles (measured r2: 51:47).
# Caching compiled executables across runs cuts repeat suites to minutes —
# a suite fast enough to actually run before every commit (the reference's
# 15-minute CI budget, BASELINE.md). The cache dir is repo-local and
# gitignored. The cpu_aot_loader "machine feature" stderr noise on cache
# hits refers to XLA preference flags (prefer-no-scatter/gather), not host
# ISA — harmless.
_cache_dir = os.path.join(os.path.dirname(__file__), '..', '.jax_cache')
jax.config.update('jax_compilation_cache_dir', os.path.abspath(_cache_dir))
# min_compile_time 0: with the per-module clear_caches below, even
# sub-second programs re-JIT once per module — serve them from disk too.
jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.0)
jax.config.update('jax_persistent_cache_min_entry_size_bytes', 0)

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope='module')
def _clear_jax_caches_per_module():
    """Drop in-memory compiled executables after each test module.

    The full suite accumulates every module's jitted programs (~49 GB RSS
    observed at the pipeline tests, round 4), and the resulting memory
    pressure inflated individual tests 3-4x over their isolated times
    (e.g. zigzag gradients: 133 s in-suite vs 37 s isolated). Modules
    don't share programs, and re-JITs after a clear are served by the
    persistent on-disk cache, so clearing at module teardown trades a
    little deserialization for a bounded working set.
    """
    yield
    jax.clear_caches()
