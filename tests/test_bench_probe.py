"""Unit tests for bench.py's TPU-probe retry loop.

The probe's failure modes (r1: instant UNAVAILABLE rc=1; r2: serial
timeouts while the chip was healthy moments later) can't be reproduced on
demand, so the retry/backoff/grace logic is validated against a scripted
fake subprocess and clock.
"""

import types

import pytest

import bench


class _FakeProc:
    def __init__(self, outcome):
        self.outcome = outcome  # 'ok' | 'cpu' | 'timeout' | 'rc1'
        self.returncode = {'ok': 0, 'cpu': 0, 'rc1': 1}.get(outcome)

    def communicate(self, timeout=None):
        if self.outcome == 'timeout':
            raise bench.subprocess.TimeoutExpired('probe', timeout)
        if self.outcome == 'ok':
            return 'PROBE tpu TPU v5 lite\n', ''
        if self.outcome == 'cpu':
            return 'PROBE cpu \n', ''
        return '', ''

    def terminate(self):
        pass

    def kill(self):
        pass

    def wait(self, timeout=None):
        return 0


@pytest.fixture
def scripted(monkeypatch):
    """Drive _probe_backend with a scripted outcome sequence and a clock
    that advances by each attempt's timeout (sleeps are instant)."""
    state = types.SimpleNamespace(outcomes=[], clock=0.0, attempts=0)

    def fake_popen(args, **kw):
        state.attempts += 1
        outcome = (
            state.outcomes[state.attempts - 1]
            if state.attempts <= len(state.outcomes)
            else state.outcomes[-1]
        )
        return _FakeProc(outcome)

    orig_communicate = _FakeProc.communicate

    def comm(self, timeout=None):
        if self.outcome == 'timeout':
            state.clock += timeout
        return orig_communicate(self, timeout=timeout)

    monkeypatch.setattr(_FakeProc, 'communicate', comm)
    monkeypatch.setattr(bench.subprocess, 'Popen', fake_popen)
    monkeypatch.setattr(bench.time, 'monotonic', lambda: state.clock)
    monkeypatch.setattr(bench.time, 'sleep', lambda s: None)
    monkeypatch.delenv('JAX_PLATFORMS', raising=False)
    monkeypatch.setenv('BENCH_PROBE_BUDGET_S', '420')
    return state


def test_probe_healthy_first_attempt(scripted):
    scripted.outcomes = ['ok']
    assert bench._probe_backend() == ('tpu', 'TPU v5 lite')
    assert scripted.attempts == 1


def test_probe_cpu_default_stops_immediately(scripted):
    # rc=0 with platform cpu means no accelerator plugin is registered at
    # all: retrying cannot change that, so exactly one attempt happens
    scripted.outcomes = ['cpu']
    assert bench._probe_backend() is None
    assert scripted.attempts == 1


def test_probe_env_pinned_cpu_skips_probe(scripted, monkeypatch):
    monkeypatch.setenv('JAX_PLATFORMS', 'cpu')
    assert bench._probe_backend() is None
    assert scripted.attempts == 0


def test_probe_retries_through_timeouts_to_success(scripted):
    # r2 failure mode: the old 2-attempt probe gave up at 125 s while the
    # chip came healthy moments later — the budgeted loop must ride it out
    scripted.outcomes = ['timeout', 'timeout', 'rc1', 'ok']
    assert bench._probe_backend() == ('tpu', 'TPU v5 lite')
    assert scripted.attempts == 4


def test_probe_exhausts_budget_with_final_grace_attempt(scripted):
    scripted.outcomes = ['timeout']
    assert bench._probe_backend() is None
    # attempts kept coming until the 420 s budget was spent (90 s first,
    # then shorter) PLUS exactly one grace attempt past the budget
    assert scripted.attempts >= 5
    assert scripted.clock > 420.0


def test_probe_rc1_unavailable_is_retryable(scripted):
    # r1 failure mode: UNAVAILABLE raises in the child (rc=1) when another
    # client holds the single-client claim; must retry, not bail
    scripted.outcomes = ['rc1', 'rc1', 'ok']
    assert bench._probe_backend() == ('tpu', 'TPU v5 lite')
    assert scripted.attempts == 3


def test_persist_writes_partial_snapshot(tmp_path, monkeypatch):
    """_persist leaves an atomic JSON snapshot flagged partial=True (plus
    this run's id for attribution), so a killed run's completed phases
    survive on disk."""
    import json

    path = tmp_path / 'part.json'
    monkeypatch.setenv('BENCH_PARTIAL_PATH', str(path))
    monkeypatch.setenv('BENCH_RUNS_DIR', str(tmp_path / 'runs'))
    bench._persist({'metric': 'm', 'value': 1.5})
    got = json.loads(path.read_text())
    assert got == {
        'metric': 'm', 'value': 1.5, 'partial': True, 'run_id': bench._RUN_ID
    }
    # completed runs re-stamp partial=False
    bench._persist({'metric': 'm', 'value': 1.5}, partial=False)
    assert json.loads(path.read_text())['partial'] is False
    # overwrite is atomic (no stale tmp files left behind)
    bench._persist({'metric': 'm', 'value': 2.5})
    assert json.loads(path.read_text())['value'] == 2.5
    assert list(tmp_path.glob('*.tmp.*')) == []
    # the per-run record carries the same payload, keyed by run id
    run_file = tmp_path / 'runs' / f'run_{bench._RUN_ID}.json'
    assert json.loads(run_file.read_text())['value'] == 2.5


def test_persist_never_clobbers_tpu_record_with_cpu(tmp_path, monkeypatch):
    """The round-4 data-loss: a CPU-fallback run overwrote the only TPU
    measurement of the round. The latest-pointer now refuses that write;
    the CPU run's own numbers land in its per-run file instead."""
    import json

    path = tmp_path / 'part.json'
    monkeypatch.setenv('BENCH_PARTIAL_PATH', str(path))
    monkeypatch.setenv('BENCH_RUNS_DIR', str(tmp_path / 'runs'))
    path.write_text(json.dumps(
        {'platform': 'tpu', 'value': 123.0, 'run_id': 'older_tpu_run'}
    ))
    bench._persist({'metric': 'm', 'platform': 'cpu', 'value': 1.0})
    kept = json.loads(path.read_text())
    assert kept['platform'] == 'tpu' and kept['value'] == 123.0
    run_file = tmp_path / 'runs' / f'run_{bench._RUN_ID}.json'
    assert json.loads(run_file.read_text())['platform'] == 'cpu'
    # a TPU-platform result DOES refresh the pointer
    bench._persist({'metric': 'm', 'platform': 'tpu', 'value': 2.0})
    assert json.loads(path.read_text())['value'] == 2.0


def test_mark_run_started_stamps_latest(tmp_path, monkeypatch):
    """Attribution marker: bench_partial.json describes the current run iff
    its run_id matches LATEST.json (the pointer can lag after the clobber
    guard or a pre-first-phase death)."""
    import json

    monkeypatch.setenv('BENCH_PARTIAL_PATH', str(tmp_path / 'part.json'))
    monkeypatch.setenv('BENCH_RUNS_DIR', str(tmp_path / 'runs'))
    bench._mark_run_started()
    latest = json.loads((tmp_path / 'runs' / 'LATEST.json').read_text())
    assert latest['run_id'] == bench._RUN_ID


def test_persist_disabled_with_empty_path(tmp_path, monkeypatch):
    monkeypatch.setenv('BENCH_PARTIAL_PATH', '')
    monkeypatch.chdir(tmp_path)
    bench._persist({'metric': 'm'})
    bench._mark_run_started()
    assert list(tmp_path.iterdir()) == []


def test_stage_config_cli_pairing():
    """--stage/--config/--out must be validated together at parse time —
    a mismatch discovered after the backend claim burns a chip-session
    stage budget (r5s3 lesson)."""
    import subprocess
    import sys

    cases = [
        (['--stage', 'resnet', '--config', 'large'], 'not a resnet config'),
        (['--config', 'large'], 'requires --stage'),
        (['--stage', 'lm'], 'requires --config'),
        (['--stage', 'lm', '--config', 'tiny'], 'requires --out'),
    ]
    bench_path = bench.os.path.abspath(bench.__file__)
    for argv, needle in cases:
        r = subprocess.run(
            [sys.executable, bench_path, *argv],
            capture_output=True, text=True,
            env={**bench.os.environ, 'JAX_PLATFORMS': 'cpu',
                 'PALLAS_AXON_POOL_IPS': ''},
        )
        assert r.returncode == 2, (argv, r.returncode, r.stderr)
        assert needle in r.stderr, (argv, r.stderr)


def test_orchestrator_tpu_plan_routes_stages(tmp_path, monkeypatch):
    """The TPU plan dispatches each stage with the right --stage/--config
    pair (incl. the opportunistic lm_large / resnet32_cifar tail), gates
    lm_flagship_pallas on micro_pallas, and lifts the flagship to the
    headline with opportunistic results as summary fields."""
    import json

    monkeypatch.setenv('BENCH_PARTIAL_PATH', str(tmp_path / 'part.json'))
    monkeypatch.setenv('BENCH_RUNS_DIR', str(tmp_path / 'runs'))
    monkeypatch.setenv('BENCH_DEADLINE_S', '100000')
    monkeypatch.setattr(bench, '_probe_backend', lambda: ('tpu', 'fake v5'))

    calls = []

    def fake_run_stage(name, argv, env, budget, stdout_path=None):
        calls.append((name, argv, stdout_path))
        # stage writes its json/jsonl record like the real subprocess
        if name.startswith('lm_') or name in bench._RESNET_CONFIGS:
            out = argv[argv.index('--out') + 1]
            rec = {'platform': 'tpu', 'sgd_tokens_per_sec': 100.0,
                   'value': 90.0, 'vs_baseline': 0.9, 'mfu': 0.3,
                   'sgd_mfu': 0.33, 'ok': True}
            if name in bench._RESNET_CONFIGS:
                rec.update(kfac_images_per_sec=500.0)
            with open(out, 'w') as f:
                json.dump(rec, f)
        elif stdout_path:
            with open(stdout_path, 'w') as f:
                f.write(json.dumps({'op': 'cov_512', 'max_err': 0.0}) + '\n')
        return 'ok'

    monkeypatch.setattr(bench, '_run_stage', fake_run_stage)
    result = {'metric': 'm', 'value': 0.0, 'platform': 'unknown'}
    bench._orchestrate(result)

    by_name = {c[0]: c[1] for c in calls}
    order = [c[0] for c in calls]
    assert order[:3] == ['micro_safe', 'lm_tiny', 'lm_flagship']
    assert order[-1] == 'acc'
    assert {'lm_large', 'resnet32_cifar'} <= set(order)

    def cfg_of(name):
        a = by_name[name]
        return a[a.index('--stage') + 1], a[a.index('--config') + 1]

    assert cfg_of('lm_tiny') == ('lm', 'tiny')
    assert cfg_of('lm_flagship') == ('lm', 'flagship')
    assert cfg_of('lm_large') == ('lm', 'large')
    assert cfg_of('resnet32_cifar') == ('resnet', 'resnet32_cifar')
    assert result['headline_stage'] == 'lm_flagship'
    assert result['large_mfu'] == 0.3
    assert result['resnet32_vs_baseline'] == 0.9
    assert result['resnet32_kfac_images_per_sec'] == 500.0
    # the kernel-enabled flagship rode along, never the headline
    assert result['pallas_tokens_per_sec'] == 90.0


@pytest.mark.slow
def test_resnet_stage_end_to_end_cpu(tmp_path, monkeypatch):
    """The vision stage runs a real SGD-vs-K-FAC measurement on a tiny
    config (the on-chip configs are driven by scripts/tpu_session2*.sh;
    this guards the stage code path itself)."""
    import json

    monkeypatch.setitem(
        bench._RESNET_CONFIGS, 'tiny_test',
        dict(arch='resnet20', batch=4, hw=32, classes=10),
    )
    out = tmp_path / 'rs.json'
    bench.run_resnet_stage('tiny_test', str(out))
    rec = json.loads(out.read_text())
    assert rec['ok'] and rec['vs_baseline'] > 0
    assert rec['n_kfac_layers'] == 20
    assert rec['sgd_images_per_sec'] > 0 and rec['kfac_images_per_sec'] > 0


@pytest.mark.slow
def test_async_spike_probe_flattens_refresh_spike():
    """ISSUE-6 acceptance: at d>=512 the sliced async backend holds the
    per-step refresh spike to <= 1.5x the median step, where the
    synchronous boundary refresh spikes multi-x."""
    out = bench._async_spike_probe(windows=2)
    assert out['refresh_spike_ratio'] <= 1.5, out
    assert out['refresh_spike_ratio_sync'] > out['refresh_spike_ratio'], out
    for k in ('step_p50_ms', 'step_p95_ms', 'step_max_ms'):
        assert out[k] > 0 and out[f'{k}_sync'] > 0


def test_pipeline_probe_folds_committed_bubble_table():
    """The pipeline probe republishes the committed measured-vs-simulated
    schedule table with its one-dispatch harness provenance, read-only."""
    out = bench._pipeline_probe()
    assert out['status'] == 'ok'
    assert out['clean_rows'] >= len(out['rows']) // 2
    covered = {(r['schedule'], r['p'], r['v']) for r in out['rows']}
    assert {('1f1b', 2, 1), ('interleaved', 4, 2)} <= covered
    for r in out['rows']:
        assert 0.0 <= r['predicted_fraction'] < 1.0
        assert r['wall_clock_p50_s'] > 0.0
        if not r['contaminated']:
            assert abs(
                r['measured_fraction'] - r['predicted_fraction']
            ) <= out['tolerance']
    harness = out['provenance']['harness']
    assert harness['harness_version'] == 2
    assert harness['dispatches'] == 1
