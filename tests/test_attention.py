"""Ring attention must equal dense attention on the gathered sequence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kfac_tpu.models import attention


def _qkv(b=2, s=32, h=4, d=8, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def test_dense_causal_matches_manual():
    q, k, v = _qkv(s=8)
    out = attention.dense_causal_attention(q, k, v)
    # manual per-position computation for the last position of head 0
    logits = (q[0, :, 0] @ k[0, :, 0].T) * (8**-0.5)
    mask = np.tril(np.ones((8, 8), bool))
    logits = np.where(mask, np.asarray(logits), -np.inf)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    expected_last = probs[7] @ np.asarray(v[0, :, 0])
    np.testing.assert_allclose(out[0, 7, 0], expected_last, rtol=2e-3, atol=2e-5)


@pytest.mark.parametrize('causal', [True, False])
@pytest.mark.parametrize('n_shards', [2, 4, 8])
def test_ring_matches_dense(causal, n_shards):
    mesh = Mesh(np.asarray(jax.devices()[:n_shards]).reshape(n_shards), ('seq',))
    q, k, v = _qkv(s=8 * n_shards)
    ring = attention.make_context_parallel_attention(mesh, 'seq', causal=causal)
    spec = NamedSharding(mesh, P(None, 'seq'))
    qs, ks, vs = (jax.device_put(t, spec) for t in (q, k, v))
    out_ring = jax.jit(ring)(qs, ks, vs)
    if causal:
        out_dense = attention.dense_causal_attention(q, k, v)
    else:
        scale = q.shape[-1] ** -0.5
        logits = jnp.einsum('bqhd,bkhd->bhqk', q * scale, k)
        probs = jax.nn.softmax(logits, -1)
        out_dense = jnp.einsum('bhqk,bkhd->bqhd', probs, v)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_dense), rtol=2e-3, atol=2e-5
    )


def test_ring_bf16_inputs():
    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ('seq',))
    q, k, v = _qkv(s=64, dtype=jnp.bfloat16)
    ring = attention.make_context_parallel_attention(mesh, 'seq')
    out = jax.jit(ring)(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = attention.dense_causal_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=0.1, atol=0.05
    )


def test_ring_gradients_flow():
    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(4), ('seq',))
    q, k, v = _qkv(s=16)
    ring = attention.make_context_parallel_attention(mesh, 'seq')

    def loss(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(attention.dense_causal_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss)(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(g_ring), np.asarray(g_dense), rtol=5e-3, atol=5e-4
    )


@pytest.mark.parametrize('n_shards', [2, 4, 8])
def test_zigzag_matches_dense(n_shards):
    """Zigzag (load-balanced) causal ring attention equals the dense oracle
    for natural-order inputs/outputs."""
    mesh = Mesh(np.asarray(jax.devices()[:n_shards]).reshape(n_shards), ('seq',))
    q, k, v = _qkv(s=8 * n_shards)
    fn = attention.make_context_parallel_attention(
        mesh, 'seq', causal=True, zigzag=True
    )
    out = jax.jit(fn)(q, k, v)
    expected = attention.dense_causal_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-3, atol=2e-5
    )


def test_zigzag_indices_roundtrip():
    perm, inv = attention.zigzag_indices(32, 4)
    x = np.arange(32)
    np.testing.assert_array_equal(x[perm][inv], x)
    # shard 0 holds the first and LAST chunks (balanced causal load)
    c = 32 // 8
    np.testing.assert_array_equal(perm[:c], np.arange(c))
    np.testing.assert_array_equal(perm[c:2 * c], np.arange(28, 32))


def test_zigzag_rejects_noncausal():
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ('seq',))
    with pytest.raises(ValueError, match='causal'):
        attention.make_context_parallel_attention(
            mesh, 'seq', causal=False, zigzag=True
        )


def test_zigzag_gradients_flow():
    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(4), ('seq',))
    q, k, v = _qkv(s=32)
    fn = attention.make_context_parallel_attention(
        mesh, 'seq', causal=True, zigzag=True
    )
    dense_grad = jax.grad(lambda q: attention.dense_causal_attention(q, k, v).sum())(q)
    zz_grad = jax.grad(lambda q: fn(q, k, v).sum())(q)
    np.testing.assert_allclose(
        np.asarray(zz_grad), np.asarray(dense_grad), rtol=2e-3, atol=2e-4
    )
