"""Native prefetch loader tests: build, correctness, reuse across epochs."""

import numpy as np
import pytest

from kfac_tpu.utils import native_loader


@pytest.fixture(scope='module')
def loader_cls():
    try:
        native_loader._load_lib()
    except native_loader.NativeLoaderUnavailable as e:
        pytest.skip(f'no native toolchain: {e}')
    return native_loader.PrefetchLoader


def test_batches_cover_epoch_exactly(loader_cls):
    n, bs = 103, 10
    data = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    labels = np.arange(n, dtype=np.int32)
    ldr = loader_cls(data, labels, batch_size=bs, seed=1)
    assert ldr.batches_per_epoch == n // bs
    seen = []
    for x, y in ldr.epoch_batches():
        assert x.shape == (bs, 4)
        assert y.shape == (bs,)
        # data/label correspondence: row i of data is [4i, 4i+1, ...]
        np.testing.assert_array_equal(x[:, 0].astype(np.int32), y * 4)
        seen.extend(y.tolist())
    assert len(seen) == (n // bs) * bs
    assert len(set(seen)) == len(seen)  # no duplicates within an epoch
    ldr.close()


def test_shuffle_differs_across_epochs(loader_cls):
    n, bs = 64, 8
    data = np.random.default_rng(0).normal(size=(n, 3)).astype(np.float32)
    labels = np.arange(n, dtype=np.int32)
    ldr = loader_cls(data, labels, batch_size=bs, seed=7)
    e1 = [y for _, y in ldr.epoch_batches()]
    e2 = [y for _, y in ldr.epoch_batches()]
    assert not all((a == b).all() for a, b in zip(e1, e2))
    # both epochs are complete permutations
    assert sorted(np.concatenate(e1).tolist()) == list(range(n))
    assert sorted(np.concatenate(e2).tolist()) == list(range(n))
    ldr.close()


def test_prefetch_overlaps(loader_cls):
    """The ring fills in the background: consuming after a pause is instant."""
    import time

    n, bs = 4096, 256
    data = np.zeros((n, 128), dtype=np.float32)
    labels = np.zeros(n, dtype=np.int32)
    ldr = loader_cls(data, labels, batch_size=bs, n_ring=4, seed=0)
    it = ldr.epoch_batches()
    next(it)
    time.sleep(0.2)  # background thread fills the ring meanwhile
    t0 = time.perf_counter()
    next(it)
    # generous bound: only guards against a fully-serial (non-prefetching)
    # implementation, not scheduler jitter
    assert time.perf_counter() - t0 < 1.0
    ldr.close()


def test_zero_batches_raises(loader_cls):
    data = np.zeros((5, 2), dtype=np.float32)
    labels = np.zeros(5, dtype=np.int32)
    with pytest.raises(ValueError):
        loader_cls(data, labels, batch_size=10)


def test_early_break_resyncs_next_epoch(loader_cls):
    """A consumer abandoning an epoch mid-stream must not leak its leftover
    batches into the next epoch_batches() call (stale slots are drained
    using the producer's epoch counter)."""
    n, bs = 64, 8
    data = np.zeros((n, 2), dtype=np.float32)
    labels = np.arange(n, dtype=np.int32)
    ldr = loader_cls(data, labels, batch_size=bs, n_ring=3, seed=3)
    for i, (_, y) in enumerate(ldr.epoch_batches()):
        if i == 2:
            break  # abandon epoch 0 after 3 of 8 batches
    e1 = [y for _, y in ldr.epoch_batches()]
    # the next call serves one *complete* fresh epoch
    assert len(e1) == ldr.batches_per_epoch
    assert sorted(np.concatenate(e1).tolist()) == list(range(n))
    ldr.close()


def test_augmented_batches_are_crops_and_flips(loader_cls):
    """In-worker augmentation: every emitted sample is a zero-padded random
    crop (optionally flipped) of its source image — nonzero pixels must all
    come from the source, and augmentation must actually perturb samples."""
    n, h, w, c = 32, 8, 8, 3
    data = np.random.default_rng(0).normal(size=(n, h, w, c)).astype(np.float32)
    labels = np.arange(n, dtype=np.int32)
    ldr = loader_cls(
        data, labels, batch_size=8, seed=5, augment={'pad': 2, 'flip': True}
    )
    differing = 0
    for x, y in ldr.epoch_batches():
        assert x.shape == (8, h, w, c)
        for xi, yi in zip(x, y):
            orig = data[yi]
            if not np.array_equal(xi, orig):
                differing += 1
            vals = set(np.round(xi[xi != 0], 5).ravel().tolist())
            ovals = set(np.round(orig, 5).ravel().tolist())
            assert vals <= ovals
    ldr.close()
    assert differing > n // 2


def test_start_epoch_fast_forwards_shuffle(loader_cls):
    """A loader created with start_epoch=k must emit exactly the batches a
    fresh loader emits for its (k+1)-th epoch — the resume contract."""
    n, bs = 48, 8
    data = np.zeros((n, 2), dtype=np.float32)
    labels = np.arange(n, dtype=np.int32)
    fresh = loader_cls(data, labels, batch_size=bs, seed=11)
    _ = [y for _, y in fresh.epoch_batches()]        # epoch 0
    want = [y for _, y in fresh.epoch_batches()]     # epoch 1
    fresh.close()
    resumed = loader_cls(data, labels, batch_size=bs, seed=11, start_epoch=1)
    got = [y for _, y in resumed.epoch_batches()]
    resumed.close()
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
