"""Model family + Trainer tests: registration coverage and training smokes."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import kfac_tpu
from kfac_tpu import training
from kfac_tpu.models import MLP, TransformerLM, lm_loss, resnet20, resnet50


def test_resnet20_forward_and_registration():
    m = resnet20(num_classes=10)
    x = jnp.ones((2, 32, 32, 3))
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    reg = kfac_tpu.register_model(m, x, train=False)
    # 1 stem conv + 3 stages * 3 blocks * 2 convs + head = 20 kfac layers
    assert len(reg) == 20
    conv_names = [n for n in reg.names() if 'conv' in n]
    assert len(conv_names) == 19
    assert 'head' in reg.names()


def test_resnet50_registration_count():
    m = resnet50(num_classes=1000)
    x = jnp.ones((1, 64, 64, 3))  # small spatial for test speed
    reg = kfac_tpu.register_model(m, x, train=False)
    # stem + 3*(3 convs) + 4*(3) + 6*(3) + 3*(3) + 4 projections + head
    assert len(reg) == 1 + 48 + 4 + 1


def test_transformer_registration_and_skip():
    m = TransformerLM(vocab_size=100, d_model=32, num_heads=4, num_layers=2, max_len=16)
    tokens = jnp.zeros((2, 16), jnp.int32)
    reg = kfac_tpu.register_model(m, tokens)
    names = reg.names()
    # 2 blocks * (q,k,v,out,mlp_up,mlp_down) + lm_head
    assert len(reg) == 2 * 6 + 1
    assert 'block0/attn/q_proj' in names and 'lm_head' in names
    # embedding is not a dense layer -> never registered
    assert not any('embed' in n for n in names)
    # the reference LM example skips attention by default
    # (examples/torch_language_model.py:163-168) — same flag surface here:
    reg2 = kfac_tpu.register_model(m, tokens, skip_layers=['.*attn.*', 'lm_head'])
    assert len(reg2) == 2 * 2


def test_trainer_resnet_with_batch_stats():
    m = resnet20(num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 32, 32, 3))
    y = jax.nn.one_hot(jnp.arange(8) % 10, 10)
    variables = m.init(jax.random.PRNGKey(1), x, train=True)
    reg = kfac_tpu.register_model(m, x, train=False)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, damping=0.01, lr=0.1, factor_update_steps=2,
        inv_update_steps=2,
    )

    def loss_fn(params, model_state, batch):
        xx, yy = batch
        logits, updates = m.apply(
            {'params': params, 'batch_stats': model_state}, xx, train=True,
            mutable=['batch_stats'],
        )
        loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yy, axis=-1))
        return loss, updates['batch_stats']

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.1, momentum=0.9), kfac=kfac
    )
    state = trainer.init(variables['params'], variables['batch_stats'])
    losses = []
    for _ in range(6):
        state, loss = trainer.step(state, (x, y))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))
    # batch stats actually updated
    bn_mean = state.model_state['bn0']['mean']
    assert float(jnp.abs(bn_mean).sum()) > 0


def test_trainer_cadence_uses_both_variants():
    m = MLP(features=(16,), num_classes=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 8))
    y = jax.nn.one_hot(jnp.arange(16) % 4, 4)
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, factor_update_steps=3, inv_update_steps=3, damping=0.01
    )

    def loss_fn(params, model_state, batch):
        xx, yy = batch
        logits = m.apply({'params': params}, xx)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yy, -1)), model_state

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac
    )
    state = trainer.init(params)
    for i in range(7):
        state, loss = trainer.step(state, (x, y))
    assert int(state.kfac_state.step) == 7
    # factors were updated on steps 0,3,6 only: EMA applied 3 times
    assert float(jnp.abs(state.kfac_state.a['dense0'] - jnp.eye(9)).max()) > 0


def test_trainer_first_order_baseline():
    m = MLP(features=(16,), num_classes=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 8))
    y = jax.nn.one_hot(jnp.arange(16) % 4, 4)
    params = m.init(jax.random.PRNGKey(1), x)['params']

    def loss_fn(params, model_state, batch):
        xx, yy = batch
        logits = m.apply({'params': params}, xx)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yy, -1)), model_state

    trainer = training.Trainer(loss_fn=loss_fn, optimizer=optax.adam(1e-2))
    state = trainer.init(params)
    losses = []
    for _ in range(10):
        state, loss = trainer.step(state, (x, y))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_transformer_training_smoke():
    m = TransformerLM(
        vocab_size=50, d_model=32, num_heads=4, num_layers=2, max_len=16
    )
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (4, 16), 0, 50)
    targets = jnp.roll(tokens, -1, axis=1)
    params = m.init(jax.random.PRNGKey(1), tokens)['params']
    reg = kfac_tpu.register_model(m, tokens)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, damping=0.01, lr=0.05)
    loss = lm_loss(m)

    def loss_fn(params, model_state, batch):
        return loss(params, batch), model_state

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05, momentum=0.9), kfac=kfac
    )
    state = trainer.init(params)
    losses = []
    for _ in range(8):
        state, l = trainer.step(state, (tokens, targets))
        losses.append(float(l))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_grad_accumulation_matches_full_batch():
    """Two half-size micro-batches must equal one full-batch step (grads and
    curvature stats both average exactly for equal-size halves)."""
    m = MLP(features=(16,), num_classes=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 8))
    y = jax.nn.one_hot(jnp.arange(32) % 4, 4)
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(params, model_state, batch):
        xx, yy = batch
        logits = m.apply({'params': params}, xx)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yy, -1)), model_state

    def make_trainer():
        kfac = kfac_tpu.KFACPreconditioner(registry=reg, damping=0.01, kl_clip=None)
        return training.Trainer(loss_fn=loss_fn, optimizer=optax.sgd(0.1), kfac=kfac)

    t1 = make_trainer()
    s1 = t1.init(params)
    s1, l1 = t1.step(s1, (x, y))

    t2 = make_trainer()
    s2 = t2.init(params)
    micro = [(x[:16], y[:16]), (x[16:], y[16:])]
    s2, l2 = t2.step_accumulate(s2, micro)

    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(s1.params['dense0']['kernel']),
        np.asarray(s2.params['dense0']['kernel']),
        rtol=1e-4, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(s1.kfac_state.a['dense0']),
        np.asarray(s2.kfac_state.a['dense0']),
        rtol=1e-4, atol=1e-6,
    )


def test_trainer_resumes_cadence_from_restored_state():
    """A fresh Trainer driving a mid-cadence state must keep host dispatch
    aligned with the device-side lax.cond cadence: factor EMA updates must
    continue after 'resume' (regression: host counter started at 0 and the
    two cadences stayed permanently offset, silently freezing factors)."""
    m = MLP(features=(16,), num_classes=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 8))
    y = jax.nn.one_hot(jnp.arange(16) % 4, 4)
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(params, model_state, batch):
        xx, yy = batch
        logits = m.apply({'params': params}, xx)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yy, -1)), model_state

    def make_trainer():
        kfac = kfac_tpu.KFACPreconditioner(
            registry=reg, factor_update_steps=3, inv_update_steps=3,
            damping=0.01,
        )
        return training.Trainer(
            loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac
        )

    # run 4 steps (captures at 0 and 3), "restore" into a fresh Trainer
    t1 = make_trainer()
    state = t1.init(params)
    for _ in range(4):
        state, _ = t1.step(state, (x, y))
    a_before = state.kfac_state.a['dense0']

    t2 = make_trainer()  # simulates a new process after checkpoint.restore
    for _ in range(3):
        state, _ = t2.step(state, (x, y))
    # steps 4,5,6 ran; the device cadence captured at step 6 — factors moved
    assert int(state.kfac_state.step) == 7
    assert float(jnp.abs(state.kfac_state.a['dense0'] - a_before).max()) > 0


def test_scan_steps_matches_eager_loop():
    """The single-compiled lax.scan loop (device-side cadence cond) must
    produce the same trajectory as the host-dispatched eager step loop."""
    m = MLP(features=(16,), num_classes=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 8))
    y = jax.nn.one_hot(jnp.arange(16) % 4, 4)
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(params, model_state, batch):
        xx, yy = batch
        logits = m.apply({'params': params}, xx)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yy, -1)), model_state

    def make():
        kfac = kfac_tpu.KFACPreconditioner(
            registry=reg, factor_update_steps=3, inv_update_steps=3,
            damping=0.01,
        )
        return training.Trainer(
            loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac
        )

    n_steps = 7
    batches = (
        jnp.broadcast_to(x, (n_steps,) + x.shape),
        jnp.broadcast_to(y, (n_steps,) + y.shape),
    )

    t_eager = make()
    s_eager = t_eager.init(params)
    eager_losses = []
    for i in range(n_steps):
        s_eager, l = t_eager.step(s_eager, (x, y))
        eager_losses.append(float(l))

    t_scan = make()
    s_scan, losses = t_scan.scan_steps(t_scan.init(params), batches)
    np.testing.assert_allclose(
        np.asarray(losses), eager_losses, rtol=1e-5, atol=1e-7
    )
    assert int(s_scan.kfac_state.step) == n_steps
    for a, b in zip(
        jax.tree_util.tree_leaves(s_eager.params),
        jax.tree_util.tree_leaves(s_scan.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
    # factors followed the same cadence
    np.testing.assert_allclose(
        np.asarray(s_eager.kfac_state.a['dense0']),
        np.asarray(s_scan.kfac_state.a['dense0']),
        rtol=1e-5, atol=1e-6,
    )
    # the scan loop keeps working after a resume-style handoff to eager
    s_scan, _ = t_scan.step(s_scan, (x, y))
    assert int(s_scan.kfac_state.step) == n_steps + 1


def test_step_accumulate_scan_matches_eager_accumulate():
    m = MLP(features=(16,), num_classes=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (24, 8))
    y = jax.nn.one_hot(jnp.arange(24) % 4, 4)
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(m, x[:8])

    def loss_fn(params, model_state, batch):
        xx, yy = batch
        logits = m.apply({'params': params}, xx)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yy, -1)), model_state

    def make():
        kfac = kfac_tpu.KFACPreconditioner(
            registry=reg, factor_update_steps=2, inv_update_steps=2,
            damping=0.01,
        )
        return training.Trainer(
            loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac
        )

    mbs_list = [(x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8]) for i in range(3)]
    mbs_stacked = (
        jnp.stack([mb[0] for mb in mbs_list]),
        jnp.stack([mb[1] for mb in mbs_list]),
    )

    t_e = make()
    s_e = t_e.init(params)
    for _ in range(3):  # cross both cadence phases
        s_e, l_e = t_e.step_accumulate(s_e, mbs_list)

    t_s = make()
    s_s = t_s.init(params)
    for _ in range(3):
        s_s, l_s = t_s.step_accumulate_scan(s_s, mbs_stacked)

    np.testing.assert_allclose(float(l_s), float(l_e), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_e.params),
        jax.tree_util.tree_leaves(s_s.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_scan_steps_with_unexecuted_registered_layer():
    """A registered module the loss_fn never executes must not break the
    compiled loop, and its factors must stay untouched (engines treat
    stats-absent layers as keep-current-value)."""
    import flax.linen as nn

    class TwoHeads(nn.Module):
        @nn.compact
        def __call__(self, x, use_aux=False):
            h = nn.relu(nn.Dense(16, name='trunk')(x))
            if use_aux:
                return nn.Dense(4, name='aux_head')(h)
            return nn.Dense(4, name='main_head')(h)

    m = TwoHeads()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 8))
    y = jax.nn.one_hot(jnp.arange(16) % 4, 4)
    params = m.init(jax.random.PRNGKey(1), x)['params']
    aux_p = m.init(jax.random.PRNGKey(1), x, use_aux=True)['params']
    params['aux_head'] = aux_p['aux_head']
    # register BOTH heads (probe executes aux), train only main
    reg_aux = kfac_tpu.register_model(m, x, apply_fn=lambda xx: (
        m.init(jax.random.PRNGKey(0), xx), m.init(jax.random.PRNGKey(0), xx, use_aux=True)
    ))
    assert 'aux_head' in reg_aux.layers and 'main_head' in reg_aux.layers

    def loss_fn(params, model_state, batch):
        xx, yy = batch
        logits = m.apply({'params': params}, xx)  # aux never runs
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yy, -1)), model_state

    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg_aux, factor_update_steps=2, inv_update_steps=2,
        damping=0.01,
    )
    t = training.Trainer(loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac)
    state = t.init(params)
    batches = (
        jnp.broadcast_to(x, (4,) + x.shape),
        jnp.broadcast_to(y, (4,) + y.shape),
    )
    state, losses = t.scan_steps(state, batches)
    assert np.isfinite(np.asarray(losses)).all()
    # the unexecuted head's factor is untouched (identity from init)
    np.testing.assert_array_equal(
        np.asarray(state.kfac_state.a['aux_head']), np.eye(17)
    )
    assert float(jnp.abs(state.kfac_state.a['main_head'] - jnp.eye(17)).max()) > 0
    # accumulate path too
    mbs = (
        jnp.broadcast_to(x, (2,) + x.shape),
        jnp.broadcast_to(y, (2,) + y.shape),
    )
    state, _ = t.step_accumulate_scan(state, mbs)
    np.testing.assert_array_equal(
        np.asarray(state.kfac_state.a['aux_head']), np.eye(17)
    )


def test_reset_batch_discards_poisoned_accumulation():
    """AMP-overflow parity (reference base_preconditioner.py:384-387): a
    poisoned micro-batch accumulated and then dropped via reset_batch must
    leave NO trace — the finished step equals a clean step_accumulate over
    the same good micro-batches."""
    m = MLP(features=(16,), num_classes=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 8))
    y = jax.nn.one_hot(jnp.arange(32) % 4, 4)
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(params, model_state, batch):
        xx, yy = batch
        logits = m.apply({'params': params}, xx)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yy, -1)), model_state

    def make_trainer():
        kfac = kfac_tpu.KFACPreconditioner(registry=reg, damping=0.01, kl_clip=None)
        return training.Trainer(loss_fn=loss_fn, optimizer=optax.sgd(0.1), kfac=kfac)

    good = [(x[:16], y[:16]), (x[16:], y[16:])]
    poisoned = (jnp.full_like(x[:16], jnp.inf), y[:16])

    # incremental path with a simulated overflow mid-accumulation
    t1 = make_trainer()
    s1 = t1.init(params)
    t1.accumulate_microbatch(s1, good[0])
    loss_bad = t1.accumulate_microbatch(s1, poisoned)
    assert not np.isfinite(float(loss_bad))  # the overflow the scaler sees
    t1.reset_batch()
    for mb in good:
        t1.accumulate_microbatch(s1, mb)
    s1, l1 = t1.apply_accumulated(s1)

    # oracle: the same good batch with no poisoning detour
    t2 = make_trainer()
    s2 = t2.init(params)
    s2, l2 = t2.step_accumulate(s2, good)

    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(s1.params['dense0']['kernel']),
        np.asarray(s2.params['dense0']['kernel']),
        rtol=1e-6, atol=1e-7,
    )
    np.testing.assert_allclose(
        np.asarray(s1.kfac_state.a['dense0']),
        np.asarray(s2.kfac_state.a['dense0']),
        rtol=1e-6, atol=1e-7,
    )
    assert int(s1.kfac_state.step) == int(s2.kfac_state.step) == 1
    # a second apply without new accumulation is an error
    with pytest.raises(ValueError):
        t1.apply_accumulated(s1)
