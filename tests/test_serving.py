"""Posterior serving tier tests (kfac_tpu/serving/, docs/SERVING.md).

Pins PR 20's acceptance criteria:

- bucketed outputs match direct posterior calls: the MC path is
  bit-identical to the unpadded posterior-predictive formula under a
  fixed key (weight draws depend only on the key, padded rows slice
  off), across batch sizes that pad, fill, and chunk the buckets; the
  closed-form path matches ``linearized_variance`` to float tolerance;
- the same parity holds for an export from the *distributed* engine
  (``parallel.DistributedKFAC``), not just the single-host
  preconditioner;
- ``LaplacePosterior.predictive`` no longer recompiles per batch shape:
  three distinct request sizes inside one bucket land on ONE compile
  (the ``testing/compile_pins.py`` pin against the engine's own
  CompileWatch entry);
- ``warmup`` compiles exactly the configured bucket set once
  (re-warmup adds zero compiles) and ``recompiles_after_warmup`` reads
  0 after serving every padding/filling/chunking size on both paths;
- ``serve`` routing semantics: path validation, key requirements,
  threshold escalation (whole-bucket MC + per-row select), the
  closed-form fallback and the mc fallback for exports without a
  closed form;
- the metrics JSONL round-trips through the ledger's ``serving``
  stream adapter with the engine's run header;
- KFL114 pins the docs/SERVING.md knob table to the live
  ``ServingConfig`` dataclass (clean doc passes, doctored copy caught,
  rule registered).

Compile budget: one module-scope trained model + one warmed module-scope
engine carry the parity and steady-state tests; only the routing,
fallback, distributed and predictive-pin tests build private engines
(tiny model, few buckets each).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import kfac_tpu
from kfac_tpu import health as health_lib
from kfac_tpu.analysis import drift
from kfac_tpu.laplace import LaplaceConfig
from kfac_tpu.models import MLP
from kfac_tpu.observability import ledger
from kfac_tpu.parallel.kaisa import size_class
from kfac_tpu.serving import PATHS, ServingConfig, ServingEngine
from testing import compile_pins, models

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- fixtures


@pytest.fixture(scope='module')
def trained():
    """One trained tiny classifier shared by every test in the module:
    the engine/capture compiles are the expensive part, not the asserts."""
    m = MLP(features=(8,), num_classes=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 6))
    y = jax.random.randint(jax.random.PRNGKey(2), (64,), 0, 4)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, health=health_lib.HealthConfig(warn=False)
    )

    def loss_fn(p, b):
        xx, yy = b
        logits = m.apply({'params': p}, xx)
        onehot = jax.nn.one_hot(yy, 4)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))

    cap = kfac_tpu.CurvatureCapture(reg)
    _, grads, stats = cap.value_stats_and_grad(loss_fn)(params, (x, y))
    state = kfac.update_factors(kfac.init(), stats)

    def apply_fn(p, xx):
        return m.apply({'params': p}, xx)

    def phi_fn(p, xx):
        h = xx.reshape(xx.shape[0], -1)
        return jax.nn.relu(h @ p['dense0']['kernel'] + p['dense0']['bias'])

    return kfac, state, params, x, apply_fn, phi_fn


@pytest.fixture(scope='module')
def ll_dir(trained, tmp_path_factory):
    """Committed-on-disk last_layer export shared by the module."""
    kfac, state, params, _, _, _ = trained
    path = tmp_path_factory.mktemp('serving') / 'll'
    kfac_tpu.export_posterior(
        kfac, state, params, path,
        config=LaplaceConfig(mode='last_layer'), overwrite=True,
    )
    return str(path)


@pytest.fixture(scope='module')
def ll_post(ll_dir):
    return kfac_tpu.load_posterior(ll_dir)


@pytest.fixture(scope='module')
def kron_post(trained, tmp_path_factory):
    """Full-kron export: MC-only coverage (no closed form without a
    last_layer mode)."""
    kfac, state, params, _, _, _ = trained
    path = tmp_path_factory.mktemp('serving') / 'kron'
    kfac_tpu.export_posterior(kfac, state, params, path, overwrite=True)
    return kfac_tpu.load_posterior(path)


@pytest.fixture(scope='module')
def warm_engine(ll_post, trained):
    """One warmed engine shared by the parity/steady-state tests: the
    warmup covers every bucket the tests serve (8/16/24/32), so the
    compile set is paid once for the module."""
    _, _, _, x, apply_fn, phi_fn = trained
    eng = ServingEngine(
        ll_post, apply_fn, phi_fn=phi_fn,
        config=ServingConfig(
            bucket_granularity=8, max_batch=32, n_samples=4,
            warmup_batches=(8, 16, 24, 32),
        ),
    )
    report = eng.warmup(x_spec=x[:1], key=jax.random.PRNGKey(0))
    return eng, report


def _ref_mc(post, apply_fn):
    """The direct (unbucketed) posterior-predictive formula, jitted at
    the request's own shape — the offline reference the engine must
    match."""

    def raw(xx, key, n):
        keys = jax.random.split(key, n)

        def one(k):
            return jax.nn.softmax(apply_fn(post.sample_params(k), xx))

        return jax.vmap(one)(keys).mean(0)

    return jax.jit(raw, static_argnums=2)


# ------------------------------------------------------------ config knobs


def test_config_defaults_and_paths():
    cfg = ServingConfig()
    assert cfg.bucket_granularity == 32
    assert cfg.max_batch == 256
    assert cfg.n_samples is None
    assert PATHS == ('mc', 'closed_form', 'auto')


@pytest.mark.parametrize(
    'kw, match',
    [
        ({'max_batch': 0}, 'max_batch'),
        ({'bucket_granularity': 8, 'max_batch': 20}, 'multiple'),
        ({'n_samples': 0}, 'n_samples'),
        ({'n_samples': 8, 'escalated_n_samples': 4}, 'escalated'),
        ({'variance_threshold': 0.0}, 'positive'),
        ({'variance_threshold': -1.0}, 'positive'),
        ({'warmup_batches': (8, 0)}, 'warmup_batches'),
    ],
)
def test_config_validation(kw, match):
    with pytest.raises(ValueError, match=match):
        ServingConfig(**kw)


def test_bucket_mapping_uses_size_class(warm_engine):
    eng, _ = warm_engine
    for n in (1, 3, 8, 9, 13, 17, 24, 31, 32):
        assert eng.bucket(n) == size_class(n, 8)
    # requests above max_batch clamp to it (the chunker splits first)
    assert eng.bucket(999) == 32
    with pytest.raises(ValueError, match='>= 1'):
        eng.bucket(0)
    # chunking: 50 rows under max_batch=32 -> one full chunk + an 18-row
    # tail that buckets to 24
    assert eng._chunks(50) == [(0, 32), (32, 18)]


# ------------------------------------------------------- warmup & compiles


def test_warmup_compiles_the_bucket_set_once(warm_engine, trained):
    eng, report = warm_engine
    _, _, _, x, _, _ = trained
    assert report['buckets'] == [8, 16, 24, 32]
    # two programs per bucket (base MC + closed form; no escalated MC
    # without a variance_threshold), each compiled exactly once
    assert report['compiles'] == 2 * len(report['buckets'])
    # re-warmup is a no-op on the compile counter
    again = eng.warmup(x_spec=x[:1], key=jax.random.PRNGKey(0))
    assert again['compiles'] == 0
    assert eng.recompiles_after_warmup() == 0


def test_zero_recompiles_across_served_sizes(warm_engine, trained):
    """The steady-state pin: every size that pads, fills, or chunks the
    warmed buckets serves without a single fresh compile."""
    eng, _ = warm_engine
    _, _, _, x, _, _ = trained
    key = jax.random.PRNGKey(3)
    before = eng.watch.compile_count()
    for b in (3, 8, 13, 16, 32, 50):
        eng.mc_probs(x[:b], key)
        eng.closed_form(x[:b])
    assert eng.watch.compile_count() == before
    assert eng.recompiles_after_warmup() == 0


# --------------------------------------------------------- offline parity


def test_mc_parity_bit_identical_across_buckets(warm_engine, ll_post,
                                                trained):
    """Bucketed MC == the direct posterior formula, bit for bit: the
    weight draws depend only on the key (never the batch), padded rows
    are sliced off, and every chunk reuses the same key. Sizes cover
    padding (3, 13), an exact bucket fill (8, 32), and chunking (50)."""
    eng, _ = warm_engine
    _, _, _, x, apply_fn, _ = trained
    ref = _ref_mc(ll_post, apply_fn)
    key = jax.random.PRNGKey(7)
    for b in (3, 8, 13, 32, 50):
        got = np.asarray(eng.mc_probs(x[:b], key, n_samples=4))
        want = np.asarray(ref(x[:b], key, 4))
        np.testing.assert_array_equal(got, want, err_msg=f'batch {b}')
        np.testing.assert_allclose(got.sum(-1), 1.0, rtol=1e-5)


def test_predictive_matches_engine(ll_post, trained):
    """Offline ``predictive`` and the serving engine are the same code
    path now — same key, same numbers."""
    _, _, _, x, apply_fn, _ = trained
    key = jax.random.PRNGKey(11)
    off = np.asarray(ll_post.predictive(apply_fn, x[:13], key, n_samples=4))
    eng = ll_post.serving_engine(apply_fn)
    np.testing.assert_array_equal(
        off, np.asarray(eng.mc_probs(x[:13], key, n_samples=4)))


def test_closed_form_parity(warm_engine, ll_post, trained):
    _, _, _, x, apply_fn, phi_fn = trained
    eng, _ = warm_engine
    for b in (3, 8, 13, 50):
        probs, var = eng.closed_form(x[:b])
        ref_probs = jax.nn.softmax(apply_fn(ll_post.params, x[:b]))
        ref_var = ll_post.linearized_variance(phi_fn(ll_post.params, x[:b]))
        np.testing.assert_allclose(
            np.asarray(probs), np.asarray(ref_probs), rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(
            np.asarray(var), np.asarray(ref_var), rtol=1e-6, atol=1e-7)


def test_distributed_export_serves_identically(tmp_path):
    """The serving tier is engine-agnostic: an export from
    ``parallel.DistributedKFAC`` serves with the same bucketed-vs-direct
    parity as the single-host preconditioner's."""
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=32)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None)
    dk = DistributedKFAC(config=cfg, mesh=kaisa_mesh(1.0))
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(
        models.mse_loss(m))(params, (x, y))
    state, _ = jax.jit(dk.step)(dk.init(), grads, stats)

    kfac_tpu.export_posterior(
        dk, state, params, tmp_path,
        config=LaplaceConfig(mode='last_layer'), overwrite=True,
    )
    post = kfac_tpu.load_posterior(tmp_path)

    def apply_fn(p, xx):
        return m.apply({'params': p}, xx)

    def phi_fn(p, xx):
        return jax.nn.relu(xx @ p['fc1']['kernel'] + p['fc1']['bias'])

    eng = ServingEngine(
        post, apply_fn, phi_fn=phi_fn,
        config=ServingConfig(bucket_granularity=8, max_batch=32,
                             n_samples=4),
    )
    key = jax.random.PRNGKey(5)
    ref = _ref_mc(post, apply_fn)
    for b in (5, 11):
        np.testing.assert_array_equal(
            np.asarray(eng.mc_probs(x[:b], key)),
            np.asarray(ref(x[:b], key, 4)), err_msg=f'batch {b}')
    _, var = eng.closed_form(x[:11])
    np.testing.assert_allclose(
        np.asarray(var),
        np.asarray(post.linearized_variance(phi_fn(post.params, x[:11]))),
        rtol=1e-6, atol=1e-7)


# ------------------------------------------------------------ compile pin


def test_predictive_one_compile_across_batch_shapes(ll_dir, trained):
    """The PR-20 recompile fix: sweeping ``predictive`` over distinct
    batch shapes inside one padding bucket lands on ONE compiled
    program (it used to retrace the n-sample vmap per shape). Pinned
    with the shared testing/compile_pins.py helper against the engine's
    own CompileWatch entry."""
    _, _, _, x, apply_fn, _ = trained
    post = kfac_tpu.load_posterior(ll_dir)  # fresh engine cache
    key = jax.random.PRNGKey(13)
    for b in (3, 5, 8):  # three shapes, one b8 bucket
        probs = post.predictive(apply_fn, x[:b], key, n_samples=4)
        assert probs.shape == (b, 4)
    eng = post.serving_engine(apply_fn)
    compile_pins.assert_compiled_once(
        eng._watched_mc(8, 4), entry='serving.mc.b8.n4')
    assert eng.recompiles_after_warmup() == 0
    # the engine is cached per apply_fn: a fourth call adds nothing
    post.predictive(apply_fn, x[:6], key, n_samples=4)
    assert post.serving_engine(apply_fn) is eng
    compile_pins.assert_compiled_once(
        eng._watched_mc(8, 4), entry='serving.mc.b8.n4')


# ----------------------------------------------------------- serve routing


def test_serve_path_and_key_validation(warm_engine, trained):
    eng, _ = warm_engine
    _, _, _, x, _, _ = trained
    with pytest.raises(ValueError, match='path'):
        eng.serve(x[:3], key=jax.random.PRNGKey(0), path='bogus')
    with pytest.raises(ValueError, match='key'):
        eng.serve(x[:3], path='mc')


def test_serve_result_fields(warm_engine, trained):
    eng, _ = warm_engine
    _, _, _, x, _, _ = trained
    key = jax.random.PRNGKey(17)
    res = eng.serve(x[:13], key=key, path='mc')
    assert res.path == 'mc'
    assert res.probs.shape == (13, 4)
    assert res.variance is None and res.escalated is None
    assert res.bucket == (16,)
    assert res.latency_s > 0
    res_cf = eng.serve(x[:50], path='closed_form')
    assert res_cf.path == 'closed_form'
    assert res_cf.variance.shape == (50, 4)
    assert res_cf.bucket == (32, 24)
    # no threshold configured: auto == closed_form, nothing escalates
    res_auto = eng.serve(x[:8], path='auto')
    assert res_auto.escalated is None
    np.testing.assert_array_equal(
        np.asarray(res_auto.probs),
        np.asarray(eng.closed_form(x[:8])[0]))


def test_auto_routing_escalates_above_threshold(ll_post, trained):
    _, _, _, x, apply_fn, phi_fn = trained
    key = jax.random.PRNGKey(19)

    def build(threshold):
        return ServingEngine(
            ll_post, apply_fn, phi_fn=phi_fn,
            config=ServingConfig(
                bucket_granularity=8, max_batch=32, n_samples=4,
                escalated_n_samples=16, variance_threshold=threshold,
            ),
        )

    # a threshold below every variance escalates every row, and the
    # escalated rows carry exactly the 16-sample MC answer
    eng = build(1e-12)
    res = eng.serve(x[:8], key=key, path='auto')
    assert res.path == 'auto'
    assert res.escalated.dtype == jnp.bool_
    assert bool(jnp.all(res.escalated))
    np.testing.assert_array_equal(
        np.asarray(res.probs),
        np.asarray(eng.mc_probs(x[:8], key, n_samples=16)))

    # a threshold above every variance escalates nothing: the answer is
    # the closed-form one and no MC program ever compiles
    hi = build(1e9)
    res_hi = hi.serve(x[:8], key=key, path='auto')
    assert not bool(jnp.any(res_hi.escalated))
    np.testing.assert_array_equal(
        np.asarray(res_hi.probs), np.asarray(hi.closed_form(x[:8])[0]))
    assert hi.watch.compile_count('serving.mc.b8.n16') == 0

    # routing with a threshold needs a key for the escalated pass
    with pytest.raises(ValueError, match='key'):
        eng.serve(x[:3], path='auto')


def test_auto_falls_back_to_mc_without_closed_form(kron_post, trained):
    """A kron export has no closed form: ``auto`` degrades to the MC
    path, ``closed_form`` refuses with the actionable message."""
    _, _, _, x, apply_fn, _ = trained
    eng = ServingEngine(
        kron_post, apply_fn,
        config=ServingConfig(bucket_granularity=8, max_batch=32,
                             n_samples=4),
    )
    assert not eng.closed_form_available
    res = eng.serve(x[:5], key=jax.random.PRNGKey(23), path='auto')
    assert res.path == 'mc'
    assert res.variance is None and res.escalated is None
    with pytest.raises(ValueError, match='closed-form'):
        eng.closed_form(x[:5])
    with pytest.raises(ValueError, match='closed-form'):
        eng.serve(x[:5], path='closed_form')


# --------------------------------------------------------- ledger metrics


def test_metrics_roundtrip_through_serving_adapter(ll_post, trained,
                                                   tmp_path):
    """With ``metrics_path`` set the engine appends one ``serve`` record
    per answered batch under the shared run header, and the ledger's
    ``serving`` adapter reads them back with the run_id attached."""
    _, _, _, x, apply_fn, phi_fn = trained
    mpath = str(tmp_path / 'serving.jsonl')
    eng = ServingEngine(
        ll_post, apply_fn, phi_fn=phi_fn,
        config=ServingConfig(bucket_granularity=8, max_batch=32,
                             n_samples=4, metrics_path=mpath),
        run_id='abc123def456',
    )
    key = jax.random.PRNGKey(29)
    eng.serve(x[:3], key=key, path='mc')
    eng.serve(x[:50], path='closed_form')
    eng.close()

    assert ledger.ADAPTERS['serving'] is ledger.parse_serving
    events = ledger.parse_serving(mpath)
    assert len(events) == 2
    assert all(e['stream'] == 'serving' and e['kind'] == 'serve'
               for e in events)
    assert all(e['run_id'] == 'abc123def456' for e in events)
    assert events[0]['data']['requests'] == 3
    assert events[0]['data']['bucket'] == [8]
    assert events[0]['data']['path'] == 'mc'
    assert events[1]['data']['bucket'] == [32, 24]
    assert events[1]['data']['latency_ms'] > 0
    # step-less stream: events carry wall clock, never a step
    assert all(e['step'] is None and e['t'] is not None for e in events)


# ------------------------------------------------------------------ drift


def test_kfl114_clean_on_committed_doc():
    assert drift.check_serving_knobs() == []


def test_kfl114_catches_doc_drift(tmp_path):
    doc = os.path.join(REPO, 'docs', 'SERVING.md')
    with open(doc, encoding='utf-8') as f:
        text = f.read()
    doctored = tmp_path / 'SERVING.md'
    doctored.write_text(
        text.replace('| `variance_threshold` |', '| `varaince_threshold` |'))
    problems = drift.check_serving_knobs(str(doctored))
    assert problems
    assert any('variance_threshold' in p for p in problems)


def test_kfl114_registered():
    rules = {r.code for r in drift.core.all_rules()}
    assert 'KFL114' in rules
