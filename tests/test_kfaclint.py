"""kfaclint analyzer suite: per-rule fixtures, suppressions, baseline,
reporters, and the registry/doc contract.

Every KFL001–KFL005 rule is demonstrated by a true-positive fixture that
is asserted to flag *under that rule* and to be clean under every other
AST rule — so disabling (unregistering) a rule makes its fixture test
fail, which is the acceptance bar in docs/ANALYSIS.md.
"""

import json
import os
import textwrap

import pytest

from kfac_tpu import analysis
from kfac_tpu.analysis import core, drift


def run_snippet(tmp_path, source, codes=None, filename='mod.py'):
    """Write ``source`` into a scratch project and analyze it."""
    path = tmp_path / filename
    path.write_text(textwrap.dedent(source))
    project, errors = analysis.load_project(str(tmp_path))
    rules = analysis.get_rules(codes or analysis.AST_RULE_CODES)
    return analysis.analyze(project, rules, parse_errors=errors)


def codes_of(findings):
    return sorted({f.code for f in findings})


OTHER = {
    code: [c for c in analysis.AST_RULE_CODES if c != code]
    for code in analysis.AST_RULE_CODES
}


# ------------------------------------------------------------------ KFL001


KFL001_TP = '''
    from kfac_tpu import tracing

    @tracing.scope('kfac.step')
    def step(state, grads):
        scale = float(grads)
        return _apply(state, scale)

    def _apply(state, scale):
        return state.loss.item() + scale
'''


def test_kfl001_flags_host_sync(tmp_path):
    findings = run_snippet(tmp_path, KFL001_TP, ['KFL001'])
    msgs = [f.message for f in findings]
    # float() on the traced param at the entry point itself...
    assert any('float()' in m and 'step' in m for m in msgs), msgs
    # ...and .item() in a helper reached through the call graph
    assert any('.item()' in m and '_apply' in m for m in msgs), msgs


def test_kfl001_silent_when_disabled(tmp_path):
    assert run_snippet(tmp_path, KFL001_TP, OTHER['KFL001']) == []


def test_kfl001_clean_negatives(tmp_path):
    # host-side code (no scope/jit decorator) may sync freely; nested
    # defs handed to io_callback run on the host; float() on config
    # plumbing is trace-time constant folding
    assert run_snippet(tmp_path, '''
        import numpy as np
        from jax.experimental import io_callback
        from kfac_tpu import tracing

        def drain(state):
            return float(np.asarray(state.loss))

        @tracing.scope('kfac.launch')
        def launch(x, cfg):
            def compute(arr):
                return float(np.asarray(arr))
            damp = float(cfg.damping)
            return io_callback(compute, None, x), damp
    ''', ['KFL001']) == []


def test_kfl001_reaches_through_lax_cond_branch(tmp_path):
    # a function passed as a lax.cond branch is in-jit even though it is
    # never called by name
    findings = run_snippet(tmp_path, '''
        from jax import lax
        from kfac_tpu import tracing

        def _branch(x):
            return x.item()

        def _noop(x):
            return x

        @tracing.scope('kfac.maybe')
        def maybe(pred, x):
            return lax.cond(pred, _branch, _noop, x)
    ''', ['KFL001'])
    assert any('_branch' in f.message for f in findings), findings


# ------------------------------------------------------------------ KFL002


KFL002_TP = '''
    import os
    import jax

    def commit(path):
        if jax.process_index() != 0:
            return
        os.replace(path + '.tmp', path)
'''


def test_kfl002_flags_unordered_rank0_io(tmp_path):
    findings = run_snippet(tmp_path, KFL002_TP, ['KFL002'])
    assert codes_of(findings) == ['KFL002']
    assert 'os.replace()' in findings[0].message


def test_kfl002_silent_when_disabled(tmp_path):
    assert run_snippet(tmp_path, KFL002_TP, OTHER['KFL002']) == []


def test_kfl002_guard_form_a(tmp_path):
    findings = run_snippet(tmp_path, '''
        import shutil
        import jax

        def rotate(d):
            if jax.process_index() == 0:
                shutil.rmtree(d)
    ''', ['KFL002'])
    assert any('shutil.rmtree()' in f.message for f in findings)


def test_kfl002_clean_with_ordering_edge(tmp_path):
    # the PR-4 fix shape: rank-0 mutation ordered by an explicit barrier
    assert run_snippet(tmp_path, '''
        import os
        import jax
        from kfac_tpu.parallel import multihost

        def commit(path, step):
            if jax.process_index() == 0:
                os.replace(path + '.tmp', path)
            multihost.barrier(f'commit-{step}')
    ''', ['KFL002']) == []


def test_kfl002_clean_without_rank_guard(tmp_path):
    # symmetric I/O (every rank writes its own file) is not this race
    assert run_snippet(tmp_path, '''
        import os

        def spill(path):
            os.replace(path + '.tmp', path)
    ''', ['KFL002']) == []


# ------------------------------------------------------------------ KFL003


KFL003_TP = '''
    import jax

    @jax.tree_util.register_pytree_node_class
    class S:
        def __init__(self, names, a, b):
            self.names = names
            self.a = a
            self.b = b

        def tree_flatten(self):
            return ((self.b, self.a), (self.names,))

        @classmethod
        def tree_unflatten(cls, aux, children):
            (names,) = aux
            return cls(names, *children)
'''


def test_kfl003_flags_scrambled_flatten_order(tmp_path):
    findings = run_snippet(tmp_path, KFL003_TP, ['KFL003'])
    assert codes_of(findings) == ['KFL003']
    assert 'field order' in findings[0].message


def test_kfl003_silent_when_disabled(tmp_path):
    assert run_snippet(tmp_path, KFL003_TP, OTHER['KFL003']) == []


def test_kfl003_clean_consistent_pytree(tmp_path):
    assert run_snippet(tmp_path, KFL003_TP.replace(
        '((self.b, self.a), (self.names,))',
        '((self.a, self.b), (self.names,))',
    ), ['KFL003']) == []


def test_kfl003_durable_state_reading_ephemeral(tmp_path):
    findings = run_snippet(tmp_path, '''
        from typing import Any, NamedTuple

        class KState(NamedTuple):
            step: Any
            a: Any
            metrics: Any = None

        def durable_state(state):
            return {'step': state.step, 'metrics': state.metrics}
    ''', ['KFL003'])
    assert any('metrics' in f.message and 'durable_state' in f.message
               for f in findings), findings


def test_kfl003_durable_state_getattr_guard_is_clean(tmp_path):
    assert run_snippet(tmp_path, '''
        from typing import Any, NamedTuple

        class KState(NamedTuple):
            step: Any
            a: Any
            metrics: Any = None

        def durable_state(state):
            out = {'step': state.step, 'a': state.a}
            m = getattr(state, 'metrics', None)
            if m is not None:
                out['metrics'] = m
            return out
    ''', ['KFL003']) == []


def test_kfl003_state_shardings_missing_field(tmp_path):
    findings = run_snippet(tmp_path, '''
        from typing import Any, NamedTuple

        class KState(NamedTuple):
            step: Any
            a: Any
            shadow: Any = None

        def state_shardings(rep):
            return KState(step=rep, a=rep)
    ''', ['KFL003'])
    assert any('shadow' in f.message for f in findings), findings


# ------------------------------------------------------------------ KFL004


KFL004_TP = '''
    import jax
    from functools import partial

    @partial(jax.jit, static_argnames=('cfg',))
    def step(x, cfg: dict):
        if x:
            return x
        return x
'''


def test_kfl004_flags_dict_static_and_truthiness(tmp_path):
    findings = run_snippet(tmp_path, KFL004_TP, ['KFL004'])
    msgs = [f.message for f in findings]
    assert any('static arg' in m and "'cfg'" in m for m in msgs), msgs
    assert any('truthiness' in m and "'x'" in m for m in msgs), msgs


def test_kfl004_silent_when_disabled(tmp_path):
    assert run_snippet(tmp_path, KFL004_TP, OTHER['KFL004']) == []


def test_kfl004_clean_static_branch(tmp_path):
    # branching on a declared-static parameter is exactly what statics
    # are for; hashable statics are fine
    assert run_snippet(tmp_path, '''
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=('flag',))
        def step(x, flag):
            if flag:
                return x + 1
            return x
    ''', ['KFL004']) == []


def test_kfl004_dict_literal_static_kwarg(tmp_path):
    findings = run_snippet(tmp_path, '''
        import jax

        def build(f):
            return jax.jit(f, static_argnames={'cfg': 1})
    ''', ['KFL004'])
    assert any('dict literal' in f.message for f in findings)


# ------------------------------------------------------------------ KFL005


KFL005_TP = '''
    from jax.experimental import io_callback

    def launch(cb, x):
        return io_callback(cb, None, x)
'''


def test_kfl005_flags_unstated_ordering(tmp_path):
    findings = run_snippet(tmp_path, KFL005_TP, ['KFL005'])
    assert codes_of(findings) == ['KFL005']
    assert 'ordered=' in findings[0].message


def test_kfl005_silent_when_disabled(tmp_path):
    assert run_snippet(tmp_path, KFL005_TP, OTHER['KFL005']) == []


def test_kfl005_clean_with_explicit_ordered(tmp_path):
    assert run_snippet(tmp_path, KFL005_TP.replace(
        'io_callback(cb, None, x)', 'io_callback(cb, None, x, ordered=False)'
    ), ['KFL005']) == []


def test_kfl005_discarded_pure_callback(tmp_path):
    findings = run_snippet(tmp_path, '''
        import jax

        def f(cb, shape, x):
            jax.pure_callback(cb, shape, x)
            return x
    ''', ['KFL005'])
    assert any('discarded' in f.message for f in findings)


# ------------------------------------------------------------- suppressions


def test_suppression_with_reason_silences(tmp_path):
    assert run_snippet(tmp_path, KFL005_TP.replace(
        'return io_callback(cb, None, x)',
        'return io_callback(cb, None, x)  '
        '# kfaclint: disable=KFL005 (test fixture: ordering irrelevant)',
    ), ['KFL005']) == []


def test_standalone_suppression_covers_next_line(tmp_path):
    assert run_snippet(tmp_path, KFL005_TP.replace(
        'return io_callback(cb, None, x)',
        '# kfaclint: disable=KFL005 (test fixture: ordering irrelevant)\n'
        '        return io_callback(cb, None, x)',
    ), ['KFL005']) == []


def test_reasonless_suppression_is_kfl000_and_does_not_silence(tmp_path):
    findings = run_snippet(tmp_path, KFL005_TP.replace(
        'return io_callback(cb, None, x)',
        'return io_callback(cb, None, x)  # kfaclint: disable=KFL005',
    ), ['KFL005'])
    assert 'KFL000' in codes_of(findings)
    assert 'KFL005' in codes_of(findings)  # still reported


def test_malformed_directive_is_kfl000(tmp_path):
    findings = run_snippet(
        tmp_path, 'x = 1  # kfaclint: disbale=KFL005 (typo)\n', ['KFL005']
    )
    assert codes_of(findings) == ['KFL000']
    assert 'malformed' in findings[0].message


def test_kfl000_cannot_be_suppressed(tmp_path):
    findings = run_snippet(
        tmp_path,
        'x = 1  # kfaclint: disable=KFL000,KFL005\n',
        ['KFL005'],
    )
    assert 'KFL000' in codes_of(findings)


def test_mentions_in_strings_are_not_directives(tmp_path):
    assert run_snippet(tmp_path, '''
        MSG = "write a '# kfaclint: disable=CODE (reason)' comment"
    ''', ['KFL005']) == []


def test_parse_error_becomes_finding(tmp_path):
    findings = run_snippet(tmp_path, 'def broken(:\n', ['KFL005'])
    assert codes_of(findings) == ['KFL000']
    assert 'does not parse' in findings[0].message


# ----------------------------------------------------------------- baseline


def test_baseline_round_trip(tmp_path):
    findings = run_snippet(tmp_path, KFL002_TP, ['KFL002'])
    assert findings
    bpath = str(tmp_path / 'baseline.json')
    analysis.save_baseline(bpath, findings)
    loaded = analysis.load_baseline(bpath)
    new, matched = analysis.split_baseline(findings, loaded)
    assert new == [] and matched == len(findings)


def test_baseline_is_line_number_tolerant(tmp_path):
    findings = run_snippet(tmp_path, KFL002_TP, ['KFL002'])
    bpath = str(tmp_path / 'baseline.json')
    analysis.save_baseline(bpath, findings)
    shifted = [
        core.Finding(path=f.path, line=f.line + 40, code=f.code,
                     message=f.message)
        for f in findings
    ]
    new, matched = analysis.split_baseline(
        shifted, analysis.load_baseline(bpath)
    )
    assert new == [] and matched == len(findings)


def test_baseline_entries_consumed_once(tmp_path):
    findings = run_snippet(tmp_path, KFL002_TP, ['KFL002'])
    bpath = str(tmp_path / 'baseline.json')
    analysis.save_baseline(bpath, findings)
    doubled = findings + [
        core.Finding(path=f.path, line=f.line + 7, code=f.code,
                     message=f.message)
        for f in findings
    ]
    new, matched = analysis.split_baseline(
        doubled, analysis.load_baseline(bpath)
    )
    assert matched == len(findings) and len(new) == len(findings)


def test_baseline_schema_mismatch_rejected(tmp_path):
    bpath = tmp_path / 'baseline.json'
    bpath.write_text(json.dumps({'schema': 99, 'findings': []}))
    with pytest.raises(ValueError, match='schema'):
        analysis.load_baseline(str(bpath))


def test_checked_in_baseline_is_empty():
    repo = os.path.join(os.path.dirname(__file__), os.pardir)
    loaded = analysis.load_baseline(
        os.path.join(repo, 'tools', 'kfaclint_baseline.json')
    )
    assert loaded == []


# ---------------------------------------------------------------- reporters


def test_json_reporter_schema(tmp_path):
    findings = run_snippet(tmp_path, KFL002_TP, ['KFL002'])
    payload = json.loads(
        analysis.render_json(findings, baselined=2, checked=5)
    )
    assert payload['schema'] == 1
    assert payload['tool'] == 'kfaclint'
    assert payload['summary'] == {
        'total': len(findings),
        'baselined': 2,
        'files_checked': 5,
        'by_code': {'KFL002': len(findings)},
    }
    for entry in payload['findings']:
        assert set(entry) == {'code', 'rule', 'path', 'line', 'col',
                              'message'}
        assert entry['rule'] == 'rank-divergent-io'


def test_text_reporter_renders_location(tmp_path):
    findings = run_snippet(tmp_path, KFL002_TP, ['KFL002'])
    text = analysis.render_text(findings, baselined=1, checked=3)
    assert 'mod.py:' in text and 'KFL002' in text
    assert '1 baselined' in text and '3 file(s)' in text


# ----------------------------------------------------------------- registry


def test_registry_rejects_unknown_code():
    with pytest.raises(KeyError, match='KFL999'):
        analysis.get_rules(['KFL999'])


def test_registry_rejects_duplicate_registration():
    rule = analysis.all_rules()[0]
    with pytest.raises(ValueError, match='duplicate'):
        analysis.register(rule)


def test_all_ast_and_project_rules_registered():
    codes = {r.code for r in analysis.all_rules()}
    assert set(analysis.AST_RULE_CODES) <= codes
    assert set(analysis.PROJECT_RULE_CODES) <= codes


def test_doc_rule_table_in_sync():
    # KFL100 on the real repo doc: every registered rule has a row with
    # the exact registry name, and no stale rows
    assert drift.check_rule_table() == []


def test_repo_is_clean_under_ast_rules():
    # the acceptance bar: zero findings on kfac_tpu/ at head with the
    # checked-in (empty) baseline — suppressions must carry reasons
    project, errors = analysis.load_project(
        drift.REPO_ROOT, targets=['kfac_tpu']
    )
    findings = analysis.analyze(
        project, analysis.get_rules(analysis.AST_RULE_CODES),
        parse_errors=errors,
    )
    assert findings == [], [f.render() for f in findings]


# --------------------------------------------- statement-anchored suppression


def test_trailing_suppression_on_continuation_line_covers_statement(tmp_path):
    # the directive trails a *continuation* line of a wrapped call; the
    # finding anchors to the call's first line — statement anchoring must
    # cover the whole logical statement, not just the physical line
    findings = run_snippet(tmp_path, '''
        import jax

        @jax.jit
        def step(state, grads):
            y = combine(
                state,
                grads.item(),  # kfaclint: disable=KFL001 (regression: wrapped call)
            )
            return y
    ''', codes=['KFL001'])
    assert findings == [], [f.render() for f in findings]


def test_trailing_suppression_on_first_line_covers_continuations(tmp_path):
    # directive on the opening line, sync on a later line of the same call
    findings = run_snippet(tmp_path, '''
        import jax

        @jax.jit
        def step(state, grads):
            y = combine(  # kfaclint: disable=KFL001 (regression: wrapped call)
                state,
                grads.item(),
            )
            return y
    ''', codes=['KFL001'])
    assert findings == [], [f.render() for f in findings]


def test_standalone_suppression_covers_whole_next_statement(tmp_path):
    findings = run_snippet(tmp_path, '''
        import jax

        @jax.jit
        def step(state, grads):
            # kfaclint: disable=KFL001 (regression: multi-line statement)
            y = combine(
                state,
                grads.item(),
            )
            return y
    ''', codes=['KFL001'])
    assert findings == [], [f.render() for f in findings]


def test_suppression_does_not_leak_past_its_statement(tmp_path):
    # the statement range must not swallow findings in the NEXT statement
    findings = run_snippet(tmp_path, '''
        import jax

        @jax.jit
        def step(state, grads):
            y = combine(
                state,  # kfaclint: disable=KFL001 (covers only this call)
            )
            return float(grads)
    ''', codes=['KFL001'])
    assert [f.code for f in findings] == ['KFL001']
    assert 'float()' in findings[0].message


# ------------------------------------- callgraph: lambdas, partial, aliases


def test_kfl001_host_sync_behind_partial_jit_of_lambda(tmp_path):
    # the PR-7 blind spot named in ISSUE 9: a host sync hidden behind
    # partial(jit, ...) applied to a lambda — no decorator list anywhere
    findings = run_snippet(tmp_path, '''
        from functools import partial
        import jax

        step = partial(jax.jit, static_argnums=())(lambda g: float(g))
    ''', codes=['KFL001'])
    assert [f.code for f in findings] == ['KFL001']
    assert 'float()' in findings[0].message


def test_kfl001_through_jit_applied_to_named_function(tmp_path):
    findings = run_snippet(tmp_path, '''
        import jax

        def refresh(state):
            return state.metrics.item()

        refresh_jit = jax.jit(refresh)
    ''', codes=['KFL001'])
    assert [f.code for f in findings] == ['KFL001']
    assert '.item()' in findings[0].message


def test_kfl001_through_decorator_alias(tmp_path):
    findings = run_snippet(tmp_path, '''
        from functools import partial
        import jax

        _jitted = partial(jax.jit, donate_argnums=(0,))

        @_jitted
        def step(state):
            return float(state)
    ''', codes=['KFL001'])
    assert [f.code for f in findings] == ['KFL001']


def test_kfl001_lambda_argument_to_lax_cond(tmp_path):
    findings = run_snippet(tmp_path, '''
        import jax

        @jax.jit
        def outer(x):
            return jax.lax.cond(x > 0, lambda v: bool(v), lambda v: False, x)
    ''', codes=['KFL001'])
    assert [f.code for f in findings] == ['KFL001']
    assert 'bool()' in findings[0].message


def test_kfl001_partial_wrapped_callee_argument(tmp_path):
    # reachability must flow through partial(...) handed to a combinator
    findings = run_snippet(tmp_path, '''
        import jax
        from functools import partial

        def launch(cfg, x):
            return x.item()

        @jax.jit
        def outer(x):
            return jax.lax.cond(x > 0, partial(launch, None), lambda v: v, x)
    ''', codes=['KFL001'])
    assert [f.code for f in findings] == ['KFL001']
    assert '.item()' in findings[0].message


def test_kfl001_partial_alias_forwards_to_wrapped_function(tmp_path):
    findings = run_snippet(tmp_path, '''
        import jax
        from functools import partial

        def drain(cfg, x):
            return float(x)

        drain_now = partial(drain, None)

        @jax.jit
        def outer(x):
            return drain_now(x)
    ''', codes=['KFL001'])
    assert [f.code for f in findings] == ['KFL001']


def test_lambda_behind_host_callback_still_not_flagged(tmp_path):
    # host-callback argument edges stay dropped even for lambdas
    findings = run_snippet(tmp_path, '''
        import jax
        from jax.experimental import io_callback

        @jax.jit
        def outer(x):
            io_callback(lambda v: float(v), None, x, ordered=True)
            return x
    ''', codes=['KFL001'])
    assert findings == [], [f.render() for f in findings]


def test_plain_lambda_assignment_is_not_an_entry(tmp_path):
    # a lambda never wrapped in jit is host-side code
    findings = run_snippet(tmp_path, '''
        to_python = lambda g: float(g)
    ''', codes=['KFL001'])
    assert findings == [], [f.render() for f in findings]


# ------------------------------------------------------------ baseline remap


def test_remap_baseline_exact_path():
    base = [{'code': 'KFL001', 'path': 'old/a.py', 'message': 'm'}]
    out = analysis.remap_baseline(base, {'old/a.py': 'new/b.py'})
    assert out[0]['path'] == 'new/b.py'
    # non-matching entries pass through untouched
    out = analysis.remap_baseline(base, {'other.py': 'x.py'})
    assert out[0]['path'] == 'old/a.py'


def test_remap_baseline_directory_prefix():
    base = [
        {'code': 'KFL001', 'path': 'old/sub/a.py', 'message': 'm'},
        {'code': 'KFL002', 'path': 'oldish/a.py', 'message': 'm'},
    ]
    out = analysis.remap_baseline(base, {'old/': 'new/'})
    assert out[0]['path'] == 'new/sub/a.py'
    assert out[1]['path'] == 'oldish/a.py'  # prefix match is on path parts


def test_cli_baseline_remap_survives_git_mv(tmp_path, monkeypatch):
    import sys

    tools_dir = os.path.join(drift.REPO_ROOT, 'tools')
    monkeypatch.syspath_prepend(tools_dir)
    import kfaclint

    src = textwrap.dedent('''
        import jax

        @jax.jit
        def step(grads):
            return float(grads)
    ''')
    old = tmp_path / 'old_name.py'
    old.write_text(src)
    bpath = tmp_path / 'baseline.json'
    assert kfaclint.main([
        '--update-baseline', '--baseline', str(bpath), str(old),
    ]) == 0
    # simulate git mv: same content, new path — baseline keys go stale
    new = tmp_path / 'new_name.py'
    old.rename(new)
    assert kfaclint.main(['--baseline', str(bpath), str(new)]) == 1
    old_rel = os.path.relpath(str(old), drift.REPO_ROOT).replace(os.sep, '/')
    new_rel = os.path.relpath(str(new), drift.REPO_ROOT).replace(os.sep, '/')
    assert kfaclint.main([
        '--baseline', str(bpath),
        '--baseline-remap', f'{old_rel}:{new_rel}', str(new),
    ]) == 0
    assert kfaclint.main([
        '--baseline', str(bpath), '--baseline-remap', 'notapath', str(new),
    ]) == 2
