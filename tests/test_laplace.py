"""KFAC-Laplace posterior tests (kfac_tpu/laplace/).

Round-trip determinism, the TunedPlan-style schema discipline of
POSTERIOR.json (versioned, unknown/missing keys rejected), and the
export refusals (quarantined health sentinel, spilled factor slots).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import kfac_tpu
from kfac_tpu import health as health_lib
from kfac_tpu.laplace import LaplaceConfig
from kfac_tpu.models import MLP
from testing import models


@pytest.fixture(scope='module')
def trained():
    """One trained tiny classifier shared by every test in the module:
    the engine/capture compiles are the expensive part, not the asserts."""
    m = MLP(features=(8,), num_classes=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 6))
    y = jax.random.randint(jax.random.PRNGKey(2), (32,), 0, 4)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, health=health_lib.HealthConfig(warn=False)
    )

    def loss_fn(p, b):
        xx, yy = b
        logits = m.apply({'params': p}, xx)
        onehot = jax.nn.one_hot(yy, 4)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))

    cap = kfac_tpu.CurvatureCapture(reg)
    _, grads, stats = cap.value_stats_and_grad(loss_fn)(params, (x, y))
    state = kfac.init()
    state = kfac.update_factors(state, stats)

    def apply_fn(p, xx):
        return m.apply({'params': p}, xx)

    return m, params, (x, y), kfac, state, apply_fn


def _export(trained, path, **cfg_kw):
    _, params, _, kfac, state, _ = trained
    cfg = LaplaceConfig(**cfg_kw) if cfg_kw else None
    return kfac_tpu.export_posterior(
        kfac, state, params, path, config=cfg, overwrite=True
    )


def test_round_trip_determinism(trained, tmp_path):
    doc = _export(trained, tmp_path)
    post = kfac_tpu.load_posterior(tmp_path)
    assert post.fingerprint == doc['fingerprint']
    key = jax.random.PRNGKey(7)
    s1 = post.sample_params(key)
    s2 = post.sample_params(key)
    jax.tree_util.tree_map(np.testing.assert_array_equal, s1, s2)
    # a different key gives a different draw
    s3 = post.sample_params(jax.random.PRNGKey(8))
    assert float(
        jnp.abs(s1['dense0']['kernel'] - s3['dense0']['kernel']).max()
    ) > 0
    # jit matches eager: sample_params is pure in (key, stored arrays)
    s_jit = jax.jit(post.sample_params)(key)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), s1, s_jit
    )
    # the doc itself is byte-stable across re-exports (no timestamps)
    doc_bytes = open(tmp_path / 'POSTERIOR.json', 'rb').read()
    _export(trained, tmp_path)
    assert open(tmp_path / 'POSTERIOR.json', 'rb').read() == doc_bytes


def test_predictive_is_a_distribution(trained, tmp_path):
    _, _, (x, y), _, _, apply_fn = trained
    _export(trained, tmp_path)
    post = kfac_tpu.load_posterior(tmp_path)
    probs = post.predictive(apply_fn, x, jax.random.PRNGKey(0), n_samples=4)
    assert probs.shape == (32, 4)
    np.testing.assert_allclose(np.asarray(probs).sum(-1), 1.0, rtol=1e-5)
    post2, nlls = kfac_tpu.fit_prior_precision(
        post, apply_fn, (x, y), jax.random.PRNGKey(1),
        grid=(0.1, 1.0, 10.0), n_samples=4,
    )
    assert post2.config.prior_precision in (0.1, 1.0, 10.0)
    assert nlls[post2.config.prior_precision] == min(nlls.values())


def test_diag_and_last_layer_modes(trained, tmp_path):
    _, params, (x, _), _, _, apply_fn = trained
    _export(trained, tmp_path / 'diag', mode='diag')
    doc = json.load(open(tmp_path / 'diag' / 'POSTERIOR.json'))
    assert all(
        layer['arrays'] == ['da', 'dg'] for layer in doc['layers'].values()
    )
    post = kfac_tpu.load_posterior(tmp_path / 'diag')
    s = post.sample_params(jax.random.PRNGKey(0))
    assert s['head']['kernel'].shape == params['head']['kernel'].shape

    _export(trained, tmp_path / 'll', mode='last_layer')
    post_ll = kfac_tpu.load_posterior(tmp_path / 'll')
    assert sorted(post_ll.layers) == ['head']  # default: last registered
    # closed-form linearized variance: per-sample x per-class, positive
    phi = np.asarray(jax.nn.relu(x @ params['dense0']['kernel']
                                 + params['dense0']['bias']))
    var = post_ll.linearized_variance(phi)
    assert var.shape == (32, 4)
    assert float(np.min(np.asarray(var))) >= 0
    with pytest.raises(ValueError, match='last-layer'):
        kfac_tpu.load_posterior(tmp_path / 'diag').linearized_variance(phi)


def test_schema_version_rejected(trained, tmp_path):
    _export(trained, tmp_path)
    doc_path = tmp_path / 'POSTERIOR.json'
    doc = json.load(open(doc_path))
    doc['schema'] = 99
    doc_path.write_text(json.dumps(doc))
    with pytest.raises(ValueError, match='schema 99'):
        kfac_tpu.load_posterior(tmp_path)


def test_unknown_and_missing_keys_rejected(trained, tmp_path):
    _export(trained, tmp_path)
    doc_path = tmp_path / 'POSTERIOR.json'
    doc = json.load(open(doc_path))
    doc_path.write_text(json.dumps({**doc, 'surprise': 1}))
    with pytest.raises(ValueError, match='unknown'):
        kfac_tpu.load_posterior(tmp_path)
    missing = {k: v for k, v in doc.items() if k != 'fingerprint'}
    doc_path.write_text(json.dumps(missing))
    with pytest.raises(ValueError, match='missing'):
        kfac_tpu.load_posterior(tmp_path)
    os.unlink(doc_path)
    with pytest.raises(ValueError, match='no POSTERIOR.json'):
        kfac_tpu.load_posterior(tmp_path)


def test_existing_artifact_needs_overwrite(trained, tmp_path):
    _, params, _, kfac, state, _ = trained
    _export(trained, tmp_path)
    with pytest.raises(ValueError, match='already exists'):
        kfac_tpu.export_posterior(kfac, state, params, tmp_path)


def test_export_refuses_quarantined(trained, tmp_path):
    _, params, _, kfac, state, _ = trained
    name = next(iter(kfac.registry.layers))
    bad = state._replace(
        health=state.health._replace(
            quarantined={
                **state.health.quarantined, name: jnp.ones((), jnp.int32)
            }
        )
    )
    with pytest.raises(ValueError, match='quarantined'):
        kfac_tpu.export_posterior(
            kfac, bad, params, tmp_path / 'q', overwrite=True
        )


def test_export_refuses_spilled(trained, tmp_path):
    _, params, _, kfac, state, _ = trained
    spilled = state._replace(
        a={n: jnp.zeros((0,), jnp.float32) for n in state.a},
        g={n: jnp.zeros((0,), jnp.float32) for n in state.g},
    )
    with pytest.raises(ValueError, match='spilled'):
        kfac_tpu.export_posterior(
            kfac, spilled, params, tmp_path / 's', overwrite=True
        )


def test_laplace_config_validation():
    with pytest.raises(ValueError, match='mode'):
        LaplaceConfig(mode='banana')
    with pytest.raises(ValueError, match='prior_precision'):
        LaplaceConfig(prior_precision=0.0)
    with pytest.raises(ValueError, match='temperature'):
        LaplaceConfig(temperature=-1.0)
    with pytest.raises(ValueError, match='last_layer'):
        LaplaceConfig(last_layer='head')  # only meaningful in last_layer mode
    with pytest.raises(ValueError, match='n_samples'):
        LaplaceConfig(n_samples=0)


def test_frozen_layers_stay_at_map(tmp_path):
    """A mask-frozen layer is absent from the posterior: sampling returns
    its MAP value untouched (merged from params, no noise)."""
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=16, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x, mask={'fc2': False})
    kfac = kfac_tpu.KFACPreconditioner(registry=reg)
    cap = kfac_tpu.CurvatureCapture(reg)
    loss_fn = models.mse_loss(m)
    _, grads, stats = cap.value_stats_and_grad(loss_fn)(params, (x, y))
    state = kfac.update_factors(kfac.init(), stats)
    kfac_tpu.export_posterior(kfac, state, params, tmp_path, overwrite=True)
    post = kfac_tpu.load_posterior(tmp_path)
    assert sorted(post.layers) == ['fc1']
    s = post.sample_params(jax.random.PRNGKey(3))
    np.testing.assert_array_equal(s['fc2']['kernel'], params['fc2']['kernel'])
    assert float(jnp.abs(s['fc1']['kernel'] - params['fc1']['kernel']).max()) > 0
