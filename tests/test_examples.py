"""Example-trainer smoke tests (CLI surface, tiny synthetic runs)."""

import sys

import pytest


@pytest.mark.slow
def test_cifar_example_smoke(monkeypatch):
    from examples import train_cifar_resnet

    acc = train_cifar_resnet.main(
        [
            '--model', 'resnet20', '--epochs', '1', '--batch-size', '32',
            '--limit-steps', '3', '--kfac-factor-update-steps', '1',
            '--kfac-inv-update-steps', '1', '--kfac-strategy', 'hybrid-opt',
        ]
    )
    assert 0.0 <= acc <= 1.0


def test_lm_example_smoke():
    from examples import train_language_model

    ppl = train_language_model.main(
        [
            '--epochs', '1', '--batch-size', '8', '--seq-len', '32',
            '--d-model', '32', '--num-heads', '4', '--num-layers', '2',
            '--vocab-size', '128', '--limit-steps', '3',
            '--kfac-factor-update-steps', '1', '--kfac-inv-update-steps', '1',
        ]
    )
    assert ppl > 0


def test_lm_example_trains_on_real_tokenized_corpus(tmp_path):
    """End-to-end real-text path: raw text -> tools/tokenize_corpus ->
    memmapped corpus.npy + vocab.json -> LM trainer via --data-dir (the
    reference's PTB flow, examples/torch_language_model.py:80-85)."""
    import numpy as np

    from examples import data, train_language_model
    from tools import tokenize_corpus

    text = tmp_path / 'corpus.txt'
    sentences = [
        'the quick brown fox jumps over the lazy dog',
        'a stitch in time saves nine',
        'all that glitters is not gold',
        'the early bird catches the worm',
    ]
    text.write_text('\n'.join(sentences * 200) + '\n')
    out = tmp_path / 'tok'
    tokenize_corpus.main(
        [str(text), '--out-dir', str(out), '--vocab-size', '64']
    )

    # the loader memory-maps and reports the tokenizer's vocab size
    toks, vocab = data.lm_corpus(str(out))
    assert isinstance(toks, np.memmap)
    assert vocab == len(
        __import__('json').load(open(out / 'vocab.json'))['itos']
    )
    assert toks.max() < vocab

    ppl = train_language_model.main(
        [
            '--epochs', '1', '--batch-size', '8', '--seq-len', '16',
            '--d-model', '32', '--num-heads', '4', '--num-layers', '2',
            '--limit-steps', '3', '--data-dir', str(out),
            '--kfac-factor-update-steps', '1', '--kfac-inv-update-steps', '1',
        ]
    )
    import math

    assert math.isfinite(ppl) and ppl < math.exp(20.0)


def test_tokenize_corpus_rejects_empty_input(tmp_path):
    from tools import tokenize_corpus

    empty = tmp_path / 'empty.txt'
    empty.write_text('\n  \n')
    with pytest.raises(SystemExit, match='no tokens'):
        tokenize_corpus.main(
            [str(empty), '--out-dir', str(tmp_path / 'out')]
        )


def test_lm_batches_resume_consistent():
    """The window sampler is a pure function of (seed + epoch): a resumed
    run replays the uninterrupted run's batches exactly."""
    import numpy as np

    from examples import data

    toks = np.arange(1000, dtype=np.int32) % 97
    a = list(data.lm_batches(toks, 4, 16, seed=7))
    b = list(data.lm_batches(toks, 4, 16, seed=7))
    assert len(a) == len(b) > 0
    for (xa, ya), (xb, yb) in zip(a, b):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)


def test_lm_example_with_tp_and_sp():
    from examples import train_language_model

    ppl = train_language_model.main(
        [
            '--epochs', '1', '--batch-size', '4', '--seq-len', '32',
            '--d-model', '32', '--num-heads', '4', '--num-layers', '2',
            '--vocab-size', '128', '--limit-steps', '2',
            '--model-shards', '2', '--seq-shards', '2',
            '--kfac-factor-update-steps', '1', '--kfac-inv-update-steps', '1',
        ]
    )
    assert ppl > 0


@pytest.mark.slow
def test_cifar_example_no_kfac():
    from examples import train_cifar_resnet

    acc = train_cifar_resnet.main(
        [
            '--no-kfac', '--epochs', '1', '--batch-size', '32',
            '--limit-steps', '2',
        ]
    )
    assert 0.0 <= acc <= 1.0


def test_cifar_real_npz_with_augmentation(tmp_path):
    """Real-dataset path: a cifar10.npz on disk trains with normalization
    and crop/flip augmentation (VERDICT: reference examples train real
    CIFAR, examples/vision/datasets.py:1-154)."""
    import numpy as np

    from examples import data as data_lib
    from examples import train_cifar_resnet

    rng = np.random.default_rng(0)
    x, y = data_lib.synthetic_classification(256, (32, 32, 3), 10, seed=3)
    np.savez(
        tmp_path / 'cifar10.npz',
        x_train=x, y_train=y,
        x_test=x[:64], y_test=y[:64],
    )
    acc = train_cifar_resnet.main(
        [
            '--model', 'resnet20', '--epochs', '1', '--batch-size', '32',
            '--limit-steps', '3', '--data-dir', str(tmp_path),
            '--kfac-factor-update-steps', '1', '--kfac-inv-update-steps', '1',
        ]
    )
    assert 0.0 <= acc <= 1.0


@pytest.mark.slow
def test_cifar_resume_matches_uninterrupted(tmp_path):
    """Interrupted-then-resumed training must match the uninterrupted run:
    same batches (epoch-seeded), factors restored bit-exact, decomps
    rematerialized every step (cadence 1) — so final params agree
    (reference resume: torch_cifar10_resnet.py:313-354)."""
    import numpy as np

    from examples import train_cifar_resnet
    from kfac_tpu import checkpoint as ckpt_lib

    base = [
        '--model', 'resnet20', '--batch-size', '32', '--limit-steps', '2',
        '--kfac-factor-update-steps', '1', '--kfac-inv-update-steps', '1',
    ]

    # uninterrupted 2-epoch run
    d_full = str(tmp_path / 'full')
    train_cifar_resnet.main(
        base + ['--epochs', '2', '--checkpoint-dir', d_full]
    )

    # same config "killed" right after the epoch-0 checkpoint, then resumed
    # with identical flags (so the lr schedule is identical)
    from examples import common

    d_r = str(tmp_path / 'resumable')
    orig_save = common.save_checkpoint
    die = {'armed': True}

    def save_and_die(ckpt_dir, state, epoch=0, **kw):
        orig_save(ckpt_dir, state, epoch, **kw)
        if die['armed'] and epoch == 0:
            raise KeyboardInterrupt

    common.save_checkpoint = save_and_die
    try:
        import pytest as _pytest

        with _pytest.raises(KeyboardInterrupt):
            train_cifar_resnet.main(
                base + ['--epochs', '2', '--checkpoint-dir', d_r]
            )
        die['armed'] = False
        train_cifar_resnet.main(
            base + ['--epochs', '2', '--checkpoint-dir', d_r, '--resume']
        )
    finally:
        common.save_checkpoint = orig_save

    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    full = ckptr.restore(d_full + '/e00001/kfac')
    res = ckptr.restore(d_r + '/e00001/kfac')

    # factors agree between the resumed and uninterrupted runs (to float
    # tolerance: separate processes recompile, and threaded CPU matmuls are
    # not bit-reproducible across processes; bit-exactness of the
    # save/restore round-trip itself is asserted in
    # test_restore_checkpoint_roundtrip_bit_exact)
    for key in full['kfac']['a']:
        np.testing.assert_allclose(
            np.asarray(full['kfac']['a'][key]),
            np.asarray(res['kfac']['a'][key]),
            rtol=1e-3, atol=1e-5,
        )
    np.testing.assert_array_equal(
        np.asarray(full['kfac']['step']), np.asarray(res['kfac']['step'])
    )
    # params agree to float tolerance
    flat_f = jax_flat(full['params'])
    flat_r = jax_flat(res['params'])
    for a, b in zip(flat_f, flat_r):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5)


def jax_flat(tree):
    import jax
    import numpy as np

    return [np.asarray(l) for l in jax.tree_util.tree_leaves(tree)]


@pytest.mark.slow
def test_restore_checkpoint_roundtrip_bit_exact(tmp_path):
    """common.save_checkpoint -> common.restore_checkpoint restores factors
    and params bit-exact (the durable state; decomps rematerialize)."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import kfac_tpu
    from examples import common
    from kfac_tpu import training

    class M(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4, name='d1')(nn.relu(nn.Dense(16, name='d0')(x)))

    m = M()
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 8))
    y = jax.nn.one_hot(jnp.arange(32) % 4, 4)
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, damping=0.01, factor_update_steps=1, inv_update_steps=1
    )

    def loss_fn(params, model_state, batch):
        xb, yb = batch
        logits = m.apply({'params': params}, xb)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yb, -1)), model_state

    trainer = training.Trainer(loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac)
    state = trainer.init(params)
    for _ in range(3):
        state, _ = trainer.step(state, (x, y))

    common.save_checkpoint(str(tmp_path), state, epoch=0)
    restored = common.restore_checkpoint(str(tmp_path), trainer.init(params), kfac)
    assert restored is not None
    rstate, next_epoch = restored
    assert next_epoch == 1
    for name in state.kfac_state.a:
        np.testing.assert_array_equal(
            np.asarray(state.kfac_state.a[name]), np.asarray(rstate.kfac_state.a[name])
        )
        np.testing.assert_array_equal(
            np.asarray(state.kfac_state.g[name]), np.asarray(rstate.kfac_state.g[name])
        )
    for a, b in zip(jax_flat(state.params), jax_flat(rstate.params)):
        np.testing.assert_array_equal(a, b)
    assert int(rstate.kfac_state.step) == int(state.kfac_state.step)


@pytest.mark.slow
def test_imagenet_memmap_layout_and_normalization(tmp_path):
    """The on-disk memmap ImageNet layout trains through the native loader
    with per-batch normalization (x stays a read-only memmap)."""
    import numpy as np

    from examples import data as data_lib
    from examples import train_imagenet_resnet

    rng = np.random.default_rng(0)
    for split, n in (('train', 64), ('test', 16)):
        x = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 1000, n).astype(np.int32)
        np.save(tmp_path / f'imagenet_x_{split}.npy', x)
        np.save(tmp_path / f'imagenet_y_{split}.npy', y)
    (xt, yt), _ = data_lib.imagenet_like(str(tmp_path), image_size=32)
    assert isinstance(xt, np.memmap)
    acc = train_imagenet_resnet.main(
        [
            '--image-size', '32', '--epochs', '1', '--batch-size', '16',
            '--limit-steps', '2', '--data-dir', str(tmp_path),
            '--native-loader', '--arch', 'resnet20',
            '--kfac-factor-update-steps', '1', '--kfac-inv-update-steps', '1',
        ]
    )
    assert 0.0 <= acc <= 1.0


@pytest.mark.slow
def test_lm_pipeline_example_smoke():
    """The LM trainer's pipeline path (DP x PP, 1F1B) runs end to end."""
    from examples import train_language_model

    ppl = train_language_model.main(
        [
            '--d-model', '32', '--num-heads', '4', '--num-layers', '2',
            '--seq-len', '16', '--vocab-size', '64', '--epochs', '1',
            '--batch-size', '8', '--limit-steps', '3',
            '--pipeline-stages', '2', '--pipeline-microbatches', '2',
            '--kfac-factor-update-steps', '1', '--kfac-inv-update-steps', '1',
        ]
    )
    # exp(20) is the divergence cap: reaching it means loss blew up
    import math

    assert math.isfinite(ppl) and ppl < math.exp(20.0)
