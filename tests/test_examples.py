"""Example-trainer smoke tests (CLI surface, tiny synthetic runs)."""

import sys

import pytest


def test_cifar_example_smoke(monkeypatch):
    from examples import train_cifar_resnet

    acc = train_cifar_resnet.main(
        [
            '--model', 'resnet20', '--epochs', '1', '--batch-size', '32',
            '--limit-steps', '3', '--kfac-factor-update-steps', '1',
            '--kfac-inv-update-steps', '1', '--kfac-strategy', 'hybrid-opt',
        ]
    )
    assert 0.0 <= acc <= 1.0


def test_lm_example_smoke():
    from examples import train_language_model

    ppl = train_language_model.main(
        [
            '--epochs', '1', '--batch-size', '8', '--seq-len', '32',
            '--d-model', '32', '--num-heads', '4', '--num-layers', '2',
            '--vocab-size', '128', '--limit-steps', '3',
            '--kfac-factor-update-steps', '1', '--kfac-inv-update-steps', '1',
        ]
    )
    assert ppl > 0


def test_lm_example_with_tp_and_sp():
    from examples import train_language_model

    ppl = train_language_model.main(
        [
            '--epochs', '1', '--batch-size', '4', '--seq-len', '32',
            '--d-model', '32', '--num-heads', '4', '--num-layers', '2',
            '--vocab-size', '128', '--limit-steps', '2',
            '--model-shards', '2', '--seq-shards', '2',
            '--kfac-factor-update-steps', '1', '--kfac-inv-update-steps', '1',
        ]
    )
    assert ppl > 0


def test_cifar_example_no_kfac():
    from examples import train_cifar_resnet

    acc = train_cifar_resnet.main(
        [
            '--no-kfac', '--epochs', '1', '--batch-size', '32',
            '--limit-steps', '2',
        ]
    )
    assert 0.0 <= acc <= 1.0
