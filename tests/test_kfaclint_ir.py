"""IR-tier (KFL201–KFL205) analyzer suite.

Each rule gets a true-positive fixture (synthetic jaxpr or doctored
trace) and a clean negative; the cost-model parity tests assert the
acceptance bar from ISSUE 9 directly — jaxpr-counted collective bytes
for the three canonical KAISA strategies equal ``comms_report()``
byte-for-byte, and decomposition FLOPs equal
``autotune.model.decomp_flops()`` exactly. The full strategy × method ×
transport matrix runs behind the ``slow`` marker.
"""

import copy
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kfac_tpu import analysis
from kfac_tpu.analysis import drift
from kfac_tpu.analysis.ir import harness, rules, visitor

ALL_CHECKS = (
    rules.check_dtype_drift,
    rules.check_collective_axes,
    rules.check_sharding_contract,
    rules.check_step_callbacks,
    rules.check_cost_model_parity,
)


def run_all(suite):
    out = []
    for check in ALL_CHECKS:
        out.extend(check(suite))
    return out


@pytest.fixture(scope='session')
def smoke_suite():
    return harness.build('smoke')


@pytest.fixture(scope='session')
def default_suite():
    return harness.build('default')


def make_trace(fn, *args, tainted=None, step_path=False, allow=frozenset(),
               entry='step', **over):
    """Synthetic EngineTrace around a hand-written traced function."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    n = len(jaxpr.jaxpr.invars)
    return harness.EngineTrace(
        config_name='synthetic', engine='kaisa', entry=entry, jaxpr=jaxpr,
        path='kfac_tpu/analysis/ir/harness.py', line=1,
        world=len(jax.devices()), step_path=step_path,
        tainted_invars=list(tainted) if tainted is not None else [True] * n,
        callback_allowlist=allow, cfg=None, **over,
    )


def suite_of(*traces, errors=()):
    return harness.Suite('synthetic', list(traces), list(errors))


# ------------------------------------------------------------------ KFL201


def test_kfl201_flags_bf16_demotion_in_factor_math():
    def factor_update(a, stat):
        ema = 0.95 * a + 0.05 * stat.astype(jnp.bfloat16)  # the bug
        return ema @ ema.T

    x = jnp.zeros((4, 4), jnp.float32)
    findings = rules.check_dtype_drift(suite_of(make_trace(factor_update, x, x)))
    assert findings and all(f.code == 'KFL201' for f in findings)
    assert any('bfloat16' in f.message for f in findings)


def test_kfl201_flags_f64_promotion():
    with jax.experimental.enable_x64(True):
        def factor_update(a):
            return a @ a.astype(jnp.float64).T

        x = jnp.zeros((4, 4), jnp.float32)
        trace = make_trace(factor_update, x)
    findings = rules.check_dtype_drift(suite_of(trace))
    assert findings and all(f.code == 'KFL201' for f in findings)
    assert any('float64' in f.message for f in findings)


def test_kfl201_clean_on_f32_math_with_untainted_low_precision():
    def factor_update(a, wire):
        # a bf16 value NOT derived from factor math is not a finding
        # (e.g. activations in a mixed-precision fwd pass)
        _ = wire.astype(jnp.bfloat16)
        return 0.95 * a + 0.05 * (a @ a.T)

    x = jnp.zeros((4, 4), jnp.float32)
    trace = make_trace(factor_update, x, x, tainted=[True, False])
    assert rules.check_dtype_drift(suite_of(trace)) == []


def test_kfl201_taint_flows_through_while_loop():
    def ns_iter(a):
        def body(carry):
            i, m = carry
            return i + 1, (m @ m).astype(jnp.bfloat16).astype(jnp.float32)

        return jax.lax.while_loop(
            lambda c: c[0] < 3, body, (jnp.int32(0), a)
        )[1]

    x = jnp.zeros((4, 4), jnp.float32)
    findings = rules.check_dtype_drift(suite_of(make_trace(ns_iter, x)))
    assert any('bfloat16' in f.message for f in findings)


def test_kfl201_reports_trace_errors_once():
    suite = suite_of(errors=[('broken-config', '<config>', 'ValueError: x')])
    findings = rules.check_dtype_drift(suite)
    assert len(findings) == 1 and 'failed to trace' in findings[0].message


def test_kfl201_int8_compression_wire_is_not_a_violation():
    def quantize(a):
        scale = jnp.max(jnp.abs(a)) / 127.0
        return (a / scale).astype(jnp.int8), scale

    x = jnp.zeros((8,), jnp.float32)
    assert rules.check_dtype_drift(suite_of(make_trace(quantize, x))) == []


# ------------------------------------------------------------------ KFL202


def _rogue_mesh_trace():
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ('rogue',))
    spec = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec('rogue'))

    def pin(x):
        return jax.lax.with_sharding_constraint(x, spec)

    return make_trace(pin, jnp.zeros((len(jax.devices()),), jnp.float32))


def test_kfl202_flags_undeclared_axis():
    findings = rules.check_collective_axes(suite_of(_rogue_mesh_trace()))
    assert findings and all(f.code == 'KFL202' for f in findings)
    assert any("'rogue'" in f.message for f in findings)


def test_kfl202_clean_on_declared_axes(smoke_suite):
    assert rules.check_collective_axes(smoke_suite) == []


def test_kfl202_flags_chunk_plan_mismatch(smoke_suite):
    t = next(x for x in smoke_suite.traces if x.entry == 'update_factors')
    bad = copy.copy(t)
    bad.comms = copy.deepcopy(t.comms)
    st = bad.comms['stat_transport']
    st['chunks'] = []  # doctored plan: declares a count the IR can't match
    st['collectives'] = 999
    findings = rules.check_collective_axes(suite_of(bad))
    assert [f.code for f in findings] == ['KFL202']
    assert 'chunk plan' in findings[0].message


# ------------------------------------------------------------------ KFL203


def test_kfl203_flags_undeclared_state_field(smoke_suite):
    t = next(x for x in smoke_suite.traces
             if x.entry == 'step' and x.declared_shardings is not None)
    bad = copy.copy(t)
    # doctor the declared tree so its structure no longer matches the
    # real state — the drifted-contract hazard the rule exists for
    bad.declared_shardings = {'doctored': t.declared_shardings}
    findings = rules.check_sharding_contract(suite_of(bad))
    assert [f.code for f in findings] == ['KFL203']
    assert 'differs from the real state tree' in findings[0].message


def test_kfl203_clean_on_real_contract(smoke_suite):
    assert rules.check_sharding_contract(smoke_suite) == []


def test_kfl203_dense_engine_has_no_contract_and_is_skipped(default_suite):
    dense = [t for t in default_suite.traces if t.engine == 'dense']
    assert dense, 'default profile must include the dense engine'
    assert all(t.declared_shardings is None for t in dense)


# ------------------------------------------------------------------ KFL204


def _callback_step_trace(allow):
    def step(x):
        jax.experimental.io_callback(
            lambda v: None, None, x, ordered=False
        )
        return x + 1

    return make_trace(step, jnp.zeros((2,), jnp.float32),
                      step_path=True, allow=allow)


def test_kfl204_flags_undeclared_step_callback():
    findings = rules.check_step_callbacks(suite_of(_callback_step_trace(
        frozenset()
    )))
    assert [f.code for f in findings] == ['KFL204']
    assert 'io_callback' in findings[0].message


def test_kfl204_allowlisted_callback_is_clean():
    assert rules.check_step_callbacks(suite_of(_callback_step_trace(
        frozenset({'io_callback'})
    ))) == []


def test_kfl204_async_host_config_is_allowlisted(default_suite):
    t = next(x for x in default_suite.traces
             if 'async-host' in x.config_name and x.entry == 'step')
    # the callback is really there AND really allowlisted — the rule's
    # pass on this config is a decision, not absence of signal
    assert visitor.callback_eqns(t.jaxpr)
    assert 'io_callback' in t.callback_allowlist
    assert rules.check_step_callbacks(default_suite) == []


def test_kfl204_ignores_off_step_path_entries():
    trace = _callback_step_trace(frozenset())
    trace.step_path = False
    assert rules.check_step_callbacks(suite_of(trace)) == []


# ------------------------------------------------------------------ KFL205

#: world=8 maps the canonical fracs onto the three KAISA strategies
CANONICAL = {1.0: 'COMM_OPT', 0.5: 'HYBRID_OPT', 0.125: 'MEM_OPT'}


@pytest.fixture(scope='session')
def canonical_traces():
    world = len(jax.devices())
    out = {}
    for frac in CANONICAL:
        spec = harness._ConfigSpec(
            f'parity-f{frac}', 'kaisa', 16, frac, {}
        )
        out[frac] = {t.entry: t for t in harness._trace_config(spec, world)}
    return out


@pytest.mark.parametrize('frac', sorted(CANONICAL))
def test_kfl205_byte_parity_three_canonical_strategies(
    canonical_traces, frac
):
    # the acceptance bar: jaxpr-counted collective bytes == comms_report,
    # byte-for-byte, for COMM_OPT / HYBRID_OPT / MEM_OPT
    by = canonical_traces[frac]
    comms = by['update_factors'].comms
    assert comms['strategy'] == CANONICAL[frac]

    uf = visitor.constraint_pins(by['update_factors'].jaxpr)
    assert visitor.replicated_pin_bytes(uf) == (
        comms['stat_transport']['wire_bytes']
    )

    ui = visitor.constraint_pins(by['update_inverses'].jaxpr)
    assert visitor.total_pin_bytes(ui) == comms['decomp_reshard_bytes']

    pc = visitor.constraint_pins(by['precondition'].jaxpr)
    mult = 2 if comms['strategy'] == 'COMM_OPT' else 1  # documented: the
    # replicated eigenbasis under COMM_OPT pins the broadcast twice
    assert visitor.rank3_replicated_pin_bytes(pc) == (
        comms['grad_broadcast_bytes'] * mult
    )


def test_kfl205_eigh_flop_parity(canonical_traces):
    t = canonical_traces[0.5]['update_inverses']
    got = visitor.eigh_flops(t.jaxpr) * t.world
    assert got == t.expected_decomp_flops  # exact, not approximate


def test_kfl205_newton_schulz_flop_parity():
    import kfac_tpu

    world = len(jax.devices())
    spec = harness._ConfigSpec(
        'parity-ns', 'kaisa', 16, 0.5,
        dict(compute_method=kfac_tpu.ComputeMethod.INVERSE,
             inverse_solver='newton_schulz', newton_schulz_iters=6),
    )
    by = {t.entry: t for t in harness._trace_config(spec, world)}
    t = by['update_inverses']
    got = visitor.while_dot_flops(t.jaxpr, t.cfg.newton_schulz_iters) * world
    assert got == t.expected_decomp_flops


def test_kfl205_flags_model_divergence(smoke_suite):
    t = next(x for x in smoke_suite.traces if x.entry == 'update_factors')
    bad = copy.copy(t)
    bad.comms = copy.deepcopy(t.comms)
    bad.comms['stat_transport']['wire_bytes'] += 4
    findings = rules.check_cost_model_parity(suite_of(bad))
    assert [f.code for f in findings] == ['KFL205']
    assert 'cost model' in findings[0].message


def test_kfl205_clean_at_head(default_suite):
    assert rules.check_cost_model_parity(default_suite) == []


def test_kfl205_skips_async_host_decomposition(default_suite):
    # async-host moves the decomposition out of the traced program; its
    # update_inverses must be skipped by parity, not falsely flagged
    t = next(x for x in default_suite.traces
             if 'async-host' in x.config_name and x.entry == 'update_inverses')
    assert not rules._decomp_in_jit(t.cfg)


# ------------------------------------------------------- head-clean + wiring


def test_smoke_profile_clean_at_head(smoke_suite):
    findings = run_all(smoke_suite)
    assert findings == [], [f.render() for f in findings]
    assert smoke_suite.errors == []


def test_default_profile_clean_at_head(default_suite):
    findings = run_all(default_suite)
    assert findings == [], [f.render() for f in findings]
    assert default_suite.errors == []


@pytest.mark.slow
def test_full_matrix_clean_at_head():
    suite = harness.build('full')
    assert suite.errors == []
    # the full matrix must include compression, prediv, host-eigh and
    # the sub-unity fractions — guard against silent profile shrinkage
    names = {t.config_name for t in suite.traces}
    assert any('int8' in n for n in names)
    assert any('prediv' in n for n in names)
    assert any('eigh-host' in n for n in names)
    findings = run_all(suite)
    assert findings == [], [f.render() for f in findings]


def test_ir_rules_registered_with_ir_kind():
    by_code = {r.code: r for r in analysis.all_rules()}
    for code in analysis.IR_RULE_CODES:
        assert code in by_code, code
        assert by_code[code].kind == 'ir'


def test_both_engines_register_entry_points():
    from kfac_tpu import preconditioner
    from kfac_tpu.parallel import kaisa

    for cls in (preconditioner.KFACPreconditioner, kaisa.DistributedKFAC):
        assert cls.IR_ENTRY_POINTS == (
            'update_factors', 'update_inverses', 'precondition', 'step',
        )
        assert set(cls.IR_STEP_PATH) <= set(cls.IR_ENTRY_POINTS)
        for entry in cls.IR_ENTRY_POINTS:
            assert callable(getattr(cls, entry))


def test_trace_targets_cover_both_engines(default_suite):
    engines = {t.engine for t in default_suite.traces}
    assert engines == {'kaisa', 'dense'}
    entries = {t.entry for t in default_suite.traces}
    assert entries == set(
        ('update_factors', 'update_inverses', 'precondition', 'step')
    )


def test_finding_paths_anchor_to_real_entry_defs(smoke_suite):
    for t in smoke_suite.traces:
        assert os.path.exists(os.path.join(drift.REPO_ROOT, t.path)), t.path
        assert t.line > 1


def test_cli_ir_smoke_exits_clean(monkeypatch):
    import sys  # noqa: F401

    monkeypatch.syspath_prepend(os.path.join(drift.REPO_ROOT, 'tools'))
    import kfaclint

    assert kfaclint.main(['--ir', '--smoke']) == 0


def test_invalid_profile_rejected():
    with pytest.raises(ValueError, match='unknown IR profile'):
        harness.set_profile('warp')
