"""Dynamic loss scaling: scaler semantics + the fp16 end-to-end flow
(reference examples/vision/engine.py:80-88 torch.cuda.amp parity).

The end-to-end recovery run is slow-marked: fp16 matmuls are software-
emulated on CPU (~8 s/step), so the 40-step flow costs minutes while the
scaler semantics it rides on are pinned by the fast unit tests above it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kfac_tpu import amp


def test_scaler_backoff_and_growth():
    s = amp.init(1024.0)
    # overflow halves and resets the good-step count
    s = amp.update(s, jnp.asarray(False))
    assert float(s.scale) == 512.0 and int(s.good_steps) == 0
    # growth after growth_interval consecutive good steps
    for _ in range(3):
        s = amp.update(s, jnp.asarray(True), growth_interval=3)
    assert float(s.scale) == 1024.0
    assert int(s.good_steps) == 0  # counter resets at growth
    # partial streaks do not grow
    s2 = amp.update(s, jnp.asarray(True), growth_interval=3)
    assert float(s2.scale) == 1024.0 and int(s2.good_steps) == 1


def test_all_finite_and_unscale():
    good = {'a': jnp.ones((2, 2)), 'b': jnp.zeros(3)}
    assert bool(amp.all_finite(good))
    bad = {'a': jnp.ones((2, 2)).at[0, 0].set(jnp.inf), 'b': jnp.zeros(3)}
    assert not bool(amp.all_finite(bad))
    nan = {'a': jnp.array([jnp.nan])}
    assert not bool(amp.all_finite(nan))
    un = amp.unscale({'g': jnp.full((2,), 8.0)}, jnp.asarray(4.0))
    np.testing.assert_allclose(np.asarray(un['g']), [2.0, 2.0])


@pytest.mark.slow
def test_amp_training_recovers_from_real_overflow():
    """examples/train_amp.py end to end on a tiny config with an absurd
    initial scale: fp16 cotangents MUST overflow (scale * O(0.1) >> 65504),
    the step is skipped in-jit, the scale halves until representable,
    training proceeds, and the K-FAC step counter advances only on applied
    steps."""
    from examples import train_amp

    loss, skipped, kfac_steps = train_amp.main([
        '--steps', '40',
        '--batch-size', '32',
        '--init-scale', str(2.0**24),
        '--growth-interval', '1000',
    ])
    assert skipped >= 1, 'the absurd initial scale must trigger a real overflow'
    assert kfac_steps == 40 - skipped, 'skipped steps must not advance K-FAC'
    assert np.isfinite(loss)
    # after recovery the remaining steps actually train (loss below the
    # 10-class uniform 2.3026 takes only a handful of applied steps)
    assert loss < 2.3
