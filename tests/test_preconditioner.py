"""Preconditioner state-machine and training-smoke tests.

Behavioral targets: reference tests/base_preconditioner_test.py (hooks /
state dict / step pipeline) and tests/training_test.py:15-79 (loss strictly
decreases over 20 steps of TinyModel).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import kfac_tpu
from kfac_tpu import enums
from kfac_tpu.ops import factors as factors_lib
from testing import models


def _setup(compute_method=enums.ComputeMethod.EIGEN, **kw):
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=32, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    loss_fn = models.mse_loss(m)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, compute_method=compute_method, **kw
    )
    return m, params, (x, y), reg, loss_fn, kfac


def test_init_state_shapes():
    _, _, _, reg, _, kfac = _setup()
    state = kfac.init()
    assert int(state.step) == 0
    for name, h in reg.layers.items():
        assert state.a[name].shape == h.a_factor_shape
        assert state.g[name].shape == h.g_factor_shape
        np.testing.assert_allclose(state.a[name], np.eye(h.a_factor_shape[0]))
    assert state.a_inv == {}  # eigen method leaves inverse slots empty


def test_factor_ema_identity_init_semantics():
    _, params, batch, reg, loss_fn, kfac = _setup(factor_decay=0.9)
    cap = kfac_tpu.CurvatureCapture(reg)
    _, grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    state = kfac.init()
    state2 = kfac.update_factors(state, stats)
    expected = 0.9 * np.eye(7) + 0.1 * np.asarray(stats.a['fc1'])
    np.testing.assert_allclose(state2.a['fc1'], expected, rtol=1e-5, atol=1e-6)


def test_step_preconditions_and_advances():
    _, params, batch, reg, loss_fn, kfac = _setup(kl_clip=None)
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    state = kfac.init()
    state, pgrads = jax.jit(kfac.step)(state, grads, stats)
    assert int(state.step) == 1
    # preconditioned grads differ from raw grads but are finite
    for name in reg.names():
        raw = grads[name]['kernel']
        new = pgrads[name]['kernel']
        assert new.shape == raw.shape
        assert bool(jnp.isfinite(new).all())
        assert float(jnp.abs(new - raw).max()) > 0


def test_unregistered_params_pass_through():
    m, params, batch, reg_full, loss_fn, _ = _setup()
    reg = kfac_tpu.register_model(m, batch[0], skip_layers=['fc2'])
    kfac = kfac_tpu.KFACPreconditioner(registry=reg)
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    state = kfac.init()
    _, pgrads = kfac.step(state, grads, stats)
    np.testing.assert_array_equal(pgrads['fc2']['kernel'], grads['fc2']['kernel'])
    assert float(jnp.abs(pgrads['fc1']['kernel'] - grads['fc1']['kernel']).max()) > 0


def test_identity_factors_recover_sgd_direction():
    """With A=G=I and damping d, preconditioned grad = grad / (1 + d)."""
    _, params, batch, reg, loss_fn, kfac = _setup(kl_clip=None, damping=0.0)
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, _ = cap.value_stats_and_grad(loss_fn)(params, batch)
    state = kfac.init()
    # skip factor update entirely: factors stay identity; inverses at step 0
    state = kfac.update_inverses(state)
    pgrads = kfac.precondition(state, grads)
    np.testing.assert_allclose(
        pgrads['fc1']['kernel'], grads['fc1']['kernel'], rtol=1e-4, atol=1e-6
    )


@pytest.mark.parametrize('method', [enums.ComputeMethod.EIGEN, enums.ComputeMethod.INVERSE])
def test_eigen_and_inverse_methods_agree(method):
    """For PSD factors both methods solve the same damped Kronecker system."""
    _, params, batch, reg, loss_fn, _ = _setup()
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    results = {}
    for cm in (enums.ComputeMethod.EIGEN, enums.ComputeMethod.INVERSE):
        kfac = kfac_tpu.KFACPreconditioner(
            registry=reg, compute_method=cm, kl_clip=None, damping=0.01
        )
        state = kfac.init()
        state = kfac.update_factors(state, stats)
        state = kfac.update_inverses(state)
        results[cm] = kfac.precondition(state, grads)
    e = results[enums.ComputeMethod.EIGEN]['fc1']['kernel']
    i = results[enums.ComputeMethod.INVERSE]['fc1']['kernel']
    # eigen solves (G x A + l)^-1 exactly; inverse approximates with
    # (G + lI)^-1 (x) (A + lI)^-1 — close but not equal. Loose tolerance.
    np.testing.assert_allclose(e, i, rtol=0.35, atol=5e-3)


def test_kl_clip_bounds_update_norm():
    _, params, batch, reg, loss_fn, kfac = _setup(kl_clip=1e-8, lr=1.0)
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    state = kfac.init()
    _, pgrads = kfac.step(state, grads, stats)
    _, pgrads_noclip = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None).step(
        kfac.init(), grads, stats
    )
    n_clip = float(jnp.linalg.norm(pgrads['fc1']['kernel']))
    n_noclip = float(jnp.linalg.norm(pgrads_noclip['fc1']['kernel']))
    assert n_clip < n_noclip


def test_update_cadence():
    """Factors only move on factor_update_steps boundaries."""
    _, params, batch, reg, loss_fn, kfac = _setup(
        factor_update_steps=2, inv_update_steps=2, kl_clip=None
    )
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    state = kfac.init()
    step_fn = jax.jit(kfac.step)
    state1, _ = step_fn(state, grads, stats)   # step 0: update
    a_after0 = np.asarray(state1.a['fc1'])
    state2, _ = step_fn(state1, grads, stats)  # step 1: no update
    np.testing.assert_array_equal(np.asarray(state2.a['fc1']), a_after0)
    state3, _ = step_fn(state2, grads, stats)  # step 2: update
    assert np.abs(np.asarray(state3.a['fc1']) - a_after0).max() > 0


def test_schedule_hyperparams():
    """Callable-or-constant hyperparams resolved on the traced step
    (reference: kfac/base_preconditioner.py:160-208)."""
    _, params, batch, reg, loss_fn, _ = _setup()
    damping_fn = lambda step: 0.01 * jnp.exp(-0.1 * step.astype(jnp.float32))
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, damping=damping_fn, kl_clip=None)
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    state = kfac.init()
    state, pg = jax.jit(kfac.step)(state, grads, stats)
    assert bool(jnp.isfinite(pg['fc1']['kernel']).all())


def test_rematerialize_after_restore():
    """Factors survive a save/load roundtrip; decomps are recomputed
    (reference semantics: kfac/base_preconditioner.py:296-308)."""
    _, params, batch, reg, loss_fn, kfac = _setup(kl_clip=None)
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    state = kfac.init()
    state, _ = kfac.step(state, grads, stats)
    # simulate checkpoint: keep only step/a/g
    restored = kfac.init()._replace(step=state.step, a=state.a, g=state.g)
    restored = kfac.rematerialize(restored)
    np.testing.assert_allclose(
        np.asarray(restored.qa['fc1']), np.asarray(state.qa['fc1']),
        rtol=1e-4, atol=1e-5,
    )
    p1 = kfac.precondition(state, grads)
    p2 = kfac.precondition(restored, grads)
    np.testing.assert_allclose(
        p1['fc1']['kernel'], p2['fc1']['kernel'], rtol=1e-4, atol=1e-6
    )


def test_memory_usage_reports_bytes():
    _, _, _, reg, _, kfac = _setup()
    state = kfac.init()
    usage = kfac.memory_usage(state)
    assert usage['total'] > 0
    assert usage['a_factors'] == sum(
        np.prod(h.a_factor_shape) * 4 for h in reg.layers.values()
    )


@pytest.mark.parametrize('method', ['eigen', 'inverse'])
def test_training_loss_decreases(method):
    """20 K-FAC-SGD steps on TinyModel must strictly reduce the loss
    (analogue of reference tests/training_test.py:15-79)."""
    m, params, batch, reg, loss_fn, _ = _setup()
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, compute_method=method, damping=0.003, lr=0.05
    )
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(loss_fn)
    state = kfac.init()

    @jax.jit
    def train_step(params, state, batch):
        (loss, _), grads, stats = run(params, batch)
        state, pgrads = kfac.step(state, grads, stats)
        params = jax.tree_util.tree_map(lambda p, g: p - 0.05 * g, params, pgrads)
        return params, state, loss

    losses = []
    for _ in range(20):
        params, state, loss = train_step(params, state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_training_conv_net_decreases():
    m = models.TinyConvNet()
    x = jax.random.normal(jax.random.PRNGKey(5), (8, 32, 32, 1))
    labels = jnp.arange(8) % 10
    y = jax.nn.one_hot(labels, 10)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(p, batch):
        xx, yy = batch
        logits = m.apply({'params': p}, xx)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yy, axis=-1))

    kfac = kfac_tpu.KFACPreconditioner(registry=reg, damping=0.01, lr=0.05)
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(loss_fn)
    state = kfac.init()

    @jax.jit
    def train_step(params, state, batch):
        (loss, _), grads, stats = run(params, batch)
        state, pgrads = kfac.step(state, grads, stats)
        params = jax.tree_util.tree_map(lambda p, g: p - 0.05 * g, params, pgrads)
        return params, state, loss

    losses = []
    for _ in range(15):
        params, state, loss = train_step(params, state, (x, y))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


# ---------------------------------------------------------- platform defaults


def test_default_compute_method_per_platform():
    # TPU gets the matmul-only Newton-Schulz INVERSE path; everything else
    # keeps the reference's EIGEN default (kfac/preconditioner.py:245-256).
    assert kfac_tpu.default_compute_method('tpu') == (
        enums.ComputeMethod.INVERSE,
        'newton_schulz',
    )
    for platform in ('cpu', 'gpu', 'cuda'):
        assert kfac_tpu.default_compute_method(platform) == (
            enums.ComputeMethod.EIGEN,
            'cholesky',
        )


def test_unset_compute_method_resolves_to_platform_default():
    m = models.TinyModel()
    x, _ = models.regression_data(jax.random.PRNGKey(1), n=8, dim=6)
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg)
    # conftest pins JAX_PLATFORMS=cpu, so the resolved default is EIGEN.
    assert kfac.compute_method == enums.ComputeMethod.EIGEN
    assert kfac.inverse_solver == 'cholesky'


def test_fully_pinned_config_never_touches_the_backend(monkeypatch):
    """jax.default_backend() initializes the JAX backend as a side effect;
    a config with compute_method, inverse_solver, and bucket_granularity
    all explicit must not call it (first-touch hazard: constructing a
    config would otherwise lock the platform before a caller's
    jax.config.update('jax_platforms', ...))."""
    m = models.TinyModel()
    x, _ = models.regression_data(jax.random.PRNGKey(1), n=8, dim=6)
    reg = kfac_tpu.register_model(m, x)

    def boom():
        raise AssertionError('backend touched during pinned-config init')

    monkeypatch.setattr(jax, 'default_backend', boom)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg,
        compute_method='inverse',
        inverse_solver='newton_schulz',
        bucket_granularity=1,
    )
    assert kfac.inverse_solver == 'newton_schulz'
    # Explicit EIGEN is also pinned: the TPU perf warning probes the
    # platform ONLY when the backend is already initialized, so an
    # uninitialized backend stays untouched (the warning is skipped).
    from jax._src import xla_bridge

    monkeypatch.setattr(xla_bridge, 'backends_are_initialized', lambda: False)
    kfac_tpu.KFACPreconditioner(
        registry=reg,
        compute_method='eigen',
        inverse_solver='cholesky',
        bucket_granularity=1,
    )


def test_forced_eigen_on_tpu_warns(monkeypatch):
    m = models.TinyModel()
    x, _ = models.regression_data(jax.random.PRNGKey(1), n=8, dim=6)
    reg = kfac_tpu.register_model(m, x)
    monkeypatch.setattr(jax, 'default_backend', lambda: 'tpu')
    with pytest.warns(kfac_tpu.warnings.TPUPerformanceWarning):
        kfac_tpu.KFACPreconditioner(registry=reg, compute_method='eigen')
    # unset on TPU: silent, resolves to the native path
    import warnings as stdlib_warnings

    with stdlib_warnings.catch_warnings():
        stdlib_warnings.simplefilter('error')
        kfac = kfac_tpu.KFACPreconditioner(registry=reg)
    assert kfac.compute_method == enums.ComputeMethod.INVERSE
    assert kfac.inverse_solver == 'newton_schulz'
