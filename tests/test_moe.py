"""Mixture-of-Experts K-FAC tests (beyond the reference: EP factor buckets).

The per-expert Dense submodules register as individual K-FAC layers with
shared shapes, so the stacked distributed engine buckets them together and
shards their eigendecompositions — expert-parallel second-order work with
no engine changes.
"""

import jax
import jax.numpy as jnp
import numpy as np

import kfac_tpu
from kfac_tpu.models import TransformerLM, lm_loss, moe
from kfac_tpu.parallel import DistributedKFAC, batch_sharding, kaisa_mesh
from kfac_tpu.parallel import tensor_parallel
from kfac_tpu.parallel import mesh as mesh_lib


def _moe_lm(**kw):
    cfg = dict(
        vocab_size=64, d_model=32, num_heads=4, num_layers=2, max_len=16,
        num_experts=4, moe_every=2,
    )
    cfg.update(kw)
    return TransformerLM(**cfg)


def test_moe_registration_and_bucketing():
    m = _moe_lm()
    tokens = jnp.zeros((4, 16), jnp.int32)
    reg = kfac_tpu.register_model(m, tokens)
    names = reg.names()
    assert 'block1/moe/router' in names
    experts = [n for n in names if 'expert' in n]
    assert len(experts) == 8  # 4 experts x (up, down)
    # the stacked engine groups the shape-sharing experts into buckets
    dk = DistributedKFAC(
        config=kfac_tpu.KFACPreconditioner(registry=reg),
        mesh=kaisa_mesh(grad_worker_fraction=0.5),
    )
    by_bucket = {b.key: b.layers for b in dk.buckets}
    up_bucket = next(
        layers for layers in by_bucket.values()
        if any('expert0_up' in n for n in layers)
    )
    assert sum('expert' in n for n in up_bucket) == 4  # all up experts share


def test_moe_kfac_training_decreases_loss_and_factors_differ():
    m = _moe_lm()
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    targets = jnp.roll(tokens, -1, 1)
    params = m.init(jax.random.PRNGKey(1), tokens)['params']
    reg = kfac_tpu.register_model(m, tokens)
    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    dk = DistributedKFAC(
        config=kfac_tpu.KFACPreconditioner(
            registry=reg, damping=0.01, lr=0.1,
            factor_update_steps=1, inv_update_steps=1,
        ),
        mesh=mesh,
    )
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(lm_loss(m))
    state = dk.init()

    @jax.jit
    def step(params, state, batch):
        (l, _), grads, stats = run(params, batch)
        state, pg = dk.step(state, grads, stats)
        return jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g, params, pg
        ), state, l

    bs = batch_sharding(mesh)
    batch = (jax.device_put(tokens, bs), jax.device_put(targets, bs))
    losses = []
    for _ in range(6):
        params, state, l = step(params, state, batch)
        losses.append(float(l))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))

    # routing sends different tokens to different experts, so their
    # captured factors diverge
    (_, _), _, stats = run(params, batch)
    a0 = np.asarray(stats.a['block1/moe/expert0_up'])
    a1 = np.asarray(stats.a['block1/moe/expert1_up'])
    assert float(np.abs(a0 - a1).max()) > 1e-8


def test_moe_expert_parallel_layout():
    """expert_tp_overrides shards expert weights Megatron-style over the
    model axis; training still runs under GSPMD."""
    mesh = mesh_lib.train_mesh(grad_worker_fraction=1.0, model=2)
    m = _moe_lm()
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    targets = jnp.roll(tokens, -1, 1)
    params = m.init(jax.random.PRNGKey(1), tokens)['params']
    reg = kfac_tpu.register_model(m, tokens)
    specs = tensor_parallel.registry_param_specs(
        params, reg, overrides=moe.expert_tp_overrides(),
        warn_unmatched=False,
    )
    from jax.sharding import PartitionSpec as P

    assert specs['block1']['moe']['expert0_up']['kernel'] == P(None, 'model')
    assert specs['block1']['moe']['expert0_down']['kernel'] == P('model', None)
    tp_params = tensor_parallel.shard_params_from_registry(
        params, mesh, reg, overrides=moe.expert_tp_overrides(),
        warn_unmatched=False,
    )
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(lm_loss(m))
    dk = DistributedKFAC(
        config=kfac_tpu.KFACPreconditioner(registry=reg, damping=0.01),
        mesh=mesh,
    )
    state = dk.init()

    @jax.jit
    def step(params, state, batch):
        (l, _), grads, stats = run(params, batch)
        state, pg = dk.step(state, grads, stats)
        return jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g, params, pg
        ), state, l

    ts = mesh_lib.token_sharding(mesh)
    batch = (jax.device_put(tokens, ts), jax.device_put(targets, ts))
    tp_params, state, l = step(tp_params, state, batch)
    assert np.isfinite(float(l))


def test_capacity_dispatch_matches_dense_when_capacity_suffices():
    """With enough slots for every token, the capacity path reproduces the
    dense masked path exactly (same params, same routing)."""
    dense = _moe_lm()
    sparse = _moe_lm(moe_capacity_factor=float(4))  # C = T: nothing drops
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 64)
    params = dense.init(jax.random.PRNGKey(1), tokens)['params']
    # identical parameter structure: the capacity path reuses the same
    # named expert modules
    chex = jax.tree_util.tree_structure(params)
    assert chex == jax.tree_util.tree_structure(
        sparse.init(jax.random.PRNGKey(1), tokens)['params']
    )
    y_dense = dense.apply({'params': params}, tokens)
    y_sparse = sparse.apply({'params': params}, tokens)
    np.testing.assert_allclose(
        np.asarray(y_dense), np.asarray(y_sparse), atol=1e-5
    )


def test_capacity_dispatch_drops_overflow_tokens():
    """With one slot per expert, at most num_experts tokens get expert
    output; dropped tokens pass through the residual unchanged."""
    m = moe.MoEMLP(num_experts=2, capacity_factor=None)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 12, 8))
    params = m.init(jax.random.PRNGKey(1), x)['params']
    tight = moe.MoEMLP(num_experts=2, capacity_factor=2 * 1.0 / 12)  # C=1
    y = tight.apply({'params': params}, x)
    # at most 2 rows (one slot per expert) are nonzero
    nonzero_rows = int(jnp.sum(jnp.any(jnp.abs(y[0]) > 1e-9, axis=-1)))
    assert nonzero_rows <= 2
    # and those rows match the dense path's output for the same tokens
    y_dense = m.apply({'params': params}, x)
    rows = jnp.any(jnp.abs(y[0]) > 1e-9, axis=-1)
    np.testing.assert_allclose(
        np.asarray(y[0][rows]), np.asarray(y_dense[0][rows]), atol=1e-5
    )


def test_capacity_dispatch_trains_with_kfac():
    """End-to-end: capacity-dispatched MoE LM trains under distributed
    K-FAC (factors captured from the C-row expert buffers)."""
    m = _moe_lm(moe_capacity_factor=1.5)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    targets = jnp.roll(tokens, -1, 1)
    params = m.init(jax.random.PRNGKey(1), tokens)['params']
    reg = kfac_tpu.register_model(m, tokens)
    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    dk = DistributedKFAC(
        config=kfac_tpu.KFACPreconditioner(
            registry=reg, damping=0.01, lr=0.1,
            factor_update_steps=1, inv_update_steps=1,
        ),
        mesh=mesh,
    )
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(lm_loss(m))
    state = dk.init()

    @jax.jit
    def step(params, state, batch):
        (l, _), grads, stats = run(params, batch)
        state, pg = dk.step(state, grads, stats)
        return jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g, params, pg
        ), state, l

    bs = batch_sharding(mesh)
    batch = (jax.device_put(tokens, bs), jax.device_put(targets, bs))
    losses = []
    for _ in range(6):
        params, state, l = step(params, state, batch)
        losses.append(float(l))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_load_balance_loss_uniform_is_one():
    probs = jnp.full((2, 8, 4), 0.25)
    idx = jnp.tile(jnp.arange(4), 4).reshape(2, 8)
    lb = moe.load_balance_loss(probs, idx, 4)
    np.testing.assert_allclose(float(lb), 1.0, rtol=1e-6)


def test_moe_factor_approximation_identity_and_precond_bound():
    """Quantify the two documented MoE factor approximations (module
    docstring) against a per-expert-normalized oracle instead of asserting
    'the damping absorbs it':

    1. STRUCTURE (exact): the captured A factor of expert e equals
       ``f_e * A_oracle + (1 - f_e) * e_bias e_bias^T`` where
       ``f_e = n_e / T`` is the routed fraction — masked-out rows are
       all-zero except the homogeneous bias one.
    2. CHARACTERIZATION (exact): preconditioning with the captured factor
       at damping lam IS preconditioning with the renormalized factor
       ``captured / f_e`` at effective damping ``lam / f_e``, up to a
       global 1/f_e scale that kl-clip/lr absorb — the matrix identity
       ``(M + lam)^-1 = (1/f)((M/f) + lam/f)^-1``. Verified to float
       precision.
    3. BOUND (measured): against the TRUE per-expert oracle the direction
       error is real for low-traffic experts — the empty-row bias corner
       inflates by ``(1-f_e)/f_e`` on top of the damping shift. Measured
       on this fixture (d=8, T=64, E=4): cos(captured, oracle) at
       lam=1e-3 is ~0.31-0.36 for f_e~0.13-0.23 but >0.91 for f_e>=0.3,
       and increases with damping (>=0.68 at lam=0.1 everywhere). The
       assertions pin exactly that shape: monotone improvement with
       damping, and high-traffic experts accurate at default damping.
    """
    from kfac_tpu.ops import factors as factors_lib

    d, t, n_experts = 8, 64, 4
    m = moe.MoEMLP(num_experts=n_experts, mlp_ratio=1)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, t, d))
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(p, batch):
        out = m.apply({'params': p}, batch[0])
        return jnp.mean(out**2)

    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss_fn)
    (_, _), grads, stats = run(params, (x, None))

    # routing decisions, read from the module's own sown intermediates
    _, inter = m.apply({'params': params}, x, mutable=['intermediates'])
    idx = np.asarray(
        inter['intermediates']['expert_index'][0]
    ).reshape(-1)
    xf = np.asarray(x).reshape(-1, d)

    cos = lambda u, v: float(
        np.dot(u, v) / (np.linalg.norm(u) * np.linalg.norm(v))
    )
    checked = 0
    for e in range(n_experts):
        routed = xf[idx == e]
        n_e = len(routed)
        if n_e == 0:
            continue
        f_e = n_e / t
        xb = np.concatenate([routed, np.ones((n_e, 1), np.float32)], 1)
        a_oracle = xb.T @ xb / n_e
        captured = np.asarray(stats.a[f'expert{e}_up'])

        # 1. exact structural identity
        bias_corner = np.zeros_like(a_oracle)
        bias_corner[-1, -1] = 1.0
        np.testing.assert_allclose(
            captured, f_e * a_oracle + (1 - f_e) * bias_corner,
            rtol=1e-4, atol=1e-5,
        )

        g = np.asarray(jax.random.normal(jax.random.PRNGKey(e), (d + 1,)))
        by_lam = {}
        for lam in (0.001, 0.1):
            m_cap = np.asarray(
                factors_lib.compute_inverse(jnp.asarray(captured), lam)
            ) @ g
            # 2. exact effective-damping characterization
            m_eff = np.asarray(
                factors_lib.compute_inverse(
                    jnp.asarray(captured / f_e), lam / f_e
                )
            ) @ g
            assert cos(m_cap, m_eff) > 1 - 1e-5, (e, lam)
            # 3. measured bound vs the true per-expert oracle
            m_or = np.asarray(
                factors_lib.compute_inverse(jnp.asarray(a_oracle), lam)
            ) @ g
            by_lam[lam] = cos(m_cap, m_or)
        # damping absorbs more of the approximation as it grows
        assert by_lam[0.1] > by_lam[0.001] - 1e-6, (e, by_lam)
        assert by_lam[0.1] > 0.6, (e, by_lam)
        # high-traffic experts are accurate already at default damping
        if f_e >= 0.3:
            assert by_lam[0.001] > 0.9, (e, f_e, by_lam)
        checked += 1
    assert checked >= 3  # the fixture routes to most experts


def test_routed_capture_matches_per_expert_oracle_exactly():
    """register_model(routed_layers=...) removes both documented MoE
    approximations: the captured A and G factors equal the
    per-expert-normalized oracle (live-row count, bias ones on live rows
    only), so preconditioning matches the oracle to float precision even
    for low-traffic experts at default damping."""
    from kfac_tpu.ops import factors as factors_lib

    d, t, n_experts = 8, 64, 4
    m = moe.MoEMLP(num_experts=n_experts, mlp_ratio=1)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, t, d))
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(
        m, x, routed_layers=[r'.*expert\d+_(up|down)']
    )
    assert reg.layers['expert0_up'].routed
    assert not reg.layers['router'].routed

    def loss_fn(p, batch):
        out = m.apply({'params': p}, batch[0])
        return jnp.mean(out**2)

    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss_fn)
    (_, _), grads, stats = run(params, (x, None))

    _, inter = m.apply({'params': params}, x, mutable=['intermediates'])
    idx = np.asarray(
        inter['intermediates']['expert_index'][0]
    ).reshape(-1)
    xf = np.asarray(x).reshape(-1, d)

    cos = lambda u, v: float(
        np.dot(u, v) / (np.linalg.norm(u) * np.linalg.norm(v))
    )
    checked = 0
    for e in range(n_experts):
        routed = xf[idx == e]
        n_e = len(routed)
        if n_e == 0:
            continue
        xb = np.concatenate([routed, np.ones((n_e, 1), np.float32)], 1)
        a_oracle = xb.T @ xb / n_e
        captured = np.asarray(stats.a[f'expert{e}_up'])
        np.testing.assert_allclose(captured, a_oracle, rtol=1e-4, atol=1e-5)

        # preconditioning now matches the oracle everywhere, including
        # the low-traffic experts that the shared normalization distorted
        g = np.asarray(jax.random.normal(jax.random.PRNGKey(e), (d + 1,)))
        m_cap = np.asarray(
            factors_lib.compute_inverse(jnp.asarray(captured), 0.001)
        ) @ g
        m_or = np.asarray(
            factors_lib.compute_inverse(jnp.asarray(a_oracle), 0.001)
        ) @ g
        assert cos(m_cap, m_or) > 1 - 1e-5, (e, n_e)
        checked += 1
    assert checked >= 3

    # G factors are oracle-normalized too: routed G must equal the
    # shared-normalization G rescaled by EXACTLY T / n_e (non-routed rows
    # have identically-zero cotangents, so only the normalization — the
    # live-row count — differs between the two captures)
    reg_plain = kfac_tpu.register_model(m, x)
    run_plain = kfac_tpu.CurvatureCapture(reg_plain).value_stats_and_grad(
        loss_fn
    )
    (_, _), _, stats_plain = run_plain(params, (x, None))
    for e in range(n_experts):
        n_e = int((idx == e).sum())
        if n_e == 0:
            continue
        np.testing.assert_allclose(
            np.asarray(stats.g[f'expert{e}_up']),
            np.asarray(stats_plain.g[f'expert{e}_up']) * (t / n_e),
            rtol=1e-4, atol=1e-7,
        )


def test_routed_capture_weights_and_weighted_ema():
    """Routed captures carry their live-row fraction as an evidence weight
    (``stats.w``) and both engines weight the factor EMA by it
    (``alpha_eff = 1 - (1-alpha)*w``): a capture where an expert saw zero
    tokens leaves its running factors unchanged instead of diluting them
    toward zero, partial traffic follows the closed form, and layers
    without a weight reduce exactly to the unweighted EMA."""
    d, t, n_experts = 8, 64, 4
    m = moe.MoEMLP(num_experts=n_experts, mlp_ratio=1)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, t, d))
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(
        m, x, routed_layers=[r'.*expert\d+_(up|down)']
    )

    def loss_fn(p, batch):
        out = m.apply({'params': p}, batch[0])
        return jnp.mean(out**2)

    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss_fn)
    (_, _), _, stats = run(params, (x, None))

    # weights exist exactly for the routed layers and equal n_e / T
    _, inter = m.apply({'params': params}, x, mutable=['intermediates'])
    idx = np.asarray(inter['intermediates']['expert_index'][0]).reshape(-1)
    assert set(stats.w) == {
        f'expert{e}_{s}' for e in range(n_experts) for s in ('up', 'down')
    }
    for e in range(n_experts):
        n_e = int((idx == e).sum())
        np.testing.assert_allclose(
            float(stats.w[f'expert{e}_up']), n_e / t, atol=1e-6
        )

    # dense engine: a starved capture (w=0, all-zero factors) keeps the
    # running factors; other layers still move
    alpha = 0.9
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, damping=1e-3, lr=0.1, factor_decay=alpha
    )
    state1 = jax.jit(kfac.update_factors)(kfac.init(), stats)
    name = 'expert0_up'
    starved = kfac_tpu.CapturedStats(
        a={**stats.a, name: jnp.zeros_like(stats.a[name])},
        g={**stats.g, name: jnp.zeros_like(stats.g[name])},
        w={**stats.w, name: jnp.float32(0.0)},
    )
    state2 = jax.jit(kfac.update_factors)(state1, starved)
    np.testing.assert_allclose(
        np.asarray(state2.a[name]), np.asarray(state1.a[name]), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(state2.g[name]), np.asarray(state1.g[name]), atol=1e-6
    )
    assert (
        np.abs(
            np.asarray(state2.a['router']) - np.asarray(state1.a['router'])
        ).max() > 1e-8
    )

    # partial traffic: closed-form alpha_eff; unweighted layers unchanged
    # semantics (router uses plain alpha)
    w = 0.25
    partial = kfac_tpu.CapturedStats(
        a=stats.a, g=stats.g, w={**stats.w, name: jnp.float32(w)}
    )
    state3 = jax.jit(kfac.update_factors)(state1, partial)
    alpha_eff = 1 - (1 - alpha) * w
    np.testing.assert_allclose(
        np.asarray(state3.a[name]),
        alpha_eff * np.asarray(state1.a[name])
        + (1 - alpha_eff) * np.asarray(stats.a[name], np.float32),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(state3.a['router']),
        alpha * np.asarray(state1.a['router'])
        + (1 - alpha) * np.asarray(stats.a['router'], np.float32),
        rtol=1e-5, atol=1e-6,
    )

    # stacked KAISA engine: the starved slot keeps its factor row too —
    # under BOTH transports (the bucketed path packs factor triangles
    # into flat buffers before stacking; the weighted alpha must land on
    # the same slots after the round trip)
    for method in ('allreduce', 'allreduce_bucketed'):
        dk = DistributedKFAC(
            config=kfac_tpu.KFACPreconditioner(
                registry=reg, damping=1e-3, lr=0.1, factor_decay=alpha,
                allreduce_method=method,
            ),
            mesh=kaisa_mesh(grad_worker_fraction=0.5),
        )
        dstate1 = jax.jit(dk.update_factors)(dk.init(), stats)
        dstate2 = jax.jit(dk.update_factors)(dstate1, starved)
        for b in dk.buckets:
            if name in b.layers:
                i = b.layers.index(name)
                np.testing.assert_allclose(
                    np.asarray(dstate2.a[b.key][i]),
                    np.asarray(dstate1.a[b.key][i]),
                    atol=1e-6, err_msg=method,
                )
                # a sibling expert with traffic still moves
                busiest = max(
                    (f'expert{e}_up' for e in range(1, n_experts)),
                    key=lambda n: float(stats.w[n]),
                )
                j = b.layers.index(busiest)
                assert (
                    np.abs(
                        np.asarray(dstate2.a[b.key][j])
                        - np.asarray(dstate1.a[b.key][j])
                    ).max() > 1e-8
                ), method
                break
        else:
            raise AssertionError(f'{name} not found in any bucket')


def test_multi_invocation_routed_capture_is_traffic_weighted():
    """A weight-shared routed layer invoked twice per loss — once with
    tokens, once fully starved — must capture the busy invocation's
    oracle factors, not half of them (within-capture invocations combine
    as sum(w_i F_i)/sum(w_i), the same convention as micro-step
    accumulation)."""
    import flax.linen as nn

    d = 6

    class TwoCall(nn.Module):
        @nn.compact
        def __call__(self, x):
            shared = nn.Dense(4, name='shared')
            # invocation 1: real rows; invocation 2: all rows masked out
            return shared(x).sum(-1) + shared(jnp.zeros_like(x)).sum(-1)

    m = TwoCall()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, d))
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(m, x, routed_layers=['shared'])
    assert reg.layers['shared'].routed

    def loss_fn(p, batch):
        return jnp.mean(m.apply({'params': p}, batch) ** 2)

    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss_fn)
    (_, _), _, stats = run(params, x)

    # oracle: the busy invocation alone (all 16 rows live, bias ones)
    xb = np.concatenate([np.asarray(x), np.ones((16, 1), np.float32)], 1)
    np.testing.assert_allclose(
        np.asarray(stats.a['shared']), xb.T @ xb / 16, rtol=1e-4, atol=1e-6
    )
    # combined weight is the mean live fraction over invocations
    np.testing.assert_allclose(float(stats.w['shared']), 0.5, atol=1e-6)


def test_multi_invocation_routed_g_divides_by_cotangent_weight():
    """G-side counterpart of the A-side caveat test: the starved second
    invocation sees all-zero INPUT but a fully dense COTANGENT (both
    invocations' outputs add into the loss), so the G divisor must come
    from the cotangent live fractions — dividing by the A-side input
    weight (sum 1.0) would double the captured G."""
    import flax.linen as nn

    from kfac_tpu.ops import cov

    d = 6

    class TwoCall(nn.Module):
        @nn.compact
        def __call__(self, x):
            shared = nn.Dense(4, name='shared')
            return shared(x).sum(-1) + shared(jnp.zeros_like(x)).sum(-1)

    m = TwoCall()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, d))
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(m, x, routed_layers=['shared'])

    def loss_fn(p, batch):
        return jnp.mean(m.apply({'params': p}, batch) ** 2)

    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss_fn)
    (_, _), _, stats = run(params, x)

    # oracle cotangents, straight from the layer-output computation
    y1 = x @ params['shared']['kernel'] + params['shared']['bias']
    y2 = jnp.broadcast_to(params['shared']['bias'], y1.shape)
    g1, g2 = jax.grad(
        lambda ys: jnp.mean((ys[0].sum(-1) + ys[1].sum(-1)) ** 2)
    )((y1, y2))
    f1 = float(cov.routed_live_fraction(g1))
    f2 = float(cov.routed_live_fraction(g2))
    assert f1 == 1.0 and f2 == 1.0  # dense cotangents despite zero input
    expected = (
        np.asarray(cov.linear_g_factor(g1)) + np.asarray(cov.linear_g_factor(g2))
    ) / (f1 + f2)
    np.testing.assert_allclose(
        np.asarray(stats.g['shared']), expected, rtol=1e-4, atol=1e-6
    )


def test_fully_starved_routed_g_stays_finite_and_exact():
    """All-zero input + nonzero cotangent in a SINGLE invocation: the old
    A-side divisor was the WEIGHT_FLOOR (input live fraction 0), blowing
    the captured G up by ~1e8; the cotangent-side divisor yields the
    plain per-row covariance of the cotangent."""
    import flax.linen as nn

    from kfac_tpu.ops import cov

    d = 6

    class Starved(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4, name='shared')(jnp.zeros_like(x)).sum(-1)

    m = Starved()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, d))
    params = m.init(jax.random.PRNGKey(1), x)['params']
    # nonzero bias so the starved layer still emits a nonzero cotangent
    params = jax.tree.map(lambda v: v, params)
    params['shared']['bias'] = jnp.ones_like(params['shared']['bias'])
    reg = kfac_tpu.register_model(m, x, routed_layers=['shared'])

    def loss_fn(p, batch):
        return jnp.mean(m.apply({'params': p}, batch) ** 2)

    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss_fn)
    (_, _), _, stats = run(params, x)

    ybar = jax.grad(
        lambda y: jnp.mean(y.sum(-1) ** 2)
    )(jnp.broadcast_to(params['shared']['bias'], (16, 4)))
    expected = np.asarray(cov.linear_g_factor(ybar))  # live fraction 1
    assert np.abs(expected).max() > 0
    g = np.asarray(stats.g['shared'])
    assert np.all(np.isfinite(g))
    np.testing.assert_allclose(g, expected, rtol=1e-4, atol=1e-6)
    # the A side keeps the documented starved convention: factor 0, w 0
    np.testing.assert_allclose(np.asarray(stats.a['shared']), 0.0, atol=0)
    np.testing.assert_allclose(float(stats.w['shared']), 0.0, atol=0)


def test_weighted_ema_invariants_property_sweep():
    """Property sweep over random weight sequences: (1) w==1 everywhere
    reproduces the plain EMA bitwise-close, (2) w==0 captures are exact
    no-ops, (3) the update is monotone in w (larger evidence moves the
    factor strictly closer to the capture), for both the dense and the
    stacked engines."""
    from kfac_tpu.ops import factors as factors_lib

    rng = np.random.default_rng(23)
    alpha = 0.9
    d = 4
    running = jnp.asarray(rng.normal(size=(d, d)) @ np.eye(d), jnp.float32)
    new = jnp.asarray(rng.normal(size=(d, d)), jnp.float32)
    # (1) w=1 == plain EMA
    np.testing.assert_allclose(
        np.asarray(factors_lib.ema_update(
            running, new, factors_lib.effective_alpha(alpha, jnp.float32(1.0))
        )),
        np.asarray(factors_lib.ema_update(running, new, alpha)),
        rtol=1e-6,
    )
    # (2) w=0 == no-op
    np.testing.assert_array_equal(
        np.asarray(factors_lib.ema_update(
            running, new, factors_lib.effective_alpha(alpha, jnp.float32(0.0))
        )),
        np.asarray(running),
    )
    # (3) monotone in w: distance to the capture strictly decreases
    dists = []
    for w in np.linspace(0.0, 1.0, 9):
        out = factors_lib.ema_update(
            running, new, factors_lib.effective_alpha(alpha, jnp.float32(w))
        )
        dists.append(float(jnp.linalg.norm(out - new)))
    assert all(a > b for a, b in zip(dists, dists[1:])), dists

    # engine-level: random w sequences drive the dense engine to exactly
    # the closed-form recurrence
    m = moe.MoEMLP(num_experts=4, mlp_ratio=1)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 64, 8))
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(
        m, x, routed_layers=[r'.*expert\d+_(up|down)']
    )

    def loss_fn(p, batch):
        return jnp.mean(m.apply({'params': p}, batch[0]) ** 2)

    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss_fn)
    (_, _), _, stats = run(params, (x, None))
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, damping=1e-3, lr=0.1, factor_decay=alpha
    )
    name = 'expert2_down'
    state = kfac.init()
    expect = np.asarray(state.a[name])
    capture = np.asarray(stats.a[name], np.float32)
    for w in rng.uniform(0.0, 1.0, size=6):
        mod = kfac_tpu.CapturedStats(
            a=stats.a, g=stats.g, w={**stats.w, name: jnp.float32(w)}
        )
        state = jax.jit(kfac.update_factors)(state, mod)
        a_eff = 1.0 - (1.0 - alpha) * w
        expect = a_eff * expect + (1.0 - a_eff) * capture
        np.testing.assert_allclose(
            np.asarray(state.a[name]), expect, rtol=2e-5, atol=1e-6
        )


def test_weighted_ema_preserves_bf16_factor_dtype():
    """The weighted EMA must not promote bfloat16 factor state to float32
    (the float32 capture weight would otherwise break kfac.step's
    lax.cond branch-type equality)."""
    m = moe.MoEMLP(num_experts=4, mlp_ratio=1)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 64, 8))
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(
        m, x, routed_layers=[r'.*expert\d+_(up|down)'],
        factor_dtype=jnp.bfloat16,
    )

    def loss_fn(p, batch):
        return jnp.mean(m.apply({'params': p}, batch[0]) ** 2)

    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss_fn)
    (_, _), grads, stats = run(params, (x, None))
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, damping=1e-3, lr=0.1, factor_dtype=jnp.bfloat16,
        factor_update_steps=1, inv_update_steps=1,
    )
    state, pg = jax.jit(kfac.step)(kfac.init(), grads, stats)
    assert state.a['expert0_up'].dtype == jnp.bfloat16
    assert all(np.isfinite(np.asarray(v, np.float32)).all()
               for lv in pg.values() for v in lv.values())


def test_accumulated_routed_stats_are_traffic_weighted():
    """Gradient accumulation combines routed micro-captures by traffic:
    an expert that saw tokens only in micro-step 1 (factor F, w=1) and
    none in micro-step 2 (factor 0, w=0) must average to F — not F/2,
    which would systematically understate the per-expert covariance.
    Unweighted layers keep the plain mean."""
    from kfac_tpu.layers import capture as capture_lib

    f = jnp.eye(3) * 2.0
    plain = jnp.ones((2, 2))
    s1 = kfac_tpu.CapturedStats(
        a={'e': f, 'd': plain}, g={'e': f, 'd': plain},
        w={'e': jnp.float32(1.0)},
    )
    s2 = kfac_tpu.CapturedStats(
        a={'e': jnp.zeros_like(f), 'd': 3.0 * plain},
        g={'e': jnp.zeros_like(f), 'd': 3.0 * plain},
        w={'e': jnp.float32(0.0)},
    )
    acc = capture_lib.accumulate_stats(None, s1)
    acc = capture_lib.accumulate_stats(acc, s2)
    avg = capture_lib.average_stats(acc, 2)
    np.testing.assert_allclose(np.asarray(avg.a['e']), np.asarray(f))
    np.testing.assert_allclose(np.asarray(avg.g['e']), np.asarray(f))
    np.testing.assert_allclose(np.asarray(avg.a['d']), 2.0 * np.ones((2, 2)))
    np.testing.assert_allclose(float(avg.w['e']), 0.5)

    # partial traffic: w=0.75 then w=0.25 combines as (0.75*F1+0.25*F2)/1.0
    f2 = jnp.eye(3)
    t1 = kfac_tpu.CapturedStats(
        a={'e': f}, g={'e': f}, w={'e': jnp.float32(0.75)}
    )
    t2 = kfac_tpu.CapturedStats(
        a={'e': f2}, g={'e': f2}, w={'e': jnp.float32(0.25)}
    )
    avg2 = capture_lib.average_stats(
        capture_lib.accumulate_stats(capture_lib.accumulate_stats(None, t1), t2), 2
    )
    np.testing.assert_allclose(
        np.asarray(avg2.a['e']), np.asarray(0.75 * f + 0.25 * f2), rtol=1e-6
    )
    # fully-starved across every micro-step: factor 0, weight 0 (EMA skips)
    z = kfac_tpu.CapturedStats(
        a={'e': jnp.zeros_like(f)}, g={'e': jnp.zeros_like(f)},
        w={'e': jnp.float32(0.0)},
    )
    avg3 = capture_lib.average_stats(
        capture_lib.accumulate_stats(capture_lib.accumulate_stats(None, z), z), 2
    )
    np.testing.assert_allclose(np.asarray(avg3.a['e']), 0.0)
    np.testing.assert_allclose(float(avg3.w['e']), 0.0)


def test_routed_layers_rejects_non_dense():
    import flax.linen as nn
    import pytest as _pytest

    class ConvNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Conv(4, (3, 3), name='c1')(x)

    x = jnp.zeros((1, 8, 8, 3))
    with _pytest.raises(ValueError, match='not a dense layer'):
        kfac_tpu.register_model(ConvNet(), x, routed_layers=['c1'])


def test_routed_layers_rejects_unmatched_pattern():
    """A typo'd routed pattern must error, not silently fall back to the
    approximate capture."""
    import pytest as _pytest

    m = moe.MoEMLP(num_experts=2, mlp_ratio=1)
    x = jnp.zeros((1, 8, 4))
    with _pytest.raises(ValueError, match='matched no registered layer'):
        kfac_tpu.register_model(
            m, x, routed_layers=[r'.*expert\d+_(upp|dwn)']  # typo'd suffixes
        )
