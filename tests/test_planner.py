"""3D topology planner: enumeration, executed-schedule bubble terms, the
committed measured bubble table, ppermute wire parity against the traced
scans, and the plan plumbing (resolve_auto_layout / fleet guards).

The committed-artifact test re-derives every row of
``planner/bubble_table.json`` from the schedule simulators: the
executed-tick counts must match EXACTLY (they are structural), and every
row the measured tier called clean must sit within the artifact's own
documented tolerance — the acceptance gate for the measured tier.
"""

import json
import os
import warnings as pywarnings

import jax
import numpy as np
import pytest

import kfac_tpu
from kfac_tpu.autotune import plan as plan_mod
from kfac_tpu.planner import execute, topology
from testing import models

WORLD = 8


@pytest.fixture(scope='module')
def base_config():
    m = models.TinyModel(hidden=8, out=4)
    x, _ = models.regression_data(jax.random.PRNGKey(1), n=16, dim=6)
    reg = kfac_tpu.register_model(m, x)
    return kfac_tpu.KFACPreconditioner(registry=reg, damping=1e-3, lr=0.1)


# ------------------------------------------------------------- enumeration


def test_enumerate_topologies_factorizes_world():
    cands = topology.enumerate_topologies(WORLD)
    assert cands
    for c in cands:
        assert c.dp * c.tp * c.pp == WORLD
        assert c.pp >= 2  # pp == 1 is the KAISA autotuner's domain
        assert c.microbatches % c.pp == 0
        if c.schedule == '1f1b':
            assert c.virtual_chunks == 1  # 2-slot scan has no chunks
    # both schedule families and every pipe divisor >= 2 appear
    assert {c.schedule for c in cands} == {'1f1b', 'interleaved'}
    assert {c.pp for c in cands} == {2, 4, 8}


def test_enumerate_topologies_respects_bounds():
    cfg = topology.TopologyConfig(
        schedules=('interleaved',), pipeline_ranks=(2,),
        virtual_chunks=(4,), microbatch_multiples=(2,),
    )
    cands = topology.enumerate_topologies(WORLD, cfg)
    assert [
        (c.dp, c.tp, c.pp, c.virtual_chunks, c.microbatches) for c in cands
    ] == [(4, 1, 2, 4, 4)]


# ----------------------------------------------------------- bubble terms


@pytest.mark.parametrize('schedule', ['1f1b', 'interleaved'])
@pytest.mark.parametrize('p,v,m', [(2, 1, 4), (2, 2, 8), (4, 2, 8)])
def test_schedule_terms_executes_simulator(schedule, p, v, m):
    if schedule == '1f1b':
        v = 1
    terms = topology.schedule_terms(schedule, p, v, m)
    assert terms['source'] == 'simulator'
    # the executed tables happen to agree with the fill/drain closed
    # forms at these sizes — the simulator must reproduce them, slot for
    # slot (the closed form is only the overflow fallback)
    closed = topology._closed_form(schedule, p, v, m)
    assert terms['ticks'] == closed['ticks']
    assert terms['bubble_slots'] == closed['bubble_slots']
    assert terms['fraction'] == pytest.approx(closed['fraction'])


def test_schedule_terms_overflow_falls_back_to_closed_form():
    terms = topology.schedule_terms('interleaved', 2, 2, 4, max_sim_slots=4)
    assert terms['source'] == 'closed-form'


def test_schedule_terms_rejects_bad_points():
    with pytest.raises(ValueError, match='multiple'):
        topology.schedule_terms('interleaved', 2, 2, 3)
    with pytest.raises(ValueError, match='schedule'):
        topology.schedule_terms('gpipe2', 2, 1, 4)


def test_bubble_fraction_applies_measured_correction(tmp_path):
    sim = topology.schedule_terms('interleaved', 2, 2, 8)['fraction']
    doc = {
        'schema': execute.SCHEMA_VERSION,
        'tolerance': 0.45,
        'rows': [{
            'schedule': 'interleaved', 'p': 2, 'v': 2,
            'predicted_fraction': sim,
            'measured': {'fraction': sim * 1.5},
            'contaminated': False,
        }],
    }
    path = os.path.join(tmp_path, 'table.json')
    with open(path, 'w') as f:
        json.dump(doc, f)
    got = topology.bubble_fraction('interleaved', 2, 2, 8, bubble_table=path)
    assert got == pytest.approx(min(0.99, sim * 1.5))
    # unknown rows and missing tables degrade to the raw simulator value
    assert topology.bubble_fraction(
        '1f1b', 2, 1, 8, bubble_table=path
    ) == pytest.approx(topology.schedule_terms('1f1b', 2, 1, 8)['fraction'])
    assert topology.bubble_fraction(
        'interleaved', 2, 2, 8,
        bubble_table=os.path.join(tmp_path, 'missing.json'),
    ) == pytest.approx(sim)


def test_measured_correction_is_clipped(tmp_path):
    doc = {
        'schema': execute.SCHEMA_VERSION,
        'rows': [{
            'schedule': '1f1b', 'p': 2, 'v': 1,
            'predicted_fraction': 0.1,
            'measured': {'fraction': 0.9},
            'contaminated': False,
        }],
    }
    path = os.path.join(tmp_path, 'table.json')
    with open(path, 'w') as f:
        json.dump(doc, f)
    assert execute.measured_bubble_correction('1f1b', 2, 1, path=path) == 2.0


# ------------------------------------------------------ committed artifact


def test_committed_bubble_table_matches_simulators():
    """Every row of the committed artifact re-derives from the schedule
    simulators (exact tick agreement) and every clean row's measured
    fraction sits within the artifact's own documented tolerance."""
    table = execute.load_bubble_table(execute.ARTIFACT_PATH)
    assert table, 'committed planner/bubble_table.json failed to load'
    assert table['schema'] == execute.SCHEMA_VERSION
    tol = float(table['tolerance'])
    rows = table['rows']
    covered = {(r['schedule'], r['p'], r['v']) for r in rows}
    assert covered == {
        (s, p, v)
        for s in ('1f1b', 'interleaved') for p in (2, 4) for v in (1, 2, 4)
    }
    clean = 0
    for row in rows:
        s, p, v, m = row['schedule'], row['p'], row['v'], row['microbatches']
        sim = topology.schedule_terms(s, p, v, m)
        assert sim['source'] == 'simulator'
        assert row['predicted_ticks'] == sim['ticks'], row
        assert row['predicted_bubble_slots'] == sim['bubble_slots'], row
        assert row['predicted_fraction'] == pytest.approx(sim['fraction'])
        assert row['executed_ticks'] == sim['ticks'], (
            'executed tick count diverged from the simulator', row
        )
        if not row['contaminated']:
            clean += 1
            err = abs(row['measured']['fraction'] - row['predicted_fraction'])
            assert err <= tol, (
                f'clean row {s} p={p} v={v} off by {err:.3f} > {tol}'
            )
    assert clean >= len(rows) // 2, 'most rows should be floor-clean'


# --------------------------------------------------------- ppermute parity


@pytest.mark.parametrize('schedule', ['1f1b', 'interleaved'])
def test_ppermute_bytes_parity_with_traced_scan(schedule):
    """KFL205-style parity: the planner's per-tick ppermute byte term
    equals ``analysis.ir.visitor.ppermute_bytes`` of the actual traced
    scan (each scan-body permute appears once in the jaxpr = one tick of
    one rank), so the cost model cannot drift from the executed code."""
    from kfac_tpu.analysis.ir import visitor

    p, v, m = 2, (2 if schedule == 'interleaved' else 1), 4
    model, params, batch = execute._build(schedule, p, v, m)
    jaxpr = jax.make_jaxpr(model.loss_and_stats)(params, batch)
    traced = visitor.ppermute_bytes(jaxpr.jaxpr)
    g = execute.GEOMETRY
    predicted = topology.pipeline_ppermute_bytes_per_tick(
        schedule, m // m, g['seq_len'], g['d_model']
    )
    assert traced == predicted, (traced, predicted)


# ---------------------------------------------------------------- plumbing


def test_plan_topology_is_deterministic_and_complete(base_config):
    p1 = topology.plan_topology(base_config, world=WORLD)
    p2 = topology.plan_topology(base_config, world=WORLD)
    assert p1.to_json() == p2.to_json()
    topo = p1.knobs['topology']
    assert topo['pp'] >= 2
    assert set(p1.knobs) == set(plan_mod.KNOB_KEYS)
    assert p1.meta['planner'] == 'topology3d'
    assert p1.meta['grid_size'] == len(p1.cost_table)
    # every cost row prices a real factorization with simulator terms
    for row in p1.cost_table:
        t = row['knobs']['topology']
        assert t['dp'] * t['tp'] * t['pp'] == WORLD
        assert row['schedule']['source'] == 'simulator'
        assert row['predicted_step_s'] > 0.0


def test_resolve_auto_layout_topology(base_config):
    from kfac_tpu.parallel.mesh import PIPE_AXIS
    from kfac_tpu.warnings import LayoutPlanWarning, reset_layout_warnings

    plan = topology.plan_topology(base_config, world=WORLD)
    cfg, mesh, applied = plan_mod.resolve_auto_layout(
        base_config, None, plan
    )
    assert applied
    assert dict(mesh.shape)[PIPE_AXIS] == plan.knobs['topology']['pp']

    # a factorization that does not divide this world is a fingerprint
    # mismatch: warn, fall back, never build a broken mesh
    bad = plan_mod.TunedPlan.from_json(plan.to_json())
    bad.knobs['topology'] = dict(bad.knobs['topology'], pp=3, tp=1)
    reset_layout_warnings()
    with pywarnings.catch_warnings(record=True) as rec:
        pywarnings.simplefilter('always')
        cfg, mesh, applied = plan_mod.resolve_auto_layout(
            base_config, None, bad
        )
    assert not applied and mesh is None
    assert any(isinstance(r.message, LayoutPlanWarning) for r in rec)


def test_fleet_topology_fits(base_config):
    from kfac_tpu.resilience.fleet import FleetController

    plan = topology.plan_topology(base_config, world=WORLD)
    assert FleetController._topology_fits(plan)
    flat = plan_mod.TunedPlan.from_json(plan.to_json())
    flat.knobs['topology'] = None
    assert FleetController._topology_fits(flat)
    bad = plan_mod.TunedPlan.from_json(plan.to_json())
    bad.knobs['topology'] = dict(bad.knobs['topology'], pp=3, tp=1)
    assert not FleetController._topology_fits(bad)


def test_load_bubble_table_env_override(tmp_path, monkeypatch):
    doc = {'schema': execute.SCHEMA_VERSION, 'rows': []}
    path = os.path.join(tmp_path, 'env_table.json')
    with open(path, 'w') as f:
        json.dump(doc, f)
    monkeypatch.setenv(execute.ENV_VAR, path)
    execute.invalidate_cache()
    try:
        assert execute.load_bubble_table()['rows'] == []
        # schema mismatch degrades to empty (load-or-default), not a crash
        with open(path, 'w') as f:
            json.dump({'schema': 999, 'rows': []}, f)
        execute.invalidate_cache()
        assert execute.load_bubble_table() == {}
    finally:
        execute.invalidate_cache()


@pytest.mark.slow
def test_measure_row_smoke():
    """One real measured-tier row on the CPU mesh: structural fields
    populated, executed ticks == simulator, provenance from the
    one-dispatch harness."""
    row = execute.measure_row('interleaved', 2, 1, iters=2, repeats=1)
    sim = topology.schedule_terms('interleaved', 2, 1, row['microbatches'])
    assert row['executed_ticks'] == sim['ticks']
    assert row['predicted_bubble_slots'] == sim['bubble_slots']
    assert row['measured']['wall_clock_p50_s'] > 0.0
    assert all(w > 0.0 for w in row['measured']['wall_s'].values())
    assert row['provenance']['harness_version'] == 2
    assert isinstance(row['contaminated'], bool)
