"""Flight recorder tests: ring contract, postmortem bundles, triage CLI.

Pins the PR's acceptance criteria: the in-jit ring records the last N
steps on both engines (both KAISA stat transports) and via all four
Trainer paths with ZERO added recompilations after step 1 (pinned via
``testing.compile_pins``, mirroring tests/test_observability.py),
skipped steps leave gaps rather than rows, an injected fault produces
exactly one complete bundle per health event, and
``tools/kfac_inspect.py`` parses a bundle back into a correct
first-bad-layer divergence timeline.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import kfac_tpu
from kfac_tpu import health as health_lib
from kfac_tpu import tracing, training
from kfac_tpu.observability import flight_recorder as flight_lib
from kfac_tpu.observability import sinks
from kfac_tpu.parallel import multihost
from testing import compile_pins, faults, models

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), os.pardir, 'tools')
)
import kfac_inspect  # noqa: E402
import lint_metric_keys  # noqa: E402


def _dense_setup(**cfg_kw):
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, **cfg_kw)
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(models.mse_loss(m))
    return m, params, (x, y), reg, kfac, run


def _trainer_setup(**cfg_kw):
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, **cfg_kw)

    def loss_fn(p, model_state, batch):
        xx, yy = batch
        pred = m.apply({'params': p}, xx)
        return jnp.mean((pred - yy) ** 2), model_state

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac
    )
    return trainer, params, (x, y), reg, kfac


# ------------------------------------------------------------------ config


def test_flight_config_normalization():
    reg = _dense_setup()[3]
    k = kfac_tpu.KFACPreconditioner(registry=reg, flight=True)
    assert isinstance(k.flight, kfac_tpu.FlightRecorderConfig)
    assert k.flight.capacity == 64
    assert k.metrics is not None  # flight auto-enables metrics
    k = kfac_tpu.KFACPreconditioner(registry=reg, flight=8)
    assert k.flight.capacity == 8
    k = kfac_tpu.KFACPreconditioner(registry=reg, flight=False)
    assert k.flight is None and k.init().flight is None
    with pytest.raises(TypeError):
        kfac_tpu.KFACPreconditioner(registry=reg, flight='yes')
    with pytest.raises(ValueError):
        kfac_tpu.FlightRecorderConfig(capacity=0)
    # explicit metrics config is preserved, not overwritten
    mc = kfac_tpu.MetricsConfig(grad_norms=False)
    k = kfac_tpu.KFACPreconditioner(registry=reg, flight=4, metrics=mc)
    assert k.metrics is mc


# ------------------------------------------------------------- dense ring


def test_ring_records_last_n_dense():
    """Last-capacity steps survive, chronological, with loss and grad
    norm; one compiled program serves every step."""
    _, params, batch, reg, kfac, run = _dense_setup(flight=4)
    state = kfac.init()
    assert state.flight is not None and state.flight.capacity == 4
    step = compile_pins.watched_jit(kfac.step)
    for i in range(6):
        (_, _), grads, stats = run(params, batch)
        state, _ = step(state, grads, stats, loss=jnp.float32(10.0 + i))
    compile_pins.assert_compiled_once(step)
    recs = flight_lib.drain_flight(state)
    assert [r['step'] for r in recs] == [2, 3, 4, 5]
    assert [r['loss'] for r in recs] == [12.0, 13.0, 14.0, 15.0]
    keys = set(kfac_tpu.observability.metric_keys(
        kfac.metrics, list(reg.layers)))
    for r in recs:
        assert keys <= set(r)
        assert r['process_index'] == 0
        assert r['grad_norm'] > 0 and np.isfinite(r['grad_norm'])
    # ring rows equal the collector's view of the same step
    final = kfac_tpu.MetricsCollector(include_health=False).drain(state)
    last = recs[-1]
    for k in keys:
        np.testing.assert_allclose(last[k], final[k], rtol=1e-6)


def test_ring_loss_optional():
    """Engine steps without a Trainer loss mark the slot loss-invalid
    (no placeholder zeros that could fake-trigger postmortems)."""
    _, params, batch, _, kfac, run = _dense_setup(flight=4)
    state = kfac.init()
    (_, _), grads, stats = run(params, batch)
    state, _ = jax.jit(kfac.step)(state, grads, stats)
    recs = flight_lib.drain_flight(state)
    assert len(recs) == 1 and 'loss' not in recs[0]


def test_global_grad_norm_matches_numpy():
    tree = {'a': jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            'b': {'c': -jnp.ones((4,), jnp.bfloat16)},
            'n': jnp.arange(3)}  # integer leaf excluded
    got = float(flight_lib.global_grad_norm(tree))
    want = np.sqrt(float(np.sum(np.arange(6.0) ** 2)) + 4.0)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_flight_disabled_by_default():
    _, params, batch, _, kfac, run = _dense_setup(metrics=True)
    state = kfac.init()
    assert state.flight is None
    assert flight_lib.drain_flight(state) == []


def test_skipped_steps_leave_gaps():
    """The Trainer's skip-step gate writes no slot: the gap IS the
    signal (and the skip/record cond branches stay structural twins)."""
    trainer, params, (x, y), _, _ = _trainer_setup(
        flight=8, health=health_lib.HealthConfig(warn=False))
    state = trainer.init(params)
    for _ in range(2):
        state, _ = trainer.step(state, (x, y))
    state, _ = trainer.step(state, faults.poison_batch((x, y), kind='nan'))
    state, _ = trainer.step(state, (x, y))
    recs = flight_lib.drain_flight(state)
    assert [r['step'] for r in recs] == [0, 1, 3]
    assert int(jax.device_get(state.kfac_state.health.skipped_steps)) == 1


def test_flight_is_ephemeral_not_checkpointed():
    from kfac_tpu import checkpoint

    _, params, batch, _, kfac, run = _dense_setup(flight=4)
    state = kfac.init()
    (_, _), grads, stats = run(params, batch)
    state, _ = jax.jit(kfac.step)(state, grads, stats, loss=jnp.float32(2.0))
    durable = checkpoint.durable_state(state)
    assert 'flight' not in durable
    # a fresh init has an empty ring regardless of prior history
    assert flight_lib.drain_flight(kfac.init()) == []


# ------------------------------------------------------------- distributed


@pytest.mark.parametrize('transport', ['allreduce', 'allreduce_bucketed'])
def test_ring_distributed(transport):
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(
        registry=reg, flight=4, allreduce_method=transport)
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(models.mse_loss(m))
    state = dk.init()
    step = compile_pins.watched_jit(dk.step)
    for i in range(5):
        (_, _), grads, stats = run(params, (x, y))
        state, _ = step(state, grads, stats, loss=jnp.float32(i))
    compile_pins.assert_compiled_once(step)
    recs = flight_lib.drain_flight(state)
    assert [r['step'] for r in recs] == [1, 2, 3, 4]
    assert [r['loss'] for r in recs] == [1.0, 2.0, 3.0, 4.0]
    expected = set(kfac_tpu.observability.metric_keys(
        cfg.metrics, list(reg.layers)))
    assert expected <= set(recs[-1])
    # every state field has a sharding spec, flight included
    sh = dk.state_shardings()
    assert sh.flight is not None
    assert set(sh._fields) == set(state._fields)


def test_distributed_ring_matches_dense():
    """Same stats in, same ring row out — telemetry parity across
    engines (mirrors test_distributed_metrics_match_dense)."""
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    mesh = kaisa_mesh(grad_worker_fraction=1.0)
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg, flight=4, damping=0.01)
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(
        models.mse_loss(m))(params, (x, y))
    loss = jnp.float32(3.25)

    ref_state, _ = cfg.step(cfg.init(), grads, stats, loss=loss)
    dist_state, _ = jax.jit(dk.step)(dk.init(), grads, stats, loss=loss)
    ref = flight_lib.drain_flight(ref_state)[-1]
    dist = flight_lib.drain_flight(dist_state)[-1]
    assert set(ref) == set(dist)
    for k in ref:
        np.testing.assert_allclose(ref[k], dist[k], rtol=5e-3, atol=1e-6)


# ----------------------------------------------------------- trainer paths


def test_trainer_step_and_scan_record_loss():
    trainer, params, (x, y), _, _ = _trainer_setup(flight=8)
    state = trainer.init(params)
    losses = []
    for _ in range(3):
        state, loss = trainer.step(state, (x, y))
        losses.append(float(loss))
    recs = flight_lib.drain_flight(state)
    assert [r['step'] for r in recs] == [0, 1, 2]
    np.testing.assert_allclose([r['loss'] for r in recs], losses, rtol=1e-6)

    trainer, params, (x, y), _, _ = _trainer_setup(flight=8)
    state = trainer.init(params)
    state, losses = trainer.scan_steps(
        state, (jnp.stack([x] * 3), jnp.stack([y] * 3)))
    recs = flight_lib.drain_flight(state)
    assert [r['step'] for r in recs] == [0, 1, 2]
    np.testing.assert_allclose(
        [r['loss'] for r in recs], np.asarray(losses), rtol=1e-6)


def test_trainer_accumulate_paths_record_loss():
    trainer, params, (x, y), _, _ = _trainer_setup(flight=8)
    state = trainer.init(params)
    losses = []
    for _ in range(2):
        state, loss = trainer.step_accumulate(state, [(x, y)] * 4)
        losses.append(float(loss))
    recs = flight_lib.drain_flight(state)
    assert [r['step'] for r in recs] == [0, 1]
    np.testing.assert_allclose([r['loss'] for r in recs], losses, rtol=1e-6)

    trainer, params, (x, y), _, _ = _trainer_setup(flight=8)
    state = trainer.init(params)
    losses = []
    for _ in range(2):
        state, loss = trainer.step_accumulate_scan(
            state, (jnp.stack([x] * 4), jnp.stack([y] * 4)))
        losses.append(float(loss))
    recs = flight_lib.drain_flight(state)
    assert [r['step'] for r in recs] == [0, 1]
    np.testing.assert_allclose([r['loss'] for r in recs], losses, rtol=1e-6)


# -------------------------------------------------------------------- skew


def test_skew_columns_single_host():
    """Single-process: skew columns exist and equal the local value (the
    gather is a pure-numpy no-op)."""
    _, params, batch, _, kfac, run = _dense_setup(flight=4)
    state = kfac.init()
    (_, _), grads, stats = run(params, batch)
    state, _ = jax.jit(kfac.step)(state, grads, stats, loss=jnp.float32(2.5))
    rec = flight_lib.drain_flight(state)[-1]
    for k in ('loss', 'grad_norm', 'kl_clip_scale'):
        assert rec[f'skew_min/{k}'] == rec[k]
        assert rec[f'skew_max/{k}'] == rec[k]
        assert rec[f'skew_mean/{k}'] == rec[k]
    # skew off: no columns
    rec = flight_lib.drain_flight(state, skew_keys=None)[-1]
    assert not any(k.startswith('skew_') for k in rec)


def test_allgather_scalars_single_process():
    mat = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = multihost.allgather_scalars(mat)
    assert out.shape == (1, 2, 3)
    np.testing.assert_array_equal(out[0], mat)


# -------------------------------------------------------------- postmortem
# (ride the faults marker: these are the sentinel's fault-injection
# triggers observed from the telemetry side)


@pytest.mark.faults
def test_postmortem_skip_event_exactly_once(tmp_path):
    trainer, params, (x, y), _, kfac = _trainer_setup(
        flight=8, health=health_lib.HealthConfig(warn=False))
    state = trainer.init(params)
    pm = kfac_tpu.PostmortemWriter(tmp_path / 'pms', engine=kfac)
    coll = kfac_tpu.MetricsCollector()
    for _ in range(3):
        state, _ = trainer.step(state, (x, y))
        assert pm.observe(state, coll.drain(state)) is None
    state, _ = trainer.step(state, faults.poison_batch((x, y), kind='nan'))
    bundle = pm.observe(state, coll.drain(state))
    assert bundle is not None and 'skip' in os.path.basename(bundle)
    # same event seen again -> no second bundle; a NEW skip fires again
    assert pm.observe(state, coll.drain(state)) is None
    state, _ = trainer.step(state, (x, y))
    assert pm.observe(state, coll.drain(state)) is None
    state, _ = trainer.step(state, faults.poison_batch((x, y), kind='inf'))
    second = pm.observe(state, coll.drain(state))
    assert second is not None and second != bundle
    assert pm.bundles == [bundle, second]


@pytest.mark.faults
def test_postmortem_bundle_complete(tmp_path):
    trainer, params, (x, y), reg, kfac = _trainer_setup(
        flight=8, health=health_lib.HealthConfig(warn=False))
    state = trainer.init(params)
    for _ in range(2):
        state, _ = trainer.step(state, (x, y))
    state, _ = trainer.step(state, faults.poison_batch((x, y)))
    pm = kfac_tpu.PostmortemWriter(tmp_path / 'pms', engine=kfac)
    bundle = pm.observe(state)  # writer drains for itself
    assert bundle is not None

    names = set(os.listdir(bundle))
    assert {'MANIFEST.json', 'history.npz', 'history.jsonl',
            'factors.json', 'health.json', 'describe.txt', 'config.json',
            'fingerprint.json'} <= names
    man = json.load(open(os.path.join(bundle, 'MANIFEST.json')))
    assert man['schema'] == flight_lib.BUNDLE_SCHEMA
    assert man['reason'] == 'skip' and man['process_index'] == 0
    assert set(man['files']) == names - {'MANIFEST.json'}

    hist = [json.loads(l)
            for l in open(os.path.join(bundle, 'history.jsonl'))]
    assert [h['step'] for h in hist] == [0, 1]  # poisoned step skipped
    npz = np.load(os.path.join(bundle, 'history.npz'))
    assert list(npz['keys']) == list(
        kfac_tpu.observability.metric_keys(kfac.metrics, list(reg.layers)))
    assert npz['scalars'].shape == (8, len(npz['keys']))

    factors = json.load(open(os.path.join(bundle, 'factors.json')))
    assert set(factors) == set(reg.layers)
    for entry in factors.values():
        for side in ('a', 'g'):
            assert entry[side]['finite'] is True
            assert entry[side]['gershgorin_lmax'] >= \
                entry[side]['gershgorin_lmin']
    health = json.load(open(os.path.join(bundle, 'health.json')))
    assert health['enabled'] is True and health['skipped_steps'] == 1
    fp = json.load(open(os.path.join(bundle, 'fingerprint.json')))
    assert fp['jax'] == jax.__version__ and fp['device_count'] >= 1
    cfg = json.load(open(os.path.join(bundle, 'config.json')))
    assert cfg['registry']['layers'] == list(reg.layers)
    assert cfg['flight']['capacity'] == 8


@pytest.mark.faults
def test_postmortem_quarantine_event(tmp_path):
    """A poisoned factor stat (grads clean) fires the quarantine trigger."""
    _, params, batch, _, kfac, run = _dense_setup(
        flight=8, health=health_lib.HealthConfig(warn=False))
    state = kfac.init()
    step = jax.jit(kfac.step)
    pm = kfac_tpu.PostmortemWriter(tmp_path / 'pms', engine=kfac)
    (_, _), grads, stats = run(params, batch)
    state, _ = step(state, grads, stats, loss=jnp.float32(1.0))
    assert pm.observe(state) is None
    state, _ = step(state, grads,
                    faults.poison_stats(stats, 'fc2', side='a'),
                    loss=jnp.float32(1.0))
    bundle = pm.observe(state)
    assert bundle is not None and 'quarantine' in os.path.basename(bundle)
    health = json.load(open(os.path.join(bundle, 'health.json')))
    assert health['layers']['fc2']['quarantine_events'] == 1


@pytest.mark.faults
def test_postmortem_max_bundles(tmp_path):
    trainer, params, (x, y), _, kfac = _trainer_setup(
        flight=4, health=health_lib.HealthConfig(warn=False))
    state = trainer.init(params)
    pm = kfac_tpu.PostmortemWriter(tmp_path / 'pms', engine=kfac,
                                   max_bundles=1)
    state, _ = trainer.step(state, faults.poison_batch((x, y)))
    assert pm.observe(state) is not None
    state, _ = trainer.step(state, (x, y))
    state, _ = trainer.step(state, faults.poison_batch((x, y), kind='inf'))
    assert pm.observe(state) is None  # capped
    assert len(pm.bundles) == 1


# ------------------------------------------------------------ kfac_inspect


@pytest.mark.faults
def test_inspect_roundtrip_names_first_bad_layer(tmp_path, capsys):
    """Inject a divergence into ONE layer; the bundle round-trips through
    kfac_inspect into a timeline whose first bad layer is that layer."""
    _, params, batch, _, kfac, run = _dense_setup(flight=16, health=None)
    state = kfac.init()
    step = jax.jit(kfac.step)
    for i in range(3):
        (_, _), grads, stats = run(params, batch)
        state, _ = step(state, grads, stats, loss=jnp.float32(1.0))
    # fc2's A stats blow up (finite) -> its Gershgorin bound crosses HUGE
    (_, _), grads, stats = run(params, batch)
    state, _ = step(state, grads, faults.huge_stats(stats, 'fc2', side='a'),
                    loss=jnp.float32(2.0))
    # two steps later the loss goes non-finite -> postmortem trigger
    (_, _), grads, stats = run(params, batch)
    state, _ = step(state, grads, stats, loss=jnp.float32(5.0))
    state, _ = step(state, grads, stats, loss=jnp.float32(np.nan))

    pm = kfac_tpu.PostmortemWriter(tmp_path / 'pms', engine=kfac)
    bundle = pm.observe(state)
    assert bundle is not None and 'nonfinite' in os.path.basename(bundle)

    analysis = kfac_inspect.analyze(kfac_inspect.load_bundle(bundle)['history'])
    fb = analysis['first_bad_layer']
    assert fb is not None
    assert fb['layer'] == 'fc2' and fb['step'] == 3
    assert fb['kind'] == 'huge_factor'
    kinds = {(e['step'], e['kind']) for e in analysis['events']}
    assert (5, 'nonfinite_loss') in kinds
    # the factor summaries agree: fc2's A bound is the huge one
    factors = json.load(open(os.path.join(bundle, 'factors.json')))
    assert factors['fc2']['a']['gershgorin_lmax'] >= kfac_inspect.HUGE
    assert factors['fc1']['a']['gershgorin_lmax'] < kfac_inspect.HUGE

    # CLI smoke: text mode mentions the layer, --json parses
    assert kfac_inspect.main([bundle]) == 0
    out = capsys.readouterr().out
    assert 'first bad layer: fc2' in out
    assert kfac_inspect.main([bundle, '--json']) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed['first_bad_layer']['layer'] == 'fc2'
    assert parsed['manifest']['reason'] == 'nonfinite'


def test_inspect_reads_collector_jsonl(tmp_path):
    """The CLI's JSONL mode consumes ordinary MetricsCollector output."""
    # kl_clip off: on this tiny problem the clip legitimately bites,
    # which the analyzer would (correctly) flag as a kl_clip_hard event
    _, params, batch, _, kfac, run = _dense_setup(flight=4, kl_clip=None)
    state = kfac.init()
    step = jax.jit(kfac.step)
    coll = kfac_tpu.MetricsCollector(include_health=False)
    path = tmp_path / 'metrics.jsonl'
    with sinks.JSONLWriter(path) as sink:
        for _ in range(3):
            (_, _), grads, stats = run(params, batch)
            state, _ = step(state, grads, stats, loss=jnp.float32(1.0))
            sink.write(coll.drain(state))
    analysis = kfac_inspect.analyze(kfac_inspect.load_jsonl(str(path)))
    assert analysis['n_records'] == 3
    assert analysis['events'] == [] and analysis['first_bad_layer'] is None


def test_inspect_selftest():
    assert kfac_inspect.selftest() == 0


# ------------------------------------------------------------- satellites


def test_jsonl_writer_creates_parent_dirs(tmp_path):
    path = tmp_path / 'runs' / '2026-08-05' / 'metrics.jsonl'
    with sinks.JSONLWriter(path) as w:
        w.write({'step': 1})
    assert json.loads(path.read_text()) == {'step': 1}


def test_jsonl_writer_flush_before_close():
    """close() flushes explicitly BEFORE closing the underlying file."""

    class Spy:
        def __init__(self):
            self.calls = []

        def write(self, s):
            self.calls.append(('write', s))

        def flush(self):
            self.calls.append(('flush', None))

        def close(self):
            self.calls.append(('close', None))

    w = sinks.JSONLWriter(os.devnull)
    spy = w._file = Spy()
    w.write({'step': 1})
    w.close()
    ops = [c[0] for c in spy.calls]
    assert ops == ['write', 'flush', 'flush', 'close']
    assert w._file is None
    with pytest.raises(ValueError):
        w.write({'step': 2})


def test_collector_trace_window_bounded():
    """include_trace averages a bounded recent window by default, so one
    ancient outlier (a warm-up compile) can't skew time/* forever."""
    _, params, batch, _, kfac, run = _dense_setup(metrics=True)
    state = kfac.init()
    saved = dict(tracing._func_traces)
    try:
        tracing._func_traces.clear()
        tracing._func_traces['warm'] = [100.0] + [1.0] * 500
        rec = kfac_tpu.MetricsCollector(
            include_health=False, include_trace=True).drain(state)
        assert rec['time/warm'] == 1.0  # default window (256) drops the spike
        rec = kfac_tpu.MetricsCollector(
            include_health=False, include_trace=True,
            trace_max_history=None).drain(state)
        assert rec['time/warm'] > 1.0  # unbounded: the spike dominates
    finally:
        tracing._func_traces.clear()
        tracing._func_traces.update(saved)


def test_metric_key_lint_in_sync():
    assert lint_metric_keys.check(
        os.path.join(os.path.dirname(__file__), os.pardir,
                     'docs', 'OBSERVABILITY.md')) == []
