"""Telemetry spine tests: in-jit metrics, sinks, comms accounting, lint.

Pins the contracts docs/OBSERVABILITY.md documents: the metric-key schema
is identical across both engines and both KAISA stat transports, metrics
add zero recompilations after step 1, the collector is a strict no-op
when disabled, and every public jitted engine entry point carries a named
scope (tools/lint_named_scopes.py).
"""

import json
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import kfac_tpu
from kfac_tpu import checkpoint, health, tracing
from kfac_tpu.observability import comms as comms_lib
from kfac_tpu.observability import metrics as metrics_lib
from kfac_tpu.observability import profiler as profiler_lib
from kfac_tpu.observability import sinks
from kfac_tpu.parallel import collectives
from testing import compile_pins, models


def _dense_setup(**cfg_kw):
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, **cfg_kw)
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(models.mse_loss(m))
    return m, params, (x, y), reg, kfac, run


def _run_steps(kfac, run, params, batch, n):
    state = kfac.init()
    step = compile_pins.watched_jit(kfac.step)
    for _ in range(n):
        (_, _), grads, stats = run(params, batch)
        state, _ = step(state, grads, stats)
    return state, step


# ------------------------------------------------------------ schema: dense


@pytest.mark.parametrize('method', ['eigen', 'inverse'])
def test_metric_schema_dense(method):
    """Drained keys == documented schema, for both compute methods."""
    _, params, batch, reg, kfac, run = _dense_setup(
        metrics=True, compute_method=method, kl_clip=0.001
    )
    state, _ = _run_steps(kfac, run, params, batch, 3)
    rec = kfac_tpu.MetricsCollector(include_health=False).drain(state)
    expected = set(
        metrics_lib.metric_keys(kfac.metrics, list(reg.layers))
    ) | {'step'}
    assert set(rec) == expected
    assert rec['step'] == 3
    for k, v in rec.items():
        assert np.isfinite(v), k
    # factors/inverses refreshed this step (cadence 1): staleness is 0,
    # Gershgorin bounds bracket a PSD EMA factor
    for n in reg.names():
        assert rec[f'factor_staleness/{n}'] == 0.0
        assert rec[f'inv_staleness/{n}'] == 0.0
        assert rec[f'factor_lmax/a/{n}'] >= rec[f'factor_lmin/a/{n}']
        assert rec[f'grad_norm/{n}'] > 0.0
        assert rec[f'precond_grad_norm/{n}'] > 0.0
        assert rec[f'damping_eff/{n}'] > 0.0


def test_metrics_disabled_state_and_drain_noop():
    _, params, batch, _, kfac, run = _dense_setup(metrics=None)
    state, _ = _run_steps(kfac, run, params, batch, 1)
    assert state.metrics is None
    rec = kfac_tpu.MetricsCollector(include_health=False).drain(state)
    assert rec == {}


def test_metrics_no_recompilation_across_steps():
    """The static key schema compiles the step exactly once."""
    _, params, batch, _, kfac, run = _dense_setup(metrics=True)
    _, step = _run_steps(kfac, run, params, batch, 5)
    compile_pins.assert_compiled_once(step)


def test_staleness_tracks_update_cadence():
    _, params, batch, reg, kfac, run = _dense_setup(
        metrics=True, factor_update_steps=2, inv_update_steps=2
    )
    state, _ = _run_steps(kfac, run, params, batch, 4)
    rec = kfac_tpu.MetricsCollector(include_health=False).drain(state)
    # updates ran at steps 0 and 2 (internal step counter), so after 4
    # steps the last accepted update is 1 step old
    for n in reg.names():
        assert rec[f'factor_staleness/{n}'] == 1.0
        assert rec[f'inv_staleness/{n}'] == 1.0


def test_kl_clip_disabled_reports_unit_scale():
    _, params, batch, _, kfac, run = _dense_setup(metrics=True, kl_clip=None)
    state, _ = _run_steps(kfac, run, params, batch, 2)
    rec = kfac_tpu.MetricsCollector(include_health=False).drain(state)
    assert rec['kl_clip_scale'] == 1.0


def test_collector_folds_health_counters():
    _, params, batch, reg, kfac, run = _dense_setup(metrics=True, health=True)
    state, _ = _run_steps(kfac, run, params, batch, 2)
    rec = kfac_tpu.MetricsCollector(include_health=True).drain(state)
    expected_health = set(health.health_metric_keys(reg.names()))
    assert expected_health <= set(rec)
    assert rec['health/skipped_steps'] == 0


def test_health_metric_keys_match_counters():
    """The documented health/* schema is exactly what drains emit."""
    _, params, batch, reg, kfac, run = _dense_setup(health=True)
    state, _ = _run_steps(kfac, run, params, batch, 1)
    counters = tracing.health_counters(state)
    assert set(counters) == set(health.health_metric_keys(reg.names()))


def test_checkpoint_roundtrip_ignores_metrics(tmp_path):
    """Metrics state is ephemeral: restore rebuilds it fresh."""
    _, params, batch, _, kfac, run = _dense_setup(metrics=True)
    state, _ = _run_steps(kfac, run, params, batch, 2)
    path = str(tmp_path / 'ckpt')
    checkpoint.save(path, state)
    restored, _ = checkpoint.restore(path, kfac)
    assert int(restored.step) == 2
    assert restored.metrics is not None
    # freshly initialized, not the saved live values
    assert float(restored.metrics.as_dict()['kl_clip_scale']) == 1.0


# -------------------------------------------------------------- config edges


def test_metrics_config_normalization():
    _, _, _, reg, kfac_on, _ = _dense_setup(metrics=True)
    assert isinstance(kfac_on.metrics, kfac_tpu.MetricsConfig)
    kfac_off = kfac_tpu.KFACPreconditioner(registry=reg, metrics=False)
    assert kfac_off.metrics is None
    with pytest.raises(TypeError):
        kfac_tpu.KFACPreconditioner(registry=reg, metrics='yes')


def test_metrics_config_rejects_all_disabled():
    with pytest.raises(ValueError):
        kfac_tpu.MetricsConfig(
            grad_norms=False, factor_bounds=False, staleness=False
        )


def test_partial_schema_drops_family_keys():
    _, params, batch, reg, kfac, run = _dense_setup(
        metrics=kfac_tpu.MetricsConfig(grad_norms=False, factor_bounds=False)
    )
    state, _ = _run_steps(kfac, run, params, batch, 1)
    rec = kfac_tpu.MetricsCollector(include_health=False).drain(state)
    assert not any(k.startswith('grad_norm/') for k in rec)
    assert not any(k.startswith('factor_lmax/') for k in rec)
    for n in reg.names():
        assert f'factor_staleness/{n}' in rec


def test_gershgorin_bounds_reference_values():
    lmin, lmax = metrics_lib.gershgorin_bounds(jnp.eye(4))
    assert float(lmin) == 1.0 and float(lmax) == 1.0
    m = jnp.array([[2.0, 1.0], [1.0, 3.0]])
    lmin, lmax = metrics_lib.gershgorin_bounds(m)
    assert float(lmin) == 1.0 and float(lmax) == 4.0
    # stacked: bounds over the stack
    lmin, lmax = metrics_lib.gershgorin_bounds(jnp.stack([jnp.eye(2), m]))
    assert float(lmin) == 1.0 and float(lmax) == 4.0


# ------------------------------------------------------- schema: distributed


@pytest.mark.parametrize('transport', ['allreduce', 'allreduce_bucketed'])
def test_metric_schema_distributed(transport):
    """Same drained schema on the sharded engine, both stat transports."""
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(
        registry=reg, metrics=True, kl_clip=0.001,
        allreduce_method=transport,
    )
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(models.mse_loss(m))
    state = dk.init()
    step = compile_pins.watched_jit(dk.step)
    for _ in range(2):
        (_, _), grads, stats = run(params, (x, y))
        state, _ = step(state, grads, stats)
    compile_pins.assert_compiled_once(step)
    rec = kfac_tpu.MetricsCollector(include_health=False).drain(state)
    expected = set(
        metrics_lib.metric_keys(cfg.metrics, list(reg.layers))
    ) | {'step'}
    assert set(rec) == expected
    for k, v in rec.items():
        assert np.isfinite(v), k
    for n in reg.names():
        assert rec[f'grad_norm/{n}'] > 0.0
        assert rec[f'factor_lmax/a/{n}'] >= rec[f'factor_lmin/a/{n}']


def test_distributed_metrics_match_dense():
    """Per-layer metric values agree with the dense engine on the same
    stats — the telemetry reads the same math both ways."""
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    mesh = kaisa_mesh(grad_worker_fraction=1.0)
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(
        registry=reg, metrics=True, kl_clip=0.001, damping=0.01
    )
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(
        models.mse_loss(m))(params, (x, y))

    ref_state, _ = cfg.step(cfg.init(), grads, stats)
    dist_state, _ = jax.jit(dk.step)(dk.init(), grads, stats)
    ref = kfac_tpu.MetricsCollector(include_health=False).drain(ref_state)
    dist = kfac_tpu.MetricsCollector(include_health=False).drain(dist_state)
    assert set(ref) == set(dist)
    for k in ref:
        np.testing.assert_allclose(ref[k], dist[k], rtol=5e-3, atol=1e-6)


# ------------------------------------------------------------ sinks


def test_jsonl_writer_roundtrip(tmp_path):
    path = tmp_path / 'metrics.jsonl'
    with sinks.JSONLWriter(path, append=False) as w:
        w.write({'step': np.int32(1), 'x': np.float32(0.5)})
        w.write({})  # empty drain: no line
        w.write({'step': 2, 'x': 0.25})
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert rows == [{'step': 1, 'x': 0.5}, {'step': 2, 'x': 0.25}]
    # append mode extends, write-after-close raises
    w2 = sinks.JSONLWriter(path)
    w2.write({'step': 3})
    w2.close()
    with pytest.raises(ValueError):
        w2.write({'step': 4})
    assert len(path.read_text().splitlines()) == 3


def test_rate_limited_logger(caplog):
    rl = sinks.RateLimitedLogger(min_interval_s=3600.0)
    with caplog.at_level(logging.INFO, logger='kfac_tpu.observability'):
        assert rl.emit({'step': 1, 'kl_clip_scale': 0.5, 'extra': 1.0})
        assert not rl.emit({'step': 2})  # inside the interval
    assert not rl.emit({})  # empty: never logs
    assert len(caplog.records) == 1
    assert 'kl_clip_scale' in caplog.records[0].message


# ------------------------------------------------------------ tracing


def test_trace_sync_blocks_full_pytree():
    tracing.clear_trace()

    @tracing.trace(sync=True, name='pytree_work')
    def work(x):
        return {'a': x * 2, 'b': (x + 1, jnp.sum(x))}

    out = work(jnp.arange(8.0))
    assert float(out['b'][1]) == 28.0
    assert tracing.get_trace()['pytree_work'] > 0
    tracing.clear_trace()


def test_force_sync_toggle():
    assert not tracing.sync_forced()
    tracing.force_sync(True)
    try:
        assert tracing.sync_forced()

        @tracing.trace(name='forced')
        def f(x):
            return x + 1

        f(jnp.zeros(4))
        assert 'forced' in tracing.get_trace()
    finally:
        tracing.force_sync(False)
        tracing.clear_trace()
    assert not tracing.sync_forced()


def test_trainer_step_paths_traced():
    """Trainer.step lands in the tracing table under its scope name."""
    import optax

    m, params, batch, reg, kfac, _ = _dense_setup(metrics=True)
    trainer = kfac_tpu.Trainer(
        loss_fn=lambda p, ms, b: (models.mse_loss(m)(p, b), ms),
        optimizer=optax.sgd(0.05),
        kfac=kfac,
    )
    tracing.clear_trace()
    tstate = trainer.init(params)
    tstate, _ = trainer.step(tstate, batch)
    assert 'trainer/step' in tracing.get_trace()
    # the collector unwraps TrainState.kfac_state
    rec = kfac_tpu.MetricsCollector(include_health=False).drain(tstate)
    assert rec['step'] == 1
    tracing.clear_trace()


def test_lint_named_scopes_clean():
    import sys
    sys.path.insert(0, 'tools')
    try:
        import lint_named_scopes
    finally:
        sys.path.pop(0)
    assert lint_named_scopes.check() == []


# ------------------------------------------------------------ comms


def _dist_engine(transport, **cfg_kw):
    from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh

    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    m = models.TinyModel(hidden=8, out=4)
    x, _ = models.regression_data(jax.random.PRNGKey(1), n=64, dim=6)
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(
        registry=reg, allreduce_method=transport, **cfg_kw
    )
    return DistributedKFAC(config=cfg, mesh=mesh)


def test_comms_report_transports():
    dense = _dist_engine('allreduce').comms_report()
    buck = _dist_engine('allreduce_bucketed').comms_report()
    assert dense['stat_transport']['method'] == 'ALLREDUCE'
    assert buck['stat_transport']['method'] == 'ALLREDUCE_BUCKETED'
    # triangles beat dense bytes; savings consistent
    assert buck['stat_transport']['bytes'] < buck['stat_transport']['dense_bytes']
    assert buck['stat_transport']['savings'] > 0
    for rep in (dense, buck):
        assert rep['grad_broadcast_bytes'] > 0
        assert rep['decomp_reshard_bytes'] > 0
        assert rep['grad_worker_fraction'] == 0.5
        totals = rep['padding_totals']
        per_class = rep['padding']
        assert totals['resident_bytes'] == sum(
            p['resident_bytes'] for p in per_class.values())


def test_comms_report_respects_bucket_cap():
    dk = _dist_engine('allreduce_bucketed', allreduce_bucket_cap_mb=1e-4)
    chunks = dk.comms_report()['stat_transport']['chunks']
    assert len(chunks) > 1
    # the cap is honored except for single oversized tensors
    for c in chunks:
        assert c['tensors'] == 1 or c['bytes'] <= 100


def test_plan_chunks_matches_concat_flat_chunked():
    tensors = [
        jnp.zeros(10, jnp.float32),
        jnp.zeros(300, jnp.bfloat16),
        jnp.zeros(5000, jnp.float32),
        jnp.zeros(7, jnp.float32),
    ]
    specs = [(int(t.size), t.dtype) for t in tensors]
    for cap in (None, 100, 1024, 10_000, 1e9):
        actual = collectives.concat_flat_chunked(tensors, max_bytes=cap)
        plan = collectives.plan_chunks(specs, max_bytes=cap)
        assert len(plan) == len(actual)
        for p, (buf, metas) in zip(plan, actual):
            assert p['tensors'] == len(metas)
            assert p['elements'] == int(buf.size)
            assert p['dtype'] == str(buf.dtype)
            assert p['bytes'] == buf.size * buf.dtype.itemsize


def test_memory_usage_padding_waste_consistent():
    dk = _dist_engine('allreduce')
    state = dk.init()
    usage = dk.memory_usage(state)
    waste = usage['padding_waste']
    per_class = waste['per_class']
    item = jnp.dtype(dk.config.factor_dtype).itemsize
    for side, store in (('a', dk.a_store), ('g', dk.g_store)):
        for sb in store:
            p = per_class[f'{side}/{sb.key}']
            assert (
                p['resident_bytes'] + p['identity_pad_bytes']
                + p['slot_pad_bytes'] == p['total_bytes']
            )
            assert p['total_bytes'] == sb.padded * sb.d * sb.d * item
            assert 0 < p['fill'] <= 1
    assert waste['resident_bytes'] == sum(
        p['resident_bytes'] for p in per_class.values())
    # the waste breakdown rides alongside, not inside, the byte categories
    assert usage['total'] == (
        usage['a_factors'] + usage['g_factors']
        + usage['a_inverses'] + usage['g_inverses']
    )


def test_describe_reports_fill_and_metrics():
    dk = _dist_engine('allreduce', metrics=True)
    d = dk.describe()
    assert 'fill' in d
    assert 'metrics:' in d


# ------------------------------------------------------------ profiler


def test_capture_steps_writes_trace(tmp_path):
    _, params, batch, _, kfac, run = _dense_setup(metrics=True)
    state = kfac.init()
    step = jax.jit(kfac.step)
    carry = {'state': state}

    def one(i):
        (_, _), grads, stats = run(params, batch)
        carry['state'], pg = step(carry['state'], grads, stats)
        return pg

    logdir = tmp_path / 'trace'
    out = profiler_lib.capture_steps(str(logdir), one, steps=2)
    assert out is not None
    assert int(carry['state'].step) == 2
    assert any(logdir.rglob('*')), 'profiler wrote nothing'
