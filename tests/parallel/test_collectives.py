"""Collective wrapper and transport utility tests (8-device CPU mesh).

Behavioral targets from reference tests/distributed_test.py:51-313
(allreduce/broadcast/symmetric transport), restated for mesh collectives.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from kfac_tpu.parallel import collectives


def _mesh1d():
    return Mesh(np.asarray(jax.devices()).reshape(8), ('x',))


def test_psum_mean():
    mesh = _mesh1d()

    def body(x):
        return collectives.psum_mean(x, 'x')

    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    out = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=P('x'), out_specs=P('x'))
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 3.5))


def test_broadcast_from_src():
    mesh = _mesh1d()

    def body(x):
        return collectives.broadcast_from(x, 'x', src_index=3)

    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    out = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=P('x'), out_specs=P('x'))
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 3.0))


def test_all_gather_axis():
    mesh = _mesh1d()

    def body(x):
        return collectives.all_gather_axis(x, 'x', axis=0)

    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    out = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=P('x'), out_specs=P(None, 'x'))
    )(x)
    assert out.shape == (8, 8)
    np.testing.assert_allclose(np.asarray(out)[:, 0], np.arange(8))


def test_reduce_scatter():
    mesh = _mesh1d()

    def body(x):
        # local view is (1, 8); scatter the 8-wide dim across the axis
        return collectives.reduce_scatter_axis(x, 'x', axis=1)

    x = jnp.ones((8, 8), dtype=jnp.float32)
    out = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=P('x'), out_specs=P('x'))
    )(x)
    # row i of the result is the sum over devices of their column-i slice
    assert out.shape == (8, 1)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 8.0))


def test_triu_roundtrip():
    rng = np.random.default_rng(0)
    m = rng.normal(size=(7, 7)).astype(np.float32)
    sym = (m + m.T) / 2
    packed = collectives.get_triu(jnp.asarray(sym))
    assert packed.shape == (7 * 8 // 2,)
    restored = collectives.fill_triu((7, 7), packed)
    np.testing.assert_allclose(np.asarray(restored), sym, rtol=1e-6)


def test_triu_rejects_nonsquare():
    import pytest

    with pytest.raises(ValueError):
        collectives.get_triu(jnp.ones((3, 4)))


def test_concat_split_roundtrip():
    tensors = [
        jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
        jnp.ones((4,)),
        jnp.zeros((2, 2, 2)),
    ]
    flat, specs = collectives.concat_flat(tensors)
    assert flat.shape == (6 + 4 + 8,)
    back = collectives.split_flat(flat, specs)
    for orig, rec in zip(tensors, back):
        np.testing.assert_array_equal(np.asarray(orig), np.asarray(rec))


def test_concat_split_restores_mixed_dtypes():
    tensors = [jnp.ones((2, 2), jnp.bfloat16), jnp.ones((3,), jnp.float32)]
    flat, specs = collectives.concat_flat(tensors)
    back = collectives.split_flat(flat, specs)
    assert back[0].dtype == jnp.bfloat16
    assert back[1].dtype == jnp.float32
