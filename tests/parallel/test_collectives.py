"""Symmetric/bucketed transport utility tests.

Behavioral targets from reference tests/distributed_test.py:51-313
(symmetric/bucketed transport). The thin collective wrappers were removed:
XLA collectives are used directly where needed.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from kfac_tpu.parallel import collectives


def test_triu_roundtrip():
    rng = np.random.default_rng(0)
    m = rng.normal(size=(7, 7)).astype(np.float32)
    sym = (m + m.T) / 2
    packed = collectives.get_triu(jnp.asarray(sym))
    assert packed.shape == (7 * 8 // 2,)
    restored = collectives.fill_triu((7, 7), packed)
    np.testing.assert_allclose(np.asarray(restored), sym, rtol=1e-6)


def test_triu_rejects_nonsquare():
    import pytest

    with pytest.raises(ValueError):
        collectives.get_triu(jnp.ones((3, 4)))


def test_concat_split_roundtrip():
    tensors = [
        jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
        jnp.ones((4,)),
        jnp.zeros((2, 2, 2)),
    ]
    flat, specs = collectives.concat_flat(tensors)
    assert flat.shape == (6 + 4 + 8,)
    back = collectives.split_flat(flat, specs)
    for orig, rec in zip(tensors, back):
        np.testing.assert_array_equal(np.asarray(orig), np.asarray(rec))


def test_concat_split_restores_mixed_dtypes():
    tensors = [jnp.ones((2, 2), jnp.bfloat16), jnp.ones((3,), jnp.float32)]
    flat, specs = collectives.concat_flat(tensors)
    back = collectives.split_flat(flat, specs)
    assert back[0].dtype == jnp.bfloat16
    assert back[1].dtype == jnp.float32


def test_concat_flat_chunked_respects_byte_cap():
    """Greedy in-order packing under a byte cap (the reference's 25 MB
    bucket cap, kfac/distributed.py:305-374): chunk boundaries respect the
    cap, order is preserved, an oversized tensor gets its own chunk."""
    tensors = [
        jnp.full((25,), i, jnp.float32) for i in range(4)  # 100 B each
    ]
    chunks = collectives.concat_flat_chunked(tensors, max_bytes=200)
    assert [c[0].size for c in chunks] == [50, 50]
    back = collectives.split_flat_chunked(chunks)
    for orig, rec in zip(tensors, back):
        np.testing.assert_array_equal(np.asarray(orig), np.asarray(rec))

    # an oversized tensor is never split — it rides alone
    tensors = [jnp.ones((10,)), jnp.ones((100,)), jnp.ones((10,))]
    chunks = collectives.concat_flat_chunked(tensors, max_bytes=64)
    assert [c[0].size for c in chunks] == [10, 100, 10]
    assert len(collectives.split_flat_chunked(chunks)) == 3


def test_concat_flat_chunked_uncapped_and_empty():
    tensors = [jnp.ones((3,)), jnp.zeros((2, 2))]
    chunks = collectives.concat_flat_chunked(tensors, max_bytes=None)
    assert len(chunks) == 1 and chunks[0][0].size == 7
    # empty input: one empty chunk, splits to nothing
    chunks = collectives.concat_flat_chunked([], max_bytes=128)
    assert len(chunks) == 1
    assert collectives.split_flat_chunked(chunks) == []


def test_concat_flat_chunked_sizes_at_promoted_dtype():
    """Mixed-dtype packing promotes in the buffer (concat_flat), so the
    cap must be applied at the PROMOTED size: 25 bf16 elems next to 25 f32
    elems cost 50*4 B packed, not 25*2 + 25*4."""
    tensors = [
        jnp.ones((25,), jnp.bfloat16),   # 100 B packed at f32
        jnp.ones((25,), jnp.float32),    # 100 B
        jnp.ones((25,), jnp.bfloat16),   # 100 B packed at f32
    ]
    # naive (pre-promotion) sizing would fit the first two in a 180 B cap
    # (50+100); promoted sizing (100+100) must split them
    chunks = collectives.concat_flat_chunked(tensors, max_bytes=180)
    assert [c[0].size for c in chunks] == [25, 25, 25]
    back = collectives.split_flat_chunked(chunks)
    assert [b.dtype for b in back] == [jnp.bfloat16, jnp.float32, jnp.bfloat16]


# --------------------------------------------------------------- property
_hyp = pytest.importorskip('hypothesis')
from hypothesis import given, settings, strategies as st  # noqa: E402

@given(
    sizes=st.lists(st.integers(1, 40), min_size=0, max_size=12),
    dtypes=st.lists(st.sampled_from(['f32', 'bf16']), min_size=12,
                    max_size=12),
    cap=st.integers(16, 400),
)
@settings(max_examples=60, deadline=None)
def test_chunked_packing_properties(sizes, dtypes, cap):
    """For ANY tensor list and byte cap: roundtrip preserves values,
    dtypes, and order; every multi-tensor chunk respects the cap at
    the PROMOTED dtype (single oversized tensors ride alone)."""
    dt = {'f32': jnp.float32, 'bf16': jnp.bfloat16}
    tensors = [
        jnp.arange(n, dtype=jnp.float32).astype(dt[d])
        for n, d in zip(sizes, dtypes)
    ]
    chunks = collectives.concat_flat_chunked(tensors, max_bytes=cap)
    back = collectives.split_flat_chunked(chunks)
    assert len(back) == len(tensors)
    for orig, rec in zip(tensors, back):
        assert rec.dtype == orig.dtype
        np.testing.assert_array_equal(
            np.asarray(orig, np.float32), np.asarray(rec, np.float32)
        )
    for flat, specs in chunks:
        if len(specs) > 1:
            assert flat.size * flat.dtype.itemsize <= cap, (
                flat.size, flat.dtype, cap
            )
