"""Symmetric/bucketed transport utility tests.

Behavioral targets from reference tests/distributed_test.py:51-313
(symmetric/bucketed transport). The thin collective wrappers were removed:
XLA collectives are used directly where needed.
"""

import jax.numpy as jnp
import numpy as np

from kfac_tpu.parallel import collectives


def test_triu_roundtrip():
    rng = np.random.default_rng(0)
    m = rng.normal(size=(7, 7)).astype(np.float32)
    sym = (m + m.T) / 2
    packed = collectives.get_triu(jnp.asarray(sym))
    assert packed.shape == (7 * 8 // 2,)
    restored = collectives.fill_triu((7, 7), packed)
    np.testing.assert_allclose(np.asarray(restored), sym, rtol=1e-6)


def test_triu_rejects_nonsquare():
    import pytest

    with pytest.raises(ValueError):
        collectives.get_triu(jnp.ones((3, 4)))


def test_concat_split_roundtrip():
    tensors = [
        jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
        jnp.ones((4,)),
        jnp.zeros((2, 2, 2)),
    ]
    flat, specs = collectives.concat_flat(tensors)
    assert flat.shape == (6 + 4 + 8,)
    back = collectives.split_flat(flat, specs)
    for orig, rec in zip(tensors, back):
        np.testing.assert_array_equal(np.asarray(orig), np.asarray(rec))


def test_concat_split_restores_mixed_dtypes():
    tensors = [jnp.ones((2, 2), jnp.bfloat16), jnp.ones((3,), jnp.float32)]
    flat, specs = collectives.concat_flat(tensors)
    back = collectives.split_flat(flat, specs)
    assert back[0].dtype == jnp.bfloat16
    assert back[1].dtype == jnp.float32
