"""Pipeline-parallel LM + K-FAC tests (GPipe schedule over a pipe axis).

Behavioral targets: the reference's GPT-NeoX pipeline e2e suite
(tests/gpt_neox/gpt_preconditioner_test.py: preconditioner over pipeline
stages {1,2,4}) — here the schedule itself is also validated against an
unpipelined sequential application of the same stage weights.
"""

import flax.linen as flax_nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import kfac_tpu
from kfac_tpu.parallel import pipeline


def _mesh(n_stages):
    return Mesh(
        np.asarray(jax.devices()[:n_stages]).reshape(n_stages), ('pipe',)
    )


def _model(n_stages, num_layers=4, micro=4, d=32):
    return pipeline.PipelinedLM(
        mesh=_mesh(n_stages),
        vocab_size=64,
        d_model=d,
        num_heads=4,
        num_layers=num_layers,
        n_microbatches=micro,
        max_len=16,
    )


def _sequential_logits(model, params, tokens):
    """Oracle: apply stages one after another without the pipeline."""
    x = model._embed(params, tokens)
    for s in range(model.n_stages):
        sp = jax.tree_util.tree_map(lambda v: v[s], params['stages'])
        x = model.stage.apply({'params': sp}, x)
    x = model.ln_f.apply({'params': params['ln_f']}, x.astype(jnp.float32))
    return model.head.apply({'params': params['head']}, x)


@pytest.mark.parametrize(
    'n_stages,layers',
    [(1, 2), (2, 4), pytest.param(4, 4, marks=pytest.mark.slow)],
)
def test_pipeline_forward_matches_sequential(n_stages, layers):
    model = _model(n_stages, num_layers=layers)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(1))
    logits, a_stats, counts = jax.jit(model.apply)(params, tokens)
    expected = _sequential_logits(model, params, tokens)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(expected), rtol=2e-3, atol=2e-4
    )
    # every stage processed all microbatches
    np.testing.assert_allclose(np.asarray(counts), model.n_microbatches)
    for name, h in model.stage_registry.layers.items():
        assert a_stats[name].shape == (n_stages,) + h.a_factor_shape


def test_pipeline_stats_match_dense_capture():
    """Stage-stacked A/G stats must equal the dense interceptor capture on
    the equivalent unpipelined model (single stage)."""
    model = _model(1, num_layers=2, micro=2)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(1))
    targets = jnp.roll(tokens, -1, 1)
    loss, grads, stats = model.loss_and_stats(params, (tokens, targets))

    # dense oracle: same computation as a flat flax model via the standard
    # capture machinery
    def flat_loss(stage_params, batch):
        tk, tg = batch
        x = model._embed(params, tk)
        x = model.stage.apply({'params': stage_params}, x)
        x = model.ln_f.apply({'params': params['ln_f']}, x.astype(jnp.float32))
        logits = model.head.apply({'params': params['head']}, x)
        logp = jax.nn.log_softmax(logits, -1)
        return -jnp.mean(jnp.take_along_axis(logp, tg[..., None], -1))

    cap = kfac_tpu.CurvatureCapture(model.stage_registry)
    sp0 = jax.tree_util.tree_map(lambda v: v[0], params['stages'])
    (loss0, _), grads0, stats0 = cap.value_stats_and_grad(flat_loss)(
        sp0, (tokens, targets)
    )
    np.testing.assert_allclose(float(loss), float(loss0), rtol=1e-5)
    for name in stats0.a:
        np.testing.assert_allclose(
            np.asarray(stats.a[name][0]), np.asarray(stats0.a[name]),
            rtol=1e-3, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(stats.g[name][0]), np.asarray(stats0.g[name]),
            rtol=1e-3, atol=1e-6,
        )
    # stage grads match too
    np.testing.assert_allclose(
        np.asarray(
            grads['stages']['block0']['attn']['q_proj']['kernel'][0]
        ),
        np.asarray(grads0['block0']['attn']['q_proj']['kernel']),
        rtol=1e-3, atol=1e-6,
    )


@pytest.mark.parametrize(
    'n_stages', [2, pytest.param(4, marks=pytest.mark.slow)]
)
def test_pipeline_kfac_training(n_stages):
    model = _model(n_stages, num_layers=4, micro=4)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    targets = jnp.roll(tokens, -1, 1)
    params = model.init(jax.random.PRNGKey(1))
    cfg = kfac_tpu.KFACPreconditioner(
        registry=model.stage_registry, damping=0.01, lr=0.1
    )
    pk = pipeline.PipelineKFAC(config=cfg, model=model)
    state = pk.init()

    @jax.jit
    def train_step(params, state, batch):
        loss, grads, stats = model.loss_and_stats(params, batch)
        state, grads = pk.step(state, grads, stats)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g, params, grads
        )
        return params, state, loss

    losses = []
    for _ in range(6):
        params, state, loss = train_step(params, state, (tokens, targets))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))
    assert int(state['step']) == 6
    # stage factor state is actually sharded over pipe
    key = next(iter(state['a']))
    assert 'pipe' in str(state['a'][key].sharding.spec)


@pytest.mark.slow
def test_pipeline_dp_matches_pipe_only():
    """PP composed with DP: the (2 pipe x 4 data) mesh must produce the
    same loss trajectory as the pipe-only 2-stage run on the same global
    batch — proving the batch shard / stat psum / grad reduction over the
    data axes is exact (the reference's DP factor allreduce,
    kfac/gpt_neox/layer.py:61-93)."""
    from kfac_tpu.parallel import mesh as mesh_lib

    def run(mesh, steps=5):
        model = pipeline.PipelinedLM(
            mesh=mesh, vocab_size=64, d_model=32, num_heads=4,
            num_layers=4, n_microbatches=2, max_len=16,
        )
        tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
        targets = jnp.roll(tokens, -1, 1)
        params = model.init(jax.random.PRNGKey(1))
        cfg = kfac_tpu.KFACPreconditioner(
            registry=model.stage_registry, damping=0.01, lr=0.1,
            factor_update_steps=2, inv_update_steps=2,
        )
        pk = pipeline.PipelineKFAC(config=cfg, model=model)
        state = pk.init()

        @jax.jit
        def train_step(params, state, batch):
            loss, grads, stats = model.loss_and_stats(params, batch)
            state, grads = pk.step(state, grads, stats)
            params = jax.tree_util.tree_map(
                lambda p, g: p - 0.1 * g, params, grads
            )
            return params, state, loss

        losses = []
        for _ in range(steps):
            params, state, loss = train_step(params, state, (tokens, targets))
            losses.append(float(loss))
        return losses, model

    dp_mesh = mesh_lib.pipeline_mesh(n_stages=2)
    assert dict(dp_mesh.shape) == {
        'pipe': 2, 'kfac_gw': 1, 'kfac_col': 4, 'model': 1,
    }
    losses_dp, model_dp = run(dp_mesh)
    losses_pp, _ = run(_mesh(2))
    np.testing.assert_allclose(losses_dp, losses_pp, rtol=2e-4)
    assert losses_dp[-1] < losses_dp[0]


@pytest.mark.slow
def test_pipeline_dp_stats_match_dense_capture():
    """A/G statistics captured under PP x DP equal the dense interceptor
    capture of the same single-stage model on the full batch."""
    from kfac_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.pipeline_mesh(n_stages=1)
    model = pipeline.PipelinedLM(
        mesh=mesh, vocab_size=64, d_model=32, num_heads=4,
        num_layers=2, n_microbatches=2, max_len=16,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(0), (16, 16), 0, 64)
    targets = jnp.roll(tokens, -1, 1)
    params = model.init(jax.random.PRNGKey(1))
    loss, grads, stats = model.loss_and_stats(params, (tokens, targets))

    def flat_loss(stage_params, batch):
        tk, tg = batch
        x = model._embed(params, tk)
        x = model.stage.apply({'params': stage_params}, x)
        x = model.ln_f.apply({'params': params['ln_f']}, x.astype(jnp.float32))
        logits = model.head.apply({'params': params['head']}, x)
        logp = jax.nn.log_softmax(logits, -1)
        return -jnp.mean(jnp.take_along_axis(logp, tg[..., None], -1))

    cap = kfac_tpu.CurvatureCapture(model.stage_registry)
    sp0 = jax.tree_util.tree_map(lambda v: v[0], params['stages'])
    (loss0, _), grads0, stats0 = cap.value_stats_and_grad(flat_loss)(
        sp0, (tokens, targets)
    )
    np.testing.assert_allclose(float(loss), float(loss0), rtol=1e-5)
    for name in stats0.a:
        np.testing.assert_allclose(
            np.asarray(stats.a[name][0]), np.asarray(stats0.a[name]),
            rtol=1e-3, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(stats.g[name][0]), np.asarray(stats0.g[name]),
            rtol=1e-3, atol=1e-6,
        )


# deliberately NOT slow-marked: this is the equivalence guard on the
# hardest scheduling code (VERDICT r3 weak #7 — the fast tier must keep
# it); ~60 s warm-cache on the 1-core container
def test_1f1b_matches_gpipe_loss_grads_stats():
    """The combined-scan 1F1B schedule computes the same loss, parameter
    gradients, and A/G statistics as the GPipe autodiff path — on a
    DP x PP mesh (2 pipe x 2 data)."""
    from kfac_tpu.parallel.mesh import pipeline_mesh

    mesh = pipeline_mesh(n_stages=2, devices=jax.devices()[:4])
    kw = dict(
        mesh=mesh, vocab_size=64, d_model=32, num_heads=4, num_layers=2,
        n_microbatches=4, max_len=16,
    )
    gp = pipeline.PipelinedLM(**kw, schedule='gpipe')
    ob = pipeline.PipelinedLM(**kw, schedule='1f1b')
    params = gp.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    targets = jnp.roll(tokens, -1, 1)
    l_g, g_g, s_g = jax.jit(gp.loss_and_stats)(params, (tokens, targets))
    l_o, g_o, s_o = jax.jit(ob.loss_and_stats)(params, (tokens, targets))
    np.testing.assert_allclose(float(l_g), float(l_o), rtol=1e-5)
    flat_g = jax.tree_util.tree_leaves_with_path(g_g)
    flat_o = jax.tree_util.tree_leaves_with_path(g_o)
    for (pg, vg), (po, vo) in zip(flat_g, flat_o):
        assert pg == po
        np.testing.assert_allclose(
            np.asarray(vg), np.asarray(vo), rtol=2e-4, atol=2e-6,
            err_msg=str(pg),
        )
    for k in s_g.a:
        np.testing.assert_allclose(
            np.asarray(s_g.a[k]), np.asarray(s_o.a[k]),
            rtol=1e-4, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(s_g.g[k]), np.asarray(s_o.g[k]),
            rtol=1e-4, atol=1e-7,
        )


@pytest.mark.slow
def test_1f1b_kfac_training():
    """End-to-end: PipelineKFAC trains on the 1F1B schedule, many
    microbatches (the regime the O(stages) residual ring exists for)."""
    model = pipeline.PipelinedLM(
        mesh=_mesh(2), vocab_size=64, d_model=32, num_heads=4, num_layers=2,
        n_microbatches=8, max_len=16, schedule='1f1b',
    )
    tokens = jax.random.randint(jax.random.PRNGKey(0), (16, 16), 0, 64)
    targets = jnp.roll(tokens, -1, 1)
    params = model.init(jax.random.PRNGKey(1))
    cfg = kfac_tpu.KFACPreconditioner(
        registry=model.stage_registry, damping=0.01, lr=0.1
    )
    pk = pipeline.PipelineKFAC(config=cfg, model=model)
    state = pk.init()

    @jax.jit
    def train_step(params, state, batch):
        loss, grads, stats = model.loss_and_stats(params, batch)
        state, grads = pk.step(state, grads, stats)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g, params, grads
        )
        return params, state, loss

    losses = []
    for _ in range(6):
        params, state, loss = train_step(params, state, (tokens, targets))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_1f1b_rejects_unknown_schedule():
    with pytest.raises(ValueError):
        pipeline.PipelinedLM(
            mesh=_mesh(2), vocab_size=64, d_model=32, num_heads=4,
            num_layers=2, schedule='2f2b',
        )


@pytest.mark.slow
def test_pipeline_inverse_method_matches_eigen():
    """INVERSE (Newton-Schulz) and EIGEN solve the same damped Kronecker
    system, so pipelined training trajectories coincide."""
    def run(**cfg_kw):
        model = _model(2, num_layers=2, micro=4)
        tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
        targets = jnp.roll(tokens, -1, 1)
        params = model.init(jax.random.PRNGKey(1))
        cfg = kfac_tpu.KFACPreconditioner(
            registry=model.stage_registry, damping=0.01, lr=0.1,
            kl_clip=None, **cfg_kw,
        )
        pk = pipeline.PipelineKFAC(config=cfg, model=model)
        state = pk.init()

        @jax.jit
        def train_step(params, state, batch):
            loss, grads, stats = model.loss_and_stats(params, batch)
            state, grads = pk.step(state, grads, stats)
            params = jax.tree_util.tree_map(
                lambda p, g: p - 0.1 * g, params, grads
            )
            return params, state, loss

        losses = []
        for _ in range(5):
            params, state, loss = train_step(
                params, state, (tokens, targets)
            )
            losses.append(float(loss))
        return losses

    eig = run(compute_method='eigen')
    inv = run(compute_method='inverse', inverse_solver='newton_schulz')
    chol = run(compute_method='inverse')
    assert all(np.isfinite(eig)) and eig[-1] < eig[0]
    np.testing.assert_allclose(eig, inv, rtol=2e-3)
    np.testing.assert_allclose(chol, inv, rtol=2e-3)


@pytest.mark.slow
def test_pipeline_checkpoint_roundtrip(tmp_path):
    """PipelineKFAC state saves/restores through kfac_tpu.checkpoint:
    factors persist, decompositions rematerialize, trajectories continue
    identically."""
    pytest.importorskip('orbax.checkpoint')
    from kfac_tpu import checkpoint as ckpt_lib

    model = _model(2, num_layers=2, micro=2)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 64)
    targets = jnp.roll(tokens, -1, 1)
    params = model.init(jax.random.PRNGKey(1))
    cfg = kfac_tpu.KFACPreconditioner(
        registry=model.stage_registry, damping=0.01, lr=0.1
    )
    pk = pipeline.PipelineKFAC(config=cfg, model=model)
    state = pk.init()

    @jax.jit
    def train_step(params, state, batch):
        loss, grads, stats = model.loss_and_stats(params, batch)
        state, grads = pk.step(state, grads, stats)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g, params, grads
        )
        return params, state, loss

    for _ in range(3):
        params, state, _ = train_step(params, state, (tokens, targets))

    ckpt_lib.save(str(tmp_path / 'pp'), state, extra={'params': params})
    restored, extra = ckpt_lib.restore(
        str(tmp_path / 'pp'), pk, extra_template={'params': params}
    )
    assert int(restored['step']) == int(state['step'])
    key = next(iter(state['a']))
    np.testing.assert_allclose(
        np.asarray(restored['a'][key]), np.asarray(state['a'][key])
    )
    # decompositions rematerialized from factors, not zeros
    assert float(jnp.abs(restored['qa'][key]).max()) > 0

    # training continues identically from the restored state
    p1, s1, l1 = train_step(params, state, (tokens, targets))
    p2, s2, l2 = train_step(extra['params'], restored, (tokens, targets))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(p1)[0]),
        np.asarray(jax.tree_util.tree_leaves(p2)[0]),
        rtol=1e-5,
    )


@pytest.mark.parametrize(
    'schedule', [pytest.param('gpipe', marks=pytest.mark.slow), '1f1b']
)
def test_tp_pp_matches_pp_dp_only(schedule):
    """3D composition (pipe=2 x dp=2 x model=2) must reproduce the
    (pipe=2 x dp=4) loss trajectory on the same global batch: tensor
    parallelism enters only through the auto model axis + param shardings,
    so GSPMD's Megatron all-reduces cannot change the math (the
    reference's DeepSpeed 3D topology, gpt_neox/preconditioner.py:70-73).
    """
    from kfac_tpu.parallel import mesh as mesh_lib

    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    targets = jnp.roll(tokens, -1, 1)

    def run(tp):
        mesh = mesh_lib.pipeline_mesh(n_stages=2, model=tp)
        model = pipeline.PipelinedLM(
            mesh=mesh, vocab_size=64, d_model=32, num_heads=4,
            num_layers=2, n_microbatches=2, max_len=16, schedule=schedule,
        )
        params = model.init(jax.random.PRNGKey(1))
        cfg = kfac_tpu.KFACPreconditioner(
            registry=model.stage_registry, damping=0.01, lr=0.1
        )
        pk = pipeline.PipelineKFAC(config=cfg, model=model)
        state = pk.init()

        @jax.jit
        def train_step(params, state, batch):
            loss, grads, stats = model.loss_and_stats(params, batch)
            state, grads = pk.step(state, grads, stats)
            params = jax.tree_util.tree_map(
                lambda p, g: p - 0.1 * g, params, grads
            )
            return params, state, loss

        losses = []
        for _ in range(3):
            params, state, loss = train_step(params, state, (tokens, targets))
            losses.append(float(loss))
        return losses, model, params

    losses_3d, model_3d, params_3d = run(tp=2)
    losses_dp, _, _ = run(tp=1)
    np.testing.assert_allclose(losses_3d, losses_dp, rtol=2e-4)
    assert losses_3d[-1] < losses_3d[0]
    # TP actually sharded the Megatron pairs over the model axis
    spec = params_3d['stages']['block0']['attn']['q_proj']['kernel'].sharding.spec
    assert 'model' in str(spec), spec
    spec = params_3d['stages']['block0']['mlp_down']['kernel'].sharding.spec
    assert 'model' in str(spec), spec
    # ... and the LM head is vocab-parallel: its (d, V) kernel shards V
    # over the model axis, so the head matmul + fused-NLL softmax run at
    # 1/tp per device instead of replicated per microbatch
    hspec = params_3d['head']['kernel'].sharding.spec
    assert hspec == jax.sharding.PartitionSpec(None, 'model'), hspec


class _MLPStage(flax_nn.Module):
    """Non-transformer stage: a residual MLP over the feature dim."""

    width: int = 64

    @flax_nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        h = flax_nn.Dense(self.width, name='up')(x)
        h = flax_nn.relu(h)
        return x + flax_nn.Dense(d, name='down')(h)


def test_pipeline_custom_stage_module_trains():
    """Any flax (B,S,D)->(B,S,D) module pipelines with K-FAC (reference
    wraps arbitrary DeepSpeed PipelineModules,
    gpt_neox/preconditioner.py:161-165): registry, capture, and both
    schedule paths are derived from the module itself."""
    from kfac_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.pipeline_mesh(n_stages=2)
    model = pipeline.PipelinedLM(
        mesh=mesh, vocab_size=64, d_model=32, num_heads=4,
        num_layers=2, n_microbatches=2, max_len=16, schedule='1f1b',
        stage_module=_MLPStage(width=48),
    )
    assert set(model.stage_registry.layers) == {'up', 'down'}
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    targets = jnp.roll(tokens, -1, 1)
    params = model.init(jax.random.PRNGKey(1))
    cfg = kfac_tpu.KFACPreconditioner(
        registry=model.stage_registry, damping=0.01, lr=0.1
    )
    pk = pipeline.PipelineKFAC(config=cfg, model=model)
    state = pk.init()

    @jax.jit
    def train_step(params, state, batch):
        loss, grads, stats = model.loss_and_stats(params, batch)
        state, grads = pk.step(state, grads, stats)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g, params, grads
        )
        return params, state, loss

    losses = []
    for _ in range(6):
        params, state, loss = train_step(params, state, (tokens, targets))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))
    # factor state carries the custom module's layers, stage-stacked
    assert state['a']['up'].shape[0] == 2


def test_pipeline_rejects_shape_changing_stage():
    from kfac_tpu.parallel import mesh as mesh_lib

    with pytest.raises(ValueError, match='map'):
        pipeline.PipelinedLM(
            mesh=mesh_lib.pipeline_mesh(n_stages=2), vocab_size=64,
            d_model=32, num_heads=4, num_layers=2, n_microbatches=2,
            max_len=16, stage_module=flax_nn.Dense(16),
        )


def test_custom_stage_tp_overrides_shard_over_model_axis():
    """A custom stage module with square layers plus explicit tp_overrides
    shards over the model axis (the heuristic would replicate squares);
    without overrides, the silent-replication warning fires."""
    import warnings as stdlib_warnings

    from kfac_tpu.parallel import mesh as mesh_lib
    from kfac_tpu.parallel.tensor_parallel import UnshardedParamWarning

    class SquarePair(flax_nn.Module):
        @flax_nn.compact
        def __call__(self, x):
            d = x.shape[-1]
            h = flax_nn.relu(flax_nn.Dense(d, name='first')(x))
            return x + flax_nn.Dense(d, name='second')(h)

    mesh = mesh_lib.pipeline_mesh(n_stages=2, model=2)

    def build(overrides):
        return pipeline.PipelinedLM(
            mesh=mesh, vocab_size=64, d_model=32, num_heads=4,
            num_layers=2, n_microbatches=2, max_len=16,
            stage_module=SquarePair(), tp_overrides=overrides,
        )

    # no matching override: everything replicates, loudly
    with stdlib_warnings.catch_warnings(record=True) as w:
        stdlib_warnings.simplefilter('always')
        build(()).init(jax.random.PRNGKey(0))
    assert any(isinstance(x.message, UnshardedParamWarning) for x in w)

    # explicit Megatron pairing: kernels shard over model, silently
    plm = build((('.*first', 'column'), ('.*second', 'row')))
    with stdlib_warnings.catch_warnings(record=True) as w:
        stdlib_warnings.simplefilter('always')
        params = plm.init(jax.random.PRNGKey(0))
    assert not any(isinstance(x.message, UnshardedParamWarning) for x in w)
    first = params['stages']['first']['kernel']
    second = params['stages']['second']['kernel']
    assert str(first.sharding.spec) == str(
        jax.sharding.PartitionSpec('pipe', None, 'model')
    )
    assert str(second.sharding.spec) == str(
        jax.sharding.PartitionSpec('pipe', 'model', None)
    )
    # and the sharded stage trains
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    loss, grads, stats = jax.jit(plm.loss_and_stats)(
        params, (tokens, jnp.roll(tokens, -1, 1))
    )
    assert np.isfinite(float(loss))
    assert set(stats.a) == {'first', 'second'}
