"""Simulation validation of the interleaved 1F1B schedule generator.

Pure Python (no JAX): checks the static tables against the pipeline's
physical constraints — activation/cotangent dependency order across
ranks, one F + one B slot per rank per tick, exactly-once execution —
and that interleaving actually shrinks the bubble.
"""

import numpy as np
import pytest

from kfac_tpu.parallel import interleaved


def _execution_ticks(sched):
    """(f_tick, b_tick) dicts keyed by (stage, microbatch)."""
    p = sched.p
    f_tick, b_tick = {}, {}
    for t in range(sched.ticks):
        for r in range(p):
            c, mb = sched.f[t, r]
            if c >= 0:
                key = (int(c) * p + r, int(mb))
                assert key not in f_tick, f'duplicate F {key}'
                f_tick[key] = t
            c, mb = sched.b[t, r]
            if c >= 0:
                key = (int(c) * p + r, int(mb))
                assert key not in b_tick, f'duplicate B {key}'
                b_tick[key] = t
    return f_tick, b_tick


@pytest.mark.parametrize('p,v,m', [
    (2, 1, 4), (2, 2, 4), (2, 2, 8), (4, 1, 8), (4, 2, 8), (4, 3, 8),
    (2, 4, 8), (8, 2, 16),
])
def test_schedule_is_a_valid_pipeline_execution(p, v, m):
    sched = interleaved.generate(p, v, m)
    f_tick, b_tick = _execution_ticks(sched)
    last = p * v - 1

    # every chunk-execution happens exactly once:
    # (p*v logical stages) x (m microbatches)
    assert len(f_tick) == p * v * m
    assert len(b_tick) == p * v * m

    for (s, mb), t in f_tick.items():
        if s > 0:
            assert f_tick[(s - 1, mb)] < t, (
                f'F({s},{mb})@{t} before its input F({s - 1},{mb})@'
                f'{f_tick[(s - 1, mb)]}'
            )
    for (s, mb), t in b_tick.items():
        assert t >= f_tick[(s, mb)], f'B({s},{mb}) before its own F'
        if s == last:
            # last logical stage pivots in-tick off its own forward
            assert t == f_tick[(s, mb)]
        else:
            assert b_tick[(s + 1, mb)] < t, (
                f'B({s},{mb})@{t} before cotangent B({s + 1},{mb})@'
                f'{b_tick[(s + 1, mb)]}'
            )


def test_v1_matches_noninterleaved_1f1b_tick_count():
    """v=1 degenerates to the classic schedule: m + 2p - 2 ticks."""
    for p, m in [(2, 4), (4, 8), (4, 16)]:
        sched = interleaved.generate(p, 1, m)
        assert sched.ticks == m + 2 * p - 2, (p, m, sched.ticks)


def test_interleaving_reduces_bubble():
    """Same device count and total work: more chunks -> fewer idle slots
    (the (p-1)/v bubble reduction), and never more ticks than v=1 spread
    over v-times-smaller chunk executions."""
    p, m = 4, 16
    # total work per rank is m*v chunk-slots; normalize bubble per work
    fractions = {}
    for v in (1, 2, 4):
        sched = interleaved.generate(p, v, m)
        work = 2 * m * v  # F + B chunk-executions per rank
        total_slots = 2 * sched.ticks
        # bubble_slots (counted from the tables) and the arithmetic
        # derivation must agree: every non-idle slot is real work
        assert sched.bubble_slots() == (total_slots - work) * p
        fractions[v] = (total_slots - work) / total_slots
    assert fractions[2] < fractions[1], fractions
    assert fractions[4] < fractions[2], fractions


def test_rejects_invalid_configs():
    with pytest.raises(ValueError, match='multiple'):
        interleaved.generate(4, 2, 6)
    with pytest.raises(ValueError, match='chunks'):
        interleaved.generate(2, 0, 4)
