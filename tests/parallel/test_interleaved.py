"""Simulation validation of the interleaved 1F1B schedule generator.

Pure Python (no JAX): checks the static tables against the pipeline's
physical constraints — activation/cotangent dependency order across
ranks, one F + one B slot per rank per tick, exactly-once execution —
and that interleaving actually shrinks the bubble.
"""

import numpy as np
import pytest

from kfac_tpu.parallel import interleaved


def _execution_ticks(sched):
    """(f_tick, b_tick) dicts keyed by (stage, microbatch)."""
    p = sched.p
    f_tick, b_tick = {}, {}
    for t in range(sched.ticks):
        for r in range(p):
            c, mb = sched.f[t, r]
            if c >= 0:
                key = (int(c) * p + r, int(mb))
                assert key not in f_tick, f'duplicate F {key}'
                f_tick[key] = t
            c, mb = sched.b[t, r]
            if c >= 0:
                key = (int(c) * p + r, int(mb))
                assert key not in b_tick, f'duplicate B {key}'
                b_tick[key] = t
    return f_tick, b_tick


@pytest.mark.parametrize('p,v,m', [
    (2, 1, 4), (2, 2, 4), (2, 2, 8), (4, 1, 8), (4, 2, 8), (4, 3, 8),
    (2, 4, 8), (8, 2, 16),
])
def test_schedule_is_a_valid_pipeline_execution(p, v, m):
    sched = interleaved.generate(p, v, m)
    f_tick, b_tick = _execution_ticks(sched)
    last = p * v - 1

    # every chunk-execution happens exactly once:
    # (p*v logical stages) x (m microbatches)
    assert len(f_tick) == p * v * m
    assert len(b_tick) == p * v * m

    for (s, mb), t in f_tick.items():
        if s > 0:
            assert f_tick[(s - 1, mb)] < t, (
                f'F({s},{mb})@{t} before its input F({s - 1},{mb})@'
                f'{f_tick[(s - 1, mb)]}'
            )
    for (s, mb), t in b_tick.items():
        assert t >= f_tick[(s, mb)], f'B({s},{mb}) before its own F'
        if s == last:
            # last logical stage pivots in-tick off its own forward
            assert t == f_tick[(s, mb)]
        else:
            assert b_tick[(s + 1, mb)] < t, (
                f'B({s},{mb})@{t} before cotangent B({s + 1},{mb})@'
                f'{b_tick[(s + 1, mb)]}'
            )


def test_v1_matches_noninterleaved_1f1b_tick_count():
    """v=1 degenerates to the classic schedule: m + 2p - 2 ticks."""
    for p, m in [(2, 4), (4, 8), (4, 16)]:
        sched = interleaved.generate(p, 1, m)
        assert sched.ticks == m + 2 * p - 2, (p, m, sched.ticks)


def test_interleaving_reduces_bubble():
    """Same device count and total work: more chunks -> fewer idle slots
    (the (p-1)/v bubble reduction), and never more ticks than v=1 spread
    over v-times-smaller chunk executions."""
    p, m = 4, 16
    # total work per rank is m*v chunk-slots; normalize bubble per work
    fractions = {}
    for v in (1, 2, 4):
        sched = interleaved.generate(p, v, m)
        work = 2 * m * v  # F + B chunk-executions per rank
        total_slots = 2 * sched.ticks
        # bubble_slots (counted from the tables) and the arithmetic
        # derivation must agree: every non-idle slot is real work
        assert sched.bubble_slots() == (total_slots - work) * p
        fractions[v] = (total_slots - work) / total_slots
    assert fractions[2] < fractions[1], fractions
    assert fractions[4] < fractions[2], fractions


def test_rejects_invalid_configs():
    with pytest.raises(ValueError, match='multiple'):
        interleaved.generate(4, 2, 6)
    with pytest.raises(ValueError, match='chunks'):
        interleaved.generate(2, 0, 4)


@pytest.mark.parametrize('p,v,m', [(2, 1, 2), (2, 2, 4), (4, 2, 8), (4, 4, 16), (8, 2, 16)])
def test_single_slot_schedule_is_valid(p, v, m):
    """Single-slot tables: dependency order, one op per rank per tick, op
    counts, residual-slot pairing, and inbox-depth claims all hold."""
    s = interleaved.generate_single_slot(p, v, m)
    last = p * v - 1
    f_done, b_done = {}, {}
    slot_of = {}
    stored = [set() for _ in range(p)]
    act_live, cot_live = {}, {}
    nf = nb = 0
    for t in range(s.ticks):
        consumed = []
        produced = []
        for r in range(p):
            kind, c, mb, slot = (int(x) for x in s.ops[t, r])
            if kind < 0:
                continue
            stage = c * p + r
            if kind == 0:
                if stage > 0:
                    assert f_done[(stage - 1, mb)] < t, (t, r, stage, mb)
                    consumed.append(('a', r, c))
                # residual slot free and inside the ring
                assert 0 <= slot < s.ring
                assert slot not in stored[r], (t, r, slot)
                stored[r].add(slot)
                slot_of[(stage, mb)] = slot
                f_done[(stage, mb)] = t
                if stage < last:
                    produced.append(('a', (stage + 1) % p, (stage + 1) // p))
                nf += 1
            else:
                assert f_done[(stage, mb)] < t
                if stage < last:
                    assert b_done[(stage + 1, mb)] < t
                    consumed.append(('c', r, c))
                # reads and frees exactly its F's slot
                assert slot_of.pop((stage, mb)) == slot
                stored[r].discard(slot)
                b_done[(stage, mb)] = t
                if stage > 0:
                    produced.append(('c', (stage - 1) % p, (stage - 1) // p))
                nb += 1
        for kind, r, c in consumed:
            d = act_live if kind == 'a' else cot_live
            d[(r, c)] = d.get((r, c), 0) - 1
        for kind, r, c in produced:
            d = act_live if kind == 'a' else cot_live
            d[(r, c)] = d.get((r, c), 0) + 1
            cap = s.act_depth if kind == 'a' else s.cot_depth
            assert d[(r, c)] <= cap, (t, kind, r, c)
    assert nf == nb == p * m * v
    assert not slot_of  # every F was retired by its B


def test_single_slot_realizes_megatron_bubble():
    """The whole point: per-rank bubble in stage-units is 2*(p-1)/v — the
    full Megatron reduction — where the 2-slot tick model plateaus at
    ~25% (12 -> 10 -> 9 stage-units at p=4, m=16)."""
    for p, m in ((4, 16), (8, 32)):
        for v in (1, 2, 4):
            s = interleaved.generate_single_slot(p, v, m)
            su = s.bubble_slots() / p / v
            assert su == 2 * (p - 1) / v, (p, v, su)
            two = interleaved.generate(p, v, m)
            assert s.bubble_slots() <= two.bubble_slots()


def test_single_slot_rejects_invalid():
    with pytest.raises(ValueError):
        interleaved.generate_single_slot(4, 2, 6)  # m not multiple of p
    with pytest.raises(ValueError):
        interleaved.generate_single_slot(4, 0, 8)


# --------------------------------------------------------------- property
# hypothesis sweep: the schedule invariants must hold for EVERY valid
# (p, v, m), not just the hand-picked configs above — the generator is
# the single source of truth for the executing scan's indexing
_hyp = pytest.importorskip('hypothesis')
from hypothesis import given, settings, strategies as st  # noqa: E402

@given(
    p=st.integers(1, 8),
    v=st.integers(1, 4),
    mult=st.integers(1, 4),
)
@settings(max_examples=60, deadline=None)
def test_single_slot_schedule_properties(p, v, mult):
    m = p * mult
    s = interleaved.generate_single_slot(p, v, m)
    last = p * v - 1
    f_done, b_done, slot_of = {}, {}, {}
    stored = [set() for _ in range(p)]
    nf = nb = 0
    for t in range(s.ticks):
        for r in range(p):
            kind, c, mb, slot = (int(x) for x in s.ops[t, r])
            if kind < 0:
                continue
            stage = c * p + r
            assert 0 <= c < v and 0 <= mb < m
            if kind == 0:
                if stage > 0:
                    assert f_done[(stage - 1, mb)] < t
                assert 0 <= slot < s.ring
                assert slot not in stored[r]
                stored[r].add(slot)
                slot_of[(stage, mb)] = slot
                f_done[(stage, mb)] = t
                nf += 1
            else:
                assert f_done[(stage, mb)] < t
                if stage < last:
                    assert b_done[(stage + 1, mb)] < t
                assert slot_of.pop((stage, mb)) == slot
                stored[r].discard(slot)
                b_done[(stage, mb)] = t
                nb += 1
    assert nf == nb == p * m * v
    assert not slot_of
    # the Megatron bound: per-rank bubble in stage units
    assert s.bubble_slots() / p / v == 2 * (p - 1) / v
