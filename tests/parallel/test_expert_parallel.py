"""Expert parallelism: all-to-all dispatch over the ``expert`` mesh axis.

Ground truth is the dense masked MoEMLP path on the SAME parameters (the
two share the router/expert{e}_up/expert{e}_down naming): with capacity
high enough to never drop, the EP output/loss/gradients and the captured
per-expert statistics must match the routed-registry interceptor capture
to float tolerance.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import kfac_tpu
from kfac_tpu.models.moe import MoEMLP
from kfac_tpu.parallel import EPSwitchFFN, train_mesh
from kfac_tpu.parallel.mesh import EXPERT_AXIS, token_sharding

E = 4       # experts
D = 8       # model dim
B, S = 8, 4


def _setup(expert=2, capacity_factor=float(E)):
    mesh = train_mesh(expert=expert)
    moe = MoEMLP(num_experts=E, mlp_ratio=2)
    x = jax.random.normal(jax.random.PRNGKey(0), (B, S, D))
    params = moe.init(jax.random.PRNGKey(1), x)['params']
    ep = EPSwitchFFN(
        mesh=mesh, num_experts=E, mlp_ratio=2,
        capacity_factor=capacity_factor,
    )
    return mesh, moe, ep, params, x


def test_train_mesh_expert_axis_and_token_sharding():
    mesh = train_mesh(expert=2)
    assert mesh.shape[EXPERT_AXIS] == 2
    ts = token_sharding(mesh)
    # tokens shard over data+expert jointly (EP groups reuse DP)
    assert EXPERT_AXIS in ts.spec[0]
    # expert=1 keeps the 4-axis mesh unchanged
    assert EXPERT_AXIS not in train_mesh().shape


def test_ep_forward_matches_dense_masked_moe():
    mesh, moe, ep, params, x = _setup()
    want = moe.apply({'params': params}, x)
    xs = jax.device_put(x, token_sharding(mesh))
    got = jax.jit(lambda p, x: ep.apply(p, x))(params, xs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6
    )


def test_ep_num_experts_must_divide_axis():
    mesh = train_mesh(expert=2)
    with pytest.raises(ValueError, match='not divisible'):
        EPSwitchFFN(mesh=mesh, num_experts=3)


def test_ep_capacity_drops_are_finite_and_sparse():
    # tiny capacity: most tokens drop; output stays finite and equals the
    # dense path only on the surviving slots (just sanity here)
    mesh, moe, ep, params, x = _setup(capacity_factor=0.25)
    xs = jax.device_put(x, token_sharding(mesh))
    y = jax.jit(lambda p, x: ep.apply(p, x))(params, xs)
    assert np.all(np.isfinite(np.asarray(y)))


def test_ep_grads_and_stats_match_routed_interceptor_capture():
    """The headline equivalence: loss, grads, A stats, AND G stats from the
    EP all-to-all path equal the dense masked path with routed capture."""
    mesh, moe, ep, params, x = _setup()
    target = jax.random.normal(jax.random.PRNGKey(2), (B, S, D))

    # --- oracle: dense masked MoEMLP with routed interceptor capture
    reg = kfac_tpu.register_model(
        moe, x, routed_layers=[r'.*expert\d+_(up|down)']
    )

    def moe_loss(p, batch):
        xb, tb = batch
        y = moe.apply({'params': p}, xb)
        return jnp.mean((y - tb) ** 2)

    run_ref = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(moe_loss)
    (l_ref, _), g_ref, s_ref = run_ref(params, (x, target))

    # --- EP path on the same params
    def ep_loss(p, batch, ffn):
        xb, tb = batch
        return jnp.mean((ffn(p, xb) - tb) ** 2)

    xs = jax.device_put(x, token_sharding(mesh))
    ts = jax.device_put(target, token_sharding(mesh))
    run_ep = ep.value_stats_and_grad(ep_loss)
    (l_ep, _), g_ep, s_ep = run_ep(params, (xs, ts))

    np.testing.assert_allclose(float(l_ep), float(l_ref), rtol=1e-5)
    for name in g_ref:
        for leaf in g_ref[name]:
            np.testing.assert_allclose(
                np.asarray(g_ep[name][leaf]), np.asarray(g_ref[name][leaf]),
                rtol=2e-4, atol=1e-6,
                err_msg=f'grad mismatch: {name}/{leaf}',
            )
    assert set(s_ep.a) == set(s_ref.a) and set(s_ep.g) == set(s_ref.g)
    # evidence weights for the traffic-weighted EMA match the routed
    # interceptor capture's live fractions (nothing drops at this capacity)
    assert set(s_ep.w) == set(s_ref.w)
    for name in s_ref.w:
        np.testing.assert_allclose(
            float(s_ep.w[name]), float(s_ref.w[name]),
            rtol=1e-5, atol=1e-6, err_msg=f'weight mismatch: {name}',
        )
    for name in s_ref.a:
        np.testing.assert_allclose(
            np.asarray(s_ep.a[name]), np.asarray(s_ref.a[name]),
            rtol=2e-4, atol=1e-6, err_msg=f'A mismatch: {name}',
        )
        np.testing.assert_allclose(
            np.asarray(s_ep.g[name]), np.asarray(s_ref.g[name]),
            rtol=2e-4, atol=1e-6, err_msg=f'G mismatch: {name}',
        )


def test_ep_kfac_step_trains():
    """Full loop: EP capture feeds the dense KFACPreconditioner through
    the hand-assembled registry; loss decreases."""
    mesh, moe, ep, params, x = _setup()
    target = jnp.tanh(jnp.roll(x, 1, axis=-1))
    reg = ep.registry(D)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, damping=0.01, lr=0.1,
        factor_update_steps=1, inv_update_steps=2,
    )

    def ep_loss(p, batch, ffn):
        xb, tb = batch
        return jnp.mean((ffn(p, xb) - tb) ** 2)

    run = ep.value_stats_and_grad(ep_loss)
    xs = jax.device_put(x, token_sharding(mesh))
    ts = jax.device_put(target, token_sharding(mesh))

    @jax.jit
    def step(params, kstate, batch):
        (l, _), grads, stats = run(params, batch)
        kstate, pg = kfac.step(kstate, grads, stats)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 0.2 * g, params, pg
        )
        return params, kstate, l

    kstate = kfac.init()
    losses = []
    for _ in range(20):
        params, kstate, l = step(params, kstate, (xs, ts))
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.7, losses
    assert all(b <= a * 1.02 for a, b in zip(losses, losses[1:])), losses


def test_combined_capture_two_ep_blocks_plus_flax_layer():
    """combined_value_stats_and_grad spans interceptor capture (a dense
    projection) and TWO EP blocks in one value_and_grad; loss, grads, and
    every A/G factor match the all-flax oracle (Proj + two MoEMLPs with
    routed registry capture) on shared parameters."""
    from kfac_tpu.layers.registry import merge_registries
    from kfac_tpu.parallel.expert_parallel import (
        combined_value_stats_and_grad,
    )

    class Oracle(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(D, name='proj')(x)
            x = MoEMLP(num_experts=E, mlp_ratio=2, name='moe0')(x)
            return MoEMLP(num_experts=E, mlp_ratio=2, name='moe1')(x)

    mesh = train_mesh(expert=2)
    x = jax.random.normal(jax.random.PRNGKey(0), (B, S, D))
    target = jnp.tanh(jnp.roll(x, 1, -1))
    oracle = Oracle()
    oparams = oracle.init(jax.random.PRNGKey(1), x)['params']
    oreg = kfac_tpu.register_model(
        oracle, x, routed_layers=[r'.*expert\d+_(up|down)']
    )

    def oracle_loss(p, batch):
        xb, tb = batch
        return jnp.mean((oracle.apply({'params': p}, xb) - tb) ** 2)

    run_ref = kfac_tpu.CurvatureCapture(oreg).value_stats_and_grad(
        oracle_loss
    )
    (l_ref, _), g_ref, s_ref = run_ref(oparams, (x, target))

    # --- EP path: same params, flattened EP entries + the flax proj
    class Proj(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(D, name='proj')(x)

    proj = Proj()
    eparams = {'proj': oparams['proj']}
    for blk in ('moe0', 'moe1'):
        for k, v in oparams[blk].items():
            eparams[f'{blk}/{k}'] = v
    ffn0 = EPSwitchFFN(
        mesh=mesh, num_experts=E, mlp_ratio=2, capacity_factor=float(E),
        name_prefix='moe0/',
    )
    ffn1 = EPSwitchFFN(
        mesh=mesh, num_experts=E, mlp_ratio=2, capacity_factor=float(E),
        name_prefix='moe1/',
    )
    preg = kfac_tpu.register_model(proj, x)
    merged = merge_registries(preg, ffn0.registry(D), ffn1.registry(D))
    assert set(merged.layers) == set(oreg.layers)

    def ep_loss(p, batch, ffns):
        xb, tb = batch
        h = proj.apply({'params': {'proj': p['proj']}}, xb)
        h = ffns[0](p, h)
        return jnp.mean((ffns[1](p, h) - tb) ** 2)

    xs = jax.device_put(x, token_sharding(mesh))
    ts = jax.device_put(target, token_sharding(mesh))
    run_ep = combined_value_stats_and_grad(
        ep_loss, registry=preg, ep_ffns=(ffn0, ffn1)
    )
    (l_ep, _), g_ep, s_ep = run_ep(eparams, (xs, ts))

    np.testing.assert_allclose(float(l_ep), float(l_ref), rtol=1e-5)
    # grads: flax-nested oracle vs flat EP keys
    def oracle_leaf(name, leaf):
        if '/' in name:
            blk, sub = name.split('/')
            return g_ref[blk][sub][leaf]
        return g_ref[name][leaf]

    for name in eparams:
        for leaf in eparams[name]:
            np.testing.assert_allclose(
                np.asarray(g_ep[name][leaf]),
                np.asarray(oracle_leaf(name, leaf)),
                rtol=5e-4, atol=2e-6, err_msg=f'grad {name}/{leaf}',
            )
    assert set(s_ep.a) == set(s_ref.a)
    for name in s_ref.a:
        np.testing.assert_allclose(
            np.asarray(s_ep.a[name]), np.asarray(s_ref.a[name]),
            rtol=5e-4, atol=2e-6, err_msg=f'A {name}',
        )
        np.testing.assert_allclose(
            np.asarray(s_ep.g[name]), np.asarray(s_ref.g[name]),
            rtol=5e-4, atol=2e-6, err_msg=f'G {name}',
        )


def test_combined_capture_rejects_duplicate_prefixes_and_double_call():
    from kfac_tpu.parallel.expert_parallel import (
        combined_value_stats_and_grad,
    )

    mesh = train_mesh(expert=2)
    ffn = EPSwitchFFN(mesh=mesh, num_experts=E, mlp_ratio=2)
    with pytest.raises(ValueError, match='distinct'):
        combined_value_stats_and_grad(
            lambda p, b, f: 0.0, ep_ffns=(ffn, ffn)
        )

    params = ffn.init(jax.random.PRNGKey(0), D)
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (B, S, D)),
        token_sharding(mesh),
    )

    def loss_double_call(p, batch, ffns):
        y = ffns[0](p, batch)
        return jnp.mean(ffns[0](p, y) ** 2)  # second call: must raise

    run = combined_value_stats_and_grad(loss_double_call, ep_ffns=(ffn,))
    with pytest.raises(ValueError, match='more than once'):
        run(params, x)


def test_combined_capture_rejects_uninvoked_block():
    """A block that loss_fn never calls would contribute all-zero G
    factors with no A factors — the runner raises instead."""
    from kfac_tpu.parallel.expert_parallel import (
        combined_value_stats_and_grad,
    )

    mesh = train_mesh(expert=2)
    ffn0 = EPSwitchFFN(mesh=mesh, num_experts=E, mlp_ratio=2,
                       name_prefix='a/')
    ffn1 = EPSwitchFFN(mesh=mesh, num_experts=E, mlp_ratio=2,
                       name_prefix='b/')
    params = {**ffn0.init(jax.random.PRNGKey(0), D),
              **ffn1.init(jax.random.PRNGKey(1), D)}
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (B, S, D)),
        token_sharding(mesh),
    )
    run = combined_value_stats_and_grad(
        lambda p, b, ffns: jnp.mean(ffns[0](p, b) ** 2),  # ffn1 unused
        ep_ffns=(ffn0, ffn1),
    )
    with pytest.raises(ValueError, match='never called'):
        run(params, x)
