"""Shared compiled-pipeline fixtures for tests/parallel/.

The pipeline scans are the most expensive compiles in the suite (a
shard_map'd combined forward/backward scan per schedule); the
module-scope fixtures here run ``loss_stats_and_ticks`` ONCE per test
module and hand every consumer the same outputs, so adding a new
assertion over the executed schedule costs zero extra compiles.
"""

import jax
import pytest

TICK_GEOM = dict(
    vocab_size=64, d_model=32, num_heads=4, n_microbatches=4, max_len=16,
)


def _ilv_run(p: int, v: int):
    """(model, loss, grads, stats, tick_counts) for one interleaved
    point — m = n_microbatches rows of one sample each, dp = 1."""
    from kfac_tpu.parallel import interleaved_scan
    from kfac_tpu.parallel.mesh import pipeline_mesh

    mesh = pipeline_mesh(n_stages=p, devices=jax.devices()[:p])
    model = interleaved_scan.InterleavedPipelinedLM(
        mesh=mesh, virtual_chunks=v, num_layers=p * v, **TICK_GEOM
    )
    params = model.init(jax.random.PRNGKey(0))
    m, s = TICK_GEOM['n_microbatches'], TICK_GEOM['max_len']
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (m, s), 0, TICK_GEOM['vocab_size']
    )
    targets = jax.random.randint(
        jax.random.PRNGKey(2), (m, s), 0, TICK_GEOM['vocab_size']
    )
    out = jax.jit(model.loss_stats_and_ticks)(params, (tokens, targets))
    return (model,) + tuple(out)


@pytest.fixture(scope='module')
def ilv_ticks_p2v2():
    """Compiled interleaved p=2 v=2 m=4 run, shared across the module."""
    return _ilv_run(2, 2)


@pytest.fixture(scope='module')
def ilv_ticks_p4v2():
    """Compiled interleaved p=4 v=2 m=4 run (the heaviest schedule the
    fast tier touches lives behind this one compile)."""
    return _ilv_run(4, 2)
