"""Multi-process multihost validation.

The reference exercises its distributed code under real forked process
groups (testing/distributed.py:24-141, gloo). Until round 4 the repo's
``parallel/multihost.py`` had only ever executed its single-process
early-return branch; these tests launch 2 or 4 OS processes that rendezvous
through ``jax.distributed.initialize`` (CPU backend, the KFAC_TPU_* env
surface run_pod.sh sets per node), build a ``hybrid_kaisa_mesh`` spanning
both, run a real DistributedKFAC step over it, and check the numbers
against the same step computed in a single process.
"""

import json
import os
import socket
import subprocess
import sys

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
WORKER = os.path.join(REPO, 'testing', 'multihost_worker.py')
VOTE_WORKER = os.path.join(REPO, 'testing', 'multihost_vote_worker.py')
PIPELINE_WORKER = os.path.join(
    REPO, 'testing', 'multihost_pipeline_worker.py'
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _launch_workers(
    n: int, port: int, worker: str = WORKER, devices_per_proc: int = 2
):
    procs = []
    for pid in range(n):
        env = dict(os.environ)
        env['PALLAS_AXON_POOL_IPS'] = ''  # never touch the TPU tunnel
        env['JAX_PLATFORMS'] = 'cpu'
        flags = ' '.join(
            f
            for f in env.get('XLA_FLAGS', '').split()
            if 'xla_force_host_platform_device_count' not in f
        )
        env['XLA_FLAGS'] = (
            flags
            + f' --xla_force_host_platform_device_count={devices_per_proc}'
        ).strip()
        env['KFAC_TPU_COORDINATOR'] = f'127.0.0.1:{port}'
        env['KFAC_TPU_NUM_PROCESSES'] = str(n)
        env['KFAC_TPU_PROCESS_ID'] = str(pid)
        # share the suite's persistent compile cache: n concurrent COLD
        # compiles contending for this container's single core could push
        # a worker past the communicate timeout
        env.setdefault(
            'JAX_COMPILATION_CACHE_DIR', os.path.join(REPO, '.jax_cache')
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, worker],
                env=env,
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    return procs


def _collect_results(procs, timeout: int = 600):
    """JSON result line per worker; kills the pod on any failure so a
    blocked rendezvous never orphans workers on this single core."""
    results = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                # collect every worker's stderr tail — a hang with no
                # diagnostics is undebuggable (the finally kills them)
                tails = []
                for qi, q in enumerate(procs):
                    q.kill()
                    try:
                        _, qerr = q.communicate(timeout=30)
                    except Exception:  # noqa: BLE001
                        qerr = '<unreadable>'
                    tails.append(
                        f'--- worker {qi} stderr ---\n{qerr[-1500:]}'
                    )
                raise AssertionError(
                    'multihost rendezvous timed out:\n' + '\n'.join(tails)
                ) from None
            assert p.returncode == 0, f'worker failed:\n{err[-3000:]}'
            line = [l for l in out.splitlines() if l.startswith('{')][-1]
            results.append(json.loads(line))
    finally:
        # ANY exit (a failed worker's assert included) must not orphan the
        # rest of the rendezvous — blocked workers would spin on this
        # container's single core for their full timeout
        for q in procs:
            if q.poll() is None:
                q.kill()
                q.wait()
    return results


@pytest.mark.slow
@pytest.mark.parametrize('n_procs', [2, 4])
def test_multi_process_step_matches_single_process(n_procs):
    """{2, 4} OS processes x 2 virtual devices each rendezvous through
    jax.distributed.initialize and run a real DistributedKFAC step over a
    hybrid mesh; replicated outputs agree across processes and match the
    same step computed in one process. The 4-process case exercises a
    4-host x 2-device hybrid grid (the DCN-topology shape multihost.
    hybrid_kaisa_mesh exists for) rather than the minimal pair."""
    if len(jax.devices()) < 2 * n_procs:
        pytest.skip(
            f'single-process reference needs {2 * n_procs} virtual '
            f'devices (XLA_FLAGS overrides the conftest default)'
        )
    port = _free_port()
    procs = _launch_workers(n_procs, port)
    results = _collect_results(procs)

    # every process saw the full world and agrees bit-for-bit on the
    # replicated outputs
    for r in results:
        assert r['n_processes'] == n_procs
        assert r['n_devices'] == 2 * n_procs
    for r in results[1:]:
        assert r['loss'] == results[0]['loss']
        assert r['checksum'] == results[0]['checksum']

    # and the multi-process numbers match the same step computed in ONE
    # process over the suite's virtual devices (identical mesh grid:
    # hybrid_kaisa_mesh orders host-major, which degenerates to device
    # order here)
    import jax.numpy as jnp

    import kfac_tpu
    from kfac_tpu.parallel import batch_sharding, multihost
    from testing import models

    mesh = multihost.hybrid_kaisa_mesh(
        0.5, devices=jax.devices()[: 2 * n_procs]
    )
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=32, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(
        registry=reg, compute_method='eigen', damping=0.01, lr=0.1,
        bucket_granularity=1,
    )
    dk = kfac_tpu.parallel.DistributedKFAC(config=cfg, mesh=mesh)
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(
        models.mse_loss(m)
    )
    bs = batch_sharding(mesh)
    batch = (jax.device_put(x, bs), jax.device_put(y, bs))

    @jax.jit
    def step(params, state, batch):
        (loss, _), grads, stats = run(params, batch)
        state, pg = dk.step(state, grads, stats)
        return state, pg, loss

    _, pg, loss = step(params, dk.init(), batch)
    checksum = float(
        sum(
            jnp.sum(jnp.abs(leaf.astype(jnp.float32)))
            for leaf in jax.tree_util.tree_leaves(pg)
        )
    )
    np.testing.assert_allclose(results[0]['loss'], float(loss), rtol=1e-5)
    np.testing.assert_allclose(results[0]['checksum'], checksum, rtol=1e-4)


@pytest.mark.slow
def test_eight_process_protocol_smoke():
    """8 OS processes x 1 virtual device rendezvous and drive the raw
    coordination protocol — no model step, just the ops the pod
    analyzer verifies statically: unanimous and dissenting
    ``agree_decision`` rounds, ``agree_emergency`` (max code, max step)
    convergence under a one-rank signal plus a one-rank step skew,
    ``assert_same_step`` on both the agreeing and the diverging path,
    barriers, and the (4, 2) host-major ``hybrid_kaisa_mesh`` grid over
    a world wider than any single host."""
    n_procs = 8
    port = _free_port()
    procs = _launch_workers(
        n_procs, port, worker=VOTE_WORKER, devices_per_proc=1
    )
    results = _collect_results(procs)

    assert sorted(r['process'] for r in results) == list(range(n_procs))
    for r in results:
        assert r['n_processes'] == n_procs
        assert r['vote_unanimous'] is True
        # rank 3's veto must reach every rank (unanimous min-reduction)
        assert r['vote_dissent'] is False
        # rank 2's signal code and rank 5's skewed step, pod-wide
        assert (r['agreed_code'], r['agreed_step']) == (2, 18)
        assert r['skew_raises'] is True
        # 8 devices at grad_worker_fraction 0.5 -> (gw=4, col=2),
        # host-major: the first column is whole hosts 0..3
        assert r['mesh_shape'] == [4, 2]
        assert r['mesh_axes'] == ['kfac_gw', 'kfac_col']
        assert r['col0_hosts'] == [0, 1, 2, 3]


@pytest.mark.slow
def test_two_process_pipeline_matches_single_process():
    """2 OS processes x 1 virtual device run the interleaved pipeline
    scan (p=2, v=2, m=4) over a pipeline mesh that SPANS the process
    boundary — every per-tick ppermute crosses the coordination-service
    transport. The replicated loss and embed/head/ln_f gradient checksum
    agree across ranks and match the same scan computed in one process,
    and each rank's executed (F, B, idle) tick-counter row equals the
    static schedule table's per-rank prediction."""
    port = _free_port()
    procs = _launch_workers(
        2, port, worker=PIPELINE_WORKER, devices_per_proc=1
    )
    results = _collect_results(procs)

    assert sorted(r['process'] for r in results) == [0, 1]
    for r in results[1:]:
        assert r['loss'] == results[0]['loss']
        assert r['checksum'] == results[0]['checksum']

    # single-process reference over 2 of the suite's virtual devices,
    # identical geometry and PRNG streams (multihost_pipeline_worker.GEOM)
    import jax.numpy as jnp

    from kfac_tpu.parallel import interleaved_scan
    from kfac_tpu.parallel.mesh import pipeline_mesh
    from testing import multihost_pipeline_worker as worker_mod

    geom = worker_mod.GEOM
    mesh = pipeline_mesh(n_stages=2, devices=jax.devices()[:2])
    model = interleaved_scan.InterleavedPipelinedLM(
        mesh=mesh, virtual_chunks=2, **geom
    )
    params = model.init(jax.random.PRNGKey(0))
    m, s = geom['n_microbatches'], geom['max_len']
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (m, s), 0, geom['vocab_size']
    )
    targets = jax.random.randint(
        jax.random.PRNGKey(2), (m, s), 0, geom['vocab_size']
    )
    loss, grads, _, ticks = jax.jit(model.loss_stats_and_ticks)(
        params, (tokens, targets)
    )
    checksum = float(
        sum(
            jnp.sum(jnp.abs(leaf.astype(jnp.float32)))
            for key in ('embed', 'pos_embed', 'head', 'ln_f')
            for leaf in jax.tree_util.tree_leaves(grads[key])
        )
    )
    np.testing.assert_allclose(results[0]['loss'], float(loss), rtol=1e-5)
    np.testing.assert_allclose(results[0]['checksum'], checksum, rtol=1e-4)

    # executed counters, per rank, against the schedule table — the
    # cross-process run must execute the exact same slot sequence the
    # simulator prices
    report = model.tick_report(np.asarray(ticks))
    assert report['matches_schedule'], report
    predicted = report['predicted']
    by_rank = {r['process']: r['ticks'] for r in results}
    for rank in (0, 1):
        assert by_rank[rank] == [
            predicted['executed_f'][rank],
            predicted['executed_b'][rank],
            predicted['idle'][rank],
        ], (rank, by_rank[rank], predicted)


@pytest.mark.slow
def test_initialize_noop_without_rendezvous_env():
    """Single process, no KFAC_TPU_*/pod env: initialize() must be a no-op
    (the branch every in-process test exercises implicitly — asserted
    explicitly here in a subprocess with a clean env)."""
    env = dict(os.environ)
    env['PALLAS_AXON_POOL_IPS'] = ''
    env['JAX_PLATFORMS'] = 'cpu'
    for var in (
        'KFAC_TPU_COORDINATOR', 'KFAC_TPU_NUM_PROCESSES',
        'KFAC_TPU_PROCESS_ID', 'TPU_WORKER_HOSTNAMES',
        'SLURM_JOB_NUM_NODES', 'MEGASCALE_COORDINATOR_ADDRESS',
    ):
        env.pop(var, None)
    code = (
        'import jax; jax.config.update("jax_platforms", "cpu");\n'
        'from kfac_tpu.parallel import multihost\n'
        'multihost.initialize()\n'
        'assert jax.process_count() == 1\n'
        'print("noop-ok")\n'
    )
    out = subprocess.run(
        [sys.executable, '-c', code],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert 'noop-ok' in out.stdout
