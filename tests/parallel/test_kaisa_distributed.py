"""Distributed KAISA tests on the 8-virtual-device CPU mesh.

The analogue of the reference's forked-gloo distributed suite
(tests/layers/layers_test.py world {1,4} x {MEM,COMM}-OPT and
tests/training_test.py): the same SPMD programs that run on a TPU pod run
here on 8 host devices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import kfac_tpu
from kfac_tpu import enums
from kfac_tpu.parallel import DistributedKFAC, batch_sharding, kaisa_mesh, mesh as mesh_lib
from testing import models

WORLD = 8


def _setup(frac, compute_method='eigen', **cfg_kw):
    mesh = kaisa_mesh(grad_worker_fraction=frac)
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=WORLD * 8, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(
        registry=reg, compute_method=compute_method, **cfg_kw
    )
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    loss_fn = models.mse_loss(m)
    return mesh, m, params, (x, y), reg, cfg, dk, loss_fn


@pytest.mark.parametrize('frac,shape', [(1.0, (8, 1)), (0.5, (4, 2)), (0.25, (2, 4)), (1 / 8, (1, 8))])
def test_mesh_shapes(frac, shape):
    mesh = kaisa_mesh(grad_worker_fraction=frac)
    assert (mesh_lib.grad_workers(mesh), mesh_lib.n_cols(mesh)) == shape
    assert mesh_lib.world_size(mesh) == WORLD


def test_bucketing_pads_to_world():
    _, _, _, _, reg, _, dk, _ = _setup(1.0)
    for b in dk.buckets:
        assert b.padded % WORLD == 0
        assert set(b.layers) <= set(reg.names())
    assert sum(len(b.layers) for b in dk.buckets) == len(reg)


@pytest.mark.parametrize('frac', [1.0, 0.5, 1 / 8])
def test_state_shardings_and_memory(frac):
    _, _, _, _, _, _, dk, _ = _setup(frac)
    state = dk.init()
    assert int(state.step) == 0
    usage = dk.memory_usage(state)
    assert usage['total'] > 0
    # MEM-OPT keeps strictly less resident than COMM-OPT
    if frac == 1 / 8:
        _, _, _, _, _, _, dk_comm, _ = _setup(1.0)
        comm_usage = dk_comm.memory_usage(dk_comm.init())
        assert usage['a_inverses'] < comm_usage['a_inverses']


@pytest.mark.parametrize(
    'frac,method',
    [
        (1.0, 'eigen'),
        (0.5, 'eigen'),
        (1 / 8, 'eigen'),
        (1.0, 'inverse'),
        (1 / 8, 'inverse'),
    ],
)
def test_distributed_matches_single_device(frac, method):
    """The sharded stacked engine must numerically match the dense
    single-device preconditioner (same stats, same grads)."""
    mesh, m, params, batch, reg, cfg, dk, loss_fn = _setup(
        frac, compute_method=method, kl_clip=0.001, damping=0.01
    )
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)

    # dense reference path
    ref_state = cfg.init()
    ref_state, ref_grads = cfg.step(ref_state, grads, stats)

    # distributed path
    state = dk.init()

    @jax.jit
    def dstep(state, grads, stats):
        return dk.step(state, grads, stats)

    state, dist_grads = dstep(state, grads, stats)
    assert int(state.step) == 1
    for name in reg.names():
        np.testing.assert_allclose(
            np.asarray(dist_grads[name]['kernel']),
            np.asarray(ref_grads[name]['kernel']),
            rtol=5e-3, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(dist_grads[name]['bias']),
            np.asarray(ref_grads[name]['bias']),
            rtol=5e-3, atol=1e-5,
        )


@pytest.mark.parametrize('frac', [1.0, 0.5, 1 / 8])
def test_distributed_training_loss_decreases(frac):
    """Full data-parallel training with sharded batch: loss must decrease
    (reference smoke: tests/training_test.py:15-79)."""
    mesh, m, params, (x, y), reg, cfg2, dk, loss_fn = _setup(
        frac, damping=0.003, lr=0.05
    )
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(loss_fn)
    state = dk.init()
    bs = batch_sharding(mesh)
    x = jax.device_put(x, bs)
    y = jax.device_put(y, bs)

    @jax.jit
    def train_step(params, state, batch):
        (loss, _), grads, stats = run(params, batch)
        state, pgrads = dk.step(state, grads, stats)
        params = jax.tree_util.tree_map(lambda p, g: p - 0.05 * g, params, pgrads)
        return params, state, loss

    losses = []
    for _ in range(12):
        params, state, loss = train_step(params, state, (x, y))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_conv_model_distributed():
    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    m = models.TinyConvNet()
    x = jax.random.normal(jax.random.PRNGKey(3), (16, 32, 32, 1))
    y = jax.nn.one_hot(jnp.arange(16) % 10, 10)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg, damping=0.01)
    dk = DistributedKFAC(config=cfg, mesh=mesh)

    def loss_fn(p, batch):
        xx, yy = batch
        logits = m.apply({'params': p}, xx)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yy, axis=-1))

    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(loss_fn)
    state = dk.init()
    bs = batch_sharding(mesh)
    x, y = jax.device_put(x, bs), jax.device_put(y, bs)

    @jax.jit
    def train_step(params, state, batch):
        (loss, _), grads, stats = run(params, batch)
        state, pgrads = dk.step(state, grads, stats)
        params = jax.tree_util.tree_map(lambda p, g: p - 0.05 * g, params, pgrads)
        return params, state, loss

    losses = []
    for _ in range(8):
        params, state, loss = train_step(params, state, (x, y))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_assignment_parity_object():
    _, _, _, _, _, _, dk, _ = _setup(0.5)
    kaisa = dk.assignment
    assert kaisa.mesh_shape() == (4, 2)
    assert kaisa.broadcast_gradients() and kaisa.broadcast_inverses()


def test_unexecuted_layer_keeps_factors():
    """Registered layers skipped by the loss_fn keep their factors (parity
    with the dense engine's update_factors)."""
    mesh, m, params, batch, reg, cfg, dk, loss_fn = _setup(0.5)
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    # drop one layer's stats as if its module never ran
    partial = kfac_tpu.CapturedStats(
        a={k: v for k, v in stats.a.items() if k != 'fc2'},
        g={k: v for k, v in stats.g.items() if k != 'fc2'},
    )
    state = dk.init()
    state2 = jax.jit(dk.update_factors)(state, partial)
    # find fc2's bucket and slot: its factor row must be unchanged (identity)
    for b in dk.buckets:
        if 'fc2' in b.layers:
            i = b.layers.index('fc2')
            np.testing.assert_allclose(
                np.asarray(state2.a[b.key][i]), np.eye(b.da), atol=1e-6
            )
        if 'fc1' in b.layers:
            i = b.layers.index('fc1')
            assert np.abs(np.asarray(state2.a[b.key][i]) - np.eye(b.da)).max() > 0


def test_prediv_eigenvalues_distributed_matches_plain():
    """prediv fuses 1/(dg x da + damping) at inverse time; results must
    match the on-the-fly division path."""
    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    loss_fn = models.mse_loss(m)
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, (x, y))

    outs = {}
    for prediv in (False, True):
        cfg = kfac_tpu.KFACPreconditioner(
            registry=reg, damping=0.01, kl_clip=None,
            prediv_eigenvalues=prediv,
        )
        dk = DistributedKFAC(config=cfg, mesh=mesh)
        state = dk.init()
        if prediv:
            assert state.dgda and not state.da
        state, pg = jax.jit(dk.step)(state, grads, stats)
        outs[prediv] = pg
    np.testing.assert_allclose(
        np.asarray(outs[True]['fc1']['kernel']),
        np.asarray(outs[False]['fc1']['kernel']),
        rtol=1e-4, atol=1e-6,
    )


def test_prediv_memory_accounted():
    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    m = models.TinyModel()
    x, _ = models.regression_data(jax.random.PRNGKey(1), n=64, dim=6)
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg, prediv_eigenvalues=True)
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    usage = dk.memory_usage(dk.init())
    # the fused dgda buffer must be counted (it replaces da/dg)
    expected_dgda = sum(
        b.padded * b.dg * b.da * 4 for b in dk.buckets
    ) / mesh_lib.n_cols(mesh)
    assert usage['g_inverses'] >= expected_dgda


def test_bucketed_allreduce_matches_default():
    """ALLREDUCE_BUCKETED (triangle-packed single-buffer stat transport)
    must be numerically identical to the per-factor default — engaging the
    reference's symmetric bucketing (kfac/distributed.py:305-374,422-465)."""

    def run(method):
        mesh, m, params, batch, reg, cfg, dk, loss_fn = _setup(
            0.5, kl_clip=0.001, damping=0.01,
            factor_update_steps=1, inv_update_steps=1,
            allreduce_method=method,
        )
        cap = kfac_tpu.CurvatureCapture(reg)
        runner = cap.value_stats_and_grad(loss_fn)
        state = dk.init()

        @jax.jit
        def step(params, state, batch):
            (l, _), grads, stats = runner(params, batch)
            state, pg = dk.step(state, grads, stats)
            params = jax.tree_util.tree_map(
                lambda p, g: p - 0.05 * g, params, pg
            )
            return params, state, l

        bs = batch_sharding(mesh)
        batch = tuple(jax.device_put(b, bs) for b in batch)
        losses = []
        for _ in range(4):
            params, state, l = step(params, state, batch)
            losses.append(float(l))
        return losses, params

    l_def, p_def = run('allreduce')
    l_b, p_b = run('allreduce_bucketed')
    np.testing.assert_allclose(l_b, l_def, rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(p_def), jax.tree_util.tree_leaves(p_b)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )


def test_bucketed_allreduce_chunked_matches_default():
    """A byte cap small enough to force one chunk per factor triangle must
    not change the numerics — only the packing granularity (the
    reference's 25 MB cap, kfac/distributed.py:305-374)."""

    def run(**kw):
        mesh, m, params, batch, reg, cfg, dk, loss_fn = _setup(
            0.5, kl_clip=0.001, damping=0.01,
            factor_update_steps=1, inv_update_steps=1, **kw,
        )
        cap = kfac_tpu.CurvatureCapture(reg)
        runner = cap.value_stats_and_grad(loss_fn)
        state = dk.init()

        @jax.jit
        def step(params, state, batch):
            (l, _), grads, stats = runner(params, batch)
            state, pg = dk.step(state, grads, stats)
            params = jax.tree_util.tree_map(
                lambda p, g: p - 0.05 * g, params, pg
            )
            return params, state, l

        bs = batch_sharding(mesh)
        batch = tuple(jax.device_put(b, bs) for b in batch)
        for _ in range(3):
            params, state, l = step(params, state, batch)
        return float(l), params

    l_def, p_def = run(allreduce_method='allreduce')
    # ~100-byte cap: every factor triangle in this model exceeds it, so
    # each rides its own chunk — maximal chunking
    l_c, p_c = run(
        allreduce_method='allreduce_bucketed',
        allreduce_bucket_cap_mb=1e-4,
    )
    np.testing.assert_allclose(l_c, l_def, rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(p_def), jax.tree_util.tree_leaves(p_c)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )


@pytest.mark.parametrize('method', ['eigen', 'inverse'])
def test_colocate_factors_false_placement_and_numerics(method):
    """colocate_factors=False stores A and G in independent dimension
    buckets (different placement: one layer's factors in different
    stacks/slots, reference kfac/assignment.py:268-304) while the
    preconditioned gradients stay numerically identical to the dense
    engine."""
    import flax.linen as nn

    class Wide(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(16, name='p')(x))
            x = nn.relu(nn.Dense(16, name='q')(x))
            return nn.Dense(4, name='r')(x)

    m = Wide()
    x = jax.random.normal(jax.random.PRNGKey(0), (WORLD * 4, 16))
    y = jax.random.normal(jax.random.PRNGKey(1), (WORLD * 4, 4))
    params = m.init(jax.random.PRNGKey(2), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(params, batch):
        xb, yb = batch
        return jnp.mean((m.apply({'params': params}, xb) - yb) ** 2)

    mesh = kaisa_mesh(grad_worker_fraction=0.5)
    cfg = kfac_tpu.KFACPreconditioner(
        registry=reg, compute_method=method, damping=0.01, kl_clip=0.001,
        colocate_factors=False,
    )
    dk = DistributedKFAC(config=cfg, mesh=mesh)

    # placement: A side groups all three layers (shared da=17) in ONE
    # stack while G splits 16s from 4s — slots no longer pairwise aligned
    # (bucket_granularity resolves to 1 = exact dims on the CPU mesh)
    assert [sb.key for sb in dk.a_store] == ['a17']
    assert sorted(sb.key for sb in dk.g_store) == ['g16', 'g4']
    assert dk._a_slot['r'] == ('a17', 2)
    assert dk._g_slot['r'] == ('g4', 0)
    assert not dk.assignment.colocate_factors

    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, (x, y))

    ref_cfg = kfac_tpu.KFACPreconditioner(
        registry=reg, compute_method=method, damping=0.01, kl_clip=0.001,
    )
    ref_state, ref_grads = ref_cfg.step(ref_cfg.init(), grads, stats)

    state = dk.init()
    assert set(state.a) == {'a17'}
    assert set(state.g) == {'g16', 'g4'}

    @jax.jit
    def dstep(state, grads, stats):
        return dk.step(state, grads, stats)

    state, dist_grads = dstep(state, grads, stats)
    for name in reg.names():
        np.testing.assert_allclose(
            np.asarray(dist_grads[name]['kernel']),
            np.asarray(ref_grads[name]['kernel']),
            rtol=5e-3, atol=1e-5,
        )


def test_mem_opt_requires_colocated():
    mesh = kaisa_mesh(grad_worker_fraction=1 / WORLD)
    m = models.TinyModel(hidden=8, out=4)
    x, _ = models.regression_data(jax.random.PRNGKey(1), n=8, dim=6)
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg, colocate_factors=False)
    with pytest.raises(ValueError, match='MEM-OPT'):
        DistributedKFAC(config=cfg, mesh=mesh)


def test_memory_usage_reads_actual_shard_bytes():
    """memory_usage must report the real per-device shard footprint:
    factors always shard over the full mesh; decomps replicate under
    COMM-OPT and shard by column otherwise."""
    _, _, _, _, _, _, dk_comm, _ = _setup(1.0)
    st = dk_comm.init()
    usage = dk_comm.memory_usage(st)
    # compute the expectation straight from the arrays' shardings
    expect_a = sum(
        int(np.prod(v.sharding.shard_shape(v.shape))) * v.dtype.itemsize
        for v in st.a.values()
    )
    assert usage['a_factors'] == expect_a
    expect_qa = sum(
        int(np.prod(v.sharding.shard_shape(v.shape))) * v.dtype.itemsize
        for v in st.qa.values()
    )
    assert usage['a_inverses'] == expect_qa + sum(
        int(np.prod(v.sharding.shard_shape(v.shape))) * v.dtype.itemsize
        for v in st.da.values()
    )
    # COMM-OPT decomps are replicated: per-device bytes == global bytes
    for v in st.qa.values():
        assert np.prod(v.sharding.shard_shape(v.shape)) == v.size
    # MEM-OPT keeps a 1/world column shard
    _, _, _, _, _, _, dk_mem, _ = _setup(1 / WORLD)
    stm = dk_mem.init()
    um = dk_mem.memory_usage(stm)
    assert um['a_inverses'] < usage['a_inverses']
    for v in stm.qa.values():
        assert np.prod(v.sharding.shard_shape(v.shape)) * WORLD == v.size


def test_newton_schulz_solver_matches_cholesky_distributed():
    """inverse_solver='newton_schulz' (matmul-only, the TPU-native path)
    produces the same preconditioned grads as the Cholesky solver in the
    sharded stacked engine."""
    mesh, m, params, batch, reg, cfg, dk, loss_fn = _setup(
        0.5, compute_method='inverse', kl_clip=None, damping=0.01,
        inverse_solver='newton_schulz',
    )
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    state = dk.init()
    state, ns_grads = jax.jit(dk.step)(state, grads, stats)

    _, _, _, _, _, _, dk_chol, _ = _setup(
        0.5, compute_method='inverse', kl_clip=None, damping=0.01,
    )
    cstate = dk_chol.init()
    cstate, chol_grads = jax.jit(dk_chol.step)(cstate, grads, stats)
    for name in reg.names():
        np.testing.assert_allclose(
            np.asarray(ns_grads[name]['kernel']),
            np.asarray(chol_grads[name]['kernel']),
            rtol=5e-3, atol=5e-5,
        )


def test_describe_placement_matches_actual_shard_layout():
    """The dump's executed-placement section must report the device that
    REALLY holds each layer's factor slot (VERDICT r3 weak #2: the greedy
    table alone misled load-imbalance debugging), and the greedy table is
    labeled as the cost-model view."""
    _, _, _, _, reg, _, dk, _ = _setup(0.5, kl_clip=None)
    state = dk.init()
    dump = dk.describe()
    assert 'NOT the executed placement' in dump
    assert 'executed placement' in dump
    for name in reg.names():
        for side in ('a', 'g'):
            claimed = dk.slot_device(side, name)
            key, i = (dk._a_slot if side == 'a' else dk._g_slot)[name]
            arr = (state.a if side == 'a' else state.g)[key]
            # find the device whose actual shard covers slot i
            owners = [
                dev
                for dev, idx in arr.sharding.devices_indices_map(
                    arr.shape
                ).items()
                if (idx[0].start or 0) <= i < (idx[0].stop or arr.shape[0])
            ]
            assert claimed in owners, (name, side, claimed, owners)
            # the dump names that device id on the layer's placement line
            placement = dump.split('executed placement')[1].split(
                'cost-model view'
            )[0]
            line = next(
                l
                for l in placement.splitlines()
                if l.strip().startswith(name + ':')
            )
            assert f'device {claimed.id}' in line


def test_host_eigh_impl_matches_xla_in_stacked_engine():
    """eigh_impl='host' (pure_callback -> LAPACK inside the shard_map)
    produces the same preconditioned grads as the device eigh — the EIGEN
    method's TPU escape hatch, exercised on the sharded stacked path."""
    mesh, m, params, batch, reg, cfg, dk_host, loss_fn = _setup(
        0.5, kl_clip=None, damping=0.01, eigh_impl='host'
    )
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    state = dk_host.init()
    state, host_grads = jax.jit(dk_host.step)(state, grads, stats)

    _, _, _, _, _, _, dk_xla, _ = _setup(0.5, kl_clip=None, damping=0.01)
    xstate = dk_xla.init()
    xstate, xla_grads = jax.jit(dk_xla.step)(xstate, grads, stats)
    for name in reg.names():
        np.testing.assert_allclose(
            np.asarray(host_grads[name]['kernel']),
            np.asarray(xla_grads[name]['kernel']),
            rtol=2e-4, atol=1e-6,
        )


def test_auto_solver_stacked_single_runtime_branch():
    """inverse_solver='auto' on the stacked engine runs the batched
    Cholesky behind ONE scalar runtime cond per device-local block
    (factors.batched_damped_inverse_auto) — no construction-time
    TPUPerformanceWarning anymore, and on well-conditioned factors the
    preconditioned grads match the pure newton_schulz engine."""
    import warnings as warnings_mod

    with warnings_mod.catch_warnings():
        warnings_mod.simplefilter(
            'error', kfac_tpu.warnings.TPUPerformanceWarning
        )
        out = {}
        for solver in ('auto', 'newton_schulz'):
            mesh, m, params, batch, reg, cfg, dk, loss_fn = _setup(
                0.5, compute_method='inverse', kl_clip=None, damping=0.01,
                inverse_solver=solver,
            )
            cap = kfac_tpu.CurvatureCapture(reg)
            (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(
                params, batch
            )
            state, pg = jax.jit(dk.step)(dk.init(), grads, stats)
            out[solver] = pg
    for name in out['auto']:
        np.testing.assert_allclose(
            np.asarray(out['auto'][name]['kernel']),
            np.asarray(out['newton_schulz'][name]['kernel']),
            rtol=1e-4, atol=1e-6,
        )


def test_size_classes_collapse_heterogeneous_shapes_exactly():
    """Heterogeneous factor dims collapse into few class buckets (the
    execution-side load balancing of the reference's greedy assignment,
    kfac/assignment.py:227-319) and the identity/zero padding is EXACT:
    preconditioned grads match a granularity=1 (exact-dims) run."""
    import flax.linen as nn

    from kfac_tpu.parallel.kaisa import size_class

    # classing rules: powers of two below the granularity, multiples above
    assert size_class(7, 128) == 8
    assert size_class(8, 128) == 8
    assert size_class(100, 128) == 128
    assert size_class(129, 128) == 256
    assert size_class(513, 256) == 768
    assert size_class(513, 1) == 513  # disabled
    # non-power-of-two granularity: the sub-granularity power-of-two class
    # is capped at the granularity (65 -> 100, not 128 > the class 100 that
    # a dim of exactly 100 gets)
    assert size_class(65, 100) == 100
    assert size_class(7, 100) == 8

    class Hetero(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(19, name='l0')(x))
            x = nn.relu(nn.Dense(23, name='l1')(x))
            x = nn.relu(nn.Dense(21, name='l2')(x))
            return nn.Dense(5, name='l3')(x)

    m = Hetero()
    x = jax.random.normal(jax.random.PRNGKey(0), (WORLD * 4, 13))
    y = jax.random.normal(jax.random.PRNGKey(1), (WORLD * 4, 5))
    params = m.init(jax.random.PRNGKey(2), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(params, batch):
        xb, yb = batch
        return jnp.mean((m.apply({'params': params}, xb) - yb) ** 2)

    mesh = kaisa_mesh(grad_worker_fraction=1.0)
    cap = kfac_tpu.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, (x, y))

    def run(granularity):
        cfg = kfac_tpu.KFACPreconditioner(
            registry=reg, damping=0.01, kl_clip=0.001,
            bucket_granularity=granularity,
        )
        dk = DistributedKFAC(config=cfg, mesh=mesh)
        state, pgrads = jax.jit(dk.step)(dk.init(), grads, stats)
        return dk, pgrads

    dk_cls, pg_cls = run(128)
    dk_exact, pg_exact = run(1)
    # 4 distinct (da, dg) pairs collapse into 2 class buckets:
    # (14,19)->(16,32)... wait-free check by count
    assert len(dk_cls.buckets) < len(dk_exact.buckets)
    for a, b in zip(
        jax.tree_util.tree_leaves(pg_cls), jax.tree_util.tree_leaves(pg_exact)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        )


def test_inverse_residuals_out_of_band_monitoring():
    """VERDICT r4 weak #6: the stacked INVERSE engine exposes per-slot
    damped-inverse residuals out-of-band; benign factors sit far below
    the NS fallback threshold, EIGEN configs refuse the query."""
    from kfac_tpu.ops import factors as factors_lib

    mesh, m, params, batch, reg, cfg, dk, loss_fn = _setup(
        1.0, damping=0.01, compute_method='inverse',
        inverse_solver='newton_schulz',
        factor_update_steps=1, inv_update_steps=1,
    )
    cap = kfac_tpu.CurvatureCapture(reg)
    runner = cap.value_stats_and_grad(loss_fn)
    state = dk.init()
    (l, _), grads, stats = runner(params, batch)
    state, _ = dk.step(state, grads, stats)
    res = jax.jit(dk.inverse_residuals)(state)
    assert set(res) == {'a', 'g'}
    for side in ('a', 'g'):
        assert res[side], 'residuals must cover every bucket'
        for key, r in res[side].items():
            r = np.asarray(r)
            assert r.ndim == 1 and np.all(np.isfinite(r))
            assert np.all(r < factors_lib.NS_FALLBACK_RESIDUAL), (key, r)

    # EIGEN method: the query is meaningless and must say so
    mesh2, m2, p2, b2, reg2, cfg2, dk2, lf2 = _setup(
        1.0, compute_method='eigen',
    )
    with pytest.raises(ValueError, match='INVERSE'):
        dk2.inverse_residuals(dk2.init())


def test_inverse_residuals_use_inversion_time_damping():
    """A scheduled damping must not poison the monitor: residuals measure
    the inverse against the damping it was BUILT with (state.inv_damping),
    not the current step's value — otherwise a perfect inverse shows a
    spurious |delta_damping| * ||F_inv|| floor."""
    from kfac_tpu.ops import factors as factors_lib

    # damping drops 100x right after the inversion step
    sched = lambda step: jnp.where(step < 1, 1.0, 0.01)
    mesh, m, params, batch, reg, cfg, dk, loss_fn = _setup(
        1.0, damping=sched, compute_method='inverse',
        inverse_solver='newton_schulz',
        factor_update_steps=1, inv_update_steps=10,  # invert at step 0 only
    )
    cap = kfac_tpu.CurvatureCapture(reg)
    runner = cap.value_stats_and_grad(loss_fn)
    state = dk.init()
    for _ in range(3):  # step counter now well past the inversion
        (l, _), grads, stats = runner(params, batch)
        state, _ = dk.step(state, grads, stats)
    assert float(state.inv_damping) == 1.0  # built at step 0
    res = dk.inverse_residuals(state)
    worst = max(
        float(np.asarray(r).max())
        for side in res.values()
        for r in side.values()
    )
    assert worst < factors_lib.NS_FALLBACK_RESIDUAL, worst
