"""Tensor-parallel + context-parallel K-FAC training tests (8-device mesh).

Behavioral targets: the reference's GPT-NeoX e2e suite
(tests/gpt_neox/gpt_preconditioner_test.py) — K-FAC over model-parallel
layers — plus context parallelism the reference lacks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import kfac_tpu
from kfac_tpu.models import TransformerLM, lm_loss
from kfac_tpu.parallel import (
    DistributedKFAC,
    tensor_parallel,
)
from kfac_tpu.parallel import mesh as mesh_lib
from kfac_tpu.parallel.mesh import token_sharding, train_mesh


def _lm(mesh=None, ring_axis=None, **kw):
    cfg = dict(
        vocab_size=64, d_model=32, num_heads=4, num_layers=2, max_len=32
    )
    cfg.update(kw)
    return TransformerLM(ring_mesh=mesh, ring_axis=ring_axis, **cfg)


def test_train_mesh_axes():
    mesh = train_mesh(grad_worker_fraction=1.0, model=2, seq=2)
    assert dict(mesh.shape) == {
        'kfac_gw': 2, 'kfac_col': 1, 'model': 2, 'seq': 2,
    }
    with pytest.raises(ValueError):
        train_mesh(model=3, seq=1)  # 8 % 3 != 0


def test_param_specs_rules():
    m = _lm()
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), tokens)['params']
    specs = tensor_parallel.param_specs(params)
    from jax.sharding import PartitionSpec as P

    assert specs['block0']['attn']['q_proj']['kernel'] == P(None, 'model')
    assert specs['block0']['attn']['out_proj']['kernel'] == P('model', None)
    assert specs['block0']['attn']['out_proj']['bias'] == P()
    assert specs['block0']['mlp_up']['bias'] == P('model')
    assert specs['embed']['embedding'] == P()
    assert specs['lm_head']['kernel'] == P(None, 'model')


def test_tp_kfac_training_matches_replicated():
    """K-FAC over TP-sharded params must match the fully-replicated run."""
    mesh = train_mesh(grad_worker_fraction=1.0, model=2)
    m = _lm()
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    targets = jnp.roll(tokens, -1, 1)
    params = m.init(jax.random.PRNGKey(1), tokens)['params']
    reg = kfac_tpu.register_model(m, tokens)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg, damping=0.01, lr=0.1)
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    loss = lm_loss(m)
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(loss)

    def step(params, state, batch):
        (l, _), grads, stats = run(params, batch)
        state, pg = dk.step(state, grads, stats)
        params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, pg)
        return params, state, l

    # TP run: params sharded over the model axis
    tp_params = tensor_parallel.shard_params(params, mesh)
    batch = (
        jax.device_put(tokens, token_sharding(mesh)),
        jax.device_put(targets, token_sharding(mesh)),
    )
    state = dk.init()
    tp_step = jax.jit(step)
    p_tp, s_tp, l_tp = tp_step(tp_params, state, batch)
    # replicated run (same math, no TP layout)
    p_rep, s_rep, l_rep = tp_step(params, dk.init(), (tokens, targets))
    np.testing.assert_allclose(float(l_tp), float(l_rep), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(p_tp['block0']['attn']['q_proj']['kernel']),
        np.asarray(p_rep['block0']['attn']['q_proj']['kernel']),
        rtol=2e-3, atol=2e-5,
    )
    # the TP params actually live sharded
    assert 'model' in str(
        p_tp['block0']['attn']['q_proj']['kernel'].sharding.spec
    )


def test_context_parallel_kfac_training():
    """Ring-attention LM with the sequence sharded trains under K-FAC and
    matches the dense-attention model's loss trajectory."""
    mesh = train_mesh(grad_worker_fraction=1.0, seq=4)
    m_ring = _lm(mesh=mesh, ring_axis=mesh_lib.SEQ_AXIS)
    m_dense = _lm()
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 32), 0, 64)
    targets = jnp.roll(tokens, -1, 1)
    params = m_dense.init(jax.random.PRNGKey(1), tokens)['params']
    reg = kfac_tpu.register_model(m_ring, tokens)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg, damping=0.01, lr=0.1)
    dk = DistributedKFAC(config=cfg, mesh=mesh)

    def make_step(model):
        loss = lm_loss(model)
        cap = kfac_tpu.CurvatureCapture(reg)
        run = cap.value_stats_and_grad(loss)

        @jax.jit
        def step(params, state, batch):
            (l, _), grads, stats = run(params, batch)
            state, pg = dk.step(state, grads, stats)
            params = jax.tree_util.tree_map(
                lambda p, g: p - 0.1 * g, params, pg
            )
            return params, state, l

        return step

    ring_step = make_step(m_ring)
    dense_step = make_step(m_dense)
    ts = token_sharding(mesh)
    batch_ring = (jax.device_put(tokens, ts), jax.device_put(targets, ts))

    p_r, s_r = params, dk.init()
    p_d, s_d = params, dk.init()
    for _ in range(3):
        p_r, s_r, l_r = ring_step(p_r, s_r, batch_ring)
        p_d, s_d, l_d = dense_step(p_d, s_d, (tokens, targets))
    np.testing.assert_allclose(float(l_r), float(l_d), rtol=1e-3)
    assert np.isfinite(float(l_r))


def test_tp_with_hybrid_kaisa():
    """TP (model=2) composed with HYBRID-OPT KAISA (dp=4 -> 2x2 grid)."""
    mesh = train_mesh(grad_worker_fraction=0.5, model=2)
    assert dict(mesh.shape) == {
        'kfac_gw': 2, 'kfac_col': 2, 'model': 2, 'seq': 1,
    }
    m = _lm()
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    targets = jnp.roll(tokens, -1, 1)
    params = tensor_parallel.shard_params(
        m.init(jax.random.PRNGKey(1), tokens)['params'], mesh
    )
    reg = kfac_tpu.register_model(m, tokens)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg, damping=0.01)
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    assert dk.world == 4 and dk.grad_workers == 2
    loss = lm_loss(m)
    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(loss)

    @jax.jit
    def step(params, state, batch):
        (l, _), grads, stats = run(params, batch)
        state, pg = dk.step(state, grads, stats)
        params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, pg)
        return params, state, l

    ts = token_sharding(mesh)
    batch = (jax.device_put(tokens, ts), jax.device_put(targets, ts))
    state = dk.init()
    losses = []
    for _ in range(4):
        params, state, l = step(params, state, batch)
        losses.append(float(l))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


class _GenericNet:
    """A model with names unlike anything in kfac_tpu.models — proves the
    registry-derived TP rules need no name table (VERDICT round 1)."""

    def build(self):
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.relu(nn.Dense(128, name='expander')(x))
                x = nn.Dense(32, name='contractor')(x)
                return nn.Dense(10, name='classify_out', use_bias=False)(x)

        return Net()


def test_registry_derived_tp_rules_generic_model():
    from jax.sharding import PartitionSpec as P

    m = _GenericNet().build()
    x = jnp.zeros((4, 32))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)

    kinds = tensor_parallel.derive_layer_kinds(reg)
    assert kinds == {
        'expander': 'column',      # 32 -> 128 expands
        'contractor': 'row',       # 128 -> 32 contracts
        'classify_out': 'row',     # 32 -> 10 contracts
    }
    # user override: keep the head replicated
    kinds = tensor_parallel.derive_layer_kinds(
        reg, overrides=[('classify_out', 'replicated')]
    )
    assert kinds['classify_out'] == 'replicated'

    specs = tensor_parallel.registry_param_specs(
        params, reg, overrides=[('classify_out', 'replicated')],
        warn_unmatched=False,
    )
    assert specs['expander']['kernel'] == P(None, 'model')
    assert specs['expander']['bias'] == P('model')
    assert specs['contractor']['kernel'] == P('model', None)
    assert specs['contractor']['bias'] == P()
    assert specs['classify_out']['kernel'] == P()


def test_registry_tp_warns_on_unmatched_params():
    import warnings as pywarnings

    import flax.linen as nn

    class WithNorm(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(64, name='wide')(x)
            x = nn.LayerNorm(name='normalizer')(x)
            return nn.Dense(8, name='narrow')(x)

    m = WithNorm()
    x = jnp.zeros((2, 16))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    with pywarnings.catch_warnings(record=True) as rec:
        pywarnings.simplefilter('always')
        tensor_parallel.registry_param_specs(params, reg)
    msgs = [str(w.message) for w in rec
            if issubclass(w.category, tensor_parallel.UnshardedParamWarning)]
    assert msgs and 'normalizer' in msgs[0]


def test_row_parallel_a_factor_matches_gathered_oracle():
    """The reference gathers a row-parallel layer's model-sharded input
    activations before computing A (kfac/gpt_neox/layer.py:129-163). Under
    GSPMD the captured A factor of a row-parallel layer must equal the
    oracle computed from the unsharded activations."""
    mesh = train_mesh(grad_worker_fraction=1.0, model=4)
    m = _GenericNet().build()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 32))
    y = jax.nn.one_hot(jnp.arange(16) % 10, 10)
    params = m.init(jax.random.PRNGKey(1), x)['params']
    reg = kfac_tpu.register_model(m, x)

    def loss_fn(params, batch):
        xb, yb = batch
        logits = m.apply({'params': params}, xb)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yb, -1))

    cap = kfac_tpu.CurvatureCapture(reg)
    run = cap.value_stats_and_grad(loss_fn)

    # oracle: fully replicated params/batch
    (_, _), _, stats_rep = jax.jit(run)(params, (x, y))

    # TP: 'contractor' is row-parallel, so its input activations (the
    # 'expander' output) are model-sharded under GSPMD
    tp_params = tensor_parallel.shard_params_from_registry(
        params, mesh, reg, warn_unmatched=False
    )
    bs = mesh_lib.batch_sharding(mesh)
    batch = (jax.device_put(x, bs), jax.device_put(jnp.asarray(y), bs))
    (_, _), _, stats_tp = jax.jit(run)(tp_params, batch)

    for name in ('contractor', 'expander'):
        np.testing.assert_allclose(
            np.asarray(stats_tp.a[name]), np.asarray(stats_rep.a[name]),
            rtol=1e-4, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(stats_tp.g[name]), np.asarray(stats_rep.g[name]),
            rtol=1e-4, atol=1e-6,
        )
