"""Single-slot interleaved 1F1B scan: equivalence against the 2-slot 1F1B
on the same logical stages, plus K-FAC integration.

The interleaved model stacks stages RANK-MAJOR (stack index r*v + c holds
logical stage c*p + r); the baseline stacks them logically, so the tests
permute via logical_to_stack before comparing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import kfac_tpu
from kfac_tpu.parallel import interleaved_scan, pipeline
from kfac_tpu.parallel.interleaved_scan import (
    InterleavedPipelinedLM,
    logical_to_stack,
)
from kfac_tpu.parallel.mesh import pipeline_mesh

V = 64  # vocab


def _models(p=2, v=2, dp_devices=4, m=4):
    """Interleaved (p ranks, v chunks) and 2-slot baseline (p*v ranks)
    over the same p*v logical stages."""
    ilv_mesh = pipeline_mesh(n_stages=p, devices=jax.devices()[:dp_devices])
    base_mesh = pipeline_mesh(
        n_stages=p * v, devices=jax.devices()[: p * v]
    )
    kw = dict(
        vocab_size=V, d_model=32, num_heads=4, num_layers=p * v,
        n_microbatches=m, max_len=16,
    )
    ilv = InterleavedPipelinedLM(mesh=ilv_mesh, virtual_chunks=v, **kw)
    base = pipeline.PipelinedLM(mesh=base_mesh, schedule='1f1b', **kw)
    return ilv, base


def _stack_perm(p, v):
    """perm[s] = interleaved stack index of logical stage s."""
    return np.array([logical_to_stack(p, v, s) for s in range(p * v)])


# deliberately NOT slow-marked: the equivalence guard on the hardest new
# scheduling code must stay in the fast tier (same policy as the
# 1f1b-vs-gpipe guard)
def test_interleaved_matches_1f1b_loss_grads_stats():
    """Loss, every parameter gradient, and all A/G statistics from the
    single-slot interleaved scan (p=2, v=2, dp=2) equal the 2-slot 1F1B
    on the same 4 logical stages (p=4), modulo the stack permutation."""
    p, v = 2, 2
    ilv, base = _models(p=p, v=v)
    perm = _stack_perm(p, v)

    iparams = ilv.init(jax.random.PRNGKey(0))
    # baseline stages in LOGICAL order: base[s] = ilv_stack[perm[s]]
    bparams = dict(iparams)
    bparams['stages'] = jax.tree_util.tree_map(
        lambda a: np.asarray(a)[perm], iparams['stages']
    )

    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, V)
    targets = jnp.roll(tokens, -1, 1)
    l_i, g_i, s_i = jax.jit(ilv.loss_and_stats)(iparams, (tokens, targets))
    l_b, g_b, s_b = jax.jit(base.loss_and_stats)(bparams, (tokens, targets))

    np.testing.assert_allclose(float(l_i), float(l_b), rtol=1e-5)
    for name in ('embed', 'pos_embed', 'head', 'ln_f'):
        for (pa, va), (pb, vb) in zip(
            jax.tree_util.tree_leaves_with_path(g_i[name]),
            jax.tree_util.tree_leaves_with_path(g_b[name]),
        ):
            assert pa == pb
            np.testing.assert_allclose(
                np.asarray(va), np.asarray(vb), rtol=2e-4, atol=2e-6,
                err_msg=f'{name}{pa}',
            )
    # stage grads: ilv stack index perm[s] vs baseline logical index s
    for (pa, va), (pb, vb) in zip(
        jax.tree_util.tree_leaves_with_path(g_i['stages']),
        jax.tree_util.tree_leaves_with_path(g_b['stages']),
    ):
        assert pa == pb
        np.testing.assert_allclose(
            np.asarray(va)[perm], np.asarray(vb), rtol=2e-4, atol=2e-6,
            err_msg=f'stages{pa}',
        )
    for k in s_b.a:
        np.testing.assert_allclose(
            np.asarray(s_i.a[k])[perm], np.asarray(s_b.a[k]),
            rtol=1e-4, atol=1e-6, err_msg=f'A {k}',
        )
        np.testing.assert_allclose(
            np.asarray(s_i.g[k])[perm], np.asarray(s_b.g[k]),
            rtol=1e-4, atol=1e-7, err_msg=f'G {k}',
        )


@pytest.mark.slow
def test_interleaved_kfac_training():
    """PipelineKFAC drives the interleaved model unchanged (state stacked
    over p*v logical stages, v per rank): loss decreases."""
    mesh = pipeline_mesh(n_stages=2, devices=jax.devices()[:2])
    plm = InterleavedPipelinedLM(
        mesh=mesh, vocab_size=V, d_model=16, num_heads=2, num_layers=4,
        n_microbatches=4, max_len=8, virtual_chunks=2,
    )
    cfg = kfac_tpu.KFACPreconditioner(
        registry=plm.stage_registry, damping=0.01, lr=0.1,
        factor_update_steps=1, inv_update_steps=2,
    )
    pk = pipeline.PipelineKFAC(config=cfg, model=plm)
    params = plm.init(jax.random.PRNGKey(0))
    state = pk.init()
    tok = jax.random.randint(jax.random.PRNGKey(1), (8, 8), 0, V)
    batch = (tok, jnp.roll(tok, -1, 1))

    @jax.jit
    def step(params, state, batch):
        loss, grads, stats = plm.loss_and_stats(params, batch)
        state, grads = pk.step(state, grads, stats)
        params = jax.tree_util.tree_map(
            lambda p_, g: p_ - 0.1 * g, params, grads
        )
        return params, state, loss

    losses = []
    for _ in range(6):
        params, state, l = step(params, state, batch)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses


def test_interleaved_validates_config():
    mesh = pipeline_mesh(n_stages=2, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match='virtual_chunks'):
        InterleavedPipelinedLM(
            mesh=mesh, vocab_size=V, d_model=16, num_heads=2, num_layers=4,
            n_microbatches=4, max_len=8, virtual_chunks=0,
        )
    with pytest.raises(ValueError, match='divide evenly'):
        InterleavedPipelinedLM(
            mesh=mesh, vocab_size=V, d_model=16, num_heads=2, num_layers=6,
            n_microbatches=4, max_len=8, virtual_chunks=2,
        )
    with pytest.raises(ValueError, match='multiple'):
        # m=3 not a multiple of p=2: rejected by the schedule generator
        InterleavedPipelinedLM(
            mesh=mesh, vocab_size=V, d_model=16, num_heads=2, num_layers=4,
            n_microbatches=3, max_len=8, virtual_chunks=2,
        )
    # the plain class refuses the interleaved schedule with a pointer here
    with pytest.raises(ValueError, match='InterleavedPipelinedLM'):
        pipeline.PipelinedLM(
            mesh=mesh, vocab_size=V, d_model=16, num_heads=2, num_layers=4,
            schedule='interleaved',
        )


def test_interleaved_field_roundtrip_and_apply_guard():
    """schedule='interleaved' survives dataclasses.replace (the parent
    validation accepts it for this subclass), and the forward-only apply()
    fails with a clear message instead of a wrong-permutation error."""
    import dataclasses as dc

    mesh = pipeline_mesh(n_stages=2, devices=jax.devices()[:2])
    plm = InterleavedPipelinedLM(
        mesh=mesh, vocab_size=V, d_model=16, num_heads=2, num_layers=4,
        n_microbatches=4, max_len=8, virtual_chunks=2,
    )
    assert plm.schedule == 'interleaved'
    plm2 = dc.replace(plm, n_microbatches=8)
    assert plm2.n_microbatches == 8 and plm2._sched.ticks > plm._sched.ticks
    # v=1 is valid (plain 1F1B as a single-slot schedule) and must also
    # round-trip: the parent guard keys on the class, not the chunk count
    plm1 = InterleavedPipelinedLM(
        mesh=mesh, vocab_size=V, d_model=16, num_heads=2, num_layers=4,
        n_microbatches=4, max_len=8, virtual_chunks=1,
    )
    assert dc.replace(plm1, n_microbatches=8).virtual_chunks == 1
    with pytest.raises(NotImplementedError, match='loss_and_stats'):
        plm.apply(plm.init(jax.random.PRNGKey(0)), jnp.zeros((8, 8), jnp.int32))


def test_tick_counters_match_schedule_table(ilv_ticks_p2v2):
    """The executed (F, B, idle) counters surfaced from the scan carry
    equal the static schedule table's per-rank slot counts exactly."""
    model, _, _, _, ticks = ilv_ticks_p2v2
    report = model.tick_report(np.asarray(ticks))
    assert report['matches_schedule'], report
    assert report['executed'] == report['predicted']


def test_tick_idle_equals_simulator_bubble_slots(ilv_ticks_p2v2):
    """Total executed idle slots == the simulator's ``bubble_slots()``
    == the planner's ``schedule_terms`` accounting — the runtime ground
    truth the 3D topology planner prices candidates with."""
    from kfac_tpu.planner import topology

    model, _, _, _, ticks = ilv_ticks_p2v2
    counts = np.asarray(ticks)
    p, v, m = 2, 2, 4
    idle_total = int(counts[:, 2].sum())
    assert idle_total == int(model._sched.bubble_slots())
    terms = topology.schedule_terms('interleaved', p, v, m)
    assert terms['source'] == 'simulator'
    assert idle_total == terms['bubble_slots']
    # every rank counts each tick exactly once (F, B, or idle)
    assert int(counts.sum()) == terms['ticks'] * p
    assert counts[:, :2].sum(axis=1).tolist() == [2 * m * v] * p


@pytest.mark.slow
def test_tick_counters_p4(ilv_ticks_p4v2):
    """Same executed-vs-simulator identity on the deepest pipe the
    suite's 8 virtual devices admit (p=4, v=2: 8 logical stages)."""
    from kfac_tpu.planner import topology

    model, _, _, _, ticks = ilv_ticks_p4v2
    counts = np.asarray(ticks)
    report = model.tick_report(counts)
    assert report['matches_schedule'], report
    terms = topology.schedule_terms('interleaved', 4, 2, 4)
    assert terms['source'] == 'simulator'
    assert int(counts[:, 2].sum()) == terms['bubble_slots']
    assert int(counts.sum()) == terms['ticks'] * 4


def test_logical_to_stack_is_a_permutation():
    for p, v in ((2, 2), (4, 2), (2, 4), (4, 4)):
        idx = [logical_to_stack(p, v, s) for s in range(p * v)]
        assert sorted(idx) == list(range(p * v))
        # rank-major: logical stage c*p + r lands at r*v + c
        for s, i in enumerate(idx):
            r, c = s % p, s // p
            assert i == r * v + c
