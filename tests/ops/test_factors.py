"""Unit tests for factor math: EMA, eigh, inverse, preconditioning, kl-clip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kfac_tpu.ops import factors


def _random_spd(n, seed):
    rng = np.random.default_rng(seed)
    m = rng.normal(size=(n, n)).astype(np.float32)
    return m @ m.T / n + 0.1 * np.eye(n, dtype=np.float32)


def test_ema_update_identity_init():
    new = jnp.full((3, 3), 2.0)
    out = factors.ema_update(None, new, alpha=0.95)
    expected = 0.95 * np.eye(3) + 0.05 * 2.0 * np.ones((3, 3))
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_ema_update_running():
    run = jnp.ones((2, 2))
    new = jnp.zeros((2, 2))
    out = factors.ema_update(run, new, alpha=0.5)
    np.testing.assert_allclose(out, 0.5 * np.ones((2, 2)))


def test_eigh_reconstructs_and_clamps():
    f = _random_spd(6, 0)
    dec = factors.compute_eigh(jnp.asarray(f))
    recon = np.asarray(dec.q) @ np.diag(np.asarray(dec.d)) @ np.asarray(dec.q).T
    np.testing.assert_allclose(recon, f, rtol=1e-4, atol=1e-5)
    assert (np.asarray(dec.d) >= 0).all()


def test_inverse_matches_numpy():
    f = _random_spd(5, 1)
    damping = 0.01
    inv = factors.compute_inverse(jnp.asarray(f), damping)
    expected = np.linalg.inv(f + damping * np.eye(5))
    np.testing.assert_allclose(inv, expected, rtol=1e-3, atol=1e-4)


def test_eigen_precondition_equals_explicit_inverse_formula():
    """qg [ (qg^T W qa) / (dg x da + l) ] qa^T == (G x A + l)^-1 applied."""
    a = _random_spd(4, 2)
    g = _random_spd(3, 3)
    grad = np.random.default_rng(4).normal(size=(3, 4)).astype(np.float32)
    damping = 0.05
    adec = factors.compute_eigh(jnp.asarray(a))
    gdec = factors.compute_eigh(jnp.asarray(g))
    got = factors.eigen_preconditioned_grad(jnp.asarray(grad), adec, gdec, damping)
    # explicit Kronecker solve: vec form with kron(A, G) (row-major vec)
    kron = np.kron(a, g) + damping * np.eye(12)
    vec = grad.T.reshape(-1)  # column-major stacking matches kron(A, G)
    expected = np.linalg.solve(kron, vec).reshape(4, 3).T
    np.testing.assert_allclose(got, expected, rtol=1e-3, atol=1e-4)


def test_prediv_matches_on_the_fly_division():
    a = _random_spd(4, 5)
    g = _random_spd(3, 6)
    grad = np.random.default_rng(7).normal(size=(3, 4)).astype(np.float32)
    damping = 0.01
    adec = factors.compute_eigh(jnp.asarray(a))
    gdec = factors.compute_eigh(jnp.asarray(g))
    direct = factors.eigen_preconditioned_grad(
        jnp.asarray(grad), adec, gdec, damping
    )
    dgda = factors.prediv_eigenvalues(adec, gdec, damping)
    v1 = np.asarray(gdec.q).T @ grad @ np.asarray(adec.q)
    via_prediv = np.asarray(gdec.q) @ (v1 * np.asarray(dgda)) @ np.asarray(adec.q).T
    np.testing.assert_allclose(direct, via_prediv, rtol=1e-4, atol=1e-5)


def test_inverse_precondition_formula():
    a_inv = _random_spd(4, 8)
    g_inv = _random_spd(3, 9)
    grad = np.random.default_rng(10).normal(size=(3, 4)).astype(np.float32)
    got = factors.inverse_preconditioned_grad(
        jnp.asarray(grad), jnp.asarray(a_inv), jnp.asarray(g_inv)
    )
    np.testing.assert_allclose(got, g_inv @ grad @ a_inv, rtol=1e-4, atol=1e-4)


def test_kl_clip_scale():
    assert float(factors.kl_clip_scale(jnp.asarray(0.0), 0.001)) == 1.0
    # |vg| tiny -> clipped at 1
    assert float(factors.kl_clip_scale(jnp.asarray(1e-9), 0.001)) == 1.0
    got = float(factors.kl_clip_scale(jnp.asarray(4.0), 0.001))
    np.testing.assert_allclose(got, np.sqrt(0.001 / 4.0), rtol=1e-6)
    got_neg = float(factors.kl_clip_scale(jnp.asarray(-4.0), 0.001))
    np.testing.assert_allclose(got_neg, np.sqrt(0.001 / 4.0), rtol=1e-6)


def test_newton_schulz_inverse_matches_cholesky():
    """The matmul-only solver converges to the direct damped inverse for
    well- and mildly ill-conditioned SPD factors."""
    for n, seed in ((16, 0), (128, 1)):
        f = jnp.asarray(_random_spd(n, seed))
        ns = factors.newton_schulz_inverse(f, 0.01)
        direct = factors.compute_inverse(f, 0.01)
        np.testing.assert_allclose(
            np.asarray(ns), np.asarray(direct), atol=5e-4
        )


def test_newton_schulz_handles_near_singular_factor():
    """Damping floors the spectrum, so a rank-deficient factor still
    inverts (the curvature-factor regime: PSD + damping*I)."""
    f = jnp.zeros((32, 32))  # zero factor: inverse is I/damping
    ns = factors.newton_schulz_inverse(f, 0.1)
    np.testing.assert_allclose(
        np.asarray(ns), np.eye(32) / 0.1, rtol=1e-3
    )


def test_newton_schulz_converges_for_ill_conditioned_factor():
    """Condition number ~1e6 (large-norm factor, small damping): the
    Gershgorin init + residual-monitored loop must still converge."""
    rng = np.random.default_rng(7)
    q, _ = np.linalg.qr(rng.normal(size=(64, 64)))
    evals = np.logspace(0, 4, 64)  # factor norm 1e4, damping 1e-2 -> 1e6
    f = jnp.asarray((q * evals) @ q.T, jnp.float32)
    ns = factors.newton_schulz_inverse(f, 0.01)
    direct = factors.compute_inverse(f, 0.01)
    m = np.asarray(f) + 0.01 * np.eye(64)
    # NS limiting accuracy in fp32 is O(kappa * eps) ~ 0.1 here (Cholesky's
    # backward-stable solve does better; for preconditioning the difference
    # is immaterial — see newton_schulz_inverse_info docstring)
    resid = np.abs(np.asarray(ns) @ m - np.eye(64)).max()
    assert resid < 5e-2, resid
    # and the two inverses agree where the spectrum is well-resolved
    assert np.median(np.abs(np.asarray(ns) - np.asarray(direct))) < 1e-5


def test_newton_schulz_early_exit_on_benign_factor():
    """The residual stopping rule exits well before the iteration cap on a
    well-conditioned factor, and reports a residual at/below tolerance."""
    f = jnp.asarray(_random_spd(64, 3))
    info = factors.newton_schulz_inverse_info(f, 0.01, max_iters=40)
    assert int(info.iterations) < 25, int(info.iterations)
    assert float(info.residual) <= 1e-6, float(info.residual)
    direct = factors.compute_inverse(f, 0.01)
    np.testing.assert_allclose(
        np.asarray(info.inverse), np.asarray(direct), atol=5e-4
    )


def test_newton_schulz_stagnation_stop_at_fp32_floor():
    """Spectrum spread ~1e9 with tiny damping: the fp32 iteration cannot
    reach tol, so the monotonicity rule must stop it at the accuracy floor
    (well under the cap) and report the honest, large residual."""
    rng = np.random.default_rng(11)
    q, _ = np.linalg.qr(rng.normal(size=(96, 96)))
    evals = np.logspace(-5, 4, 96)  # spread 1e9
    f = jnp.asarray((q * evals) @ q.T, jnp.float32)
    info = factors.newton_schulz_inverse_info(f, 1e-5, max_iters=100)
    assert float(info.residual) > 1e-6  # floor, not convergence
    assert int(info.iterations) < 100  # stagnation fired, not the cap


def test_newton_schulz_dead_relu_factor():
    """Activation covariance of a layer with mostly dead units: near-zero
    rows/cols except a small live block. Damping floors the dead subspace;
    NS must match Cholesky on the whole inverse."""
    rng = np.random.default_rng(13)
    # cov of activations where only the first 8 of 48 units ever fire
    acts = np.zeros((256, 48), np.float32)
    acts[:, :8] = rng.normal(size=(256, 8))
    a = acts.T @ acts / 256
    ns = factors.newton_schulz_inverse(jnp.asarray(a), 0.01)
    direct = factors.compute_inverse(jnp.asarray(a), 0.01)
    np.testing.assert_allclose(
        np.asarray(ns), np.asarray(direct), atol=5e-3, rtol=1e-3
    )


def test_damped_inverse_auto_falls_back_on_pathological_factor():
    """solver='auto': when the NS residual exceeds the fallback threshold
    (kappa ~1e9 in fp32), the result must be the Cholesky inverse."""
    rng = np.random.default_rng(17)
    q, _ = np.linalg.qr(rng.normal(size=(64, 64)))
    evals = np.logspace(-5, 4, 64)
    f = jnp.asarray((q * evals) @ q.T, jnp.float32)
    info = factors.newton_schulz_inverse_info(f, 1e-5, max_iters=100)
    assert float(info.residual) > factors.NS_FALLBACK_RESIDUAL  # premise
    auto = factors.damped_inverse(f, 1e-5, solver='auto', iters=100)
    direct = factors.compute_inverse(f, 1e-5)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(direct))


def test_damped_inverse_auto_keeps_ns_when_converged():
    """solver='auto' on a benign factor returns the NS inverse (bitwise:
    the cond must take the cheap branch), which matches Cholesky."""
    f = jnp.asarray(_random_spd(32, 19))
    auto = factors.damped_inverse(f, 0.01, solver='auto')
    ns = factors.newton_schulz_inverse(f, 0.01)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(ns))
    direct = factors.compute_inverse(f, 0.01)
    np.testing.assert_allclose(np.asarray(auto), np.asarray(direct), atol=5e-4)


def test_newton_schulz_warm_start_fewer_iters_and_safeguard():
    """Warm-starting from a near inverse converges in strictly fewer
    iterations to the same answer; a zeros/garbage x0 trips the
    safeguard and reproduces the cold start bitwise."""
    f = jnp.asarray(_random_spd(64, 31))
    cold = factors.newton_schulz_inverse_info(f, 0.01, max_iters=40)
    assert float(cold.residual) <= 1e-6

    # near inverse: the solution for a slightly different damping
    near = factors.newton_schulz_inverse(f, 0.0125)
    warm = factors.newton_schulz_inverse_info(f, 0.01, max_iters=40, x0=near)
    assert int(warm.iterations) < int(cold.iterations), (
        int(warm.iterations), int(cold.iterations)
    )
    assert float(warm.residual) <= 1e-6
    np.testing.assert_allclose(
        np.asarray(warm.inverse), np.asarray(cold.inverse),
        rtol=1e-4, atol=1e-6,
    )

    # safeguarded fallbacks: zeros (fresh state) and garbage both
    # reproduce the Gershgorin cold start exactly
    for bad in (jnp.zeros_like(f), jnp.full_like(f, 1e6)):
        fb = factors.newton_schulz_inverse_info(f, 0.01, max_iters=40, x0=bad)
        np.testing.assert_array_equal(
            np.asarray(fb.inverse), np.asarray(cold.inverse)
        )
        assert int(fb.iterations) == int(cold.iterations)


def test_batched_auto_inverse_single_branch_per_slot_fallback():
    """batched_damped_inverse_auto: well-conditioned slots get the NS
    inverse bitwise (the scalar cond takes the cheap branch when ALL
    slots converge); with one pathological slot in the stack, only that
    slot becomes the Cholesky inverse and the good slot keeps NS."""
    rng = np.random.default_rng(17)
    good = jnp.asarray(_random_spd(64, 19))
    q, _ = np.linalg.qr(rng.normal(size=(64, 64)))
    bad = jnp.asarray((q * np.logspace(-5, 4, 64)) @ q.T, jnp.float32)
    info = factors.newton_schulz_inverse_info(bad, 1e-5, max_iters=100)
    assert float(info.residual) > factors.NS_FALLBACK_RESIDUAL  # premise

    # all-good stack: bitwise the batched NS result
    stack = jnp.stack([good, good])
    out = factors.batched_damped_inverse_auto(stack, 1e-5, iters=100)
    ns_good = np.asarray(
        factors.newton_schulz_inverse(good, 1e-5, iters=100)
    )
    np.testing.assert_array_equal(np.asarray(out[0]), ns_good)

    # mixed stack: per-slot selection. The good slot is allclose rather
    # than bitwise: the batched while_loop iterates until every lane
    # stops, so it may take extra (stable) NS trips vs the solo run.
    out = factors.batched_damped_inverse_auto(
        jnp.stack([good, bad]), 1e-5, iters=100
    )
    np.testing.assert_allclose(
        np.asarray(out[0]), ns_good, rtol=1e-4, atol=1e-5
    )
    np.testing.assert_array_equal(
        np.asarray(out[1]),
        np.asarray(factors.compute_inverse(bad, 1e-5)),
    )


def test_host_eigh_matches_xla_eigh():
    """impl='host' (pure_callback -> LAPACK) reconstructs the factor and
    agrees with the device path on eigenvalues; batched input works
    without vmap (numpy eigh batches natively)."""
    f = jnp.asarray(_random_spd(24, 29))
    host = factors.compute_eigh(f, impl='host')
    xla = factors.compute_eigh(f, impl='xla')
    recon = np.asarray(host.q) @ np.diag(np.asarray(host.d)) @ np.asarray(host.q).T
    np.testing.assert_allclose(recon, np.asarray(f), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.sort(np.asarray(host.d)), np.sort(np.asarray(xla.d)),
        rtol=1e-4, atol=1e-5,
    )
    batch = jnp.stack([jnp.asarray(_random_spd(16, s)) for s in (1, 2, 3)])
    w, v = jax.jit(lambda b: factors.batched_eigh(b, 'host'))(batch)
    for i in range(3):
        recon = np.asarray(v[i]) @ np.diag(np.asarray(w[i])) @ np.asarray(v[i]).T
        np.testing.assert_allclose(
            recon, np.asarray(batch[i]), rtol=1e-4, atol=1e-5
        )


def test_batched_eigh_upcasts_bf16_host_under_vmap():
    """The fp32 upcast guard: a bf16 factor stack through the 'host'
    impl under vmap (the async host-refresh shape) decomposes in fp32 —
    outputs are fp32, finite, and reconstruct the upcast factors."""
    stack = jnp.stack([jnp.asarray(_random_spd(16, s)) for s in (7, 8, 9)])
    bf16 = stack.astype(jnp.bfloat16)
    w, v = jax.jit(
        jax.vmap(lambda m: factors.batched_eigh(m, impl='host'))
    )(bf16)
    assert w.dtype == jnp.float32 and v.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(w))) and bool(jnp.all(jnp.isfinite(v)))
    f32 = np.asarray(bf16.astype(jnp.float32))
    for i in range(3):
        recon = np.asarray(v[i]) @ np.diag(np.asarray(w[i])) @ np.asarray(v[i]).T
        np.testing.assert_allclose(recon, f32[i], rtol=1e-4, atol=1e-5)
    # the xla impl rides the same guard
    w2, _ = factors.batched_eigh(bf16, impl='xla')
    assert w2.dtype == jnp.float32
    # non-real inputs are rejected outright rather than silently cast
    with pytest.raises(TypeError, match='floating'):
        factors.batched_eigh(jnp.eye(4, dtype=jnp.int32), impl='host')


def test_gershgorin_condition_bound_bounds_true_condition():
    f = _random_spd(32, 23)
    damping = 0.01
    m = f + damping * np.eye(32, dtype=np.float32)
    true_cond = np.linalg.cond(m)
    bound = float(factors.gershgorin_condition_bound(jnp.asarray(f), damping))
    assert bound >= true_cond * 0.99, (bound, true_cond)
    # and it is not absurdly loose: within d * kappa
    assert bound <= true_cond * 32, (bound, true_cond)


def test_gershgorin_condition_bound_finite_at_zero_damping():
    """damping == 0 must saturate, not divide by zero: an inf (or 0/0 nan)
    bound would poison every downstream comparison in the health sentinel
    (inf * 0 in jnp.where, threshold compares)."""
    f = _random_spd(8, 5)
    bound = factors.gershgorin_condition_bound(jnp.asarray(f), 0.0)
    assert bool(jnp.isfinite(bound))
    # saturated: huge enough that any sane quarantine_threshold flags it
    assert float(bound) > 1e30
    # batched, with a per-matrix damping vector mixing zero and nonzero
    stack = jnp.stack([jnp.asarray(f)] * 3)
    damp = jnp.asarray([0.0, 1e-3, 1.0], jnp.float32)
    bounds = factors.gershgorin_condition_bound(stack, damp)
    assert bounds.shape == (3,)
    assert bool(jnp.isfinite(bounds).all())
    assert float(bounds[0]) > float(bounds[1]) > float(bounds[2])
    # a NaN factor still fails closed: NaN bound compares False vs any
    # threshold, so factor_ok quarantines it (health.factor_ok contract)
    nan_bound = factors.gershgorin_condition_bound(
        jnp.asarray(f) + jnp.nan, 0.01
    )
    assert not bool(nan_bound <= 1e8)


def test_eig_host_matches_eigh_on_symmetric():
    """The non-symmetric escape hatch (reference kfac/layers/eigen.py:
    295-348 symmetric=False, torch.linalg.eig real-part): on an actually
    symmetric factor it must agree with eigh up to eigenvector sign."""
    rng = np.random.default_rng(7)
    m = rng.normal(size=(12, 6)).astype(np.float32)
    cov = jnp.asarray(m.T @ m / 12)
    d_ref, q_ref = factors.batched_eigh(cov, impl='host')
    d_eig, q_eig = factors.batched_eigh(cov, impl='eig_host')
    np.testing.assert_allclose(np.asarray(d_eig), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-5)
    # eigenvectors match up to per-column sign
    dots = np.abs(np.sum(np.asarray(q_eig) * np.asarray(q_ref), axis=0))
    np.testing.assert_allclose(dots, np.ones(6), atol=1e-4)


def test_eig_host_handles_nonsymmetric_real_parts():
    """A factor that drifted numerically non-symmetric still decomposes
    (real parts, ascending order) instead of silently assuming symmetry."""
    rng = np.random.default_rng(8)
    m = rng.normal(size=(10, 5)).astype(np.float32)
    cov = m.T @ m / 10
    skew = cov + 1e-3 * rng.normal(size=(5, 5)).astype(np.float32)
    d, q = jax.jit(
        lambda c: factors.batched_eigh(c, impl='eig_host')
    )(jnp.asarray(skew))
    d, q = np.asarray(d), np.asarray(q)
    assert np.all(np.diff(d) >= 0)  # ascending, eigh convention
    assert d.dtype == np.float32 and q.dtype == np.float32
    # real-part eigenpairs still nearly diagonalize the nearly-symmetric
    # factor: reconstruction error at the perturbation scale
    recon = q @ np.diag(d) @ np.linalg.inv(q)
    assert np.abs(recon - skew).max() < 1e-2


def test_batched_eigh_rejects_unknown_impl():
    with pytest.raises(ValueError):
        factors.batched_eigh(jnp.eye(3), impl='cuda')


def test_newton_schulz_differentiable_variant():
    """The fixed-trip scan variant matches the while_loop outputs and is
    reverse-differentiable (the while_loop path has no transpose rule)."""
    rng = np.random.default_rng(9)
    m = rng.normal(size=(32, 8)).astype(np.float32)
    cov = jnp.asarray(m.T @ m / 32)
    info_w = factors.newton_schulz_inverse_info(cov, 0.01)
    info_s = factors.newton_schulz_inverse_info(cov, 0.01, differentiable=True)
    np.testing.assert_allclose(
        np.asarray(info_s.inverse), np.asarray(info_w.inverse),
        rtol=1e-6, atol=1e-7,
    )
    assert int(info_s.iterations) == int(info_w.iterations)
    np.testing.assert_allclose(
        float(info_s.residual), float(info_w.residual), rtol=1e-5, atol=1e-8
    )

    # reverse mode works through the scan variant...
    def loss(c):
        return jnp.sum(
            factors.newton_schulz_inverse(c, 0.01, differentiable=True)
        )

    g = jax.grad(loss)(cov)
    assert np.all(np.isfinite(np.asarray(g)))
    # ...and the gradient is correct: d/dc sum(inv(c+dI)) via the identity
    # d(M^-1) = -M^-1 dM M^-1  =>  grad = -(M^-T 1 M^-T)
    inv = np.linalg.inv(np.asarray(cov) + 0.01 * np.eye(8))
    expected = -(inv.T @ np.ones((8, 8)) @ inv.T)
    np.testing.assert_allclose(np.asarray(g), expected, rtol=1e-3, atol=1e-4)

    # the while_loop path indeed cannot transpose (documents the contract)
    with pytest.raises(Exception):
        jax.grad(
            lambda c: jnp.sum(factors.newton_schulz_inverse(c, 0.01))
        )(cov)
