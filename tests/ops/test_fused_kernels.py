"""Fused step-path kernels (docs/ARCHITECTURE.md "Fused step-path
kernels"): equivalence contracts, dispatch gates, threshold derivation,
autotune FLOP parity (KFL205-style), and the two lint rules that pin the
family (KFL110 doc drift, KFL206 kernel allowlist).

Everything runs in Pallas interpret mode (CPU backend); the shared
inputs and the expensive fused/unfused result pairs are module-scope
fixtures so each kernel compiles once per session.
"""

import json
import warnings as pywarnings
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kfac_tpu import warnings as kfac_warnings
from kfac_tpu.ops import dispatch_tables, factors, pallas_cov_ema, pallas_ns
from kfac_tpu.ops.cov import get_cov
from kfac_tpu.ops.pallas_cov import K_BLOCK, TILE

BETA = 0.95
N, D = 512, 256


# ----------------------------------------------------- shared inputs


@pytest.fixture(scope='module')
def rng():
    return np.random.default_rng(20260806)


@pytest.fixture(scope='module')
def cov_inputs(rng):
    a = jnp.asarray(rng.standard_normal((N, D)), jnp.float32)
    f = jnp.asarray(rng.standard_normal((D, D)), jnp.float32)
    f = 0.5 * (f + f.T)  # the running factor is symmetric by invariant
    return f, a


@pytest.fixture(scope='module')
def cov_ema_pair(cov_inputs):
    """(fused, unfused) cov+EMA results — one compile each, shared by
    the equivalence and symmetry tests."""
    f, a = cov_inputs
    coeff = (1.0 - BETA) / N
    fused = pallas_cov_ema._fused(f, a, BETA, coeff, interpret=True)
    unfused = factors.ema_update(f, get_cov(a, scale=N), BETA)
    return np.asarray(fused), np.asarray(unfused)


@pytest.fixture(scope='module')
def ns_problem(rng):
    """Damped SPD factor + Gershgorin cold start, as
    ``newton_schulz_inverse_info`` sets them up."""
    d = 256
    g = jnp.asarray(rng.standard_normal((d, d)), jnp.float32)
    m = g @ g.T / d + 0.1 * jnp.eye(d, dtype=jnp.float32)
    x0 = jnp.eye(d, dtype=jnp.float32) / jnp.max(
        jnp.sum(jnp.abs(m), axis=1)
    )
    return m, x0


@pytest.fixture(scope='module')
def ns_chains(ns_problem):
    """Three fused iterations next to the unfused body they replace."""
    m, x0 = ns_problem
    d = m.shape[0]
    eye = jnp.eye(d, dtype=jnp.float32)

    def unfused_step(x, mx):
        x_new = x @ (2.0 * eye - mx)
        mx_new = m @ x_new
        resid = jnp.linalg.norm(eye - mx_new) / jnp.sqrt(
            jnp.asarray(d, jnp.float32)
        )
        return x_new, mx_new, resid

    fused, unfused = [], []
    xf = xu = x0
    mxf = mxu = m @ x0
    for _ in range(3):
        xf, mxf, rf = pallas_ns.fused_ns_step(m, xf, mxf, interpret=True)
        fused.append((np.asarray(xf), np.asarray(mxf), float(rf)))
        xu, mxu, ru = unfused_step(xu, mxu)
        unfused.append((np.asarray(xu), np.asarray(mxu), float(ru)))
    return fused, unfused


# ----------------------------------------------------- cov+EMA fusion


def test_fused_cov_ema_matches_unfused_pair(cov_ema_pair):
    fused, unfused = cov_ema_pair
    np.testing.assert_allclose(fused, unfused, rtol=1e-5, atol=1e-5)


def test_fused_cov_ema_exactly_symmetric(cov_ema_pair):
    fused, _ = cov_ema_pair
    # mirror-the-upper-triangle construction: symmetry is exact, not
    # approximate — no defensive (C + C^T)/2 anywhere downstream
    assert np.array_equal(fused, fused.T)


def test_fused_cov_ema_padding_case(rng):
    # n, d both off the K_BLOCK/TILE grid: padded rows/cols contribute
    # exact zeros to the contraction and the pad-region EMA is cropped
    n, d = 640, 192
    assert n % K_BLOCK != 0 and d % TILE != 0
    a = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    f = jnp.asarray(rng.standard_normal((d, d)), jnp.float32)
    f = 0.5 * (f + f.T)
    coeff = (1.0 - BETA) / n
    fused = pallas_cov_ema._fused(f, a, BETA, coeff, interpret=True)
    unfused = factors.ema_update(f, get_cov(a, scale=n), BETA)
    np.testing.assert_allclose(fused, unfused, rtol=1e-5, atol=1e-5)
    assert np.array_equal(np.asarray(fused), np.asarray(fused).T)


def test_fused_cov_ema_stacked_vmap(rng):
    fs = jnp.asarray(rng.standard_normal((2, 128, 128)), jnp.float32)
    fs = 0.5 * (fs + jnp.swapaxes(fs, -1, -2))
    As = jnp.asarray(rng.standard_normal((2, 256, 128)), jnp.float32)
    coeff = (1.0 - BETA) / 256
    fused = jax.vmap(
        lambda f, a: pallas_cov_ema._fused(f, a, BETA, coeff, interpret=True)
    )(fs, As)
    unfused = jax.vmap(
        lambda f, a: factors.ema_update(f, get_cov(a, scale=256), BETA)
    )(fs, As)
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(unfused), rtol=1e-5, atol=1e-5
    )


def test_fused_cov_ema_dispatcher_falls_back_on_cpu(cov_inputs):
    # off-TPU the dispatcher must run literally the unfused pair, so the
    # outputs are bitwise identical — not merely allclose
    f, a = cov_inputs
    out = pallas_cov_ema.fused_cov_ema(f, a, BETA)
    ref = factors.ema_update(f, get_cov(a, scale=N), BETA)
    assert np.array_equal(np.asarray(out), np.asarray(ref))


def test_fused_cov_ema_cold_start_matches_ema_update(rng):
    a = jnp.asarray(rng.standard_normal((256, 64)), jnp.float32)
    out = pallas_cov_ema.fused_cov_ema(None, a, BETA)
    ref = factors.ema_update(None, get_cov(a, scale=256), BETA)
    assert np.array_equal(np.asarray(out), np.asarray(ref))


# ----------------------------------------------------- NS fusion


def test_fused_ns_chain_matches_unfused(ns_chains):
    fused, unfused = ns_chains
    for (xf, mxf, rf), (xu, mxu, ru) in zip(fused, unfused):
        np.testing.assert_allclose(xf, xu, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(mxf, mxu, rtol=1e-5, atol=1e-5)
        assert rf == pytest.approx(ru, rel=1e-4, abs=1e-6)


def test_fused_ns_residual_feeds_stopping_rule(ns_chains):
    # the stopping rule consumes a strictly-shrinking residual while the
    # iteration is in its quadratic phase; the fused in-pass reduction
    # must preserve that shape
    fused, _ = ns_chains
    resids = [r for _, _, r in fused]
    assert resids[0] > resids[1] > resids[2]


def test_fused_ns_stacked_vmap(rng):
    d = 128
    g = jnp.asarray(rng.standard_normal((2, d, d)), jnp.float32)
    m = g @ jnp.swapaxes(g, -1, -2) / d + 0.1 * jnp.eye(d)
    x0 = jnp.eye(d) / jnp.max(jnp.sum(jnp.abs(m), axis=-1), axis=-1)[
        :, None, None
    ]
    mx0 = m @ x0
    eye = jnp.eye(d, dtype=jnp.float32)
    xf, mxf, rf = jax.vmap(
        lambda mm, xx, mxmx: pallas_ns.fused_ns_step(
            mm, xx, mxmx, interpret=True
        )
    )(m, x0, mx0)
    xu = x0 @ (2.0 * eye - mx0)
    mxu = m @ xu
    ru = jnp.linalg.norm(eye - mxu, axis=(-2, -1)) / jnp.sqrt(
        jnp.asarray(d, jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(xf), np.asarray(xu), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(mxf), np.asarray(mxu), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(rf), np.asarray(ru), rtol=1e-4, atol=1e-6
    )


# ----------------------------------------------------- kl-clip fusion


def test_fused_klclip_dot_matches(rng):
    # rectangular + off-tile dims: zero padding is exact for the
    # multiply-reduce; tiled accumulation order differs from XLA's, so
    # allclose rather than bitwise
    p = jnp.asarray(rng.standard_normal((200, 72)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((200, 72)), jnp.float32)
    got = pallas_ns.fused_klclip_dot(p, g, interpret=True)
    want = jnp.sum(p * g)
    assert float(got) == pytest.approx(float(want), rel=1e-5, abs=1e-3)


def test_fused_klclip_scale_matches(rng):
    p = jnp.asarray(rng.standard_normal((200, 72)), jnp.float32)
    s = jnp.asarray(0.37, jnp.float32)
    got = pallas_ns.fused_klclip_scale(p, s, interpret=True)
    assert got.shape == p.shape
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(p * s), rtol=1e-6, atol=0
    )


# ----------------------------------------------------- dispatch gates


def test_gates_stay_off_cpu_even_when_enabled(monkeypatch):
    monkeypatch.setenv('KFAC_TPU_PALLAS', '1')
    assert not pallas_cov_ema.use_fused_cov_ema_for(4096, jnp.float32)
    assert not pallas_ns.use_fused_ns_for(4096)
    assert not pallas_ns.use_fused_klclip_for((4096, 4096))


def test_gate_win_regimes_under_committed_artifact(monkeypatch):
    """The committed artifact holds every fused family at its prior
    (cov_ema 256/f32, ns 512, klclip 512); faking the TPU backend pins
    the gates to exactly those regimes."""
    monkeypatch.setenv('KFAC_TPU_PALLAS', '1')
    monkeypatch.setattr(jax, 'default_backend', lambda: 'tpu')
    monkeypatch.setattr(jax, 'devices', lambda *a: [object()])
    assert pallas_cov_ema.use_fused_cov_ema_for(256, jnp.float32)
    assert not pallas_cov_ema.use_fused_cov_ema_for(128, jnp.float32)
    assert not pallas_cov_ema.use_fused_cov_ema_for(256, jnp.bfloat16)
    assert pallas_ns.use_fused_ns_for(512)
    assert not pallas_ns.use_fused_ns_for(384)   # below min_dim
    assert not pallas_ns.use_fused_ns_for(520)   # not whole-tile
    assert pallas_ns.use_fused_klclip_for((512, 512))
    assert pallas_ns.use_fused_klclip_for((1024, 256))  # same traffic
    assert not pallas_ns.use_fused_klclip_for((64, 64))
    assert not pallas_ns.use_fused_klclip_for((512, 512, 2))


@pytest.fixture
def contaminated_artifact(monkeypatch, tmp_path):
    """Point the gates at an artifact whose fused baselines are all
    latency-floor contaminated, with the warning dedupe reset."""
    art = json.loads(json.dumps(dispatch_tables.DEFAULTS))
    art['schema'] = dispatch_tables.SCHEMA_VERSION
    art['provenance'] = {'contaminated': {
        f'{fam}_unfused': {'contaminated': True, 'reason': 'flat'}
        for fam in ('cov_ema', 'ns', 'klclip')
    }}
    p = tmp_path / 'contaminated.json'
    p.write_text(json.dumps(art))
    monkeypatch.setenv(dispatch_tables.ENV_VAR, str(p))
    dispatch_tables.invalidate_cache()
    kfac_warnings.reset_dispatch_warnings()
    yield p
    dispatch_tables.invalidate_cache()
    kfac_warnings.reset_dispatch_warnings()


def test_gate_holds_on_contaminated_floor_and_warns_once(
    monkeypatch, contaminated_artifact
):
    monkeypatch.setenv('KFAC_TPU_PALLAS', '1')
    monkeypatch.setattr(jax, 'default_backend', lambda: 'tpu')
    monkeypatch.setattr(jax, 'devices', lambda *a: [object()])
    with pytest.warns(kfac_warnings.DispatchTableWarning) as rec:
        assert not pallas_cov_ema.use_fused_cov_ema_for(4096, jnp.float32)
    assert 'cov_ema_unfused' in str(rec[0].message)
    # once per family: the repeat gate check stays silent
    with pywarnings.catch_warnings():
        pywarnings.simplefilter('error')
        assert not pallas_cov_ema.use_fused_cov_ema_for(4096, jnp.float32)
    with pytest.warns(kfac_warnings.DispatchTableWarning):
        assert not pallas_ns.use_fused_ns_for(4096)
    with pytest.warns(kfac_warnings.DispatchTableWarning):
        assert not pallas_ns.use_fused_klclip_for((4096, 4096))


# ----------------------------------------------------- threshold derivation


def _fused_sweep(fam, unfused_ms, fused_ms, sizes=(256, 512, 1024, 2048)):
    suffix = '_f32' if fam == 'cov_ema' else ''
    return (
        [{'op': f'{fam}_unfused_{d}{suffix}', 'ms': unfused_ms(d)}
         for d in sizes]
        + [{'op': f'{fam}_fused_{d}{suffix}', 'ms': fused_ms(d)}
           for d in sizes]
    )


def test_derive_fused_holds_prior_on_contaminated_baseline():
    t = dispatch_tables.derive_tables(_fused_sweep(
        'ns', lambda d: 50.0 + d % 5, lambda d: 1.0))
    assert t['ns'] == dispatch_tables.DEFAULTS['ns']
    assert 'ns_unfused' in t['provenance']['contaminated']
    assert 'ns' in t['provenance']['held']


def test_derive_fused_moves_threshold_on_clean_win_suffix():
    t = dispatch_tables.derive_tables(_fused_sweep(
        'ns',
        lambda d: 0.001 * d ** 3 / 256 ** 2,
        lambda d: 9.0 if d < 1024 else 0.0002 * d ** 3 / 256 ** 2,
    ))
    assert t['ns']['min_dim'] == 1024
    assert t['provenance']['derived']['ns']['win_from_dim'] == 1024


def test_derive_fused_rejects_single_point_win():
    t = dispatch_tables.derive_tables(_fused_sweep(
        'cov_ema',
        lambda d: 0.01 * d * d / 256,
        lambda d: 9000.0 if d < 2048 else 1.0,
    ))
    assert t['cov_ema'] == dispatch_tables.DEFAULTS['cov_ema']
    assert 'cov_ema' in t['provenance']['held']


def test_derive_fused_rejects_non_suffix_wins():
    # wins at 256 and 512 but a loss at 2048: no clean win regime
    t = dispatch_tables.derive_tables(_fused_sweep(
        'klclip',
        lambda d: 0.004 * d * d / 256,
        lambda d: 0.5 if d <= 512 else 9000.0,
    ))
    assert t['klclip'] == dispatch_tables.DEFAULTS['klclip']
    assert 'no clean win regime' in t['provenance']['held']['klclip']


def test_committed_artifact_is_clean_and_has_fused_families():
    """Satellite: the committed thresholds were re-derived from a clean
    one-dispatch sweep — no contaminated baselines remain, every fused
    family has a row, and provenance names its source sweep."""
    tables = dispatch_tables.load_tables()
    assert tables.get('schema') == dispatch_tables.SCHEMA_VERSION
    for fam in ('cov_ema', 'ns', 'klclip'):
        assert 'min_dim' in tables[fam]
        assert dispatch_tables.floor_contaminated(fam) is None
    assert tables['provenance']['contaminated'] == {}
    assert tables['provenance']['source']['records'] > 0


# ----------------------------------------------------- autotune FLOP parity


def test_kfl205_fused_cov_ema_flop_parity(cov_inputs):
    """Jaxpr-counted MXU FLOPs (triangular executing subset of the
    launch grid × per-tile dot FLOPs) must equal the autotune price
    EXACTLY — the pricing model and the kernel share their geometry."""
    from kfac_tpu.analysis.ir import visitor
    from kfac_tpu.autotune import model

    f, a = cov_inputs
    jaxpr = jax.make_jaxpr(
        lambda ff, aa: pallas_cov_ema._fused(
            ff, aa, BETA, (1.0 - BETA) / N, interpret=True
        )
    )(f, a)
    (summary,) = [
        s for s in visitor.pallas_call_summaries(jaxpr)
        if s['name'] == '_sym_cov_ema_kernel'
    ]
    nblk_i, nblk_j, nk = summary['grid']
    assert nblk_i == nblk_j
    executing_tiles = nk * nblk_i * (nblk_i + 1) // 2
    counted = executing_tiles * summary['dot_flops_per_tile']
    assert counted == model.fused_cov_ema_flops(N, D)


def test_kfl205_fused_ns_flop_parity(ns_problem):
    from kfac_tpu.analysis.ir import visitor
    from kfac_tpu.autotune import model

    m, x0 = ns_problem
    d = m.shape[0]
    jaxpr = jax.make_jaxpr(
        lambda mm, xx, mxmx: pallas_ns.fused_ns_step(
            mm, xx, mxmx, interpret=True
        )
    )(m, x0, m @ x0)
    summaries = [
        s for s in visitor.pallas_call_summaries(jaxpr)
        if s['name'] in ('_ns_xupdate_kernel', '_ns_mx_resid_kernel')
    ]
    assert sorted(s['name'] for s in summaries) == [
        '_ns_mx_resid_kernel', '_ns_xupdate_kernel'
    ]
    counted = 0.0
    for s in summaries:
        ni, nj, nk = s['grid']
        counted += ni * nj * nk * s['dot_flops_per_tile']
    # one fused iteration == the unfused 2 matmuls == 4d^3: fusing
    # removes HBM traffic, never FLOPs, so decomp parity is preserved
    # by construction
    assert counted == model.fused_ns_iter_flops(d) == 4.0 * d ** 3


def test_fused_klclip_price_pads_to_tiles():
    from kfac_tpu.autotune import model

    assert model.fused_klclip_flops((512, 512)) == 3.0 * 512 * 512
    assert model.fused_klclip_flops((200, 72)) == 3.0 * 256 * 128


def test_fused_hbm_saved_is_one_f32_roundtrip():
    from kfac_tpu.autotune import model

    assert model.fused_cov_ema_hbm_saved(1024) == 8.0 * 1024 * 1024
    assert model.fused_ns_iter_hbm_saved(1024) == 8.0 * 1024 * 1024


# ----------------------------------------------------- lint rules


def test_kfl110_fused_dispatch_doc_in_sync():
    from kfac_tpu.analysis import drift

    assert drift.check_fused_dispatch_table() == []


def test_kfl110_detects_doc_drift(tmp_path):
    from kfac_tpu.analysis import drift

    doc = tmp_path / 'ARCH.md'
    doc.write_text(
        '### Fused-kernel dispatch families\n\n'
        '| family | kernel |\n|---|---|\n'
        '| `cov` | x |\n| `attn` | x |\n| `cov_ema` | x |\n'
        '| `klclip` | x |\n| `ghost` | x |\n'
    )
    problems = drift.check_fused_dispatch_table(str(doc))
    assert any('ns' in p and 'undocumented' in p for p in problems)
    assert any('ghost' in p for p in problems)


def test_kfl206_allowlist_passes_fused_kernels(ns_problem):
    from kfac_tpu.analysis.ir import rules

    m, x0 = ns_problem
    jaxpr = jax.make_jaxpr(
        lambda mm, xx, mxmx: pallas_ns.fused_ns_step(
            mm, xx, mxmx, interpret=True
        )
    )(m, x0, m @ x0)
    trace = SimpleNamespace(
        path='tests/fake.py', line=1, display='fake:step', jaxpr=jaxpr
    )
    suite = SimpleNamespace(traces=[trace], errors=[])
    assert rules.check_pallas_allowlist(suite) == []


def test_kfl206_flags_unlisted_kernel():
    from jax.experimental import pallas as pl

    from kfac_tpu.analysis.ir import rules

    def _rogue_kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0

    def run(x):
        return pl.pallas_call(
            _rogue_kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            interpret=True,
        )(x)

    jaxpr = jax.make_jaxpr(run)(jnp.zeros((8, 128), jnp.float32))
    trace = SimpleNamespace(
        path='tests/fake.py', line=1, display='fake:step', jaxpr=jaxpr
    )
    suite = SimpleNamespace(traces=[trace], errors=[])
    findings = rules.check_pallas_allowlist(suite)
    assert len(findings) == 1
    assert findings[0].code == 'KFL206'
    assert '_rogue_kernel' in findings[0].message
