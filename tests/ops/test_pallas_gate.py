"""The on-chip validation gate: Pallas kernels stay off the default TPU
path unless KFAC_TPU_PALLAS opts them in (VERDICT r4 item 2)."""

import pytest

from kfac_tpu.ops import pallas_attention, pallas_cov, pallas_gate


@pytest.mark.parametrize(
    'val,cov,attn',
    [
        (None, False, False),     # unset: default OFF
        ('0', False, False),
        ('', False, False),
        ('off', False, False),
        ('1', True, True),
        ('true', True, True),
        ('all', True, True),
        ('cov', True, False),
        ('attn', False, True),
        ('cov,attn', True, True),
        (' cov , attn ', True, True),
        ('bogus', False, False),
    ],
)
def test_enabled_parsing(monkeypatch, val, cov, attn):
    if val is None:
        monkeypatch.delenv('KFAC_TPU_PALLAS', raising=False)
    else:
        monkeypatch.setenv('KFAC_TPU_PALLAS', val)
    assert pallas_gate.enabled('cov') is cov
    assert pallas_gate.enabled('attn') is attn


def test_dispatch_stays_off_cpu_even_when_enabled(monkeypatch):
    # the gate only ever ADDS a restriction: enabling it off-TPU must not
    # flip the backend check
    monkeypatch.setenv('KFAC_TPU_PALLAS', '1')
    assert not pallas_cov.use_pallas_for(4096)
    assert not pallas_attention.use_flash_for(1024, 1024, 128)


def test_dispatch_gated_off_by_default(monkeypatch):
    monkeypatch.delenv('KFAC_TPU_PALLAS', raising=False)
    assert not pallas_cov.use_pallas_for(4096)
    assert not pallas_attention.use_flash_for(1024, 1024, 128)
