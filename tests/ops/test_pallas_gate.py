"""The Pallas dispatch gate: default ON since the round-5 on-chip
validation, with dispatch restricted to each kernel's measured win
regime; KFAC_TPU_PALLAS=0 restores the pure-XLA paths."""

import pytest

from kfac_tpu.ops import pallas_attention, pallas_cov, pallas_gate


@pytest.mark.parametrize(
    'val,cov,attn',
    [
        (None, True, True),       # unset: default ON (validated on-chip r5)
        ('0', False, False),
        ('', False, False),
        ('off', False, False),
        ('1', True, True),
        ('true', True, True),
        ('all', True, True),
        ('cov', True, False),
        ('attn', False, True),
        ('cov,attn', True, True),
        (' cov , attn ', True, True),
        ('bogus', False, False),
    ],
)
def test_enabled_parsing(monkeypatch, val, cov, attn):
    if val is None:
        monkeypatch.delenv('KFAC_TPU_PALLAS', raising=False)
    else:
        monkeypatch.setenv('KFAC_TPU_PALLAS', val)
    assert pallas_gate.enabled('cov') is cov
    assert pallas_gate.enabled('attn') is attn


def test_dispatch_stays_off_cpu_even_when_enabled(monkeypatch):
    # the gate only ever ADDS a restriction: enabling it off-TPU must not
    # flip the backend check
    import jax.numpy as jnp

    monkeypatch.setenv('KFAC_TPU_PALLAS', '1')
    assert not pallas_cov.use_pallas_for(4096, jnp.float32)
    assert not pallas_attention.use_flash_for(1024, 1024, 128)


def test_dispatch_default_on_but_cpu_backend_off(monkeypatch):
    # default gate is ON since the round-5 on-chip validation, but the
    # CPU test backend still never dispatches
    monkeypatch.delenv('KFAC_TPU_PALLAS', raising=False)
    import jax.numpy as jnp

    assert pallas_gate.enabled('cov') and pallas_gate.enabled('attn')
    assert not pallas_cov.use_pallas_for(4096, jnp.float32)
    assert not pallas_attention.use_flash_for(1024, 1024, 128)


def test_dispatch_win_regimes(monkeypatch):
    """Measured win regimes (BENCH_TPU.md): cov f32-only; flash s_k>=2048.
    Verified by faking the TPU backend check."""
    import jax as _jax
    import jax.numpy as jnp

    monkeypatch.setenv('KFAC_TPU_PALLAS', '1')
    monkeypatch.setattr(_jax, 'default_backend', lambda: 'tpu')
    # single-device process (the real tunnel): mesh-less dispatch allowed
    monkeypatch.setattr(_jax, 'devices', lambda *a: [object()])
    assert pallas_cov.use_pallas_for(4096, jnp.float32)     # f32: win
    assert not pallas_cov.use_pallas_for(4096, jnp.bfloat16)  # bf16: loss
    assert not pallas_cov.use_pallas_for(128, jnp.float32)  # < 2 tiles
    # dense path: XLA's fused attention wins below s=2048 (measured)
    assert pallas_attention.use_flash_for(2048, 2048, 128, dense=True)
    assert not pallas_attention.use_flash_for(512, 512, 128, dense=True)
    # blockwise-partials path (ring steps): no length floor — the
    # alternative is the unfused einsum partials the kernel beat 300x
    assert pallas_attention.use_flash_for(512, 512, 128)


def test_mosaic_context_guard(monkeypatch):
    """Raw Mosaic calls cannot be auto-partitioned (measured on-chip:
    NotImplementedError from a flash dispatch inside the pipeline's
    partial shard_map). The dispatch heuristics must refuse
    partial-manual contexts and allow fully-manual ones."""
    import jax as _jax
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    monkeypatch.setenv('KFAC_TPU_PALLAS', '1')
    monkeypatch.setattr(_jax, 'default_backend', lambda: 'tpu')

    mesh = Mesh(np.array(_jax.devices()).reshape(4, 2), ('a', 'b'))
    n_real_devices = len(_jax.devices())
    seen = {}

    def body_full(x):
        seen['full'] = pallas_attention.use_flash_for(512, 512, 128)
        return x

    def body_partial(x):
        seen['partial'] = pallas_attention.use_flash_for(512, 512, 128)
        return x

    x = np.zeros((8, 8), np.float32)
    _jax.eval_shape(
        _jax.shard_map(body_full, mesh=mesh, in_specs=P('a', 'b'),
                       out_specs=P('a', 'b')), x)
    _jax.eval_shape(
        _jax.shard_map(body_partial, mesh=mesh, in_specs=P('a', None),
                       out_specs=P('a', None), axis_names={'a'}), x)
    assert seen['full'] is True       # fully-manual: kernel allowed
    assert seen['partial'] is False   # partial-manual: einsum fallback
    # no mesh + multi-device process: inputs may arrive sharded via
    # device_put(NamedSharding) with no mesh context — refuse
    assert n_real_devices > 1
    assert not pallas_attention.use_flash_for(512, 512, 128)
    # no mesh + single device: plain jit — allowed
    monkeypatch.setattr(_jax, 'devices', lambda *a: [object()])
    assert pallas_attention.use_flash_for(512, 512, 128)


def test_get_cov_partial_manual_falls_back_to_xla(monkeypatch):
    """get_cov inside a partial-manual shard_map must use the XLA
    contraction (neither kernel form can trace there) and still produce
    the exact symmetric covariance."""
    import jax as _jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from kfac_tpu.ops import cov as cov_lib

    monkeypatch.setenv('KFAC_TPU_PALLAS', '1')
    # force the size/dtype heuristic on so only the context logic decides
    monkeypatch.setattr(pallas_cov, 'use_pallas_for',
                        lambda d, dtype: True)

    mesh = Mesh(np.array(_jax.devices()).reshape(4, 2), ('a', 'b'))
    a = _jax.random.normal(_jax.random.PRNGKey(0), (64, 32), jnp.float32)

    def body(x):
        # rows sharded over manual axis 'a'; axis 'b' stays automatic
        c = cov_lib.get_cov(x, scale=64.0)
        return _jax.lax.psum(c, 'a')

    got = _jax.jit(
        _jax.shard_map(body, mesh=mesh, in_specs=P('a', None),
                       out_specs=P(None, None), axis_names={'a'},
                       check_vma=False)
    )(a)
    ref = np.asarray(a).T @ (np.asarray(a) / 64.0)
    ref = (ref + ref.T) / 2
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5, atol=1e-6)
