"""The Pallas dispatch gate: default ON since the round-5 on-chip
validation, with dispatch restricted to each kernel's measured win
regime; KFAC_TPU_PALLAS=0 restores the pure-XLA paths."""

import pytest

from kfac_tpu.ops import pallas_attention, pallas_cov, pallas_gate


@pytest.mark.parametrize(
    'val,cov,attn',
    [
        (None, True, True),       # unset: default ON (validated on-chip r5)
        ('0', False, False),
        ('', False, False),
        ('off', False, False),
        ('1', True, True),
        ('true', True, True),
        ('all', True, True),
        ('cov', True, False),
        ('attn', False, True),
        ('cov,attn', True, True),
        (' cov , attn ', True, True),
        ('bogus', False, False),
    ],
)
def test_enabled_parsing(monkeypatch, val, cov, attn):
    if val is None:
        monkeypatch.delenv('KFAC_TPU_PALLAS', raising=False)
    else:
        monkeypatch.setenv('KFAC_TPU_PALLAS', val)
    assert pallas_gate.enabled('cov') is cov
    assert pallas_gate.enabled('attn') is attn


def test_dispatch_stays_off_cpu_even_when_enabled(monkeypatch):
    # the gate only ever ADDS a restriction: enabling it off-TPU must not
    # flip the backend check
    import jax.numpy as jnp

    monkeypatch.setenv('KFAC_TPU_PALLAS', '1')
    assert not pallas_cov.use_pallas_for(4096, jnp.float32)
    assert not pallas_attention.use_flash_for(1024, 1024, 128)


def test_dispatch_default_on_but_cpu_backend_off(monkeypatch):
    # default gate is ON since the round-5 on-chip validation, but the
    # CPU test backend still never dispatches
    monkeypatch.delenv('KFAC_TPU_PALLAS', raising=False)
    import jax.numpy as jnp

    assert pallas_gate.enabled('cov') and pallas_gate.enabled('attn')
    assert not pallas_cov.use_pallas_for(4096, jnp.float32)
    assert not pallas_attention.use_flash_for(1024, 1024, 128)


def test_dispatch_win_regimes(monkeypatch):
    """Measured win regimes (BENCH_TPU.md): cov f32-only; flash s_k>=2048.
    Verified by faking the TPU backend check."""
    import jax as _jax
    import jax.numpy as jnp

    monkeypatch.setenv('KFAC_TPU_PALLAS', '1')
    monkeypatch.setattr(_jax, 'default_backend', lambda: 'tpu')
    assert pallas_cov.use_pallas_for(4096, jnp.float32)     # f32: win
    assert not pallas_cov.use_pallas_for(4096, jnp.bfloat16)  # bf16: loss
    assert not pallas_cov.use_pallas_for(128, jnp.float32)  # < 2 tiles
    # dense path: XLA's fused attention wins below s=2048 (measured)
    assert pallas_attention.use_flash_for(2048, 2048, 128, dense=True)
    assert not pallas_attention.use_flash_for(512, 512, 128, dense=True)
    # blockwise-partials path (ring steps): no length floor — the
    # alternative is the unfused einsum partials the kernel beat 300x
    assert pallas_attention.use_flash_for(512, 512, 128)
