"""Unit tests for the fused vocab-parallel NLL."""

import jax
import jax.numpy as jnp
import numpy as np

from kfac_tpu.ops import losses


def _naive_nll(logits, targets):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]


def test_vocab_parallel_nll_matches_log_softmax_gather():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(4, 16, 64)).astype(np.float32))
    targets = jnp.asarray(rng.integers(0, 64, size=(4, 16)))
    np.testing.assert_allclose(
        np.asarray(losses.vocab_parallel_nll(logits, targets)),
        np.asarray(_naive_nll(logits, targets)),
        rtol=1e-5, atol=1e-6,
    )


def test_vocab_parallel_nll_gradient_is_softmax_minus_onehot():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(8, 32)).astype(np.float32))
    targets = jnp.asarray(rng.integers(0, 32, size=(8,)))
    grad = jax.grad(lambda l: jnp.sum(losses.vocab_parallel_nll(l, targets)))(
        logits
    )
    expected = jax.nn.softmax(logits, axis=-1) - jax.nn.one_hot(
        targets, 32, dtype=jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(grad), np.asarray(expected), rtol=1e-5, atol=1e-6
    )
    # and it matches autodiff of the naive form
    naive_grad = jax.grad(
        lambda l: jnp.sum(_naive_nll(l, targets))
    )(logits)
    np.testing.assert_allclose(
        np.asarray(grad), np.asarray(naive_grad), rtol=1e-5, atol=1e-6
    )


def test_vocab_parallel_nll_stable_at_large_logits():
    """The max-shift must prevent overflow for bf16-scale logit magnitudes."""
    logits = jnp.asarray([[1e4, 1e4 - 5.0, 0.0]], jnp.float32)
    targets = jnp.asarray([0])
    nll = np.asarray(losses.vocab_parallel_nll(logits, targets))
    assert np.isfinite(nll).all()
    # both NLL terms are computed in max-shifted space, so the result is
    # accurate to fp32 eps even though the raw logits sit at 1e4
    np.testing.assert_allclose(nll[0], np.log1p(np.exp(-5.0)), rtol=1e-4)


def test_vocab_parallel_nll_bf16_logits_reduce_in_fp32():
    rng = np.random.default_rng(2)
    logits32 = rng.normal(size=(4, 48)).astype(np.float32)
    targets = jnp.asarray(rng.integers(0, 48, size=(4,)))
    out = losses.vocab_parallel_nll(
        jnp.asarray(logits32, jnp.bfloat16), targets
    )
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_naive_nll(jnp.asarray(logits32), targets)),
        rtol=0.05, atol=0.05,  # bf16 logit rounding only
    )
