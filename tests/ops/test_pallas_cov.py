"""Triangular Pallas covariance kernel vs dense oracle (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kfac_tpu.ops import pallas_cov


@pytest.mark.parametrize(
    'n,d',
    [(64, 96), (512, 128), (700, 300), (1024, 256)],
)
def test_sym_cov_matches_dense(n, d):
    rng = np.random.default_rng(0)
    a = rng.normal(size=(n, d)).astype(np.float32)
    got = pallas_cov.sym_cov(jnp.asarray(a), interpret=True)
    expected = a.T @ a / n
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-4)
    # exact symmetry by construction
    np.testing.assert_array_equal(np.asarray(got), np.asarray(got).T)


def test_sym_cov_scale_and_dtype():
    rng = np.random.default_rng(1)
    a = rng.normal(size=(130, 140)).astype(np.float32)
    got = pallas_cov.sym_cov(jnp.asarray(a, jnp.bfloat16), scale=10.0, interpret=True)
    assert got.dtype == jnp.bfloat16
    expected = a.T @ a / 10.0
    np.testing.assert_allclose(
        np.asarray(got, np.float32), expected, rtol=0.05, atol=0.5
    )


def test_use_pallas_heuristic_cpu_off():
    # on the CPU test backend the dispatch heuristic must stay off
    import jax.numpy as jnp
    assert not pallas_cov.use_pallas_for(4096, jnp.float32)


def test_sym_cov_spmd_row_sharded_matches_dense():
    """The custom_partitioning wrapper: row-sharded input -> local kernel +
    psum, result equal to the dense covariance (interpret mode on CPU)."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(8), ('x',))
    a = jax.random.normal(jax.random.PRNGKey(0), (128, 48))
    a_sharded = jax.device_put(a, NamedSharding(mesh, P('x', None)))
    out = jax.jit(pallas_cov.sym_cov_spmd)(a_sharded)
    ref = np.asarray(a).T @ np.asarray(a)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out).T)


def test_get_cov_dispatches_to_pallas(monkeypatch):
    """With the heuristic forced on, get_cov must route through the kernel
    in jit (spmd wrapper) and inside shard_map (direct local kernel), both
    matching the XLA contraction."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from kfac_tpu.ops import cov

    monkeypatch.setattr(pallas_cov, 'use_pallas_for',
                        lambda d, dtype=None: True)
    a = jax.random.normal(jax.random.PRNGKey(1), (64, 32))
    ref = np.asarray(a).T @ (np.asarray(a) / 64)
    ref = (ref + ref.T) / 2

    mesh = Mesh(np.array(jax.devices()).reshape(8), ('x',))
    a_sharded = jax.device_put(a, NamedSharding(mesh, P('x', None)))
    out_jit = jax.jit(cov.get_cov)(a_sharded)
    np.testing.assert_allclose(np.asarray(out_jit), ref, rtol=1e-5, atol=1e-4)

    def body(a_local):
        c = cov.get_cov(a_local, scale=1.0)  # local rows, unscaled
        return jax.lax.psum(c, 'x')

    out_sm = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=P('x', None), out_specs=P()
        )
    )(a_sharded) / 64
    np.testing.assert_allclose(np.asarray(out_sm), ref, rtol=1e-5, atol=1e-4)


def test_sym_cov_spmd_replicated_and_feature_sharded():
    """Edge shardings the partition callback must handle: fully replicated
    (rank-0 PartitionSpec) and feature-sharded (gathered, never propagated
    into C's dims)."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(8), ('x',))
    a = jax.random.normal(jax.random.PRNGKey(2), (96, 40))
    ref = np.asarray(a).T @ np.asarray(a)
    for spec in (P(), P(None, 'x')):
        a_s = jax.device_put(a, NamedSharding(mesh, spec))
        out = jax.jit(pallas_cov.sym_cov_spmd)(a_s)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-4)
