"""Triangular Pallas covariance kernel vs dense oracle (interpret mode)."""

import jax.numpy as jnp
import numpy as np
import pytest

from kfac_tpu.ops import pallas_cov


@pytest.mark.parametrize(
    'n,d',
    [(64, 96), (512, 128), (700, 300), (1024, 256)],
)
def test_sym_cov_matches_dense(n, d):
    rng = np.random.default_rng(0)
    a = rng.normal(size=(n, d)).astype(np.float32)
    got = pallas_cov.sym_cov(jnp.asarray(a), interpret=True)
    expected = a.T @ a / n
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-4)
    # exact symmetry by construction
    np.testing.assert_array_equal(np.asarray(got), np.asarray(got).T)


def test_sym_cov_scale_and_dtype():
    rng = np.random.default_rng(1)
    a = rng.normal(size=(130, 140)).astype(np.float32)
    got = pallas_cov.sym_cov(jnp.asarray(a, jnp.bfloat16), scale=10.0, interpret=True)
    assert got.dtype == jnp.bfloat16
    expected = a.T @ a / 10.0
    np.testing.assert_allclose(
        np.asarray(got, np.float32), expected, rtol=0.05, atol=0.5
    )


def test_use_pallas_heuristic_cpu_off():
    # on the CPU test backend the dispatch heuristic must stay off
    assert not pallas_cov.use_pallas_for(4096)
