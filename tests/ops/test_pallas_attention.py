"""Flash-attention kernel tests (interpret mode on the CPU mesh).

The kernel computes the same blockwise-softmax partials as
attend_partials_einsum — exactness is the contract that makes the
custom_vjp pairing (kernel forward / einsum backward) valid.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kfac_tpu.models import attention as att
from kfac_tpu.ops import pallas_attention as pa


def _qkv(b=2, s=256, h=2, d=128, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape) for k in ks)


def test_flash_causal_matches_dense():
    q, k, v = _qkv()
    out = att._finish(
        pa.flash_attention_partials(q, k, v, causal=True, interpret=True)
    )
    want = att.dense_causal_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), atol=1e-5
    )


def test_flash_noncausal_matches_softmax():
    q, k, v = _qkv(seed=1)
    out = att._finish(
        pa.flash_attention_partials(q, k, v, causal=False, interpret=True)
    )
    logits = jnp.einsum('bqhd,bkhd->bhqk', q * q.shape[-1] ** -0.5, k)
    probs = jax.nn.softmax(logits, -1)
    want = jnp.einsum('bhqk,bkhd->bqhd', probs, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), atol=1e-5
    )


@pytest.mark.parametrize('q_off,k_off', [(128, 0), (0, 0), (384, 128)])
def test_flash_ring_chunk_partials_match_einsum(q_off, k_off):
    """Exactness vs the einsum implementation at ring offsets — acc, m,
    and l all byte-match so cross-step _merge sees identical inputs."""
    q, k, v = _qkv(s=128, seed=2)
    got = pa.flash_attention_partials(
        q, k, v, q_offset=q_off, k_offset=k_off, causal=True, interpret=True
    )
    want = pa.attend_partials_einsum(q, k, v, q_off, k_off, True)
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=1e-5
        )


def test_flash_fully_masked_chunk_is_zero():
    """A K chunk entirely after the Q chunk contributes nothing (the
    kernel's dynamic tile bound skips it outright)."""
    q, k, v = _qkv(s=128, seed=3)
    acc, m, l = pa.flash_attention_partials(
        q, k, v, q_offset=0, k_offset=128, causal=True, interpret=True
    )
    assert float(jnp.abs(l).max()) == 0.0
    assert float(jnp.abs(acc).max()) == 0.0


def test_flash_gradients_match_einsum_path():
    """custom_vjp: gradients through the kernel equal gradients through
    the einsum implementation."""
    q, k, v = _qkv(s=128, seed=4)

    def loss_flash(q, k, v):
        out = att._finish(pa.flash_attention_partials(
            q, k, v, causal=True, interpret=True))
        return jnp.sum(out ** 2)

    def loss_einsum(q, k, v):
        out = att._finish(pa.attend_partials_einsum(q, k, v, 0, 0, True))
        return jnp.sum(out ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    ge = jax.grad(loss_einsum, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, ge):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


def test_flash_rejects_unaligned_sequence():
    q, k, v = _qkv(s=100, seed=5)
    with pytest.raises(ValueError):
        pa.flash_attention_partials(q, k, v, interpret=True)
