"""Unit tests for covariance numerics.

Behavioral parity targets: the value tables exercised by the reference's
tests/layers/utils_test.py and modules_test.py, re-derived by hand (and via
an independent torch oracle for conv patches) — not ported code.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kfac_tpu.ops import cov


def test_append_bias_ones():
    x = jnp.arange(12, dtype=jnp.float32).reshape(4, 3)
    out = cov.append_bias_ones(x)
    assert out.shape == (4, 4)
    np.testing.assert_allclose(out[:, -1], np.ones(4))
    np.testing.assert_allclose(out[:, :3], x)


def test_append_bias_ones_3d():
    x = jnp.ones((2, 3, 5))
    out = cov.append_bias_ones(x)
    assert out.shape == (2, 3, 6)


def test_get_cov_self_matches_manual():
    rng = np.random.default_rng(0)
    a = rng.normal(size=(8, 5)).astype(np.float32)
    expected = a.T @ a / 8
    expected = (expected + expected.T) / 2
    got = cov.get_cov(jnp.asarray(a))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-6)


def test_get_cov_symmetry():
    rng = np.random.default_rng(1)
    a = rng.normal(size=(16, 7)).astype(np.float32)
    got = np.asarray(cov.get_cov(jnp.asarray(a)))
    np.testing.assert_allclose(got, got.T, rtol=0, atol=0)


def test_get_cov_pair_and_scale():
    rng = np.random.default_rng(2)
    a = rng.normal(size=(4, 3)).astype(np.float32)
    b = rng.normal(size=(4, 3)).astype(np.float32)
    got = cov.get_cov(jnp.asarray(a), jnp.asarray(b), scale=2.0)
    np.testing.assert_allclose(got, a.T @ b / 2.0, rtol=1e-5)


def test_get_cov_rejects_bad_shapes():
    with pytest.raises(ValueError):
        cov.get_cov(jnp.ones((2, 3, 4)))
    with pytest.raises(ValueError):
        cov.get_cov(jnp.ones((2, 3)), jnp.ones((3, 2)))


def test_reshape_data_concat_and_collapse():
    xs = [jnp.ones((2, 3, 4)), jnp.ones((2, 3, 4))]
    out = cov.reshape_data(xs, batch_first=True, collapse_dims=True)
    assert out.shape == (12, 4)
    out2 = cov.reshape_data(xs, batch_first=False, collapse_dims=False)
    assert out2.shape == (2, 6, 4)


def test_linear_a_factor_hand_value():
    # a = [[1, 2]], bias -> rows [[1, 2, 1]]; cov = r^T r / 1
    a = jnp.asarray([[1.0, 2.0]])
    got = cov.linear_a_factor(a, has_bias=True)
    expected = np.asarray([[1, 2, 1], [2, 4, 2], [1, 2, 1]], dtype=np.float32)
    np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_linear_a_factor_flattens_sequence_dims():
    rng = np.random.default_rng(3)
    a3 = rng.normal(size=(2, 5, 4)).astype(np.float32)
    got = cov.linear_a_factor(jnp.asarray(a3), has_bias=False)
    flat = a3.reshape(-1, 4)
    expected = flat.T @ flat / 10
    expected = (expected + expected.T) / 2
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-6)


def test_linear_g_factor_hand_value():
    g = jnp.asarray([[1.0, -1.0], [3.0, 1.0]])
    got = cov.linear_g_factor(g)
    gn = np.asarray(g)
    expected = gn.T @ gn / 2
    np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_conv_patches_match_conv():
    """patches @ W_mat^T must equal the convolution itself (ordering check)."""
    rng = np.random.default_rng(4)
    x = rng.normal(size=(2, 6, 6, 3)).astype(np.float32)
    w = rng.normal(size=(3, 3, 3, 5)).astype(np.float32)  # HWIO
    out = jax.lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w), (1, 1), 'SAME',
        dimension_numbers=('NHWC', 'HWIO', 'NHWC'),
    )
    patches = cov.extract_patches_nhwc(jnp.asarray(x), (3, 3), (1, 1), 'SAME')
    w_mat = jnp.transpose(jnp.asarray(w), (3, 2, 0, 1)).reshape(5, -1)
    recon = (patches.reshape(-1, patches.shape[-1]) @ w_mat.T).reshape(out.shape)
    np.testing.assert_allclose(recon, out, rtol=1e-4, atol=1e-4)


def test_conv_patches_against_torch_unfold():
    """Independent oracle: torch's unfold-based im2col (CPU)."""
    torch = pytest.importorskip('torch')
    rng = np.random.default_rng(5)
    x = rng.normal(size=(2, 5, 5, 3)).astype(np.float32)  # NHWC
    patches = cov.extract_patches_nhwc(
        jnp.asarray(x), (3, 3), (2, 2), [(1, 1), (1, 1)]
    )
    xt = torch.from_numpy(x).permute(0, 3, 1, 2)  # NCHW
    unf = torch.nn.functional.unfold(xt, kernel_size=3, stride=2, padding=1)
    # unfold: (N, C*kh*kw, L) with C-major feature order -> (N, L, C*kh*kw)
    unf = unf.transpose(1, 2).numpy()
    got = np.asarray(patches).reshape(2, -1, patches.shape[-1])
    np.testing.assert_allclose(got, unf, rtol=1e-5, atol=1e-5)


def test_conv2d_a_factor_shape_and_spatial_norm():
    rng = np.random.default_rng(6)
    x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    got = cov.conv2d_a_factor(
        jnp.asarray(x), (3, 3), (1, 1), 'SAME', has_bias=True
    )
    assert got.shape == (3 * 9 + 1, 3 * 9 + 1)
    # manual: patches/spatial, bias ones/spatial, cov over N*oh*ow rows
    patches = np.asarray(
        cov.extract_patches_nhwc(jnp.asarray(x), (3, 3), (1, 1), 'SAME')
    )
    spatial = patches.shape[1] * patches.shape[2]
    rows = patches.reshape(-1, patches.shape[-1])
    rows = np.concatenate([rows, np.ones((rows.shape[0], 1), np.float32)], 1)
    rows = rows / spatial
    expected = rows.T @ rows / rows.shape[0]
    expected = (expected + expected.T) / 2
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-6)


def test_conv2d_g_factor_shape():
    rng = np.random.default_rng(7)
    g = rng.normal(size=(2, 4, 4, 6)).astype(np.float32)
    got = cov.conv2d_g_factor(jnp.asarray(g))
    assert got.shape == (6, 6)
    rows = g.reshape(-1, 6) / 16
    expected = rows.T @ rows / rows.shape[0]
    expected = (expected + expected.T) / 2
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-7)
