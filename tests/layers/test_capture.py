"""Curvature capture tests: A/G statistics must equal hand-derived values.

The G oracle uses the perturbation identity: adding an explicit zero epsilon
to a layer's output and differentiating the loss w.r.t. it yields dL/dy,
from which the expected G = cov(dL/dy) is computed independently of the
g-tap custom_vjp machinery.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from kfac_tpu.layers import capture as capture_lib
from kfac_tpu.layers import registry as registry_lib
from kfac_tpu.ops import cov
from testing import models


def _setup_tiny():
    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1), n=16, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = registry_lib.register_model(m, x)
    loss_fn = models.mse_loss(m)
    return m, params, (x, y), reg, loss_fn


def test_grads_match_plain_value_and_grad():
    m, params, batch, reg, loss_fn = _setup_tiny()
    cap = capture_lib.CurvatureCapture(reg)
    (loss, _), grads, _ = cap.value_stats_and_grad(loss_fn)(params, batch)
    loss0, grads0 = jax.value_and_grad(loss_fn)(params, batch)
    np.testing.assert_allclose(loss, loss0, rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(grads), jax.tree_util.tree_leaves(grads0)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_a_stats_match_manual():
    m, params, batch, reg, loss_fn = _setup_tiny()
    cap = capture_lib.CurvatureCapture(reg)
    _, _, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    x, _ = batch
    expected_fc1 = cov.linear_a_factor(x, has_bias=True)
    np.testing.assert_allclose(stats.a['fc1'], expected_fc1, rtol=1e-5, atol=1e-6)
    # fc2 input = relu(fc1(x))
    h = nn.relu(x @ params['fc1']['kernel'] + params['fc1']['bias'])
    expected_fc2 = cov.linear_a_factor(h, has_bias=True)
    np.testing.assert_allclose(stats.a['fc2'], expected_fc2, rtol=1e-5, atol=1e-6)


def test_g_stats_match_perturbation_oracle():
    m, params, batch, reg, loss_fn = _setup_tiny()
    cap = capture_lib.CurvatureCapture(reg)
    _, _, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    x, y = batch

    def loss_with_eps(eps1, eps2):
        h = x @ params['fc1']['kernel'] + params['fc1']['bias'] + eps1
        out = nn.relu(h) @ params['fc2']['kernel'] + params['fc2']['bias'] + eps2
        return jnp.mean((out - y) ** 2)

    e1 = jnp.zeros((x.shape[0], 8))
    e2 = jnp.zeros((x.shape[0], 4))
    g1, g2 = jax.grad(loss_with_eps, argnums=(0, 1))(e1, e2)
    np.testing.assert_allclose(
        stats.g['fc1'], cov.linear_g_factor(g1), rtol=1e-5, atol=1e-7
    )
    np.testing.assert_allclose(
        stats.g['fc2'], cov.linear_g_factor(g2), rtol=1e-5, atol=1e-7
    )


def test_capture_under_jit():
    m, params, batch, reg, loss_fn = _setup_tiny()
    cap = capture_lib.CurvatureCapture(reg)
    run = jax.jit(cap.value_stats_and_grad(loss_fn))
    (loss, _), grads, stats = run(params, batch)
    _, _, stats0 = cap.value_stats_and_grad(loss_fn)(params, batch)
    np.testing.assert_allclose(stats.a['fc1'], stats0.a['fc1'], rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(stats.g['fc2'], stats0.g['fc2'], rtol=1e-5, atol=1e-7)


def test_shared_module_accumulates():
    m = models.SharedDense()
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 5))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = registry_lib.register_model(m, x)
    assert set(reg.names()) == {'shared'}

    def loss_fn(p, xx):
        return jnp.sum(m.apply({'params': p}, xx) ** 2)

    cap = capture_lib.CurvatureCapture(reg)
    (_, _), _, stats = cap.value_stats_and_grad(loss_fn)(params, x)
    # A-stat should be the average of the two call-site A factors
    h = nn.relu(x @ params['shared']['kernel'] + params['shared']['bias'])
    expected = (
        cov.linear_a_factor(x, True) + cov.linear_a_factor(h, True)
    ) / 2
    np.testing.assert_allclose(stats.a['shared'], expected, rtol=1e-5, atol=1e-6)


def test_conv_capture_shapes():
    m = models.TinyConvNet()
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 32, 32, 1))
    y = jax.nn.one_hot(jnp.array([1, 2]), 10)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = registry_lib.register_model(m, x)

    def loss_fn(p, batch):
        xx, yy = batch
        logits = m.apply({'params': p}, xx)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * yy, axis=-1))

    cap = capture_lib.CurvatureCapture(reg)
    (_, _), grads, stats = cap.value_stats_and_grad(loss_fn)(params, (x, y))
    for name, h in reg.layers.items():
        assert stats.a[name].shape == h.a_factor_shape
        assert stats.g[name].shape == h.g_factor_shape
        assert not bool(jnp.isnan(stats.a[name]).any())
        assert not bool(jnp.isnan(stats.g[name]).any())
    # G stats should be nonzero (loss depends on every layer)
    assert float(jnp.abs(stats.g['conv1']).sum()) > 0


def test_grad_scale_unscaling():
    m, params, batch, reg, loss_fn = _setup_tiny()
    cap = capture_lib.CurvatureCapture(reg)

    def scaled_loss(p, b):
        return 128.0 * loss_fn(p, b)

    _, _, stats_scaled = cap.value_stats_and_grad(scaled_loss)(params, batch)
    _, _, stats = cap.value_stats_and_grad(loss_fn)(params, batch)
    unscaled = stats_scaled.scaled(128.0)
    np.testing.assert_allclose(
        unscaled.g['fc2'], stats.g['fc2'], rtol=1e-4, atol=1e-7
    )
    # A stats are unaffected by loss scaling
    np.testing.assert_allclose(stats_scaled.a['fc1'], stats.a['fc1'], rtol=1e-6)
