"""Registration tests (behavioral targets from reference
tests/layers/register_test.py: discovery, nesting, skip patterns)."""

import flax.linen as nn
import jax.numpy as jnp

from kfac_tpu.layers import helpers, registry
from testing import models


def test_register_tiny_model():
    m = models.TinyModel()
    reg = registry.register_model(m, jnp.ones((2, 6)))
    assert set(reg.names()) == {'fc1', 'fc2'}
    h1 = reg.layers['fc1']
    assert isinstance(h1, helpers.DenseHelper)
    assert h1.a_factor_shape == (7, 7)  # 6 in + bias
    assert h1.g_factor_shape == (8, 8)
    assert reg.param_paths['fc1'] == ('fc1',)


def test_register_conv_model():
    m = models.TinyConvNet()
    reg = registry.register_model(m, jnp.ones((2, 32, 32, 1)))
    assert set(reg.names()) == {'conv1', 'conv2', 'fc1', 'fc2'}
    c1 = reg.layers['conv1']
    assert isinstance(c1, helpers.Conv2dHelper)
    assert c1.a_factor_shape == (1 * 25 + 1, 1 * 25 + 1)
    assert c1.g_factor_shape == (6, 6)
    c2 = reg.layers['conv2']
    assert c2.a_factor_shape == (6 * 25 + 1, 6 * 25 + 1)


def test_register_nested_paths():
    class Block(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4, name='inner')(x)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = Block(name='b1')(x)
            return Block(name='b2')(x)

    reg = registry.register_model(Net(), jnp.ones((2, 4)))
    assert set(reg.names()) == {'b1/inner', 'b2/inner'}
    assert reg.param_paths['b1/inner'] == ('b1', 'inner')


def test_skip_patterns_by_name_and_class():
    m = models.TinyModel()
    reg = registry.register_model(m, jnp.ones((2, 6)), skip_layers=['fc1'])
    assert set(reg.names()) == {'fc2'}
    # class-name skip, case-insensitive-ish: class names are lowercased
    reg2 = registry.register_model(m, jnp.ones((2, 6)), skip_layers=['dense'])
    assert len(reg2) == 0


def test_skip_pattern_regex():
    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(4, name='attn_q')(x)
            x = nn.Dense(4, name='attn_k')(x)
            return nn.Dense(4, name='mlp')(x)

    reg = registry.register_model(Net(), jnp.ones((2, 4)), skip_layers=['attn.*'])
    assert set(reg.names()) == {'mlp'}


def test_no_bias_shapes():
    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4, use_bias=False, name='d')(x)

    reg = registry.register_model(Net(), jnp.ones((2, 5)))
    assert reg.layers['d'].a_factor_shape == (5, 5)


def test_slice_and_merge_roundtrip():
    m = models.TinyConvNet()
    import jax

    variables = m.init(jax.random.PRNGKey(0), jnp.ones((1, 32, 32, 1)))
    params = variables['params']
    reg = registry.register_model(m, jnp.ones((1, 32, 32, 1)))
    sliced = registry.slice_layer_grads(params, reg)
    assert set(sliced) == set(reg.names())
    merged = registry.merge_layer_grads(params, sliced, reg)
    flat1 = jax.tree_util.tree_leaves(params)
    flat2 = jax.tree_util.tree_leaves(merged)
    assert all((a == b).all() for a, b in zip(flat1, flat2))


def test_unsupported_conv_variants_not_registered():
    """Dilated, grouped, and exotic-padding convs stay unregistered instead of
    failing later in capture."""
    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Conv(4, (3, 3), kernel_dilation=2, name='dil')(x)
            x = nn.Conv(4, (3, 3), padding='CIRCULAR', name='circ')(x)
            x = nn.Conv(4, (3, 3), feature_group_count=2, name='grp')(x)
            return nn.Conv(4, (3, 3), name='ok')(x)

    from kfac_tpu.layers import registry as _r
    reg = _r.register_model(Net(), jnp.ones((1, 8, 8, 2)))
    assert set(reg.names()) == {'ok'}


def test_register_with_container_batch_arg():
    """Arrays nested in tuple/dict args are abstracted per-leaf (no real
    init compute at registration time)."""
    class Net(nn.Module):
        @nn.compact
        def __call__(self, batch, train=False):
            x = batch['x']
            return nn.Dense(4, name='d')(x)

    reg = registry.register_model(
        Net(), {'x': jnp.ones((2, 5))}, train=False
    )
    assert set(reg.names()) == {'d'}
