"""Compressed curvature collectives + cold-factor offload suite.

Covers the contracts of docs/ARCHITECTURE.md "Compression & offload":
quantization round-trip bounds, the >= 3x wire-ratio acceptance on the
bucketed transport, error-feedback durability across checkpoints,
bit-exactness of the offload round trip, knob validation, and the
autotuner integration (plan backward compat, HBM soft-constraint
fallback, model<->engine byte parity).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import kfac_tpu
from kfac_tpu import checkpoint, training
from kfac_tpu.autotune import model as model_lib
from kfac_tpu.autotune import plan as plan_lib
from kfac_tpu.autotune import search as search_lib
from kfac_tpu.compression import (
    CompressionConfig,
    OffloadConfig,
    dequantize_blockwise,
    error_bound,
    quantize_blockwise,
    wire_bytes,
)
from kfac_tpu.parallel import DistributedKFAC, kaisa_mesh
from testing import models

WORLD = 8

_HAS_FP8 = hasattr(jnp, 'float8_e4m3fn')
_DTYPES = ('int8', 'fp8') if _HAS_FP8 else ('int8',)


# ------------------------------------------------------------- quantization


@pytest.mark.parametrize('dtype', _DTYPES)
@pytest.mark.parametrize('block_size', [32, 256])
@pytest.mark.parametrize('n', [7, 256, 1000])
def test_quant_round_trip_within_bound(dtype, block_size, n):
    x = jax.random.normal(jax.random.PRNGKey(n), (n,)) * 3.0
    payload, scales = quantize_blockwise(x, dtype, block_size)
    assert payload.shape == (n,)
    deq = dequantize_blockwise(payload, scales, n, block_size)
    err = np.asarray(jnp.abs(deq - x))
    xb = np.asarray(x)
    for b in range(scales.shape[0]):
        blk = slice(b * block_size, min((b + 1) * block_size, n))
        amax = float(np.max(np.abs(xb[blk]))) if xb[blk].size else 0.0
        assert float(err[blk].max(initial=0.0)) <= error_bound(amax, dtype)


@pytest.mark.parametrize('dtype', _DTYPES)
def test_quant_all_zero_block_is_exact(dtype):
    x = jnp.zeros((300,))
    payload, scales = quantize_blockwise(x, dtype, 256)
    deq = dequantize_blockwise(payload, scales, 300, 256)
    np.testing.assert_array_equal(np.asarray(deq), 0.0)


def test_wire_bytes_trimmed_payload():
    # 119 elements in 256-wide blocks: 1 block, payload trimmed to 119
    wb = wire_bytes(119, 'int8', 256)
    assert wb == {
        'payload_bytes': 119, 'scale_bytes': 4, 'wire_bytes': 123}
    # ratio vs an f32 raw buffer clears 3x even on this tiny chunk
    assert 119 * 4 / wb['wire_bytes'] > 3.0


# ------------------------------------------------------------- config knobs


def _setup(frac=1.0, **cfg_kw):
    mesh = kaisa_mesh(grad_worker_fraction=frac)
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=WORLD * 8, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(registry=reg, damping=1e-3, **cfg_kw)
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    return m, params, (x, y), reg, cfg, dk, models.mse_loss(m)


def _reg():
    m = models.TinyModel(hidden=8, out=4)
    x, _ = models.regression_data(jax.random.PRNGKey(1), n=64, dim=6)
    return kfac_tpu.register_model(m, x)


def test_compression_requires_bucketed_transport():
    with pytest.raises(ValueError, match='allreduce_bucketed'):
        kfac_tpu.KFACPreconditioner(
            registry=_reg(), stat_compression='int8')


def test_offload_rejects_sliced_async_and_callable_cadence():
    with pytest.raises(ValueError, match='sliced'):
        kfac_tpu.KFACPreconditioner(
            registry=_reg(), offload=True, async_inverse='sliced',
            inv_update_steps=4)
    with pytest.raises(ValueError, match='callable|schedule'):
        kfac_tpu.KFACPreconditioner(
            registry=_reg(), offload=True,
            factor_update_steps=lambda s: 8)


def test_config_shorthands():
    cfg = kfac_tpu.KFACPreconditioner(
        registry=_reg(), allreduce_method='allreduce_bucketed',
        stat_compression=True, offload=2)
    assert cfg.stat_compression == CompressionConfig()
    assert cfg.offload == OffloadConfig(min_cold_steps=2)
    off = kfac_tpu.KFACPreconditioner(
        registry=_reg(), stat_compression=None, offload=False)
    assert off.stat_compression is None and off.offload is None


# --------------------------------------------------- compressed stat transport


def _one_step(dk, params, batch, loss_fn):
    run = kfac_tpu.CurvatureCapture(dk.config.registry).value_stats_and_grad(
        loss_fn)

    @jax.jit
    def step(state, p, b):
        (l, _), grads, stats = run(p, b)
        return dk.step(state, grads, stats, loss=l)

    state, pg = step(dk.init(), params, batch)
    return state, pg


def test_compression_off_wire_equals_raw_and_no_ef_state():
    _, params, batch, _, _, dk, loss_fn = _setup(
        allreduce_method='allreduce_bucketed')
    st = dk.comms_report()['stat_transport']
    assert st['wire_bytes'] == st['raw_bytes'] == st['bytes']
    assert st['compression'] is None
    state, _ = _one_step(dk, params, batch, loss_fn)
    assert state.comp_ef is None


def test_compressed_step_close_to_fp32_and_ef_carried():
    _, params, batch, _, _, dk32, loss_fn = _setup(
        allreduce_method='allreduce_bucketed')
    _, _, _, _, _, dk8, _ = _setup(
        allreduce_method='allreduce_bucketed', stat_compression='int8')
    _, pg32 = _one_step(dk32, params, batch, loss_fn)
    state8, pg8 = _one_step(dk8, params, batch, loss_fn)
    for a, b in zip(jax.tree_util.tree_leaves(pg32),
                    jax.tree_util.tree_leaves(pg8)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-3, rtol=2e-2)
    # the error-feedback residual is real state: present, f32, nonzero
    assert state8.comp_ef is not None
    total = sum(
        float(jnp.abs(v).sum()) for v in state8.comp_ef.values())
    assert total > 0.0


def test_wire_ratio_clears_3x():
    _, _, _, _, _, dk8, _ = _setup(
        allreduce_method='allreduce_bucketed', stat_compression='int8')
    st = dk8.comms_report()['stat_transport']
    assert st['compression']['ratio'] >= 3.0
    assert st['wire_bytes'] * 3 <= st['raw_bytes']
    assert st['bytes'] == st['wire_bytes']


def test_comp_ef_checkpoint_round_trip(tmp_path):
    _, params, batch, _, _, dk8, loss_fn = _setup(
        allreduce_method='allreduce_bucketed', stat_compression='int8')
    state, _ = _one_step(dk8, params, batch, loss_fn)
    path = str(tmp_path / 'ckpt')
    checkpoint.save(path, state, engine=dk8)
    restored, _ = checkpoint.restore(path, dk8)
    assert restored.comp_ef is not None
    for k, v in state.comp_ef.items():
        np.testing.assert_array_equal(
            np.asarray(v), np.asarray(restored.comp_ef[k]))


def test_pre_compression_checkpoint_restores_with_zero_ef(tmp_path):
    # a checkpoint saved by a compression-less engine restores into a
    # compressed engine with the EF residual reset to zeros
    _, params, batch, _, _, dk32, loss_fn = _setup(
        allreduce_method='allreduce_bucketed')
    state32, _ = _one_step(dk32, params, batch, loss_fn)
    path = str(tmp_path / 'ckpt_old')
    checkpoint.save(path, state32, engine=dk32)
    _, _, _, _, _, dk8, _ = _setup(
        allreduce_method='allreduce_bucketed', stat_compression='int8')
    restored, _ = checkpoint.restore(path, dk8)
    assert restored.comp_ef is not None
    total = sum(float(jnp.abs(v).sum()) for v in restored.comp_ef.values())
    assert total == 0.0


def test_ef_checkpoint_into_efless_engine_raises(tmp_path):
    _, params, batch, _, _, dk8, loss_fn = _setup(
        allreduce_method='allreduce_bucketed', stat_compression='int8')
    state8, _ = _one_step(dk8, params, batch, loss_fn)
    path = str(tmp_path / 'ckpt_ef')
    checkpoint.save(path, state8, engine=dk8)
    _, _, _, _, _, dk32, _ = _setup(
        allreduce_method='allreduce_bucketed')
    with pytest.raises(ValueError, match='stat_compression'):
        checkpoint.restore(path, dk32)


# ----------------------------------------------------------- offload trainer


def _trainer_losses(offload, steps=17):
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, damping=1e-3, lr=0.1,
        factor_update_steps=8, inv_update_steps=8, offload=offload)

    def loss_fn(p, model_state, batch):
        return models.mse_loss(m)(p, batch), model_state

    import optax

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac)
    state = trainer.init(params)
    losses = []
    for _ in range(steps):
        state, l = trainer.step(state, (x, y))
        losses.append(np.asarray(l))
    return trainer, state, losses


def test_offload_bit_identical_and_counters_move():
    _, state_off, base = _trainer_losses(offload=None)
    trainer, state_on, spilled = _trainer_losses(
        offload=OffloadConfig(min_cold_steps=2, prefetch_lead=1))
    np.testing.assert_array_equal(np.asarray(base), np.asarray(spilled))
    stats = trainer.kfac._offload_manager.stats
    assert stats['spills'] > 0 and stats['restores'] > 0
    assert stats['prefetch_hits'] > 0 and stats['prefetch_misses'] == 0
    assert stats['bytes_to_host'] == stats['bytes_to_device'] > 0
    # the factor EMAs themselves round-tripped exactly
    for a, b in zip(jax.tree_util.tree_leaves(state_off.kfac_state.a),
                    jax.tree_util.tree_leaves(state_on.kfac_state.a)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_spilled_state_cannot_be_checkpointed_directly():
    from kfac_tpu.compression import offload as offload_lib

    _, params, batch, _, _, dk, loss_fn = _setup(
        allreduce_method='allreduce_bucketed',
        factor_update_steps=8, inv_update_steps=8, offload=2)
    state, _ = _one_step(dk, params, batch, loss_fn)
    mgr = dk._offload_manager
    # step 3 with f=c=8: next use is step 8, 5 cold steps away -> spill
    spilled = offload_lib.pump(dk, state, step=3)
    assert offload_lib.is_spilled(spilled)
    with pytest.raises(ValueError, match='spilled'):
        checkpoint.durable_state(spilled)
    # host_view substitutes the host copies so a saver can still read it
    view = mgr.host_view(spilled)
    assert not offload_lib.is_spilled(view)
    mgr.reset()


def test_offload_comms_report_merges_live_counters():
    _, params, batch, _, _, dk, loss_fn = _setup(
        allreduce_method='allreduce_bucketed', offload=2)
    rep = dk.comms_report()['offload']
    assert rep['min_cold_steps'] == 2 and rep['prefetch_lead'] == 1
    assert rep['spill_bytes'] > 0
    assert rep['spills'] == 0 and rep['prefetch_hits'] == 0
    # no-offload engines report None
    _, _, _, _, _, dk_plain, _ = _setup(
        allreduce_method='allreduce_bucketed')
    assert dk_plain.comms_report()['offload'] is None


# ----------------------------------------------------------------- autotune


def _base(**kw):
    return kfac_tpu.KFACPreconditioner(registry=_reg(), **kw)


def test_plan_round_trip_and_pre_pr8_compat(tmp_path):
    import json

    base = _base(allreduce_method='allreduce_bucketed',
                 stat_compression='int8')
    p = search_lib.autotune(base, world=WORLD, measure=False)
    assert 'stat_compression' in p.knobs and 'offload' in p.knobs
    path = str(tmp_path / 'plan.json')
    p.save(path)
    assert plan_lib.TunedPlan.load(path).knobs == p.knobs
    # a pre-compression plan document (no new knobs) still loads, with
    # the optional knobs defaulted
    doc = json.loads(json.dumps(p.to_json()))
    for k in ('stat_compression', 'offload'):
        doc['knobs'].pop(k)
    old = plan_lib.TunedPlan.from_json(doc)
    assert old.knobs['stat_compression'] is None
    assert old.knobs['offload'] is False
    cfg = plan_lib.apply_knobs(base, old.knobs)
    assert cfg.stat_compression is None and cfg.offload is None


def test_autotune_offload_fallback_when_hbm_too_small():
    base = _base(allreduce_method='allreduce_bucketed')
    cands = search_lib.enumerate_candidates(WORLD, base)
    hw = model_lib.HardwareSpec()
    resident = min(
        model_lib.predict(c, base, WORLD, hw)[
            'memory_per_device_bytes']['total']
        for c in cands)
    spilled = min(
        model_lib.predict(
            dataclasses.replace(c, offload=True), base, WORLD, hw)[
            'memory_per_device_bytes']['total']
        for c in cands)
    assert spilled < resident
    budget = (resident + spilled) / 2
    plan = search_lib.autotune(
        base, world=WORLD, measure=False,
        hardware=model_lib.HardwareSpec(hbm_bytes=budget))
    assert plan.meta['offload_fallback'] is True
    assert plan.knobs['offload'] is True
    row = next(r for r in plan.cost_table if r['feasible'])
    assert row['memory_per_device_bytes']['factors'] == 0.0
    assert row['memory_per_device_bytes']['factors_offloaded'] > 0.0
    assert row['offload_transfer_s'] > 0.0
    # no fallback exists under sliced async refresh
    sliced = _base(async_inverse='sliced', inv_update_steps=4)
    with pytest.raises(ValueError, match='HBM'):
        search_lib.autotune(
            sliced, world=WORLD, measure=False,
            hardware=model_lib.HardwareSpec(hbm_bytes=budget))


def test_predict_prices_wire_bytes_with_engine_parity():
    base = _base(allreduce_method='allreduce_bucketed')
    cand = model_lib.Candidate(
        grad_worker_fraction=1.0, bucket_granularity=1,
        allreduce_method='ALLREDUCE_BUCKETED', allreduce_bucket_cap_mb=25.0,
        stat_compression='int8')
    row = model_lib.predict(cand, base, WORLD)
    cfg = model_lib.candidate_config(base, cand)
    eng = DistributedKFAC(
        config=cfg, mesh=kaisa_mesh(grad_worker_fraction=1.0))
    st = eng.comms_report()['stat_transport']
    assert row['bytes_per_occurrence']['stat_transport'] == st['bytes']
    assert st['bytes'] == st['wire_bytes'] < st['raw_bytes']
    # the uncompressed candidate prices strictly more stat bytes
    dense = model_lib.predict(
        dataclasses.replace(cand, stat_compression=None), base, WORLD)
    assert (row['bytes_per_occurrence']['stat_transport']
            < dense['bytes_per_occurrence']['stat_transport'])


# -------------------------------------------------------- convergence parity


@pytest.mark.slow
def test_int8_error_feedback_convergence_parity():
    """int8+EF training tracks the f32 wire to a close final loss."""
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=64, dim=6)
    params0 = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    loss_fn = models.mse_loss(m)

    def train(stat_compression, steps=40):
        cfg = kfac_tpu.KFACPreconditioner(
            registry=reg, damping=1e-3, lr=0.1,
            allreduce_method='allreduce_bucketed',
            factor_update_steps=2, inv_update_steps=2,
            stat_compression=stat_compression)
        dk = DistributedKFAC(
            config=cfg, mesh=kaisa_mesh(grad_worker_fraction=1.0))
        run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(loss_fn)

        @jax.jit
        def step(state, p, b):
            (l, _), grads, stats = run(p, b)
            state, pg = dk.step(state, grads, stats, loss=l)
            p = jax.tree_util.tree_map(lambda w, g: w - 0.1 * g, p, pg)
            return state, p, l

        state, p = dk.init(), params0
        l = None
        for _ in range(steps):
            state, p, l = step(state, p, (x, y))
        return float(l)

    l32 = train(None)
    l8 = train('int8')
    assert np.isfinite(l8)
    # parity: the compressed run lands within 5% of the f32 final loss
    assert abs(l8 - l32) <= 0.05 * max(abs(l32), 1e-8)
