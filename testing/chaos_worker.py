"""Pod member for the chaos harness (kfac_tpu/resilience/chaos.py).

Launched by :class:`ChaosConductor` as a real OS process with the
KFAC_TPU_* rendezvous env surface set. Builds the REAL stack — a
DistributedKFAC engine over the global gloo mesh (or a FleetController
owning one), a CheckpointManager rotation shared by every rank, and a
Trainer — then hands control to :func:`kfac_tpu.resilience.chaos
.run_worker`, which recovers via the pod-coordinated
CHAOS_RECOVERY_PROTOCOL and trains to ``max_steps`` emitting one JSON
line per event (the ``resilience_worker.py`` convention).

Usage: ``python chaos_worker.py <config.json>`` where the JSON carries
``ckpt_dir`` / ``max_steps`` / ``save_interval`` / ``keep`` /
``step_sleep_s`` / ``use_fleet`` / ``skew`` (written by the conductor).

Determinism is the contract: model init keys, the per-step batch, and
the optimizer are fixed, so the loss at step k is a pure function of k
— the conductor's zero-divergence check compares the storm-ridden
trajectory bit-for-bit against an uninterrupted control pod.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update('jax_platforms', 'cpu')

from kfac_tpu.parallel import multihost  # noqa: E402

multihost.initialize()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import kfac_tpu  # noqa: E402
from kfac_tpu.parallel import DistributedKFAC, batch_sharding  # noqa: E402
from kfac_tpu.resilience import CheckpointManager, chaos  # noqa: E402
from testing import models  # noqa: E402


def emit(**payload) -> None:
    print(json.dumps(payload), flush=True)


def _global_put(arr, sharding):
    """Host array -> global jax.Array across processes (every process
    passes the same full array; each contributes its local shards)."""
    arr = np.asarray(arr)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def main() -> int:
    with open(sys.argv[1]) as f:
        cfg = json.load(f)

    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=32, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    bare = kfac_tpu.KFACPreconditioner(
        registry=reg, compute_method='eigen', damping=0.01, lr=0.1,
        kl_clip=None, bucket_granularity=1,
    )

    def loss_fn(params, model_state, batch):
        bx, by = batch
        pred = m.apply({'params': params}, bx)
        return jnp.mean((pred - by) ** 2), model_state

    fleet = None
    if cfg.get('use_fleet'):
        from kfac_tpu.resilience import FleetConfig, FleetController
        from testing import faults

        manager = CheckpointManager(
            cfg['ckpt_dir'], save_interval_steps=cfg['save_interval'],
            keep=cfg['keep'],
        )
        skew = float(cfg.get('skew') or 0.0)
        fleet = FleetController(
            manager,
            FleetConfig(
                check_every=2, drift_keys=('grad_norm',),
                drift_threshold=0.5, drift_window=2, drift_patience=1,
                cooldown_steps=4,
            ),
            drain=faults.skewed_drain('grad_norm', skew) if skew else None,
        )
        trainer = kfac_tpu.Trainer(
            loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=bare,
            fleet=fleet,
        )
    else:
        engine = DistributedKFAC(
            config=bare, mesh=multihost.hybrid_kaisa_mesh(0.5)
        )
        manager = CheckpointManager(
            cfg['ckpt_dir'], engine=engine,
            save_interval_steps=cfg['save_interval'], keep=cfg['keep'],
        )
        trainer = kfac_tpu.Trainer(
            loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=engine,
            checkpoints=manager,
        )

    def make_batch(trainer):
        mesh = getattr(trainer.kfac, 'mesh', None)
        if mesh is None:
            return (x, y)
        bs = batch_sharding(mesh)
        return (_global_put(x, bs), _global_put(y, bs))

    return chaos.run_worker(
        trainer,
        trainer.checkpoints,
        params,
        make_batch,
        int(cfg['max_steps']),
        emit,
        step_sleep_s=float(cfg.get('step_sleep_s') or 0.0),
    )


if __name__ == '__main__':
    sys.exit(main())
