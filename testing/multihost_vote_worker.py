"""Worker process for the 8-process multihost protocol smoke.

Launched by tests/parallel/test_multihost.py with the same
KFAC_TPU_COORDINATOR / KFAC_TPU_NUM_PROCESSES / KFAC_TPU_PROCESS_ID
rendezvous surface as multihost_worker.py, but with ONE virtual device
per process and no model step — the point is the coordination protocol
itself at a pod-ish process count, cheap enough for eight workers on a
single core:

- ``agree_decision``: a unanimous round (all True) and a dissent round
  (one rank votes False) must resolve identically everywhere;
- ``agree_emergency``: one rank reports a signal code and one rank a
  skewed step — every rank must receive the pod-wide (max code,
  max step);
- ``assert_same_step``: passes on agreement, and the divergent case
  must raise on every rank (the gather is symmetric, so the negative
  path is SPMD-safe to exercise);
- ``barrier`` brackets the rounds;
- ``hybrid_kaisa_mesh(0.5)`` over the 8x1 world must build the (4, 2)
  host-major grid with whole-host gradient-worker columns.

Prints one JSON line with every agreed value for the test to compare
across ranks.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update('jax_platforms', 'cpu')

from kfac_tpu.parallel import multihost  # noqa: E402

multihost.initialize()


def main() -> None:
    expected = int(os.environ['KFAC_TPU_NUM_PROCESSES'])
    assert jax.process_count() == expected, jax.process_count()
    pidx = multihost.process_index()

    multihost.barrier('vote-smoke-start')

    vote_unanimous = multihost.agree_decision(True)
    vote_dissent = multihost.agree_decision(pidx != 3)

    # rank 2 saw an exit-semantics signal; rank 5 is one step ahead
    # (shared-filesystem skew) — everyone must converge on (2, 18)
    code = 2 if pidx == 2 else 0
    step = 18 if pidx == 5 else 17
    agreed_code, agreed_step = multihost.agree_emergency(code, step)

    multihost.assert_same_step(agreed_step, 'vote smoke')
    try:
        multihost.assert_same_step(1000 + pidx, 'divergence probe')
        skew_raises = False
    except RuntimeError:
        skew_raises = True

    mesh = multihost.hybrid_kaisa_mesh(0.5)
    col0_hosts = sorted(
        d.process_index for d in mesh.devices[:, 0].ravel()
    )

    multihost.barrier('vote-smoke-end')
    print(
        json.dumps(
            {
                'process': pidx,
                'n_processes': multihost.process_count(),
                'vote_unanimous': vote_unanimous,
                'vote_dissent': vote_dissent,
                'agreed_code': agreed_code,
                'agreed_step': agreed_step,
                'skew_raises': skew_raises,
                'mesh_shape': list(mesh.devices.shape),
                'mesh_axes': list(mesh.axis_names),
                'col0_hosts': col0_hosts,
            }
        ),
        flush=True,
    )


if __name__ == '__main__':
    main()
