"""Deterministic numerical-fault injection for the health sentinel tests.

Each injector takes healthy data and returns a poisoned copy — no RNG, no
mutation of the input — so a fault test is exactly reproducible and the
healthy original stays available for bitwise "nothing moved" assertions.
Faults mirror the real-world failure modes the sentinel defends against
(kfac_tpu/health.py): a corrupt input batch (dead loss/grads), a corrupt
micro-batch inside an accumulation, poisoned curvature statistics, a
factor blow-up past the conditioning bound, factors corrupted at rest
(e.g. a bad checkpoint), and torn checkpoint writes on disk (host crash
or preemption mid-write — the resilience rotation's fallback trigger).
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp

from kfac_tpu.layers import capture as capture_lib

#: supported non-finite poison values by name
POISONS = {
    'nan': float('nan'),
    'inf': float('inf'),
    '-inf': float('-inf'),
}


def _poison_value(kind: str) -> float:
    try:
        return POISONS[kind]
    except KeyError:
        raise ValueError(
            f'unknown poison kind {kind!r}; expected one of {sorted(POISONS)}'
        ) from None


def poison_batch(batch: Any, kind: str = 'nan', index: int = 0) -> Any:
    """Poison one element of every array leaf of a ``(x, y, ...)`` batch.

    Flattens each leaf and sets position ``index`` to the poison value —
    a single bad training example is enough to drive loss and every
    gradient non-finite, the skip-step trigger.
    """
    val = _poison_value(kind)

    def leaf(x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.inexact):
            return x
        flat = x.reshape(-1)
        return flat.at[index].set(val).reshape(x.shape)

    return jax.tree_util.tree_map(leaf, batch)


def poison_microbatch(
    microbatches: Any, which: int, kind: str = 'nan'
) -> Any:
    """Poison micro-batch ``which`` of a stacked micro-batch pytree.

    ``microbatches`` has a leading micro-batch axis on every leaf (the
    :meth:`kfac_tpu.Trainer.step_accumulate_scan` input convention). One
    poisoned micro-batch must make the whole accumulated step skip.
    """
    val = _poison_value(kind)

    def leaf(x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.inexact):
            return x
        flat = x[which].reshape(-1)
        return x.at[which].set(flat.at[0].set(val).reshape(x[which].shape))

    return jax.tree_util.tree_map(leaf, microbatches)


def poison_stats(
    stats: capture_lib.CapturedStats,
    layers: Any,
    side: str = 'a',
    kind: str = 'nan',
) -> capture_lib.CapturedStats:
    """Poison the captured ``A`` (or ``G``) statistics of the given layers.

    Builds a NEW CapturedStats (custom pytree — no ``_replace``): the
    factor-quarantine trigger, while grads stay finite so the skip-step
    gate does NOT fire and the engine-level quarantine is isolated.
    """
    if side not in ('a', 'g'):
        raise ValueError(f"side must be 'a' or 'g', got {side!r}")
    if isinstance(layers, str):
        layers = [layers]
    val = _poison_value(kind)
    a = dict(stats.a)
    g = dict(stats.g)
    tgt = a if side == 'a' else g
    for name in layers:
        if name not in tgt:
            raise KeyError(
                f'layer {name!r} not in captured stats {sorted(tgt)}'
            )
        tgt[name] = tgt[name] + val  # NaN/inf poisons every entry
    return capture_lib.CapturedStats(a=a, g=g, w=dict(stats.w))


def huge_stats(
    stats: capture_lib.CapturedStats,
    layers: Any,
    scale: float = 1e30,
    side: str = 'a',
) -> capture_lib.CapturedStats:
    """Blow the given layers' statistics up by ``scale`` — FINITE values
    that push the factor's Gershgorin conditioning estimate past any sane
    ``quarantine_threshold``, exercising the bound-based (rather than
    finiteness-based) quarantine path."""
    if side not in ('a', 'g'):
        raise ValueError(f"side must be 'a' or 'g', got {side!r}")
    if isinstance(layers, str):
        layers = [layers]
    a = dict(stats.a)
    g = dict(stats.g)
    tgt = a if side == 'a' else g
    for name in layers:
        if name not in tgt:
            raise KeyError(
                f'layer {name!r} not in captured stats {sorted(tgt)}'
            )
        tgt[name] = tgt[name] * scale
    return capture_lib.CapturedStats(a=a, g=g, w=dict(stats.w))


def poison_factors(
    engine: Any,
    state: Any,
    layers: Any,
    side: str = 'a',
    kind: str = 'nan',
) -> Any:
    """Corrupt resident factors in an engine state (any engine layout).

    Round-trips through ``extract_factors``/``insert_factors`` so the same
    injector poisons the dense per-layer dicts and the stacked KAISA slot
    buckets — the "factors corrupted at rest" scenario (bad checkpoint,
    bit flip) that inversion-time health verdicts and
    ``checkpoint.restore`` validation must catch.
    """
    if isinstance(layers, str):
        layers = [layers]
    val = _poison_value(kind)
    factors = engine.extract_factors(state)
    out = {}
    for name, fg in factors.items():
        fg = dict(fg)
        if name in layers:
            fg[side] = fg[side] + val
        out[name] = fg
    missing = set(layers) - set(factors)
    if missing:
        raise KeyError(f'layers {sorted(missing)} not in engine factors')
    return engine.insert_factors(state, out)


#: supported on-disk checkpoint corruption modes
CHECKPOINT_CORRUPTIONS = ('truncate', 'delete', 'garbage', 'metadata')


def corrupt_checkpoint(path: str, mode: str = 'truncate') -> str:
    """Deterministically corrupt a committed orbax checkpoint directory.

    Simulates a torn write / partial loss after commit (host crash during
    an fsync-less copy, filesystem rollback, bit rot): the checkpoint
    still LOOKS committed (its metadata markers remain for every mode but
    ``'metadata'``), so only an actual restore attempt discovers the
    damage — exactly the case :meth:`kfac_tpu.resilience
    .CheckpointManager.restore_latest` must survive by falling back to
    the previous rotation entry.

    The victim is chosen deterministically (largest payload file, path as
    the tie-break), no RNG. Modes:

    - ``'truncate'``: cut the victim to half its size (torn write).
    - ``'delete'``: remove the victim (lost object).
    - ``'garbage'``: overwrite the victim's first bytes in place
      (bit rot / torn page).
    - ``'metadata'``: remove the orbax commit markers — the checkpoint no
      longer looks committed at all (crash before commit).

    Returns the corrupted/removed file's path.
    """
    if mode not in CHECKPOINT_CORRUPTIONS:
        raise ValueError(
            f'unknown corruption mode {mode!r}; expected one of '
            f'{CHECKPOINT_CORRUPTIONS}'
        )
    if not os.path.isdir(path):
        raise FileNotFoundError(f'checkpoint dir {path!r} does not exist')
    if mode == 'metadata':
        victim = None
        for marker in ('_CHECKPOINT_METADATA', '_METADATA'):
            mpath = os.path.join(path, marker)
            if os.path.exists(mpath):
                os.remove(mpath)
                victim = mpath
        if victim is None:
            raise FileNotFoundError(
                f'no orbax metadata markers under {path!r}'
            )
        return victim
    candidates = []
    for root, _, files in os.walk(path):
        for name in files:
            if name.startswith('_'):  # keep commit markers intact
                continue
            fp = os.path.join(root, name)
            candidates.append((-os.path.getsize(fp), fp))
    if not candidates:
        raise FileNotFoundError(f'no payload files under {path!r}')
    _, victim = min(candidates)
    if mode == 'delete':
        os.remove(victim)
    elif mode == 'truncate':
        size = os.path.getsize(victim)
        with open(victim, 'r+b') as f:
            f.truncate(size // 2)
    else:  # garbage
        with open(victim, 'r+b') as f:
            f.write(b'\xde\xad\xbe\xef' * 16)
    return victim
