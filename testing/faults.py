"""Deterministic numerical-fault injection for the health sentinel tests.

Each injector takes healthy data and returns a poisoned copy — no RNG, no
mutation of the input — so a fault test is exactly reproducible and the
healthy original stays available for bitwise "nothing moved" assertions.
Faults mirror the real-world failure modes the sentinel defends against
(kfac_tpu/health.py): a corrupt input batch (dead loss/grads), a corrupt
micro-batch inside an accumulation, poisoned curvature statistics, a
factor blow-up past the conditioning bound, factors corrupted at rest
(e.g. a bad checkpoint), and torn checkpoint writes on disk (host crash
or preemption mid-write — the resilience rotation's fallback trigger).

The fleet injectors (:func:`change_topology`, :func:`induce_skew` /
:func:`skewed_drain`) simulate the two deployment events the
self-driving fleet controller (kfac_tpu/resilience/fleet.py) reacts to
— a restore onto a resized pod, and sustained cross-host comms skew —
so the whole retune/migrate loop is testable on a single CPU host.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable

import jax
import jax.numpy as jnp

from kfac_tpu.layers import capture as capture_lib

#: supported non-finite poison values by name
POISONS = {
    'nan': float('nan'),
    'inf': float('inf'),
    '-inf': float('-inf'),
}


def _poison_value(kind: str) -> float:
    try:
        return POISONS[kind]
    except KeyError:
        raise ValueError(
            f'unknown poison kind {kind!r}; expected one of {sorted(POISONS)}'
        ) from None


def poison_batch(batch: Any, kind: str = 'nan', index: int = 0) -> Any:
    """Poison one element of every array leaf of a ``(x, y, ...)`` batch.

    Flattens each leaf and sets position ``index`` to the poison value —
    a single bad training example is enough to drive loss and every
    gradient non-finite, the skip-step trigger.
    """
    val = _poison_value(kind)

    def leaf(x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.inexact):
            return x
        flat = x.reshape(-1)
        return flat.at[index].set(val).reshape(x.shape)

    return jax.tree_util.tree_map(leaf, batch)


def poison_microbatch(
    microbatches: Any, which: int, kind: str = 'nan'
) -> Any:
    """Poison micro-batch ``which`` of a stacked micro-batch pytree.

    ``microbatches`` has a leading micro-batch axis on every leaf (the
    :meth:`kfac_tpu.Trainer.step_accumulate_scan` input convention). One
    poisoned micro-batch must make the whole accumulated step skip.
    """
    val = _poison_value(kind)

    def leaf(x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.inexact):
            return x
        flat = x[which].reshape(-1)
        return x.at[which].set(flat.at[0].set(val).reshape(x[which].shape))

    return jax.tree_util.tree_map(leaf, microbatches)


def poison_stats(
    stats: capture_lib.CapturedStats,
    layers: Any,
    side: str = 'a',
    kind: str = 'nan',
) -> capture_lib.CapturedStats:
    """Poison the captured ``A`` (or ``G``) statistics of the given layers.

    Builds a NEW CapturedStats (custom pytree — no ``_replace``): the
    factor-quarantine trigger, while grads stay finite so the skip-step
    gate does NOT fire and the engine-level quarantine is isolated.
    """
    if side not in ('a', 'g'):
        raise ValueError(f"side must be 'a' or 'g', got {side!r}")
    if isinstance(layers, str):
        layers = [layers]
    val = _poison_value(kind)
    a = dict(stats.a)
    g = dict(stats.g)
    tgt = a if side == 'a' else g
    for name in layers:
        if name not in tgt:
            raise KeyError(
                f'layer {name!r} not in captured stats {sorted(tgt)}'
            )
        tgt[name] = tgt[name] + val  # NaN/inf poisons every entry
    return capture_lib.CapturedStats(a=a, g=g, w=dict(stats.w))


def huge_stats(
    stats: capture_lib.CapturedStats,
    layers: Any,
    scale: float = 1e30,
    side: str = 'a',
) -> capture_lib.CapturedStats:
    """Blow the given layers' statistics up by ``scale`` — FINITE values
    that push the factor's Gershgorin conditioning estimate past any sane
    ``quarantine_threshold``, exercising the bound-based (rather than
    finiteness-based) quarantine path."""
    if side not in ('a', 'g'):
        raise ValueError(f"side must be 'a' or 'g', got {side!r}")
    if isinstance(layers, str):
        layers = [layers]
    a = dict(stats.a)
    g = dict(stats.g)
    tgt = a if side == 'a' else g
    for name in layers:
        if name not in tgt:
            raise KeyError(
                f'layer {name!r} not in captured stats {sorted(tgt)}'
            )
        tgt[name] = tgt[name] * scale
    return capture_lib.CapturedStats(a=a, g=g, w=dict(stats.w))


def poison_factors(
    engine: Any,
    state: Any,
    layers: Any,
    side: str = 'a',
    kind: str = 'nan',
) -> Any:
    """Corrupt resident factors in an engine state (any engine layout).

    Round-trips through ``extract_factors``/``insert_factors`` so the same
    injector poisons the dense per-layer dicts and the stacked KAISA slot
    buckets — the "factors corrupted at rest" scenario (bad checkpoint,
    bit flip) that inversion-time health verdicts and
    ``checkpoint.restore`` validation must catch.
    """
    if isinstance(layers, str):
        layers = [layers]
    val = _poison_value(kind)
    factors = engine.extract_factors(state)
    out = {}
    for name, fg in factors.items():
        fg = dict(fg)
        if name in layers:
            fg[side] = fg[side] + val
        out[name] = fg
    missing = set(layers) - set(factors)
    if missing:
        raise KeyError(f'layers {sorted(missing)} not in engine factors')
    return engine.insert_factors(state, out)


#: supported on-disk checkpoint corruption modes
CHECKPOINT_CORRUPTIONS = (
    'truncate', 'delete', 'garbage', 'metadata', 'torn_latest'
)


def corrupt_checkpoint(path: str, mode: str = 'truncate') -> str:
    """Deterministically corrupt a committed orbax checkpoint directory.

    Simulates a torn write / partial loss after commit (host crash during
    an fsync-less copy, filesystem rollback, bit rot): the checkpoint
    still LOOKS committed (its metadata markers remain for every mode but
    ``'metadata'``), so only an actual restore attempt discovers the
    damage — exactly the case :meth:`kfac_tpu.resilience
    .CheckpointManager.restore_latest` must survive by falling back to
    the previous rotation entry.

    The victim is chosen deterministically (largest payload file, path as
    the tie-break), no RNG. Modes:

    - ``'truncate'``: cut the victim to half its size (torn write).
    - ``'delete'``: remove the victim (lost object).
    - ``'garbage'``: overwrite the victim's first bytes in place
      (bit rot / torn page).
    - ``'metadata'``: remove the orbax commit markers — the checkpoint no
      longer looks committed at all (crash before commit).
    - ``'torn_latest'``: tear the rotation's ``LATEST`` pointer itself —
      ``path`` is the ROTATION ROOT (the CheckpointManager directory),
      not a step dir. The pointer is truncated to half and garbage bytes
      appended, so ``latest_step()`` cannot parse it; the payload step
      dirs stay intact and ``restore_latest`` must recover via the
      rotation scan instead of crashing on the pointer. Distinct from
      the payload modes: the fault is in the commit pointer, not the
      checkpoint bytes.

    Returns the corrupted/removed file's path.
    """
    if mode not in CHECKPOINT_CORRUPTIONS:
        raise ValueError(
            f'unknown corruption mode {mode!r}; expected one of '
            f'{CHECKPOINT_CORRUPTIONS}'
        )
    if not os.path.isdir(path):
        raise FileNotFoundError(f'checkpoint dir {path!r} does not exist')
    if mode == 'torn_latest':
        victim = os.path.join(path, 'LATEST')
        if not os.path.exists(victim):
            raise FileNotFoundError(
                f'no LATEST pointer under {path!r} — pass the rotation '
                'root (the CheckpointManager directory), not a step dir'
            )
        size = os.path.getsize(victim)
        with open(victim, 'r+b') as f:
            f.truncate(size // 2)
            f.seek(0, os.SEEK_END)
            f.write(b'\xde\xad\xbe\xef')
        return victim
    if mode == 'metadata':
        victim = None
        for marker in ('_CHECKPOINT_METADATA', '_METADATA'):
            mpath = os.path.join(path, marker)
            if os.path.exists(mpath):
                os.remove(mpath)
                victim = mpath
        if victim is None:
            raise FileNotFoundError(
                f'no orbax metadata markers under {path!r}'
            )
        return victim
    candidates = []
    for root, _, files in os.walk(path):
        for name in files:
            if name.startswith('_'):  # keep commit markers intact
                continue
            fp = os.path.join(root, name)
            candidates.append((-os.path.getsize(fp), fp))
    if not candidates:
        raise FileNotFoundError(f'no payload files under {path!r}')
    _, victim = min(candidates)
    if mode == 'delete':
        os.remove(victim)
    elif mode == 'truncate':
        size = os.path.getsize(victim)
        with open(victim, 'r+b') as f:
            f.truncate(size // 2)
    else:  # garbage
        with open(victim, 'r+b') as f:
            f.write(b'\xde\xad\xbe\xef' * 16)
    return victim


def change_topology(
    plan: Any,
    *,
    device_count: int | None = None,
    local_device_count: int | None = None,
    process_count: int | None = None,
    backend: str | None = None,
) -> Any:
    """A copy of a ``TunedPlan`` whose fingerprint claims a different
    topology — the "job restored onto a resized pod" fault.

    The knobs/cost table are untouched (the plan was genuinely tuned,
    just for a pod that no longer exists); only the topology fields of
    the fingerprint are doctored, so ``fingerprint_matches`` fails in
    this process and the fleet controller's retune-on-restore path
    fires. With no explicit override the device count doubles (the
    archetypal elastic resize). Deterministic, input unmutated.

    Accepts a ``TunedPlan`` or a path to a plan file; given a path, the
    doctored plan is also written back to it (like
    :func:`corrupt_checkpoint`, the on-disk artifact is the fault site)
    and returned.
    """
    from kfac_tpu.autotune import plan as plan_lib

    path = None
    if isinstance(plan, (str, os.PathLike)):
        path = os.fspath(plan)
        plan = plan_lib.TunedPlan.load(path)
    fp = json.loads(json.dumps(plan.fingerprint))
    if (
        device_count is None and local_device_count is None
        and process_count is None and backend is None
    ):
        device_count = int(fp.get('device_count', 1)) * 2
    if device_count is not None:
        fp['device_count'] = int(device_count)
    if local_device_count is not None:
        fp['local_device_count'] = int(local_device_count)
    if process_count is not None:
        fp['process_count'] = int(process_count)
    if backend is not None:
        fp['backend'] = backend
    doctored = dataclasses.replace(plan, fingerprint=fp)
    if path is not None:
        doctored.save(path)
    return doctored


def induce_skew(
    records: list[dict[str, Any]],
    key: str = 'grad_norm',
    ratio: float = 1.0,
) -> list[dict[str, Any]]:
    """Widen the cross-host skew columns of drained flight records.

    Returns a new record list (inputs unmutated) where every record
    carrying ``key`` gets ``skew_min/skew_max`` spread symmetrically
    around its mean such that the relative skew
    ``(skew_max - skew_min) / (|skew_mean| + eps)`` — the fleet
    controller's drift signal, :func:`kfac_tpu.observability
    .flight_recorder.skew_ratio` — equals exactly ``ratio``. The mean
    comes from the record's existing ``skew_mean`` column when present
    (so single-host drains gain plausible multi-host columns), else the
    local value.
    """
    out = []
    for rec in records:
        rec = dict(rec)
        if key in rec:
            mean = float(rec.get(f'skew_mean/{key}', rec[key]))
            half = 0.5 * ratio * (abs(mean) + 1e-12)
            rec[f'skew_mean/{key}'] = mean
            rec[f'skew_min/{key}'] = mean - half
            rec[f'skew_max/{key}'] = mean + half
        out.append(rec)
    return out


def skewed_drain(
    key: str = 'grad_norm', ratio: float = 1.0
) -> Callable[[Any], list[dict[str, Any]]]:
    """A drop-in flight-recorder drain injecting deterministic
    cross-host skew — pass as ``FleetController(drain=...)`` to drive
    the drift detector on a single-host CPU test."""
    from kfac_tpu.observability import flight_recorder as flight_lib

    def drain(state: Any) -> list[dict[str, Any]]:
        records = flight_lib.drain_flight(state, skew_keys=(key,))
        return induce_skew(records, key=key, ratio=ratio)

    return drain
