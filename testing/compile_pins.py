"""Shared no-recompile test pins on the CompileWatch counter API.

Historically each suite pinned "metrics/flight/calibration add zero
recompilations" by hand as ``jit(f)._cache_size() == 1`` — a private-API
probe scattered across tests/test_observability.py,
tests/test_flight_recorder.py, tests/test_calibration.py. PR 17's
compile watch (kfac_tpu/observability/compile_watch.py,
docs/OBSERVABILITY.md "Compile & memory truth") makes the recompile
count a first-class runtime counter, so the pins now route through one
helper pair:

    step = compile_pins.watched_jit(kfac.step)
    ... drive steps ...
    compile_pins.assert_compiled_once(step)

and a failing pin reports the fingerprint diff naming exactly which
dimension/dtype/sharding forced the extra compile, instead of a bare
cache-size integer.
"""

import jax

from kfac_tpu.observability import compile_watch as compile_watch_lib


def watched_jit(fn, entry='pin.step', static_argnames=()):
    """``jax.jit(fn)`` routed through a fresh private CompileWatch.

    Returns the :class:`~kfac_tpu.observability.compile_watch.
    WatchedFunction`; its ``.watch`` carries the counters/events. The
    engine's own configured watch (if any) is deliberately not reused —
    a pin must count only the compiles the test itself drives.
    """
    watch = compile_watch_lib.CompileWatch(
        compile_watch_lib.CompileWatchConfig())
    return watch.wrap(
        entry, jax.jit(fn, static_argnames=static_argnames or None),
        static_argnames=static_argnames)


def assert_compiled_once(step, entry=None):
    """The historic ``jit(f)._cache_size() == 1`` pin: the entry
    compiled exactly once across everything driven through ``step``.

    On failure the message carries each extra compile's fingerprint
    diff — the attribution the old cache-size assertion could not give.
    """
    watch = step.watch
    n = watch.compile_count(entry)
    assert n == 1, (
        f'expected exactly 1 compile, saw {n}: '
        f'{[e["diff"] for e in watch.events]}')
    assert watch.recompile_count(entry) == 0
