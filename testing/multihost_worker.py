"""Worker process for the multi-process multihost test.

Launched by tests/parallel/test_multihost.py with KFAC_TPU_COORDINATOR /
KFAC_TPU_NUM_PROCESSES (2 or 4) / KFAC_TPU_PROCESS_ID set (the same
rendezvous env-var surface scripts/run_pod.sh exports per node). Each
process owns 2 virtual CPU devices; ``multihost.initialize`` brings up
the JAX distributed runtime, so a 2N-device world spans N OS processes —
the analogue of the reference's forked gloo process groups
(testing/distributed.py:24-141), exercising the coordination-service +
cross-process-collective paths the in-process 8-device mesh cannot.

Prints one JSON line: {process, n_processes, n_devices, loss, checksum}.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update('jax_platforms', 'cpu')

from kfac_tpu.parallel import multihost  # noqa: E402

multihost.initialize()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import kfac_tpu  # noqa: E402
from kfac_tpu.parallel import DistributedKFAC, batch_sharding  # noqa: E402
from testing import models  # noqa: E402


def global_put(arr, sharding):
    """Host array -> global jax.Array across processes (every process
    passes the same full array; each contributes its addressable shards)."""
    arr = np.asarray(arr)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def main() -> None:
    expected = int(os.environ['KFAC_TPU_NUM_PROCESSES'])
    assert jax.process_count() == expected, jax.process_count()
    assert len(jax.devices()) == 2 * expected, jax.devices()

    mesh = multihost.hybrid_kaisa_mesh(0.5)
    m = models.TinyModel(hidden=8, out=4)
    x, y = models.regression_data(jax.random.PRNGKey(1), n=32, dim=6)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    cfg = kfac_tpu.KFACPreconditioner(
        registry=reg, compute_method='eigen', damping=0.01, lr=0.1,
        bucket_granularity=1,
    )
    dk = DistributedKFAC(config=cfg, mesh=mesh)
    run = kfac_tpu.CurvatureCapture(reg).value_stats_and_grad(
        models.mse_loss(m)
    )
    bs = batch_sharding(mesh)
    batch = (global_put(x, bs), global_put(y, bs))

    @jax.jit
    def step(params, state, batch):
        (loss, _), grads, stats = run(params, batch)
        state, pg = dk.step(state, grads, stats)
        return state, pg, loss

    state = dk.init()
    state, pg, loss = step(params, state, batch)
    jax.block_until_ready(loss)
    # loss and preconditioned grads are fully replicated over the mesh, so
    # every process can read them directly
    checksum = float(
        sum(
            jnp.sum(jnp.abs(leaf.astype(jnp.float32)))
            for leaf in jax.tree_util.tree_leaves(pg)
        )
    )
    print(
        json.dumps(
            {
                'process': jax.process_index(),
                'n_processes': jax.process_count(),
                'n_devices': len(jax.devices()),
                'loss': float(loss),
                'checksum': checksum,
            }
        ),
        flush=True,
    )


if __name__ == '__main__':
    main()
