"""Tiny flax models for tests (analogue of reference testing/models.py:13-67)."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class TinyModel(nn.Module):
    """Two dense layers, the smallest end-to-end K-FAC target."""

    hidden: int = 8
    out: int = 4

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Dense(self.hidden, name='fc1')(x)
        x = nn.relu(x)
        x = nn.Dense(self.out, name='fc2')(x)
        return x


class TinyConvNet(nn.Module):
    """LeNet-flavored conv+dense stack (NHWC)."""

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Conv(6, (5, 5), padding='VALID', name='conv1')(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding='VALID', name='conv2')(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(32, name='fc1')(x)
        x = nn.relu(x)
        x = nn.Dense(10, name='fc2')(x)
        return x


class SharedDense(nn.Module):
    """Calls the same dense module twice (weight sharing / accumulation)."""

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        d = nn.Dense(x.shape[-1], name='shared')
        return d(nn.relu(d(x)))


def regression_data(key: jax.Array, n: int = 32, dim: int = 6):
    """Deterministic least-squares problem with a fixed optimal map."""
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(k1, (n, dim))
    w_true = jax.random.normal(k2, (dim, 4))
    y = jnp.tanh(x @ w_true)
    return x, y


def mse_loss(model: nn.Module):
    def loss_fn(params, batch):
        x, y = batch
        pred = model.apply({'params': params}, x)
        return jnp.mean((pred - y) ** 2)

    return loss_fn
