"""Reusable test fixtures for kfac_tpu (analogue of the reference's
``testing/`` package: models, fake assignments, mesh helpers)."""
