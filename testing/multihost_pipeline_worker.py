"""Worker process for the 2-process pipeline-parallel multihost smoke.

Launched by tests/parallel/test_multihost.py with the same
KFAC_TPU_COORDINATOR / KFAC_TPU_NUM_PROCESSES / KFAC_TPU_PROCESS_ID
rendezvous surface as multihost_worker.py, ONE virtual device per
process: the 2-stage pipeline mesh spans the OS-process boundary, so
every per-tick ``ppermute`` of the interleaved scan crosses the
coordination-service transport instead of staying inside one process —
the path the in-process 8-device tests cannot exercise.

Each rank runs the single-slot interleaved scan (p=2, v=2, m=4) on a
fixed-PRNG tiny LM, reports the replicated loss, a checksum of the
replicated (embed/head/ln_f) gradients, and its OWN executed
(F, B, idle) tick-counter row from the scan carry. The test pins the
loss against the same scan computed in a single process and the tick
rows against the schedule tables.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update('jax_platforms', 'cpu')

from kfac_tpu.parallel import multihost  # noqa: E402

multihost.initialize()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from kfac_tpu.parallel import interleaved_scan, mesh as mesh_lib  # noqa: E402

GEOM = dict(
    vocab_size=64, d_model=32, num_heads=4, num_layers=4,
    n_microbatches=4, max_len=16,
)


def global_put(arr, sharding):
    """Host array -> global jax.Array across processes (every process
    passes the same full array; each contributes its addressable shards).
    Arrays that already span the world (model.init device_puts the stage
    stack over the pipe axis itself) pass through untouched."""
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        return arr
    arr = np.asarray(arr)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def main() -> None:
    expected = int(os.environ['KFAC_TPU_NUM_PROCESSES'])
    assert jax.process_count() == expected, jax.process_count()
    assert len(jax.devices()) == expected, jax.devices()

    mesh = mesh_lib.pipeline_mesh(n_stages=2, devices=jax.devices())
    model = interleaved_scan.InterleavedPipelinedLM(
        mesh=mesh, virtual_chunks=2, **GEOM
    )
    params = model.init(jax.random.PRNGKey(0))
    m, s = GEOM['n_microbatches'], GEOM['max_len']
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (m, s), 0, GEOM['vocab_size']
    )
    targets = jax.random.randint(
        jax.random.PRNGKey(2), (m, s), 0, GEOM['vocab_size']
    )

    rep = NamedSharding(mesh, P())
    stage_sh = NamedSharding(mesh, P(mesh_lib.PIPE_AXIS))
    params = {
        key: jax.tree_util.tree_map(
            lambda x: global_put(
                x, stage_sh if key == 'stages' else rep  # noqa: B023
            ),
            params[key],
        )
        for key in params
    }
    batch = (global_put(tokens, rep), global_put(targets, rep))

    loss, grads, _, ticks = jax.jit(model.loss_stats_and_ticks)(
        params, batch
    )
    jax.block_until_ready(loss)
    # embed/head/ln_f gradients come out replicated (psum over the pipe
    # axis), so every process can checksum them locally; stage grads are
    # pipe-sharded and stay out of the cross-rank comparison
    checksum = float(
        sum(
            jnp.sum(jnp.abs(leaf.astype(jnp.float32)))
            for key in ('embed', 'pos_embed', 'head', 'ln_f')
            for leaf in jax.tree_util.tree_leaves(grads[key])
        )
    )
    # this process's executed (F, B, idle) tick-counter row
    local_ticks = np.asarray(ticks.addressable_data(0)).reshape(3)
    print(
        json.dumps(
            {
                'process': jax.process_index(),
                'n_processes': jax.process_count(),
                'loss': float(loss),
                'checksum': checksum,
                'ticks': [int(t) for t in local_ticks],
            }
        ),
        flush=True,
    )


if __name__ == '__main__':
    main()
