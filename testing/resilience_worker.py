"""Worker process for the subprocess preemption test.

Launched by tests/test_resilience.py as a real OS process so the parent
can deliver a genuine ``kill -TERM`` mid-training — the in-process signal
tests cover the flag/poll machinery, this covers the whole contract: the
handler fires in interrupt context, the next step boundary flushes an
emergency blocking save, ``Preempted`` unwinds the loop, and the process
exits 0 leaving a durable rotation a SECOND invocation resumes from
(``Trainer.restore_latest``) with step/loss continuity.

Usage: python resilience_worker.py <ckpt_dir> <max_steps> <save_interval>
[<per_step_sleep_s>] [<skew>]. Emits one JSON line per event (start /
step / preempted / done) on stdout; the parent reads the stream to time
its signal and to assert continuity.

A nonzero ``skew`` simulates a pod peer running that many steps ahead:
``multihost.process_count`` is shimmed to 2 and ``agree_emergency`` to
return ``step + skew``, so the manager must take the multi-host
coordination path and save the emergency checkpoint under the
POD-AGREED step, not this host's local one — the PR-4 review-fix
behavior (skewed hosts land in one rotation entry). The shim stays
above jax: ``barrier`` / ``assert_same_step`` gate on the real
``jax.process_count()`` and remain no-ops, so no rendezvous is needed.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update('jax_platforms', 'cpu')

import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

import kfac_tpu  # noqa: E402
from kfac_tpu.resilience import CheckpointManager, Preempted  # noqa: E402
from testing import models  # noqa: E402


def emit(**payload) -> None:
    print(json.dumps(payload), flush=True)


def main() -> None:
    ckpt_dir = sys.argv[1]
    max_steps = int(sys.argv[2])
    save_interval = int(sys.argv[3])
    step_sleep = float(sys.argv[4]) if len(sys.argv) > 4 else 0.0
    skew = int(sys.argv[5]) if len(sys.argv) > 5 else 0

    agree_calls: dict[str, int] = {}
    if skew:
        from kfac_tpu.parallel import multihost

        def skewed_agree(code: int, step: int) -> tuple[int, int]:
            agree_calls['local'] = step
            return code, step + skew

        multihost.process_count = lambda: 2
        multihost.agree_emergency = skewed_agree

    m = models.TinyModel()
    x, y = models.regression_data(jax.random.PRNGKey(1))
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(registry=reg, kl_clip=None)

    def loss_fn(params, model_state, batch):
        bx, by = batch
        pred = m.apply({'params': params}, bx)
        return jnp.mean((pred - by) ** 2), model_state

    manager = CheckpointManager(
        ckpt_dir, engine=kfac, save_interval_steps=save_interval, keep=2
    )
    trainer = kfac_tpu.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.05), kfac=kfac,
        checkpoints=manager,
    )
    state = trainer.restore_latest(params)
    if state is None:
        state = trainer.init(params)
    start = int(jax.device_get(state.kfac_state.step))
    emit(event='start', resumed_step=start)
    loss = None
    try:
        for _ in range(start, max_steps):
            state, loss = trainer.step(state, (x, y))
            emit(
                event='step',
                step=int(jax.device_get(state.kfac_state.step)),
                loss=float(loss),
            )
            if step_sleep:
                time.sleep(step_sleep)
        manager.finalize()
        emit(
            event='done',
            final_step=int(jax.device_get(state.kfac_state.step)),
            loss=float(loss) if loss is not None else None,
            latest=manager.latest_step(),
        )
    except Preempted as exc:
        emit(
            event='preempted',
            signal=exc.signal_name,
            saved_step=exc.step,
            path=exc.path,
            latest=manager.latest_step(),
            local_step=agree_calls.get('local'),
            rotation=manager.rotation_steps(),
        )
        sys.exit(0)


if __name__ == '__main__':
    main()
