// Threaded prefetching batch loader for host-side input pipelines.
//
// The TPU-native counterpart of the reference's torch DataLoader workers
// (examples/vision/datasets.py uses torch's C++-backed loader): background
// threads gather shuffled samples from a (possibly memory-mapped) source
// array into preallocated batch buffers while the device computes, so host
// batch assembly overlaps with TPU step time. Exposed as a plain C ABI for
// ctypes (no pybind11 in this image).
//
// Model: the Python side owns the source arrays (data, labels) and a ring
// of batch output buffers. The loader owns the shuffle order and the worker
// threads; `loader_next` blocks until the next batch slot is filled and
// returns its ring index; the consumer calls `loader_release` when the
// buffer has been handed to the device.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Batch {
  int64_t ring_index;
  int64_t epoch;
};

struct Loader {
  const float* data;         // (n, sample_elems)
  const int32_t* labels;     // (n,)
  int64_t n;
  int64_t sample_elems;
  int64_t batch_size;
  int64_t n_ring;
  float* batch_data;         // ring: (n_ring, batch_size, sample_elems)
  int32_t* batch_labels;     // ring: (n_ring, batch_size)
  uint64_t seed;
  bool drop_last;

  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_ready;
  std::condition_variable cv_free;
  std::queue<Batch> ready;
  std::vector<int64_t> free_slots;
  std::atomic<bool> stop{false};

  // producer state (single producer thread builds the order, many copy
  // threads could be added later; one thread suffices for memcpy-bound work)
  int64_t batches_per_epoch() const {
    return drop_last ? n / batch_size : (n + batch_size - 1) / batch_size;
  }
};

void producer_loop(Loader* L) {
  std::mt19937_64 rng(L->seed);
  std::vector<int64_t> order(L->n);
  for (int64_t i = 0; i < L->n; ++i) order[i] = i;
  if (L->batches_per_epoch() == 0) return;  // nothing to produce; don't spin
  int64_t epoch = 0;
  while (!L->stop.load()) {
    std::shuffle(order.begin(), order.end(), rng);
    const int64_t nb = L->batches_per_epoch();
    for (int64_t b = 0; b < nb && !L->stop.load(); ++b) {
      int64_t slot;
      {
        std::unique_lock<std::mutex> lk(L->mu);
        L->cv_free.wait(lk, [L] {
          return L->stop.load() || !L->free_slots.empty();
        });
        if (L->stop.load()) return;
        slot = L->free_slots.back();
        L->free_slots.pop_back();
      }
      float* out = L->batch_data + slot * L->batch_size * L->sample_elems;
      int32_t* lab = L->batch_labels + slot * L->batch_size;
      for (int64_t j = 0; j < L->batch_size; ++j) {
        // wrap for the final ragged batch when drop_last is false
        int64_t idx = order[(b * L->batch_size + j) % L->n];
        std::memcpy(out + j * L->sample_elems,
                    L->data + idx * L->sample_elems,
                    sizeof(float) * L->sample_elems);
        lab[j] = L->labels[idx];
      }
      {
        std::lock_guard<std::mutex> lk(L->mu);
        L->ready.push(Batch{slot, epoch});
      }
      L->cv_ready.notify_one();
    }
    ++epoch;
  }
}

}  // namespace

extern "C" {

void* loader_create(const float* data, const int32_t* labels, int64_t n,
                    int64_t sample_elems, int64_t batch_size, int64_t n_ring,
                    float* batch_data, int32_t* batch_labels, uint64_t seed,
                    int drop_last) {
  auto* L = new Loader();
  L->data = data;
  L->labels = labels;
  L->n = n;
  L->sample_elems = sample_elems;
  L->batch_size = batch_size;
  L->n_ring = n_ring;
  L->batch_data = batch_data;
  L->batch_labels = batch_labels;
  L->seed = seed;
  L->drop_last = drop_last != 0;
  for (int64_t s = 0; s < n_ring; ++s) L->free_slots.push_back(s);
  L->workers.emplace_back(producer_loop, L);
  return L;
}

// Blocks until a batch is ready; returns its ring index and writes the
// epoch it belongs to. Returns -1 if the loader is stopping.
int64_t loader_next(void* handle, int64_t* epoch_out) {
  auto* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_ready.wait(lk, [L] { return L->stop.load() || !L->ready.empty(); });
  if (L->ready.empty()) return -1;
  Batch b = L->ready.front();
  L->ready.pop();
  if (epoch_out) *epoch_out = b.epoch;
  return b.ring_index;
}

// Marks a ring slot as consumable again.
void loader_release(void* handle, int64_t ring_index) {
  auto* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->free_slots.push_back(ring_index);
  }
  L->cv_free.notify_one();
}

int64_t loader_batches_per_epoch(void* handle) {
  return static_cast<Loader*>(handle)->batches_per_epoch();
}

void loader_destroy(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  L->stop.store(true);
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  for (auto& t : L->workers) t.join();
  delete L;
}

}  // extern "C"
