// Threaded prefetching batch loader for host-side input pipelines.
//
// The TPU-native counterpart of the reference's torch DataLoader workers
// (examples/vision/datasets.py uses torch's C++-backed loader): background
// threads gather shuffled samples from a (possibly memory-mapped) source
// array into preallocated batch buffers while the device computes, so host
// batch assembly overlaps with TPU step time. Exposed as a plain C ABI for
// ctypes (no pybind11 in this image).
//
// Model: the Python side owns the source arrays (data, labels) and a ring
// of batch output buffers. The loader owns the shuffle order and the worker
// threads; `loader_next` blocks until the next batch slot is filled and
// returns its ring index; the consumer calls `loader_release` when the
// buffer has been handed to the device.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Batch {
  int64_t ring_index;
  int64_t epoch;
};

struct Loader {
  const float* data;         // (n, sample_elems)
  const int32_t* labels;     // (n,)
  int64_t n;
  int64_t sample_elems;
  int64_t batch_size;
  int64_t n_ring;
  float* batch_data;         // ring: (n_ring, batch_size, sample_elems)
  int32_t* batch_labels;     // ring: (n_ring, batch_size)
  uint64_t seed;
  bool drop_last;
  // image augmentation (HWC layout); aug_h == 0 disables
  int64_t aug_h = 0, aug_w = 0, aug_c = 0, aug_pad = 0;
  bool aug_flip = false;
  // resume support: fast-forward the shuffle stream to this epoch so a
  // resumed run sees the same batch order the uninterrupted run would
  int64_t start_epoch = 0;

  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_ready;
  std::condition_variable cv_free;
  std::queue<Batch> ready;
  std::vector<int64_t> free_slots;
  std::atomic<bool> stop{false};

  // producer state (single producer thread builds the order, many copy
  // threads could be added later; one thread suffices for memcpy-bound work)
  int64_t batches_per_epoch() const {
    return drop_last ? n / batch_size : (n + batch_size - 1) / batch_size;
  }
};

// Random pad-crop + horizontal flip of one HWC image (the reference's
// RandomCrop(32, padding=4) + RandomHorizontalFlip pipeline,
// examples/vision/datasets.py). dy/dx are crop offsets into the
// zero-padded image: out(y, x) = in(y + dy - pad, x' + dx - pad) with
// x' mirrored when flipping; out-of-bounds source pixels are zero.
void augment_sample(const Loader* L, const float* src, float* dst,
                    int64_t dy, int64_t dx, bool flip) {
  const int64_t H = L->aug_h, W = L->aug_w, C = L->aug_c, P = L->aug_pad;
  for (int64_t y = 0; y < H; ++y) {
    float* drow = dst + y * W * C;
    const int64_t sy = y + dy - P;
    if (sy < 0 || sy >= H) {
      std::memset(drow, 0, sizeof(float) * W * C);
      continue;
    }
    const float* srow = src + sy * W * C;
    if (!flip) {
      // contiguous run of in-bounds source columns
      for (int64_t x = 0; x < W; ++x) {
        const int64_t sx = x + dx - P;
        if (sx < 0 || sx >= W) {
          std::memset(drow + x * C, 0, sizeof(float) * C);
        } else {
          std::memcpy(drow + x * C, srow + sx * C, sizeof(float) * C);
        }
      }
    } else {
      for (int64_t x = 0; x < W; ++x) {
        const int64_t sx = x + dx - P;
        if (sx < 0 || sx >= W) {
          std::memset(drow + x * C, 0, sizeof(float) * C);
        } else {
          std::memcpy(drow + x * C, srow + (W - 1 - sx) * C,
                      sizeof(float) * C);
        }
      }
    }
  }
}

void producer_loop(Loader* L) {
  std::mt19937_64 rng(L->seed);
  std::vector<int64_t> order(L->n);
  for (int64_t i = 0; i < L->n; ++i) order[i] = i;
  if (L->batches_per_epoch() == 0) return;  // nothing to produce; don't spin
  // advance the shuffle (and augmentation) stream past completed epochs
  for (int64_t e = 0; e < L->start_epoch; ++e) {
    std::shuffle(order.begin(), order.end(), rng);
    if (L->aug_h > 0) {
      std::uniform_int_distribution<int64_t> off(0, 2 * L->aug_pad);
      std::uniform_int_distribution<int> coin(0, 1);
      const int64_t nb = L->batches_per_epoch();
      for (int64_t i = 0; i < nb * L->batch_size; ++i) {
        off(rng); off(rng);
        if (L->aug_flip) coin(rng);
      }
    }
  }
  int64_t epoch = L->start_epoch;
  while (!L->stop.load()) {
    std::shuffle(order.begin(), order.end(), rng);
    const int64_t nb = L->batches_per_epoch();
    for (int64_t b = 0; b < nb && !L->stop.load(); ++b) {
      int64_t slot;
      {
        std::unique_lock<std::mutex> lk(L->mu);
        L->cv_free.wait(lk, [L] {
          return L->stop.load() || !L->free_slots.empty();
        });
        if (L->stop.load()) return;
        slot = L->free_slots.back();
        L->free_slots.pop_back();
      }
      float* out = L->batch_data + slot * L->batch_size * L->sample_elems;
      int32_t* lab = L->batch_labels + slot * L->batch_size;
      const bool aug = L->aug_h > 0;
      std::uniform_int_distribution<int64_t> off(0, 2 * L->aug_pad);
      std::uniform_int_distribution<int> coin(0, 1);
      for (int64_t j = 0; j < L->batch_size; ++j) {
        // wrap for the final ragged batch when drop_last is false
        int64_t idx = order[(b * L->batch_size + j) % L->n];
        const float* src = L->data + idx * L->sample_elems;
        float* dst = out + j * L->sample_elems;
        if (aug) {
          const int64_t dy = off(rng), dx = off(rng);
          const bool flip = L->aug_flip && coin(rng) == 1;
          augment_sample(L, src, dst, dy, dx, flip);
        } else {
          std::memcpy(dst, src, sizeof(float) * L->sample_elems);
        }
        lab[j] = L->labels[idx];
      }
      {
        std::lock_guard<std::mutex> lk(L->mu);
        L->ready.push(Batch{slot, epoch});
      }
      L->cv_ready.notify_one();
    }
    ++epoch;
  }
}

}  // namespace

extern "C" {

// Full-featured constructor: random pad-crop (+/- pad pixels) + optional
// horizontal flip per sample when h > 0 (HWC images; h*w*c == sample_elems),
// and shuffle-stream fast-forward to start_epoch for resumed runs.
void* loader_create_aug(const float* data, const int32_t* labels, int64_t n,
                        int64_t sample_elems, int64_t batch_size,
                        int64_t n_ring, float* batch_data,
                        int32_t* batch_labels, uint64_t seed, int drop_last,
                        int64_t h, int64_t w, int64_t c, int64_t pad,
                        int flip, int64_t start_epoch) {
  auto* L = new Loader();
  L->data = data;
  L->labels = labels;
  L->n = n;
  L->sample_elems = sample_elems;
  L->batch_size = batch_size;
  L->n_ring = n_ring;
  L->batch_data = batch_data;
  L->batch_labels = batch_labels;
  L->seed = seed;
  L->drop_last = drop_last != 0;
  L->aug_h = h;
  L->aug_w = w;
  L->aug_c = c;
  L->aug_pad = pad;
  L->aug_flip = flip != 0;
  L->start_epoch = start_epoch;
  for (int64_t s = 0; s < n_ring; ++s) L->free_slots.push_back(s);
  L->workers.emplace_back(producer_loop, L);
  return L;
}

void* loader_create(const float* data, const int32_t* labels, int64_t n,
                    int64_t sample_elems, int64_t batch_size, int64_t n_ring,
                    float* batch_data, int32_t* batch_labels, uint64_t seed,
                    int drop_last) {
  return loader_create_aug(data, labels, n, sample_elems, batch_size, n_ring,
                           batch_data, batch_labels, seed, drop_last,
                           /*h=*/0, /*w=*/0, /*c=*/0, /*pad=*/0, /*flip=*/0,
                           /*start_epoch=*/0);
}

// Blocks until a batch is ready; returns its ring index and writes the
// epoch it belongs to. Returns -1 if the loader is stopping.
int64_t loader_next(void* handle, int64_t* epoch_out) {
  auto* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_ready.wait(lk, [L] { return L->stop.load() || !L->ready.empty(); });
  if (L->ready.empty()) return -1;
  Batch b = L->ready.front();
  L->ready.pop();
  if (epoch_out) *epoch_out = b.epoch;
  return b.ring_index;
}

// Marks a ring slot as consumable again.
void loader_release(void* handle, int64_t ring_index) {
  auto* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->free_slots.push_back(ring_index);
  }
  L->cv_free.notify_one();
}

int64_t loader_batches_per_epoch(void* handle) {
  return static_cast<Loader*>(handle)->batches_per_epoch();
}

void loader_destroy(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  L->stop.store(true);
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  for (auto& t : L->workers) t.join();
  delete L;
}

}  // extern "C"
