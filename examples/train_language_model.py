"""Transformer LM trainer with K-FAC (reference example parity:
examples/torch_language_model.py).

Like the reference, attention projections and the output head can be
excluded from K-FAC via skip patterns (the reference skips
embedding/decoder/self_attn by default, torch_language_model.py:163-168);
here the default preconditioners everything dense and ``--kfac-skip-layers
'.*attn.*' lm_head`` reproduces the reference default.

Supports context parallelism (``--seq-shards``) via ring attention and
tensor parallelism (``--model-shards``) via Megatron-style layout rules.
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, '.')
import kfac_tpu
from examples import common, data
from kfac_tpu import training
from kfac_tpu.models import TransformerLM, lm_loss
from kfac_tpu.parallel import tensor_parallel, token_sharding, train_mesh
from kfac_tpu.parallel.mesh import SEQ_AXIS


def main(argv=None) -> float:
    p = argparse.ArgumentParser(description='Transformer LM + K-FAC')
    p.add_argument('--d-model', type=int, default=256)
    p.add_argument('--num-heads', type=int, default=8)
    p.add_argument('--num-layers', type=int, default=4)
    p.add_argument('--seq-len', type=int, default=256)
    p.add_argument('--vocab-size', type=int, default=8192)
    p.add_argument('--model-shards', type=int, default=1)
    p.add_argument('--seq-shards', type=int, default=1)
    common.add_train_args(p)
    common.add_kfac_args(p)
    args = p.parse_args(argv)

    common.distributed_init()

    world = len(jax.devices())
    dp = world // (args.model_shards * args.seq_shards)
    frac = common.strategy_fraction(args.kfac_strategy, dp)
    mesh = train_mesh(
        grad_worker_fraction=frac, model=args.model_shards,
        seq=args.seq_shards,
    )
    tokens_np, vocab = data.lm_corpus(args.data_dir, args.vocab_size)
    model = TransformerLM(
        vocab_size=vocab,
        d_model=args.d_model,
        num_heads=args.num_heads,
        num_layers=args.num_layers,
        max_len=args.seq_len,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        ring_mesh=mesh if args.seq_shards > 1 else None,
        ring_axis=SEQ_AXIS if args.seq_shards > 1 else None,
    )
    sample = jnp.zeros((args.batch_size, args.seq_len), jnp.int32)
    params = model.init(jax.random.PRNGKey(args.seed), sample)['params']
    if args.model_shards > 1:
        params = tensor_parallel.shard_params(params, mesh)
    registry = kfac_tpu.register_model(
        model, sample, skip_layers=args.kfac_skip_layers
    )
    print(f'registered {len(registry)} K-FAC layers; mesh {dict(mesh.shape)}')

    loss = lm_loss(model)

    def loss_fn(params, model_state, batch):
        return loss(params, batch), model_state

    steps_per_epoch = (len(tokens_np) - 1) // (args.seq_len * args.batch_size)
    if args.limit_steps:
        steps_per_epoch = min(steps_per_epoch, args.limit_steps)
    lr_sched = common.make_lr_schedule(
        args.lr, steps_per_epoch, args.epochs, args.warmup_epochs, args.lr_decay
    )
    kfac = common.build_kfac(args, registry, mesh=mesh, lr=lr_sched)
    optimizer = optax.chain(
        optax.clip_by_global_norm(1.0),  # grad-norm clip before precondition
        optax.sgd(lr_sched, momentum=args.momentum),
    )
    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optimizer, kfac=kfac, donate_state=True
    )
    state = trainer.init(params)

    start_epoch = 0
    if args.resume and args.checkpoint_dir:
        restored = common.restore_checkpoint(args.checkpoint_dir, state, kfac)
        if restored is not None:
            state, start_epoch = restored
            trainer.resume(state)

    ts = token_sharding(mesh)
    timer = common.Timer()
    final_ppl = float('inf')
    for epoch in range(start_epoch, args.epochs):
        lm = common.Metric()
        for step, (xb, yb) in enumerate(
            data.lm_batches(tokens_np, args.batch_size, args.seq_len,
                            args.seed + epoch)
        ):
            if args.limit_steps and step >= args.limit_steps:
                break
            batch = (
                jax.device_put(jnp.asarray(xb), ts),
                jax.device_put(jnp.asarray(yb), ts),
            )
            state, l = trainer.step(state, batch)
            lm.update(l, xb.size)
        final_ppl = float(np.exp(min(20.0, lm.avg)))
        print(
            f'epoch {epoch}: train_loss={lm.avg:.4f} ppl={final_ppl:.1f} '
            f'elapsed={timer.elapsed():.1f}s'
        )
        if args.checkpoint_dir:
            common.save_checkpoint(args.checkpoint_dir, state, epoch)
    return final_ppl


if __name__ == '__main__':
    main()
