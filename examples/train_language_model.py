"""Transformer LM trainer with K-FAC (reference example parity:
examples/torch_language_model.py).

Like the reference, attention projections and the output head can be
excluded from K-FAC via skip patterns (the reference skips
embedding/decoder/self_attn by default, torch_language_model.py:163-168);
here the default preconditioners everything dense and ``--kfac-skip-layers
'.*attn.*' lm_head`` reproduces the reference default.

Supports context parallelism (``--seq-shards``) via ring attention and
tensor parallelism (``--model-shards``) via Megatron-style layout rules.
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, '.')
import kfac_tpu
from examples import common, data
from kfac_tpu import training
from kfac_tpu.models import TransformerLM, lm_loss
from kfac_tpu.parallel import tensor_parallel, token_sharding, train_mesh
from kfac_tpu.parallel.mesh import SEQ_AXIS


def _run_epochs(args, tokens_np, step_fn, start_epoch=0, on_epoch_end=None):
    """Shared epoch/step loop: corpus windows, limit-steps, perplexity.

    ``step_fn(xb, yb) -> loss`` advances whatever training state the caller
    closes over; ``on_epoch_end(epoch)`` handles checkpoints.
    """
    timer = common.Timer()
    writer = common.MetricsWriter(getattr(args, 'metrics_csv', None))
    final_ppl = float('inf')
    for epoch in range(start_epoch, args.epochs):
        lm = common.Metric()
        for step, (xb, yb) in enumerate(
            data.lm_batches(tokens_np, args.batch_size, args.seq_len,
                            args.seed + epoch)
        ):
            if args.limit_steps and step >= args.limit_steps:
                break
            lm.update(float(step_fn(xb, yb)), xb.size)
        final_ppl = float(np.exp(min(20.0, lm.avg)))
        print(
            f'epoch {epoch}: train_loss={lm.avg:.4f} ppl={final_ppl:.1f} '
            f'elapsed={timer.elapsed():.1f}s'
        )
        writer.write_many(
            epoch,
            {'train_loss': lm.avg, 'ppl': final_ppl,
             'elapsed_s': timer.elapsed()},
        )
        if on_epoch_end is not None:
            on_epoch_end(epoch)
    writer.close()
    return final_ppl


def _steps_per_epoch(args, tokens_np) -> int:
    steps = (len(tokens_np) - 1) // (args.seq_len * args.batch_size)
    if args.limit_steps:
        steps = min(steps, args.limit_steps)
    return steps


def main(argv=None) -> float:
    p = argparse.ArgumentParser(description='Transformer LM + K-FAC')
    p.add_argument('--d-model', type=int, default=256)
    p.add_argument('--num-heads', type=int, default=8)
    p.add_argument('--num-layers', type=int, default=4)
    p.add_argument('--seq-len', type=int, default=256)
    p.add_argument('--vocab-size', type=int, default=8192)
    p.add_argument('--model-shards', type=int, default=1)
    p.add_argument('--seq-shards', type=int, default=1)
    p.add_argument(
        '--pipeline-stages', type=int, default=0,
        help='pipeline the transformer blocks over this many stages '
        '(remaining devices become data-parallel peers); the reference '
        'reaches this via kfac.gpt_neox + DeepSpeed pipeline configs',
    )
    p.add_argument('--pipeline-microbatches', type=int, default=4)
    p.add_argument(
        '--pipeline-schedule',
        choices=['gpipe', '1f1b', 'interleaved'], default='1f1b',
        help="'interleaved' runs the single-slot Megatron virtual-stage "
        'schedule (--virtual-chunks model chunks per rank; microbatches '
        'must be a multiple of the stage count)',
    )
    p.add_argument(
        '--virtual-chunks', type=int, default=2,
        help='model chunks per pipeline rank under '
        '--pipeline-schedule=interleaved (bubble ~ 2*(p-1)/v stage-units)',
    )
    common.add_train_args(p)
    common.add_kfac_args(p)
    common.add_metrics_args(p)
    args = p.parse_args(argv)

    common.distributed_init()

    if args.pipeline_stages:
        return _pipeline_main(args)

    world = len(jax.devices())
    dp = world // (args.model_shards * args.seq_shards)
    frac = common.strategy_fraction(args.kfac_strategy, dp)
    mesh = train_mesh(
        grad_worker_fraction=frac, model=args.model_shards,
        seq=args.seq_shards,
    )
    tokens_np, vocab = data.lm_corpus(args.data_dir, args.vocab_size)
    model = TransformerLM(
        vocab_size=vocab,
        d_model=args.d_model,
        num_heads=args.num_heads,
        num_layers=args.num_layers,
        max_len=args.seq_len,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        ring_mesh=mesh if args.seq_shards > 1 else None,
        ring_axis=SEQ_AXIS if args.seq_shards > 1 else None,
    )
    sample = jnp.zeros((args.batch_size, args.seq_len), jnp.int32)
    params = model.init(jax.random.PRNGKey(args.seed), sample)['params']
    if args.model_shards > 1:
        params = tensor_parallel.shard_params(params, mesh)
    registry = kfac_tpu.register_model(
        model, sample, skip_layers=args.kfac_skip_layers
    )
    print(f'registered {len(registry)} K-FAC layers; mesh {dict(mesh.shape)}')

    loss = lm_loss(model)

    def loss_fn(params, model_state, batch):
        return loss(params, batch), model_state

    lr_sched = common.make_lr_schedule(
        args.lr, _steps_per_epoch(args, tokens_np), args.epochs,
        args.warmup_epochs, args.lr_decay,
    )
    kfac = common.build_kfac(args, registry, mesh=mesh, lr=lr_sched)
    optimizer = optax.chain(
        optax.clip_by_global_norm(1.0),  # grad-norm clip before precondition
        optax.sgd(lr_sched, momentum=args.momentum),
    )
    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optimizer, kfac=kfac, donate_state=True
    )
    state = trainer.init(params)

    start_epoch = 0
    if args.resume and args.checkpoint_dir:
        restored = common.restore_checkpoint(args.checkpoint_dir, state, kfac)
        if restored is not None:
            state, start_epoch = restored
            trainer.resume(state)

    ts = token_sharding(mesh)

    def step_fn(xb, yb):
        nonlocal state
        batch = (
            jax.device_put(jnp.asarray(xb), ts),
            jax.device_put(jnp.asarray(yb), ts),
        )
        state, l = trainer.step(state, batch)
        return l

    def on_epoch_end(epoch):
        if args.checkpoint_dir:
            common.save_checkpoint(
                args.checkpoint_dir, state, epoch, kfac_engine=trainer.kfac
            )

    return _run_epochs(
        args, tokens_np, step_fn, start_epoch=start_epoch,
        on_epoch_end=on_epoch_end,
    )


def _pipeline_main(args) -> float:
    """Pipeline-parallel training path (DP x PP on one mesh).

    K-FAC state is stage-sharded (MEM-OPT among pipe peers); the 1F1B
    schedule computes loss, grads, and curvature stats in one scan.
    """
    from kfac_tpu.parallel import PipelinedLM, PipelineKFAC
    from kfac_tpu.parallel.mesh import pipeline_mesh

    if args.seq_shards > 1:
        raise SystemExit(
            '--pipeline-stages does not compose with --seq-shards; '
            'sequence parallelism requires the non-pipelined path'
        )
    # DP x TP x PP on one mesh: --model-shards shards stage weights over
    # the (automatic) model axis inside the pipeline schedule
    pmesh = pipeline_mesh(
        n_stages=args.pipeline_stages, model=args.model_shards
    )
    tokens_np, vocab = data.lm_corpus(args.data_dir, args.vocab_size)
    kw = dict(
        mesh=pmesh,
        vocab_size=vocab,
        d_model=args.d_model,
        num_heads=args.num_heads,
        num_layers=args.num_layers,
        n_microbatches=args.pipeline_microbatches,
        max_len=args.seq_len,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        skip_layers=tuple(args.kfac_skip_layers),
    )
    if args.pipeline_schedule == 'interleaved':
        from kfac_tpu.parallel import InterleavedPipelinedLM

        plm = InterleavedPipelinedLM(
            virtual_chunks=args.virtual_chunks, **kw
        )
    else:
        plm = PipelinedLM(schedule=args.pipeline_schedule, **kw)
    params = plm.init(jax.random.PRNGKey(args.seed))
    print(
        f'pipeline: {args.pipeline_stages} ranks x '
        f'{dict(pmesh.shape)} mesh, {args.pipeline_microbatches} '
        f'microbatches, schedule={args.pipeline_schedule} '
        f'({plm.n_stages} logical stages); '
        f'{len(plm.stage_registry)} K-FAC layers per stage'
    )

    lr_sched = common.make_lr_schedule(
        args.lr, _steps_per_epoch(args, tokens_np), args.epochs,
        args.warmup_epochs, args.lr_decay,
    )
    cfg = common.build_kfac(
        args, plm.stage_registry, lr=lr_sched, verbose_dump=False
    )
    pk = PipelineKFAC(config=cfg, model=plm) if cfg is not None else None
    if pk is not None and args.kfac_verbose:
        print(pk.describe())
    optimizer = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.sgd(lr_sched, momentum=args.momentum),
    )
    pstate = pk.init() if pk is not None else None
    opt_state = optimizer.init(params)

    start_epoch = 0
    if args.resume and args.checkpoint_dir and pk is not None:
        from kfac_tpu import checkpoint as ckpt_lib

        found = common.latest_checkpoint(args.checkpoint_dir)
        if found is not None:
            path, epoch = found
            pstate, extra = ckpt_lib.restore(
                path + '/kfac', pk,
                extra_template={
                    'params': params,
                    'opt_state': opt_state,
                    'epoch': np.asarray(0, np.int32),
                },
            )
            params, opt_state = extra['params'], extra['opt_state']
            start_epoch = int(extra['epoch']) + 1
            print(f'resumed from {path} (epoch {epoch})')

    @jax.jit
    def train_step(params, pstate, opt_state, batch):
        loss, grads, stats = plm.loss_and_stats(params, batch)
        if pk is not None:
            pstate, grads = pk.step(pstate, grads, stats)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), pstate, opt_state, loss

    def step_fn(xb, yb):
        nonlocal params, pstate, opt_state
        params, pstate, opt_state, l = train_step(
            params, pstate, opt_state, (jnp.asarray(xb), jnp.asarray(yb))
        )
        return l

    def on_epoch_end(epoch):
        if args.checkpoint_dir and pk is not None:
            from kfac_tpu import checkpoint as ckpt_lib

            path = common._epoch_dir(args.checkpoint_dir, epoch)
            ckpt_lib.save(
                path + '/kfac', pstate,
                extra={
                    'params': params,
                    'opt_state': opt_state,
                    'epoch': np.asarray(epoch, np.int32),
                },
                engine=pk,
            )
            print(f'checkpoint written to {path}')

    return _run_epochs(
        args, tokens_np, step_fn, start_epoch=start_epoch,
        on_epoch_end=on_epoch_end,
    )


if __name__ == '__main__':
    main()
