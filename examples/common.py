"""Shared example-trainer glue: flags, metrics, schedules, checkpoints.

Parity with the reference's example utilities (examples/utils.py: Metric,
accuracy, LabelSmoothLoss, create_lr_schedule; examples/vision/
optimizers.py: the K-FAC flag surface).
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import optax

import kfac_tpu


def distributed_init() -> None:
    """Join the multi-host world before first backend use (no-op on a
    single host). Trainers call this first so ``jax.devices()`` sees the
    global world under ``scripts/run_pod.sh`` / TPU pod launches."""
    from kfac_tpu.parallel import multihost

    multihost.initialize()


def add_kfac_args(parser: argparse.ArgumentParser) -> None:
    """The reference's K-FAC CLI surface
    (examples/torch_cifar10_resnet.py:148-237)."""
    g = parser.add_argument_group('kfac')
    g.add_argument('--kfac', action='store_true', default=True)
    g.add_argument('--no-kfac', dest='kfac', action='store_false')
    g.add_argument('--kfac-factor-update-steps', type=int, default=10)
    g.add_argument('--kfac-inv-update-steps', type=int, default=100)
    g.add_argument('--kfac-damping', type=float, default=0.003)
    g.add_argument('--kfac-factor-decay', type=float, default=0.95)
    g.add_argument('--kfac-kl-clip', type=float, default=0.001)
    g.add_argument(
        '--kfac-compute-method',
        choices=('auto', 'eigen', 'inverse'),
        default='auto',
        help='auto picks per platform: eigen off-TPU (reference default), '
        'inverse+Newton-Schulz on TPU where eigh is pathological',
    )
    g.add_argument(
        '--kfac-strategy',
        choices=('comm-opt', 'mem-opt', 'hybrid-opt'),
        default='comm-opt',
        help='maps to grad_worker_fraction 1 / 1/world / 0.5',
    )
    g.add_argument('--kfac-skip-layers', nargs='*', default=[])
    g.add_argument(
        '--kfac-bucket-granularity', type=int, default=None,
        help='size-class rounding for distributed factor buckets '
        '(1 = exact dims; default picks per platform: 128 on TPU, 1 '
        'elsewhere). Pin an explicit value when a stacked checkpoint '
        'must restore on a different platform; see '
        'KFACPreconditioner.bucket_granularity',
    )
    g.add_argument(
        '--kfac-verbose', action='store_true',
        help='print the registration/assignment dump at construction '
        '(the reference logs this by default, kfac/preconditioner.py:264)',
    )


def add_metrics_args(parser: argparse.ArgumentParser) -> None:
    g = parser.add_argument_group('metrics')
    g.add_argument(
        '--metrics-csv', default=None,
        help='append step,name,value rows here (TensorBoard-writer slot of '
        'the reference vision engine, examples/vision/engine.py:106-113)',
    )


def add_train_args(parser: argparse.ArgumentParser) -> None:
    g = parser.add_argument_group('training')
    g.add_argument('--epochs', type=int, default=3)
    g.add_argument('--batch-size', type=int, default=128)
    g.add_argument('--lr', type=float, default=0.1)
    g.add_argument('--momentum', type=float, default=0.9)
    g.add_argument('--weight-decay', type=float, default=5e-4)
    g.add_argument('--warmup-epochs', type=float, default=1)
    g.add_argument('--lr-decay', nargs='*', type=float, default=[0.5, 0.75])
    g.add_argument('--seed', type=int, default=42)
    g.add_argument('--data-dir', default=None)
    g.add_argument('--checkpoint-dir', default=None)
    g.add_argument(
        '--resume', action='store_true',
        help='resume from the latest checkpoint in --checkpoint-dir',
    )
    g.add_argument(
        '--augment', action='store_true', default=None,
        help='random crop + flip on training images (default: on when '
             'training on a real dataset)',
    )
    g.add_argument(
        '--no-augment', dest='augment', action='store_false'
    )
    g.add_argument('--bf16', action='store_true')
    g.add_argument('--limit-steps', type=int, default=None,
                   help='cap steps per epoch (smoke runs)')


def strategy_fraction(name: str, world: int) -> float:
    if world < 1:
        raise ValueError(
            f'data-parallel world is {world}; model/seq shards exceed the '
            'device count'
        )
    if name == 'mem-opt':
        return 1.0 / world
    return {'comm-opt': 1.0, 'hybrid-opt': 0.5}[name]


def make_lr_schedule(base_lr, steps_per_epoch, epochs, warmup_epochs, decay_at):
    """Warmup + stepwise decay (reference examples/utils.py:92-114)."""
    boundaries = [int(d * epochs * steps_per_epoch) for d in decay_at]
    warmup = int(warmup_epochs * steps_per_epoch)
    piece = optax.piecewise_constant_schedule(
        base_lr, {b: 0.1 for b in boundaries}
    )

    def schedule(step):
        w = jnp.minimum(1.0, (step + 1) / max(1, warmup))
        return piece(step) * w

    return schedule


def label_smoothing_loss(logits, labels, num_classes, smoothing=0.1):
    """Label-smoothed cross entropy (reference examples/utils.py:41-63),
    via the optax built-ins."""
    soft = optax.smooth_labels(jax.nn.one_hot(labels, num_classes), smoothing)
    return optax.softmax_cross_entropy(
        logits.astype(jnp.float32), soft
    ).mean()


def cross_entropy_loss(logits, labels, num_classes):
    del num_classes
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    ).mean()


class MetricsWriter:
    """Append-only CSV metrics log (one row per step/epoch event).

    The TensorBoard-writer slot of the reference's vision engine
    (examples/vision/engine.py:106-113) without the TensorBoard dependency:
    rows are ``step,name,value`` so any notebook/pandas/TensorBoard-import
    path can consume them. The file is flushed per write so a killed run
    keeps its trail.
    """

    def __init__(self, path: str | None) -> None:
        self._f = None
        if path:
            import os as _os

            _os.makedirs(_os.path.dirname(path) or '.', exist_ok=True)
            self._f = open(path, 'a', buffering=1)
            if self._f.tell() == 0:
                self._f.write('step,name,value\n')

    def write(self, step: int, name: str, value) -> None:
        if self._f is not None:
            self._f.write(f'{step},{name},{float(value):.8g}\n')

    def write_many(self, step: int, metrics: dict) -> None:
        for name, value in metrics.items():
            self.write(step, name, value)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class Metric:
    """Streaming average (the allreduce is implicit: metrics are computed on
    global arrays; reference examples/utils.py:66-89)."""

    def __init__(self) -> None:
        self.total = 0.0
        self.count = 0

    def update(self, value, n: int = 1) -> None:
        self.total += float(value) * n
        self.count += n

    @property
    def avg(self) -> float:
        return self.total / max(1, self.count)


def accuracy(logits, labels) -> float:
    return float((jnp.argmax(logits, -1) == labels).mean())


def build_kfac(args, registry, mesh=None, lr=None, verbose_dump=True):
    """Construct the (distributed) preconditioner from CLI flags.

    ``lr`` should be the live optimizer schedule so the KL-clip scale
    ``min(1, sqrt(kl_clip/|vg*lr^2|))`` tracks warmup/decay the way the
    reference reads the optimizer's current lr (kfac/preconditioner.py
    lr-callable); falls back to the constant base lr.
    """
    if not args.kfac:
        return None
    cfg = kfac_tpu.KFACPreconditioner(
        registry=registry,
        factor_update_steps=args.kfac_factor_update_steps,
        inv_update_steps=args.kfac_inv_update_steps,
        damping=args.kfac_damping,
        factor_decay=args.kfac_factor_decay,
        kl_clip=args.kfac_kl_clip,
        lr=args.lr if lr is None else lr,
        compute_method=(
            None
            if args.kfac_compute_method == 'auto'
            else args.kfac_compute_method
        ),
        bucket_granularity=args.kfac_bucket_granularity,
    )
    if mesh is not None:
        from kfac_tpu.parallel import DistributedKFAC

        dk = DistributedKFAC(config=cfg, mesh=mesh)
        if verbose_dump and getattr(args, 'kfac_verbose', False):
            print(dk.describe())
        return dk
    # verbose_dump=False lets callers that wrap cfg in another engine
    # (PipelineKFAC) print that engine's dump instead of a duplicate
    if verbose_dump and getattr(args, 'kfac_verbose', False):
        print(cfg.describe())
    return cfg


def log_inverse_residuals(args, kfac_engine, kfac_state) -> None:
    """Under ``--kfac-verbose``, print the worst per-slot damped-inverse
    residual of a DistributedKFAC INVERSE engine (out-of-band
    Newton-Schulz quality monitoring — the stacked vmapped solve cannot
    surface convergence info in-band). No-op for other engines/methods."""
    if not getattr(args, 'kfac_verbose', False):
        return
    if kfac_engine is None or not hasattr(kfac_engine, 'inverse_residuals'):
        return
    import jax
    import jax.numpy as jnp

    # the reduction runs under jit to ONE replicated scalar: the state
    # arrays are sharded (non-addressable on multi-host pods), so eager
    # ops / np.asarray on them would fail exactly where this monitoring
    # matters most. jnp.max propagates NaN — a diverged solve reports NaN.
    def _worst(state):
        res = kfac_engine.inverse_residuals(state)
        return jnp.max(jnp.stack([
            jnp.max(r) for side in res.values() for r in side.values()
        ]))

    try:
        worst = float(jax.jit(_worst)(kfac_state))
    except ValueError:  # EIGEN method: the query is meaningless
        return
    from kfac_tpu.ops.factors import NS_FALLBACK_RESIDUAL

    # NaN must flag as bad (all NaN comparisons are False, so test the
    # HEALTHY direction — the library's own convention, ops/factors.py)
    flag = '' if worst <= NS_FALLBACK_RESIDUAL else (
        '  [ABOVE FALLBACK THRESHOLD]'
    )
    print(f'  kfac inverse residual (worst slot): {worst:.2e}{flag}')


def make_epoch_batches(
    args,
    x_train,
    y_train,
    augment: bool,
    start_epoch: int = 0,
    normalize_stats=None,
):
    """Shared trainer input pipeline: native prefetch loader when requested
    (with in-worker crop/flip and shuffle fast-forward to ``start_epoch``
    for resumed runs), else seeded python batches with numpy augmentation.
    ``normalize_stats=(mean, std)`` applies per-batch normalization — used
    when the source is a read-only memmap that cannot be normalized in
    place. Returns ``epoch_batches(epoch)``.
    """
    from examples import data as data_lib

    prefetcher = None
    if getattr(args, 'native_loader', False):
        from kfac_tpu.utils import native_loader

        try:
            prefetcher = native_loader.PrefetchLoader(
                x_train, y_train, batch_size=args.batch_size, seed=args.seed,
                augment={'pad': 4, 'flip': True} if augment else None,
                start_epoch=start_epoch,
            )
        except native_loader.NativeLoaderUnavailable as e:
            print(f'native loader unavailable ({e}); using python batches')

    def epoch_batches(epoch):
        import numpy as np

        if prefetcher is not None:
            it = prefetcher.epoch_batches()
            aug_rng = None  # augmentation happened in the worker
        else:
            it = data_lib.batches(
                x_train, y_train, args.batch_size, args.seed + epoch
            )
            aug_rng = (
                np.random.default_rng(args.seed * 1000 + epoch)
                if augment
                else None
            )
        for xb, yb in it:
            if aug_rng is not None:
                xb = data_lib.augment_images(xb, aug_rng)
            if normalize_stats is not None:
                xb = data_lib.normalize(xb, *normalize_stats)
            yield xb, yb

    return epoch_batches


class Timer:
    def __init__(self) -> None:
        self.start = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self.start


def _extra_payload(state, epoch: int):
    """Everything beyond the K-FAC durable state needed to resume exactly:
    params, optimizer state (momentum), mutable model state (batch_stats),
    and the epoch to restart from."""
    import numpy as np

    extra = {
        'params': state.params,
        'opt_state': state.opt_state,
        'epoch': np.asarray(epoch, np.int32),
    }
    if state.model_state is not None:
        extra['model_state'] = state.model_state
    return extra


def _epoch_dir(checkpoint_dir: str, epoch: int) -> str:
    import os

    return os.path.join(os.path.abspath(checkpoint_dir), f'e{epoch:05d}')


def save_checkpoint(
    checkpoint_dir, state, epoch: int = 0, kfac_engine=None
) -> None:
    """Write the full training state via orbax into an epoch-versioned
    subdirectory (the reference keeps per-epoch files and resumes the
    latest, examples/torch_cifar10_resnet.py:313-354). Pass ``kfac_engine``
    to record the state-layout manifest so later restores under a changed
    config (e.g. another platform's bucket_granularity default) migrate
    instead of failing."""
    from kfac_tpu import checkpoint

    path = _epoch_dir(checkpoint_dir, epoch)
    extra = _extra_payload(state, epoch)
    if state.kfac_state is not None:
        checkpoint.save(
            path + '/kfac', state.kfac_state, extra=extra, engine=kfac_engine
        )
    else:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path + '/plain', extra)
        ckptr.wait_until_finished()
    print(f'checkpoint written to {path}')


def latest_checkpoint(checkpoint_dir) -> tuple[str, int] | None:
    """Scan for the newest epoch-versioned checkpoint; None if absent."""
    import os
    import re

    root = os.path.abspath(checkpoint_dir)
    if not os.path.isdir(root):
        return None
    epochs = [
        int(m.group(1))
        for d in os.listdir(root)
        if (m := re.fullmatch(r'e(\d+)', d))
    ]
    # newest epoch whose payload actually committed (orbax writes the
    # kfac/plain subdir atomically by rename; a bare eNNNNN dir means the
    # process died mid-save — fall back to the previous complete one)
    for e in sorted(epochs, reverse=True):
        path = _epoch_dir(checkpoint_dir, e)
        if os.path.isdir(os.path.join(path, 'kfac')) or os.path.isdir(
            os.path.join(path, 'plain')
        ):
            return path, e
    return None


def restore_checkpoint(checkpoint_dir, state_template, kfac_engine):
    """Restore the latest checkpoint into ``state_template``'s structure.

    Returns ``(state, next_epoch)`` or None when no checkpoint exists.
    K-FAC decompositions are recomputed from the restored factors
    (reference semantics: derived state is not persisted,
    kfac/base_preconditioner.py:215-308).
    """
    from kfac_tpu import checkpoint

    found = latest_checkpoint(checkpoint_dir)
    if found is None:
        return None
    path, epoch = found
    extra_t = _extra_payload(state_template, 0)
    if state_template.kfac_state is not None:
        kstate, extra = checkpoint.restore(
            path + '/kfac', kfac_engine, extra_template=extra_t
        )
    else:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        extra = ckptr.restore(path + '/plain', target=extra_t)
        kstate = None
    mesh = getattr(kfac_engine, 'mesh', None)
    if mesh is not None:
        # orbax returns committed single-device arrays; replicate them over
        # the training mesh so they compose with the sharded K-FAC state
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(mesh, PartitionSpec())
        extra = jax.tree_util.tree_map(
            lambda r: jax.device_put(r, rep), extra
        )
    state = state_template._replace(
        params=extra['params'],
        opt_state=extra['opt_state'],
        kfac_state=kstate,
        model_state=extra.get('model_state', state_template.model_state),
    )
    print(f'resumed from {path} (epoch {epoch})')
    return state, epoch + 1
