"""Shared example-trainer glue: flags, metrics, schedules, checkpoints.

Parity with the reference's example utilities (examples/utils.py: Metric,
accuracy, LabelSmoothLoss, create_lr_schedule; examples/vision/
optimizers.py: the K-FAC flag surface).
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import optax

import kfac_tpu


def add_kfac_args(parser: argparse.ArgumentParser) -> None:
    """The reference's K-FAC CLI surface
    (examples/torch_cifar10_resnet.py:148-237)."""
    g = parser.add_argument_group('kfac')
    g.add_argument('--kfac', action='store_true', default=True)
    g.add_argument('--no-kfac', dest='kfac', action='store_false')
    g.add_argument('--kfac-factor-update-steps', type=int, default=10)
    g.add_argument('--kfac-inv-update-steps', type=int, default=100)
    g.add_argument('--kfac-damping', type=float, default=0.003)
    g.add_argument('--kfac-factor-decay', type=float, default=0.95)
    g.add_argument('--kfac-kl-clip', type=float, default=0.001)
    g.add_argument(
        '--kfac-compute-method', choices=('eigen', 'inverse'), default='eigen'
    )
    g.add_argument(
        '--kfac-strategy',
        choices=('comm-opt', 'mem-opt', 'hybrid-opt'),
        default='comm-opt',
        help='maps to grad_worker_fraction 1 / 1/world / 0.5',
    )
    g.add_argument('--kfac-skip-layers', nargs='*', default=[])


def add_train_args(parser: argparse.ArgumentParser) -> None:
    g = parser.add_argument_group('training')
    g.add_argument('--epochs', type=int, default=3)
    g.add_argument('--batch-size', type=int, default=128)
    g.add_argument('--lr', type=float, default=0.1)
    g.add_argument('--momentum', type=float, default=0.9)
    g.add_argument('--weight-decay', type=float, default=5e-4)
    g.add_argument('--warmup-epochs', type=float, default=1)
    g.add_argument('--lr-decay', nargs='*', type=float, default=[0.5, 0.75])
    g.add_argument('--seed', type=int, default=42)
    g.add_argument('--data-dir', default=None)
    g.add_argument('--checkpoint-dir', default=None)
    g.add_argument('--bf16', action='store_true')
    g.add_argument('--limit-steps', type=int, default=None,
                   help='cap steps per epoch (smoke runs)')


def strategy_fraction(name: str, world: int) -> float:
    if world < 1:
        raise ValueError(
            f'data-parallel world is {world}; model/seq shards exceed the '
            'device count'
        )
    if name == 'mem-opt':
        return 1.0 / world
    return {'comm-opt': 1.0, 'hybrid-opt': 0.5}[name]


def make_lr_schedule(base_lr, steps_per_epoch, epochs, warmup_epochs, decay_at):
    """Warmup + stepwise decay (reference examples/utils.py:92-114)."""
    boundaries = [int(d * epochs * steps_per_epoch) for d in decay_at]
    warmup = int(warmup_epochs * steps_per_epoch)
    piece = optax.piecewise_constant_schedule(
        base_lr, {b: 0.1 for b in boundaries}
    )

    def schedule(step):
        w = jnp.minimum(1.0, (step + 1) / max(1, warmup))
        return piece(step) * w

    return schedule


def label_smoothing_loss(logits, labels, num_classes, smoothing=0.1):
    """Label-smoothed cross entropy (reference examples/utils.py:41-63),
    via the optax built-ins."""
    soft = optax.smooth_labels(jax.nn.one_hot(labels, num_classes), smoothing)
    return optax.softmax_cross_entropy(
        logits.astype(jnp.float32), soft
    ).mean()


def cross_entropy_loss(logits, labels, num_classes):
    del num_classes
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    ).mean()


class Metric:
    """Streaming average (the allreduce is implicit: metrics are computed on
    global arrays; reference examples/utils.py:66-89)."""

    def __init__(self) -> None:
        self.total = 0.0
        self.count = 0

    def update(self, value, n: int = 1) -> None:
        self.total += float(value) * n
        self.count += n

    @property
    def avg(self) -> float:
        return self.total / max(1, self.count)


def accuracy(logits, labels) -> float:
    return float((jnp.argmax(logits, -1) == labels).mean())


def build_kfac(args, registry, mesh=None, lr=None):
    """Construct the (distributed) preconditioner from CLI flags.

    ``lr`` should be the live optimizer schedule so the KL-clip scale
    ``min(1, sqrt(kl_clip/|vg*lr^2|))`` tracks warmup/decay the way the
    reference reads the optimizer's current lr (kfac/preconditioner.py
    lr-callable); falls back to the constant base lr.
    """
    if not args.kfac:
        return None
    cfg = kfac_tpu.KFACPreconditioner(
        registry=registry,
        factor_update_steps=args.kfac_factor_update_steps,
        inv_update_steps=args.kfac_inv_update_steps,
        damping=args.kfac_damping,
        factor_decay=args.kfac_factor_decay,
        kl_clip=args.kfac_kl_clip,
        lr=args.lr if lr is None else lr,
        compute_method=args.kfac_compute_method,
    )
    if mesh is not None:
        from kfac_tpu.parallel import DistributedKFAC

        return DistributedKFAC(config=cfg, mesh=mesh)
    return cfg


class Timer:
    def __init__(self) -> None:
        self.start = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self.start


def save_checkpoint(checkpoint_dir, state) -> None:
    """Write params (always) and K-FAC factors (when enabled) via orbax."""
    from kfac_tpu import checkpoint

    if state.kfac_state is not None:
        checkpoint.save(
            checkpoint_dir + '/kfac', state.kfac_state,
            extra={'params': state.params},
        )
    else:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        ckptr.save(checkpoint_dir + '/params', {'params': state.params})
        ckptr.wait_until_finished()
    print(f'checkpoint written to {checkpoint_dir}')
