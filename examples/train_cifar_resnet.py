"""CIFAR-10 ResNet trainer with K-FAC (reference example parity:
examples/torch_cifar10_resnet.py).

Runs data-parallel over all visible devices via a KAISA mesh; the K-FAC
strategy flag picks COMM/MEM/HYBRID-OPT. With no dataset on disk it trains
on shape-faithful synthetic CIFAR (see examples/data.py).

Usage:
    python examples/train_cifar_resnet.py --model resnet20 --epochs 2 \
        --kfac-strategy hybrid-opt
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, '.')  # repo root
import kfac_tpu
from examples import common, data
from kfac_tpu import training
from kfac_tpu.models import resnet
from kfac_tpu.parallel import batch_sharding, kaisa_mesh


def main(argv=None) -> float:
    p = argparse.ArgumentParser(description='CIFAR-10 ResNet + K-FAC')
    p.add_argument(
        '--model', choices=('resnet20', 'resnet32', 'resnet56'),
        default='resnet20',
    )
    p.add_argument(
        '--native-loader', action='store_true',
        help='use the C++ prefetching batch loader (native/loader.cpp)',
    )
    common.add_train_args(p)
    common.add_kfac_args(p)
    common.add_metrics_args(p)
    args = p.parse_args(argv)

    common.distributed_init()

    world = len(jax.devices())
    frac = common.strategy_fraction(args.kfac_strategy, world)
    mesh = kaisa_mesh(grad_worker_fraction=frac)
    bs = batch_sharding(mesh)

    real_data = data.cifar_on_disk(args.data_dir)
    (x_train, y_train), (x_test, y_test) = data.cifar10(args.data_dir)
    if real_data:
        # reference order: augment RAW pixels, then normalize (crop borders
        # become (0-mean)/std, not 0) — train normalization happens
        # per-batch in make_epoch_batches below; eval sees no augmentation
        # so its split normalizes up front
        x_test = data.normalize(x_test, data.CIFAR10_MEAN, data.CIFAR10_STD)
    augment = real_data if args.augment is None else args.augment
    model = getattr(resnet, args.model)(
        num_classes=10, dtype=jnp.bfloat16 if args.bf16 else jnp.float32
    )
    rng = jax.random.PRNGKey(args.seed)
    sample = jnp.asarray(x_train[: args.batch_size])
    variables = model.init(rng, sample, train=True)
    registry = kfac_tpu.register_model(
        model, sample, train=False, skip_layers=args.kfac_skip_layers
    )
    print(f'registered {len(registry)} K-FAC layers on {world} devices '
          f'({args.kfac_strategy})')

    steps_per_epoch = len(x_train) // args.batch_size
    if args.limit_steps:
        steps_per_epoch = min(steps_per_epoch, args.limit_steps)
    lr_sched = common.make_lr_schedule(
        args.lr, steps_per_epoch, args.epochs, args.warmup_epochs, args.lr_decay
    )
    kfac = common.build_kfac(args, registry, mesh=mesh, lr=lr_sched)
    optimizer = optax.chain(
        optax.add_decayed_weights(args.weight_decay),
        optax.sgd(lr_sched, momentum=args.momentum),
    )

    def loss_fn(params, model_state, batch):
        xb, yb = batch
        logits, updates = model.apply(
            {'params': params, 'batch_stats': model_state}, xb, train=True,
            mutable=['batch_stats'],
        )
        return (
            common.cross_entropy_loss(logits, yb, 10),
            updates['batch_stats'],
        )

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optimizer, kfac=kfac, donate_state=True
    )
    state = trainer.init(variables['params'], variables['batch_stats'])

    start_epoch = 0
    if args.resume and args.checkpoint_dir:
        restored = common.restore_checkpoint(args.checkpoint_dir, state, kfac)
        if restored is not None:
            state, start_epoch = restored
            trainer.resume(state)

    epoch_batches = common.make_epoch_batches(
        args, x_train, y_train, augment, start_epoch=start_epoch,
        normalize_stats=(
            (data.CIFAR10_MEAN, data.CIFAR10_STD) if real_data else None
        ),
    )

    timer = common.Timer()
    writer = common.MetricsWriter(args.metrics_csv)
    test_acc = 0.0
    for epoch in range(start_epoch, args.epochs):
        train_loss = common.Metric()
        for step, (xb, yb) in enumerate(epoch_batches(epoch)):
            if args.limit_steps and step >= args.limit_steps:
                break
            batch = (
                jax.device_put(jnp.asarray(xb), bs),
                jax.device_put(jnp.asarray(yb), bs),
            )
            state, loss = trainer.step(state, batch)
            train_loss.update(loss, len(xb))
        # eval (capped alongside --limit-steps for smoke runs)
        acc = common.Metric()
        for eval_step, (xb, yb) in enumerate(
            data.batches(x_test, y_test, args.batch_size, 0)
        ):
            if args.limit_steps and eval_step >= args.limit_steps:
                break
            logits = model.apply(
                {'params': state.params, 'batch_stats': state.model_state},
                jnp.asarray(xb), train=False,
            )
            acc.update(common.accuracy(logits, jnp.asarray(yb)), len(xb))
        test_acc = acc.avg
        print(
            f'epoch {epoch}: train_loss={train_loss.avg:.4f} '
            f'test_acc={test_acc:.4f} elapsed={timer.elapsed():.1f}s'
        )
        writer.write_many(
            epoch,
            {'train_loss': train_loss.avg, 'test_acc': test_acc,
             'elapsed_s': timer.elapsed()},
        )
        common.log_inverse_residuals(args, trainer.kfac, state.kfac_state)
        if args.checkpoint_dir:
            common.save_checkpoint(
                args.checkpoint_dir, state, epoch, kfac_engine=trainer.kfac
            )
    writer.close()
    return test_acc


if __name__ == '__main__':
    main()
