"""LoRA fine-tuning with K-FAC over the adapters (frozen backbone).

The full parameter-efficient fine-tuning loop the trainability-mask and
LoRA-unit machinery exists for:

1. "Pretrain" a dense backbone on half the digits classes (plain SGD).
2. Wrap its hidden projections in :class:`kfac_tpu.models.LoRADense`,
   freeze the backbone two ways — ``mask=`` drops the frozen layers from
   the K-FAC registry (no capture taps, no factors, no KAISA slots) and
   ``optax.masked`` zeroes their updates — and fine-tune ONLY the
   adapters on the held-out classes, preconditioned by block-diagonal
   LoRA-unit K-FAC.
3. Optionally export a KFAC-Laplace posterior over the adapters
   (``--export-posterior DIR``): the same curvature that preconditioned
   fine-tuning becomes the uncertainty over the fine-tuned weights.

Usage:
    python examples/finetune_lora.py --steps 300 --rank 8
"""

from __future__ import annotations

import argparse
import sys

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, '.')  # repo root
import kfac_tpu
from examples import common, data
from kfac_tpu import training
from kfac_tpu.models import LoRADense


class Backbone(nn.Module):
    """Dense tower whose hidden projections get LoRA adapters when
    ``rank > 0`` (rank 0 is the pretraining configuration)."""

    width: int = 64
    num_classes: int = 10
    rank: int = 0

    @nn.compact
    def __call__(self, x):
        for i in range(2):
            if self.rank > 0:
                x = LoRADense(
                    features=self.width, rank=self.rank, name=f'dense{i}'
                )(x)
            else:
                x = nn.Dense(self.width, name=f'dense{i}')(x)
            x = nn.relu(x)
        return nn.Dense(self.num_classes, name='head')(x)


def _loss_fn(model):
    def loss_fn(params, model_state, batch):
        x, y = batch
        logits = model.apply({'params': params}, x)
        onehot = jax.nn.one_hot(y, logits.shape[-1])
        loss = -jnp.mean(
            jnp.sum(jax.nn.log_softmax(logits) * onehot, axis=-1)
        )
        return loss, model_state
    return loss_fn


def _graft_pretrained(lora_params, dense_params):
    """Move pretrained dense kernels into the LoRA modules' base slots."""
    out = jax.tree_util.tree_map(lambda v: v, lora_params)
    for name, sub in dense_params.items():
        if name in out and 'base' in out[name]:
            out[name] = {**out[name], 'base': sub}
        else:
            out[name] = sub
    return out


def main(argv=None) -> float:
    p = argparse.ArgumentParser(description='LoRA + K-FAC fine-tuning')
    p.add_argument('--steps', type=int, default=300)
    p.add_argument('--pretrain-steps', type=int, default=200)
    p.add_argument('--rank', type=int, default=8)
    p.add_argument('--batch-size', type=int, default=128)
    p.add_argument('--lr', type=float, default=0.05)
    p.add_argument('--kfac-damping', type=float, default=0.003)
    p.add_argument('--seed', type=int, default=0)
    p.add_argument(
        '--export-posterior', default=None, metavar='DIR',
        help='export a KFAC-Laplace posterior over the adapters here',
    )
    args = p.parse_args(argv)

    (x_train, y_train), (x_test, y_test) = data.digits()
    # pretrain on classes 0-4, fine-tune on 5-9: a real distribution shift
    pre = y_train < 5
    x_pre, y_pre = x_train[pre], y_train[pre]
    x_ft, y_ft = x_train[~pre], y_train[~pre]
    x_ev, y_ev = x_test[y_test >= 5], y_test[y_test >= 5]
    rng = np.random.default_rng(args.seed)

    def batches(x, y, n_steps):
        for _ in range(n_steps):
            idx = rng.integers(0, len(x), args.batch_size)
            yield jnp.asarray(x[idx]), jnp.asarray(y[idx])

    # ---- stage 1: pretrain the dense backbone with plain SGD
    dense = Backbone(rank=0)
    sample = jnp.asarray(x_pre[: args.batch_size])
    params = dense.init(jax.random.PRNGKey(args.seed), sample)['params']
    tr = training.Trainer(
        loss_fn=_loss_fn(dense), optimizer=optax.sgd(args.lr), kfac=None
    )
    st = tr.init(params, None)
    for batch in batches(x_pre, y_pre, args.pretrain_steps):
        st, loss = tr.step(st, batch)
    print(f'pretrain done: loss {float(loss):.4f}')

    # ---- stage 2: adapters on, backbone frozen, K-FAC over the units
    model = Backbone(rank=args.rank)
    lora_params = model.init(jax.random.PRNGKey(args.seed + 1), sample)[
        'params'
    ]
    params = _graft_pretrained(lora_params, st.params)
    # one mask, two consumers: K-FAC registration and the optimizer. The
    # backbone freezes; the adapters AND the classifier head train (the
    # standard LoRA fine-tuning split), so the registry mixes LoRA units
    # with a plain dense layer.
    mask = {
        'dense0': {'base': False},
        'dense1': {'base': False},
    }
    registry = kfac_tpu.register_model(model, sample, mask=mask)
    print(
        f'registered {len(registry.layers)} K-FAC unit(s): '
        f'{sorted(registry.layers)}'
    )
    kfac = kfac_tpu.KFACPreconditioner(
        registry=registry, damping=args.kfac_damping, lr=args.lr,
        factor_update_steps=1, inv_update_steps=10,
    )
    labels = jax.tree_util.tree_map_with_path(
        lambda path, _: 'frozen'
        if 'base' in [getattr(k, 'key', '') for k in path]
        else 'train',
        params,
    )
    # multi_transform, NOT optax.masked: masked passes the non-selected
    # leaves' updates through UNCHANGED (raw gradients applied at scale
    # 1), set_to_zero is what actually freezes them
    optimizer = optax.multi_transform(
        {'train': optax.sgd(args.lr), 'frozen': optax.set_to_zero()},
        labels,
    )
    tr = training.Trainer(
        loss_fn=_loss_fn(model), optimizer=optimizer, kfac=kfac
    )
    st = tr.init(params, None)
    for batch in batches(x_ft, y_ft, args.steps):
        st, loss = tr.step(st, batch)
    logits = model.apply({'params': st.params}, jnp.asarray(x_ev))
    acc = common.accuracy(logits, jnp.asarray(y_ev))
    print(
        f'fine-tune done: loss {float(loss):.4f}, '
        f'held-out accuracy {acc:.3f}'
    )

    if args.export_posterior:
        doc = kfac_tpu.export_posterior(
            kfac, st.kfac_state, st.params, args.export_posterior,
            overwrite=True,
        )
        print(
            f'exported KFAC-Laplace posterior over {sorted(doc["layers"])} '
            f'to {args.export_posterior}'
        )
    return float(loss)


if __name__ == '__main__':
    main()
