"""Datasets for the example trainers.

The reference examples pull CIFAR/ImageNet/PennTreebank via torchvision /
torchtext (examples/vision/datasets.py, examples/language/dataset.py). This
environment has no network egress, so each loader here prefers an on-disk
copy (``--data-dir`` with .npz files) and falls back to a deterministic
synthetic dataset with the same shapes — the training dynamics (throughput,
K-FAC behavior) are representative even when the labels are synthetic.
sklearn's bundled digits dataset provides a real offline classification
task for the integration gate.
"""

from __future__ import annotations

import os

import numpy as np


def _rng(seed: int) -> np.random.Generator:
    return np.random.default_rng(seed)


def synthetic_classification(
    n: int,
    shape: tuple[int, ...],
    num_classes: int,
    seed: int = 0,
    center_seed: int = 0,
):
    """Gaussian class-conditional images: learnable but synthetic.

    ``seed`` draws the labels and per-sample noise; ``center_seed`` draws
    the class centers. Centers default to a FIXED seed so differently-
    seeded splits (train vs test) share the same classification problem —
    otherwise a model that learns the train centers faces unrelated test
    centers and generalization is impossible by construction.
    """
    rng = _rng(seed)
    labels = rng.integers(0, num_classes, size=n)
    # domain-separated center stream: seeding with (center_seed, tag)
    # keeps it disjoint from the per-split noise stream even when
    # center_seed == seed (a shared PCG64 stream would replay the exact
    # words the centers consumed into the split's noise draws)
    centers = (
        np.random.default_rng([center_seed, 0xCE27E5])
        .normal(size=(num_classes,) + shape)
        .astype(np.float32)
    )
    x = 0.5 * centers[labels] + rng.normal(size=(n,) + shape).astype(np.float32)
    return x.astype(np.float32), labels.astype(np.int32)


def cifar_on_disk(data_dir: str | None) -> bool:
    """Whether :func:`cifar10` would load a real dataset from data_dir."""
    return bool(data_dir) and os.path.exists(
        os.path.join(data_dir, 'cifar10.npz')
    )


def _imagenet_memmap_files(data_dir: str) -> list[str]:
    """The on-disk memmap layout (single source of truth for the detector
    and the loader)."""
    return [
        os.path.join(data_dir, f'imagenet_{k}_{s}.npy')
        for k in ('x', 'y')
        for s in ('train', 'test')
    ]


def imagenet_on_disk(data_dir: str | None) -> bool:
    """Whether :func:`imagenet_like` would load real data (memmap .npy
    layout needs all four files, else the .npz)."""
    if not data_dir:
        return False
    return all(
        os.path.exists(f) for f in _imagenet_memmap_files(data_dir)
    ) or os.path.exists(os.path.join(data_dir, 'imagenet.npz'))


def cifar10(data_dir: str | None = None, n_train: int = 50000, n_test: int = 10000):
    """(32, 32, 3) x 10 classes; loads ``cifar10.npz`` from data_dir if
    present (keys: x_train, y_train, x_test, y_test), else synthetic."""
    if data_dir:
        path = os.path.join(data_dir, 'cifar10.npz')
        if os.path.exists(path):
            z = np.load(path)
            return (
                (z['x_train'].astype(np.float32), z['y_train'].astype(np.int32)),
                (z['x_test'].astype(np.float32), z['y_test'].astype(np.int32)),
            )
    train = synthetic_classification(n_train, (32, 32, 3), 10, seed=0)
    test = synthetic_classification(n_test, (32, 32, 3), 10, seed=1)
    return train, test


def imagenet_like(
    data_dir: str | None = None,
    image_size: int = 224,
    n_train: int = 10000,
    n_test: int = 1000,
    num_classes: int = 1000,
):
    """ImageNet-shaped data ((S, S, 3) x 1000).

    Preferred on-disk layout: ``imagenet_{x,y}_{train,test}.npy`` with x as
    C-contiguous float32 — x is memory-mapped so the native loader's worker
    reads pages straight from disk (no RAM copy of the dataset), the
    equivalent of the reference's folder-of-JPEGs DataLoader at the tensor
    level. Falls back to ``imagenet.npz`` (loaded into RAM), then synthetic.
    """
    if data_dir:
        if all(os.path.exists(f) for f in _imagenet_memmap_files(data_dir)):
            def load(split):
                x = np.load(
                    os.path.join(data_dir, f'imagenet_x_{split}.npy'),
                    mmap_mode='r',
                )
                y = np.load(
                    os.path.join(data_dir, f'imagenet_y_{split}.npy')
                ).astype(np.int32)
                return x, y

            return load('train'), load('test')
        path = os.path.join(data_dir, 'imagenet.npz')
        if os.path.exists(path):
            z = np.load(path)
            return (
                (z['x_train'].astype(np.float32), z['y_train'].astype(np.int32)),
                (z['x_test'].astype(np.float32), z['y_test'].astype(np.int32)),
            )
    shape = (image_size, image_size, 3)
    train = synthetic_classification(n_train, shape, num_classes, seed=0)
    test = synthetic_classification(n_test, shape, num_classes, seed=1)
    return train, test


def digits():
    """sklearn's offline 8x8 digits (the MNIST-gate stand-in)."""
    from sklearn.datasets import load_digits

    x, y = load_digits(return_X_y=True)
    x = (x / 16.0).astype(np.float32)
    rng = _rng(0)
    idx = rng.permutation(len(x))
    x, y = x[idx], y[idx].astype(np.int32)
    split = int(0.8 * len(x))
    return (x[:split], y[:split]), (x[split:], y[split:])


def lm_corpus(
    data_dir: str | None = None,
    vocab_size: int = 8192,
    n_tokens: int = 2_000_000,
    seed: int = 0,
):
    """Token stream for LM training.

    Preferred on-disk layout (written by ``tools/tokenize_corpus.py``, the
    offline counterpart of the reference's torchtext PTB/WikiText pipeline,
    examples/language/dataset.py): ``corpus.npy`` (int token ids,
    MEMORY-MAPPED — the corpus never loads into RAM; ``lm_batches`` copies
    only each batch's windows, the token-level equivalent of the ImageNet
    memmap path) plus optional ``vocab.json`` (``{"size": N, ...}``) to
    avoid a full scan for the vocab size. Falls back to a Zipf-distributed
    synthetic stream (realistic softmax skew).
    """
    if data_dir:
        path = os.path.join(data_dir, 'corpus.npy')
        if os.path.exists(path):
            toks = np.load(path, mmap_mode='r')
            vpath = os.path.join(data_dir, 'vocab.json')
            if os.path.exists(vpath):
                import json

                with open(vpath) as f:
                    meta = json.load(f)
                vocab = int(meta['size'])
                # a stale/hand-edited vocab.json smaller than the corpus'
                # ids would make out-of-range targets one_hot to all-zero
                # rows — the fused NLL silently degrades to bare logsumexp
                # instead of erroring. tokenize_corpus.py writes max_token,
                # making the check O(1); a vocab.json WITHOUT it is by
                # definition not the tokenizer's output, so it pays one
                # validating pass over the memmap (the cost the sidecar
                # normally avoids).
                max_tok = (
                    int(meta['max_token'])
                    if 'max_token' in meta
                    else int(toks.max())
                )
                if vocab <= max_tok:
                    raise ValueError(
                        f'vocab.json size={vocab} but {path} contains token '
                        f'id {max_tok}; vocab.json must be the tokenizer\'s '
                        f'own output (tools/tokenize_corpus.py writes a '
                        f'consistent pair)'
                    )
            else:
                vocab = int(toks.max()) + 1  # one full scan, no RAM copy
            return toks, vocab
    rng = _rng(seed)
    toks = rng.zipf(1.3, size=n_tokens).astype(np.int64)
    toks = np.clip(toks, 1, vocab_size - 1).astype(np.int32)
    return toks, vocab_size


CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def normalize(x: np.ndarray, mean: np.ndarray, std: np.ndarray) -> np.ndarray:
    """Per-channel normalization of (..., H, W, C) images (the reference's
    transforms.Normalize, examples/vision/datasets.py)."""
    return ((x - mean) / std).astype(np.float32)


def augment_images(
    x: np.ndarray, rng: np.random.Generator, pad: int = 4, flip: bool = True
) -> np.ndarray:
    """Random pad-crop + horizontal flip for a batch of (H, W, C) images —
    the numpy fallback for the native loader's in-worker augmentation
    (reference: RandomCrop(padding=4) + RandomHorizontalFlip)."""
    n, h, w, _ = x.shape
    padded = np.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    dy = rng.integers(0, 2 * pad + 1, size=n)
    dx = rng.integers(0, 2 * pad + 1, size=n)
    do_flip = flip & (rng.integers(0, 2, size=n) == 1)
    out = np.empty_like(x)
    for i in range(n):
        img = padded[i, dy[i] : dy[i] + h, dx[i] : dx[i] + w]
        out[i] = img[:, ::-1] if do_flip[i] else img
    return out


def batches(x, y, batch_size: int, seed: int, drop_last: bool = True):
    """Shuffled epoch iterator (the DistributedSampler stand-in: under pjit
    the global batch is sharded by device_put, not by per-rank sampling)."""
    rng = _rng(seed)
    idx = rng.permutation(len(x))
    end = (len(x) // batch_size) * batch_size if drop_last else len(x)
    for i in range(0, end, batch_size):
        j = idx[i : i + batch_size]
        yield x[j], y[j]


def lm_batches(tokens, batch_size: int, seq_len: int, seed: int):
    """Contiguous next-token-prediction windows.

    The shuffle is a deterministic function of ``seed`` (callers pass
    ``seed + epoch``), so a run resumed from an epoch-boundary checkpoint
    replays exactly the batches the uninterrupted run would have seen —
    the sampler-state property the reference gets from
    set_epoch-per-epoch DistributedSampler seeding. ``tokens`` may be a
    read-only memmap: only each batch's windows are copied (as int32).
    """
    rng = _rng(seed)
    n_windows = (len(tokens) - 1) // seq_len
    starts = rng.permutation(n_windows)[: (n_windows // batch_size) * batch_size]
    for i in range(0, len(starts), batch_size):
        s = starts[i : i + batch_size] * seq_len
        x = np.stack([tokens[a : a + seq_len] for a in s]).astype(
            np.int32, copy=False
        )
        y = np.stack([tokens[a + 1 : a + seq_len + 1] for a in s]).astype(
            np.int32, copy=False
        )
        yield x, y
