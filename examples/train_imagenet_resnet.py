"""ImageNet-class ResNet-50 trainer with K-FAC (reference parity:
examples/torch_imagenet_resnet.py).

Label-smoothing loss and the reference's K-FAC cadence defaults
(inv every 100 steps, factors every 10: torch_imagenet_resnet.py:158-167).
Without an on-disk dataset it runs on ImageNet-shaped synthetic data —
useful for throughput and K-FAC-overhead measurement on real hardware.
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, '.')
import kfac_tpu
from examples import common, data
from kfac_tpu import training
from kfac_tpu.models import resnet
from kfac_tpu.parallel import batch_sharding, kaisa_mesh


def main(argv=None) -> float:
    p = argparse.ArgumentParser(description='ImageNet ResNet-50 + K-FAC')
    p.add_argument('--image-size', type=int, default=224)
    p.add_argument(
        '--arch', default='resnet50',
        choices=['resnet50', 'resnet20', 'resnet32', 'resnet56'],
        help='resnet50 is the reference configuration '
        '(torch_imagenet_resnet.py); the CIFAR-style depths exist for '
        'smoke tests and small-image runs — a full ResNet-50 K-FAC '
        'compile takes tens of minutes on a 1-core host',
    )
    p.add_argument('--label-smoothing', type=float, default=0.1)
    p.add_argument(
        '--native-loader', action='store_true',
        help='C++ prefetch loader; reads memory-mapped imagenet_x_train.npy '
             'directly from disk with in-worker crop/flip augmentation',
    )
    common.add_train_args(p)
    common.add_kfac_args(p)
    common.add_metrics_args(p)
    args = p.parse_args(argv)

    common.distributed_init()

    world = len(jax.devices())
    frac = common.strategy_fraction(args.kfac_strategy, world)
    mesh = kaisa_mesh(grad_worker_fraction=frac)
    bs = batch_sharding(mesh)

    real_data = data.imagenet_on_disk(args.data_dir)
    (x_train, y_train), (x_test, y_test) = data.imagenet_like(
        args.data_dir, image_size=args.image_size,
        n_train=max(args.batch_size * 8, 1024), n_test=args.batch_size * 2,
    )
    augment = real_data if args.augment is None else args.augment
    model = getattr(resnet, args.arch)(
        num_classes=1000, dtype=jnp.bfloat16 if args.bf16 else jnp.float32
    )
    sample = jnp.asarray(x_train[: args.batch_size])
    variables = model.init(jax.random.PRNGKey(args.seed), sample, train=True)
    registry = kfac_tpu.register_model(
        model, sample, train=False, skip_layers=args.kfac_skip_layers
    )
    print(f'registered {len(registry)} K-FAC layers on {world} devices')

    steps_per_epoch = len(x_train) // args.batch_size
    if args.limit_steps:
        steps_per_epoch = min(steps_per_epoch, args.limit_steps)
    lr_sched = common.make_lr_schedule(
        args.lr, steps_per_epoch, args.epochs, args.warmup_epochs, args.lr_decay
    )
    kfac = common.build_kfac(args, registry, mesh=mesh, lr=lr_sched)
    optimizer = optax.chain(
        optax.add_decayed_weights(args.weight_decay),
        optax.sgd(lr_sched, momentum=args.momentum),
    )

    def loss_fn(params, model_state, batch):
        xb, yb = batch
        logits, updates = model.apply(
            {'params': params, 'batch_stats': model_state}, xb, train=True,
            mutable=['batch_stats'],
        )
        return (
            common.label_smoothing_loss(logits, yb, 1000, args.label_smoothing),
            updates['batch_stats'],
        )

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optimizer, kfac=kfac, donate_state=True
    )
    state = trainer.init(variables['params'], variables['batch_stats'])

    start_epoch = 0
    if args.resume and args.checkpoint_dir:
        restored = common.restore_checkpoint(args.checkpoint_dir, state, kfac)
        if restored is not None:
            state, start_epoch = restored
            trainer.resume(state)

    # x_train may be a read-only float32 memmap (the native loader's worker
    # then reads pages straight from disk), so normalization happens
    # per-batch rather than in place
    epoch_batches = common.make_epoch_batches(
        args, x_train, y_train, augment, start_epoch=start_epoch,
        normalize_stats=(
            (data.IMAGENET_MEAN, data.IMAGENET_STD) if real_data else None
        ),
    )

    acc_val = 0.0
    writer = common.MetricsWriter(args.metrics_csv)
    for epoch in range(start_epoch, args.epochs):
        epoch_timer = common.Timer()
        train_loss = common.Metric()
        n_steps = 0
        for step, (xb, yb) in enumerate(epoch_batches(epoch)):
            if args.limit_steps and step >= args.limit_steps:
                break
            batch = (
                jax.device_put(jnp.asarray(xb), bs),
                jax.device_put(jnp.asarray(yb), bs),
            )
            state, loss = trainer.step(state, batch)
            train_loss.update(loss, len(xb))
            n_steps += 1
        train_secs = epoch_timer.elapsed()
        acc = common.Metric()
        for eval_step, (xb, yb) in enumerate(
            data.batches(x_test, y_test, args.batch_size, 0)
        ):
            if args.limit_steps and eval_step >= args.limit_steps:
                break
            if real_data:
                xb = data.normalize(xb, data.IMAGENET_MEAN, data.IMAGENET_STD)
            logits = model.apply(
                {'params': state.params, 'batch_stats': state.model_state},
                jnp.asarray(xb), train=False,
            )
            acc.update(common.accuracy(logits, jnp.asarray(yb)), len(xb))
        acc_val = acc.avg
        imgs = n_steps * args.batch_size
        print(
            f'epoch {epoch}: loss={train_loss.avg:.4f} acc={acc_val:.4f} '
            f'{imgs / max(train_secs, 1e-9):.1f} img/s'
        )
        writer.write_many(
            epoch,
            {'train_loss': train_loss.avg, 'test_acc': acc_val,
             'img_per_s': imgs / max(train_secs, 1e-9)},
        )
        if args.checkpoint_dir:
            common.save_checkpoint(
                args.checkpoint_dir, state, epoch, kfac_engine=trainer.kfac
            )
    writer.close()
    return acc_val


if __name__ == '__main__':
    main()
