"""Mixed-precision (float16) K-FAC training with dynamic loss scaling.

End-to-end AMP flow (reference parity: examples/vision/engine.py:80-88,
torch.cuda.amp.GradScaler + KFAC grad-scale unscaling):

- model computes in float16 (params stay float32 masters — flax
  ``param_dtype`` default), K-FAC factors/inverses in float32;
- the loss is multiplied by the scaler's scale BEFORE backward, so fp16
  cotangents sit in representable range;
- gradients AND captured K-FAC statistics are unscaled afterwards
  (``CapturedStats.scaled`` divides G by scale**2 — G is quadratic in
  the cotangents, kfac/layers/base.py:365-366);
- an inf/nan anywhere in the grads skips the step INSIDE the compiled
  program (``lax.cond`` — no host round-trip) and halves the scale; the
  K-FAC step counter does not advance on skipped steps;
- after ``--growth-interval`` consecutive good steps the scale doubles,
  so a short run exercises the full overflow/recovery cycle against the
  fp16 max of 65504 — REAL overflows, not injected ones.

On TPU prefer plain bfloat16 (fp32 exponent range, no scaling needed);
this example is the fp16 semantics the reference's AMP engine implements,
plus the overflow-robustness exercise.

Usage:
    python examples/train_amp.py --steps 300 --growth-interval 50
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, '.')  # repo root
import flax.linen as nn

import kfac_tpu
from examples import common, data
from kfac_tpu import amp


class ConvNet(nn.Module):
    """Small BN-free CIFAR CNN computing in ``dtype`` (fp16 here)."""

    dtype: jnp.dtype = jnp.float16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), strides=(2, 2), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), strides=(2, 2), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        return nn.Dense(10, dtype=self.dtype)(x)


def build_step(model, kfac, opt, registry):
    """One jitted AMP train step: capture under the scaled loss, unscale
    grads+stats, lax.cond between apply and skip, adapt the scaler."""

    def scaled_loss(params, batch_and_scale):
        (xb, yb), scale = batch_and_scale
        logits = model.apply({'params': params}, xb)
        # loss math in fp32 (logits upcast); the SCALE rides the loss so
        # the fp16 backward through the network sees scaled cotangents
        return common.cross_entropy_loss(logits.astype(jnp.float32), yb, 10) * scale

    cap = kfac_tpu.CurvatureCapture(registry)
    run = cap.value_stats_and_grad(scaled_loss)

    @jax.jit
    def step(params, kstate, opt_state, scaler, batch, growth_interval):
        (l_scaled, _), grads, stats = run(params, (batch, scaler.scale))
        finite = amp.all_finite(grads)

        def apply(_):
            g = amp.unscale(grads, scaler.scale)
            st = stats.scaled(scaler.scale)
            kst, pg = kfac.step(kstate, g, st)
            updates, ost = opt.update(pg, opt_state, params)
            return optax.apply_updates(params, updates), kst, ost

        def skip(_):
            # poisoned grads/stats dropped; K-FAC step counter unchanged
            # (the in-jit analogue of Trainer.reset_batch's host-side drop)
            return params, kstate, opt_state

        params2, kstate2, opt_state2 = jax.lax.cond(finite, apply, skip, None)
        scaler2 = amp.update(scaler, finite, growth_interval=growth_interval)
        return (
            params2, kstate2, opt_state2, scaler2,
            l_scaled / scaler.scale, finite,
        )

    return step


def main(argv=None):
    p = argparse.ArgumentParser(description='fp16 AMP + K-FAC')
    p.add_argument('--steps', type=int, default=300)
    p.add_argument('--batch-size', type=int, default=128)
    p.add_argument('--lr', type=float, default=0.05)
    p.add_argument('--init-scale', type=float, default=2.0**16)
    p.add_argument('--growth-interval', type=int, default=50)
    p.add_argument('--data-dir', default=None)
    p.add_argument('--seed', type=int, default=0)
    args = p.parse_args(argv)

    (x_train, y_train), _ = data.cifar10(args.data_dir, n_train=4096, n_test=256)
    model = ConvNet()
    sample = jnp.asarray(x_train[: args.batch_size])
    params = model.init(jax.random.PRNGKey(args.seed), sample)['params']
    registry = kfac_tpu.register_model(model, sample)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=registry, damping=0.003, lr=args.lr,
        factor_update_steps=1, inv_update_steps=10,
    )
    opt = optax.sgd(args.lr, momentum=0.9)
    step = build_step(model, kfac, opt, registry)

    kstate, opt_state = kfac.init(), opt.init(params)
    scaler = amp.init(args.init_scale)
    n = len(x_train) // args.batch_size
    skipped = 0
    for i in range(args.steps):
        j = (i % n) * args.batch_size
        batch = (
            jnp.asarray(x_train[j : j + args.batch_size]),
            jnp.asarray(y_train[j : j + args.batch_size]),
        )
        params, kstate, opt_state, scaler, loss, finite = step(
            params, kstate, opt_state, scaler, batch, args.growth_interval
        )
        if not bool(finite):
            skipped += 1
            print(f'step {i}: OVERFLOW -> scale {float(scaler.scale):.0f}')
        elif i % 25 == 0:
            print(
                f'step {i}: loss={float(loss):.4f} '
                f'scale={float(scaler.scale):.0f} skipped={skipped}'
            )
    print(
        f'done: loss={float(loss):.4f} scale={float(scaler.scale):.0f} '
        f'skipped={skipped} kfac_steps={int(kstate.step)} of {args.steps}'
    )
    return float(loss), skipped, int(kstate.step)


if __name__ == '__main__':
    main()
