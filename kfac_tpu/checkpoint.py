"""Checkpoint / resume for K-FAC state (orbax-backed).

Reference semantics (kfac/base_preconditioner.py:215-308): persist only the
step counter and the running factors A/G; eigendecompositions are
*recomputed* on load — they are derived state, and factors are smaller and
dtype-stable. Works for both the dense :class:`kfac_tpu.KFACState` and the
stacked :class:`kfac_tpu.parallel.DistKFACState`; with sharded arrays orbax
writes one shard per host (the TPU equivalent of the reference's
per-inv-worker sharded factor directory, kfac/gpt_neox/preconditioner.py:
427-447).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax

try:
    import orbax.checkpoint as ocp

    _HAS_ORBAX = True
except Exception:  # pragma: no cover - orbax is in the image; belt+braces
    _HAS_ORBAX = False


def durable_state(state: Any) -> dict[str, Any]:
    """The persistent slice of a K-FAC state: step + factors only.

    Works for the NamedTuple states of the dense/KAISA engines and the
    dict state of :class:`kfac_tpu.parallel.PipelineKFAC`.
    """
    if isinstance(state, dict):
        return {'step': state['step'], 'a': state['a'], 'g': state['g']}
    return {'step': state.step, 'a': state.a, 'g': state.g}


def _with_durable(state: Any, loaded: dict[str, Any]) -> Any:
    if isinstance(state, dict):
        return {
            **state,
            'step': loaded['step'], 'a': loaded['a'], 'g': loaded['g'],
        }
    return state._replace(
        step=loaded['step'], a=loaded['a'], g=loaded['g']
    )


def save(path: str, state: Any, extra: dict[str, Any] | None = None) -> None:
    """Write the durable K-FAC state (plus optional extra trees, e.g. model
    params / optax state) to ``path``."""
    if not _HAS_ORBAX:
        raise RuntimeError('orbax-checkpoint is not available')
    payload = {'kfac': durable_state(state)}
    if extra:
        payload.update(extra)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, payload)
    ckptr.wait_until_finished()


def restore(
    path: str,
    engine: Any,
    extra_template: dict[str, Any] | None = None,
) -> tuple[Any, dict[str, Any]]:
    """Load factors into a fresh state from ``engine.init()`` and recompute
    decompositions via ``engine.rematerialize``.

    ``engine`` is a :class:`kfac_tpu.KFACPreconditioner` or
    :class:`kfac_tpu.parallel.DistributedKFAC`. Returns ``(state, extra)``.
    """
    if not _HAS_ORBAX:
        raise RuntimeError('orbax-checkpoint is not available')
    template_state = engine.init()
    template = {'kfac': durable_state(template_state)}
    if extra_template:
        template.update(extra_template)
    ckptr = ocp.StandardCheckpointer()
    try:
        payload = ckptr.restore(path, target=template)
    except (ValueError, KeyError) as exc:
        raise ValueError(
            f'checkpoint at {path!r} does not match the engine state '
            'layout. For DistributedKFAC the stacked bucket keys/shapes '
            'depend on the config (notably bucket_granularity and '
            'colocate_factors): restore with the SAME values the '
            f'checkpoint was saved under. Original error: {exc}'
        ) from exc
    state = _with_durable(template_state, payload['kfac'])
    state = engine.rematerialize(state)
    extra = {k: v for k, v in payload.items() if k != 'kfac'}
    return state, extra


def save_factors(path: str, engine: Any, state: Any) -> None:
    """Write per-layer TRUE-DIM factors + step, independent of layout.

    Unlike :func:`save` (which persists the engine's stacked arrays
    verbatim), this stores layer-named (d, d) factors, so the checkpoint
    restores into a DIFFERENT engine configuration — other
    bucket_granularity, colocate_factors, mesh, or even dense vs
    distributed. The reference's per-layer factor-dir checkpointing
    (kfac/gpt_neox/preconditioner.py:394-447) serves the same
    topology-migration role.
    """
    if not _HAS_ORBAX:
        raise RuntimeError('orbax-checkpoint is not available')
    step = state['step'] if isinstance(state, dict) else state.step
    payload = {
        'step': step,
        'factors': engine.extract_factors(state),
    }
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, payload)
    ckptr.wait_until_finished()


def load_factors(path: str, engine: Any) -> Any:
    """Restore a :func:`save_factors` checkpoint into ``engine``'s layout.

    Returns a fresh state with the loaded factors inserted and
    decompositions rematerialized. The engine must register EXACTLY the
    stored layer names with the stored true dims (layout — granularity,
    colocation, mesh, dense vs distributed — is free to differ; the layer
    set is not, and pipeline stage-stacked factors only reload into a
    pipeline engine with the same stage count).
    """
    if not _HAS_ORBAX:
        raise RuntimeError('orbax-checkpoint is not available')
    state = engine.init()
    step = state['step'] if isinstance(state, dict) else state.step
    template = {
        'step': step,
        'factors': engine.extract_factors(state),
    }
    ckptr = ocp.StandardCheckpointer()
    try:
        payload = ckptr.restore(path, target=template)
    except (ValueError, KeyError) as exc:
        raise ValueError(
            f'factor checkpoint at {path!r} does not match this engine: '
            'the registered layer names and their factor dims must equal '
            'those the checkpoint was saved with (engine LAYOUT may '
            'differ; the layer set may not, and pipeline stage counts '
            f'must match). Original error: {exc}'
        ) from exc
    state = engine.insert_factors(state, payload['factors'])
    if isinstance(state, dict):
        state['step'] = payload['step']
    else:
        state = state._replace(step=payload['step'])
    return engine.rematerialize(state)
