"""Checkpoint / resume for K-FAC state (orbax-backed).

Reference semantics (kfac/base_preconditioner.py:215-308): persist only the
step counter and the running factors A/G; eigendecompositions are
*recomputed* on load — they are derived state, and factors are smaller and
dtype-stable. Works for both the dense :class:`kfac_tpu.KFACState` and the
stacked :class:`kfac_tpu.parallel.DistKFACState`; with sharded arrays orbax
writes one shard per host (the TPU equivalent of the reference's
per-inv-worker sharded factor directory, kfac/gpt_neox/preconditioner.py:
427-447).
"""

from __future__ import annotations

import dataclasses
import json
import os
import warnings as _warnings
from typing import Any

import jax

try:
    import orbax.checkpoint as ocp

    _HAS_ORBAX = True
except Exception:  # pragma: no cover - orbax is in the image; belt+braces
    _HAS_ORBAX = False


def layout_manifest(engine: Any) -> dict[str, Any]:
    """JSON-serializable description of an engine's durable-state layout.

    The stacked KAISA layout depends on config (``bucket_granularity``,
    ``colocate_factors``) AND platform defaults, so two runs of "the same"
    training script can produce incompatible :func:`save` payloads — the
    reference never hits this because its ``state_dict`` is always
    layer-keyed (kfac/base_preconditioner.py:215-265). The manifest makes
    the layout explicit so :func:`restore` can diagnose a mismatch and
    migrate through per-layer factors instead of surfacing an orbax shape
    error.
    """
    man: dict[str, Any] = {'format': 1, 'engine': type(engine).__name__}
    cfg = getattr(engine, 'config', engine)
    cm = getattr(cfg, 'compute_method', None)
    man['compute_method'] = getattr(cm, 'name', str(cm))
    # informational (NOT a layout key: a topology change alone never forces
    # factor migration — orbax reshards same-layout payloads through the
    # restore template's shardings); recorded so an elastic restore can
    # report what it moved between
    topo = getattr(engine, 'topology', None)
    if callable(topo):
        man['topology'] = topo()
    if hasattr(engine, 'a_store'):  # stacked KAISA engine
        man['bucket_granularity'] = int(cfg.bucket_granularity)
        man['colocate_factors'] = bool(cfg.colocate_factors)
        man['a_store'] = [_bucket_entry(sb) for sb in engine.a_store]
        man['g_store'] = [_bucket_entry(sb) for sb in engine.g_store]
    if hasattr(engine, 'n_stages'):  # pipeline engine
        man['n_stages'] = int(engine.n_stages)
    return man


def _bucket_entry(sb: Any) -> dict[str, Any]:
    return {
        'key': str(sb.key),
        'layers': list(sb.layers),
        'd': int(sb.d),
        'padded': int(sb.padded),
        'dims': [int(d) for d in sb.dims],
    }


# Manifest keys that determine the shape/keying of the durable payload
# (compute_method does not: only step + a + g are durable).
_LAYOUT_KEYS = (
    'engine', 'bucket_granularity', 'colocate_factors', 'a_store',
    'g_store', 'n_stages',
)


def _layout_view(man: dict[str, Any]) -> dict[str, Any]:
    return {k: man[k] for k in _LAYOUT_KEYS if k in man}


def _manifest_path(path: str) -> str | None:
    """Local sidecar path for the layout manifest, or ``None`` for remote
    URIs (``gs://``, ``s3://``, ...): ``os.path.abspath`` would mangle the
    scheme and the builtin ``open`` cannot write there — orbax handles the
    checkpoint itself through its own path layer, but the sidecar is
    plain-file IO. Remote saves skip the manifest with a warning (restore
    then runs manifest-less: same-layout restores work, cross-layout
    migration is unavailable)."""
    p = str(path)
    if '://' in p:
        return None
    return os.path.abspath(p) + '.manifest.json'


def _factors_from_saved(
    kfac_payload: dict[str, Any], saved_man: dict[str, Any]
) -> dict[str, dict[str, Any]] | None:
    """Reconstruct per-layer true-dim factors from a raw :func:`save`
    payload using the manifest it was written with.

    Returns None when the saved layout is not migratable this way
    (pipeline states carry a stage axis whose re-partition is unsupported,
    as in the reference).
    """
    if 'n_stages' in saved_man:
        return None
    out: dict[str, dict[str, Any]] = {}
    if 'a_store' in saved_man:  # stacked KAISA payload: slice slots out
        for side in ('a', 'g'):
            for entry in saved_man[f'{side}_store']:
                stack = kfac_payload[side][entry['key']]
                for i, name in enumerate(entry['layers']):
                    d = entry['dims'][i]
                    out.setdefault(name, {})[side] = stack[i, :d, :d]
        return out
    # dense payload: already layer-keyed
    for name, a in kfac_payload['a'].items():
        out.setdefault(name, {})['a'] = a
    for name, g in kfac_payload['g'].items():
        out.setdefault(name, {})['g'] = g
    return out


def durable_state(state: Any) -> dict[str, Any]:
    """The persistent slice of a K-FAC state: step + factors, plus the
    numerical-health counters when the sentinel is enabled.

    Works for the NamedTuple states of the dense/KAISA engines and the
    dict state of :class:`kfac_tpu.parallel.PipelineKFAC`. The health
    counters are stored as a plain field dict of per-layer scalars —
    layout-independent, so they also survive cross-layout migration.

    The compressed-transport error-feedback residuals (``comp_ef``) are
    durable too: the residual is deferred factor mass, and dropping it at
    a restore would bias the next EMA by exactly the noise error feedback
    exists to cancel.

    Raises on a state whose factors are cold-offload placeholders
    (spilled to host RAM): persisting zero-size stubs would silently
    write an unusable checkpoint. The Trainer's checkpoint driver hands
    the manager's resident ``host_view`` here instead — this raise is the
    backstop for direct ``save`` calls on a spilled state.
    """
    if isinstance(state, dict):
        return {'step': state['step'], 'a': state['a'], 'g': state['g']}
    from kfac_tpu.compression import offload as offload_lib

    if offload_lib.is_spilled(state):
        raise ValueError(
            'cannot checkpoint a spilled K-FAC state: the factor slots are '
            'cold-offload placeholders (the real factors live in host RAM). '
            'Use OffloadManager.host_view(state) for a resident view, or '
            'let the Trainer checkpoint driver handle it.'
        )
    out = {'step': state.step, 'a': state.a, 'g': state.g}
    health = getattr(state, 'health', None)
    if health is not None:
        out['health'] = health._asdict()
    comp_ef = getattr(state, 'comp_ef', None)
    if comp_ef is not None:
        out['comp_ef'] = dict(comp_ef)
    return out


def _with_durable(state: Any, loaded: dict[str, Any]) -> Any:
    if isinstance(state, dict):
        return {
            **state,
            'step': loaded['step'], 'a': loaded['a'], 'g': loaded['g'],
        }
    state = state._replace(
        step=loaded['step'], a=loaded['a'], g=loaded['g']
    )
    if 'health' in loaded and getattr(state, 'health', None) is not None:
        state = state._replace(health=_health_from_saved(loaded['health']))
    if 'comp_ef' in loaded and getattr(state, 'comp_ef', None) is not None:
        state = state._replace(comp_ef=dict(loaded['comp_ef']))
    return state


def _health_from_saved(saved: Any) -> Any:
    """Rebuild a :class:`kfac_tpu.health.HealthState` from its saved field
    dict (or pass one through that orbax already restored structured)."""
    from kfac_tpu import health as health_lib

    if isinstance(saved, health_lib.HealthState):
        return saved
    return health_lib.HealthState(
        skipped_steps=saved['skipped_steps'],
        damping_mult=dict(saved['damping_mult']),
        quarantined=dict(saved['quarantined']),
        bad_inv=dict(saved['bad_inv']),
        quarantine_events=dict(saved['quarantine_events']),
    )


def _validate_restored_factors(path: str, engine: Any, state: Any) -> None:
    """Reject corrupt checkpoints up front with a layer-named error.

    A factor that went to disk with inf/NaN (e.g. saved before the health
    sentinel existed, or written by a run that diverged) would otherwise
    surface steps later as an unexplained eigh failure; a wrong per-layer
    shape (model width changed between save and restore) would silently
    precondition with garbage. Both checks run on the per-layer true-dim
    view, so the error names the layer, not a stacked bucket slot.
    """
    import numpy as np

    if not hasattr(engine, 'extract_factors'):
        return
    # pipeline states stack a stage axis onto the per-layer factors; only
    # the finiteness check applies there
    check_shapes = not isinstance(state, dict)
    reg = getattr(engine, 'registry', None)
    for name, fg in engine.extract_factors(state).items():
        helper = reg.layers.get(name) if reg is not None else None
        for side in ('a', 'g'):
            arr = np.asarray(jax.device_get(fg[side]))
            if not np.isfinite(arr).all():
                bad = int(arr.size - np.isfinite(arr).sum())
                raise ValueError(
                    f'checkpoint at {path!r}: restored {side.upper()} '
                    f'factor for layer {name!r} contains {bad} non-finite '
                    'values — the checkpoint is corrupt (saved from a '
                    'diverged run?); restore a different one or reinitialize '
                    'the preconditioner state.'
                )
            if helper is not None and check_shapes:
                exp = tuple(
                    helper.a_factor_shape if side == 'a'
                    else helper.g_factor_shape
                )
                if tuple(arr.shape) != exp:
                    raise ValueError(
                        f'checkpoint at {path!r}: restored {side.upper()} '
                        f'factor for layer {name!r} has shape '
                        f'{tuple(arr.shape)} but the engine expects {exp} — '
                        'the model architecture changed between save and '
                        'restore.'
                    )


def save(
    path: str,
    state: Any,
    extra: dict[str, Any] | None = None,
    engine: Any | None = None,
    wait: bool = True,
    overwrite: bool = False,
) -> Any:
    """Write the durable K-FAC state (plus optional extra trees, e.g. model
    params / optax state) to ``path``.

    Pass ``engine`` to also write a layout manifest sidecar
    (``<path>.manifest.json``): :func:`restore` uses it to detect a layout
    mismatch up front and to MIGRATE the factors into a differently-laid-out
    engine (other ``bucket_granularity``/``colocate_factors``, dense vs
    distributed) instead of failing on an orbax shape error.

    ``wait=False`` returns immediately after orbax snapshots the arrays
    and finishes the write on background threads — training continues
    while the checkpoint streams out (the pod-scale pattern; the
    reference's torch.save always blocks). Returns a handle: call its
    ``.wait_until_finished()`` before relying on the files, and before
    starting another save to the same path. The manifest sidecar is
    written only once the checkpoint is DURABLE (at wait time), so a
    manifest's presence always implies a committed checkpoint — a crash
    mid-async-save leaves neither.

    ``overwrite`` controls the policy for a pre-existing ``path``: the
    default refuses up front (orbax's ``StandardCheckpointer`` would fail
    anyway, with a less actionable message), ``overwrite=True`` replaces
    the old checkpoint. Production rotations should prefer fresh
    step-numbered directories (:class:`kfac_tpu.resilience
    .CheckpointManager`) so a crashed overwrite can never destroy the
    only good checkpoint.
    """
    if not _HAS_ORBAX:
        raise RuntimeError('orbax-checkpoint is not available')
    if not overwrite and '://' not in str(path) and os.path.exists(path):
        raise ValueError(
            f'checkpoint path {path!r} already exists; pass '
            'overwrite=True to replace it, or save each step to a fresh '
            'step-numbered directory (kfac_tpu.resilience.CheckpointManager '
            'manages such a rotation with an atomic LATEST pointer)'
        )
    payload = {'kfac': durable_state(state)}
    if extra:
        payload.update(extra)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, payload, force=overwrite)
    # remove any STALE sidecar from an earlier save at this path
    # immediately (before the async return): whatever happens next — crash
    # pre-commit (no checkpoint, no manifest) or crash between orbax's
    # commit and the caller's wait (new checkpoint, no manifest: restore
    # runs manifest-less) — a manifest on disk can only describe THIS save
    mpath0 = _manifest_path(path)
    if jax.process_index() == 0 and mpath0 is not None and (
        os.path.exists(mpath0)
    ):
        os.remove(mpath0)

    def _finalize_manifest() -> None:
        if jax.process_index() != 0:
            return
        mpath = _manifest_path(path)
        if engine is not None:
            if mpath is None:
                _warnings.warn(
                    f'checkpoint path {path!r} is a remote URI: the layout '
                    f'manifest sidecar is plain-file IO and is skipped — '
                    f'cross-layout factor migration will be unavailable '
                    f'for this checkpoint',
                    stacklevel=3,
                )
            else:
                with open(mpath, 'w') as f:
                    json.dump(layout_manifest(engine), f, indent=1)

    if wait:
        ckptr.wait_until_finished()
        _finalize_manifest()
        return ckptr
    return _AsyncSaveHandle(ckptr, _finalize_manifest)


class _AsyncSaveHandle:
    """Returned by ``save(..., wait=False)``: finishing the write also
    finalizes the manifest sidecar, preserving the invariant that a
    manifest on disk implies a durable checkpoint.

    Usable as a context manager (``with save(..., wait=False):`` waits on
    exit). Dropping the handle without ``wait_until_finished()`` warns: the
    orbax background threads may still commit the checkpoint, but the
    manifest is never finalized — a durable checkpoint that silently lost
    its cross-layout migration metadata.
    """

    def __init__(self, ckptr, finalize):
        self._ckptr = ckptr
        self._finalize = finalize
        self._done = False

    def wait_until_finished(self) -> None:
        self._ckptr.wait_until_finished()
        if not self._done:
            self._done = True
            self._finalize()

    def __enter__(self) -> '_AsyncSaveHandle':
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.wait_until_finished()

    def __del__(self) -> None:
        if getattr(self, '_done', True):
            return
        try:  # pragma: no cover - interpreter-shutdown ordering
            _warnings.warn(
                'async checkpoint save handle dropped without '
                'wait_until_finished(): the checkpoint may commit in the '
                'background but its layout manifest is never written '
                '(cross-layout migration will be unavailable); hold the '
                'handle and wait on it, or use it as a context manager',
                ResourceWarning,
                stacklevel=2,
            )
        except Exception:
            pass


def restore(
    path: str,
    engine: Any,
    extra_template: dict[str, Any] | None = None,
) -> tuple[Any, dict[str, Any]]:
    """Load factors into a fresh state from ``engine.init()`` and recompute
    decompositions via ``engine.rematerialize``.

    ``engine`` is a :class:`kfac_tpu.KFACPreconditioner` or
    :class:`kfac_tpu.parallel.DistributedKFAC`. Returns ``(state, extra)``.

    If the checkpoint carries a layout manifest (written by
    ``save(..., engine=engine)``) and the layout differs from ``engine``'s
    — other ``bucket_granularity``/``colocate_factors`` (including the
    platform-resolved defaults changing across hosts), or a dense vs
    distributed engine swap — the factors are MIGRATED automatically
    through their per-layer true-dim form (with a warning). Only
    stage-stacked pipeline states refuse cross-layout moves (a stage
    re-partition is unsupported, as in the reference).
    """
    if not _HAS_ORBAX:
        raise RuntimeError('orbax-checkpoint is not available')
    template_state = engine.init()
    template = {'kfac': durable_state(template_state)}
    if extra_template:
        template.update(extra_template)
    ckptr = ocp.StandardCheckpointer()

    saved_man = None
    mpath = _manifest_path(path)
    if mpath is not None and os.path.exists(mpath):
        with open(mpath) as f:
            saved_man = json.load(f)
    elif mpath is not None and os.path.isdir(path):
        # the checkpoint committed but its sidecar never landed: either a
        # crash between orbax's commit and the manifest finalize (the
        # async-save window CheckpointManager's rotation tolerates) or a
        # save() without engine= — restore proceeds layout-exact either way
        from kfac_tpu.warnings import CheckpointResilienceWarning

        _warnings.warn(
            f'checkpoint at {path!r} has no layout-manifest sidecar '
            '(saved without engine=, or the writer died between the orbax '
            'commit and the manifest finalize): restoring manifest-less — '
            'cross-layout migration is unavailable for this checkpoint',
            CheckpointResilienceWarning,
            stacklevel=2,
        )
    if saved_man is not None:
        cur_man = layout_manifest(engine)
        if _layout_view(saved_man) != _layout_view(cur_man):
            return _migrate_restore(
                path, engine, template_state, saved_man, cur_man,
                extra_template, ckptr,
            )

    try:
        payload = ckptr.restore(path, target=template)
    except (ValueError, KeyError) as exc:
        payload = _retry_health_mismatch(
            ckptr, path, template, template_state, engine, exc
        )
    state = _with_durable(template_state, payload['kfac'])
    _validate_restored_factors(path, engine, state)
    loaded_health = (
        getattr(state, 'health', None)
        if not isinstance(state, dict)
        else None
    )
    state = engine.rematerialize(state)
    if loaded_health is not None:
        # rematerialize ticks the degradation counters from ITS verdicts on
        # the freshly recomputed decompositions; the checkpoint's counters
        # are the durable truth for a resumed run, so they win
        state = state._replace(health=loaded_health)
    extra = {k: v for k, v in payload.items() if k != 'kfac'}
    return state, extra


def _retry_health_mismatch(
    ckptr: Any,
    path: str,
    template: dict[str, Any],
    template_state: Any,
    engine: Any,
    exc: Exception,
) -> dict[str, Any]:
    """Structure-mismatch fallback: tolerate config-presence drift.

    A checkpoint written without health counters must restore into a
    health-enabled engine (counters start fresh), and one written WITH
    them must restore into a health-disabled engine (counters dropped) —
    toggling the sentinel between runs is configuration, not a layout
    change. Likewise a pre-compression checkpoint (no ``comp_ef``) must
    restore into an error-feedback engine: the residual starts from
    init()'s zeros. (The opposite comp_ef direction — an EF checkpoint
    into an EF-less engine — has no template to offer orbax and falls
    through to the layout diagnosis, which names ``stat_compression``.)
    Anything else re-raises the layout diagnosis."""
    kfac_t = template['kfac']
    health_toggled = None
    if 'health' in kfac_t:
        health_toggled = {
            k: v for k, v in kfac_t.items() if k != 'health'
        }
    else:
        reg = getattr(engine, 'registry', None)
        if reg is not None and not isinstance(template_state, dict):
            from kfac_tpu import health as health_lib

            health_toggled = {
                **kfac_t,
                'health': health_lib.init_health(reg.names())._asdict(),
            }
    variants = []
    if health_toggled is not None:
        variants.append(health_toggled)
    # toggle comp_ef independently and jointly with the health toggle
    for base in (kfac_t, health_toggled):
        if base is not None and 'comp_ef' in base:
            variants.append(
                {k: v for k, v in base.items() if k != 'comp_ef'}
            )
    for kf in variants:
        try:
            payload = ckptr.restore(path, target={**template, 'kfac': kf})
        except (ValueError, KeyError):
            continue
        # either health direction resolves to "no health in the loaded
        # payload": a sentinel-less checkpoint keeps init()'s fresh
        # counters; a sentinel-less engine drops the saved ones. A
        # comp_ef-less payload keeps init()'s zero residuals.
        payload['kfac'].pop('health', None)
        return payload
    raise ValueError(
        f'checkpoint at {path!r} does not match the engine state '
        'layout. For DistributedKFAC the stacked bucket keys/shapes '
        'depend on the config (notably bucket_granularity and '
        'colocate_factors), and error-feedback residuals saved under '
        'stat_compression need a compression-enabled engine (or the same '
        'chunking) to restore into: restore with the SAME values the '
        'checkpoint was saved under — or write checkpoints with '
        'save(..., engine=engine) so restore can diagnose and migrate '
        f'layout changes. Original error: {exc}'
    ) from exc


def _raw_host_restore(path: str) -> dict[str, Any]:
    """Target-less restore of a checkpoint's full payload to HOST numpy.

    A bare ``StandardCheckpointer.restore(path)`` rebuilds every array
    with the checkpoint's SAVED sharding, whose serialized device mesh
    names the WRITER's devices — on an elastic restore after the pod
    shrank or grew, orbax cannot map those device ids and dies with
    "available devices are different". Restoring against the checkpoint's
    own metadata with the sharding stripped forces plain ``np.ndarray``
    leaves (scalars keep their python types), which never touches device
    placement; the migration path re-shards through the engine template
    anyway.
    """
    import numpy as np

    from orbax.checkpoint import checkpoint_utils

    reader = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    meta = reader.metadata(path)
    meta = jax.tree_util.tree_map(
        lambda m: (
            dataclasses.replace(m, sharding=None)
            if dataclasses.is_dataclass(m) and hasattr(m, 'sharding')
            else m
        ),
        meta,
    )
    restore_args = checkpoint_utils.construct_restore_args(meta)
    raw = reader.restore(
        path, args=ocp.args.PyTreeRestore(restore_args=restore_args)
    )
    return jax.tree_util.tree_map(np.asarray, raw)


def _migrate_restore(
    path: str,
    engine: Any,
    template_state: Any,
    saved_man: dict[str, Any],
    cur_man: dict[str, Any],
    extra_template: dict[str, Any] | None,
    ckptr: Any,
) -> tuple[Any, dict[str, Any]]:
    """Cross-layout restore: raw-load the saved payload, slice per-layer
    factors out of it using the SAVED manifest, insert them into the
    current engine's layout, and rematerialize."""
    import jax.numpy as jnp

    import numpy as np

    diff = [
        k
        for k in _LAYOUT_KEYS
        if saved_man.get(k) != cur_man.get(k)
    ]
    # no target shapes needed; materialized to HOST numpy — a raw restore
    # through the SAVED shardings would both commit arrays to device 0
    # (conflicting with the engine's mesh-sharded template inside
    # insert_factors' scatter) and break outright when the device set
    # changed (elastic shrink/grow)
    raw = _raw_host_restore(path)
    factors = _factors_from_saved(raw['kfac'], saved_man)
    if factors is None or 'n_stages' in cur_man:
        raise ValueError(
            f'checkpoint at {path!r} was saved under a different, '
            f'non-migratable state layout (differing fields: {diff}; '
            f"saved engine {saved_man.get('engine')}, restoring into "
            f"{cur_man.get('engine')}). Stage-stacked pipeline factors "
            'only restore into an identical pipeline layout; use '
            'checkpoint.save_factors / load_factors for portable factor '
            'checkpoints.'
        )
    saved_layers = set(factors)
    reg = getattr(engine, 'registry', None)
    if reg is not None and set(reg.names()) != saved_layers:
        raise ValueError(
            f'checkpoint at {path!r} stores factors for layers '
            f'{sorted(saved_layers)} but the restoring engine registers '
            f'{sorted(reg.names())}; factor migration requires identical '
            'layer sets.'
        )
    if reg is not None:
        # Same names but different layer WIDTHS (e.g. the script's d_model
        # changed between save and resume) must error: insert_factors would
        # otherwise silently identity-pad the stale factors into the wider
        # slots and train with a numerically wrong preconditioner.
        for name, fg in factors.items():
            h = reg.layers.get(name)
            if h is None:
                continue
            exp = (tuple(h.a_factor_shape), tuple(h.g_factor_shape))
            got = (tuple(fg['a'].shape), tuple(fg['g'].shape))
            if exp != got:
                raise ValueError(
                    f'checkpoint at {path!r}: layer {name!r} stores factor '
                    f'shapes {got} but the restoring engine expects {exp} '
                    '— the model architecture changed between save and '
                    'restore; factors cannot migrate across layer widths.'
                )
    _warnings.warn(
        f'checkpoint at {path!r} was saved under a different state layout '
        f'(differing fields: {diff}); migrating through per-layer factors '
        '(slower than a layout-exact restore, numerically identical)',
        stacklevel=3,
    )
    state = engine.insert_factors(template_state, factors)
    step_t = (
        template_state['step']
        if isinstance(template_state, dict)
        else template_state.step
    )
    step = jax.device_put(
        jnp.asarray(raw['kfac']['step'], jnp.asarray(step_t).dtype),
        step_t.sharding,
    )
    if isinstance(state, dict):
        state['step'] = step
    else:
        state = state._replace(step=step)
    state = engine.rematerialize(state)
    if (
        not isinstance(state, dict)
        and getattr(template_state, 'health', None) is not None
        and isinstance(raw.get('kfac'), dict)
        and 'health' in raw['kfac']
    ):
        # per-layer health counters are layout-independent (keyed by layer
        # name, scalar values) — they migrate verbatim
        saved_h = jax.tree_util.tree_map(
            jnp.asarray, raw['kfac']['health']
        )
        state = state._replace(health=_health_from_saved(saved_h))

    # pin the migrated state to the new engine's declared shardings: the
    # insert/rematerialize path mostly lands there already, but factors
    # that round-tripped through host numpy (and the scalar step) may sit
    # committed to default placement — an elastic restore onto a different
    # mesh must hand back arrays jit can consume without a resharding
    # surprise on the first donated step
    shard_fn = getattr(engine, 'state_shardings', None)
    if callable(shard_fn):
        shardings = shard_fn()
        if shardings is not None and jax.tree_util.tree_structure(
            state
        ) == jax.tree_util.tree_structure(shardings):
            state = jax.device_put(state, shardings)

    if extra_template:
        # The target-less restore flattens custom pytree nodes (optax
        # namedtuples and the like) into dicts/lists, so the extras must be
        # re-read against their real templates. The raw kfac payload serves
        # as its own target (saved structure/shapes by construction), which
        # lets one structured restore recover the extras with the
        # template's pytree types AND shardings.
        payload = ckptr.restore(
            path, target={'kfac': raw['kfac'], **extra_template}
        )
        extra = {k: v for k, v in payload.items() if k != 'kfac'}
    else:
        extra = {k: v for k, v in raw.items() if k != 'kfac'}
    return state, extra


def save_factors(path: str, engine: Any, state: Any) -> None:
    """Write per-layer TRUE-DIM factors + step, independent of layout.

    Unlike :func:`save` (which persists the engine's stacked arrays
    verbatim), this stores layer-named (d, d) factors, so the checkpoint
    restores into a DIFFERENT engine configuration — other
    bucket_granularity, colocate_factors, mesh, or even dense vs
    distributed. The reference's per-layer factor-dir checkpointing
    (kfac/gpt_neox/preconditioner.py:394-447) serves the same
    topology-migration role.
    """
    if not _HAS_ORBAX:
        raise RuntimeError('orbax-checkpoint is not available')
    step = state['step'] if isinstance(state, dict) else state.step
    payload = {
        'step': step,
        'factors': engine.extract_factors(state),
    }
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, payload)
    ckptr.wait_until_finished()


def load_factors(path: str, engine: Any) -> Any:
    """Restore a :func:`save_factors` checkpoint into ``engine``'s layout.

    Returns a fresh state with the loaded factors inserted and
    decompositions rematerialized. The engine must register EXACTLY the
    stored layer names with the stored true dims (layout — granularity,
    colocation, mesh, dense vs distributed — is free to differ; the layer
    set is not, and pipeline stage-stacked factors only reload into a
    pipeline engine with the same stage count).
    """
    if not _HAS_ORBAX:
        raise RuntimeError('orbax-checkpoint is not available')
    state = engine.init()
    step = state['step'] if isinstance(state, dict) else state.step
    template = {
        'step': step,
        'factors': engine.extract_factors(state),
    }
    ckptr = ocp.StandardCheckpointer()
    try:
        payload = ckptr.restore(path, target=template)
    except (ValueError, KeyError) as exc:
        raise ValueError(
            f'factor checkpoint at {path!r} does not match this engine: '
            'the registered layer names and their factor dims must equal '
            'those the checkpoint was saved with (engine LAYOUT may '
            'differ; the layer set may not, and pipeline stage counts '
            f'must match). Original error: {exc}'
        ) from exc
    state = engine.insert_factors(state, payload['factors'])
    if isinstance(state, dict):
        state['step'] = payload['step']
    else:
        state = state._replace(step=payload['step'])
    return engine.rematerialize(state)
